"""Headline benchmark — prints ONE JSON line.

Metric: SHA-256d proof-of-work throughput of the single-chip nonce-sweep
kernel (the graft's headline number, BASELINE.json: target >=500 GH/s/chip
on TPU v5e). vs_baseline is value/500.

Method: sweep a fixed header template against an impossible target (no
early exit) for a fixed tile count entirely on-device (one dispatch,
lax.while_loop over tiles), timed after a warmup dispatch that absorbs
compile time. Each nonce costs two SHA-256 compressions (midstate path);
a "hash" below = one full SHA-256d of an 80-byte header.
"""

import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
import numpy as np

from bitcoincashplus_tpu.crypto.hashes import header_midstate
from bitcoincashplus_tpu.ops.miner import sweep_jit
from bitcoincashplus_tpu.ops.sha256 import bytes_to_words_np, target_to_limbs_np

BASELINE_GHS = 500.0  # BASELINE.json north star, per chip


def main():
    on_cpu = jax.default_backend() == "cpu" and "axon" not in str(jax.devices())
    header = bytes(range(80))
    midstate = jnp.asarray(np.array(header_midstate(header), dtype=np.uint32))
    tail = jnp.asarray(bytes_to_words_np(np.frombuffer(header[64:76], np.uint8)))
    target = jnp.asarray(target_to_limbs_np(0))  # impossible: full sweep

    tile = 1 << 14 if on_cpu else 1 << 20
    n_tiles = 4 if on_cpu else 128

    # warmup / compile
    jax.block_until_ready(
        sweep_jit(midstate, tail, target, jnp.uint32(0), jnp.uint32(1), tile=tile)
    )

    rates = []
    for _ in range(4):
        # random start nonce: the serving layer memoizes identical
        # (program, args) dispatches, which would fake the timing
        start = jnp.uint32(random.getrandbits(32))
        t0 = time.perf_counter()
        found, nonce, tiles = jax.block_until_ready(
            sweep_jit(midstate, tail, target, start, jnp.uint32(n_tiles), tile=tile)
        )
        dt = time.perf_counter() - t0
        rates.append(int(tiles) * tile / dt)

    # the first post-warmup dispatch returns anomalously fast through the
    # serving tunnel; median of the remaining runs is the honest figure
    rates = sorted(rates[1:])
    ghs = rates[len(rates) // 2] / 1e9
    print(json.dumps({
        "metric": "sha256d_sweep_throughput_per_chip",
        "value": round(ghs, 4),
        "unit": "GH/s",
        "vs_baseline": round(ghs / BASELINE_GHS, 6),
    }))


if __name__ == "__main__":
    main()
