"""Headline benchmarks — one JSON object per line, headline metric LAST
(the driver parses the final line; the tail carries all five BASELINE.json
configs, VERDICT r2 item 5).

Configs (BASELINE.json):
  1. batched 80-byte header double-SHA (device), correctness-anchored against
     the known mainnet genesis hash + hashlib vectors
  2. getblocktemplate nonce-sweep miner, single chip  <- HEADLINE (last line)
  3. Merkle-root construction over a 4096-tx snapshot
  4. secp256k1 ECDSA batch-verify, 10k-sig ConnectBlock-scale batch
  5. 8-chip nonce shard — reported from the 8-device VIRTUAL CPU mesh
     (no multi-chip hardware on this host; the metric is scaling speedup,
     clearly labeled, not GH/s)

Timing honesty: the axon serving layer memoizes identical (program, args)
dispatches, so every timed run randomizes an argument; medians over repeats;
a warmup dispatch absorbs compile. The sweep timings force a scalar host
fetch (int(tiles)) because block_until_ready alone does not synchronize
through the serving tunnel.
"""

import json
import os
import random
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
import numpy as np

BASELINE_GHS = 500.0  # BASELINE.json north star, per chip (see ROOFLINE.md)

# BENCH_r*.json schema: v1 = the unstamped r01-r07 shape; v2 adds this
# stamp (schema_version + host fingerprint) so the bench trajectory is
# comparable across hosts — a number measured on a 1-core CI sandbox and
# one from a v5e host must never be read as the same series point.
BENCH_SCHEMA_VERSION = 2


def _bench_stamp() -> dict:
    """schema_version + host fingerprint for every BENCH_r*.json write."""
    import platform

    host = {
        "platform": platform.platform(),
        "python": platform.python_version(),
        "machine": platform.machine(),
        "host_cpus": os.cpu_count(),
    }
    try:
        host["jax_version"] = jax.__version__
        host["backend"] = jax.default_backend()
        devs = jax.devices()
        host["device_count"] = len(devs)
        host["device_kind"] = (getattr(devs[0], "device_kind", None)
                               if devs else None)
    except Exception:  # pragma: no cover - backend-less environments
        pass
    return {"schema_version": BENCH_SCHEMA_VERSION, "host": host}


def emit(metric, value, unit, vs_baseline, **extra):
    line = {"metric": metric, "value": value, "unit": unit,
            "vs_baseline": vs_baseline}
    line.update(extra)
    print(json.dumps(line), flush=True)


def bench_header_hash():
    """Config 1: device batch header double-SHA, anchored to known vectors."""
    import hashlib

    from bitcoincashplus_tpu.consensus.params import main_params
    from bitcoincashplus_tpu.ops.sha256 import sha256d_headers

    # correctness anchor: mainnet genesis header hashes to the known hash
    genesis = main_params().genesis
    hdr = genesis.header.serialize()
    digest = sha256d_headers(np.frombuffer(hdr, np.uint8).reshape(1, 80))[0]
    assert bytes(digest) == genesis.get_hash(), "genesis vector mismatch"

    B = 1 << 16
    rng = np.random.default_rng(1)
    warm = rng.integers(0, 256, (B, 80), dtype=np.uint8)
    out = sha256d_headers(warm)
    # spot-check a lane against hashlib
    h0 = hashlib.sha256(hashlib.sha256(warm[0].tobytes()).digest()).digest()
    assert bytes(out[0]) == h0
    ts = []
    for _ in range(3):
        batch = rng.integers(0, 256, (B, 80), dtype=np.uint8)
        t0 = time.perf_counter()
        out = sha256d_headers(batch)
        ts.append(time.perf_counter() - t0)
    dt = sorted(ts)[1]
    mhs = B / dt / 1e6
    # device-resident form: same kernel with the batch already on device —
    # separates chip throughput from the serving-tunnel's ~4 MB/s bulk
    # transfer bandwidth (a co-located deployment pays PCIe/ICI, not this)
    import jax.numpy as jnp

    from bitcoincashplus_tpu.ops.sha256 import (
        headers_to_words_np,
        sha256d_headers_jit,
    )

    dev_words = jnp.asarray(headers_to_words_np(batch))
    sha256d_headers_jit(dev_words).block_until_ready()
    dts = []
    for _ in range(3):
        t0 = time.perf_counter()
        sha256d_headers_jit(dev_words).block_until_ready()
        dts.append(time.perf_counter() - t0)
    dev_mhs = B / sorted(dts)[1] / 1e6
    # honest CPU comparison: the native C++ scalar path on the same batch
    # (hashlib-equivalent; what one host core does) — VERDICT r3 #5
    cpu_mhs = None
    from bitcoincashplus_tpu import native as _nat

    if _nat.available():
        flat = batch.tobytes()
        t0 = time.perf_counter()
        _nat.hash_headers(flat)
        cpu_mhs = B / (time.perf_counter() - t0) / 1e6
    emit("header_hash_batch_throughput", round(mhs, 2), "MH/s",
         round(mhs / cpu_mhs, 4) if cpu_mhs else 0.0,
         device_resident_mhs=round(dev_mhs, 2),
         cpu_native_mhs=round(cpu_mhs, 2) if cpu_mhs else None,
         note="64Ki-header batch incl host pack/unpack + tunnel transfers "
              "(transfer-bound here); device_resident_mhs excludes "
              "host<->device transfer; vs_baseline = end-to-end device / "
              "one-native-CPU-core ratio (the 500 GH/s north star would "
              "round to 0 at this scale; see ROOFLINE.md §4); "
              "genesis+hashlib anchored")
    return {"header_mhs": round(mhs, 2),
            "header_device_resident_mhs": round(dev_mhs, 2)}


def bench_merkle():
    """Config 3: 4096-tx Merkle root on device vs the scalar host oracle."""
    from bitcoincashplus_tpu.consensus.merkle import compute_merkle_root
    from bitcoincashplus_tpu.ops.merkle import compute_merkle_root_tpu

    rng = np.random.default_rng(2)
    txids = [rng.bytes(32) for _ in range(4096)]
    root_ref, _ = compute_merkle_root(txids)
    root_dev, _ = compute_merkle_root_tpu(txids)  # warm + correctness
    assert root_dev == root_ref
    ts = []
    for _ in range(3):
        txids = [rng.bytes(32) for _ in range(4096)]
        t0 = time.perf_counter()
        compute_merkle_root_tpu(txids)
        ts.append(time.perf_counter() - t0)
    dt = sorted(ts)[1]
    # honest CPU comparison: native C++ (or hashlib) on the same snapshot —
    # on a tunneled single chip the device number loses to the host; the
    # point of the config is kernel validation, and the bench says so
    from bitcoincashplus_tpu import native as _nat

    t0 = time.perf_counter()
    if _nat.available():
        _nat.merkle_root(txids)
    else:
        compute_merkle_root(txids)
    cpu_ms = (time.perf_counter() - t0) * 1e3
    emit("merkle_root_4096tx", round(dt * 1e3, 2), "ms",
         round(cpu_ms / (dt * 1e3), 4),
         cpu_native_ms=round(cpu_ms, 2),
         note="single-dispatch on-device tree reduction (masked "
              "odd-duplication); vs_baseline = cpu_ms/device_ms — the "
              "device pays one serving-tunnel round trip (~200 ms), so "
              "host CPU wins this config outright on this deployment; "
              "see ROOFLINE.md §6")
    return {"merkle_ms": round(dt * 1e3, 1)}


def _make_sig_records(rng, n_distinct: int, n_total: int):
    """n_total SigCheckRecords tiled from n_distinct fresh (key, sig, msg)
    triples — FRESH per timed run: the serving tunnel memoizes identical
    (program, args) dispatches, so reusing one batch across runs over-reads
    by up to 1.5x (VERDICT r4 weak-2)."""
    from bitcoincashplus_tpu import native as _nat
    from bitcoincashplus_tpu.crypto import secp256k1 as oracle
    from bitcoincashplus_tpu.script.interpreter import SigCheckRecord

    sign = _nat.ecdsa_sign if _nat.available() else oracle.ecdsa_sign
    base = []
    for _ in range(n_distinct):
        secret = int.from_bytes(rng.bytes(32), "big") % (oracle.N - 1) + 1
        pub = oracle.point_mul(secret, oracle.G)
        e = int.from_bytes(rng.bytes(32), "big") % oracle.N
        r, s = sign(secret, e)
        base.append((pub, r, s, e))
    return [SigCheckRecord(*base[i % n_distinct], b"\x00" * 32, 0)
            for i in range(n_total)]


def bench_ecdsa_batch():
    """Config 4: the 10k-sig ConnectBlock batch through the real dispatch
    path (pack -> bucket-pad -> device kernel -> unpack). Every timed run
    verifies a freshly signed batch (content-randomized per iteration —
    VERDICT r4 item 3). Returns the measured device sigs/s for the reindex
    projection."""
    from bitcoincashplus_tpu.ops import ecdsa_batch

    rng = np.random.default_rng(5)
    warm = _make_sig_records(rng, 64, 10_000)
    ok = ecdsa_batch.verify_batch(warm, backend="device")  # warm/compile
    assert bool(ok.all())
    ts = []
    for _ in range(3):
        records = _make_sig_records(rng, 64, 10_000)  # fresh content
        t0 = time.perf_counter()
        ok = ecdsa_batch.verify_batch(records, backend="device")
        ts.append(time.perf_counter() - t0)
        assert bool(ok.all())
    dt = sorted(ts)[1]
    sps = len(warm) / dt
    from bitcoincashplus_tpu.ops.ecdsa_batch import STATS as _st
    from bitcoincashplus_tpu.ops.ecdsa_batch import pallas_enabled as _pe

    # label from the same predicate dispatch uses (a disabled/fallen-back
    # pallas path must not be reported as pallas)
    kernel = "pallas-w4-3d" if _pe() and not _st.pallas_fallbacks else "xla"
    # honest CPU comparison: the native C++ scalar verify on the same
    # records (one thread per core; 1 core on this host)
    from bitcoincashplus_tpu import native as _nat

    cpu_sps = None
    if _nat.available():
        sample = warm[:1000]
        t0 = time.perf_counter()
        _nat.ecdsa_verify_batch(sample)
        cpu_sps = len(sample) / (time.perf_counter() - t0)
    emit("ecdsa_batch_verify_10k", round(sps), "sigs/s",
         round(sps / cpu_sps, 2) if cpu_sps else 0.0,
         kernel=kernel,
         cpu_native_sigs_per_s=round(cpu_sps) if cpu_sps else None,
         note=f"B=10000, fresh signatures per timed run ({dt:.2f}s, median "
              "of 3); w=4 windowed Pallas ladder; vs_baseline = "
              "device/cpu-core ratio")
    return sps


def bench_virtual_shard():
    """Config 5: nonce-shard scaling CURVE (1/2/4/8) on the VIRTUAL CPU
    mesh, with per-chip tiles-done (shard-imbalance observability) and an
    8-way sig_shard leg (config 4 x config 5 composition). One real chip on
    this host, so these numbers measure the shard_map program's scaling on
    a CPU mesh — NOISY and not ICI: virtual devices share host cores, so
    the curve is a lower bound sanity check, not a hardware claim (the r3
    run printed 1.84x, an earlier r4 run 4.45x for the same code). The
    program itself is identical to what rides ICI on real hardware.
    Subprocess keeps JAX_PLATFORMS clean."""
    code = r"""
import os, time, json, tempfile
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
import sys; sys.path.insert(0, %r)
import jax
# config, not just env: the accelerator plugin wins default-backend
# selection over JAX_PLATFORMS=cpu (tests/conftest.py documents this)
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_device", jax.devices("cpu")[0])
jax.config.update("jax_compilation_cache_dir",
                  os.path.join(tempfile.gettempdir(), "bcp-jax-test-cache"))
from bitcoincashplus_tpu.parallel.nonce_shard import sweep_header_sharded
header = bytes(range(80))
def timed(n_chips, tiles_per_chip):
    t0 = time.perf_counter()
    nonce, hashes, per_chip = sweep_header_sharded(
        header, 0, max_nonces=tiles_per_chip * n_chips * 4096,
        tile=4096, n_chips=n_chips, return_per_chip=True)
    return time.perf_counter() - t0, hashes, per_chip
curve = {}
spread = {}
per_chip_8 = None
for n in (1, 2, 4, 8):
    timed(n, 1)  # warm/compile this mesh shape
    rates = []
    for _ in range(5):  # median-of-5 + spread (VERDICT r4 item 7)
        t, h, pc = timed(n, 16)
        rates.append(h / t)
        if n == 8:
            per_chip_8 = pc
    rates.sort()
    curve[n] = rates[2] / 1e6
    spread[n] = [round(rates[0] / 1e6, 2), round(rates[-1] / 1e6, 2)]
# sig_shard leg: the PRODUCTION w4 kernel sharded over the virtual mesh
# (pallas interpret mode on CPU — same program that rides ICI on hardware)
from dataclasses import dataclass
import random
from bitcoincashplus_tpu.crypto import secp256k1 as o
from bitcoincashplus_tpu.parallel.sig_shard import verify_batch_sharded
@dataclass
class Rec:
    pubkey: tuple; r: int; s: int; msg_hash: int
rng = random.Random(7)
base = []
for _ in range(16):
    sk = rng.randrange(1, o.N); e = rng.getrandbits(256)
    r, s = o.ecdsa_sign(sk, e)
    base.append(Rec(o.point_mul(sk, o.G), r, s, e))
recs = base * 512  # 8192 lanes: 1024-lane shards on the 8-way mesh
sig = {}
sig_spread = {}
for n in (1, 8):
    verify_batch_sharded(recs, n)  # warm/compile this mesh shape
    rates = []
    for _ in range(3):
        t0 = time.perf_counter()
        ok = verify_batch_sharded(recs, n)
        rates.append(len(recs) / (time.perf_counter() - t0))
        assert ok.all()
    rates.sort()
    sig[n] = rates[1]
    sig_spread[n] = [round(rates[0]), round(rates[-1])]
print(json.dumps({"curve_mhs": curve, "curve_spread_mhs": spread,
                  "per_chip_tiles_8": per_chip_8,
                  "sig_1": sig[1], "sig_8": sig[8],
                  "sig_spread": sig_spread}))
""" % os.path.dirname(os.path.abspath(__file__))
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    try:
        out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                             text=True, env=env, timeout=1800)
        line = out.stdout.strip().splitlines()[-1] if out.stdout.strip() else "{}"
        r = json.loads(line)
        curve = r["curve_mhs"]
        speedup = round(curve["8"] / curve["1"], 2) if "1" in curve else \
            round(curve[8] / curve[1], 2)
        emit("nonce_shard_virtual8_speedup", speedup, "x", 0.0,
             scaling_curve_mhs={k: round(v, 2) for k, v in curve.items()},
             curve_spread_mhs=r["curve_spread_mhs"],
             per_chip_tiles_8=r["per_chip_tiles_8"],
             sig_shard_sigs_per_s={"1": round(r["sig_1"]),
                                   "8": round(r["sig_8"])},
             sig_shard_spread=r["sig_spread"],
             sig_shard_kernel="pallas-w4-3d (interpret on CPU mesh)",
             host_cpus=os.cpu_count(),
             note="VIRTUAL 8-device CPU mesh (no multi-chip hardware): "
                  "median-of-5 + [min,max] spread; lower-bound sanity "
                  "check, NOT an ICI claim. On a 1-core host a "
                  "work-conserving shard can at best TIE 1-way (the 8-way "
                  "deficit is shard_map partition overhead); the claim is "
                  "kernel identity — the sharded program IS config 4's w4 "
                  "pipeline (sig_shard dryrun proves execution)")
        return {"shard8_speedup": speedup}
    except Exception as e:  # pragma: no cover - diagnostics only
        emit("nonce_shard_virtual8_speedup", -1, "x", 0.0,
             note=f"subprocess failed: {e}")
        return None


def bench_sweep_headline():
    """Config 2 (HEADLINE, printed last): single-chip nonce-sweep GH/s on
    the tuned Pallas kernel, XLA while-loop fallback if Pallas fails."""
    from bitcoincashplus_tpu.crypto.hashes import header_midstate
    from bitcoincashplus_tpu.ops.sha256 import bytes_to_words_np, target_to_limbs_np

    header = bytes(range(80))
    mid = jnp.asarray(np.array(header_midstate(header), dtype=np.uint32))
    tail = jnp.asarray(bytes_to_words_np(np.frombuffer(header[64:76], np.uint8)))

    on_cpu = jax.default_backend() == "cpu"
    kernel = "pallas"
    try:
        if on_cpu:
            raise RuntimeError("pallas TPU kernel needs the chip")
        from bitcoincashplus_tpu.ops.pallas_sweep import pallas_sweep_jit

        sublanes, max_tiles = 64, 262144  # tuned: tools/roofline.py sweep
        # (r5 re-swept 32/64/128 sublanes x 128Ki-512Ki tiles on-chip:
        # alternatives measure within run-to-run noise of this setting;
        # the ~12% gap to the op ceiling is not a tiling artifact)
        tile = sublanes * 128

        def run(start, n):
            _f, _n, t = pallas_sweep_jit(mid, tail, jnp.uint32(0), start, n,
                                         sublanes=sublanes, max_tiles=max_tiles)
            return int(t)

        n_units = max_tiles
        run(jnp.uint32(0), jnp.uint32(1))  # warm/compile INSIDE the try:
        # jax.jit compiles lazily, so a Mosaic lowering failure on another
        # TPU generation surfaces here, not at import
    except Exception:
        kernel = "xla-while"
        from bitcoincashplus_tpu.ops.miner import sweep_jit

        tgt = jnp.asarray(target_to_limbs_np(0))
        tile = 1 << 14 if on_cpu else 1 << 20
        n_units = 4 if on_cpu else 128

        def run(start, n):
            _f, _n, t = sweep_jit(mid, tail, tgt, start, n, tile=tile)
            return int(t)

        run(jnp.uint32(0), jnp.uint32(1))  # warm/compile the fallback
    rates = []
    for _ in range(4):
        start = jnp.uint32(random.getrandbits(32))
        t0 = time.perf_counter()
        tiles = run(start, jnp.uint32(n_units))
        dt = time.perf_counter() - t0
        rates.append(tiles * tile / dt)
    rates = sorted(rates[1:])
    ghs = rates[len(rates) // 2] / 1e9
    emit("sha256d_sweep_throughput_per_chip", round(ghs, 4), "GH/s",
         round(ghs / BASELINE_GHS, 6),
         kernel=kernel,
         note="truncated-h7 specialized double-SHA; r4 measured 88% of "
              "the 1.04 GH/s op-bound VPU ceiling — see ROOFLINE.md")


def _run_reindex(workdir, pipeline_depth=None, force_python=False,
                 telemetry=None):
    """One Node(-reindex) import; returns a stats dict (the native import's
    last_import_stats when that path ran, else a wall/verify decomposition
    from the chainstate bench counters that the Python path populates).
    ``pipeline_depth`` sets -pipelinedepth; ``force_python`` routes around
    the native fast-import engine so the Python validation engine (the
    pipelined-IBD code path) does the work; ``telemetry`` pins the
    -telemetry level (process-global — the telemetry_overhead bench
    restores it afterwards)."""
    from bitcoincashplus_tpu.node.config import Config
    from bitcoincashplus_tpu.node.node import Node

    cfg = Config()
    cfg.args["datadir"] = [workdir]
    cfg.args["regtest"] = ["1"]
    cfg.args["reindex"] = ["1"]
    if pipeline_depth is not None:
        cfg.args["pipelinedepth"] = [str(pipeline_depth)]
    if telemetry is not None:
        cfg.args["telemetry"] = [str(telemetry)]
    env_save = os.environ.get("BCP_NO_NATIVE_IMPORT")
    if force_python:
        os.environ["BCP_NO_NATIVE_IMPORT"] = "1"
    try:
        t0 = time.perf_counter()
        node = Node(config=cfg)
        wall_total = time.perf_counter() - t0
    finally:
        if force_python:
            if env_save is None:
                os.environ.pop("BCP_NO_NATIVE_IMPORT", None)
            else:
                os.environ["BCP_NO_NATIVE_IMPORT"] = env_save
    stats = node.last_import_stats or {}
    # Python-path import (no native engine): verify time lives in the
    # chainstate bench counters, not last_import_stats
    stats.setdefault("verify_s", node.chainstate.bench["verify_ms"] / 1e3)
    stats["pipeline"] = node.chainstate.pipeline_snapshot()
    tip = node.chainstate.tip()
    node.close()
    stats.setdefault("wall_s", wall_total)
    stats["node_wall_s"] = wall_total
    stats["tip_height"] = tip.height
    return stats


def _chainstate_digest(workdir) -> str:
    """Deterministic digest of the persisted UTXO set + best-block marker:
    coin rows are merged across the (possibly sharded) layout and hashed
    in global key order, so equal digests mean identical coin sets.
    Per-shard epoch/accumulator meta is excluded (flush-cadence local)."""
    import glob
    import hashlib

    from bitcoincashplus_tpu.store.kvstore import KVStore

    root = os.path.join(workdir, "regtest")
    paths = sorted(glob.glob(
        os.path.join(root, "chainstate.shard*.sqlite"))) or \
        [os.path.join(root, "chainstate.sqlite")]
    rows: dict[bytes, bytes] = {}
    for p in paths:
        kv = KVStore(p)
        for k, v in kv.iterate():
            if k[:1] == b"C" or k == b"B":
                rows[k] = v
        kv.close()
    h = hashlib.sha256()
    for k in sorted(rows):
        v = rows[k]
        h.update(len(k).to_bytes(4, "little"))
        h.update(k)
        h.update(len(v).to_bytes(4, "little"))
        h.update(v)
    return h.hexdigest()


def _make_chaos_corpus(srcdir, dstdir, window: int = 6, seed: int = 13):
    """Adversarial framing variant of a generated corpus: block records
    shuffled within a sliding window (out-of-order arrival -> the import
    loop's parking/cascade path, which forces settle-horizon barriers
    mid-pipeline) and garbage bytes interleaved between records (the
    scan-forward framing recovery). Consensus content is untouched, so
    every engine must still land on the identical chainstate."""
    import glob
    import random
    import struct

    from bitcoincashplus_tpu.consensus.params import regtest_params

    magic = regtest_params().netmagic
    records = []
    for path in sorted(glob.glob(
            os.path.join(srcdir, "regtest", "blocks", "blk*.dat"))):
        with open(path, "rb") as f:
            data = f.read()
        pos = 0
        while pos + 8 <= len(data):
            if data[pos:pos + 4] != magic:
                pos += 1
                continue
            (size,) = struct.unpack_from("<I", data, pos + 4)
            if pos + 8 + size > len(data):
                break
            records.append(data[pos + 8:pos + 8 + size])
            pos += 8 + size
    rng = random.Random(seed)
    # window shuffle (keep the genesis record first so the store's genesis
    # short-circuit stays cheap; every other ordering is fair game)
    out = records[:1]
    rest = records[1:]
    i = 0
    while i < len(rest):
        chunk = rest[i:i + window]
        rng.shuffle(chunk)
        out.extend(chunk)
        i += window
    blocks_dir = os.path.join(dstdir, "regtest", "blocks")
    os.makedirs(blocks_dir, exist_ok=True)
    with open(os.path.join(blocks_dir, "blk00000.dat"), "wb") as f:
        for raw in out:
            if rng.random() < 0.15:
                f.write(rng.randbytes(rng.randrange(1, 48)))  # garbage
            f.write(magic + struct.pack("<I", len(raw)) + raw)
    return len(out)


def _run_kernel_dimension(workdir, depth, gen):
    """ecdsa_kernel dimension (ISSUE 5): the pipelined import over the
    SAME mixed corpus once per device verify kernel (glv, w4), each in a
    fresh subprocess with BCP_ECDSA_KERNEL pinned and BCP_NO_NATIVE=1 —
    kernel selection is process-global and the native CPU lane would
    otherwise swallow every batch on CPU hosts (the native handle is also
    memoized at first load, so in-process toggling is unreliable). Each
    run warms its kernel at the packer's bucket shapes before the timed
    import, so compile cost stays out of the walls. Returns
    {kernel: {wall_s, digest, decompose_s, pack_s, device_s, ...}} plus
    glv_speedup."""
    code = r"""
import os, sys, json, time, tempfile
sys.path.insert(0, %(repo)r)
import jax
jax.config.update("jax_compilation_cache_dir",
                  os.path.join(tempfile.gettempdir(), "bcp-jax-test-cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)
import numpy as np
import bench
from bitcoincashplus_tpu.ops import ecdsa_batch
kernel = os.environ["BCP_ECDSA_KERNEL"]
# warm the kernel at the cross-block packer's dispatch shapes (2048 and
# the 1024 tail bucket) so XLA compile lands outside the timed legs
rng = np.random.default_rng(3)
for n in (2046, 900):
    ecdsa_batch.verify_batch(bench._make_sig_records(rng, 8, n),
                             backend="device", kernel=kernel)
# end-to-end dispatch path (host pack + lattice decompose + device +
# verdict) over one full packer bucket, fresh-content per run — the leg
# this kernel swap targets, free of the Python byte engine's wall
vts = []
for _ in range(3):
    recs = bench._make_sig_records(rng, 64, 2046)
    t0 = time.perf_counter()
    ok = ecdsa_batch.verify_batch(recs, backend="device", kernel=kernel)
    vts.append(time.perf_counter() - t0)
    assert bool(ok.all())
verify_wall = sorted(vts)[1]
s0 = ecdsa_batch.STATS.snapshot()
t0 = time.perf_counter()
st = bench._run_reindex(%(workdir)r, pipeline_depth=%(depth)d,
                        force_python=True)
wall = time.perf_counter() - t0
s1 = ecdsa_batch.STATS.snapshot()
out = {
    "wall_s": round(st["wall_s"], 2),
    "subprocess_wall_s": round(wall, 2),
    "verify_wall_s": round(verify_wall, 3),
    "verify_sigs_per_s": round(2046 / verify_wall),
    "tip_height": st["tip_height"],
    "digest": bench._chainstate_digest(%(workdir)r),
    "decompose_s": round(s1["glv_decompose_s"] - s0["glv_decompose_s"], 3),
    "pack_s": round(s1["glv_pack_s"] - s0["glv_pack_s"], 3),
    "device_s": round(s1["device_seconds"] - s0["device_seconds"], 3),
    "glv_dispatches": s1["glv_dispatches"] - s0["glv_dispatches"],
    "glv_fallbacks": s1["glv_fallbacks"] - s0["glv_fallbacks"],
    "dispatches": s1["dispatches"] - s0["dispatches"],
    "cpu_fallback_sigs": s1["cpu_fallback_sigs"] - s0["cpu_fallback_sigs"],
}
print("BENCHJSON " + json.dumps(out))
""" % {"repo": os.path.dirname(os.path.abspath(__file__)),
       "workdir": workdir, "depth": depth}
    runs = {}
    for kernel in ("w4", "glv"):
        env = dict(os.environ)
        env["BCP_ECDSA_KERNEL"] = kernel
        env["BCP_NO_NATIVE"] = "1"
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True, env=env,
                             timeout=3600)
        line = [ln for ln in out.stdout.splitlines()
                if ln.startswith("BENCHJSON ")]
        if not line:
            raise RuntimeError(
                f"kernel-dimension subprocess ({kernel}) failed: "
                f"{out.stderr[-400:]}")
        runs[kernel] = json.loads(line[-1][len("BENCHJSON "):])
        runs[kernel]["sigs_per_s"] = round(
            gen["sigs"] / max(runs[kernel]["wall_s"], 1e-9))
    return runs


def bench_import_pipeline():
    """ISSUE 4 tentpole metric: the pipelined Python IBD engine (settle
    horizon + cross-block lane packer) vs the serial engine on the SAME
    mixed-script corpus — per-leg wall times, measured overlap fraction,
    end-to-end sigs/s, and byte-identical-chainstate checks on both the
    mixed and the chaos (shuffled/garbage-framed) corpora. ISSUE 5 adds
    the ecdsa_kernel dimension: the same mixed corpus imported once per
    device verify kernel (w4 vs GLV, device-forced batches), emitting
    glv_speedup, per-stage packer/decompose/device timings, and the
    cross-kernel chainstate digest equality check."""
    import shutil
    import tempfile

    n_sigs = int(os.environ.get("BCP_BENCH_PIPELINE_SIGS", "4000"))
    depth = int(os.environ.get("BCP_BENCH_PIPELINE_DEPTH", "8"))
    workdir = tempfile.mkdtemp(prefix="bcp-pipe-mixed-")
    chaosdir = tempfile.mkdtemp(prefix="bcp-pipe-chaos-")
    try:
        from tools.gen_sigchain import generate

        gen = generate(workdir, n_sigs, mixed=True)
        _make_chaos_corpus(workdir, chaosdir)

        runs = {}
        digests = {}
        for corpus, cdir in (("mixed", workdir), ("chaos", chaosdir)):
            for mode, d in (("pipelined", depth), ("serial", 1)):
                st = _run_reindex(cdir, pipeline_depth=d, force_python=True)
                runs[(corpus, mode)] = st
                digests[(corpus, mode)] = _chainstate_digest(cdir)

        # ecdsa_kernel dimension: both kernels over the mixed corpus
        # (device-forced, subprocess-isolated); digests must match each
        # other AND the in-process runs above
        try:
            kruns = _run_kernel_dimension(workdir, depth, gen)
            # headline ratio: the verify dispatch path end to end (host
            # pack + lattice decompose + device + verdict) — the leg this
            # kernel swap targets; the import-wall ratio is reported
            # alongside but is byte-engine-bound under BCP_NO_NATIVE
            # (Python deserialization dominates it on CPU hosts)
            glv_speedup = round(
                kruns["w4"]["verify_wall_s"]
                / max(kruns["glv"]["verify_wall_s"], 1e-9), 4)
            glv_import_speedup = round(
                kruns["w4"]["wall_s"] / max(kruns["glv"]["wall_s"], 1e-9), 4)
            kernel_digests_identical = (
                kruns["w4"].pop("digest") == kruns["glv"].pop("digest")
            )
        except Exception as e:  # pragma: no cover - diagnostics only
            kruns = {"error": f"{type(e).__name__}: {e}"}
            glv_speedup = None
            glv_import_speedup = None
            kernel_digests_identical = None

        mp = runs[("mixed", "pipelined")]
        ms = runs[("mixed", "serial")]
        pipe = mp["pipeline"]
        sps_pipe = round(gen["sigs"] / mp["wall_s"])
        sps_serial = round(gen["sigs"] / ms["wall_s"])
        identical = {
            "mixed": digests[("mixed", "pipelined")]
            == digests[("mixed", "serial")],
            "chaos": digests[("chaos", "pipelined")]
            == digests[("chaos", "serial")],
            "mixed_vs_chaos": digests[("mixed", "pipelined")]
            == digests[("chaos", "pipelined")],
        }
        emit(
            "import_pipeline", sps_pipe, "sigs/s",
            round(sps_pipe / max(sps_serial, 1), 4),
            sigs_per_s_end_to_end=sps_pipe,
            serial_sigs_per_s_end_to_end=sps_serial,
            overlap_fraction=pipe.get("overlap_fraction", 0.0),
            legs_ms={
                "scan_ms": round(pipe.get("scan_ms", 0.0), 1),
                "device_ms": round(pipe.get("settle_wait_ms", 0.0), 1),
                "commit_ms": round(pipe.get("commit_ms", 0.0), 1),
            },
            pipeline={
                "depth": pipe.get("depth"),
                "max_depth": pipe.get("max_depth"),
                "settled_blocks": pipe.get("settled_blocks"),
                "unwinds": pipe.get("unwinds"),
                "lane_fill_pct": pipe.get("lane_fill_pct"),
                "packer_dispatches":
                    pipe.get("packer", {}).get("dispatches"),
            },
            corpus={"sigs": gen["sigs"], "blocks": gen["blocks"],
                    "bytes": gen["bytes"], "mixed": True},
            ecdsa_kernel=kruns,
            glv_speedup=glv_speedup,
            glv_import_speedup=glv_import_speedup,
            kernel_digests_identical=kernel_digests_identical,
            chaos={
                "pipelined_wall_s":
                    round(runs[("chaos", "pipelined")]["wall_s"], 2),
                "serial_wall_s":
                    round(runs[("chaos", "serial")]["wall_s"], 2),
                "unwinds": runs[("chaos", "pipelined")]["pipeline"]
                    .get("unwinds"),
            },
            chainstate_identical=identical,
            wall_s={"pipelined": round(mp["wall_s"], 2),
                    "serial": round(ms["wall_s"], 2)},
            note="Python validation engine (BCP_NO_NATIVE_IMPORT=1), "
                 "settle horizon depth vs serial on the identical corpora; "
                 "overlap_fraction = share of dispatched-batch lifetime "
                 "the host spent NOT blocked on settle (sync CPU backend "
                 "books verify at enqueue, inside scan_ms); vs_baseline = "
                 "pipelined/serial end-to-end sigs/s; glv_speedup = w4/glv "
                 "verify-dispatch wall (pack+decompose+device+verdict, "
                 "full 2048 bucket, fresh content, median of 3) — "
                 "glv_import_speedup is the whole-import ratio, byte-"
                 "engine-bound under BCP_NO_NATIVE on CPU hosts; kernel "
                 "runs are device-forced with chainstate digests compared "
                 "across kernels",
        )
        return {"pipeline_sigs_per_s": sps_pipe,
                "pipeline_overlap": pipe.get("overlap_fraction", 0.0),
                "pipeline_identical": all(identical.values()),
                "glv_speedup": glv_speedup}
    except Exception as e:  # pragma: no cover - diagnostics only
        emit("import_pipeline", -1, "sigs/s", 0.0,
             error=f"{type(e).__name__}: {e}")
        return None
    finally:
        shutil.rmtree(workdir, ignore_errors=True)
        shutil.rmtree(chaosdir, ignore_errors=True)


def _scalar_sweep(header80, target, max_nonces=1 << 32, tile=0):
    """Scalar host PoW loop for corpus generation — regtest targets hit
    in ~2 nonces, so the batched device sweep's per-dispatch latency
    would dominate corpus build time for no measurement value."""
    import struct as _st

    from bitcoincashplus_tpu.consensus.block import NONCE_OFFSET
    from bitcoincashplus_tpu.crypto.hashes import sha256d

    base = header80[:NONCE_OFFSET]
    for nonce in range(max_nonces):
        raw = base + _st.pack("<I", nonce)
        if int.from_bytes(sha256d(raw), "little") <= target:
            return nonce, nonce + 1
    return None, max_nonces


def bench_mining():
    """ISSUE 10: the device-resident mining loop's end-to-end trajectory.
    Three engines sweep the same nonce work on the same host:

      scalar        sweep_header_cpu — the reference generateBlocks loop
      per_dispatch  supervised sweep_header, one dispatch + blocking
                    scalar fetch per poll (the PR<=9 end-to-end shape);
                    measured at two poll granularities
      resident      mining/resident.ResidentSweep.advance — persistent
                    template buffers, pipelined segments, FIFO polls

    The headline ratio compares the resident path against the
    per-dispatch path at the FINEST poll cadence the per-dispatch shape
    can afford (its per-call overhead floors poll latency near ~1 ms on
    any host; the resident loop polls FASTER than that while sweeping
    bigger segments — the decoupling is the design). The equal-dispatch-
    size ratio is recorded alongside, honestly smaller. Digest parity:
    every engine must find the oracle-identical first hit on an easy
    target before its throughput counts. Writes BENCH_r10.json
    (schema_version=2 + host stamp) with the ROOFLINE.md §8 ops/nonce
    census delta inline."""
    import importlib.util

    from bitcoincashplus_tpu.mining.resident import ResidentSweep
    from bitcoincashplus_tpu.ops.dispatch import supervised_sweep
    from bitcoincashplus_tpu.ops.miner import sweep_header_cpu

    header = b"\xa5" * 80
    easy = 0x7FFFFF << (8 * 29)
    polls = int(os.environ.get("BCP_BENCH_MINING_POLLS", "40"))
    tile_small = 1 << 12   # per-dispatch fine poll granularity
    tile_big = 1 << 14     # resident segment / per-dispatch coarse

    def med(xs):
        return sorted(xs)[len(xs) // 2]

    # --- digest parity gate (easy target, all engines vs the oracle) ---
    n_oracle, _ = sweep_header_cpu(header, easy, max_nonces=1 << 13)
    assert n_oracle is not None
    sup = supervised_sweep()
    n_pd, _ = sup(header, easy, max_nonces=1 << 13, tile=tile_small)
    rs_par = ResidentSweep(tile=tile_small, seg_tiles=2, inflight=2,
                           kernel="exact")
    n_res, _ = rs_par.sweep(header, easy, max_nonces=1 << 13)
    rs_par.close()
    parity_ok = (n_pd == n_oracle and n_res == n_oracle)
    assert parity_ok, (n_oracle, n_pd, n_res)

    # --- scalar engine -------------------------------------------------
    n_scalar = 1 << 14
    t0 = time.perf_counter()
    sweep_header_cpu(header, 0, max_nonces=n_scalar)
    scalar_mhs = n_scalar / (time.perf_counter() - t0) / 1e6

    # --- per-dispatch engine (supervised, one dispatch per poll) -------
    def per_dispatch(tile):
        sup(header, 0, max_nonces=tile, tile=tile)  # warm/compile
        walls = []
        for _r in range(3):
            t0 = time.perf_counter()
            for k in range(polls):
                sup(header, 0, start_nonce=(k * tile) & 0xFFFFFFFF,
                    max_nonces=tile, tile=tile)
            walls.append(time.perf_counter() - t0)
        wall = med(walls)
        return {"tile": tile, "polls": polls,
                "mhs": round(polls * tile / wall / 1e6, 3),
                "poll_wall_ms": round(wall / polls * 1e3, 3)}

    pd_fine = per_dispatch(tile_small)
    pd_coarse = per_dispatch(tile_big)

    # --- resident engine (continuous advance over one template) --------
    rs = ResidentSweep(tile=tile_big, seg_tiles=1, inflight=2,
                       kernel="exact")
    rs.set_template(header, 0)
    rs.advance(tile_big)  # warm (shares the per-dispatch compile cache)
    walls = []
    for _r in range(3):
        t0 = time.perf_counter()
        rs.advance(polls * tile_big)
        walls.append(time.perf_counter() - t0)
    wall = med(walls)
    res = {"tile": tile_big, "seg_tiles": 1, "inflight": 2,
           "mhs": round(polls * tile_big / wall / 1e6, 3),
           "poll_wall_ms": round(wall / polls * 1e3, 3),
           "snapshot": rs.snapshot()}
    rs.close()

    # the headline: resident vs the per-dispatch path at the finest
    # cadence it affords — valid only while the resident loop's own poll
    # wall is no WORSE (it settles one pipelined segment per poll)
    cadence_ok = res["poll_wall_ms"] <= pd_fine["poll_wall_ms"] * 1.25
    headline_x = round(res["mhs"] / pd_fine["mhs"], 2)
    same_size_x = round(res["mhs"] / pd_coarse["mhs"], 2)

    # --- ops/nonce census delta (ROOFLINE.md §8) -----------------------
    census = None
    try:
        spec = importlib.util.spec_from_file_location(
            "bcp_roofline", os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "tools", "roofline.py"))
        roofline = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(roofline)
        h7, full, full_hoisted, _ = roofline.run_census()
        census = {"h7_hoisted": h7, "h7_pre_hoist": roofline.PRE_HOIST_H7,
                  "full_generic": full, "full_hoisted": full_hoisted}
    except Exception as e:  # pragma: no cover - census is best-effort
        census = {"error": f"{type(e).__name__}: {e}"}

    result = {
        "metric": "mining",
        **_bench_stamp(),
        "scalar_mhs": round(scalar_mhs, 3),
        "per_dispatch_fine": pd_fine,
        "per_dispatch_coarse": pd_coarse,
        "resident": res,
        "resident_vs_dispatch_x": headline_x,
        "resident_same_dispatch_size_x": same_size_x,
        "resident_poll_cadence_ok": cadence_ok,
        "digest_parity": {"oracle_nonce": int(n_oracle),
                          "per_dispatch": int(n_pd),
                          "resident": int(n_res), "ok": parity_ok},
        "census_ops_per_nonce": census,
        "note": "CPU backend = memcpy-scale dispatch lower bound; the "
                "real gap is the tunneled-TPU ~15x (BENCH_r05/r08). "
                "headline resident_vs_dispatch_x compares against the "
                "finest poll cadence the per-dispatch shape affords "
                "(per-call overhead floors its poll latency); the "
                "resident loop polls at least as often while dispatching "
                "bigger segments — equal-dispatch-size ratio recorded "
                "as resident_same_dispatch_size_x",
    }
    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "BENCH_r10.json"), "w") as f:
        json.dump(result, f, indent=1)
        f.write("\n")
    emit("mining_resident_speedup", headline_x, "x", 0.0,
         **{k: v for k, v in result.items() if k != "metric"})
    return {"mining_resident_vs_dispatch_x": headline_x,
            "mining_resident_mhs": res["mhs"]}


def _gen_fork_corpus(workdir, segments=6, seg_len=4, fork_depth=3):
    """A reorg-heavy corpus (ISSUE 9): linear segments punctuated by
    deeper competing branches. Each round mines ``seg_len`` blocks, rolls
    the chain back ``fork_depth`` (invalidateblock), mines a longer
    replacement branch, and reconsiders the stale branch — the block
    files then carry BOTH branches in chronological order, so a reimport
    must fight through a fork war every few blocks: stale branches enter
    the speculation tree, lose on work, and drop (or reorg out if they
    settled first). Returns corpus counts."""
    from bitcoincashplus_tpu.mining.assembler import BlockAssembler
    from bitcoincashplus_tpu.mining.generate import mine_block
    from bitcoincashplus_tpu.node.config import Config
    from bitcoincashplus_tpu.node.node import Node
    from bitcoincashplus_tpu.wallet.keys import CKey

    cfg = Config()
    cfg.args["datadir"] = [workdir]
    cfg.args["regtest"] = ["1"]
    node = Node(config=cfg)
    cs = node.chainstate
    spk = CKey(0x0906).p2pkh_script()
    assembler = BlockAssembler(cs, None)
    xn = [0]

    def mine(n):
        # per-block extranonce entropy: a replacement branch's first
        # block must not assemble byte-identical to the stale one it
        # replaces (same parent/height/time/script -> same hash, which
        # would arrive as a duplicate of a FAILED index)
        for _ in range(n):
            xn[0] += 1009
            blk = mine_block(assembler, spk, sweep=_scalar_sweep,
                             extranonce_start=xn[0])
            cs.process_new_block(blk)

    n_blocks = n_forks = 0
    for _ in range(segments):
        mine(seg_len)
        n_blocks += seg_len
        tip = cs.tip()
        stale_root = tip.get_ancestor(tip.height - fork_depth + 1)
        cs.invalidate_block(stale_root)
        mine(fork_depth + 1)
        n_blocks += fork_depth + 1
        cs.reconsider_block(stale_root)  # stale branch: candidate again
        n_forks += 1
    height = cs.tip().height
    node.close()
    return {"blocks": n_blocks, "forks": n_forks, "height": height,
            "fork_depth": fork_depth}


def bench_fork_storm():
    """ISSUE 9 satellite metric: the speculation-tree pipelined engine vs
    the serial engine over the SAME reorg-heavy corpus — wall times, the
    unwind/branch-drop overhead fraction (speculative connects whose work
    was thrown away), reorg accounting, and the byte-identical-chainstate
    check. Writes BENCH_r09.json (schema_version=2 host stamp)."""
    import shutil
    import tempfile

    segments = int(os.environ.get("BCP_BENCH_FORKSTORM_SEGMENTS", "6"))
    depth = int(os.environ.get("BCP_BENCH_PIPELINE_DEPTH", "8"))
    workdir = tempfile.mkdtemp(prefix="bcp-forkstorm-")
    try:
        corpus = _gen_fork_corpus(workdir, segments=segments)
        runs = {}
        digests = {}
        for mode, d in (("pipelined", depth), ("serial", 1)):
            runs[mode] = _run_reindex(workdir, pipeline_depth=d,
                                      force_python=True)
            digests[mode] = _chainstate_digest(workdir)
        pipe = runs["pipelined"]["pipeline"]
        tree = pipe.get("tree", {})
        settled = max(1, pipe.get("settled_blocks", 0))
        wasted = (pipe.get("unwound_blocks", 0)
                  + tree.get("dropped_blocks", 0))
        overhead_fraction = round(wasted / (settled + wasted), 4)
        speedup = round(runs["serial"]["wall_s"]
                        / max(runs["pipelined"]["wall_s"], 1e-9), 4)
        result = {
            "metric": "fork_storm",
            **_bench_stamp(),
            "corpus": corpus,
            "wall_s": {"pipelined": round(runs["pipelined"]["wall_s"], 3),
                       "serial": round(runs["serial"]["wall_s"], 3)},
            "pipelined_vs_serial_speedup": speedup,
            "unwind_overhead_fraction": overhead_fraction,
            "tree": {
                "reorgs": tree.get("reorgs"),
                "reorg_depth_max": tree.get("reorg_depth_max"),
                "branch_drops": tree.get("branch_drops"),
                "dropped_blocks": tree.get("dropped_blocks"),
                "branches_live_max": tree.get("branches_live_max"),
                "serial_linear_fallbacks":
                    tree.get("serial_linear_fallbacks"),
            },
            "unwinds": pipe.get("unwinds"),
            "chainstate_identical": digests["pipelined"]
            == digests["serial"],
            "note": "Python validation engine (BCP_NO_NATIVE_IMPORT=1) "
                    "over a coinbase-only fork-war corpus: every segment "
                    "carries a stale branch the import must out-work; "
                    "unwind_overhead_fraction = speculative blocks whose "
                    "work was dropped / (settled + dropped) — the price "
                    "of concurrent branch validation on this corpus",
        }
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "BENCH_r09.json"), "w") as f:
            json.dump(result, f, indent=1)
            f.write("\n")
        emit("fork_storm", runs["pipelined"]["wall_s"], "s", speedup,
             **{k: v for k, v in result.items() if k != "metric"})
        return {"fork_storm_speedup": speedup,
                "fork_storm_identical": result["chainstate_identical"]}
    except Exception as e:  # pragma: no cover - diagnostics only
        emit("fork_storm", -1, "s", 0.0, error=f"{type(e).__name__}: {e}")
        return None
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def _utxo_key(i: int) -> bytes:
    return i.to_bytes(32, "little") + b"\x00\x00\x00\x00"


def _utxo_coin(i: int) -> bytes:
    # valid Coin serialization: compact(height*2+cb), compact(value),
    # var_bytes(20-byte script)
    return bytes([2, 5, 20]) + bytes([i & 0xFF]) * 20


def _churn_store(workdir, n_shards, n_coins, chunk, rounds, half,
                 wal=False, bloom=True):
    """Seed n_coins into a fresh store in `chunk`-sized commits, then run
    `rounds` churn commits of `half` adds + `half` deletes each. Returns
    seed/churn wall times and the store's own flush-phase seconds."""
    from bitcoincashplus_tpu.store.sharded import ShardedCoinsDB

    db = ShardedCoinsDB(workdir, n_shards=n_shards, wal=wal)
    db.bloom_enabled = bloom
    best = b"\x11" * 32
    t0 = time.perf_counter()
    for lo in range(0, n_coins, chunk):
        hi = min(lo + chunk, n_coins)
        db.batch_write_serialized(
            [(_utxo_key(i), _utxo_coin(i)) for i in range(lo, hi)], best)
    seed_s = time.perf_counter() - t0

    churn_wall = []
    churn_flush = []
    for r in range(rounds):
        adds = range(n_coins + r * half, n_coins + (r + 1) * half)
        dels = range(r * half, (r + 1) * half)
        entries = [(_utxo_key(i), _utxo_coin(i)) for i in adds]
        entries += [(_utxo_key(i), None) for i in dels]
        ta = time.perf_counter()
        db.batch_write_serialized(entries, best)
        churn_wall.append(time.perf_counter() - ta)
        churn_flush.append(db.last_flush["seconds"])
    bl = db.bloom_stats
    return db, {
        "seed_s": round(seed_s, 3),
        "seed_coins_per_s": round(n_coins / seed_s),
        "churn_wall_s": round(sum(churn_wall), 3),
        "churn_flush_s": round(sum(churn_flush), 4),
        "churn_entries_per_s": round(rounds * 2 * half / sum(churn_wall)),
        "flush_entries_per_s": round(rounds * 2 * half / sum(churn_flush)),
        "wal": wal,
        "bloom": {"enabled": bloom, **bl,
                  "old_lookup_cut": round(
                      bl["skipped"] / max(bl["checked"], 1), 4)},
    }


def bench_utxo_store():
    """ISSUE 13 satellite metric: sharded chainstate flush throughput (4
    shards vs the single-shard degenerate case) over a million-coin
    churn, snapshot dump/load rates at the same scale, and the snapshot
    path's time-to-first-RPC. Re-measured multi-core (BENCH_r12 follow-
    up): the sweep now also covers -coinswal=1 at 4 shards and a bloom-
    off control quantifying the write-side accumulator-lookup cut.
    Writes BENCH_r12.json."""
    import shutil
    import tempfile

    from bitcoincashplus_tpu.store import snapshot as snapshot_mod
    from bitcoincashplus_tpu.store.sharded import ShardedCoinsDB

    n_coins = int(os.environ.get("BCP_BENCH_UTXO_COINS", "1000000"))
    chunk = 100_000
    rounds = 4
    half = max(1, min(50_000, n_coins // (2 * rounds)))
    workdir = tempfile.mkdtemp(prefix="bcp-utxostore-")
    try:
        configs = {}
        snap_stats = {}
        # label -> (n_shards, wal, bloom); "4" is the canonical config
        # (snapshot round-trip hangs off it), the extra legs isolate the
        # WAL commit win and the bloom filter's old-value-lookup cut
        sweep = (("1", 1, False, True), ("4", 4, False, True),
                 ("4_wal", 4, True, True), ("4_nobloom", 4, False, False))
        for label, n_shards, wal, bloom in sweep:
            d = os.path.join(workdir, f"s{label}")
            db, stats = _churn_store(d, n_shards, n_coins, chunk,
                                     rounds, half, wal=wal, bloom=bloom)
            configs[label] = stats
            if label != "4":
                db.close()
                continue
            # snapshot round-trip from the 4-shard store at full size
            live = db.count_coins()
            best = db.best_block()
            digest = db.muhash_digest()
            snap_dir = os.path.join(workdir, "snap")
            ta = time.perf_counter()
            snapshot_mod.dump_snapshot(db, snap_dir, [bytes(80)], 0,
                                       best, "regtest")
            dump_s = time.perf_counter() - ta
            db.close()
            dst = ShardedCoinsDB(os.path.join(workdir, "dst"), n_shards=4)
            tb = time.perf_counter()
            snapshot_mod.load_snapshot(snap_dir, dst, "regtest",
                                       expected_hash=best,
                                       expected_digest=digest)
            load_s = time.perf_counter() - tb
            # first RPC off the snapshot: a point read at the new tip
            probe = _utxo_key(n_coins + rounds * half - 1)  # churn survivor
            tc = time.perf_counter()
            got = dst.get_serialized_many([probe])
            first_read_s = time.perf_counter() - tc
            assert probe in got
            dst.close()
            snap_stats = {
                "coins": live,
                "dump_s": round(dump_s, 3),
                "dump_coins_per_s": round(live / dump_s),
                "load_s": round(load_s, 3),
                "load_coins_per_s": round(live / load_s),
                "first_read_after_load_s": round(first_read_s, 6),
                "time_to_first_rpc_s": round(load_s + first_read_s, 3),
            }
        flush_speedup = round(
            configs["4"]["flush_entries_per_s"]
            / max(configs["1"]["flush_entries_per_s"], 1), 4)
        commit_speedup = round(
            configs["4"]["churn_entries_per_s"]
            / max(configs["1"]["churn_entries_per_s"], 1), 4)
        wal_commit_speedup = round(
            configs["4_wal"]["churn_entries_per_s"]
            / max(configs["4"]["churn_entries_per_s"], 1), 4)
        bloom_commit_speedup = round(
            configs["4"]["churn_entries_per_s"]
            / max(configs["4_nobloom"]["churn_entries_per_s"], 1), 4)
        result = {
            "metric": "utxo_store",
            **_bench_stamp(),
            "coins": n_coins,
            "churn": {"rounds": rounds, "adds": half, "deletes": half},
            "cores_ge_shards": (os.cpu_count() or 1) >= 4,
            "shards": configs,
            "flush_speedup_4v1": flush_speedup,
            "commit_speedup_4v1": commit_speedup,
            "wal_commit_speedup_4": wal_commit_speedup,
            "bloom_commit_speedup_4": bloom_commit_speedup,
            "bloom_old_lookup_cut": configs["4"]["bloom"]["old_lookup_cut"],
            "meets_1_5x_bar": flush_speedup >= 1.5,
            "snapshot": snap_stats,
            "note": "flush_* = the parallel per-shard apply phase "
                    "(journals/manifest/accumulator excluded — those are "
                    "identical work at any fanout); commit_* = whole "
                    "batch_write_serialized wall. On a single-core host "
                    "the fanout win is bounded by the fsync/IO fraction "
                    "of the flush (sqlite page work serializes on the "
                    "one core) — the 1.5x bar presumes cores >= shards "
                    "(cores_ge_shards records whether this host met "
                    "that). 4_wal = -coinswal=1 at the same fanout; "
                    "4_nobloom disables the write-side key bloom, so "
                    "bloom_commit_speedup_4 is the accumulator "
                    "old-value-lookup cut's whole-commit win and "
                    "bloom_old_lookup_cut the fraction of changed-key "
                    "lookups the filter skipped. time_to_first_rpc_s = "
                    "snapshot load + first point read — the assumeutxo "
                    "serve point; a full IBD instead scales with chain "
                    "length (see BENCH.md reindex numbers), not UTXO "
                    "size.",
        }
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "BENCH_r12.json"), "w") as f:
            json.dump(result, f, indent=1)
            f.write("\n")
        emit("utxo_store_flush_speedup_4v1", flush_speedup, "x",
             flush_speedup,
             **{k: v for k, v in result.items() if k != "metric"})
        return {"utxo_store_flush_speedup_4v1": flush_speedup,
                "utxo_store_wal_commit_speedup": wal_commit_speedup,
                "utxo_store_bloom_commit_speedup": bloom_commit_speedup,
                "utxo_snapshot_load_coins_per_s":
                    snap_stats.get("load_coins_per_s")}
    except Exception as e:  # pragma: no cover - diagnostics only
        emit("utxo_store_flush_speedup_4v1", -1, "x", 0.0,
             error=f"{type(e).__name__}: {e}")
        return None
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def _storm_corpus(n_txs: int, seed: int = 20):
    """Seeded flood corpus: structurally-valid unsigned transactions in
    random package shapes — chains up to the 25-deep ancestor limit,
    1-3-output fans, fees in [100, 50000). Same seed => byte-identical
    corpus, so the batched and per-tx pools see the same flood."""
    import random as _random

    from bitcoincashplus_tpu.consensus.tx import (COutPoint, CTransaction,
                                                  CTxIn, CTxOut)

    rng = _random.Random(seed)
    corpus = []     # (tx, fee)
    open_outs = []  # (txid, vout, depth): spendable in-corpus outpoints
    for i in range(n_txs):
        n_out = rng.randint(1, 3)
        if open_outs and rng.random() < 0.72:
            j = rng.randrange(len(open_outs))
            parent_txid, vout, depth = open_outs[j]
            open_outs[j] = open_outs[-1]
            open_outs.pop()
            inputs = [COutPoint(parent_txid, vout)]
        else:
            depth = 0
            inputs = [COutPoint(i.to_bytes(4, "big") * 8, 0)]
        tx = CTransaction(
            vin=tuple(CTxIn(op, bytes([i & 0xFF, (i >> 8) & 0xFF]))
                      for op in inputs),
            vout=tuple(CTxOut(10_000, b"\x51") for _ in range(n_out)))
        corpus.append((tx, rng.randint(100, 50_000)))
        if depth + 1 < 25:
            for v in range(n_out):
                open_outs.append((tx.txid, v, depth + 1))
    return corpus


def _storm_admit(pool, corpus, mempool_mod):
    """Flood `corpus` through the pool the way AcceptToMemoryPool does —
    add_unchecked + trim_to_size per admission, a prioritise delta every
    97th tx — timing each admission. Returns per-admission seconds."""
    lat = []
    for k, (tx, fee) in enumerate(corpus):
        entry = mempool_mod.MempoolEntry(tx, fee, k, 1)
        t0 = time.perf_counter()
        pool.add_unchecked(entry)
        pool.trim_to_size()
        if k % 97 == 96:
            # mid-storm prioritise (negative deltas included) — the
            # frontier must absorb re-scores while eviction is live
            pool.prioritise(corpus[k - 31][0].txid,
                            ((k * 2654435761) % 11_000) - 3_000)
        lat.append(time.perf_counter() - t0)
    return lat


def bench_mempool_storm():
    """ISSUE 20 headline: flood-scale mempool. Leg (a) feeds the same
    seeded flood (matched scale, -maxmempool sized to force bulk
    eviction) through the batched pool and the per-tx reference pool and
    asserts byte-identical surviving mempool contents AND a
    byte-identical block template, reporting the batched-vs-per-tx
    speedup at saturation. Leg (b) runs the full 100k-tx flood batched
    and enforces the accept-p99 and template-build latency bars. Writes
    BENCH_r20.json."""
    from bitcoincashplus_tpu.consensus.merkle import compute_merkle_root
    from bitcoincashplus_tpu.mempool import mempool as mempool_mod

    n_txs = int(os.environ.get("BCP_BENCH_STORM_TXS", "100000"))
    n_par = min(n_txs, int(os.environ.get("BCP_BENCH_STORM_PARITY_TXS",
                                          "20000")))
    p99_bar_ms = float(os.environ.get("BCP_BENCH_STORM_P99_MS", "2.0"))
    tpl_bar_ms = float(os.environ.get("BCP_BENCH_STORM_TPL_MS", "5000"))
    # block-sized template cap: the reference selector's full scan per
    # emitted package is O(template_txs * pool) — an uncapped template
    # over the whole pool would make the per-tx control take hours at
    # parity scale, and real templates are block-capped anyway
    tpl_cap = int(os.environ.get("BCP_BENCH_STORM_TPL_BYTES", "200000"))
    corpus = _storm_corpus(n_txs)

    def total_bytes(txs):
        return sum(mempool_mod.MempoolEntry(tx, fee, 0, 1).size
                   for tx, fee in txs)

    def quantile(xs, q):
        ys = sorted(xs)
        return ys[min(len(ys) - 1, int(q * len(ys)))]

    def run_flavor(batch, flood, cap):
        pool = mempool_mod.CTxMemPool(max_size_bytes=cap, batch=batch)
        lat = _storm_admit(pool, flood, mempool_mod)
        # template builds at saturation: select + pack + merkle root —
        # the CreateNewBlock work that doesn't need a chainstate
        sel, tpl_times = None, []
        for _ in range(3):
            t0 = time.perf_counter()
            sel = pool.select_for_block(tpl_cap, 2, 1_000_000_000)
            vtx = [e.tx.serialize() for e in sel]
            root, _ = compute_merkle_root(
                [b"\x00" * 32] + [e.txid for e in sel])
            tpl_times.append(time.perf_counter() - t0)
        assert root is not None and vtx is not None
        return pool, lat, tpl_times, sel

    # ---- leg (a): batched-vs-per-tx parity + speedup at saturation ----
    flood_a = corpus[:n_par]
    cap_a = int(total_bytes(flood_a) * 0.6)  # forces bulk eviction
    pool_ref, lat_ref, tpl_ref, sel_ref = run_flavor(False, flood_a, cap_a)
    pool_bat, lat_bat, tpl_bat, sel_bat = run_flavor(True, flood_a, cap_a)
    assert sorted(pool_bat.entries) == sorted(pool_ref.entries), \
        "batched pool diverged from per-tx reference"
    assert pool_bat.total_size == pool_ref.total_size
    tmpl_bat = b"".join(e.tx.serialize() for e in sel_bat)
    tmpl_ref = b"".join(e.tx.serialize() for e in sel_ref)
    assert tmpl_bat == tmpl_ref, "block template diverged"
    # saturation = the flood tail, where eviction + deep frontiers bite
    tail = len(flood_a) // 2
    admit_speedup = sum(lat_ref[tail:]) / max(sum(lat_bat[tail:]), 1e-9)
    tpl_speedup = (sorted(tpl_ref)[len(tpl_ref) // 2]
                   / max(sorted(tpl_bat)[len(tpl_bat) // 2], 1e-9))
    total_speedup = ((sum(lat_ref) + sum(tpl_ref))
                     / max(sum(lat_bat) + sum(tpl_bat), 1e-9))

    # ---- leg (b): full-scale batched flood with latency bars ----------
    cap_b = int(total_bytes(corpus) * 0.7)
    pool_b, lat_b, tpl_b, sel_b = run_flavor(True, corpus, cap_b)
    p50_ms = quantile(lat_b, 0.50) * 1e3
    p99_ms = quantile(lat_b, 0.99) * 1e3
    tpl_ms = sorted(tpl_b)[len(tpl_b) // 2] * 1e3
    perf = pool_b.perf_snapshot()
    meets_p99 = p99_ms <= p99_bar_ms
    meets_tpl = tpl_ms <= tpl_bar_ms

    result = {
        "metric": "mempool_storm",
        **_bench_stamp(),
        "txs": n_txs,
        "template_cap_bytes": tpl_cap,
        "parity": {
            "txs": n_par,
            "maxmempool_bytes": cap_a,
            "survivors": len(pool_bat.entries),
            "template_txs": len(sel_bat),
            "template_bytes": len(tmpl_bat),
            "byte_identical_mempool": True,   # asserted above
            "byte_identical_template": True,  # asserted above
            "admit_speedup_at_saturation": round(admit_speedup, 3),
            "template_speedup": round(tpl_speedup, 3),
            "total_speedup": round(total_speedup, 3),
        },
        "flood": {
            "txs": len(corpus),
            "maxmempool_bytes": cap_b,
            "survivors": len(pool_b.entries),
            "accept_p50_ms": round(p50_ms, 4),
            "accept_p99_ms": round(p99_ms, 4),
            "accept_p99_bar_ms": p99_bar_ms,
            "template_build_ms": round(tpl_ms, 3),
            "template_build_bar_ms": tpl_bar_ms,
            "template_txs": len(sel_b),
            "meets_accept_p99_bar": meets_p99,
            "meets_template_bar": meets_tpl,
            "pool_perf": {k: perf[k] for k in
                          ("frontier_depth", "column_syncs", "rows_synced",
                           "frontier_pushes", "frontier_stale_pops",
                           "frontier_rebuilds", "bulk_evict_episodes",
                           "bulk_evicted", "staged_removals",
                           "select_batched") if k in perf},
        },
        "note": "admission = add_unchecked + trim_to_size per tx (the "
                "ATMP commit path) with prioritise deltas mid-storm; "
                "template = select_for_block + tx pack + merkle root "
                "(the chainstate-free CreateNewBlock work). Saturation "
                "speedup compares the flood tail, where the reference "
                "path's full-scan eviction and selection go quadratic "
                "while the batched pool pops incremental frontiers. "
                "Parity legs assert byte-identical surviving mempool "
                "contents and a byte-identical template vs the per-tx "
                "reference on the same seeded flood.",
    }
    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "BENCH_r20.json"), "w") as f:
        json.dump(result, f, indent=1)
        f.write("\n")
    emit("mempool_storm_accept_p99_ms", round(p99_ms, 4), "ms",
         round(p99_bar_ms / max(p99_ms, 1e-9), 2), bar_ms=p99_bar_ms,
         p50_ms=round(p50_ms, 4), meets_bar=meets_p99)
    emit("mempool_storm_template_ms", round(tpl_ms, 3), "ms",
         round(tpl_bar_ms / max(tpl_ms, 1e-9), 2), bar_ms=tpl_bar_ms,
         template_txs=len(sel_b), meets_bar=meets_tpl)
    emit("mempool_storm_batched_speedup", round(total_speedup, 3), "x",
         round(total_speedup, 3),
         admit_speedup_at_saturation=round(admit_speedup, 3),
         template_speedup=round(tpl_speedup, 3),
         parity_txs=n_par, flood_txs=n_txs,
         byte_identical=True)
    assert meets_p99, (
        f"accept p99 {p99_ms:.3f}ms over the {p99_bar_ms}ms bar")
    assert meets_tpl, (
        f"template build {tpl_ms:.1f}ms over the {tpl_bar_ms}ms bar")
    return {"mempool_storm_batched_speedup": round(total_speedup, 3),
            "mempool_storm_accept_p99_ms": round(p99_ms, 4),
            "mempool_storm_template_ms": round(tpl_ms, 3)}


def bench_telemetry_overhead():
    """ISSUE 6 satellite: what the unified telemetry layer costs. The
    import_pipeline corpus is imported through the pipelined Python
    engine once per -telemetry level (off / counters / trace), min-of-N
    walls (min is the noise-robust statistic for a fixed workload on a
    shared host). The counters level must stay under the 2% budget —
    asserted, and recorded in BENCH_r06.json next to this script. The
    trace run also schema-checks its own span dump (every event carries
    name/ph/ts, X-phase events carry dur) so the perfetto contract is
    bench-enforced, not just unit-tested."""
    import shutil
    import tempfile

    from bitcoincashplus_tpu.util import telemetry as tm

    n_sigs = int(os.environ.get("BCP_BENCH_TELEMETRY_SIGS", "3000"))
    depth = int(os.environ.get("BCP_BENCH_PIPELINE_DEPTH", "8"))
    repeats = int(os.environ.get("BCP_BENCH_TELEMETRY_REPEATS", "3"))
    workdir = tempfile.mkdtemp(prefix="bcp-telemetry-bench-")
    mode_save = tm.mode()
    try:
        from tools.gen_sigchain import generate

        gen = generate(workdir, n_sigs, mixed=True)
        # untimed warm-up import: the first reindex pays one-off costs
        # (jit/cache warming, sqlite page cache) that would otherwise be
        # billed entirely to whichever level runs first
        _run_reindex(workdir, pipeline_depth=depth, force_python=True,
                     telemetry="counters")
        # INTERLEAVED rounds (off, counters, trace per round), min per
        # level: host-cache drift across a long run would otherwise bias
        # whichever level ran last faster than the first — a consecutive
        # per-level loop measured "off" consistently SLOWER than counters
        walls = {"off": [], "counters": [], "trace": []}
        trace_events = 0
        trace_schema_ok = None
        for _ in range(repeats):
            for level in ("off", "counters", "trace"):
                tm.TRACER.clear()
                st = _run_reindex(workdir, pipeline_depth=depth,
                                  force_python=True, telemetry=level)
                walls[level].append(st["wall_s"])
                if level == "trace":
                    events = tm.TRACER.chrome_trace()["traceEvents"]
                    trace_events = len(events)
                    trace_schema_ok = bool(events) and all(
                        isinstance(ev.get("name"), str)
                        and ev.get("ph") in ("X", "i")
                        and isinstance(ev.get("ts"), (int, float))
                        and (ev["ph"] != "X"
                             or isinstance(ev.get("dur"), (int, float)))
                        for ev in events
                    )
        walls = {k: min(v) for k, v in walls.items()}
        counters_pct = (walls["counters"] / walls["off"] - 1.0) * 100.0
        trace_pct = (walls["trace"] / walls["off"] - 1.0) * 100.0
        # ISSUE 8 gate extension: the measured import path now includes
        # the device-lane accounting (watchdog beats per settled block,
        # program watches + transfer counters on every device dispatch,
        # the scrape-time collectors) — record that it was live so the
        # < 2% budget provably covers it
        from bitcoincashplus_tpu.util import devicewatch as _dw

        beats = _dw.WATCHDOG.beat_totals()
        device_accounting = {
            "included": True,
            "watchdog_beats": beats,
            "watched_programs": sorted(_dw.snapshot()["programs"]),
        }
        assert beats.get("pipeline", 0) > 0, (
            "device accounting not exercised: the pipelined import "
            "recorded no watchdog beats")
        result = {
            "metric": "telemetry_overhead",
            **_bench_stamp(),
            "device_accounting": device_accounting,
            "corpus": {"sigs": gen["sigs"], "blocks": gen["blocks"],
                       "bytes": gen["bytes"], "mixed": True,
                       "pipeline_depth": depth, "repeats": repeats},
            "wall_s": {k: round(v, 3) for k, v in walls.items()},
            "counters_overhead_pct": round(counters_pct, 3),
            "trace_overhead_pct": round(trace_pct, 3),
            "budget_pct": 2.0,
            "counters_under_budget": counters_pct < 2.0,
            "trace_events": trace_events,
            "trace_schema_ok": trace_schema_ok,
            "note": "pipelined Python engine (force_python), min-of-N "
                    "walls per -telemetry level on the import_pipeline "
                    "corpus; trace run schema-checks its span dump",
        }
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "BENCH_r06.json"), "w") as f:
            json.dump(result, f, indent=1)
            f.write("\n")
        assert trace_schema_ok, "trace dump failed schema validation"
        assert counters_pct < 2.0, (
            f"counters-mode telemetry overhead {counters_pct:.2f}% "
            f"breaks the 2% budget (walls: {walls})")
        emit("telemetry_overhead", round(counters_pct, 3), "%",
             round(2.0 / max(counters_pct, 1e-3), 4),
             **{k: v for k, v in result.items() if k != "metric"})
        return {"telemetry_overhead_pct": round(counters_pct, 3)}
    except Exception as e:  # pragma: no cover - diagnostics only
        emit("telemetry_overhead", -1, "%", 0.0,
             error=f"{type(e).__name__}: {e}")
        return None
    finally:
        try:
            tm.set_mode(mode_save)
        except ValueError:
            pass
        shutil.rmtree(workdir, ignore_errors=True)


def _bench_serving_levels():
    """ISSUE 7 tentpole metric: synchronous vs serviced accept-path
    signature throughput at several offered-load levels, CPU lower bound.

    The unit of work is a 2-input transaction's fresh sigcheck records.
    'sync' is the -sigservice=off accept shape: one per-tx
    ecdsa_batch.verify_batch call per transaction, fanned across worker
    threads (generous to sync — the real node serializes P2P ingest on
    one event loop). 'serviced' enqueues the same transactions into a
    SigService and awaits the per-tx futures. Levels:

      light      — closed loop, 1 submitter (the latency floor: a lone
                   tx pays kick-flush handoff, never the full deadline)
      concurrent — closed loop, 8 submitters (RPC-thread shape)
      saturation — open loop: submit the whole burst, then await (the
                   tx-storm shape; arrivals outpace service, batches
                   grow to the bucket and the device-lane amortization
                   pays — the acceptance bar is serviced >= 2x sync here)

    Per-tx latencies are enqueue->verdict. Results land in BENCH_r07.json
    (first entry in the serving trajectory)."""
    import threading as _threading

    from bitcoincashplus_tpu import native as _nat
    from bitcoincashplus_tpu.crypto import secp256k1 as _oracle
    from bitcoincashplus_tpu.ops import ecdsa_batch
    from bitcoincashplus_tpu.script.interpreter import SigCheckRecord
    from bitcoincashplus_tpu.serving import SigService

    rng = np.random.RandomState(0x5E21)
    ntx = int(os.environ.get("BCP_BENCH_SERVING_TXS", "1000"))
    repeats = int(os.environ.get("BCP_BENCH_SERVING_REPEATS", "2"))

    # a small keypair pool (Python point_mul is ~50 ms each) signing a
    # FRESH message per record: every record still has a distinct
    # (sighash, r, s, pubkey) identity, so SigService in-flight dedup
    # never collapses the workload
    sign = _nat.ecdsa_sign if _nat.available() else _oracle.ecdsa_sign
    keypool = []
    for _ in range(16):
        secret = int.from_bytes(rng.bytes(32), "big") % (_oracle.N - 1) + 1
        keypool.append((secret, _oracle.point_mul(secret, _oracle.G)))

    def fresh_records(n):
        out = []
        for i in range(n):
            secret, pub = keypool[i % len(keypool)]
            e = int.from_bytes(rng.bytes(32), "big") % _oracle.N
            r, s = sign(secret, e)
            out.append(SigCheckRecord(pub, r, s, e))
        return out

    def pctl(lat, q):
        s = sorted(lat)
        return s[min(len(s) - 1, int(q * len(s)))] * 1e3

    def run_sync(txs, workers):
        import queue as _queue

        q = _queue.Queue()
        for t in txs:
            q.put(t)
        lat = []
        lock = _threading.Lock()

        def w():
            while True:
                try:
                    chunk = q.get_nowait()
                except _queue.Empty:
                    return
                t0 = time.monotonic()
                ecdsa_batch.verify_batch(chunk, backend="cpu")
                with lock:
                    lat.append(time.monotonic() - t0)

        threads = [_threading.Thread(target=w) for _ in range(workers)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return time.monotonic() - t0, lat

    def run_serviced(txs, submitters, open_loop):
        svc = SigService(backend="cpu", deadline_ms=4, lanes=2046).start()
        lat = []
        lock = _threading.Lock()
        chunks = [txs[i::submitters] for i in range(submitters)]

        def w(i):
            if open_loop:
                pairs = [(time.monotonic(), svc.submit(c))
                         for c in chunks[i]]
                for te, f in pairs:
                    f.result()
                    with lock:
                        lat.append(time.monotonic() - te)
            else:
                for c in chunks[i]:
                    t0 = time.monotonic()
                    svc.submit(c).result()
                    with lock:
                        lat.append(time.monotonic() - t0)

        threads = [_threading.Thread(target=w, args=(i,))
                   for i in range(submitters)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.monotonic() - t0
        stats = dict(svc.stats)
        svc.stop()
        return wall, lat, stats

    # warm the native/CPU lane outside the timed runs
    ecdsa_batch.verify_batch(fresh_records(4), backend="cpu")
    levels = {
        "light": {"txs": max(50, ntx // 10), "workers": 1,
                  "open_loop": False},
        "concurrent": {"txs": max(200, ntx // 2), "workers": 8,
                       "open_loop": False},
        "saturation": {"txs": ntx, "workers": 1, "open_loop": True},
    }
    out_levels = {}
    stats_at_saturation = None
    for name, cfg in levels.items():
        best = None
        for _ in range(repeats):
            # FRESH records per timed run (the serving memoization caveat
            # in the module docstring; also keeps SigService dedup honest)
            recs = fresh_records(cfg["txs"] * 2)
            txs = [recs[i * 2:(i + 1) * 2] for i in range(cfg["txs"])]
            ws, ls = run_sync(txs, workers=max(cfg["workers"], 8)
                              if name == "saturation" else cfg["workers"])
            wv, lv, st = run_serviced(txs, cfg["workers"],
                                      cfg["open_loop"])
            row = {
                "offered_txs": cfg["txs"],
                "sync_tx_per_s": round(cfg["txs"] / ws, 1),
                "serviced_tx_per_s": round(cfg["txs"] / wv, 1),
                "speedup": round(ws / wv, 3),
                "sync_p50_ms": round(pctl(ls, 0.5), 3),
                "sync_p99_ms": round(pctl(ls, 0.99), 3),
                "serviced_p50_ms": round(pctl(lv, 0.5), 3),
                "serviced_p99_ms": round(pctl(lv, 0.99), 3),
                "serviced_dispatches": st["dispatches"],
                "serviced_lanes": st["lanes_real"],
            }
            if best is None or row["serviced_tx_per_s"] > \
                    best["serviced_tx_per_s"]:
                best = row
                if name == "saturation":
                    stats_at_saturation = st
        out_levels[name] = best
    return out_levels, stats_at_saturation


def bench_serving():
    """Wrapper: run _bench_serving_levels and record BENCH_r07.json; a
    failure is reported, never fatal to the rest of the bench run."""
    try:
        out_levels, stats_at_saturation = _bench_serving_levels()
    except Exception as e:  # pragma: no cover - diagnostics only
        emit("serving_saturation_speedup", -1, "x", 0.0,
             error=f"{type(e).__name__}: {e}")
        return None
    sat = out_levels["saturation"]
    result = {
        "metric": "serving",
        **_bench_stamp(),
        "unit_of_work": "2-input tx (2 fresh sigcheck records)",
        "backend": "cpu",
        "levels": out_levels,
        "saturation_speedup": sat["speedup"],
        "meets_2x_bar": sat["speedup"] >= 2.0,
        "flush_reasons_at_saturation": {
            k.replace("flush_", ""): v
            for k, v in (stats_at_saturation or {}).items()
            if k.startswith("flush_")},
        "note": "sync = per-tx verify_batch across worker threads "
                "(-sigservice=off shape); serviced = SigService shared "
                "lanes, deadline 4 ms, bucket 2046; saturation is the "
                "open-loop tx-storm shape",
    }
    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "BENCH_r07.json"), "w") as f:
        json.dump(result, f, indent=1)
        f.write("\n")
    emit("serving_saturation_speedup", sat["speedup"], "x", sat["speedup"],
         **{k: v for k, v in result.items() if k != "metric"})
    return {"serving_saturation_speedup": sat["speedup"]}


def bench_dispatch_breakdown():
    """ISSUE 8 tentpole metric, re-run for ISSUE 11: per-phase (pack /
    transfer / execute / fetch) decomposition of one device dispatch,
    for the ecdsa verify path and the nonce-sweep path. Phases are
    isolated with explicit staging (jax.device_put + block_until_ready)
    so transfer is not hidden inside the async dispatch; `execute` runs
    on device-resident inputs.

    Since ISSUE 11 the ecdsa leg rides the device-decompose GLV program:
    the host pack is numpy byte emission only, and the result records a
    per-stage PACK SPLIT (decompose vs emit, for both the shipped device
    path and the retained host-decompose fallback) plus a verdict-parity
    check against the host-decompose oracle program and the CPU engine.
    The acceptance bar host_share < 0.15 at bucket 2048 is ASSERTED.
    Writes BENCH_r11.json (schema v2, host-fingerprint stamped — a
    CPU-sandbox breakdown and a real-chip one are different series;
    BENCH_r08.json keeps the pre-decompose-kernel record)."""
    import tempfile

    from bitcoincashplus_tpu.ops import ecdsa_batch
    from bitcoincashplus_tpu.ops import secp256k1 as dev
    from bitcoincashplus_tpu.util import devicewatch as dwatch

    # the GLV/w4 programs are minutes of XLA compile on a cold CPU
    # backend — share the persistent compilation cache the test suite
    # and the kernel-dimension subprocesses already use (routed through
    # the -compilecache plumbing so hits land in the r11 record)
    dwatch.enable_compile_cache(
        os.environ.get("BCP_COMPILE_CACHE",
                       os.path.join(tempfile.gettempdir(),
                                    "bcp-jax-test-cache")))

    n = int(os.environ.get("BCP_BENCH_BREAKDOWN_SIGS", "2046"))
    repeats = int(os.environ.get("BCP_BENCH_BREAKDOWN_REPEATS", "3"))
    rng = np.random.default_rng(8)

    def med(xs):
        return sorted(xs)[len(xs) // 2]

    def run_phases(make_args, stage, execute, fetch):
        """One phased dispatch per repeat; returns median seconds per
        phase + the transfer byte counts of the last repeat."""
        phases = {"pack": [], "transfer": [], "execute": [], "fetch": []}
        nbytes = {"h2d": 0, "d2h": 0}
        for _ in range(repeats):
            t0 = time.perf_counter()
            host_args = make_args()
            phases["pack"].append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            dev_args = stage(host_args)
            jax.block_until_ready(dev_args)
            phases["transfer"].append(time.perf_counter() - t0)
            nbytes["h2d"] = sum(int(np.asarray(a).nbytes)
                                for a in host_args)
            t0 = time.perf_counter()
            out = execute(dev_args)
            jax.block_until_ready(out)
            phases["execute"].append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            host_out = fetch(out)
            phases["fetch"].append(time.perf_counter() - t0)
            nbytes["d2h"] = sum(int(np.asarray(o).nbytes)
                                for o in host_out)
        out_p = {k: round(med(v), 6) for k, v in phases.items()}
        total = sum(out_p.values())
        out_p["total"] = round(total, 6)
        out_p["host_share"] = round(
            1.0 - out_p["execute"] / total, 4) if total else None
        out_p["dispatch_overhead_factor"] = round(
            total / out_p["execute"], 3) if out_p["execute"] else None
        out_p["transfer_bytes"] = nbytes
        return out_p

    # --- ecdsa leg: the packed-bucket verify dispatch ------------------
    wire_n = n + 2  # + the 2 KAT lanes the supervised dispatch appends
    bucket = max(1024, ecdsa_batch._bucket_for(wire_n, pallas=True))
    use_glv = (ecdsa_batch.active_kernel() == "glv"
               and ecdsa_batch.glv_enabled())
    use_glv_dev = use_glv and ecdsa_batch.glv_dev_enabled()

    # Corpus generation happens OUTSIDE the timed pack phase: r08's
    # "pack 3.37 s" was in fact ~3.2 s of the HARNESS's own Python
    # point_mul keygen + ~0.15 s of actual pack — the node's dispatch
    # path receives records from the interpreter/deferral layer and
    # never pays keygen, so timing it as "pack" overstated host_share.
    # Fresh corpus per repeat keeps the memoization caveat honest
    # (repeats + 1: one extra for the warm/compile call below).
    corpora = [_make_sig_records(rng, 64, n)
               + list(ecdsa_batch._kat_records())
               for _ in range(repeats + 1)]

    def ecdsa_args():
        records = corpora.pop()
        if use_glv_dev:
            # ISSUE 11 production path: byte emission only — the lattice
            # split runs inside the fused device program
            return ecdsa_batch.pack_records_w4_bytes(records, bucket)
        if use_glv:
            return ecdsa_batch.pack_records_glv(records, bucket)
        return ecdsa_batch.pack_records_w4_bytes(records, bucket)

    interp = ecdsa_batch._interpret_kernels()

    def ecdsa_exec(dev_args):
        if use_glv_dev:
            return dev._glv_dev_program(*dev_args)
        if use_glv:
            return dev._glv_program(*dev_args)
        return dev._w4_bytes_program(*dev_args, interpret=interp)

    # warm/compile through the WATCHED supervised dispatch first, so the
    # devicewatch program registry (reported below) reflects a real
    # dispatch of this shape — then pre-stage once for the phased runs
    ok = ecdsa_batch.verify_batch(
        _make_sig_records(rng, 8, n), backend="device")
    assert bool(ok.all())
    warm = jax.device_put(ecdsa_args())
    jax.block_until_ready(ecdsa_exec(warm))
    ecdsa_phases = run_phases(
        ecdsa_args, jax.device_put, ecdsa_exec,
        lambda out: [np.asarray(out)])
    ecdsa_phases["kernel"] = "glv-device-decompose" if use_glv_dev else (
        "glv" if use_glv else
        ("w4-bytes-interpret" if interp else "w4-bytes"))
    ecdsa_phases["lanes"] = n
    ecdsa_phases["bucket"] = bucket
    ecdsa_phases["sigs_per_s_end_to_end"] = round(
        n / max(ecdsa_phases["total"], 1e-9))
    ecdsa_phases["sigs_per_s_device_resident"] = round(
        n / max(ecdsa_phases["execute"], 1e-9))

    # per-stage pack split (ISSUE 11 satellite): decompose vs emit, for
    # the shipped device-decompose path AND the retained host fallback —
    # the before/after of moving the lattice split on-device
    if use_glv:
        records = _make_sig_records(rng, 64, n) \
            + list(ecdsa_batch._kat_records())
        st = ecdsa_batch.STATS
        t0 = time.perf_counter()
        emit_args = ecdsa_batch.pack_records_w4_bytes(records, bucket)
        emit_s = time.perf_counter() - t0
        d0, p0 = st.glv_decompose_s, st.glv_pack_s
        t0 = time.perf_counter()
        host_args = ecdsa_batch.pack_records_glv(records, bucket)
        host_total = time.perf_counter() - t0
        # the pre-r11 per-record Python-bigint loop, replicated inline —
        # the honest "before" of the decompose leg (it no longer exists
        # on any path)
        u1b, u2b, _ok = ecdsa_batch._scalar_bitplanes(
            records, len(records))
        t0 = time.perf_counter()
        for i in range(len(records)):
            a1, _n1, a2, _n2 = dev.glv_decompose(
                int.from_bytes(u1b[i].tobytes(), "big"))
            b1, _n3, b2, _n4 = dev.glv_decompose(
                int.from_bytes(u2b[i].tobytes(), "big"))
            a1.to_bytes(16, "little"), a2.to_bytes(16, "little")
            b1.to_bytes(16, "big"), b2.to_bytes(16, "big")
        legacy_s = time.perf_counter() - t0
        ecdsa_phases["pack_split"] = {
            "device_decompose_path": {
                "decompose": 0.0, "emit": round(emit_s, 6),
            },
            "host_fallback_path": {
                "decompose": round(st.glv_decompose_s - d0, 6),
                "emit": round(st.glv_pack_s - p0, 6),
                "total": round(host_total, 6),
            },
            "legacy_per_record_bigint_loop": round(legacy_s, 6),
        }
        # verdict parity: the device-decompose program vs the
        # host-decompose oracle program vs the CPU engine, same lanes
        if use_glv_dev:
            out_dev = np.asarray(ecdsa_exec(jax.device_put(
                ecdsa_batch.pack_records_w4_bytes(records, bucket))))
            out_host = np.asarray(dev._glv_program(*host_args))
            cpu = ecdsa_batch._verify_cpu(records)
            real = slice(0, len(records))
            dev_ok = out_dev[0].reshape(-1)[real].astype(bool)
            host_ok = out_host[0].reshape(-1)[real].astype(bool)
            parity = (dev_ok.tolist() == host_ok.tolist()
                      == np.asarray(cpu, bool).tolist())
            ecdsa_phases["verdict_parity_vs_host_decompose"] = bool(parity)
            assert parity, "device-decompose verdicts diverged"
    if use_glv_dev and bucket == 2048:
        # the ISSUE 11 acceptance bar, enforced where the bench runs
        assert ecdsa_phases["host_share"] < 0.15, ecdsa_phases

    # --- sweep leg: the mining nonce dispatch --------------------------
    from bitcoincashplus_tpu.crypto.hashes import header_midstate
    from bitcoincashplus_tpu.ops.miner import sweep_jit
    from bitcoincashplus_tpu.ops.sha256 import (
        bytes_to_words_np,
        target_to_limbs_np,
    )

    on_cpu = jax.default_backend() == "cpu"
    tile = 1 << 14 if on_cpu else 1 << 16
    n_tiles = 4 if on_cpu else 64

    def sweep_args():
        header = bytes([rng.integers(0, 256) for _ in range(80)])
        return (
            np.array(header_midstate(header), dtype=np.uint32),
            bytes_to_words_np(np.frombuffer(header[64:76], np.uint8)),
            target_to_limbs_np(0),  # no hit: the sweep runs every tile
            np.uint32(rng.integers(0, 1 << 32)),
            np.uint32(n_tiles),
        )

    def sweep_exec(dev_args):
        return sweep_jit(*dev_args, tile=tile)

    warm = jax.device_put(sweep_args())
    jax.block_until_ready(sweep_exec(warm))
    sweep_phases = run_phases(
        sweep_args, jax.device_put, sweep_exec,
        lambda out: [np.asarray(o) for o in out])
    sweep_phases["tile"] = tile
    sweep_phases["n_tiles"] = n_tiles
    sweep_phases["mhs_end_to_end"] = round(
        tile * n_tiles / max(sweep_phases["total"], 1e-9) / 1e6, 3)
    sweep_phases["mhs_device_resident"] = round(
        tile * n_tiles / max(sweep_phases["execute"], 1e-9) / 1e6, 3)

    # serving re-measure (ISSUE 11 satellite): the closed-loop
    # `concurrent` level lost to sync in BENCH_r07 (0.48x) largely on
    # per-lane submit cost — re-measured now that the GLV host pack is
    # byte emission only. Recorded here (BENCH_r07.json keeps the
    # original trajectory entry).
    serving_recheck = None
    if os.environ.get("BCP_BENCH_SKIP_SERVING") != "1":
        try:
            out_levels, _sat = _bench_serving_levels()
            serving_recheck = {
                "levels": out_levels,
                "concurrent_speedup": out_levels["concurrent"]["speedup"],
                "baseline_r07_concurrent_speedup": 0.48,
            }
        except Exception as e:  # pragma: no cover - diagnostics only
            serving_recheck = {"error": f"{type(e).__name__}: {e}"}

    result = {
        "metric": "dispatch_breakdown",
        **_bench_stamp(),
        "repeats": repeats,
        "ecdsa": ecdsa_phases,
        "sweep": sweep_phases,
        "serving_recheck": serving_recheck,
        "device_watch": {
            name: {k: snap[k] for k in
                   ("dispatches", "compiles", "compile_seconds", "shapes",
                    "shape_budget", "retraces_unexpected")}
            for name, snap in dwatch.snapshot()["programs"].items()
        },
        "compilation_cache": dwatch.compile_cache_snapshot(),
        "note": "median-of-N per phase; pack = host byte-matrix emit "
                "(the GLV lattice decompose rides the DEVICE program "
                "since ISSUE 11 — pack_split records the before/after), "
                "transfer = explicit device_put staging, execute = "
                "program on device-resident inputs, fetch = host "
                "materialization of the result. MEASUREMENT CORRECTION "
                "vs BENCH_r08: r08's pack leg timed the harness's own "
                "corpus generation (~3.2 s of Python point_mul keygen) "
                "inside 'pack', overstating host_share — the node's "
                "dispatch path never pays keygen. r11 times the pack "
                "alone; the honest before/after of the real pack is in "
                "pack_split (host_fallback_path vs "
                "device_decompose_path). On a CPU backend the transfer "
                "legs are memcpy-scale lower bounds, not PCIe/tunnel "
                "numbers",
    }
    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "BENCH_r11.json"), "w") as f:
        json.dump(result, f, indent=1)
        f.write("\n")
    emit("dispatch_breakdown",
         ecdsa_phases["dispatch_overhead_factor"], "x",
         0.0, **{k: v for k, v in result.items() if k != "metric"})
    return {"ecdsa_dispatch_overhead_x":
            ecdsa_phases["dispatch_overhead_factor"],
            "sweep_dispatch_overhead_x":
            sweep_phases["dispatch_overhead_factor"]}


def bench_reindex(device_sps=None):
    """Config 6 — the NORTH STAR (BASELINE.json: mainnet -reindex wall-clock
    < 45 min on v5e-8): generate a synthetic signature-dense regtest chain
    (tools/gen_sigchain.py), run the full Node(-reindex) import over it
    (native connect engine -> packed TPU sig batches, the production path),
    and project a mainnet wall-clock from measured component rates.

    Projection model (constants are fork-era public chain shape, NOT from
    the empty reference mount), additive (conservative — the import
    pipelines device verify under host byte work, so the true wall is
    closer to max of the legs):
      byte_leg = MAINNET_BYTES / (chain_bytes / non_verify_import_seconds)
      sig_leg  = MAINNET_SIG_INPUTS / device_sigs_per_s   (config 4's
                 content-randomized measurement; the import's own verify
                 waits are partially hidden by pipelining, so the raw
                 dispatch rate is the honest per-sig cost)
    A second, heterogeneous chain (mixed input counts, P2PK, P2SH
    multisig — tools/gen_sigchain._mixed_phase) reports the script-shape
    bias of the uniform best case (VERDICT r4 item 6)."""
    import shutil
    import tempfile

    MAINNET_BLOCKS = 478_558      # the fork height (params.py uahf_height)
    MAINNET_SIG_INPUTS = 550e6    # ~240M txs x ~2.3 inputs avg at that height
    MAINNET_BYTES = 130e9         # ~130 GB serialized chain at that height

    n_sigs = int(os.environ.get("BCP_BENCH_REINDEX_SIGS", "16000"))
    n_mixed = int(os.environ.get("BCP_BENCH_REINDEX_MIXED_SIGS", "4000"))
    workdir = tempfile.mkdtemp(prefix="bcp-reindex-bench-")
    mixdir = tempfile.mkdtemp(prefix="bcp-reindex-mixed-")
    try:
        from tools.gen_sigchain import generate

        from bitcoincashplus_tpu.ops import ecdsa_batch

        gen = generate(workdir, n_sigs)
        genm = generate(mixdir, n_mixed, mixed=True)

        # warm the verify kernel at the import's dispatch shapes: the
        # aggregator slices exact 8192-lane batches plus a sub-8192 tail
        # (bucket 2048 here) — the w4 Pallas compile is ~1-2 min per shape
        # on the tunneled chip and must not land inside the measured import
        if jax.default_backend() != "cpu":
            rng = np.random.default_rng(11)
            for n in (8192, 1100, 600):  # buckets 8192 / 2048 / 1024
                ecdsa_batch.verify_batch(_make_sig_records(rng, 8, n),
                                         backend="device")

        stats0 = ecdsa_batch.STATS.snapshot()
        stats = _run_reindex(workdir)
        assert stats["tip_height"] == gen["tip_height"], (stats, gen)
        stats1 = ecdsa_batch.STATS.snapshot()
        device_wait_s = (stats1["device_seconds"]
                         - stats0.get("device_seconds", 0))
        statsm = _run_reindex(mixdir)
        assert statsm["tip_height"] == genm["tip_height"], (statsm, genm)

        wall = stats["wall_s"]
        verify_s = stats.get("verify_s", 0.0)
        sigscan_s = stats.get("sigscan_s", 0.0)
        other_s = max(wall - verify_s - sigscan_s, 1e-9)
        byte_rate = gen["bytes"] / other_s
        sig_sps = device_sps or (gen["sigs"] / max(verify_s, 1e-9))
        proj_byte_leg = MAINNET_BYTES / byte_rate
        proj_sig_leg = MAINNET_SIG_INPUTS / sig_sps
        # host signature scan (sighash + encodings + pubkey parse): per-sig
        # work, threaded under -par — measured here on host_cpus cores
        proj_sigscan_leg = (MAINNET_SIG_INPUTS
                            * (sigscan_s / max(gen["sigs"], 1)))
        proj_min = (proj_sig_leg + proj_byte_leg + proj_sigscan_leg) / 60
        mixed_wall = statsm["wall_s"]
        mixed_other = max(mixed_wall - statsm.get("verify_s", 0.0)
                          - statsm.get("sigscan_s", 0.0), 1e-9)
        emit(
            "reindex_projected_mainnet_min", round(proj_min), "min",
            round(45.0 / max(proj_min, 1e-9), 6),
            measured={
                "sigs": gen["sigs"], "blocks": gen["blocks"],
                "bytes": gen["bytes"],
                # the host's core count bounds the threaded native legs
                # (sigscan, txid hashing, CPU ECDSA): this sandbox exposes
                # 1 core, a real v5e-8 host has >100 — the byte leg
                # projection is a per-core lower bound
                "host_cpus": os.cpu_count(),
                "import_wall_s": round(wall, 2),
                "blocks_per_s": round(gen["blocks"] / wall, 1),
                "sigs_per_s_end_to_end": round(gen["sigs"] / wall),
                "byte_MB_per_s": round(byte_rate / 1e6, 2),
                "verify_wait_s": round(verify_s, 2),
                "device_wait_s": round(device_wait_s, 2),
                "sigscan_s": round(sigscan_s, 2),
                "sigscan_us_per_sig": round(
                    sigscan_s / max(gen["sigs"], 1) * 1e6, 1),
                "native_connect_s": round(
                    stats.get("native_connect_s", 0.0), 2),
                "flush_s": round(stats.get("flush_s", 0.0), 2),
                "slow_path_blocks": stats.get("slow_path_blocks"),
            },
            mixed={
                "sigs": genm["sigs"], "bytes": genm["bytes"],
                "blocks": genm["blocks"],
                "import_wall_s": round(mixed_wall, 2),
                "sigs_per_s_end_to_end": round(genm["sigs"] / mixed_wall),
                "byte_MB_per_s": round(genm["bytes"] / mixed_other / 1e6,
                                       2),
                "fallback_inputs": statsm.get("fallback_inputs"),
            },
            projection={
                "sig_leg_min": round(proj_sig_leg / 60),
                "byte_leg_min": round(proj_byte_leg / 60),
                "host_sigscan_leg_min": round(proj_sigscan_leg / 60),
                # v5e-8 model: sig leg /8 (parallel/sig_shard over ICI);
                # host legs UNSCALED from this host's core count — a real
                # v5e-8 host threads them across >100 cores
                "v5e8_modeled_min": round(
                    (proj_sig_leg / 8 + proj_byte_leg
                     + proj_sigscan_leg) / 60),
                "device_sigs_per_s": round(sig_sps),
                "model_sig_inputs": MAINNET_SIG_INPUTS,
                "model_bytes": MAINNET_BYTES,
                "model_blocks": MAINNET_BLOCKS,
                # the reference's DEFAULT -reindex skips script/sig checks
                # below the assumevalid checkpoint (~90% of history) —
                # that skips the host sigscan too, not just the device leg
                "assumevalid_projected_min": round(
                    ((proj_sig_leg + proj_sigscan_leg) * 0.10
                     + proj_byte_leg) / 60
                ),
                "model_above_assumevalid_fraction": 0.10,
                # settle-horizon bound: with the pipelined engine the three
                # legs overlap, so the wall converges on max(legs) instead
                # of their sum (measured overlap: import_pipeline metric)
                "pipelined_max_leg_min": round(
                    max(proj_sig_leg, proj_byte_leg, proj_sigscan_leg) / 60),
            },
            note="native C++ import engine + packed TPU batches; mixed = "
                 "heterogeneous script shapes; additive projection "
                 "(pipelining makes it conservative); vs_baseline = "
                 "45/projected",
        )
        return {"projected_min": round(proj_min),
                "byte_MBs": round(byte_rate / 1e6, 1)}
    except Exception as e:  # pragma: no cover - diagnostics only
        emit("reindex_projected_mainnet_min", -1, "min", 0.0,
             error=f"{type(e).__name__}: {e}")
        return None
    finally:
        shutil.rmtree(workdir, ignore_errors=True)
        shutil.rmtree(mixdir, ignore_errors=True)


def _load_functional_framework():
    """tests/functional/framework.py as a module (the fleet bench drives
    real bcpd processes through the same harness the functional suite
    uses; tests/ is not an installed package, so load by path)."""
    import importlib.util

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "tests", "functional", "framework.py")
    spec = importlib.util.spec_from_file_location("bcp_fleet_framework", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _gw_request(conn_box, port, auth, client_id, method, params,
                timeout=60.0):
    """One JSON-RPC call against the gateway's HTTP front door with an
    explicit per-client identity (X-Client-Id is what the gateway's
    token buckets key on — every bench client is its own principal).
    Returns (kind, payload, latency_s) where kind is 'ok' | 'shed' |
    'rpc_error'. Keep-alive connection per worker, one reconnect on a
    stale socket."""
    from http.client import HTTPConnection

    body = json.dumps({"jsonrpc": "2.0", "id": 1, "method": method,
                       "params": params}).encode()
    headers = {"Authorization": "Basic " + auth,
               "Content-Type": "application/json",
               "X-Client-Id": client_id}
    for attempt in (0, 1):
        conn = conn_box[0]
        if conn is None:
            conn = conn_box[0] = HTTPConnection("127.0.0.1", port,
                                                timeout=timeout)
        t0 = time.monotonic()
        try:
            conn.request("POST", "/", body, headers)
            resp = conn.getresponse()
            data = json.loads(resp.read())
        except Exception:
            try:
                conn.close()
            finally:
                conn_box[0] = None
            if attempt:
                raise
            continue
        lat = time.monotonic() - t0
        err = data.get("error")
        if resp.status == 429 or (err and err.get("code") == -429):
            return "shed", err, lat
        if err:
            return "rpc_error", err, lat
        return "ok", data.get("result"), lat


def bench_fleet():
    """ISSUE 16 acceptance harness: >= 1000 concurrent seeded clients
    hold a p99 latency bar against the gateway while a forkfeeder-driven
    fork storm reorgs the validator underneath and a chaos kill -9 takes
    a replica out (and back) mid-run. Asserted: zero inconsistent
    replies (every replied tip is a block the validator recognizes),
    nonzero shed + coalesce counters, >= 1 mid-request failover, and a
    byte-identical chainstate digest across validator and replicas at
    quiesce. Writes BENCH_r16.json (schema_version=2 host stamp)."""
    import base64
    import threading
    from concurrent.futures import ThreadPoolExecutor

    fw = _load_functional_framework()
    from bitcoincashplus_tpu.consensus.params import regtest_params
    from bitcoincashplus_tpu.wallet.keys import CKey

    n_clients = int(os.environ.get("BCP_BENCH_FLEET_CLIENTS", "1000"))
    reqs_per = int(os.environ.get("BCP_BENCH_FLEET_REQS", "3"))
    workers = int(os.environ.get("BCP_BENCH_FLEET_WORKERS", "16"))
    p99_bar_ms = float(os.environ.get("BCP_BENCH_FLEET_P99_MS", "2500"))
    seed = int(os.environ.get("BCP_BENCH_FLEET_SEED", "1607"))
    chain_h = 24
    addr = CKey(0xF1EE7).p2pkh_address(regtest_params())

    f = fw.FunctionalFramework(num_nodes=4)
    # node0 validator+gateway, nodes 1-2 replicas, node3 storm miner
    # (NOT in the pool). Tight per-client buckets so the hot clients
    # below provably shed: burst 10, refill 5/s, read floor 2.5.
    fw.setup_fleet(f, replicas=f.nodes[1:3])
    f.nodes[0].extra_args += ["-gatewayrate=5", "-gatewayburst=10"]
    t_run0 = time.monotonic()
    with f:
        validator, r1, r2, storm = f.nodes
        gw_port, auth = validator.gateway_port, base64.b64encode(
            f"{fw.FLEET_USER}:{fw.FLEET_PASSWORD}".encode()).decode()
        validator.rpc.generatetoaddress(chain_h, addr)
        fw.connect_nodes(storm, validator)
        fw.sync_blocks([validator, storm], timeout=60)

        # snapshot-bootstrap both replicas (the 30-second spin-up path)
        snap = os.path.join(validator.datadir, "fleet-bench-snapshot")
        dump = validator.rpc.dumptxoutset(snap)
        for rep in (r1, r2):
            fw.bootstrap_replica_from_snapshot(rep, validator, snap, dump)

        def rotation():
            pool = validator.rpc.gettpuinfo()["gateway"]["pool"]
            return {r["name"] for r in pool["replicas"] if r["in_rotation"]}

        fw.wait_until(lambda: len(rotation()) == 2, timeout=60)
        for rep in (r1, r2):
            fw.wait_until(lambda rep=rep: rep.rpc.gettpuinfo()["store"]
                          ["snapshot"]["validated"], timeout=180, sleep=1.0)

        # pre-mine the competing branch: the storm miner forks the tip
        # and out-works the validator's own extension by one block. Its
        # raw blocks become the forkfeeder's ammunition; the miner then
        # leaves the stage (this host is small).
        fw.disconnect_nodes(storm, validator)
        validator.rpc.generatetoaddress(3, addr)
        b_hashes = storm.rpc.generatetoaddress(4, addr)
        branch_b = [bytes.fromhex(storm.rpc.getblock(h, 0))
                    for h in b_hashes]
        b_tip = b_hashes[-1]
        storm.stop()

        # -- the storm: seeded client fleet + fork reorg + chaos kill --
        state = {"tip": validator.rpc.getbestblockhash()}
        storm_done = threading.Event()
        rng = random.Random(seed)
        jobs = []
        for i in range(n_clients):
            crng = random.Random(seed + i)
            for _ in range(reqs_per):
                r = crng.random()
                if r < 0.5:
                    jobs.append((f"c{i}", "getbestblockhash", None))
                elif r < 0.7:
                    jobs.append((f"c{i}", "getblockcount", None))
                elif r < 0.9:
                    jobs.append((f"c{i}", "getblock", "TIP"))
                else:
                    jobs.append((f"c{i}", "getblockhash",
                                 [crng.randint(1, chain_h)]))
        rng.shuffle(jobs)
        # 5 hot clients hammer 40 rapid reads each, spliced in as
        # CONTIGUOUS runs (shuffling would spread them across the whole
        # run and let their buckets refill): 40 near-simultaneous reads
        # against a burst-10 bucket guarantees the shed counter moves
        for h in range(5):
            cut = (h + 1) * len(jobs) // 6
            jobs[cut:cut] = [(f"hot{h}", "getbestblockhash", None)] * 40
        job_q, counts_lock = iter(jobs), threading.Lock()
        shared = {"lat": [], "tips": set(), "ok": 0, "shed": 0,
                  "rpc_error": 0, "transport_error": 0}

        def drain(job_iter, wid):
            conn_box, local_lat, local_tips = [None], [], set()
            ok = shed = rpc_err = terr = 0
            k = 0
            while True:
                with counts_lock:
                    job = next(job_iter, None)
                if job is None:
                    if storm_done.is_set():
                        break
                    # keep the pressure on until the storm script ends:
                    # filler reads on rotating seeded identities
                    job = (f"c{(k * 131 + wid) % n_clients}",
                           "getbestblockhash", None)
                    k += 1
                cid, method, params = job
                if params == "TIP":
                    params = [state["tip"]]
                try:
                    kind, payload, lat = _gw_request(
                        conn_box, gw_port, auth, cid, method, params or [])
                except Exception:
                    terr += 1
                    continue
                if kind == "shed":
                    shed += 1
                    continue
                if kind == "rpc_error":
                    rpc_err += 1
                    local_lat.append(lat)
                    continue
                ok += 1
                local_lat.append(lat)
                if method == "getbestblockhash":
                    local_tips.add(payload)
                    state["tip"] = payload
                elif method == "getblock":
                    local_tips.add(payload["hash"])
            with counts_lock:
                shared["lat"] += local_lat
                shared["tips"] |= local_tips
                shared["ok"] += ok
                shared["shed"] += shed
                shared["rpc_error"] += rpc_err
                shared["transport_error"] += terr

        pool_exec = ThreadPoolExecutor(max_workers=workers)
        futures = [pool_exec.submit(drain, job_q, w)
                   for w in range(workers)]
        events = {}
        try:
            # event 1: forkfeeder replays the longer competing branch —
            # the validator MUST reorg underneath the serving load
            t0 = time.monotonic()
            feeder = fw.ChaosPeer(validator.p2p_port, "forkfeeder",
                                  seed=seed, blocks=branch_b,
                                  block_rate=200)
            feeder.start()
            fw.wait_until(
                lambda: validator.rpc.getbestblockhash() == b_tip,
                timeout=90)
            events["reorg_s"] = round(time.monotonic() - t0, 3)
            feeder.stop()

            # event 2: chaos kill -9 of replica 1 mid-run, then restart
            # and re-admission — serving must not flinch in between
            t0 = time.monotonic()
            r1.kill9()
            time.sleep(1.0)
            r1.start()
            fw.connect_nodes(r1, validator)
            fw.wait_until(lambda: len(rotation()) == 2, timeout=120)
            events["kill_rejoin_s"] = round(time.monotonic() - t0, 3)

            # event 3: one more reorg cycle (invalidate/extend/
            # reconsider) so the storm has > 1 reorg in it
            count = validator.rpc.getblockcount()
            h = validator.rpc.getblockhash(count - 1)
            validator.rpc.invalidateblock(h)
            validator.rpc.generatetoaddress(3, addr)
            validator.rpc.reconsiderblock(h)
            events["reorgs"] = 2
        finally:
            storm_done.set()
            for fut in futures:
                fut.result(timeout=300)
            pool_exec.shutdown()

        # coalesce flush: one barrier-released wave of identical reads
        # (the organic mix usually coalesces too; this makes it certain)
        tip = validator.rpc.getbestblockhash()
        barrier = threading.Barrier(workers)

        def identical(w):
            # distinct client ids: coalescing keys on method+params, and
            # a shared id would shed the wave in its own token bucket
            box = [None]
            barrier.wait()
            return _gw_request(box, gw_port, auth, f"burst{w}", "getblock",
                               [tip])
        with ThreadPoolExecutor(max_workers=workers) as ex:
            burst = list(ex.map(identical, range(workers)))
        assert all(k == "ok" and p["hash"] == tip for k, p, _ in burst)

        # -- quiesce: settle, then the byte-identical chainstate check --
        validator.rpc.generatetoaddress(1, addr)
        final_tip = validator.rpc.getbestblockhash()
        fw.wait_until(lambda: r1.rpc.getbestblockhash() == final_tip
                      and r2.rpc.getbestblockhash() == final_tip,
                      timeout=120)
        infos = [n.rpc.gettxoutsetinfo() for n in (validator, r1, r2)]
        identical_chainstate = (
            len({i["muhash"] for i in infos}) == 1
            and len({i["bestblock"] for i in infos}) == 1)

        # consistency: every tip a client was ever told is a block the
        # validator recognizes — no invented, corrupt, or cross-wired
        # reply survived the storm
        inconsistent = 0
        for h in shared["tips"]:
            try:
                validator.rpc.getblockheader(h)
            except Exception:
                inconsistent += 1
        stats = validator.rpc.gettpuinfo()["gateway"]

    lat = sorted(shared["lat"])

    def pctl(q):
        return round(lat[int(q * (len(lat) - 1))] * 1e3, 2)

    p99 = pctl(0.99)
    served = shared["ok"] + shared["rpc_error"]
    # the acceptance bar, asserted (env-tunable for slower hosts)
    assert inconsistent == 0, f"{inconsistent} inconsistent replies"
    assert identical_chainstate, "chainstate digests diverged at quiesce"
    assert stats["sheds"]["read"] > 0, "shed counter never moved"
    assert stats["coalesce_hits"] > 0, "coalesce counter never moved"
    assert stats["failovers"] >= 1, "no mid-request failover recorded"
    assert shared["shed"] > 0 and served >= n_clients
    p99_ok = p99 <= p99_bar_ms
    assert p99_ok, f"p99 {p99} ms over the {p99_bar_ms} ms bar"
    result = {
        "metric": "fleet_storm",
        **_bench_stamp(),
        "clients": n_clients,
        "workers": workers,
        "requests": {"served": served, "ok": shared["ok"],
                     "shed": shared["shed"],
                     "rpc_error": shared["rpc_error"],
                     "transport_error": shared["transport_error"]},
        "latency_ms": {"p50": pctl(0.50), "p95": pctl(0.95), "p99": p99},
        "p99_bar_ms": p99_bar_ms,
        "p99_ok": p99_ok,
        "events": events,
        "gateway": {"admitted": stats["admitted"],
                    "sheds": stats["sheds"],
                    "coalesce_hits": stats["coalesce_hits"],
                    "failovers": stats["failovers"],
                    "validator_fallback": stats["validator_fallback"],
                    "rotations_out": stats["pool"]["rotations_out"]},
        "distinct_tips_replied": len(shared["tips"]),
        "inconsistent_replies": inconsistent,
        "chainstate_identical": identical_chainstate,
        "wall_s": round(time.monotonic() - t_run0, 3),
        "note": "gateway front door over 2 snapshot-bootstrapped "
                "replicas: seeded client fleet holds the p99 bar while "
                "a forkfeeder fork storm reorgs the validator and a "
                "chaos kill -9 takes a replica out and back mid-run; "
                "every replied tip verified against the validator's "
                "block index, chainstate digests compared at quiesce",
    }
    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "BENCH_r16.json"), "w") as fh:
        json.dump(result, fh, indent=1)
        fh.write("\n")
    emit("fleet_storm_p99", p99, "ms", round(p99_bar_ms / max(p99, 1e-3), 3),
         **{k: v for k, v in result.items() if k != "metric"})
    return {"fleet_p99_ms": p99,
            "fleet_inconsistent_replies": inconsistent,
            "fleet_chainstate_identical": identical_chainstate}


def _forge_epoch_cert(snap_path: str, forge_height: int) -> None:
    """Offline equivalent of the ``snapshot_cert`` poison-output drill:
    flip one bit in the committed digest of the checkpoint at
    ``forge_height`` and RE-SEAL the commitment chain over the forged
    trajectory — structurally valid at load, content-forged, caught only
    by the shadow validator's epoch tripwire."""
    from bitcoincashplus_tpu.store import certificate as cert_mod

    cert_file = os.path.join(snap_path, cert_mod.CERT_NAME)
    with open(cert_file) as f:
        cert = json.load(f)
    for ep in cert["epochs"]:
        if ep["height"] == forge_height:
            raw = bytearray(bytes.fromhex(ep["muhash"]))
            raw[0] ^= 0x01
            ep["muhash"] = bytes(raw).hex()
            break
    else:
        raise RuntimeError(f"no checkpoint at height {forge_height}")
    cert["commitment"] = cert_mod.commitment_chain(
        bytes.fromhex(cert["mmr_root"]), cert["height"],
        cert["epoch_blocks"], cert["epochs"]).hex()
    with open(cert_file, "w") as f:
        json.dump(cert, f)


def bench_snapshot_cert():
    """ISSUE 17 acceptance harness, three legs. (a) Store-level at 10^6
    coins: certificate build time at dump and verify-at-load time
    against the bar "seconds, not minutes" (the alternative this
    replaces is hours of blind shadow re-validation). (b) Node-level
    over real bcpd processes: honest full shadow re-validation vs
    -snapshotspotcheck onboarding wall-clock (byte-identical final
    digests asserted) vs forged-epoch detection latency (the hard abort
    at the first divergent checkpoint). (c) Fleet: gateway p99 over a
    3-node pool while one replica sits quarantined on a cert-less
    snapshot, zero inconsistent replies. Writes BENCH_r17.json
    (schema_version=2 host stamp)."""
    import base64
    import shutil
    import struct
    import tempfile
    import threading
    from concurrent.futures import ThreadPoolExecutor

    from bitcoincashplus_tpu.crypto.hashes import sha256d
    from bitcoincashplus_tpu.store import certificate as cert_mod
    from bitcoincashplus_tpu.store import snapshot as snapshot_mod
    from bitcoincashplus_tpu.store.sharded import ShardedCoinsDB

    n_coins = int(os.environ.get("BCP_BENCH_CERT_COINS", "1000000"))
    height = int(os.environ.get("BCP_BENCH_CERT_HEIGHT", "2048"))
    epoch = int(os.environ.get("BCP_BENCH_CERT_EPOCH", "64"))
    verify_bar_s = float(os.environ.get("BCP_BENCH_CERT_VERIFY_BAR_S", "60"))
    p99_bar_ms = float(os.environ.get("BCP_BENCH_CERT_P99_MS", "2500"))
    result = {"metric": "snapshot_cert", **_bench_stamp()}

    # -- leg (a): certificate algebra at the million-coin scale --------
    workdir = tempfile.mkdtemp(prefix="bcp_cert_bench_")
    try:
        db = ShardedCoinsDB(os.path.join(workdir, "src"), n_shards=4)
        best = b"\x17" * 32
        chunk = 50_000
        t0 = time.perf_counter()
        for lo in range(0, n_coins, chunk):
            db.batch_write_serialized(
                [(_utxo_key(i), _utxo_coin(i))
                 for i in range(lo, min(lo + chunk, n_coins))], best)
        seed_s = time.perf_counter() - t0
        headers = [sha256d(struct.pack("<I", i)) * 3
                   for i in range(height + 1)]
        headers = [h[:80] for h in headers]
        header_hashes = [sha256d(h) for h in headers]

        def deltas():
            # every coin created, none spent: coin i belongs to block
            # (i % height) + 1, walked tip -> 1 as the builder requires
            for h in range(height, 0, -1):
                yield (h, [(_utxo_key(i), _utxo_coin(i))
                           for i in range(h - 1, n_coins, height)], [])

        t0 = time.perf_counter()
        cert = cert_mod.build_certificate(
            header_hashes, height, epoch, db.muhash_state(), deltas())
        build_s = time.perf_counter() - t0
        snap = os.path.join(workdir, "snap")
        t0 = time.perf_counter()
        snapshot_mod.dump_snapshot(db, snap, headers, height, best,
                                   "regtest", certificate=cert)
        dump_s = time.perf_counter() - t0
        digest = db.muhash_digest()
        db.close()

        # the verify the loader runs BEFORE streaming a single row
        t0 = time.perf_counter()
        cps = cert_mod.verify_certificate(cert, header_hashes, height,
                                          digest.hex())
        verify_cert_s = time.perf_counter() - t0
        assert len(cps) == len(cert["epochs"])
        dst = ShardedCoinsDB(os.path.join(workdir, "dst"), n_shards=4)
        t0 = time.perf_counter()
        info = snapshot_mod.load_snapshot(snap, dst, "regtest",
                                          expected_hash=best,
                                          expected_digest=digest)
        load_s = time.perf_counter() - t0
        assert info["cert_checkpoints"]
        assert dst.muhash_digest() == digest  # byte-identical honest path
        dst.close()
        assert verify_cert_s < verify_bar_s, (
            f"verify-at-load {verify_cert_s:.1f}s breaks the "
            f"'seconds, not minutes' bar ({verify_bar_s}s)")
        result["algebra"] = {
            "coins": n_coins, "height": height, "epoch_blocks": epoch,
            "epochs": len(cert["epochs"]),
            "seed_s": round(seed_s, 3),
            "cert_build_s": round(build_s, 3),
            "dump_s": round(dump_s, 3),
            "verify_at_load_s": round(verify_cert_s, 4),
            "verify_bar_s": verify_bar_s,
            "certified_load_s": round(load_s, 3),
            "cert_overhead_pct": round(100 * verify_cert_s / load_s, 2),
        }
        emit("snapshot_cert_verify_at_load", round(verify_cert_s, 4), "s",
             round(verify_bar_s / max(verify_cert_s, 1e-6), 1),
             coins=n_coins, headers=height + 1)
    finally:
        shutil.rmtree(workdir, ignore_errors=True)

    # -- legs (b) + (c): real bcpd processes ---------------------------
    fw = _load_functional_framework()
    from bitcoincashplus_tpu.consensus.params import regtest_params
    from bitcoincashplus_tpu.wallet.keys import CKey

    mature = int(os.environ.get("BCP_BENCH_CERT_MATURE", "120"))
    spend_blocks = int(os.environ.get("BCP_BENCH_CERT_SPEND_BLOCKS", "16"))
    tx_per_block = int(os.environ.get("BCP_BENCH_CERT_TX_PER_BLOCK", "6"))
    tail_blocks = int(os.environ.get("BCP_BENCH_CERT_TAIL", "24"))
    node_epoch = 16
    chain_h = mature + spend_blocks + tail_blocks

    f = fw.FunctionalFramework(
        num_nodes=2, extra_args=[[f"-snapshotepoch={node_epoch}"], []])
    with f:
        a, b = f.nodes
        waddr = a.rpc.getnewaddress()
        a.rpc.generatetoaddress(mature, waddr)
        # spend blocks live in MIDDLE epochs (the tail keeps them out of
        # the always-sampled final checkpoint): spot-check onboarding
        # skips their script verification, full re-validation pays it
        for _ in range(spend_blocks):
            for _ in range(tx_per_block):
                a.rpc.sendtoaddress(waddr, 0.05)
            a.rpc.generatetoaddress(1, waddr)
        a.rpc.generatetoaddress(tail_blocks, waddr)
        assert a.rpc.getblockcount() == chain_h
        snap_path = os.path.join(a.datadir, "cert-bench-snapshot")
        dump = a.rpc.dumptxoutset(snap_path)
        assert dump["certified"] is True
        forged = os.path.join(a.datadir, "cert-bench-forged")
        shutil.copytree(snap_path, forged)
        forge_at = (chain_h // node_epoch // 2) * node_epoch
        _forge_epoch_cert(forged, forge_at)
        auth_arg = f"-assumeutxo={dump['bestblock']}:{dump['muhash']}"

        def onboard(path, extra, wait_dead=False):
            """Fresh-datadir onboarding; returns wall seconds from the
            P2P connect to validated (or, for the forged run, to the
            node's hard abort)."""
            b.stop()
            shutil.rmtree(b.datadir, ignore_errors=True)
            b.extra_args = [arg for arg in b.extra_args
                            if not arg.startswith(("-assumeutxo",
                                                   "-snapshotspotcheck",
                                                   "-netseed"))]
            b.extra_args += [auth_arg] + extra
            b.start()
            b.rpc.loadtxoutset(path)
            t0 = time.monotonic()
            fw.connect_nodes(b, a)
            if wait_dead:
                fw.wait_until(lambda: b.process.poll() is not None,
                              timeout=600, sleep=0.2)
            else:
                fw.wait_until(
                    lambda: b.rpc.gettpuinfo()["store"]["snapshot"]
                    ["validated"], timeout=600, sleep=0.2)
            return time.monotonic() - t0

        full_s = onboard(snap_path, [])
        digest_full = b.rpc.gettxoutsetinfo()["muhash"]
        spot_s = onboard(snap_path, ["-snapshotspotcheck=1", "-netseed=17"])
        digest_spot = b.rpc.gettxoutsetinfo()["muhash"]
        detect_s = onboard(forged, [], wait_dead=True)
        with open(os.path.join(b.datadir, "debug.log")) as fh:
            log = fh.read()
        assert "EPOCH DIGEST DIVERGENCE" in log
        assert f"checkpoint {forge_at}" in log
        b.process = None  # the corpse is the result; don't re-stop it
        digest_a = a.rpc.gettxoutsetinfo()["muhash"]

    assert digest_full == digest_spot == digest_a, \
        "onboarded chainstate digests diverged from the validator"
    assert spot_s < full_s, (
        f"spot-check onboarding ({spot_s:.1f}s) did not beat full shadow "
        f"re-validation ({full_s:.1f}s)")
    # the O(epoch) detection-latency claim is proven STRUCTURALLY above
    # (divergence logged at the forged mid-chain checkpoint, never the
    # final one); at regtest scale the wall-clock gap sits inside
    # connect/backfill fixture noise, so only gate on gross regression
    assert detect_s < full_s * 1.5, (
        f"forged-epoch detection ({detect_s:.1f}s) took >1.5x the full "
        f"re-validation window ({full_s:.1f}s)")
    result["onboarding"] = {
        "chain_height": chain_h, "epoch_blocks": node_epoch,
        "spend_txs": spend_blocks * tx_per_block,
        "full_validation_s": round(full_s, 3),
        "spotcheck_validation_s": round(spot_s, 3),
        "spotcheck_speedup": round(full_s / spot_s, 3),
        "forged_epoch_height": forge_at,
        "forged_detect_s": round(detect_s, 3),
        "detect_vs_full": round(detect_s / full_s, 3),
        "digests_identical": True,
    }

    # -- leg (c): fleet-quarantine drill p99 ---------------------------
    reads = int(os.environ.get("BCP_BENCH_CERT_READS", "400"))
    workers = int(os.environ.get("BCP_BENCH_CERT_WORKERS", "8"))
    fleet_h = 16
    addr = CKey(0x17BE7).p2pkh_address(regtest_params())
    f = fw.FunctionalFramework(num_nodes=3)
    fw.setup_fleet(f)
    with f:
        validator, r1, r2 = f.nodes
        r2_name = f"127.0.0.1:{r2.rpc_port}"
        gw_port = validator.gateway_port
        auth = base64.b64encode(
            f"{fw.FLEET_USER}:{fw.FLEET_PASSWORD}".encode()).decode()
        validator.rpc.generatetoaddress(fleet_h, addr)
        snap = os.path.join(validator.datadir, "fleet-cert-snapshot")
        dump = validator.rpc.dumptxoutset(snap)
        nocert = os.path.join(validator.datadir, "fleet-nocert-snapshot")
        shutil.copytree(snap, nocert)
        os.remove(os.path.join(nocert, "CERTIFICATE.json"))

        fw.bootstrap_replica_from_snapshot(r1, validator, snap, dump)
        # r2: cert-less, disconnected — the poisoned replica stand-in
        # that can never flip certificate_verified during the drill
        r2.stop()
        r2.extra_args.append(
            f"-assumeutxo={dump['bestblock']}:{dump['muhash']}")
        r2.start()
        r2.rpc.loadtxoutset(nocert)

        def pool_doc():
            return validator.rpc.gettpuinfo()["gateway"]["pool"]

        fw.wait_until(
            lambda: any(r["name"] == r2_name and r["quarantined"]
                        for r in pool_doc()["replicas"]), timeout=60)
        tip = validator.rpc.getbestblockhash()
        lat: list = []
        tips: set = set()
        lock = threading.Lock()

        def worker(w):
            box = [None]
            local = []
            seen = set()
            for k in range(reads // workers):
                kind, payload, dt = _gw_request(
                    box, gw_port, auth, f"q{w}", "getbestblockhash", [])
                if kind == "ok":
                    local.append(dt)
                    seen.add(payload)
            with lock:
                lat.extend(local)
                tips.update(seen)

        with ThreadPoolExecutor(max_workers=workers) as ex:
            list(ex.map(worker, range(workers)))
        pool = pool_doc()
        by_name = {r["name"]: r for r in pool["replicas"]}
        assert by_name[r2_name]["quarantined"], \
            "the cert-less replica left quarantine mid-drill"
        assert tips == {tip}, f"inconsistent replies: {len(tips)} tips"
        quarantines = pool["quarantines"]

    lat.sort()
    p99 = round(lat[int(0.99 * (len(lat) - 1))] * 1e3, 2)
    assert p99 <= p99_bar_ms, \
        f"quarantine-drill p99 {p99} ms over the {p99_bar_ms} ms bar"
    result["fleet_quarantine"] = {
        "reads": len(lat),
        "latency_ms": {
            "p50": round(lat[len(lat) // 2] * 1e3, 2),
            "p99": p99,
        },
        "p99_bar_ms": p99_bar_ms,
        "p99_ok": True,
        "quarantines": quarantines,
        "inconsistent_replies": 0,
    }
    result["note"] = (
        "proof-carrying snapshots: million-coin certificate built at "
        "dump and verified at load in seconds (vs hours of blind shadow "
        "re-validation); node-level spot-check onboarding beats full "
        "re-validation with byte-identical digests; forged epoch "
        "hard-aborts at the divergent checkpoint; gateway p99 holds "
        "while a cert-less replica sits quarantined")
    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "BENCH_r17.json"), "w") as fh:
        json.dump(result, fh, indent=1)
        fh.write("\n")
    emit("snapshot_cert_spotcheck_speedup",
         result["onboarding"]["spotcheck_speedup"], "x",
         result["onboarding"]["spotcheck_speedup"],
         **{k: v for k, v in result.items() if k != "metric"})
    return {
        "snapcert_verify_at_load_s": result["algebra"]["verify_at_load_s"],
        "snapcert_spotcheck_speedup":
            result["onboarding"]["spotcheck_speedup"],
        "snapcert_quarantine_p99_ms": p99,
    }


def _device_reachable(timeout_s: int = 180) -> bool:
    """Guard against a wedged device tunnel: backend init hangs forever in
    that state (observed this round) inside C code, where neither signals
    nor KeyboardInterrupt land — so probe from a killable subprocess and
    only touch jax backends in THIS process once the probe succeeds."""
    try:
        probe = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices(); print('ok')"],
            capture_output=True, text=True, timeout=timeout_s,
        )
        return probe.returncode == 0 and "ok" in probe.stdout
    except subprocess.TimeoutExpired:
        return False


def bench_schnorr_msm():
    """ISSUE 19: Schnorr batch verification — Pippenger MSM batch check
    vs the per-lane ladder, with the batch-vs-ladder crossover curve.

    For each batch size N the same records run through (a) the per-lane
    CPU oracle (the reference engine and the accept/reject oracle the
    batch path must match byte-identically) and (b) the full MSM dispatch
    (canary batches, host pack, one device batch equation). Sizes map to
    MSM buckets 64/64/256 by default — the bucket-1024 rung is a
    many-minute XLA compile on a CPU backend, opt in via
    BCP_BENCH_MSM_SIZES. Writes BENCH_r19.json (schema 2 + host stamp)."""
    import hashlib
    import tempfile

    from bitcoincashplus_tpu.crypto import secp256k1 as oracle
    from bitcoincashplus_tpu.ops import ecdsa_batch as eb
    from bitcoincashplus_tpu.script.interpreter import SigCheckRecord
    from bitcoincashplus_tpu.util import devicewatch as dwatch

    # bucket compiles are minutes cold on the XLA CPU backend — share
    # the persistent cache the test suite / dispatch_breakdown use
    dwatch.enable_compile_cache(
        os.environ.get("BCP_COMPILE_CACHE",
                       os.path.join(tempfile.gettempdir(),
                                    "bcp-jax-test-cache")))

    sizes = [int(x) for x in os.environ.get(
        "BCP_BENCH_MSM_SIZES", "8,31,127").split(",") if x.strip()]

    def srec(i):
        d = 0xB00 + i
        e = int.from_bytes(hashlib.sha256(b"bench%d" % i).digest(),
                           "big") % oracle.N
        r, s = oracle.schnorr_sign(d, e)
        return SigCheckRecord(oracle.point_mul(d, oracle.G), r, s, e,
                              algo="schnorr")

    curve = []
    crossover = None
    for n in sizes:
        recs = [srec(i) for i in range(n)]
        expect = [oracle.schnorr_verify(r.pubkey, r.r, r.s, r.msg_hash)
                  for r in recs]

        def run_msm():
            out = eb.dispatch_batch(
                recs, backend="device", kernel="msm").result()
            assert out.tolist() == expect, "msm verdicts diverged"
            return out

        run_msm()  # warm: pay the bucket's XLA compile outside timing
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            run_msm()
            ts.append(time.perf_counter() - t0)
        msm_s = sorted(ts)[1]

        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            out = eb.dispatch_batch(recs, backend="cpu").result()
            ts.append(time.perf_counter() - t0)
            assert out.tolist() == expect
        lad_s = sorted(ts)[1]

        point = {
            "batch_sigs": n,
            "msm_bucket": eb._msm_bucket_for(2 * n + 1),
            "msm_sigs_per_s": round(n / msm_s, 1),
            "ladder_sigs_per_s": round(n / lad_s, 1),
            "msm_speedup": round(lad_s / msm_s, 3),
        }
        curve.append(point)
        if crossover is None and msm_s < lad_s:
            crossover = n
        emit("schnorr_msm_sigs_per_s", point["msm_sigs_per_s"], "sigs/s",
             point["msm_speedup"], batch=n)

    result = {
        "metric": "schnorr_msm_crossover",
        **_bench_stamp(),
        "curve": curve,
        "crossover_batch_sigs": crossover,
        "msm_seeded": "BCP_MSM_SEED" in os.environ,
        "note": "per-dispatch cost includes the 2 canary batches + host "
                "pack + challenge hashing; the ladder column is the "
                "per-lane Python-int oracle (the byte-identical "
                "accept/reject reference). Crossover = smallest measured "
                "batch where the MSM dispatch beats the ladder; "
                "-ecdsakernel=msm routes Schnorr lanes through it while "
                "ECDSA lanes keep riding glv.",
    }
    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "BENCH_r19.json"), "w") as f:
        json.dump(result, f, indent=1)
        f.write("\n")
    best = max(curve, key=lambda p: p["msm_speedup"]) if curve else {}
    return {"schnorr_msm_crossover_sigs": crossover,
            "schnorr_msm_best_speedup": best.get("msm_speedup")}


def main():
    if not _device_reachable():
        emit("sha256d_sweep_throughput_per_chip", 0.0, "GH/s", 0.0,
             error="device tunnel unreachable (backend init timed out); "
                   "session-measured values: sweep 0.94 GH/s, ecdsa 3301 "
                   "sigs/s — see ROOFLINE.md / PARITY.md")
        return
    on_cpu = jax.default_backend() == "cpu"
    recap = {}
    recap.update(bench_header_hash() or {})
    recap.update(bench_merkle() or {})
    device_sps = None
    if not on_cpu:
        # device kernel; CPU fallback would not be news
        device_sps = bench_ecdsa_batch()
    recap["ecdsa_sigs_per_s"] = round(device_sps) if device_sps else None
    recap.update(bench_reindex(device_sps) or {})  # config 6: north star
    recap.update(bench_import_pipeline() or {})  # ISSUE 4: settle horizon
    recap.update(bench_fork_storm() or {})  # ISSUE 9: speculation tree
    try:
        recap.update(bench_mining() or {})  # ISSUE 10: resident loop
    except Exception as e:  # pragma: no cover - diagnostics only
        emit("mining_resident_speedup", -1, "x", 0.0,
             error=f"{type(e).__name__}: {e}")
    try:
        recap.update(bench_utxo_store() or {})  # ISSUE 13: sharded store
    except Exception as e:  # pragma: no cover - diagnostics only
        emit("utxo_store_flush_speedup_4v1", -1, "x", 0.0,
             error=f"{type(e).__name__}: {e}")
    try:
        recap.update(bench_mempool_storm() or {})  # ISSUE 20: flood pool
    except Exception as e:  # pragma: no cover - diagnostics only
        emit("mempool_storm_batched_speedup", -1, "x", 0.0,
             error=f"{type(e).__name__}: {e}")
    recap.update(bench_telemetry_overhead() or {})  # ISSUE 6: < 2% budget
    recap.update(bench_serving() or {})  # ISSUE 7: serviced >= 2x sync
    if os.environ.get("BCP_BENCH_FLEET", "1") != "0":
        try:
            recap.update(bench_fleet() or {})  # ISSUE 16: front door
        except Exception as e:  # pragma: no cover - diagnostics only
            emit("fleet_storm_p99", -1, "ms", 0.0,
                 error=f"{type(e).__name__}: {e}")
    if os.environ.get("BCP_BENCH_SNAPCERT", "1") != "0":
        try:
            recap.update(bench_snapshot_cert() or {})  # ISSUE 17: certs
        except Exception as e:  # pragma: no cover - diagnostics only
            emit("snapshot_cert_verify_at_load", -1, "s", 0.0,
                 error=f"{type(e).__name__}: {e}")
    if os.environ.get("BCP_BENCH_MSM", "1") != "0":
        try:
            recap.update(bench_schnorr_msm() or {})  # ISSUE 19: MSM
        except Exception as e:  # pragma: no cover - diagnostics only
            emit("schnorr_msm_sigs_per_s", -1, "sigs/s", 0.0,
                 error=f"{type(e).__name__}: {e}")
    try:
        recap.update(bench_dispatch_breakdown() or {})  # ISSUE 8: phases
    except Exception as e:  # pragma: no cover - diagnostics only
        emit("dispatch_breakdown", -1, "x", 0.0,
             error=f"{type(e).__name__}: {e}")
    recap.update(bench_virtual_shard() or {})
    # compact recap line so every config's headline value survives the
    # driver's 2000-byte tail capture (VERDICT r4 item 5); the true
    # headline still goes LAST (the driver parses the final line)
    emit("summary_recap", 1, "-", 0.0, values=recap)
    bench_sweep_headline()  # headline LAST


if __name__ == "__main__":
    # `python bench.py dispatch_breakdown` / `fork_storm` / `mining` run
    # one section alone (all are also part of the full run)
    if len(sys.argv) > 1 and sys.argv[1] == "dispatch_breakdown":
        bench_dispatch_breakdown()
    elif len(sys.argv) > 1 and sys.argv[1] == "fork_storm":
        bench_fork_storm()
    elif len(sys.argv) > 1 and sys.argv[1] == "mining":
        bench_mining()
    elif len(sys.argv) > 1 and sys.argv[1] == "utxo_store":
        bench_utxo_store()
    elif len(sys.argv) > 1 and sys.argv[1] == "mempool_storm":
        # flood-scale mempool differential + latency bars (ISSUE 20):
        # pure pool mechanics, no device needed
        bench_mempool_storm()
    elif len(sys.argv) > 1 and sys.argv[1] == "fleet":
        # multi-process fleet storm: children force JAX_PLATFORMS=cpu,
        # no device needed in this process either
        bench_fleet()
    elif len(sys.argv) > 1 and sys.argv[1] == "schnorr_msm":
        # Schnorr MSM batch-vs-ladder crossover (ISSUE 19): CPU backend
        # is fine — the MSM program is plain XLA
        bench_schnorr_msm()
    elif len(sys.argv) > 1 and sys.argv[1] == "snapshot_cert":
        # proof-carrying snapshot harness (ISSUE 17): store-level at
        # 10^6 coins plus real-process onboarding/fleet legs on CPU
        bench_snapshot_cert()
    else:
        main()
