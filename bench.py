"""Headline benchmarks — one JSON object per line, headline metric LAST
(the driver parses the final line; the tail carries all five BASELINE.json
configs, VERDICT r2 item 5).

Configs (BASELINE.json):
  1. batched 80-byte header double-SHA (device), correctness-anchored against
     the known mainnet genesis hash + hashlib vectors
  2. getblocktemplate nonce-sweep miner, single chip  <- HEADLINE (last line)
  3. Merkle-root construction over a 4096-tx snapshot
  4. secp256k1 ECDSA batch-verify, 10k-sig ConnectBlock-scale batch
  5. 8-chip nonce shard — reported from the 8-device VIRTUAL CPU mesh
     (no multi-chip hardware on this host; the metric is scaling speedup,
     clearly labeled, not GH/s)

Timing honesty: the axon serving layer memoizes identical (program, args)
dispatches, so every timed run randomizes an argument; medians over repeats;
a warmup dispatch absorbs compile. The sweep timings force a scalar host
fetch (int(tiles)) because block_until_ready alone does not synchronize
through the serving tunnel.
"""

import json
import os
import random
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
import numpy as np

BASELINE_GHS = 500.0  # BASELINE.json north star, per chip (see ROOFLINE.md)


def emit(metric, value, unit, vs_baseline, **extra):
    line = {"metric": metric, "value": value, "unit": unit,
            "vs_baseline": vs_baseline}
    line.update(extra)
    print(json.dumps(line), flush=True)


def bench_header_hash():
    """Config 1: device batch header double-SHA, anchored to known vectors."""
    import hashlib

    from bitcoincashplus_tpu.consensus.params import main_params
    from bitcoincashplus_tpu.ops.sha256 import sha256d_headers

    # correctness anchor: mainnet genesis header hashes to the known hash
    genesis = main_params().genesis
    hdr = genesis.header.serialize()
    digest = sha256d_headers(np.frombuffer(hdr, np.uint8).reshape(1, 80))[0]
    assert bytes(digest) == genesis.get_hash(), "genesis vector mismatch"

    B = 1 << 16
    rng = np.random.default_rng(1)
    warm = rng.integers(0, 256, (B, 80), dtype=np.uint8)
    out = sha256d_headers(warm)
    # spot-check a lane against hashlib
    h0 = hashlib.sha256(hashlib.sha256(warm[0].tobytes()).digest()).digest()
    assert bytes(out[0]) == h0
    ts = []
    for _ in range(3):
        batch = rng.integers(0, 256, (B, 80), dtype=np.uint8)
        t0 = time.perf_counter()
        out = sha256d_headers(batch)
        ts.append(time.perf_counter() - t0)
    dt = sorted(ts)[1]
    mhs = B / dt / 1e6
    # device-resident form: same kernel with the batch already on device —
    # separates chip throughput from the serving-tunnel's ~4 MB/s bulk
    # transfer bandwidth (a co-located deployment pays PCIe/ICI, not this)
    import jax.numpy as jnp

    from bitcoincashplus_tpu.ops.sha256 import (
        headers_to_words_np,
        sha256d_headers_jit,
    )

    dev_words = jnp.asarray(headers_to_words_np(batch))
    sha256d_headers_jit(dev_words).block_until_ready()
    dts = []
    for _ in range(3):
        t0 = time.perf_counter()
        sha256d_headers_jit(dev_words).block_until_ready()
        dts.append(time.perf_counter() - t0)
    dev_mhs = B / sorted(dts)[1] / 1e6
    emit("header_hash_batch_throughput", round(mhs, 2), "MH/s",
         round(mhs * 1e6 / (BASELINE_GHS * 1e9), 6),
         device_resident_mhs=round(dev_mhs, 2),
         note="64Ki-header batch incl host pack/unpack + tunnel transfers "
              "(transfer-bound here); device_resident_mhs excludes "
              "host<->device transfer; genesis+hashlib anchored")


def bench_merkle():
    """Config 3: 4096-tx Merkle root on device vs the scalar host oracle."""
    from bitcoincashplus_tpu.consensus.merkle import compute_merkle_root
    from bitcoincashplus_tpu.ops.merkle import compute_merkle_root_tpu

    rng = np.random.default_rng(2)
    txids = [rng.bytes(32) for _ in range(4096)]
    root_ref, _ = compute_merkle_root(txids)
    root_dev, _ = compute_merkle_root_tpu(txids)  # warm + correctness
    assert root_dev == root_ref
    ts = []
    for _ in range(3):
        txids = [rng.bytes(32) for _ in range(4096)]
        t0 = time.perf_counter()
        compute_merkle_root_tpu(txids)
        ts.append(time.perf_counter() - t0)
    dt = sorted(ts)[1]
    emit("merkle_root_4096tx", round(dt * 1e3, 2), "ms",
         0.0, note="single-dispatch on-device tree reduction (masked odd-duplication); was 12 per-level dispatches")


def bench_ecdsa_batch():
    """Config 4: the 10k-sig ConnectBlock batch through the real dispatch
    path (pack -> bucket-pad -> device kernel -> unpack)."""
    from bitcoincashplus_tpu.crypto import secp256k1 as oracle
    from bitcoincashplus_tpu.ops import ecdsa_batch
    from bitcoincashplus_tpu.script.interpreter import SigCheckRecord

    rng = np.random.default_rng(5)
    base = []
    for _ in range(64):  # 64 distinct real (key, sig, msg) triples
        secret = int.from_bytes(rng.bytes(32), "big") % (oracle.N - 1) + 1
        pub = oracle.point_mul(secret, oracle.G)
        e = int.from_bytes(rng.bytes(32), "big") % oracle.N
        r, s = oracle.ecdsa_sign(secret, e)
        base.append((pub, r, s, e))
    records = [  # tiled to 10k lanes (device work identical per lane)
        SigCheckRecord(*base[i % 64], b"\x00" * 32, 0) for i in range(10_000)
    ]
    ok = ecdsa_batch.verify_batch(records, backend="device")  # warm/compile
    assert bool(ok.all())
    t0 = time.perf_counter()
    ok = ecdsa_batch.verify_batch(records, backend="device")
    dt = time.perf_counter() - t0
    assert bool(ok.all())
    sps = len(records) / dt
    from bitcoincashplus_tpu.ops.ecdsa_batch import STATS as _st
    from bitcoincashplus_tpu.ops.ecdsa_batch import pallas_enabled as _pe

    # label from the same predicate dispatch uses (a disabled/fallen-back
    # pallas path must not be reported as pallas)
    kernel = "pallas-vmem" if _pe() and not _st.pallas_fallbacks else "xla"
    emit("ecdsa_batch_verify_10k", round(sps), "sigs/s", 0.0,
         kernel=kernel,
         note=f"B=10000 through the full dispatch path ({dt:.2f}s); 64 "
              "distinct sigs tiled (per-lane work identical); pallas "
              "kernel keeps the 256-step ladder in VMEM (2.4x the XLA form)")


def bench_virtual_shard():
    """Config 5: 8-chip nonce shard on the VIRTUAL CPU mesh — scaling
    speedup only (one real chip on this host; the same shard_map program is
    what rides ICI on real hardware). Subprocess keeps JAX_PLATFORMS clean."""
    code = r"""
import os, time, json
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
import sys; sys.path.insert(0, %r)
import jax
jax.config.update("jax_default_device", jax.devices("cpu")[0])
from bitcoincashplus_tpu.parallel.nonce_shard import sweep_header_sharded
header = bytes(range(80))
def timed(n_chips, tiles):
    t0 = time.perf_counter()
    nonce, hashes = sweep_header_sharded(header, 0, max_nonces=tiles * 4096,
                                         tile=4096, n_chips=n_chips)
    return time.perf_counter() - t0, hashes
timed(8, 8)   # warm 8-way
timed(1, 1)   # warm 1-way
t8, h8 = timed(8, 64)
t1, h1 = timed(1, 8)
r8, r1 = h8 / t8, h1 / t1
print(json.dumps({"speedup": r8 / r1, "r1_mhs": r1 / 1e6, "r8_mhs": r8 / 1e6}))
""" % os.path.dirname(os.path.abspath(__file__))
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    try:
        out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                             text=True, env=env, timeout=900)
        line = out.stdout.strip().splitlines()[-1] if out.stdout.strip() else "{}"
        r = json.loads(line)
        emit("nonce_shard_virtual8_speedup", round(r["speedup"], 2), "x", 0.0,
             note="8-device VIRTUAL CPU mesh (no multi-chip hardware here); "
                  "shard_map program identical to the ICI path")
    except Exception as e:  # pragma: no cover - diagnostics only
        emit("nonce_shard_virtual8_speedup", -1, "x", 0.0,
             note=f"subprocess failed: {e}")


def bench_sweep_headline():
    """Config 2 (HEADLINE, printed last): single-chip nonce-sweep GH/s on
    the tuned Pallas kernel, XLA while-loop fallback if Pallas fails."""
    from bitcoincashplus_tpu.crypto.hashes import header_midstate
    from bitcoincashplus_tpu.ops.sha256 import bytes_to_words_np, target_to_limbs_np

    header = bytes(range(80))
    mid = jnp.asarray(np.array(header_midstate(header), dtype=np.uint32))
    tail = jnp.asarray(bytes_to_words_np(np.frombuffer(header[64:76], np.uint8)))

    on_cpu = jax.default_backend() == "cpu"
    kernel = "pallas"
    try:
        if on_cpu:
            raise RuntimeError("pallas TPU kernel needs the chip")
        from bitcoincashplus_tpu.ops.pallas_sweep import pallas_sweep_jit

        sublanes, max_tiles = 64, 262144  # tuned: tools/roofline.py sweep
        tile = sublanes * 128

        def run(start, n):
            _f, _n, t = pallas_sweep_jit(mid, tail, jnp.uint32(0), start, n,
                                         sublanes=sublanes, max_tiles=max_tiles)
            return int(t)

        n_units = max_tiles
        run(jnp.uint32(0), jnp.uint32(1))  # warm/compile INSIDE the try:
        # jax.jit compiles lazily, so a Mosaic lowering failure on another
        # TPU generation surfaces here, not at import
    except Exception:
        kernel = "xla-while"
        from bitcoincashplus_tpu.ops.miner import sweep_jit

        tgt = jnp.asarray(target_to_limbs_np(0))
        tile = 1 << 14 if on_cpu else 1 << 20
        n_units = 4 if on_cpu else 128

        def run(start, n):
            _f, _n, t = sweep_jit(mid, tail, tgt, start, n, tile=tile)
            return int(t)

        run(jnp.uint32(0), jnp.uint32(1))  # warm/compile the fallback
    rates = []
    for _ in range(4):
        start = jnp.uint32(random.getrandbits(32))
        t0 = time.perf_counter()
        tiles = run(start, jnp.uint32(n_units))
        dt = time.perf_counter() - t0
        rates.append(tiles * tile / dt)
    rates = sorted(rates[1:])
    ghs = rates[len(rates) // 2] / 1e9
    emit("sha256d_sweep_throughput_per_chip", round(ghs, 4), "GH/s",
         round(ghs / BASELINE_GHS, 6),
         kernel=kernel,
         note="truncated-h7 specialized double-SHA at ~90% of the chip's "
              "6.17T u32-op/s VPU integer ceiling — see ROOFLINE.md")


def bench_reindex():
    """Config 6 — the NORTH STAR (BASELINE.json: mainnet -reindex wall-clock
    < 45 min on v5e-8): generate a synthetic signature-dense regtest chain
    (tools/gen_sigchain.py), run the full Node(-reindex) import over it
    (LoadExternalBlockFile -> ProcessNewBlock -> ConnectBlock -> TPU sig
    batch), and report measured blocks/s / tx/s / sigs/s plus a projected
    mainnet wall-clock from the component profile.

    Projection model (constants are fork-era public chain shape, NOT from
    the empty reference mount): total = sig_leg + byte_leg where
    sig_leg = MAINNET_SIG_INPUTS * (verify_seconds / sigs) and
    byte_leg = MAINNET_BYTES / (chain_bytes / non_verify_seconds).
    The verify leg contains host script interpretation + device ECDSA (the
    synthetic chain is 1 sig per input, like the P2PKH-dominated mainnet);
    the byte leg carries deserialize/connect/flush/index."""
    import shutil
    import tempfile

    MAINNET_BLOCKS = 478_558      # the fork height (params.py uahf_height)
    MAINNET_SIG_INPUTS = 550e6    # ~240M txs x ~2.3 inputs avg at that height
    MAINNET_BYTES = 130e9         # ~130 GB serialized chain at that height

    n_sigs = int(os.environ.get("BCP_BENCH_REINDEX_SIGS", "16000"))
    workdir = tempfile.mkdtemp(prefix="bcp-reindex-bench-")
    try:
        from tools.gen_sigchain import generate

        gen = generate(workdir, n_sigs)

        from bitcoincashplus_tpu.node.config import Config
        from bitcoincashplus_tpu.node.node import Node
        from bitcoincashplus_tpu.ops import ecdsa_batch

        stats0 = ecdsa_batch.STATS.snapshot()
        cfg = Config()
        cfg.args["datadir"] = [workdir]
        cfg.args["regtest"] = ["1"]
        cfg.args["reindex"] = ["1"]
        t0 = time.perf_counter()
        node = Node(config=cfg)
        wall = time.perf_counter() - t0
        tip = node.chainstate.tip()
        bench = dict(node.chainstate.bench)
        assert tip.height == gen["tip_height"], (tip.height, gen)

        verify_s = bench["verify_ms"] / 1e3
        other_s = max(wall - verify_s, 1e-9)
        sig_rate = gen["sigs"] / max(verify_s, 1e-9)
        byte_rate = gen["bytes"] / other_s
        proj_sig_leg = MAINNET_SIG_INPUTS / sig_rate
        proj_byte_leg = MAINNET_BYTES / byte_rate
        proj_min = (proj_sig_leg + proj_byte_leg) / 60
        stats1 = ecdsa_batch.STATS.snapshot()
        device_s = stats1["device_seconds"] - stats0.get("device_seconds", 0)
        emit(
            "reindex_projected_mainnet_min", round(proj_min), "min",
            round(45.0 / max(proj_min, 1e-9), 6),
            measured={
                "sigs": gen["sigs"], "blocks": gen["blocks"],
                "txs": gen["txs"], "bytes": gen["bytes"],
                "wall_s": round(wall, 1),
                "blocks_per_s": round(gen["blocks"] / wall, 2),
                "txs_per_s": round(gen["txs"] / wall, 1),
                "sigs_per_s_end_to_end": round(gen["sigs"] / wall),
                "verify_s": round(verify_s, 1),
                "device_verify_s": round(device_s, 1),
                "host_interpret_s": round(verify_s - device_s, 1),
                "connect_s": round(bench["connect_ms"] / 1e3, 1),
                "flush_s": round(bench["flush_ms"] / 1e3, 1),
                "other_s": round(other_s, 1),
            },
            projection={
                "sig_leg_min": round(proj_sig_leg / 60),
                "byte_leg_min": round(proj_byte_leg / 60),
                "model_sig_inputs": MAINNET_SIG_INPUTS,
                "model_bytes": MAINNET_BYTES,
                "model_blocks": MAINNET_BLOCKS,
            },
            note="synthetic P2PKH sig-dense chain via tools/gen_sigchain.py; "
                 "full script+sig validation (no assumevalid skip); target "
                 "45 min => vs_baseline = 45/projected",
        )
    except Exception as e:  # pragma: no cover - diagnostics only
        emit("reindex_projected_mainnet_min", -1, "min", 0.0,
             error=f"{type(e).__name__}: {e}")
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def _device_reachable(timeout_s: int = 180) -> bool:
    """Guard against a wedged device tunnel: backend init hangs forever in
    that state (observed this round) inside C code, where neither signals
    nor KeyboardInterrupt land — so probe from a killable subprocess and
    only touch jax backends in THIS process once the probe succeeds."""
    try:
        probe = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices(); print('ok')"],
            capture_output=True, text=True, timeout=timeout_s,
        )
        return probe.returncode == 0 and "ok" in probe.stdout
    except subprocess.TimeoutExpired:
        return False


def main():
    if not _device_reachable():
        emit("sha256d_sweep_throughput_per_chip", 0.0, "GH/s", 0.0,
             error="device tunnel unreachable (backend init timed out); "
                   "session-measured values: sweep 0.94 GH/s, ecdsa 3301 "
                   "sigs/s — see ROOFLINE.md / PARITY.md")
        return
    on_cpu = jax.default_backend() == "cpu"
    bench_header_hash()
    bench_merkle()
    if not on_cpu:
        bench_ecdsa_batch()  # device kernel; CPU fallback would not be news
    bench_reindex()  # config 6: the north-star metric
    bench_virtual_shard()
    bench_sweep_headline()  # headline LAST: the driver parses the final line


if __name__ == "__main__":
    main()
