"""Two-node P2P functional tests: block sync, tx relay, reorg, and a
fake peer feeding malformed traffic.

Reference behaviors: qa/rpc-tests/p2p-fullblocktest.py (block propagation),
mininode.py (the fake peer), plus the reference's headers-first sync flow
(src/net_processing.cpp).
"""

from __future__ import annotations

import socket
import struct
import time

import pytest

from bitcoincashplus_tpu.consensus.params import regtest_params
from bitcoincashplus_tpu.p2p.protocol import (
    HEADER_SIZE,
    VersionPayload,
    pack_message,
)
from bitcoincashplus_tpu.wallet.keys import CKey

from .framework import (
    FunctionalFramework,
    connect_nodes,
    sync_blocks,
    sync_mempools,
    wait_until,
)

pytestmark = pytest.mark.functional

KEY = CKey(0xFADE)
ADDR = KEY.p2pkh_address(regtest_params())


def test_two_node_sync_relay_reorg():
    with FunctionalFramework(num_nodes=2) as f:
        a, b = f.nodes
        connect_nodes(b, a)

        # -- initial block download: A mines, B follows ------------------
        a.rpc.generatetoaddress(101, ADDR)
        sync_blocks(f.nodes)
        assert b.rpc.getblockcount() == 101

        # -- tx relay ----------------------------------------------------
        block1 = a.rpc.getblock(a.rpc.getblockhash(1), 2)
        raw = _spend_tx(block1["tx"][0], 25_0000_0000)  # block 1 paid ADDR/KEY
        txid = a.rpc.sendrawtransaction(raw)
        sync_mempools(f.nodes)
        assert txid in b.rpc.getrawmempool()

        # -- B mines the tx; block propagates back to A ------------------
        b.rpc.generatetoaddress(1, ADDR)
        sync_blocks(f.nodes)
        assert a.rpc.getrawmempool() == []
        assert a.rpc.getblockcount() == 102

        # -- reorg: B builds a longer chain while disconnected -----------
        b.stop()
        a.rpc.generatetoaddress(2, ADDR)  # A at 104
        b.start()
        b.rpc.generatetoaddress(4, ADDR)  # B at 106 on its own branch
        assert b.rpc.getblockcount() == 106
        connect_nodes(b, a)
        sync_blocks(f.nodes, timeout=90)
        assert a.rpc.getblockcount() == 106
        assert a.rpc.getbestblockhash() == b.rpc.getbestblockhash()
        # the abandoned branch shows up as a valid-fork chain tip
        tips = a.rpc.getchaintips()
        assert any(t["status"] != "active" for t in tips)


def _spend_tx(cb: dict, amount: int) -> str:
    """Spend a coinbase (decoded tx json) paid to ADDR/KEY."""
    from bitcoincashplus_tpu.consensus.serialize import hex_to_hash
    from bitcoincashplus_tpu.consensus.tx import (
        COutPoint,
        CTransaction,
        CTxIn,
        CTxOut,
    )
    from bitcoincashplus_tpu.script.sighash import SIGHASH_ALL
    from bitcoincashplus_tpu.wallet.signing import sign_transaction

    value = int(round(cb["vout"][0]["value"] * 1e8))
    spk = bytes.fromhex(cb["vout"][0]["scriptPubKey"]["hex"])
    tx = CTransaction(
        vin=(CTxIn(COutPoint(hex_to_hash(cb["txid"]), 0)),),
        vout=(CTxOut(amount, CKey(0xF00D).p2pkh_script()),
              CTxOut(value - amount - 2000, KEY.p2pkh_script())),
    )
    signed = sign_transaction(
        tx, [(spk, value)],
        lambda ident: KEY if ident == KEY.pubkey_hash else None,
        SIGHASH_ALL, enable_forkid=True,
    )
    return signed.serialize().hex()


def test_fake_peer_malformed_messages():
    """A mininode-style raw-socket peer sends garbage; the node must
    disconnect it and keep serving (SURVEY §6.3 fault handling)."""
    with FunctionalFramework(num_nodes=1) as f:
        node = f.nodes[0]
        node.rpc.generatetoaddress(3, ADDR)
        magic = regtest_params().netmagic

        # handshake then bad checksum
        s = socket.create_connection(("127.0.0.1", node.p2p_port), timeout=10)
        s.sendall(pack_message(magic, "version", VersionPayload().serialize()))
        _read_msg(s)  # their version
        _read_msg(s)  # their verack
        bad = bytearray(pack_message(magic, "ping", b"\x00" * 8))
        bad[20] ^= 0xFF  # corrupt checksum
        s.sendall(bytes(bad))
        assert _expect_disconnect(s)

        # wrong netmagic disconnects immediately
        s2 = socket.create_connection(("127.0.0.1", node.p2p_port), timeout=10)
        s2.sendall(b"\xde\xad\xbe\xef" + b"ping".ljust(12, b"\x00")
                   + struct.pack("<I", 8) + b"\x00" * 4 + b"\x00" * 8)
        assert _expect_disconnect(s2)

        # oversized payload length disconnects
        s3 = socket.create_connection(("127.0.0.1", node.p2p_port), timeout=10)
        s3.sendall(magic + b"tx".ljust(12, b"\x00")
                   + struct.pack("<I", 1 << 30) + b"\x00" * 4)
        assert _expect_disconnect(s3)

        # node is still alive and mining
        node.rpc.generatetoaddress(1, ADDR)
        assert node.rpc.getblockcount() == 4
        assert node.rpc.getconnectioncount() == 0


def _read_msg(s: socket.socket) -> tuple[bytes, bytes]:
    header = _recv_exact(s, HEADER_SIZE)
    (length,) = struct.unpack_from("<I", header, 16)
    return header, _recv_exact(s, length)


def _recv_exact(s: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = s.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("closed")
        buf += chunk
    return buf


def _expect_disconnect(s: socket.socket, timeout: float = 15.0) -> bool:
    s.settimeout(timeout)
    try:
        deadline = time.time() + timeout
        while time.time() < deadline:
            data = s.recv(4096)
            if not data:
                return True
    except (ConnectionError, socket.timeout, OSError):
        return True
    finally:
        s.close()
    return False


def test_orphan_tx_parking_and_mempool_msg():
    """Child-before-parent relay: the child parks in the orphan pool and is
    accepted when the parent arrives (net_processing mapOrphanTransactions);
    BIP35 'mempool' answers with an inv of the pool."""
    from bitcoincashplus_tpu.consensus.serialize import ByteReader
    from bitcoincashplus_tpu.consensus.tx import (
        COutPoint,
        CTransaction,
        CTxIn,
        CTxOut,
    )
    from bitcoincashplus_tpu.p2p.protocol import MSG_TX, deser_inv
    from bitcoincashplus_tpu.script.sighash import SIGHASH_ALL
    from bitcoincashplus_tpu.wallet.signing import sign_transaction
    from .framework import wait_until

    with FunctionalFramework(num_nodes=1) as f:
        node = f.nodes[0]
        node.rpc.generatetoaddress(101, ADDR)
        blk1 = node.rpc.getblock(node.rpc.getblockhash(1), 2)
        cb = blk1["tx"][0]

        # parent spends the coinbase; child spends the parent
        prev = bytes.fromhex(cb["txid"])[::-1]
        spk = KEY.p2pkh_script()
        value = 50 * 100_000_000
        parent = sign_transaction(
            CTransaction(vin=(CTxIn(COutPoint(prev, 0)),),
                         vout=(CTxOut(value - 10_000, spk),)),
            [(spk, value)], lambda i: KEY if i == KEY.pubkey_hash else None,
            SIGHASH_ALL, enable_forkid=True,
        )
        child = sign_transaction(
            CTransaction(vin=(CTxIn(COutPoint(parent.txid, 0)),),
                         vout=(CTxOut(value - 20_000, spk),)),
            [(spk, value - 10_000)],
            lambda i: KEY if i == KEY.pubkey_hash else None,
            SIGHASH_ALL, enable_forkid=True,
        )

        magic = regtest_params().netmagic
        s = socket.create_connection(("127.0.0.1", node.p2p_port), timeout=10)
        s.sendall(pack_message(magic, "version", VersionPayload().serialize()))
        _read_msg(s)
        _read_msg(s)
        s.sendall(pack_message(magic, "verack"))

        # child FIRST: must not enter the mempool yet
        s.sendall(pack_message(magic, "tx", child.serialize()))
        time.sleep(1.0)
        assert node.rpc.getrawmempool() == []
        # parent arrives: both are accepted
        s.sendall(pack_message(magic, "tx", parent.serialize()))
        wait_until(lambda: len(node.rpc.getrawmempool()) == 2, timeout=20)

        # BIP35 mempool message: node answers with a 2-entry tx inv
        s.sendall(pack_message(magic, "mempool"))
        deadline = time.time() + 15
        got = set()
        while time.time() < deadline and len(got) < 2:
            hdr, payload = _read_msg(s)
            if hdr[4:16].rstrip(b"\x00") == b"inv":
                for t, h in deser_inv(payload):
                    if t == MSG_TX:
                        got.add(h)
        assert got == {parent.txid, child.txid}
        s.close()


def test_bip37_spv_flow():
    """SPV fake peer: filterload → mine a block paying the watched key →
    getdata(MSG_FILTERED_BLOCK) returns merkleblock + matched tx; the
    proof verifies against the header; gettxoutproof/verifytxoutproof
    round-trips the same proof over RPC."""
    from bitcoincashplus_tpu.consensus.block import CBlockHeader
    from bitcoincashplus_tpu.consensus.merkleblock import CMerkleBlock
    from bitcoincashplus_tpu.consensus.serialize import ByteReader, hash_to_hex, hex_to_hash
    from bitcoincashplus_tpu.consensus.tx import CTransaction
    from bitcoincashplus_tpu.p2p.bloom import (
        BLOOM_UPDATE_ALL,
        CBloomFilter,
        ser_filterload,
    )
    from bitcoincashplus_tpu.p2p.protocol import MSG_FILTERED_BLOCK, ser_inv

    with FunctionalFramework(num_nodes=1, extra_args=[["-txindex"]]) as f:
        node = f.nodes[0]
        magic = regtest_params().netmagic
        node.rpc.generatetoaddress(101, node.rpc.getnewaddress())

        # wallet pays a watched key
        watched = CKey(0x511511)
        waddr = watched.p2pkh_address(regtest_params())
        txid_hex = node.rpc.sendtoaddress(waddr, 1.0)
        block_hash = node.rpc.generatetoaddress(1, ADDR)[0]

        # -- SPV peer connects, loads a filter on the watched pubkey hash --
        s = socket.create_connection(("127.0.0.1", node.p2p_port), timeout=10)
        s.sendall(pack_message(magic, "version", VersionPayload().serialize()))
        _read_msg(s)  # version
        _read_msg(s)  # verack
        s.sendall(pack_message(magic, "verack"))
        f37 = CBloomFilter(5, 0.000001, 0, BLOOM_UPDATE_ALL)
        f37.insert(watched.pubkey_hash)
        s.sendall(pack_message(magic, "filterload", ser_filterload(f37)))
        s.sendall(pack_message(magic, "getdata", ser_inv(
            [(MSG_FILTERED_BLOCK, hex_to_hash(block_hash))]
        )))
        # responses: skip handshake chatter until merkleblock arrives
        deadline = time.time() + 20
        merkleblock = None
        txs = []
        while time.time() < deadline:
            header, payload = _read_msg(s)
            cmd = header[4:16].rstrip(b"\x00").decode()
            if cmd == "merkleblock":
                merkleblock = payload
            elif cmd == "tx" and merkleblock is not None:
                txs.append(payload)
                break
        s.close()
        assert merkleblock is not None, "no merkleblock received"
        mb = CMerkleBlock.from_bytes(merkleblock)
        root, matches = mb.pmt.extract_matches()
        assert root == mb.header.hash_merkle_root
        assert hash_to_hex(mb.header.get_hash()) == block_hash
        matched_txids = [t for _p, t in matches]
        assert hex_to_hash(txid_hex) in matched_txids
        assert any(CTransaction.from_bytes(t).txid == hex_to_hash(txid_hex)
                   for t in txs)

        # -- RPC proof round-trip ---------------------------------------
        proof = node.rpc.gettxoutproof([txid_hex])
        assert node.rpc.verifytxoutproof(proof) == [txid_hex]
        proof2 = node.rpc.gettxoutproof([txid_hex], block_hash)
        assert node.rpc.verifytxoutproof(proof2) == [txid_hex]
        # tampering the proof breaks it
        bad = bytearray(bytes.fromhex(proof))
        bad[40] ^= 0x01  # inside the merkle root field of the header
        from bitcoincashplus_tpu.rpc.client import JSONRPCException
        with pytest.raises(JSONRPCException):
            node.rpc.verifytxoutproof(bytes(bad).hex())


def test_bip152_compact_blocks():
    """Compact-block relay both directions against a live node:
    (a) a fake peer opts into high-bandwidth mode, mines from a template,
    and submits the block as cmpctblock only — the node reconstructs it
    from its own mempool and connects it; (b) the node announces the next
    block to that peer as cmpctblock, and serves getblocktxn."""
    import struct as _struct

    from bitcoincashplus_tpu.consensus.serialize import ByteReader, hex_to_hash
    from bitcoincashplus_tpu.p2p.compact import (
        BlockTransactions,
        BlockTransactionsRequest,
        HeaderAndShortIDs,
    )
    from .test_node_basic import _mine_template, _spend_coinbase

    with FunctionalFramework(num_nodes=1) as f:
        node = f.nodes[0]
        magic = regtest_params().netmagic
        addr = node.rpc.getnewaddress()
        node.rpc.generatetoaddress(101, addr)
        # put a tx in the node's mempool so reconstruction has work to do
        node.rpc.sendtoaddress(ADDR, 1.0)

        s = socket.create_connection(("127.0.0.1", node.p2p_port), timeout=10)
        s.sendall(pack_message(magic, "version", VersionPayload().serialize()))
        _read_msg(s)
        _read_msg(s)
        s.sendall(pack_message(magic, "verack"))
        # opt into high-bandwidth announcements
        s.sendall(pack_message(magic, "sendcmpct", _struct.pack("<BQ", 1, 1)))

        # -- (a) fake peer mines and relays via cmpctblock ---------------
        tmpl = node.rpc.getblocktemplate()
        assert len(tmpl["transactions"]) == 1
        block = _mine_template(tmpl, ADDR)
        hs = HeaderAndShortIDs.from_block(block, nonce=77)
        assert len(hs.shortids) == 1  # the mempool tx travels as a shortid
        s.sendall(pack_message(magic, "cmpctblock", hs.serialize()))
        wait_until(lambda: node.rpc.getbestblockhash() == block.hash_hex,
                   timeout=20)

        # -- (b) node announces its next block as cmpctblock -------------
        node.rpc.sendtoaddress(ADDR, 0.5)
        mined = node.rpc.generatetoaddress(1, addr)[0]
        deadline = time.time() + 20
        announced = None
        while time.time() < deadline and announced is None:
            header, payload = _read_msg(s)
            cmd = header[4:16].rstrip(b"\x00").decode()
            if cmd == "cmpctblock":
                announced = HeaderAndShortIDs.deserialize(ByteReader(payload))
        assert announced is not None
        from bitcoincashplus_tpu.consensus.serialize import hash_to_hex
        assert hash_to_hex(announced.header.get_hash()) == mined

        # pretend we know nothing: request every non-prefilled tx
        total = announced.total_tx_count()
        missing = [i for i in range(total)
                   if i not in [p[0] for p in announced.prefilled]]
        req = BlockTransactionsRequest(hex_to_hash(mined), missing)
        s.sendall(pack_message(magic, "getblocktxn", req.serialize()))
        bt = None
        deadline = time.time() + 20
        while time.time() < deadline and bt is None:
            header, payload = _read_msg(s)
            cmd = header[4:16].rstrip(b"\x00").decode()
            if cmd == "blocktxn":
                bt = BlockTransactions.deserialize(ByteReader(payload))
        assert bt is not None and len(bt.txs) == len(missing)
        # reconstruct and match the node's actual block
        from bitcoincashplus_tpu.p2p.compact import short_id, short_id_keys
        k0, k1 = short_id_keys(announced.header, announced.nonce)
        pool = {short_id(k0, k1, t.txid): t for t in bt.txs}
        got, still_missing = announced.reconstruct(pool.get)
        assert still_missing == [] and got is not None
        raw = node.rpc.getblock(mined, 0)
        assert got.serialize().hex() == raw
        s.close()


def test_feefilter_reject_and_relay_memory():
    """BIP133 feefilter suppresses low-fee invs; BIP61 reject answers an
    invalid tx; mapRelay serves getdata for a just-mined tx."""
    import struct as _struct

    from bitcoincashplus_tpu.consensus.serialize import ByteReader, hex_to_hash
    from bitcoincashplus_tpu.consensus.tx import (
        COutPoint,
        CTransaction,
        CTxIn,
        CTxOut,
    )
    from bitcoincashplus_tpu.p2p.protocol import MSG_TX, deser_inv, ser_inv

    with FunctionalFramework(num_nodes=1) as f:
        node = f.nodes[0]
        magic = regtest_params().netmagic
        addr = node.rpc.getnewaddress()
        node.rpc.generatetoaddress(2, ADDR)  # blocks 1+2 pay our test KEY
        node.rpc.generatetoaddress(100, addr)

        s = socket.create_connection(("127.0.0.1", node.p2p_port), timeout=10)
        s.sendall(pack_message(magic, "version", VersionPayload().serialize()))
        _read_msg(s)
        _read_msg(s)
        s.sendall(pack_message(magic, "verack"))
        # we should be told the node's relay floor
        got_feefilter = None
        deadline = time.time() + 10
        while time.time() < deadline and got_feefilter is None:
            header, payload = _read_msg(s)
            if header[4:16].rstrip(b"\x00") == b"feefilter":
                (got_feefilter,) = _struct.unpack("<Q", payload)
        assert got_feefilter == 1000  # default minrelaytxfee sat/kB

        # -- set an absurd filter: the node must NOT inv us the next tx --
        s.sendall(pack_message(magic, "feefilter",
                               _struct.pack("<Q", 10**9)))
        time.sleep(0.5)
        txid = node.rpc.sendtoaddress(ADDR, 1.0)
        s.settimeout(3)
        saw_inv = False
        try:
            while True:
                header, payload = _read_msg(s)
                if header[4:16].rstrip(b"\x00") == b"inv":
                    items = deser_inv(payload)
                    if any(t == MSG_TX for t, _h in items):
                        saw_inv = True
        except (socket.timeout, OSError):
            pass
        assert not saw_inv, "low-fee tx inv leaked through the feefilter"
        s.settimeout(30)

        # -- drop the filter; mine the tx; mapRelay serves getdata -------
        s.sendall(pack_message(magic, "feefilter", _struct.pack("<Q", 0)))
        node.rpc.generatetoaddress(1, addr)  # tx leaves the mempool
        assert node.rpc.getrawmempool() == []
        s.sendall(pack_message(magic, "getdata",
                               ser_inv([(MSG_TX, hex_to_hash(txid))])))
        got_tx = None
        deadline = time.time() + 15
        while time.time() < deadline and got_tx is None:
            header, payload = _read_msg(s)
            if header[4:16].rstrip(b"\x00") == b"tx":
                got_tx = CTransaction.from_bytes(payload)
        assert got_tx is not None and got_tx.txid == hex_to_hash(txid)

        # -- invalid tx gets a BIP61 reject ------------------------------
        # a bit-flipped signature on an otherwise valid spend of our own
        # mature coinbase → mandatory-script-verify-flag-failed (code 0x10)
        blk2 = node.rpc.getblock(node.rpc.getblockhash(2), 2)
        good = CTransaction.from_bytes(bytes.fromhex(
            _spend_tx(blk2["tx"][0], 1_0000_0000)))
        sig = bytearray(good.vin[0].script_sig)
        sig[10] ^= 0x01
        bad = CTransaction(
            good.version,
            (CTxIn(good.vin[0].prevout, bytes(sig), good.vin[0].sequence),),
            good.vout, good.locktime,
        )
        s.sendall(pack_message(magic, "tx", bad.serialize()))
        got_reject = None
        deadline = time.time() + 15
        while time.time() < deadline and got_reject is None:
            header, payload = _read_msg(s)
            if header[4:16].rstrip(b"\x00") == b"reject":
                got_reject = payload
        assert got_reject is not None
        r = ByteReader(got_reject)
        from bitcoincashplus_tpu.consensus.serialize import deser_compact_size
        n = deser_compact_size(r)
        assert r.read_bytes(n) == b"tx"
        code = r.read_bytes(1)[0]
        assert code in (0x10, 0x42)
        s.close()


def test_addrman_gossip_and_autodial():
    """addr gossip: a fake peer advertises node B's address to node A;
    A's ThreadOpenConnections-analogue auto-dials B. getaddr returns the
    learned address; peers.json persists it across restart."""
    import json
    import os

    from bitcoincashplus_tpu.p2p.protocol import (
        deser_addr_entries,
        ser_addr_entries,
    )

    with FunctionalFramework(num_nodes=2) as f:
        a, b = f.nodes
        magic = regtest_params().netmagic
        assert a.rpc.getconnectioncount() == 0
        assert b.rpc.getconnectioncount() == 0

        # fake peer tells A about B
        s = socket.create_connection(("127.0.0.1", a.p2p_port), timeout=10)
        s.sendall(pack_message(magic, "version", VersionPayload().serialize()))
        _read_msg(s)
        _read_msg(s)
        s.sendall(pack_message(magic, "verack"))
        now = int(time.time())
        s.sendall(pack_message(magic, "addr", ser_addr_entries(
            [(now, 1, "127.0.0.1", b.p2p_port)]
        )))

        # A auto-dials B within the open-connections interval
        wait_until(lambda: b.rpc.getconnectioncount() >= 1, timeout=30)
        wait_until(lambda: a.rpc.getconnectioncount() >= 2, timeout=30)

        # getaddr harvest: ask A for its addresses — B's must be there
        s.sendall(pack_message(magic, "getaddr"))
        got = None
        deadline = time.time() + 15
        while time.time() < deadline and got is None:
            header, payload = _read_msg(s)
            if header[4:16].rstrip(b"\x00") == b"addr":
                got = deser_addr_entries(payload)
        assert got is not None
        assert any(h == "127.0.0.1" and p == b.p2p_port
                   for _t, _s, h, p in got)
        s.close()

        # peers.json persists the learned address across restart
        a.stop()
        peers_path = os.path.join(a.datadir, "peers.json")
        assert os.path.exists(peers_path)
        with open(peers_path) as fh:
            saved = json.load(fh)
        assert any(d["host"] == "127.0.0.1" and d["port"] == b.p2p_port
                   for d in saved["addrs"])
        a.start()
        # the reloaded addrman re-dials B without any hint
        wait_until(lambda: b.rpc.getconnectioncount() >= 1, timeout=30)


def test_maxconnections_and_ancestor_limit_flags():
    """-maxconnections caps inbound accepts; -limitancestorcount bounds
    mempool chains (mempool_limit.py essentials)."""
    with FunctionalFramework(
        num_nodes=1,
        extra_args=[["-maxconnections=2", "-limitancestorcount=3"]],
    ) as f:
        node = f.nodes[0]
        magic = regtest_params().netmagic

        # two peers connect; the third is refused at the cap
        socks = []
        for _ in range(2):
            s = socket.create_connection(("127.0.0.1", node.p2p_port),
                                         timeout=10)
            s.sendall(pack_message(magic, "version",
                                   VersionPayload().serialize()))
            _read_msg(s)
            _read_msg(s)
            s.sendall(pack_message(magic, "verack"))
            socks.append(s)
        wait_until(lambda: node.rpc.getconnectioncount() == 2, timeout=15)
        s3 = socket.create_connection(("127.0.0.1", node.p2p_port), timeout=10)
        s3.sendall(pack_message(magic, "version", VersionPayload().serialize()))
        assert _expect_disconnect(s3, timeout=10)
        assert node.rpc.getconnectioncount() == 2
        for s in socks:
            s.close()

        # ancestor chain: 3 allowed, the 4th rejected by the lowered limit
        addr = node.rpc.getnewaddress()
        node.rpc.generatetoaddress(101, addr)
        from bitcoincashplus_tpu.rpc.client import JSONRPCException
        for i in range(3):
            txid = node.rpc.sendtoaddress(addr, 40.0)  # chains off change
        with pytest.raises(JSONRPCException) as e:
            node.rpc.sendtoaddress(addr, 40.0)
        assert "too-long" in str(e.value) or "chain" in str(e.value)
