"""Adversarial P2P campaigns against a live node: chaos peers (flooder /
staller / garbage-replayer) driven by deterministic seeds, the ban-score
ledger and stall-eviction machinery they exercise, and banlist
persistence across restarts.

Reference behaviors: src/net_processing.cpp Misbehaving + block-download
stall handling, src/banman.cpp banlist persistence; the chaos harness is
this framework's own (tests/functional/framework.ChaosPeer +
util/faults.ChaosSchedule).

Campaign length is env-tunable: BCP_CHAOS_ROUNDS (default 4) bounds each
chaos behavior's scripted rounds; the long soak variant is marked `slow`.
"""

from __future__ import annotations

import os
import time

import pytest

from bitcoincashplus_tpu.wallet.keys import CKey
from bitcoincashplus_tpu.consensus.params import regtest_params

from .framework import (
    ChaosPeer,
    FunctionalFramework,
    connect_nodes,
    default_chaos_rounds,
    raw_headers_for,
    sync_blocks,
    wait_until,
)

pytestmark = [pytest.mark.functional, pytest.mark.adversarial]

KEY = CKey(0xFADE)
ADDR = KEY.p2pkh_address(regtest_params())

# a victim tuned for fast supervision so campaigns finish in seconds:
# 1 s tick, 3 s download timeout, ~300 kB/s receive ceiling, pinned seed
VICTIM_ARGS = [
    "-nettick=1",
    "-blockdownloadtimeout=3",
    "-maxrecvrate=300000",
    "-netseed=7",
]


def _chainstate_dict(datadir: str) -> dict[bytes, bytes]:
    """Coin rows + best-block marker merged across the (possibly
    sharded) chainstate layout — per-shard epoch/accumulator meta is
    node-local (flush cadence), so only C/B rows are compared."""
    import glob

    from bitcoincashplus_tpu.store.kvstore import KVStore

    paths = sorted(glob.glob(
        os.path.join(datadir, "chainstate.shard*.sqlite"))) or \
        [os.path.join(datadir, "chainstate.sqlite")]
    out: dict[bytes, bytes] = {}
    for p in paths:
        kv = KVStore(p)
        for k, v in kv.iterate():
            if k[:1] == b"C" or k == b"B":
                out[k] = v
        kv.close()
    return out


def _stop_peers(*peers: ChaosPeer) -> None:
    for p in peers:
        p.stop()
    for p in peers:
        p.join(10)
        if p.error is not None:
            raise p.error


def test_stall_eviction_and_rerequest():
    """A peer that announces real headers and then withholds every block
    is charged (visible in getpeerinfo while still connected), its
    in-flight blocks are re-requested from the honest peer, sync
    completes, and the staller is evicted without operator action."""
    with FunctionalFramework(num_nodes=2,
                             extra_args=[[], VICTIM_ARGS]) as f:
        honest, victim = f.nodes
        honest.rpc.generatetoaddress(8, ADDR)
        headers = raw_headers_for(honest, 8)

        staller = ChaosPeer(victim.p2p_port, "stall", seed=11,
                            headers=headers)
        staller.start()
        # the victim asks the staller for all 8 announced blocks
        wait_until(lambda: any(p["inflight"] > 0
                               for p in victim.rpc.getpeerinfo()),
                   timeout=15)

        # honest peer joins; the blocks are already reserved against the
        # staller, so only the stall detector can move them over
        connect_nodes(victim, honest)

        # the ledger charge is observable before the eviction: the staller
        # shows stalling=true with half the threshold on its banscore
        def _charged():
            return any(
                p["stalling"] and p["banscore"] >= 50
                and p["charges"].get("stalled-block")
                for p in victim.rpc.getpeerinfo()
            )
        wait_until(_charged, timeout=20, sleep=0.1)

        # re-request from the honest peer completes the sync
        wait_until(lambda: victim.rpc.getblockcount() == 8, timeout=30)
        assert victim.rpc.getbestblockhash() == honest.rpc.getbestblockhash()

        # and the staller is gone, charged off the ledger
        wait_until(lambda: staller.evicted, timeout=30)
        net = victim.rpc.gettpuinfo()["net"]
        # how the withheld blocks moved off the staller is timing-
        # dependent (stall re-request to an announcer, parked handoff, or
        # a fresh headers-path request after the honest peer's own
        # announcement) — the deterministic observables are that the
        # stall machinery fired and sync completed anyway (asserted
        # above), so only assert the eviction counters here
        assert net["evicted_stallers"] >= 1
        assert net["discharge_reasons"].get("stalled-block", 0) >= 1
        _stop_peers(staller)


def test_chaos_sync_chainstate_identical():
    """Acceptance chaos e2e: a victim fed by one honest node plus three
    chaos peers (flooder, staller, garbage-replayer) syncs to the honest
    tip with a chainstate byte-identical to a control node synced from
    the honest peer alone, evicting the flooder and staller on its own."""
    with FunctionalFramework(
        num_nodes=3, extra_args=[[], [], VICTIM_ARGS]
    ) as f:
        honest, control, victim = f.nodes
        honest.rpc.generatetoaddress(12, ADDR)
        headers = raw_headers_for(honest, 12)

        # the staller announces first so the victim reserves the blocks
        # against it (the honest peer then only gets them via the stall
        # detector's re-request)
        staller = ChaosPeer(victim.p2p_port, "stall", seed=22,
                            headers=headers)
        staller.start()
        wait_until(lambda: any(p["inflight"] > 0
                               for p in victim.rpc.getpeerinfo()),
                   timeout=15)

        flooder = ChaosPeer(victim.p2p_port, "flood", seed=21)
        garbage = ChaosPeer(victim.p2p_port, "garbage", seed=23,
                            rounds=default_chaos_rounds())
        flooder.start()
        garbage.start()
        connect_nodes(victim, honest)
        connect_nodes(control, honest)

        # both reach the honest tip despite the hostile peers
        sync_blocks([honest, victim, control], timeout=90)
        assert victim.rpc.getblockcount() == 12

        # the flooder trips the receive ceiling, the staller the download
        # timeout — both evicted without any operator RPC
        wait_until(lambda: flooder.evicted, timeout=30)
        wait_until(lambda: staller.evicted, timeout=30)
        net = victim.rpc.gettpuinfo()["net"]
        assert net["discharge_reasons"].get("recv-flood", 0) >= 1
        assert net["discharge_reasons"].get("stalled-block", 0) >= 1
        assert net["discharged_peers"] >= 2
        _stop_peers(flooder, staller, garbage)

        # chainstates must match byte-for-byte after an orderly flush
        victim_dir, control_dir = victim.datadir, control.datadir
        victim.stop()
        control.stop()
        assert _chainstate_dict(victim_dir) == _chainstate_dict(control_dir)


def test_garbage_headers_accumulate_graduated_charges():
    """Non-connecting (but valid-PoW) headers draw the graduated charge,
    not an instant disconnect: the replayer stays connected with a rising
    banscore until the threshold discharges it."""
    # 3 charged batches = eviction; every non-connecting batch charges
    # (the production default of every-10th, with the counter resetting on
    # connecting batches and the ledger on the replayer's scripted
    # reconnects, would make graduated accumulation take minutes here)
    victim_args = VICTIM_ARGS + ["-banscore=30", "-maxunconnectingheaders=1"]
    with FunctionalFramework(num_nodes=1, extra_args=[victim_args]) as f:
        victim = f.nodes[0]
        garbage = ChaosPeer(victim.p2p_port, "garbage", seed=31, rounds=999)
        garbage.start()

        def _charged():
            return any(
                p["charges"].get("non-connecting-headers", 0) >= 10
                for p in victim.rpc.getpeerinfo()
            )
        wait_until(_charged, timeout=30, sleep=0.1)
        # the replayer keeps going; the ledger eventually discharges it
        wait_until(
            lambda: victim.rpc.gettpuinfo()["net"]["discharge_reasons"]
            .get("non-connecting-headers", 0) >= 1,
            timeout=60,
        )
        garbage.stop()
        garbage.join(10)
        # node is healthy and still serving
        victim.rpc.generatetoaddress(1, ADDR)
        assert victim.rpc.getblockcount() == 1


def test_banlist_survives_restart():
    """setban writes through to banlist.json; the ban outlives a restart
    (banman.cpp DumpBanlist/LoadBanlist) and clearbanned erases it
    durably."""
    with FunctionalFramework(num_nodes=1) as f:
        node = f.nodes[0]
        node.rpc.setban("203.0.113.77", "add", 86400)
        assert any(e["address"] == "203.0.113.77"
                   for e in node.rpc.listbanned())
        banlist = os.path.join(node.datadir, "banlist.json")
        assert os.path.exists(banlist)

        node.stop()
        node.start()
        entries = node.rpc.listbanned()
        assert any(e["address"] == "203.0.113.77" for e in entries)
        assert all(e["banned_until"] > time.time() for e in entries)

        node.rpc.clearbanned()
        node.stop()
        node.start()
        assert node.rpc.listbanned() == []


@pytest.mark.slow
def test_chaos_long_campaign():
    """Long soak: several chaos generations against one victim. Scripted
    by seed, length scaled by BCP_CHAOS_ROUNDS; the victim must keep
    serving RPC and accepting honest blocks throughout."""
    rounds = default_chaos_rounds() * 10
    with FunctionalFramework(num_nodes=2,
                             extra_args=[[], VICTIM_ARGS]) as f:
        honest, victim = f.nodes
        honest.rpc.generatetoaddress(5, ADDR)
        connect_nodes(victim, honest)
        sync_blocks([honest, victim], timeout=60)

        for generation in range(3):
            flooder = ChaosPeer(victim.p2p_port, "flood",
                                seed=100 + generation)
            garbage = ChaosPeer(victim.p2p_port, "garbage",
                                seed=200 + generation, rounds=rounds)
            flooder.start()
            garbage.start()
            wait_until(lambda: flooder.evicted, timeout=60)
            honest.rpc.generatetoaddress(1, ADDR)
            sync_blocks([honest, victim], timeout=60)
            _stop_peers(flooder, garbage)

        net = victim.rpc.gettpuinfo()["net"]
        assert net["discharge_reasons"].get("recv-flood", 0) >= 3
        assert victim.rpc.getblockcount() == 8
