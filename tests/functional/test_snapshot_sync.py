"""assumeutxo snapshot onboarding, end to end over real bcpd processes.

Node A mines a chain and dumps a UTXO snapshot; node B — restarted with
the matching ``-assumeutxo=<hash>:<digest>`` authorization — loads it and
must serve RPC at the snapshot tip BEFORE any peer connection exists,
then converge: once connected to A, the background shadow chainstate
backfills and re-validates all of history and promotes the node to fully
validated with a byte-identical set digest. (qa analogue:
feature_assumeutxo.py in the reference's functional suite.)
"""

from __future__ import annotations

import os

import pytest

from bitcoincashplus_tpu.consensus.params import regtest_params
from bitcoincashplus_tpu.wallet.keys import CKey

from .framework import FunctionalFramework, connect_nodes, wait_until

pytestmark = [pytest.mark.functional, pytest.mark.snapshot]

KEY = CKey(0x5A57)
ADDR = KEY.p2pkh_address(regtest_params())

CHAIN_H = 30


def test_snapshot_onboarding_and_background_validation():
    with FunctionalFramework(num_nodes=2) as f:
        a, b = f.nodes
        a.rpc.generatetoaddress(CHAIN_H, ADDR)
        tip_info = a.rpc.gettxoutsetinfo()
        assert tip_info["height"] == CHAIN_H
        snap_path = os.path.join(a.datadir, "utxo-snapshot")
        dump = a.rpc.dumptxoutset(snap_path)
        assert dump["height"] == CHAIN_H
        assert dump["muhash"] == tip_info["muhash"]

        # an unauthorized node must refuse the snapshot outright
        with pytest.raises(Exception, match="assumeutxo"):
            b.rpc.loadtxoutset(snap_path)

        # restart B with the matching authorization and load
        b.stop()
        b.extra_args.append(
            f"-assumeutxo={dump['bestblock']}:{dump['muhash']}")
        b.start()
        res = b.rpc.loadtxoutset(snap_path)
        assert res["height"] == CHAIN_H
        assert res["coins"] == dump["coins"]

        # the assumeutxo promise: B serves at the snapshot tip with NO
        # peer connection and NO local history
        assert b.rpc.getblockcount() == CHAIN_H
        assert b.rpc.getbestblockhash() == dump["bestblock"]
        cb1 = a.rpc.getblock(a.rpc.getblockhash(1))["tx"][0]
        out = b.rpc.gettxout(cb1, 0)
        assert out is not None and out["coinbase"]
        assert b.rpc.gettxoutsetinfo()["muhash"] == dump["muhash"]
        store = b.rpc.gettpuinfo()["store"]
        assert store["snapshot"]["validated"] is False

        # connect: the background shadow chainstate names the missing
        # heights to the P2P layer (request_backfill), replays history,
        # and promotes on digest equality
        connect_nodes(b, a)
        wait_until(
            lambda: b.rpc.gettpuinfo()["store"]["snapshot"]["validated"],
            timeout=180, sleep=1.0)

        # fully validated: the shadow is retired and B extends normally
        assert not os.path.exists(
            os.path.join(b.datadir, "chainstate_shadow"))
        a.rpc.generatetoaddress(2, ADDR)
        wait_until(lambda: b.rpc.getblockcount() == CHAIN_H + 2,
                   timeout=60)
        assert b.rpc.getbestblockhash() == a.rpc.getbestblockhash()
        ia, ib = a.rpc.gettxoutsetinfo(), b.rpc.gettxoutsetinfo()
        assert ia["muhash"] == ib["muhash"]
        assert ia["bestblock"] == ib["bestblock"]

        # and the onboarding survives a restart as a VALIDATED node
        # (normal startup path: -checkblocks replay above the snapshot)
        b.stop()
        b.start()
        assert b.rpc.getblockcount() == CHAIN_H + 2
        assert b.rpc.gettpuinfo()["store"]["snapshot"]["validated"] is True
