"""Multi-node fork-storm chaos fleet (ISSUE 9 acceptance scenario).

A seeded campaign over a 4-node ring (+ an unmolested control hanging
off node0): two partition/heal cycles drive fork wars — both sides of
each split mine competing branches, the heal forces deep reorgs — the
chain crosses the EDA->DAA difficulty boundary (-cashdaa -daaheight)
mid-campaign, and a staged fork race (two pre-mined competing tips fed
through ``forkfeeder`` ChaosPeers inside the -spechold window) proves
the speculation tree holds >1 live branch. Every node must converge to
a chainstate byte-identical to the control, with ZERO serial-engine
fallbacks on linear segments.

The whole storm replays from its seeds: the partition topology draws
come from util/faults.ChaosSchedule.bipartition and the feeders pace
off their own schedules.

Markers: ``functional`` + ``forkstorm`` — conftest orders forkstorm
campaigns dead last (the newest, heaviest adversarial coverage is the
first thing a CI timeout cuts, never the established suites).
"""

import os

import pytest

from bitcoincashplus_tpu.consensus.params import regtest_params
from bitcoincashplus_tpu.util.faults import ChaosSchedule
from bitcoincashplus_tpu.wallet.keys import CKey

from .framework import (
    ChaosPeer,
    FunctionalFramework,
    connect_nodes,
    disconnect_nodes,
    heal_fleet,
    partition_fleet,
    sync_blocks,
    wait_until,
)

pytestmark = [pytest.mark.functional, pytest.mark.forkstorm]

KEY = CKey(0x51095109)
ADDR = KEY.p2pkh_address(regtest_params())

# One rule set fleet-wide (a -cashdaa mismatch would be a consensus
# fork, not a reorg drill): EDA era to height 23, cw-144 DAA from 24 —
# the cycle-2 reorg crosses the boundary. -spechold=1500 opens the
# fork-race grace window the staged branch race below lands inside;
# -nettick=1 bounds how long a held tip can lag its settle.
FLEET_ARGS = [
    "-pipelinedepth=4", "-specbranches=4", "-spechold=1500",
    "-cashdaa", "-daaheight=24", "-nettick=1", "-netseed=1109",
]
RING = [(0, 1), (1, 2), (2, 3), (3, 0)]


def _chainstate_dict(datadir: str) -> dict[bytes, bytes]:
    """Coin rows + best-block marker merged across the (possibly
    sharded) chainstate layout. Per-shard epoch/accumulator meta is
    excluded — flush cadence legitimately differs between nodes; only
    the coin set and tip marker are consensus."""
    import glob

    from bitcoincashplus_tpu.store.kvstore import KVStore

    paths = sorted(glob.glob(
        os.path.join(datadir, "chainstate.shard*.sqlite"))) or \
        [os.path.join(datadir, "chainstate.sqlite")]
    out: dict[bytes, bytes] = {}
    for p in paths:
        kv = KVStore(p)
        for k, v in kv.iterate():
            if k[:1] == b"C" or k == b"B":
                out[k] = v
        kv.close()
    return out


def _cut_everyone(nodes, island) -> None:
    """Isolate ``island`` from every other node (including re-cuts — the
    dial loop may have redialed from addrman between applications)."""
    for other in nodes:
        if other is not island:
            disconnect_nodes(island, other)


def _mine(node, n: int) -> list[str]:
    return node.rpc.generatetoaddress(n, ADDR)


def test_fork_storm_fleet_convergence():
    sched = ChaosSchedule(1109)
    with FunctionalFramework(
        num_nodes=5, extra_args=[list(FLEET_ARGS) for _ in range(5)]
    ) as f:
        fleet = f.nodes[:4]
        control = f.nodes[4]
        heal_fleet(f.nodes, RING)
        connect_nodes(control, f.nodes[0])

        # base chain, deep inside the EDA era
        _mine(fleet[0], 18)
        sync_blocks(f.nodes, timeout=90)

        # -- two seeded partition/heal cycles: fork wars, deep reorgs --
        # cycle 1 stays below the DAA boundary (18 -> 23); cycle 2's
        # winning branch crosses it (23 -> 29 over daaheight=24), so the
        # losing side's reorg re-validates headers across the rule switch
        for cycle in range(2):
            side_a, side_b = sched.bipartition(4)
            k = sched.randint(2, 3)
            partition_fleet(fleet, (side_a, side_b))
            # the control must follow ONE side only (node0's); cut it
            # from any direct cross-side leakage it never has (ring) —
            # nothing to do: it only links node0.
            miner_a, miner_b = fleet[side_a[0]], fleet[side_b[0]]
            for step in range(k):
                _mine(miner_a, 1)
                partition_fleet(fleet, (side_a, side_b))  # re-cut redials
            for step in range(k + 2):
                _mine(miner_b, 1)
                partition_fleet(fleet, (side_a, side_b))
            heal_fleet(fleet, RING)
            sync_blocks(f.nodes, timeout=120)

        tip_before_race = fleet[1].rpc.getbestblockhash()

        # -- staged fork race: two competing children of the settled tip
        # fed to node1 within the -spechold window — the speculation
        # tree must hold BOTH branches live concurrently
        _cut_everyone(f.nodes, fleet[2])
        _cut_everyone(f.nodes, fleet[3])
        (x_hash,) = _mine(fleet[2], 1)
        (y_hash,) = _mine(fleet[3], 1)
        x_raw = bytes.fromhex(fleet[2].rpc.getblock(x_hash, 0))
        y_raw = bytes.fromhex(fleet[3].rpc.getblock(y_hash, 0))
        feeder_x = ChaosPeer(fleet[1].p2p_port, "forkfeeder", seed=71,
                             blocks=[x_raw], block_rate=500)
        feeder_y = ChaosPeer(fleet[1].p2p_port, "forkfeeder", seed=72,
                             blocks=[y_raw], block_rate=500)
        feeder_x.start()
        feeder_y.start()

        def _branched():
            tree = fleet[1].rpc.gettpuinfo()["pipeline"]["tree"]
            return tree["branches_live_max"] >= 2
        wait_until(_branched, timeout=20, sleep=0.05)
        for p in (feeder_x, feeder_y):
            p.stop()
            p.join(10)
            if p.error is not None:
                raise p.error
        # the race is a work TIE: nothing externalizes until the tie
        # breaks — node1's own next template settles the first-seen
        # winner (assembler settle barrier), drops the loser, and the
        # two fresh blocks give the whole fleet a strictly-most-work
        # chain to converge on (including the forksmiths when healed)
        assert fleet[1].rpc.getbestblockhash() == tip_before_race
        _mine(fleet[1], 2)
        heal_fleet(fleet, RING)
        sync_blocks(f.nodes, timeout=120)
        tree1 = fleet[1].rpc.gettpuinfo()["pipeline"]["tree"]
        assert tree1["branches_live_max"] >= 2
        assert tree1["branch_drops"] >= 1

        # -- fleet-wide acceptance assertions --
        reorgs_total = 0
        depth_max = 0
        for node in fleet:
            tree = node.rpc.gettpuinfo()["pipeline"]["tree"]
            # the fast path never regressed to serial on a linear segment
            assert tree["serial_linear_fallbacks"] == 0, node.index
            assert tree["collapse_level"] == 0, node.index
            reorgs_total += tree["reorgs"]
            depth_max = max(depth_max, tree["reorg_depth_max"])
        # each cycle's losing miner disconnected >= 2 of its own blocks
        assert reorgs_total >= 2
        assert depth_max >= 2
        # the campaign crossed the DAA boundary
        assert fleet[0].rpc.getblockcount() >= 27

        # -- digest-identical convergence, every node vs the control --
        tips = {n.rpc.getbestblockhash() for n in f.nodes}
        assert len(tips) == 1
        dirs = [n.datadir for n in f.nodes]
        for n in f.nodes:
            n.stop()
        want = _chainstate_dict(dirs[-1])  # the unmolested control
        for d in dirs[:-1]:
            assert _chainstate_dict(d) == want, d


@pytest.mark.slow
def test_fork_storm_soak():
    """Longer seeded storm (slow-marked): more cycles, bigger deltas,
    a forkfeeder replaying a stale losing branch mid-campaign. Same
    oracle — byte-identical convergence everywhere."""
    sched = ChaosSchedule(2207)
    with FunctionalFramework(
        num_nodes=4, extra_args=[list(FLEET_ARGS) for _ in range(4)]
    ) as f:
        fleet = f.nodes[:3]
        control = f.nodes[3]
        topo = [(0, 1), (1, 2)]
        heal_fleet(f.nodes, topo)
        connect_nodes(control, f.nodes[0])
        _mine(fleet[0], 20)
        sync_blocks(f.nodes, timeout=90)
        loser_branch: list[bytes] = []
        for cycle in range(4):
            side_a, side_b = sched.bipartition(3)
            k = sched.randint(2, 4)
            partition_fleet(fleet, (side_a, side_b))
            miner_a, miner_b = fleet[side_a[0]], fleet[side_b[0]]
            a_hashes = []
            for _ in range(k):
                a_hashes += _mine(miner_a, 1)
                partition_fleet(fleet, (side_a, side_b))
            for _ in range(k + 1):
                _mine(miner_b, 1)
                partition_fleet(fleet, (side_a, side_b))
            if cycle == 0:
                loser_branch = [
                    bytes.fromhex(miner_a.rpc.getblock(h, 0))
                    for h in a_hashes
                ]
            heal_fleet(fleet, topo)
            sync_blocks(f.nodes, timeout=120)
        # replay the cycle-0 losing branch at node1: a well-below-tip
        # fork must neither reorg the node nor wedge the tree
        feeder = ChaosPeer(fleet[1].p2p_port, "forkfeeder", seed=91,
                           blocks=loser_branch, block_rate=200)
        feeder.start()
        feeder.join(30)
        feeder.stop()
        tip = fleet[1].rpc.getbestblockhash()
        _mine(fleet[1], 1)
        sync_blocks(f.nodes, timeout=90)
        assert fleet[1].rpc.getbestblockhash() != tip  # still extending
        for node in fleet:
            tree = node.rpc.gettpuinfo()["pipeline"]["tree"]
            assert tree["serial_linear_fallbacks"] == 0
        dirs = [n.datadir for n in f.nodes]
        for n in f.nodes:
            n.stop()
        want = _chainstate_dict(dirs[-1])
        for d in dirs[:-1]:
            assert _chainstate_dict(d) == want, d
