"""Sustained tx-flood serving harness (ISSUE 7 satellite).

Two real bcpd nodes on the same 110-block regtest chain: node0 runs the
always-on SigService (the default), node1 is the `-sigservice=off`
synchronous control. A seeded ChaosPeer ``txstorm`` drives the IDENTICAL
transaction storm (same seed => same shuffled order, same pacing jitter)
at both nodes, including out-of-order child-before-parent deliveries
that bounce through the orphan pool.

Asserts:
  * zero verdict divergence — both mempools converge to the same txid
    set, and a block mined over the serviced mempool connects on the
    control node (identical chainstate by block-hash identity);
  * the PR 6 accept-latency histogram measured the storm (p99 under a
    CI-safe budget, accepted count == storm size) on the serviced node;
  * the serving surface reports the work (dispatches, flush reasons).
"""

import pytest

from .framework import ChaosPeer, FunctionalFramework, wait_until

pytestmark = [pytest.mark.functional, pytest.mark.serving]

N_COINS = 4          # mature coinbases spent by the storm
N_BLOCKS = 104       # N_COINS + coinbase maturity (100) headroom
TX_RATE = 150.0      # offered load, tx/s nominal
STORM_SEED = 1107
P99_BUDGET_MS = 1500.0  # CI-safe: CPU-lower-bound accepts are ~ms-scale


def _build_storm_txs(node):
    """parent+child spend chains over the first N_COINS coinbases (all
    keys known to the test): 2*N_COINS raw transactions."""
    from bitcoincashplus_tpu.consensus.block import CBlock
    from bitcoincashplus_tpu.consensus.tx import (
        COutPoint,
        CTransaction,
        CTxIn,
        CTxOut,
    )
    from bitcoincashplus_tpu.wallet.keys import CKey
    from bitcoincashplus_tpu.wallet.signing import sign_transaction

    key = CKey(0x53657276)
    spk = key.p2pkh_script()

    def spend(op, value, fee=10_000, n_out=1):
        per_out = (value - fee) // n_out
        tx = CTransaction(
            vin=(CTxIn(op, b""),),
            vout=tuple(CTxOut(per_out, spk) for _ in range(n_out)),
        )
        return sign_transaction(
            tx, [(spk, value)],
            lambda h: key if h == key.pubkey_hash else None,
            enable_forkid=True,
        )

    txs = []
    expected = set()
    for height in range(1, N_COINS + 1):
        raw = bytes.fromhex(
            node.rpc.getblock(node.rpc.getblockhash(height), 0))
        cb = CBlock.from_bytes(raw).vtx[0]
        parent = spend(COutPoint(cb.txid, 0), cb.vout[0].value, n_out=2)
        child = spend(COutPoint(parent.txid, 0), parent.vout[0].value)
        for tx in (parent, child):
            txs.append(tx.serialize())
            expected.add(tx.txid_hex)
    return key, txs, expected


def test_tx_flood_serviced_vs_sync_control():
    from bitcoincashplus_tpu.wallet.keys import CKey, script_to_address
    from bitcoincashplus_tpu.consensus.params import regtest_params

    key = CKey(0x53657276)
    addr = script_to_address(key.p2pkh_script(), regtest_params())
    with FunctionalFramework(
        num_nodes=2,
        extra_args=[[], ["-sigservice=off"]],
    ) as fw:
        serviced, control = fw.nodes
        # one shared chain, synced by block submission (no P2P link: each
        # node's verdicts must come from its own accept path)
        serviced.rpc.generatetoaddress(N_BLOCKS, addr)
        for height in range(1, N_BLOCKS + 1):
            raw = serviced.rpc.getblock(
                serviced.rpc.getblockhash(height), 0)
            assert control.rpc.submitblock(raw) is None
        assert (serviced.rpc.getbestblockhash()
                == control.rpc.getbestblockhash())

        _key, txs, expected = _build_storm_txs(serviced)

        # the serviced node really is serving, the control really is not
        assert serviced.rpc.gettpuinfo()["serving"]["enabled"] is True
        assert control.rpc.gettpuinfo()["serving"] == {"enabled": False}

        storms = [
            ChaosPeer(n.p2p_port, "txstorm", seed=STORM_SEED, txs=txs,
                      tx_rate=TX_RATE)
            for n in (serviced, control)
        ]
        for s in storms:
            s.start()
        try:
            wait_until(
                lambda: all(
                    set(n.rpc.getrawmempool()) >= expected
                    for n in (serviced, control)),
                timeout=90, sleep=0.5)
        finally:
            for s in storms:
                s.stop()
                s.join(10)
        for s in storms:
            assert s.error is None, f"storm peer error: {s.error!r}"
            assert s.rounds_done == len(txs)

        # zero verdict divergence: identical mempools
        assert (set(serviced.rpc.getrawmempool())
                == set(control.rpc.getrawmempool()))

        # the PR 6 histogram measured the storm on the serviced node
        info = serviced.rpc.gettpuinfo()
        lat = info["telemetry"]["accept_latency"]
        assert lat["accepted"] >= len(txs)
        assert 0.0 < lat["p99_ms"] < P99_BUDGET_MS, lat
        # the serving engine did the verifying (flush policy fired)
        serving = info["serving"]
        assert serving["dispatches"] >= 1
        assert serving["lanes_enqueued"] >= len(txs)
        flushes = sum(serving[f"flush_{r}"]
                      for r in ("full", "deadline", "kick", "stop"))
        assert flushes == serving["dispatches"]
        # control node verified the same load synchronously
        clat = control.rpc.gettpuinfo()["telemetry"]["accept_latency"]
        assert clat["accepted"] >= len(txs)

        # a block mined over the serviced mempool connects on the control
        # node: the serviced verdicts externalize to an identical chain
        (block_hash,) = serviced.rpc.generatetoaddress(1, addr)
        raw = serviced.rpc.getblock(block_hash, 0)
        assert control.rpc.submitblock(raw) is None
        assert (serviced.rpc.getbestblockhash()
                == control.rpc.getbestblockhash())
        assert serviced.rpc.getrawmempool() == []
