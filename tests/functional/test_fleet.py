"""Fleet serving front door, end to end over real bcpd processes
(ISSUE 16).

node0 is the validator and runs the ``-gateway`` front door; nodes 1-2
are read replicas in its ``-replicas`` pool, bootstrapped from a
validator UTXO snapshot (the assumeutxo spin-up path) and fed tips over
the normal P2P relay. The single campaign below walks the whole serving
story in one topology (process spawns dominate the cost, so the phases
share a fleet):

  1. snapshot bootstrap — a fresh replica loads the validator's dump and
     JOINS THE ROTATION within the health-probe window, no gateway
     restart;
  2. coalescing — 8 identical concurrent queries collapse onto one
     backend call (counter-asserted);
  3. hard-kill failover — kill -9 a replica, every in-flight-era read
     still answers correctly via mid-request failover, the corpse is
     rotated out, and the restarted replica re-enters rotation;
  4. consistency gate — a replica cut off from tip relay falls behind
     ``-maxreplicalag`` and is rotated out; reads keep flowing at the
     fresh tip; the healed replica is re-admitted.
"""

from __future__ import annotations

import os
import threading

import pytest

from bitcoincashplus_tpu.consensus.params import regtest_params
from bitcoincashplus_tpu.wallet.keys import CKey

from .framework import (
    FunctionalFramework,
    bootstrap_replica_from_snapshot,
    connect_nodes,
    disconnect_nodes,
    gateway_client,
    setup_fleet,
    wait_until,
)

pytestmark = [pytest.mark.functional, pytest.mark.fleet]

KEY = CKey(0x16F1EE7)
ADDR = KEY.p2pkh_address(regtest_params())

CHAIN_H = 16


def _gw(validator) -> dict:
    info = validator.rpc.gettpuinfo()["gateway"]
    assert info["enabled"]
    return info


def _rotation(validator) -> set[str]:
    return {r["name"] for r in _gw(validator)["pool"]["replicas"]
            if r["in_rotation"]}


def test_fleet_gateway_end_to_end(monkeypatch):
    # Arm a latency spike on the replica leg (explicit-only site: only
    # the gateway's proxied reads slow down, nothing consensus-side).
    # Every replica leg now costs ~80 ms inside the gateway, so the
    # 8-way identical-query barrage below reliably overlaps in flight —
    # the coalescing assertion is deterministic instead of a scheduling
    # race. The env is captured at node spawn; the replica processes
    # inherit it too but never execute the site.
    monkeypatch.setenv("BCP_FAULT_MODE", "latency-spike")
    monkeypatch.setenv("BCP_FAULT_OPS", "replica_rpc")
    monkeypatch.setenv("BCP_FAULT_LATENCY_MS", "80")

    f = FunctionalFramework(num_nodes=3)
    setup_fleet(f)
    with f:
        validator, r1, r2 = f.nodes
        r1_name = f"127.0.0.1:{r1.rpc_port}"
        r2_name = f"127.0.0.1:{r2.rpc_port}"
        validator.rpc.generatetoaddress(CHAIN_H, ADDR)

        # only node0 fronts the fleet; replicas report a disabled gateway
        assert r1.rpc.gettpuinfo()["gateway"] == {"enabled": False}

        # -- phase 1: snapshot bootstrap --------------------------------
        snap_path = os.path.join(validator.datadir, "fleet-snapshot")
        dump = validator.rpc.dumptxoutset(snap_path)
        for rep in (r1, r2):
            bootstrap_replica_from_snapshot(rep, validator, snap_path, dump)
            assert rep.rpc.getblockcount() == CHAIN_H
        # a fresh replica joins the rotation within the health-probe
        # window once its tip clears the lag gate — no manual re-admission
        wait_until(lambda: len(_rotation(validator)) == 2, timeout=60)

        # settle background snapshot validation before the crash drills,
        # so kill9 recovery below exercises the ordinary restart path
        for rep in (r1, r2):
            wait_until(lambda rep=rep: rep.rpc.gettpuinfo()["store"]
                       ["snapshot"]["validated"], timeout=180, sleep=1.0)

        gw = gateway_client(validator)
        tip = validator.rpc.getbestblockhash()
        assert gw.getblockcount() == CHAIN_H
        assert gw.getbestblockhash() == tip

        # -- phase 2: coalescing ----------------------------------------
        before = _gw(validator)
        results: list = [None] * 8
        barrier = threading.Barrier(8)

        def fan(i: int) -> None:
            client = gateway_client(validator)
            barrier.wait()
            results[i] = client.getblock(tip)

        threads = [threading.Thread(target=fan, args=(i,)) for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        assert all(r is not None and r["hash"] == tip for r in results)
        after = _gw(validator)
        assert after["requests"] - before["requests"] == 8
        hits = after["coalesce_hits"] - before["coalesce_hits"]
        assert hits >= 1, "identical in-flight queries did not coalesce"

        # -- phase 3: hard-kill failover --------------------------------
        before = _gw(validator)
        r1.kill9()
        # every read during the outage still answers, correctly: the
        # round-robin leg that lands on the corpse fails over to the
        # survivor (or the validator) behind one client call
        for _ in range(8):
            assert gw.getbestblockhash() == tip
        after = _gw(validator)
        assert after["failovers"] > before["failovers"]
        # the probe loop trips the corpse's breaker and rotates it out
        wait_until(lambda: _rotation(validator) == {r2_name}, timeout=30)

        # restart: crash recovery, catch up, re-enter rotation
        r1.start()
        connect_nodes(r1, validator)
        wait_until(lambda: r1.rpc.getblockcount() == CHAIN_H, timeout=60)
        wait_until(lambda: len(_rotation(validator)) == 2, timeout=60)

        # -- phase 4: consistency gate (lag rotation) -------------------
        disconnect_nodes(r2, validator)
        max_lag = _gw(validator)["pool"]["max_lag"]
        validator.rpc.generatetoaddress(max_lag + 2, ADDR)
        new_tip = validator.rpc.getbestblockhash()
        # r1 (still connected) follows the relay to the fresh tip; r2 is
        # cut off, falls past -maxreplicalag, and the gate rotates it out
        wait_until(lambda: r1.rpc.getbestblockhash() == new_tip, timeout=60)
        wait_until(lambda: r2_name not in _rotation(validator), timeout=30)
        # once the gate fires, reads keep flowing and answer at the
        # fresh tip (from the caught-up replica or validator fallback) —
        # the stale replica is REMOVED, never served from
        wait_until(lambda: gw.getbestblockhash() == new_tip, timeout=60)
        for _ in range(4):
            assert gw.getbestblockhash() == new_tip
        assert gw.getblockcount() == CHAIN_H + max_lag + 2

        # heal: the replica catches up and is re-admitted
        connect_nodes(r2, validator)
        wait_until(lambda: r2.rpc.getblockcount() == CHAIN_H + max_lag + 2,
                   timeout=60)
        wait_until(lambda: len(_rotation(validator)) == 2, timeout=60)

        # the campaign rotated replicas out at least twice (kill + lag)
        assert _gw(validator)["pool"]["rotations_out"] >= 2


def test_fleet_quarantine_campaign():
    """ISSUE 17: a replica that onboards from a snapshot whose
    certificate cannot be verified (here: stripped — the poisoned-
    provenance stand-in that never flips ``certificate_verified``) is
    QUARANTINED: pool-visible, probed, but shed from rotation so it
    never serves a read. The fleet keeps answering consistently from
    the certified replica + validator fallback, and a clean certified
    reload re-admits the quarantined node within the probe window."""
    import shutil
    import time as _time

    # -rest on the validator: the Prometheus /metrics exposition the
    # campaign asserts on at the end rides the REST interface
    f = FunctionalFramework(num_nodes=3, extra_args=[["-rest"], [], []])
    setup_fleet(f)
    with f:
        validator, r1, r2 = f.nodes
        r2_name = f"127.0.0.1:{r2.rpc_port}"
        validator.rpc.generatetoaddress(CHAIN_H, ADDR)
        tip = validator.rpc.getbestblockhash()

        snap_path = os.path.join(validator.datadir, "cert-snapshot")
        dump = validator.rpc.dumptxoutset(snap_path)
        assert dump["certified"] is True
        nocert = os.path.join(validator.datadir, "nocert-snapshot")
        shutil.copytree(snap_path, nocert)
        os.remove(os.path.join(nocert, "CERTIFICATE.json"))

        # r1: certified onboarding — admitted on certificate trust alone,
        # without waiting for background validation
        bootstrap_replica_from_snapshot(r1, validator, snap_path, dump)
        wait_until(lambda: len(_rotation(validator)) >= 1, timeout=60)

        # r2: loads the cert-less snapshot and stays DISCONNECTED from the
        # validator (no backfill → never validated → the serving gate
        # stays down deterministically). Tip == validator tip, so the lag
        # gate is NOT what sheds it — quarantine is.
        r2.stop()
        auth = f"-assumeutxo={dump['bestblock']}:{dump['muhash']}"
        if auth not in r2.extra_args:
            r2.extra_args.append(auth)
        r2.start()
        r2.rpc.loadtxoutset(nocert)
        assert r2.rpc.getblockcount() == CHAIN_H
        snap_doc = r2.rpc.getblockchaininfo()["snapshot"]
        assert snap_doc["certificate_verified"] is False

        # the probe loop sees the down gate: shed, but pool-visible

        def _r2_doc() -> dict:
            return {r["name"]: r for r in
                    _gw(validator)["pool"]["replicas"]}[r2_name]

        wait_until(lambda: _r2_doc()["quarantined"], timeout=30)
        pool = _gw(validator)["pool"]
        by_name = {r["name"]: r for r in pool["replicas"]}
        assert r2_name not in _rotation(validator)
        assert by_name[r2_name]["in_rotation"] is False
        assert pool["quarantined"] >= 1
        assert pool["quarantines"] >= 1

        # reads keep flowing and every reply is consistent (the
        # quarantined replica is never picked); p99 stays sane
        gw = gateway_client(validator)
        lat = []
        for _ in range(40):
            t0 = _time.monotonic()
            assert gw.getbestblockhash() == tip
            lat.append(_time.monotonic() - t0)
        assert gw.getblockcount() == CHAIN_H
        by_name = {r["name"]: r
                   for r in _gw(validator)["pool"]["replicas"]}
        assert by_name[r2_name]["in_rotation"] is False
        assert by_name[r2_name]["quarantined"] is True
        lat.sort()
        assert lat[int(0.99 * len(lat))] < 2.0  # the bench records the bar

        # clean certified reload: fresh datadir, verified certificate,
        # re-admitted by the ordinary probe path — no gateway restart
        r2.stop()
        shutil.rmtree(r2.datadir)
        r2.start()
        r2.rpc.loadtxoutset(snap_path)
        assert r2.rpc.getblockchaininfo()["snapshot"][
            "certificate_verified"] is True
        connect_nodes(r2, validator)
        wait_until(lambda: r2_name in _rotation(validator), timeout=60)
        by_name = {r["name"]: r
                   for r in _gw(validator)["pool"]["replicas"]}
        assert by_name[r2_name]["quarantined"] is False

        # the quarantine surfaced in the Prometheus exposition too
        # (the validator's REST /metrics; the gauge reads 0 now that the
        # replica is re-admitted, 1 while it was quarantined)
        import urllib.request
        metrics = urllib.request.urlopen(
            f"http://127.0.0.1:{validator.rpc_port}/metrics",
            timeout=10).read().decode()
        assert "bcp_gateway_replica_quarantined" in metrics
