"""Functional-test framework: spawn REAL bcpd processes on regtest and
drive them over RPC — process-level multi-node on localhost.

Reference: qa/rpc-tests/test_framework/test_framework.py
(BitcoinTestFramework: start_nodes, stop_nodes), util.py (connect_nodes,
sync_blocks, sync_mempools, assert_equal). SURVEY.md §5.2: "This is how
multi-node is tested without a cluster."

Nodes run with JAX_PLATFORMS=cpu (process spawn cost; kernel-vs-device
behavior is covered by the unit suite and the driver's bench run).
"""

from __future__ import annotations

import os
import shutil
import socket
import struct
import subprocess
import sys
import tempfile
import threading
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class TestNode:
    """One bcpd process + its RPC client."""

    def __init__(self, index: int, base_dir: str, extra_args: list[str] = ()):
        self.index = index
        self.datadir_base = os.path.join(base_dir, f"node{index}")
        os.makedirs(self.datadir_base, exist_ok=True)
        self.datadir = os.path.join(self.datadir_base, "regtest")
        self.rpc_port = _free_port()
        self.p2p_port = _free_port()
        self.extra_args = list(extra_args)
        self.process: subprocess.Popen | None = None
        self.rpc = None
        # fleet mode (setup_fleet): explicit shared RPC credentials
        # instead of per-datadir cookie auth, and the gateway's bound
        # port when this node fronts the fleet
        self.rpc_user: str | None = None
        self.rpc_password: str | None = None
        self.gateway_port: int | None = None

    def args(self, extra: list[str] = ()) -> list[str]:
        return [
            sys.executable, "-m", "bitcoincashplus_tpu.cli.bcpd",
            "-regtest", f"-datadir={self.datadir_base}",
            f"-rpcport={self.rpc_port}", f"-port={self.p2p_port}",
            "-flushinterval=8",
            *self.extra_args, *extra,
        ]

    def start(self, extra: list[str] = (), timeout: float = 120.0) -> None:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
        # every functional node runs under the lock-order sentinel
        # (util/lockwatch): an introduced lock inversion surfaces in
        # gettpuinfo.lockwatch and the node's atexit cycle report instead
        # of waiting for the unlucky schedule. Opt out per-environment
        # with BCP_LOCKWATCH=0.
        env.setdefault("BCP_LOCKWATCH", "1")
        self.process = subprocess.Popen(
            self.args(extra), env=env, cwd=REPO_ROOT,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        )
        # bcpd prints its "bcpd started" marker only AFTER the P2P
        # listener is bound (RPC comes up first) — waiting for it closes
        # the race where wait_for_rpc returns while p2p_port is not yet
        # accepting and a raw-socket test gets ECONNREFUSED. debug output
        # goes to debug.log, so the marker is the only stdout traffic.
        self._wait_for_started_marker(timeout)
        self.wait_for_rpc(timeout)

    def _wait_for_started_marker(self, timeout: float) -> None:
        import select

        # raw os.read on the fd, never the BufferedReader: readline would
        # pull everything into the userspace buffer where select can't
        # see it, and could block past the deadline on a partial line
        fd = self.process.stdout.fileno()
        deadline = time.time() + timeout
        buf = b""
        while time.time() < deadline:
            if self.process.poll() is not None:
                out, err = self.process.communicate()
                raise RuntimeError(
                    f"node{self.index} died at startup:\n{err.decode()[-2000:]}"
                )
            ready, _, _ = select.select([fd], [], [], 0.25)
            if not ready:
                continue
            buf += os.read(fd, 4096)
            if b"bcpd started" in buf:
                return
        raise TimeoutError(f"node{self.index} never printed startup marker")

    def wait_for_rpc(self, timeout: float = 120.0) -> None:
        from bitcoincashplus_tpu.rpc.client import RPCClient

        deadline = time.time() + timeout
        last_err = None
        while time.time() < deadline:
            if self.process.poll() is not None:
                out, err = self.process.communicate()
                raise RuntimeError(
                    f"node{self.index} died at startup:\n{err.decode()[-2000:]}"
                )
            try:
                if self.rpc_user:
                    self.rpc = RPCClient(port=self.rpc_port,
                                         user=self.rpc_user,
                                         password=self.rpc_password,
                                         timeout=60.0)
                else:
                    self.rpc = RPCClient(port=self.rpc_port,
                                         datadir=self.datadir, timeout=60.0)
                self.rpc.getblockcount()
                return
            except Exception as e:  # cookie not written / socket refused yet
                last_err = e
                time.sleep(0.25)
        raise TimeoutError(f"node{self.index} RPC not ready: {last_err!r}")

    def stop(self, timeout: float = 60.0) -> None:
        if self.process is None:
            return
        try:
            self.rpc.stop()
        except Exception:
            self.process.terminate()
        try:
            self.process.wait(timeout)
        except subprocess.TimeoutExpired:
            self.process.kill()
            self.process.wait(10)
        self.process = None

    def kill9(self) -> None:
        """Simulate a crash — no flush, no orderly shutdown."""
        self.process.kill()
        self.process.wait(10)
        self.process = None


class FunctionalFramework:
    """Context manager owning N nodes and a scratch directory."""

    def __init__(self, num_nodes: int = 1, extra_args=None):
        self.num_nodes = num_nodes
        self.extra_args = extra_args or [[] for _ in range(num_nodes)]
        self.base_dir = tempfile.mkdtemp(prefix="bcp_func_")
        self.nodes = [
            TestNode(i, self.base_dir, self.extra_args[i])
            for i in range(num_nodes)
        ]

    def __enter__(self):
        for node in self.nodes:
            node.start()
        return self

    def __exit__(self, *exc):
        for node in self.nodes:
            try:
                node.stop()
            except Exception:
                pass
        shutil.rmtree(self.base_dir, ignore_errors=True)


# -- chaos peers (adversarial mininodes) -------------------------------


def default_chaos_rounds() -> int:
    """Campaign length for chaos behaviors. BCP_CHAOS_ROUNDS tunes it:
    the tier-1 default stays short; the `slow`-marked long campaign and
    soak runs export a bigger value."""
    return max(1, int(os.environ.get("BCP_CHAOS_ROUNDS", "4")))


class ChaosPeer(threading.Thread):
    """A mininode gone rogue: raw-socket peer that handshakes like a real
    node, then runs one scripted adversarial behavior against the target,
    driven by a deterministic util/faults.ChaosSchedule so every campaign
    is replayable from its seed.

    Behaviors:
      - ``flood``   — valid-framing junk messages at line rate (trips the
                      per-peer receive-rate ceiling)
      - ``stall``   — announce real headers (supplied by the test), accept
                      the resulting getdata, never answer it (trips the
                      block-download stall detector)
      - ``garbage`` — replay valid-PoW headers on unknown parents, go
                      silent, and disconnect/reconnect at scripted points
                      (accumulates graduated non-connecting-headers
                      charges)
      - ``txstorm`` — sustained tx flood: replay the supplied raw
                      transactions at ~``tx_rate``/s in seeded-shuffled
                      order with seeded pacing jitter (out-of-order
                      delivery exercises the orphan pool; the mempool
                      accept path absorbs the load — the ISSUE 7 serving
                      workload)
      - ``forkfeeder`` — replay a pre-mined COMPETING branch (supplied as
                      raw serialized blocks forking ``depth`` below the
                      victim's tip): announce the branch headers, then
                      serve the node's getdata at ~``block_rate``
                      blocks/s with seeded jitter — a reproducible
                      fork-war feeder for the speculation tree (ISSUE 9)

    The thread records ``evicted`` (the node closed the connection) and
    ``rounds_done`` for assertions; ``stop()`` ends the campaign."""

    def __init__(self, p2p_port: int, behavior: str, seed: int = 0,
                 headers: list[bytes] | None = None,
                 rounds: int | None = None, flood_payload: int = 262_144,
                 txs: list[bytes] | None = None, tx_rate: float = 200.0,
                 blocks: list[bytes] | None = None,
                 block_rate: float = 50.0):
        super().__init__(daemon=True, name=f"chaos-{behavior}-{seed}")
        from bitcoincashplus_tpu.consensus.params import regtest_params
        from bitcoincashplus_tpu.util.faults import ChaosSchedule

        assert behavior in ("flood", "stall", "garbage", "txstorm",
                            "forkfeeder"), behavior
        self.magic = regtest_params().netmagic
        self.port = p2p_port
        self.behavior = behavior
        self.schedule = ChaosSchedule(seed)
        self.headers = list(headers or [])  # raw 80-byte header blobs
        self.rounds = rounds if rounds is not None else default_chaos_rounds()
        self.flood_payload = flood_payload
        self.txs = list(txs or [])  # raw serialized transactions
        self.tx_rate = tx_rate
        self.blocks = list(blocks or [])  # raw serialized fork blocks
        self.block_rate = block_rate
        self.evicted = False
        self.rounds_done = 0
        self.error: BaseException | None = None
        self._halt = threading.Event()
        self.sock: socket.socket | None = None

    # -- lifecycle ------------------------------------------------------

    def stop(self) -> None:
        self._halt.set()
        s, self.sock = self.sock, None
        if s is not None:
            try:
                s.close()
            except OSError:
                pass

    def run(self) -> None:
        try:
            self._connect_handshake()
            getattr(self, f"_run_{self.behavior}")()
        except socket.timeout as e:
            # a timeout is NOT an eviction — the connection is still up;
            # surface it so tests can't pass spuriously
            self.error = e
        except (ConnectionError, OSError):
            # the node hung up on us — the eviction the tests assert on —
            # unless we closed the socket ourselves via stop()
            if not self._halt.is_set():
                self.evicted = True
        except BaseException as e:  # surfaced by the owning test
            self.error = e
        finally:
            self.stop()

    # -- plumbing -------------------------------------------------------

    def _send(self, command: str, payload: bytes = b"") -> None:
        from bitcoincashplus_tpu.p2p.protocol import pack_message

        sock = self.sock  # local ref: stop() may null the attribute
        if self._halt.is_set() or sock is None:
            raise ConnectionError("stopped")
        # a generous send timeout: _drain leaves 0.2 s on the socket, and
        # a flood burst against a slow reader must not read as a timeout
        sock.settimeout(10.0)
        sock.sendall(pack_message(self.magic, command, payload))

    def _drain(self, duration: float) -> None:
        """Read and discard node traffic for ``duration`` seconds; an EOF
        means we were evicted."""
        sock = self.sock
        if sock is None:
            raise ConnectionError("stopped")
        deadline = time.time() + duration
        sock.settimeout(0.2)
        while time.time() < deadline and not self._halt.is_set():
            try:
                data = sock.recv(65536)
            except socket.timeout:
                continue
            if not data:
                raise ConnectionError("evicted")

    def _connect_handshake(self) -> None:
        from bitcoincashplus_tpu.p2p.protocol import VersionPayload

        self.sock = socket.create_connection(("127.0.0.1", self.port),
                                             timeout=10)
        self._send("version", VersionPayload(
            user_agent=f"/chaos-{self.behavior}:0/").serialize())
        # wait for the node's verack, discarding handshake chatter
        deadline = time.time() + 10
        while True:
            if time.time() >= deadline:
                # routes to self.error (socket.timeout is TimeoutError on
                # 3.10+), never to a spurious `evicted`
                raise socket.timeout("no verack within deadline")
            header, _payload = self._read_msg()
            if header[4:16].rstrip(b"\x00") == b"verack":
                break
        self._send("verack")

    def _read_msg(self) -> tuple[bytes, bytes]:
        header = self._recv_exact(24)
        (length,) = struct.unpack_from("<I", header, 16)
        return header, self._recv_exact(length)

    def _recv_exact(self, n: int) -> bytes:
        sock = self.sock
        if sock is None:
            raise ConnectionError("stopped")
        buf = b""
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("closed")
            buf += chunk
        return buf

    # -- behaviors ------------------------------------------------------

    def _run_flood(self) -> None:
        """Shovel valid-framing junk ("xchaos" is unknown and ignored, but
        every byte counts against the receive ceiling) until evicted."""
        while not self._halt.is_set():
            for _ in range(self.schedule.burst_size(4, 12)):
                self._send("xchaos",
                           self.schedule.randbytes(self.flood_payload))
            self.rounds_done += 1
            self._drain(0.05)

    def _run_stall(self) -> None:
        """Announce the supplied (real) headers, then accept the node's
        getdata and withhold every block forever."""
        payload = _ser_raw_headers(self.headers)
        self._send("headers", payload)
        while not self._halt.is_set():
            self._drain(0.5)  # read getdata/pings, answer nothing
            self.rounds_done += 1

    def _run_txstorm(self) -> None:
        """Drive the supplied transactions at the target rate in a
        seeded-shuffled order. The SAME (seed, txs) pair replays the
        identical storm against a control node — the zero-divergence
        assertion the serving flood test is built on."""
        order = self.schedule.shuffle(list(self.txs))
        interval = 1.0 / max(self.tx_rate, 1e-6)
        for raw in order:
            if self._halt.is_set():
                return
            self._send("tx", raw)
            self.rounds_done += 1
            # seeded jitter around the nominal rate (bursts + gaps, same
            # shape on every node fed this seed)
            time.sleep(interval * (0.5 + self.schedule.rand()))
        self._drain(0.5)  # let the node chew; collect rejects/pings

    def _run_forkfeeder(self) -> None:
        """Announce the competing branch's headers, then serve the node's
        getdata for those blocks at ~block_rate/s with seeded jitter.
        Ends once every served block went out (or on stop/eviction);
        blocks the node never requests are simply never pushed — the
        feeder is a well-formed peer, not a flooder."""
        from bitcoincashplus_tpu.crypto.hashes import sha256d
        from bitcoincashplus_tpu.p2p.protocol import MSG_BLOCK, deser_inv

        by_hash = {sha256d(raw[:80]): raw for raw in self.blocks}
        self._send("headers", _ser_raw_headers(
            [raw[:80] for raw in self.blocks]))
        served = 0
        interval = 1.0 / max(self.block_rate, 1e-6)
        deadline = time.time() + 60.0
        sock = self.sock
        if sock is None:
            raise ConnectionError("stopped")
        sock.settimeout(0.25)
        while (not self._halt.is_set() and served < len(by_hash)
               and time.time() < deadline):
            try:
                header, payload = self._read_msg()
            except socket.timeout:
                continue
            command = header[4:16].rstrip(b"\x00")
            if command != b"getdata":
                continue
            for typ, h in deser_inv(payload):
                if typ != MSG_BLOCK or h not in by_hash:
                    continue
                if self._halt.is_set():
                    return
                self._send("block", by_hash[h])
                served += 1
                self.rounds_done += 1
                # seeded pacing: the fork arrives as a paced drip, not
                # one burst — the replay shape is part of the seed
                time.sleep(interval * (0.5 + self.schedule.rand()))
        self._drain(0.5)  # let the node finish connecting the branch

    def _run_garbage(self) -> None:
        """Replay garbage on a schedule: valid-PoW headers on unknown
        parents (graduated charge), silent stretches, and scripted
        disconnect/reconnect points."""
        for _ in range(self.rounds):
            if self._halt.is_set():
                return
            action = self.schedule.next_action()
            if action == "garbage-headers":
                batch = [
                    _mine_noise_header(self.schedule)
                    for _ in range(self.schedule.randint(1, 4))
                ]
                self._send("headers", _ser_raw_headers(batch))
                self._drain(self.schedule.pause())
            elif action == "ghost":
                self._drain(self.schedule.pause())
            else:  # scripted disconnect + fresh session
                sock = self.sock  # local ref: stop() may null it
                if sock is None:
                    raise ConnectionError("stopped")
                sock.close()
                time.sleep(self.schedule.pause())
                self._connect_handshake()
            self.rounds_done += 1


def _ser_raw_headers(headers80: list[bytes]) -> bytes:
    """headers payload from raw 80-byte blobs (count + header + 0 txs)."""
    from bitcoincashplus_tpu.consensus.serialize import ser_compact_size

    return (ser_compact_size(len(headers80))
            + b"".join(h + b"\x00" for h in headers80))


def _mine_noise_header(schedule, bits: int = 0x207FFFFF) -> bytes:
    """A valid-PoW regtest header on a random (unknown) parent — passes
    the context-free PoW check, then fails connection with
    prev-blk-not-found (the graduated misbehavior charge)."""
    from bitcoincashplus_tpu.consensus.block import NONCE_OFFSET, CBlockHeader
    from bitcoincashplus_tpu.consensus.pow import compact_to_target
    from bitcoincashplus_tpu.crypto.hashes import sha256d

    target, _ = compact_to_target(bits)
    base = CBlockHeader(
        version=0x20000000,
        hash_prev_block=schedule.randhash(),
        hash_merkle_root=schedule.randhash(),
        time=int(time.time()),
        bits=bits,
        nonce=0,
    ).serialize()
    nonce = 0
    while True:  # regtest target: ~2 attempts expected
        raw = base[:NONCE_OFFSET] + struct.pack("<I", nonce)
        if int.from_bytes(sha256d(raw), "little") <= target:
            return raw
        nonce += 1


def raw_headers_for(node: TestNode, count: int) -> list[bytes]:
    """The first ``count`` post-genesis headers of ``node``'s active chain
    as raw 80-byte blobs (fed to a stalling ChaosPeer as its
    announcement)."""
    out = []
    for height in range(1, count + 1):
        raw_block = node.rpc.getblock(node.rpc.getblockhash(height), 0)
        out.append(bytes.fromhex(raw_block)[:80])
    return out


# -- fleet topology (ISSUE 16: gateway + read replicas) ----------------

FLEET_USER, FLEET_PASSWORD = "fleet", "fleetpw"


def setup_fleet(f: FunctionalFramework, user: str = FLEET_USER,
                password: str = FLEET_PASSWORD,
                replicas: list[TestNode] | None = None) -> int:
    """Wire a (not-yet-started) FunctionalFramework as a serving fleet:
    node0 is the validator AND runs the -gateway front door; every other
    node (or the explicit ``replicas`` subset — a bench fleet may carry
    extra storm-miner nodes that must stay OUT of the pool) is a read
    replica in its -replicas pool. The whole fleet shares explicit RPC
    credentials (the gateway's replica legs authenticate with the
    validator's own -rpcuser/-rpcpassword — cookie files are per-datadir
    and unusable across processes). Returns the gateway port. Call
    BEFORE ``with f:`` / ``f.__enter__``."""
    for node in f.nodes:
        node.rpc_user, node.rpc_password = user, password
        node.extra_args += [f"-rpcuser={user}", f"-rpcpassword={password}"]
    validator = f.nodes[0]
    replicas = list(replicas) if replicas is not None else f.nodes[1:]
    gport = _free_port()
    validator.gateway_port = gport
    validator.extra_args += [
        f"-gateway={gport}",
        "-replicas=" + ",".join(
            f"127.0.0.1:{r.rpc_port}" for r in replicas),
    ]
    return gport


def gateway_client(validator: TestNode, user: str = FLEET_USER,
                   password: str = FLEET_PASSWORD, timeout: float = 60.0):
    """RPC client speaking to the fleet's front door (not the node RPC)."""
    from bitcoincashplus_tpu.rpc.client import RPCClient

    assert validator.gateway_port, "setup_fleet() first"
    return RPCClient(port=validator.gateway_port, user=user,
                     password=password, timeout=timeout)


def bootstrap_replica_from_snapshot(replica: TestNode, validator: TestNode,
                                    snap_path: str, dump: dict) -> None:
    """Snapshot-onboard a replica (the 30-second spin-up): restart with
    the -assumeutxo authorization, load the validator-produced snapshot,
    and connect to the validator for tip fan-out + background history
    backfill over the normal P2P path."""
    replica.stop()
    auth = f"-assumeutxo={dump['bestblock']}:{dump['muhash']}"
    if auth not in replica.extra_args:
        replica.extra_args.append(auth)
    replica.start()
    replica.rpc.loadtxoutset(snap_path)
    connect_nodes(replica, validator)


# -- sync barriers (test_framework/util.py) ----------------------------


def connect_nodes(a: TestNode, b: TestNode) -> None:
    a.rpc.addnode(f"127.0.0.1:{b.p2p_port}", "onetry")
    wait_until(lambda: a.rpc.getconnectioncount() >= 1
               and b.rpc.getconnectioncount() >= 1, timeout=30)


def disconnect_nodes(a: TestNode, b: TestNode) -> None:
    """Tear down every live link between ``a`` and ``b`` (both
    directions — either side may own the TCP connection). onetry links
    are not redialed, so the cut persists until connect_nodes heals it."""
    for src, dst in ((a, b), (b, a)):
        for peer in src.rpc.getpeerinfo():
            addr = peer.get("addr", "")
            if addr.endswith(f":{dst.p2p_port}"):
                try:
                    src.rpc.disconnectnode(addr)
                except Exception:
                    pass  # already gone


def partition_fleet(nodes: list[TestNode],
                    sides: tuple[list[int], list[int]]) -> None:
    """Apply a seeded bipartition (util/faults.ChaosSchedule.bipartition):
    cut every cross-side link; links inside each side stay up."""
    side_a, side_b = sides
    for i in side_a:
        for j in side_b:
            disconnect_nodes(nodes[i], nodes[j])


def heal_fleet(nodes: list[TestNode], topology: list[tuple[int, int]]
               ) -> None:
    """Re-establish the fleet's base topology after a partition."""
    for i, j in topology:
        try:
            connect_nodes(nodes[i], nodes[j])
        except TimeoutError:
            # one retry: the first dial can race the disconnect teardown
            connect_nodes(nodes[i], nodes[j])


def sync_blocks(nodes, timeout: float = 60.0) -> None:
    deadline = time.time() + timeout
    while time.time() < deadline:
        tips = {n.rpc.getbestblockhash() for n in nodes}
        if len(tips) == 1:
            return
        time.sleep(0.25)
    raise TimeoutError(f"sync_blocks: tips diverged: "
                       f"{[n.rpc.getbestblockhash() for n in nodes]}")


def sync_mempools(nodes, timeout: float = 60.0) -> None:
    deadline = time.time() + timeout
    while time.time() < deadline:
        pools = [set(n.rpc.getrawmempool()) for n in nodes]
        if all(p == pools[0] for p in pools):
            return
        time.sleep(0.25)
    raise TimeoutError("sync_mempools timed out")


def wait_until(predicate, timeout: float = 30.0, sleep: float = 0.25) -> None:
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return
        time.sleep(sleep)
    raise TimeoutError("wait_until timed out")
