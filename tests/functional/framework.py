"""Functional-test framework: spawn REAL bcpd processes on regtest and
drive them over RPC — process-level multi-node on localhost.

Reference: qa/rpc-tests/test_framework/test_framework.py
(BitcoinTestFramework: start_nodes, stop_nodes), util.py (connect_nodes,
sync_blocks, sync_mempools, assert_equal). SURVEY.md §5.2: "This is how
multi-node is tested without a cluster."

Nodes run with JAX_PLATFORMS=cpu (process spawn cost; kernel-vs-device
behavior is covered by the unit suite and the driver's bench run).
"""

from __future__ import annotations

import os
import shutil
import socket
import subprocess
import sys
import tempfile
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class TestNode:
    """One bcpd process + its RPC client."""

    def __init__(self, index: int, base_dir: str, extra_args: list[str] = ()):
        self.index = index
        self.datadir_base = os.path.join(base_dir, f"node{index}")
        os.makedirs(self.datadir_base, exist_ok=True)
        self.datadir = os.path.join(self.datadir_base, "regtest")
        self.rpc_port = _free_port()
        self.p2p_port = _free_port()
        self.extra_args = list(extra_args)
        self.process: subprocess.Popen | None = None
        self.rpc = None

    def args(self, extra: list[str] = ()) -> list[str]:
        return [
            sys.executable, "-m", "bitcoincashplus_tpu.cli.bcpd",
            "-regtest", f"-datadir={self.datadir_base}",
            f"-rpcport={self.rpc_port}", f"-port={self.p2p_port}",
            "-flushinterval=8",
            *self.extra_args, *extra,
        ]

    def start(self, extra: list[str] = (), timeout: float = 120.0) -> None:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
        self.process = subprocess.Popen(
            self.args(extra), env=env, cwd=REPO_ROOT,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        )
        self.wait_for_rpc(timeout)

    def wait_for_rpc(self, timeout: float = 120.0) -> None:
        from bitcoincashplus_tpu.rpc.client import RPCClient

        deadline = time.time() + timeout
        last_err = None
        while time.time() < deadline:
            if self.process.poll() is not None:
                out, err = self.process.communicate()
                raise RuntimeError(
                    f"node{self.index} died at startup:\n{err.decode()[-2000:]}"
                )
            try:
                self.rpc = RPCClient(port=self.rpc_port, datadir=self.datadir,
                                     timeout=60.0)
                self.rpc.getblockcount()
                return
            except Exception as e:  # cookie not written / socket refused yet
                last_err = e
                time.sleep(0.25)
        raise TimeoutError(f"node{self.index} RPC not ready: {last_err!r}")

    def stop(self, timeout: float = 60.0) -> None:
        if self.process is None:
            return
        try:
            self.rpc.stop()
        except Exception:
            self.process.terminate()
        try:
            self.process.wait(timeout)
        except subprocess.TimeoutExpired:
            self.process.kill()
            self.process.wait(10)
        self.process = None

    def kill9(self) -> None:
        """Simulate a crash — no flush, no orderly shutdown."""
        self.process.kill()
        self.process.wait(10)
        self.process = None


class FunctionalFramework:
    """Context manager owning N nodes and a scratch directory."""

    def __init__(self, num_nodes: int = 1, extra_args=None):
        self.num_nodes = num_nodes
        self.extra_args = extra_args or [[] for _ in range(num_nodes)]
        self.base_dir = tempfile.mkdtemp(prefix="bcp_func_")
        self.nodes = [
            TestNode(i, self.base_dir, self.extra_args[i])
            for i in range(num_nodes)
        ]

    def __enter__(self):
        for node in self.nodes:
            node.start()
        return self

    def __exit__(self, *exc):
        for node in self.nodes:
            try:
                node.stop()
            except Exception:
                pass
        shutil.rmtree(self.base_dir, ignore_errors=True)


# -- sync barriers (test_framework/util.py) ----------------------------


def connect_nodes(a: TestNode, b: TestNode) -> None:
    a.rpc.addnode(f"127.0.0.1:{b.p2p_port}", "onetry")
    wait_until(lambda: a.rpc.getconnectioncount() >= 1
               and b.rpc.getconnectioncount() >= 1, timeout=30)


def sync_blocks(nodes, timeout: float = 60.0) -> None:
    deadline = time.time() + timeout
    while time.time() < deadline:
        tips = {n.rpc.getbestblockhash() for n in nodes}
        if len(tips) == 1:
            return
        time.sleep(0.25)
    raise TimeoutError(f"sync_blocks: tips diverged: "
                       f"{[n.rpc.getbestblockhash() for n in nodes]}")


def sync_mempools(nodes, timeout: float = 60.0) -> None:
    deadline = time.time() + timeout
    while time.time() < deadline:
        pools = [set(n.rpc.getrawmempool()) for n in nodes]
        if all(p == pools[0] for p in pools):
            return
        time.sleep(0.25)
    raise TimeoutError("sync_mempools timed out")


def wait_until(predicate, timeout: float = 30.0, sleep: float = 0.25) -> None:
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return
        time.sleep(sleep)
    raise TimeoutError("wait_until timed out")
