"""Proof-carrying snapshots, end to end over real bcpd processes
(ISSUE 17).

The producer mines a chain and dumps a CERTIFIED snapshot (MMR header
commitment + per-epoch MuHash trajectory, store/certificate.py); the
consumer proves the three trust stories:

  1. certificate-gated onboarding — a certified snapshot is verified at
     ``loadtxoutset`` and the replica serves immediately with
     ``certificate_verified`` up BEFORE background validation finishes
     (the onboarding-economics flip), then spot-check shadow validation
     converges to a byte-identical digest;
  2. the rejection matrix — bit-flipped certificate, truncated epoch
     trajectory, and the armed ``snapshot_cert`` fault site all take the
     wipe-and-reject path (never a half-loaded chainstate), and
     ``-snapshotcertrequired`` refuses a cert-less snapshot outright;
  3. forged-epoch content — a snapshot poisoned AT BUILD (the
     ``snapshot_cert`` poison-output drill) passes structural
     verification at load, and the shadow validator hard-aborts the node
     at the FIRST divergent epoch checkpoint, O(E) blocks past the
     forgery instead of at height H.
"""

from __future__ import annotations

import json
import os
import shutil

import pytest

from bitcoincashplus_tpu.consensus.params import regtest_params
from bitcoincashplus_tpu.wallet.keys import CKey

from .framework import FunctionalFramework, connect_nodes, wait_until

pytestmark = [pytest.mark.functional, pytest.mark.snapshot]

KEY = CKey(0x17CE47)
ADDR = KEY.p2pkh_address(regtest_params())

CHAIN_H = 24
EPOCH = 8  # checkpoints [8, 16, 24]

CERT_NAME = "CERTIFICATE.json"


def _forge_copy(snap_path: str, dest: str, mutate) -> str:
    """Copy the snapshot dir and run ``mutate(cert_dict)`` over its
    certificate (the tamper matrix: each mutation is applied to an
    otherwise-honest snapshot)."""
    shutil.rmtree(dest, ignore_errors=True)
    shutil.copytree(snap_path, dest)
    cert_file = os.path.join(dest, CERT_NAME)
    with open(cert_file) as f:
        cert = json.load(f)
    mutate(cert)
    with open(cert_file, "w") as f:
        json.dump(cert, f)
    return dest


def _snap_doc(node) -> dict:
    return node.rpc.getblockchaininfo()["snapshot"]


def test_certified_onboarding_with_spotcheck():
    with FunctionalFramework(
            num_nodes=2,
            extra_args=[[f"-snapshotepoch={EPOCH}"], []]) as f:
        a, b = f.nodes
        a.rpc.generatetoaddress(CHAIN_H, ADDR)
        snap_path = os.path.join(a.datadir, "utxo-snapshot")
        dump = a.rpc.dumptxoutset(snap_path)
        assert dump["certified"] is True
        assert dump["epochs"] == 3  # [8, 16, 24]
        assert os.path.exists(os.path.join(snap_path, CERT_NAME))

        # restart B authorized, with seeded spot-check sampling (1 of the
        # 3 certified epochs gets full script re-validation; the digest
        # tripwires stay armed at every boundary)
        b.stop()
        b.extra_args += [
            f"-assumeutxo={dump['bestblock']}:{dump['muhash']}",
            "-snapshotspotcheck=1", "-netseed=7",
        ]
        b.start()

        # tamper matrix first (each rejected load must leave the node
        # fresh — tip at genesis, zero coins — or the next load couldn't
        # even start)
        flipped = _forge_copy(
            snap_path, os.path.join(a.datadir, "snap-flip"),
            lambda c: c.update(commitment="00" + c["commitment"][2:]
                               if not c["commitment"].startswith("00")
                               else "ff" + c["commitment"][2:]))
        with pytest.raises(Exception, match="certificate rejected"):
            b.rpc.loadtxoutset(flipped)
        assert b.rpc.getblockcount() == 0
        assert b.rpc.gettxoutsetinfo()["txouts"] == 0  # wiped, not half-loaded

        truncated = _forge_copy(
            snap_path, os.path.join(a.datadir, "snap-trunc"),
            lambda c: c["epochs"].pop(0))
        with pytest.raises(Exception, match="certificate rejected"):
            b.rpc.loadtxoutset(truncated)
        assert b.rpc.getblockcount() == 0

        # the honest certified load: verified at load, serving instantly
        res = b.rpc.loadtxoutset(snap_path)
        assert res["height"] == CHAIN_H
        assert b.rpc.getblockcount() == CHAIN_H
        doc = _snap_doc(b)
        # trust established by the certificate, in seconds — BEFORE the
        # background replay (validated flips later, the gate is already up)
        assert doc["cert_present"] and doc["cert_verified"]
        assert doc["certificate_verified"] is True

        # background (spot-check) validation converges byte-identically
        connect_nodes(b, a)
        wait_until(lambda: _snap_doc(b)["validated"], timeout=180, sleep=1.0)
        ia, ib = a.rpc.gettxoutsetinfo(), b.rpc.gettxoutsetinfo()
        assert ia["muhash"] == ib["muhash"]
        assert ia["bestblock"] == ib["bestblock"]
        with open(os.path.join(b.datadir, "debug.log")) as fh:
            log = fh.read()
        assert "spot-check mode" in log
        # the epoch tripwire file is cleaned up once validation lands
        assert not os.path.exists(
            os.path.join(b.datadir, "snapshot_cert.json"))


def test_certificate_rejection_matrix(monkeypatch):
    # the snapshot_cert fault site is explicit-only; arming it here
    # reaches both spawned nodes, but only B's loadtxoutset executes the
    # verify leg (the producer's dump leg only fires under poison-output)
    monkeypatch.setenv("BCP_FAULT_MODE", "fail-always")
    monkeypatch.setenv("BCP_FAULT_OPS", "snapshot_cert")
    with FunctionalFramework(
            num_nodes=2,
            extra_args=[["-snapshotepoch=4"], []]) as f:
        a, b = f.nodes
        a.rpc.generatetoaddress(8, ADDR)
        snap_path = os.path.join(a.datadir, "cert-snapshot")
        dump = a.rpc.dumptxoutset(snap_path)
        assert dump["certified"] is True

        nocert = os.path.join(a.datadir, "snap-nocert")
        shutil.rmtree(nocert, ignore_errors=True)
        shutil.copytree(snap_path, nocert)
        os.remove(os.path.join(nocert, CERT_NAME))

        auth = f"-assumeutxo={dump['bestblock']}:{dump['muhash']}"
        b.stop()
        b.extra_args += [auth, "-snapshotcertrequired"]
        b.start()

        # cert-less + -snapshotcertrequired: refused before any row lands
        with pytest.raises(Exception, match="certificate"):
            b.rpc.loadtxoutset(nocert)
        assert b.rpc.getblockcount() == 0

        # armed fail-always: the certificate check blows up mid-load and
        # MUST take the wipe-and-reject path (BCP005 drill, fail leg)
        with pytest.raises(Exception, match="[Ii]njected"):
            b.rpc.loadtxoutset(snap_path)
        assert b.rpc.getblockcount() == 0
        assert b.rpc.gettxoutsetinfo()["txouts"] == 0

        # disarm and restart: the same snapshot now verifies and serves
        monkeypatch.setenv("BCP_FAULT_MODE", "off")
        b.stop()
        b.start()
        res = b.rpc.loadtxoutset(snap_path)
        assert res["height"] == 8
        assert _snap_doc(b)["certificate_verified"] is True

        # cert-less WITHOUT the required flag: allowed, but the serving
        # gate stays down (the fleet-quarantine signal) until validation
        b.stop()
        shutil.rmtree(b.datadir)  # back to a fresh node
        b.extra_args.remove("-snapshotcertrequired")
        b.start()
        res = b.rpc.loadtxoutset(nocert)
        assert res["height"] == 8
        doc = _snap_doc(b)
        assert doc["cert_present"] is False
        assert doc["certificate_verified"] is False


def test_forged_epoch_hard_abort(monkeypatch):
    # poison-output at BUILD: dumptxoutset corrupts one mid-trajectory
    # epoch digest before the commitment chain is sealed — the forgery
    # structural verification cannot see
    monkeypatch.setenv("BCP_FAULT_MODE", "poison-output")
    monkeypatch.setenv("BCP_FAULT_OPS", "snapshot_cert")
    with FunctionalFramework(
            num_nodes=2,
            extra_args=[[f"-snapshotepoch={EPOCH}"], []]) as f:
        a, b = f.nodes
        a.rpc.generatetoaddress(CHAIN_H, ADDR)
        snap_path = os.path.join(a.datadir, "forged-snapshot")
        dump = a.rpc.dumptxoutset(snap_path)
        assert dump["certified"] is True

        b.stop()
        b.extra_args.append(
            f"-assumeutxo={dump['bestblock']}:{dump['muhash']}")
        b.start()
        # the forged certificate PASSES load-time verification (the chain
        # was sealed over the forged digest; the final epoch matches the
        # manifest) — the replica starts serving
        res = b.rpc.loadtxoutset(snap_path)
        assert res["height"] == CHAIN_H
        assert _snap_doc(b)["certificate_verified"] is True

        # ... until the shadow replay crosses the forged checkpoint: the
        # running MuHash diverges from the certified digest at epoch 16
        # (the poisoned middle epoch) and the node hard-aborts there —
        # detection latency O(E) blocks, not the full height-H replay
        connect_nodes(b, a)
        wait_until(lambda: b.process.poll() is not None,
                   timeout=180, sleep=1.0)
        with open(os.path.join(b.datadir, "debug.log")) as fh:
            log = fh.read()
        assert "EPOCH DIGEST DIVERGENCE" in log
        assert "FORGED" in log
        assert "checkpoint 16" in log
        # never reached the final checkpoint: the abort beat the full
        # re-validation to the punch
        assert f"checkpoint {CHAIN_H}" not in log
