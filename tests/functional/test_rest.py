"""REST interface (-rest) + -blocknotify functional test (src/rest.cpp,
init.cpp BlockNotifyCallback) against a real bcpd process."""

import glob
import os
import time
import urllib.error
import urllib.request

from .framework import FunctionalFramework, wait_until
from .test_node_basic import KEY, _regtest_address


def _get(node, path):
    url = f"http://127.0.0.1:{node.rpc_port}{path}"
    with urllib.request.urlopen(url, timeout=30) as r:
        return r.status, r.read()


def _get_status(node, path):
    try:
        return _get(node, path)[0]
    except urllib.error.HTTPError as e:
        return e.code


def test_rest_and_blocknotify(tmp_path):
    notify_dir = str(tmp_path)
    notify_cmd = f"-blocknotify=touch {notify_dir}/notified_%s"
    with FunctionalFramework(
        num_nodes=1,
        extra_args=[["-rest", "-txindex", "-listen=0", notify_cmd]],
    ) as f:
        node = f.nodes[0]
        addr = _regtest_address(KEY)
        hashes = node.rpc.generatetoaddress(5, addr)
        tip = hashes[-1]

        # chaininfo
        status, body = _get(node, "/rest/chaininfo.json")
        assert status == 200
        import json

        info = json.loads(body)
        assert info["blocks"] == 5 and info["bestblockhash"] == tip

        # block by hash, both formats
        status, body = _get(node, f"/rest/block/{tip}.json")
        assert status == 200
        blk = json.loads(body)
        assert blk["height"] == 5 and len(blk["tx"]) == 1
        status, body = _get(node, f"/rest/block/{tip}.hex")
        assert status == 200
        raw = bytes.fromhex(body.decode().strip())
        assert len(raw) == blk["size"]

        # headers ascending from genesis-side hash
        first = hashes[0]
        status, body = _get(node, f"/rest/headers/5/{first}.hex")
        assert status == 200
        assert len(bytes.fromhex(body.decode().strip())) == 5 * 80

        # blockhashbyheight
        status, body = _get(node, "/rest/blockhashbyheight/3.json")
        assert status == 200
        assert json.loads(body)["blockhash"] == hashes[2]

        # tx via txindex
        coinbase_txid = blk["tx"][0]["txid"]
        status, body = _get(node, f"/rest/tx/{coinbase_txid}.hex")
        assert status == 200
        assert len(body.decode().strip()) > 100

        # mempool endpoints
        assert _get(node, "/rest/mempool/info.json")[0] == 200
        assert _get(node, "/rest/mempool/contents.json")[0] == 200

        # error paths: unknown hash -> 404, bad format -> 400
        assert _get_status(node, "/rest/block/" + "00" * 32 + ".json") == 404
        assert _get_status(node, f"/rest/block/{tip}.xml") == 400
        assert _get_status(node, "/rest/nonsense") == 404

        # -blocknotify fired for the tip (fire-and-forget: allow a moment)
        wait_until(
            lambda: os.path.exists(os.path.join(notify_dir, f"notified_{tip}")),
            timeout=15,
        )
        assert len(glob.glob(os.path.join(notify_dir, "notified_*"))) == 5


def test_rest_disabled_is_403():
    with FunctionalFramework(num_nodes=1,
                             extra_args=[["-listen=0"]]) as f:
        node = f.nodes[0]
        assert _get_status(node, "/rest/chaininfo.json") == 403
