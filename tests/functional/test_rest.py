"""REST interface (-rest) + -blocknotify functional test (src/rest.cpp,
init.cpp BlockNotifyCallback) against a real bcpd process."""

import glob
import os
import time
import urllib.error
import urllib.request

from .framework import FunctionalFramework, wait_until
from .test_node_basic import KEY, _regtest_address


def _get(node, path):
    url = f"http://127.0.0.1:{node.rpc_port}{path}"
    with urllib.request.urlopen(url, timeout=30) as r:
        return r.status, r.read()


def _get_status(node, path):
    try:
        return _get(node, path)[0]
    except urllib.error.HTTPError as e:
        return e.code


def test_rest_and_blocknotify(tmp_path):
    notify_dir = str(tmp_path)
    notify_cmd = f"-blocknotify=touch {notify_dir}/notified_%s"
    with FunctionalFramework(
        num_nodes=1,
        extra_args=[["-rest", "-txindex", "-listen=0", notify_cmd]],
    ) as f:
        node = f.nodes[0]
        addr = _regtest_address(KEY)
        hashes = node.rpc.generatetoaddress(5, addr)
        tip = hashes[-1]

        # chaininfo
        status, body = _get(node, "/rest/chaininfo.json")
        assert status == 200
        import json

        info = json.loads(body)
        assert info["blocks"] == 5 and info["bestblockhash"] == tip

        # block by hash, both formats
        status, body = _get(node, f"/rest/block/{tip}.json")
        assert status == 200
        blk = json.loads(body)
        assert blk["height"] == 5 and len(blk["tx"]) == 1
        status, body = _get(node, f"/rest/block/{tip}.hex")
        assert status == 200
        raw = bytes.fromhex(body.decode().strip())
        assert len(raw) == blk["size"]

        # headers ascending from genesis-side hash
        first = hashes[0]
        status, body = _get(node, f"/rest/headers/5/{first}.hex")
        assert status == 200
        assert len(bytes.fromhex(body.decode().strip())) == 5 * 80

        # blockhashbyheight
        status, body = _get(node, "/rest/blockhashbyheight/3.json")
        assert status == 200
        assert json.loads(body)["blockhash"] == hashes[2]

        # tx via txindex
        coinbase_txid = blk["tx"][0]["txid"]
        status, body = _get(node, f"/rest/tx/{coinbase_txid}.hex")
        assert status == 200
        assert len(body.decode().strip()) > 100

        # mempool endpoints
        assert _get(node, "/rest/mempool/info.json")[0] == 200
        assert _get(node, "/rest/mempool/contents.json")[0] == 200

        # error paths: unknown hash -> 404, bad format -> 400
        assert _get_status(node, "/rest/block/" + "00" * 32 + ".json") == 404
        assert _get_status(node, f"/rest/block/{tip}.xml") == 400
        assert _get_status(node, "/rest/nonsense") == 404

        # -blocknotify fired for the tip (fire-and-forget: allow a moment)
        wait_until(
            lambda: os.path.exists(os.path.join(notify_dir, f"notified_{tip}")),
            timeout=15,
        )
        assert len(glob.glob(os.path.join(notify_dir, "notified_*"))) == 5


def test_rest_disabled_is_403():
    with FunctionalFramework(num_nodes=1,
                             extra_args=[["-listen=0"]]) as f:
        node = f.nodes[0]
        assert _get_status(node, "/rest/chaininfo.json") == 403


def test_rest_getutxos():
    """/rest/getutxos (+checkmempool): bitmap + utxo rows, mempool-spent
    awareness (src/rest.cpp rest_getutxos)."""
    import json

    with FunctionalFramework(
        num_nodes=1, extra_args=[["-rest", "-txindex", "-listen=0"]],
    ) as f:
        node = f.nodes[0]
        addr = node.rpc.getnewaddress()
        node.rpc.generatetoaddress(101, addr)
        cb1 = node.rpc.getblock(node.rpc.getblockhash(1), 2)["tx"][0]

        # unspent coinbase output
        status, body = _get(node, f"/rest/getutxos/{cb1['txid']}-0.json")
        out = json.loads(body)
        assert out["bitmap"] == "1"
        assert out["utxos"][0]["value"] == 50.0
        assert out["chainHeight"] == 101

        # missing outpoint → 0 bitmap
        status, body = _get(node, f"/rest/getutxos/{cb1['txid']}-7.json")
        assert json.loads(body)["bitmap"] == "0"

        # a mempool spend flips it only under checkmempool
        txid = node.rpc.sendtoaddress(addr, 1.0)
        tx = node.rpc.getrawtransaction(txid, True)
        spent_in = tx["vin"][0]
        op = f"{spent_in['txid']}-{spent_in['vout']}"
        status, body = _get(node, f"/rest/getutxos/{op}.json")
        assert json.loads(body)["bitmap"] == "1"  # still unspent on-chain
        status, body = _get(node, f"/rest/getutxos/checkmempool/{op}.json")
        assert json.loads(body)["bitmap"] == "0"  # spent by the pool tx
        # the pool tx's own outputs are visible under checkmempool
        status, body = _get(node, f"/rest/getutxos/checkmempool/{txid}-0.json")
        out = json.loads(body)
        assert out["bitmap"] == "1" and out["utxos"][0]["height"] == 0x7FFFFFFF

        # malformed outpoint
        assert _get_status(node, "/rest/getutxos/zzzz-0.json") == 400


def test_accounts_api_and_watchonly_imports():
    """Legacy accounts surface + importaddress/importpubkey watch-only."""
    from bitcoincashplus_tpu.wallet.keys import CKey

    with FunctionalFramework(num_nodes=1,
                             extra_args=[["-listen=0"]]) as f:
        node = f.nodes[0]
        default_addr = node.rpc.getnewaddress()
        node.rpc.generatetoaddress(101, default_addr)

        # account-labelled address receives; listaccounts splits balances
        acct_addr = node.rpc.getnewaddress("savings")
        assert node.rpc.getaccount(acct_addr) == "savings"
        assert acct_addr in node.rpc.getaddressesbyaccount("savings")
        # getaccountaddress is stable across calls
        stable = node.rpc.getaccountaddress("savings")
        assert node.rpc.getaccountaddress("savings") == stable
        assert node.rpc.getaccount(stable) == "savings"
        node.rpc.sendtoaddress(acct_addr, 2.0)
        node.rpc.generatetoaddress(1, default_addr)
        accounts = node.rpc.listaccounts()
        assert accounts["savings"] == 2.0
        assert node.rpc.getreceivedbyaccount("savings") == 2.0

        # move shifts bookkeeping between accounts
        node.rpc.move("savings", "spending", 0.5)
        accounts = node.rpc.listaccounts()
        assert accounts["savings"] == 1.5
        assert accounts["spending"] == 0.5

        # setaccount relabels
        node.rpc.setaccount(acct_addr, "renamed")
        assert node.rpc.getaccount(acct_addr) == "renamed"

        # importaddress: foreign address becomes watch-only
        foreign = CKey(0xFEED).p2pkh_address(
            __import__("bitcoincashplus_tpu.consensus.params",
                       fromlist=["regtest_params"]).regtest_params())
        node.rpc.importaddress(foreign, "watched")
        node.rpc.sendtoaddress(foreign, 3.0)
        node.rpc.generatetoaddress(1, default_addr)
        rows = [u for u in node.rpc.listunspent() if not u["spendable"]]
        assert any(abs(u["amount"] - 3.0) < 1e-9 for u in rows)

        # importpubkey: watch both P2PK and P2PKH forms
        k = CKey(0xBEAD)
        node.rpc.importpubkey(k.pubkey.hex())
        node.rpc.sendtoaddress(
            k.p2pkh_address(__import__(
                "bitcoincashplus_tpu.consensus.params",
                fromlist=["regtest_params"]).regtest_params()), 1.5)
        node.rpc.generatetoaddress(1, default_addr)
        rows = [u for u in node.rpc.listunspent() if not u["spendable"]]
        assert any(abs(u["amount"] - 1.5) < 1e-9 for u in rows)


def test_zmq_notifications():
    """ZMTP 3.0 PUB notifications: hashblock/hashtx/rawblock/rawtx with
    [topic, body, seq] framing (zmq_tests.cpp / interface_zmq.py)."""
    import socket as _socket
    import struct

    from bitcoincashplus_tpu.rpc.zmq import ZMQSubscriber

    # two distinct endpoints: the reference binds one socket per notifier
    ports = []
    for _ in range(2):
        probe = _socket.socket()
        probe.bind(("127.0.0.1", 0))
        ports.append(probe.getsockname()[1])
        probe.close()
    zport, zport2 = ports

    with FunctionalFramework(
        num_nodes=1,
        extra_args=[[f"-zmqpubhashblock=tcp://127.0.0.1:{zport}",
                     f"-zmqpubhashtx={zport}",
                     f"-zmqpubrawblock={zport}",
                     f"-zmqpubrawtx={zport2}",  # its own endpoint
                     "-listen=0"]],
    ) as f:
        node = f.nodes[0]
        sub = ZMQSubscriber(zport, [b"hashblock", b"hashtx", b"rawblock"])
        sub2 = ZMQSubscriber(zport2, [b"rawtx"])
        time.sleep(0.5)  # subscription propagation
        addr = node.rpc.getnewaddress()
        mined = node.rpc.generatetoaddress(1, addr)[0]

        got = {}
        for _ in range(3):
            topic, body, seq = sub.recv_multipart()
            got[topic] = (body, struct.unpack("<I", seq)[0])
        topic, body, seq = sub2.recv_multipart()
        got[topic] = (body, struct.unpack("<I", seq)[0])
        sub2.close()
        assert set(got) == {b"hashblock", b"hashtx", b"rawblock", b"rawtx"}
        assert got[b"hashblock"][0].hex() == mined
        raw = node.rpc.getblock(mined, 0)
        assert got[b"rawblock"][0].hex() == raw
        # the coinbase tx rides hashtx/rawtx
        cb_txid = node.rpc.getblock(mined, 1)["tx"][0]
        assert got[b"hashtx"][0].hex() == cb_txid
        assert all(s == 0 for _b, s in got.values())  # first per topic

        # mempool entry notifies hashtx/rawtx with bumped sequence
        node.rpc.generatetoaddress(100, addr)
        # drain the 100 blocks' messages
        deadline = time.time() + 30
        while time.time() < deadline:
            topic, body, seq = sub.recv_multipart()
            if topic == b"hashblock" and struct.unpack("<I", seq)[0] == 100:
                break
        txid = node.rpc.sendtoaddress(addr, 1.0)
        deadline = time.time() + 15
        seen_mempool_tx = False
        while time.time() < deadline and not seen_mempool_tx:
            topic, body, seq = sub.recv_multipart()
            if topic == b"hashtx" and body.hex() == txid:
                seen_mempool_tx = True
        assert seen_mempool_tx
        sub.close()

