"""Single-node functional tests over a REAL bcpd process.

Covers the VERDICT round-2 'done =' bar for the node runtime: a node
process starts on regtest, mines via RPC, serves a template, accepts a
submitted block, answers a second client, accepts a raw transaction into
its mempool and mines it, and resumes cleanly across clean restart,
kill -9, and -reindex.

Reference behaviors: qa/rpc-tests (mining_*.py, rawtransactions.py,
reindex.py, abandonconflict-style mempool checks).
"""

from __future__ import annotations

import pytest

from bitcoincashplus_tpu.consensus.serialize import hex_to_hash
from bitcoincashplus_tpu.consensus.tx import (
    COutPoint,
    CTransaction,
    CTxIn,
    CTxOut,
)
from bitcoincashplus_tpu.rpc.client import JSONRPCException, RPCClient
from bitcoincashplus_tpu.script.sighash import SIGHASH_ALL
from bitcoincashplus_tpu.wallet.keys import CKey
from bitcoincashplus_tpu.wallet.signing import sign_transaction

from .framework import FunctionalFramework

pytestmark = pytest.mark.functional

KEY = CKey(0x1EAF)


def _regtest_address(key: CKey) -> str:
    from bitcoincashplus_tpu.consensus.params import regtest_params

    return key.p2pkh_address(regtest_params())


def _mine_template(tmpl: dict, payout_address: str):
    """Assemble + CPU-mine a block from a getblocktemplate result — an
    external miner exercising the BIP22 contract."""
    from bitcoincashplus_tpu.consensus.block import CBlock, CBlockHeader
    from bitcoincashplus_tpu.consensus.merkle import compute_merkle_root
    from bitcoincashplus_tpu.consensus.params import regtest_params
    from bitcoincashplus_tpu.consensus.pow import check_proof_of_work
    from bitcoincashplus_tpu.mining.assembler import bip34_coinbase_script_sig
    from bitcoincashplus_tpu.wallet.keys import address_to_script

    params = regtest_params()
    coinbase = CTransaction(
        vin=(CTxIn(COutPoint(), bip34_coinbase_script_sig(tmpl["height"]),
                   0xFFFFFFFF),),
        vout=(CTxOut(tmpl["coinbasevalue"],
                     address_to_script(payout_address, params)),),
    )
    vtx = (coinbase,
           *(CTransaction.from_bytes(bytes.fromhex(t["data"]))
             for t in tmpl["transactions"]))
    root, _ = compute_merkle_root([tx.txid for tx in vtx])
    header = CBlockHeader(
        version=tmpl["version"],
        hash_prev_block=hex_to_hash(tmpl["previousblockhash"]),
        hash_merkle_root=root,
        time=tmpl["curtime"],
        bits=int(tmpl["bits"], 16),
        nonce=0,
    )
    for nonce in range(1 << 20):  # regtest difficulty: a few tries suffice
        h = header.with_nonce(nonce)
        if check_proof_of_work(h.get_hash(), h.bits, params.consensus):
            return CBlock(h, vtx)
    raise AssertionError("failed to mine template")


def _spend_coinbase(node, coinbase_txid_hex: str, to_key: CKey, amount: int,
                    fee: int = 2000) -> str:
    """Build + sign a P2PKH spend of a (mature) coinbase output."""
    cb = node.rpc.getrawtransaction(coinbase_txid_hex, True)
    value = int(round(cb["vout"][0]["value"] * 1e8))
    spk = bytes.fromhex(cb["vout"][0]["scriptPubKey"]["hex"])
    tx = CTransaction(
        vin=(CTxIn(COutPoint(hex_to_hash(coinbase_txid_hex), 0)),),
        vout=(CTxOut(amount, to_key.p2pkh_script()),
              CTxOut(value - amount - fee, KEY.p2pkh_script())),
    )
    signed = sign_transaction(
        tx, [(spk, value)],
        lambda ident: KEY if ident == KEY.pubkey_hash else None,
        SIGHASH_ALL,
        enable_forkid=True,  # regtest uahf_height=0: FORKID is standard
    )
    return signed.serialize().hex()


def test_single_node_end_to_end():
    with FunctionalFramework(num_nodes=1,
                             extra_args=[["-txindex", "-listen=0"]]) as f:
        node = f.nodes[0]
        params_addr = _regtest_address(KEY)

        # -- mine via RPC ------------------------------------------------
        assert node.rpc.getblockcount() == 0
        hashes = node.rpc.generatetoaddress(101, params_addr)
        assert len(hashes) == 101
        assert node.rpc.getblockcount() == 101
        info = node.rpc.getblockchaininfo()
        assert info["blocks"] == 101 and info["chain"] == "regtest"

        # -- second concurrent client ------------------------------------
        second = RPCClient(port=node.rpc_port, datadir=node.datadir)
        assert second.getbestblockhash() == node.rpc.getbestblockhash()

        # -- raw tx into the mempool -------------------------------------
        block1 = node.rpc.getblock(hashes[0], 2)
        coinbase_txid = block1["tx"][0]["txid"]
        raw = _spend_coinbase(node, coinbase_txid, CKey(0xBEEF), 10_0000_0000)
        txid = node.rpc.sendrawtransaction(raw)
        assert txid in node.rpc.getrawmempool()
        entry = node.rpc.getmempoolentry(txid)
        assert entry["ancestorcount"] == 1

        # double-spend conflict is rejected
        raw2 = _spend_coinbase(node, coinbase_txid, CKey(0xD00D), 9_0000_0000)
        with pytest.raises(JSONRPCException) as e:
            node.rpc.sendrawtransaction(raw2)
        assert e.value.code == -26  # RPC_VERIFY_REJECTED

        # -- template contains the tx, fee-ordered -----------------------
        tmpl = node.rpc.getblocktemplate()
        assert tmpl["height"] == 102
        assert any(t["txid"] == txid for t in tmpl["transactions"])

        # -- mine it; mempool drains; txindex answers --------------------
        node.rpc.generatetoaddress(1, params_addr)
        assert node.rpc.getrawmempool() == []
        got = node.rpc.getrawtransaction(txid, True)
        assert got["confirmations"] == 1
        assert got["blockhash"] == node.rpc.getbestblockhash()

        # -- getblocktemplate -> external miner -> submitblock ------------
        tmpl = node.rpc.getblocktemplate()
        block = _mine_template(tmpl, params_addr)
        assert node.rpc.submitblock(block.serialize().hex()) is None
        assert node.rpc.getbestblockhash() == block.hash_hex
        # resubmission reports duplicate, like the reference
        assert node.rpc.submitblock(block.serialize().hex()) == "duplicate"

        # -- gettpuinfo observability ------------------------------------
        tpu = node.rpc.gettpuinfo()
        assert "batch" in tpu and "connectblock" in tpu
        assert tpu["connectblock"]["blocks"] >= 102

        # -- lock-order sentinel (ISSUE 15): the framework runs every
        # node under BCP_LOCKWATCH=1, so by now the real lock sites have
        # been exercised through mining/mempool/RPC — the acquisition
        # graph must be live, cs_main watched, and CYCLE-FREE (a lock-
        # order inversion introduced by a patch fails here even if the
        # schedules never actually deadlocked during the run)
        lw = tpu["lockwatch"]
        assert lw["enabled"] is True
        assert "cs_main" in lw["locks"]
        assert lw["acquisitions_total"] > 0
        assert lw["inversions"] == 0, lw["cycles"]

        # -- clean restart resumes (chain AND mempool) --------------------
        block2 = node.rpc.getblock(hashes[1], 2)
        raw3 = _spend_coinbase(node, block2["tx"][0]["txid"],
                               CKey(0xF00D), 10_0000_0000)
        persisted_txid = node.rpc.sendrawtransaction(raw3)
        node.rpc.prioritisetransaction(persisted_txid, 0, 5000)
        tip = node.rpc.getbestblockhash()
        height = node.rpc.getblockcount()
        node.stop()
        node.start(extra=["-txindex", "-listen=0"])
        assert node.rpc.getblockcount() == height
        assert node.rpc.getbestblockhash() == tip
        # mempool.dat round-trip: the tx is back, with its fee delta
        assert node.rpc.getrawmempool() == [persisted_txid]
        entry = node.rpc.getmempoolentry(persisted_txid)
        assert entry["modifiedfee"] == pytest.approx(entry["fee"] + 5000 / 1e8)
        node.rpc.generatetoaddress(1, params_addr)  # mine it out
        assert node.rpc.getrawmempool() == []
        height += 1
        # chain still extends after restart
        node.rpc.generatetoaddress(1, params_addr)
        assert node.rpc.getblockcount() == height + 1

        # -- -reindex reproduces the same chainstate ----------------------
        # (run before the kill-9 section so the blk files exactly match the
        # active chain — a crash leaves orphaned blocks in the files, which
        # -reindex correctly resurrects if they carry more work)
        best_before = node.rpc.getbestblockhash()
        height_before = node.rpc.getblockcount()
        utxo_before = node.rpc.gettxoutsetinfo()
        node.stop()
        node.start(extra=["-txindex", "-listen=0", "-reindex"])
        assert node.rpc.getblockcount() == height_before
        assert node.rpc.getbestblockhash() == best_before
        utxo_after = node.rpc.gettxoutsetinfo()
        assert utxo_after["txouts"] == utxo_before["txouts"]
        assert utxo_after["total_amount"] == utxo_before["total_amount"]

        # -- kill -9 resumes (crash safety, SURVEY §6.3) ------------------
        node.rpc.generatetoaddress(5, params_addr)
        height_before_kill = node.rpc.getblockcount()
        node.kill9()
        node.start(extra=["-txindex", "-listen=0"])
        # never behind the last flush point (flushinterval=8) and never
        # corrupted; re-mining works
        resumed = node.rpc.getblockcount()
        assert resumed >= height_before_kill - 8
        assert node.rpc.verifychain(3, 6)
        node.rpc.generatetoaddress(1, params_addr)
        assert node.rpc.getblockcount() == resumed + 1


def test_prune_mode():
    """-prune=1 + pruneblockchain: old block files are shed, index rows
    lose HAVE_DATA, the node keeps validating and extending; -txindex
    with -prune refuses to start (feature_pruning.py essentials)."""
    with FunctionalFramework(
        num_nodes=1,
        extra_args=[["-prune=1", "-maxblockfilesize=20000", "-listen=0"]],
    ) as f:
        node = f.nodes[0]
        addr = node.rpc.getnewaddress()
        # ~400 tiny blocks across many 20kB files (tip-288 must clear
        # the first file's top height for anything to be prunable)
        for _ in range(8):
            node.rpc.generatetoaddress(50, addr)
        assert node.rpc.getblockcount() == 400
        info = node.rpc.getblockchaininfo()
        assert info["pruned"] is True

        import glob
        import os
        sizes_before = {
            p: os.path.getsize(p)
            for p in glob.glob(os.path.join(node.datadir, "blocks", "blk*.dat"))
        }
        assert sum(1 for s in sizes_before.values() if s > 0) > 3

        kept_from = node.rpc.pruneblockchain(400)
        assert 0 < kept_from <= 400 - 288 + 1
        sizes_after = {
            p: os.path.getsize(p)
            for p in glob.glob(os.path.join(node.datadir, "blocks", "blk*.dat"))
        }
        n_emptied = sum(1 for p, s in sizes_after.items()
                        if s == 0 and sizes_before.get(p, 0) > 0)
        assert n_emptied >= 1, "no block file was pruned"
        info = node.rpc.getblockchaininfo()
        assert info["pruneheight"] > 0

        # pruned block data is gone; headers remain
        early = node.rpc.getblockhash(1)
        from bitcoincashplus_tpu.rpc.client import JSONRPCException
        with pytest.raises(JSONRPCException):
            node.rpc.getblock(early)
        assert node.rpc.getblockheader(early)["height"] == 1

        # node keeps mining + restarts cleanly with the pruned state
        node.rpc.generatetoaddress(2, addr)
        node.stop()
        node.start(extra=["-prune=1", "-maxblockfilesize=20000", "-listen=0"])
        assert node.rpc.getblockcount() == 402
        assert node.rpc.getblockchaininfo()["pruned"] is True
        node.rpc.generatetoaddress(1, addr)

    # -txindex + -prune must refuse to start
    import subprocess
    f2 = FunctionalFramework(num_nodes=1,
                             extra_args=[["-prune=1", "-txindex", "-listen=0"]])
    try:
        f2.__enter__()
        started = True
    except Exception:
        started = False
    finally:
        try:
            f2.__exit__(None, None, None)
        except Exception:
            pass
    assert not started, "-prune with -txindex must be rejected"


def test_getblocktemplate_proposal_mode():
    """BIP22 proposal mode: a valid candidate returns null; a corrupted
    one returns the reject reason; wrong prevblock is inconclusive."""
    with FunctionalFramework(num_nodes=1,
                             extra_args=[["-listen=0"]]) as f:
        node = f.nodes[0]
        addr = _regtest_address(KEY)
        node.rpc.generatetoaddress(101, addr)

        tmpl = node.rpc.getblocktemplate()
        block = _mine_template(tmpl, addr)
        raw = block.serialize().hex()
        assert node.rpc.getblocktemplate(
            {"mode": "proposal", "data": raw}) is None

        # corrupt the merkle root -> bad-txnmrklroot
        from bitcoincashplus_tpu.consensus.block import CBlock
        bad = CBlock.from_bytes(bytes.fromhex(raw))
        hdr = bad.header
        import dataclasses
        bad_hdr = dataclasses.replace(
            hdr, hash_merkle_root=b"\x55" * 32)
        bad_raw = CBlock(bad_hdr, bad.vtx).serialize().hex()
        reason = node.rpc.getblocktemplate(
            {"mode": "proposal", "data": bad_raw})
        assert reason is not None and ("mrkl" in reason or "merkle" in reason)

        # stale prevblock -> inconclusive
        node.rpc.generatetoaddress(1, addr)
        assert node.rpc.getblocktemplate(
            {"mode": "proposal", "data": raw}
        ) == "inconclusive-not-best-prevblk"

        # the proposal dry-run must not have mutated state
        assert node.rpc.getblockcount() == 102
        # estimators answer (deprecated surface)
        assert node.rpc.estimatepriority(6) == -1
        assert node.rpc.estimatesmartpriority(6)["priority"] == -1


def test_linearize_and_loadblock(tmp_path):
    """tools/linearize.py exports the chain; -loadblock imports it into a
    fresh node (contrib/linearize + init.cpp vImportFiles parity)."""
    import subprocess
    import sys

    with FunctionalFramework(num_nodes=2,
                             extra_args=[["-listen=0"], ["-listen=0"]]) as f:
        a, b = f.nodes
        addr = a.rpc.getnewaddress()
        a.rpc.generatetoaddress(20, addr)
        best = a.rpc.getbestblockhash()

        bootstrap = str(tmp_path / "bootstrap.dat")
        out = subprocess.run(
            [sys.executable, "tools/linearize.py",
             "--datadir", a.datadir, "--rpcport", str(a.rpc_port),
             "--out", bootstrap],
            capture_output=True, text=True, timeout=120,
        )
        assert out.returncode == 0, out.stderr
        assert "wrote 21 blocks" in out.stdout
        import os
        assert os.path.getsize(bootstrap) > 21 * 80

        # fresh node ingests it at startup via -loadblock
        assert b.rpc.getblockcount() == 0
        b.stop()
        b.start(extra=["-listen=0", f"-loadblock={bootstrap}"])
        assert b.rpc.getblockcount() == 20
        assert b.rpc.getbestblockhash() == best
