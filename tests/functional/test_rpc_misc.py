"""signrawtransaction, wallet tx history, and ban-list RPC functional
coverage (rpcwallet/rpcdump/rpc net parity additions)."""

import time

import pytest

from .framework import FunctionalFramework
from .test_node_basic import KEY, _regtest_address


def test_signraw_history_and_bans():
    with FunctionalFramework(num_nodes=1,
                             extra_args=[["-txindex"]]) as f:
        node = f.nodes[0]
        addr = node.rpc.getnewaddress()
        node.rpc.generatetoaddress(101, addr)

        # -- wallet history ---------------------------------------------
        txs = node.rpc.listtransactions("*", 5)
        assert txs and all(t["category"] in ("generate", "immature")
                           for t in txs)
        dest = _regtest_address(KEY)
        txid = node.rpc.sendtoaddress(dest, 2.0)
        entry = node.rpc.gettransaction(txid)
        assert entry["category"] == "send"
        assert entry["confirmations"] == 0
        node.rpc.generatetoaddress(1, addr)
        entry = node.rpc.gettransaction(txid)
        assert entry["confirmations"] == 1 and "blockhash" in entry
        newest = node.rpc.listtransactions("*", 3)
        assert any(t["txid"] == txid for t in newest)

        # -- signrawtransaction with wallet keys ------------------------
        utxos = node.rpc.listunspent()
        u = utxos[0]
        raw = node.rpc.createrawtransaction(
            [{"txid": u["txid"], "vout": u["vout"]}],
            {dest: round(u["amount"] - 0.01, 8)},
        )
        res = node.rpc.signrawtransaction(raw)
        assert res["complete"], res
        sent = node.rpc.sendrawtransaction(res["hex"])
        assert sent in node.rpc.getrawmempool()

        # -- signrawtransaction with explicit key + prevtxs -------------
        # fund the external key, then sign its spend without the wallet
        ext_wif = None
        from bitcoincashplus_tpu.consensus.params import regtest_params

        ext_wif = KEY.to_wif(regtest_params())
        node.rpc.generatetoaddress(1, addr)  # confirm the 2.0 send to dest
        # find dest's utxo via gettxout on the earlier send
        funding = node.rpc.getrawtransaction(txid, True)
        vout_n = next(o["n"] for o in funding["vout"]
                      if o.get("scriptPubKey", {}).get("addresses") == [dest]
                      or dest in str(o))
        spk = funding["vout"][vout_n]["scriptPubKey"]["hex"]
        raw2 = node.rpc.createrawtransaction(
            [{"txid": txid, "vout": vout_n}], {addr: 1.99},
        )
        res2 = node.rpc.signrawtransaction(
            raw2,
            [{"txid": txid, "vout": vout_n, "scriptPubKey": spk,
              "amount": 2.0}],
            [ext_wif],
        )
        assert res2["complete"], res2
        sent2 = node.rpc.sendrawtransaction(res2["hex"])
        assert sent2 in node.rpc.getrawmempool()

        # incomplete: no key available
        res3 = node.rpc.signrawtransaction(
            raw2,
            [{"txid": txid, "vout": vout_n, "scriptPubKey": spk,
              "amount": 2.0}],
            [],
        )
        # empty key list -> wallet keys used; wallet lacks dest's key
        assert not res3["complete"] and res3["errors"]

        # -- ban list ----------------------------------------------------
        node.rpc.ping()
        node.rpc.setban("203.0.113.7", "add", 3600)
        banned = node.rpc.listbanned()
        assert any(b["address"] == "203.0.113.7" for b in banned)
        node.rpc.setban("203.0.113.7", "remove")
        assert node.rpc.listbanned() == []
        node.rpc.setban("203.0.113.8", "add")
        node.rpc.clearbanned()
        assert node.rpc.listbanned() == []


def test_getblockstats_and_walletnotify(tmp_path):
    import glob
    import os

    from .framework import wait_until

    notify = f"-walletnotify=touch {tmp_path}/wtx_%s"
    with FunctionalFramework(num_nodes=1,
                             extra_args=[["-listen=0", notify]]) as f:
        node = f.nodes[0]
        addr = node.rpc.getnewaddress()
        node.rpc.generatetoaddress(101, addr)
        dest = _regtest_address(KEY)
        txid = node.rpc.sendtoaddress(dest, 3.0)
        tip_hash = node.rpc.generatetoaddress(1, addr)[0]

        # stats by hash and by height agree; fee data comes from undo
        stats = node.rpc.getblockstats(tip_hash)
        assert stats["height"] == 102 and stats["txs"] == 2
        assert stats["totalfee"] > 0
        assert stats["subsidy"] == 50 * 100_000_000
        assert stats["ins"] >= 1 and stats["outs"] >= 3
        by_height = node.rpc.getblockstats(102)
        assert by_height == stats
        empty = node.rpc.getblockstats(50)
        assert empty["txs"] == 1 and empty["totalfee"] == 0

        # walletnotify fired for the confirmed wallet tx (the send)
        wait_until(
            lambda: os.path.exists(os.path.join(str(tmp_path), f"wtx_{txid}")),
            timeout=15,
        )
        assert glob.glob(os.path.join(str(tmp_path), "wtx_*"))


def test_longpoll_and_wait_rpcs():
    """getblocktemplate longpoll + waitfornewblock block until the chain
    moves; getchaintxstats and getaddednodeinfo answer."""
    import threading

    from bitcoincashplus_tpu.rpc.client import RPCClient

    with FunctionalFramework(num_nodes=1) as f:
        node = f.nodes[0]
        addr = node.rpc.getnewaddress()
        node.rpc.generatetoaddress(5, addr)

        # -- longpoll: blocks until a new block arrives ------------------
        tmpl = node.rpc.getblocktemplate()
        lpid = tmpl["longpollid"]
        result = {}

        def longpoller():
            c = RPCClient(port=node.rpc_port, datadir=node.datadir)
            c.timeout = 90
            result["tmpl"] = c.call("getblocktemplate", {"longpollid": lpid})

        t = threading.Thread(target=longpoller)
        t.start()
        time.sleep(1.0)
        assert t.is_alive()  # still blocked — nothing changed
        node.rpc.generatetoaddress(1, addr)
        t.join(30)
        assert not t.is_alive()
        assert result["tmpl"]["height"] == tmpl["height"] + 1

        # -- waitfornewblock --------------------------------------------
        result2 = {}

        def waiter():
            c = RPCClient(port=node.rpc_port, datadir=node.datadir)
            c.timeout = 90
            result2["tip"] = c.call("waitfornewblock", 60_000)

        t2 = threading.Thread(target=waiter)
        t2.start()
        time.sleep(0.5)
        assert t2.is_alive()
        mined = node.rpc.generatetoaddress(1, addr)[0]
        t2.join(30)
        assert not t2.is_alive()
        assert result2["tip"]["hash"] == mined

        # waitforblockheight for an already-reached height returns now
        h = node.rpc.getblockcount()
        got = node.rpc.waitforblockheight(h, 1000)
        assert got["height"] == h

        # -- getchaintxstats --------------------------------------------
        stats = node.rpc.getchaintxstats(5)
        assert stats["window_block_count"] == 5
        assert stats["window_tx_count"] == 5  # coinbase-only blocks
        assert stats["txcount"] == node.rpc.getblockcount() + 1  # + genesis

        # -- getaddednodeinfo -------------------------------------------
        assert node.rpc.getaddednodeinfo() == []
        node.rpc.addnode("127.0.0.1:1", "add")  # nothing listens there
        info = node.rpc.getaddednodeinfo()
        assert info[0]["addednode"] == "127.0.0.1:1"
        assert info[0]["connected"] is False
        node.rpc.addnode("127.0.0.1:1", "remove")
        assert node.rpc.getaddednodeinfo() == []


def test_fee_estimator_rpc():
    """estimatefee/estimatesmartfee over the bucketed estimator: cold start
    errors, then confirmed wallet txs feed per-target estimates."""
    with FunctionalFramework(num_nodes=1) as f:
        node = f.nodes[0]
        # cold: estimatefee -1, smart falls back to the relay floor + error
        assert node.rpc.estimatefee(2) == -1
        cold = node.rpc.estimatesmartfee(2)
        assert cold["errors"]
        addr = node.rpc.getnewaddress()
        node.rpc.generatetoaddress(103, addr)
        # the estimator needs reference-scale samples (~50 decayed
        # observations, EstimateMedianVal's sufficientTxVal/(1-decay)
        # gate): a handful of txs must stay cold...
        for _ in range(6):
            node.rpc.sendtoaddress(node.rpc.getnewaddress(), 0.5)
        node.rpc.generatetoaddress(1, addr)
        assert node.rpc.estimatefee(2) == -1
        # ...and ~60 confirmed wallet txs flip it warm
        for _ in range(7):
            for _ in range(9):
                node.rpc.sendtoaddress(node.rpc.getnewaddress(), 0.2)
            node.rpc.generatetoaddress(1, addr)
        est = node.rpc.estimatesmartfee(2)
        assert "errors" not in est, est
        assert est["feerate"] > 0
        assert est["blocks"] >= 1
        # estimatefee agrees within the answering horizon
        assert node.rpc.estimatefee(est["blocks"]) > 0
