"""Wallet RPC surface functional test — rpcwallet.cpp flows against a real
bcpd process: mine to a wallet address, spend, encrypt, restart (wallet file
reload + rescan), unlock, spend again."""

import pytest

from .framework import FunctionalFramework, wait_until
from .test_node_basic import KEY, _regtest_address


def _rpc_error_code(exc_info):
    return getattr(exc_info.value, "code", None)


def test_wallet_rpc_lifecycle():
    with FunctionalFramework(num_nodes=1,
                             extra_args=[["-listen=0"]]) as f:
        node = f.nodes[0]
        addr = node.rpc.getnewaddress()
        assert addr.startswith(("m", "n"))  # regtest P2PKH prefixes

        node.rpc.generatetoaddress(101, addr)
        bal = node.rpc.getbalance()
        assert bal == 100.0  # two mature 50-coin coinbases

        # received-by accounting counts all receipts at >= minconf
        assert node.rpc.getreceivedbyaddress(addr) == 101 * 50.0
        rows = node.rpc.listreceivedbyaddress()
        assert any(r["address"] == addr and r["amount"] == 101 * 50.0
                   for r in rows)

        # plain spend to a foreign address
        dest = _regtest_address(KEY)
        txid = node.rpc.sendtoaddress(dest, 1.5)
        assert txid in node.rpc.getrawmempool()
        unspent = node.rpc.listunspent()
        assert all(u["spendable"] for u in unspent)

        # encrypt: wallet locks; spending fails with unlock-needed
        node.rpc.encryptwallet("secret phrase")
        info = node.rpc.getwalletinfo()
        assert info["unlocked_until"] == 0
        from bitcoincashplus_tpu.rpc.client import JSONRPCException as RPCClientError

        with pytest.raises(RPCClientError):
            node.rpc.sendtoaddress(dest, 1.0)
        with pytest.raises(RPCClientError):
            node.rpc.getnewaddress()

        # wrong passphrase rejected
        with pytest.raises(RPCClientError):
            node.rpc.walletpassphrase("wrong", 60)

        node.rpc.walletpassphrase("secret phrase", 600)
        assert node.rpc.getwalletinfo()["unlocked_until"] > 0
        txid2 = node.rpc.sendtoaddress(dest, 1.0)
        assert txid2 in node.rpc.getrawmempool()
        node.rpc.walletlock()
        with pytest.raises(RPCClientError):
            node.rpc.sendtoaddress(dest, 1.0)

        # restart: encrypted wallet file reloads, rescan restores coins
        node.stop()
        node.start()
        info = node.rpc.getwalletinfo()
        assert info["unlocked_until"] == 0  # still encrypted+locked
        assert node.rpc.getbalance() > 0  # rescan found the coins
        node.rpc.walletpassphrase("secret phrase", 60)
        txid3 = node.rpc.sendtoaddress(dest, 0.5)
        assert txid3 in node.rpc.getrawmempool()

        # passphrase change
        node.rpc.walletpassphrasechange("secret phrase", "new phrase")
        node.rpc.walletlock()
        with pytest.raises(RPCClientError):
            node.rpc.walletpassphrase("secret phrase", 60)
        node.rpc.walletpassphrase("new phrase", 60)


def test_hd_dump_import_backup():
    """dumpwallet/importwallet/backupwallet + HD metadata over RPC."""
    import os

    with FunctionalFramework(num_nodes=2,
                             extra_args=[["-listen=0"], ["-listen=0"]]) as f:
        node, node2 = f.nodes
        addr = node.rpc.getnewaddress()
        node.rpc.generatetoaddress(101, addr)
        info = node.rpc.getwalletinfo()
        assert "hdmasterkeyid" in info and len(info["hdmasterkeyid"]) == 40

        dump_path = os.path.join(node.datadir, "dump.txt")
        node.rpc.dumpwallet(dump_path)
        with open(dump_path) as fh:
            dump = fh.read()
        assert "extended private masterkey: xprv" in dump
        assert "hdkeypath=m/0'/0'/0'" in dump
        wif = node.rpc.dumpprivkey(addr)
        assert wif in dump

        backup_path = os.path.join(node.datadir, "wallet.bak")
        node.rpc.backupwallet(backup_path)
        assert os.path.exists(backup_path)

        # import the dump into the second node; it rescans and sees the funds
        assert node2.rpc.getbalance() == 0
        node2.rpc.importwallet(dump_path)
        # node2 hasn't seen node1's chain; sync it via submitblock
        for h in range(1, node.rpc.getblockcount() + 1):
            raw = node.rpc.getblock(node.rpc.getblockhash(h), 0)
            node2.rpc.submitblock(raw)
        assert node2.rpc.getblockcount() == node.rpc.getblockcount()
        assert node2.rpc.getbalance() == node.rpc.getbalance()


def test_wallet_rpc_breadth():
    """sendmany / lockunspent / listsinceblock / settxfee /
    abandontransaction / createmultisig / addmultisigaddress /
    fundrawtransaction against a live node."""
    from bitcoincashplus_tpu.rpc.client import JSONRPCException

    with FunctionalFramework(num_nodes=1,
                             extra_args=[["-listen=0"]]) as f:
        node = f.nodes[0]
        addr = node.rpc.getnewaddress()
        node.rpc.generatetoaddress(103, addr)
        base_hash = node.rpc.getbestblockhash()

        # -- sendmany: one tx, two recipients ---------------------------
        d1 = _regtest_address(KEY)
        from bitcoincashplus_tpu.wallet.keys import CKey as _CK
        d2 = _CK(0xD2D2).p2pkh_address(__import__(
            "bitcoincashplus_tpu.consensus.params",
            fromlist=["regtest_params"]).regtest_params())
        txid = node.rpc.sendmany("", {d1: 1.0, d2: 2.0})
        raw = node.rpc.getrawtransaction(txid, True)
        values = sorted(o["value"] for o in raw["vout"])
        assert 1.0 in values and 2.0 in values

        # -- listsinceblock sees it; after mining, still above base -----
        since = node.rpc.listsinceblock(base_hash)
        assert any(t["txid"] == txid for t in since["transactions"])
        node.rpc.generatetoaddress(1, addr)
        since = node.rpc.listsinceblock(base_hash)
        assert any(t["txid"] == txid and t["confirmations"] == 1
                   for t in since["transactions"])

        # -- lockunspent excludes a coin from selection ------------------
        unspent = node.rpc.listunspent()
        big = max(unspent, key=lambda u: u["amount"])
        node.rpc.lockunspent(False, [{"txid": big["txid"], "vout": big["vout"]}])
        locked = node.rpc.listlockunspent()
        assert {"txid": big["txid"], "vout": big["vout"]} in locked
        assert not any(u["txid"] == big["txid"] and u["vout"] == big["vout"]
                       for u in node.rpc.listunspent())
        node.rpc.lockunspent(True)  # unlock-all
        assert node.rpc.listlockunspent() == []

        # -- settxfee raises the paid fee -------------------------------
        assert node.rpc.settxfee(0.0005) is True
        txid2 = node.rpc.sendtoaddress(d1, 0.5)
        entry = node.rpc.getmempoolentry(txid2)
        assert entry["fee"] >= 0.0005 - 1e-8

        # -- abandontransaction: in-mempool txs are not eligible --------
        with pytest.raises(JSONRPCException):
            node.rpc.abandontransaction(txid2)

        # -- multisig ----------------------------------------------------
        k1, k2 = _CK(0x111), _CK(0x222)
        ms = node.rpc.createmultisig(2, [k1.pubkey.hex(), k2.pubkey.hex()])
        assert ms["address"].startswith("2")  # regtest P2SH prefix
        assert ms["redeemScript"].startswith("52")  # OP_2
        msaddr = node.rpc.addmultisigaddress(2, [k1.pubkey.hex(),
                                                 k2.pubkey.hex()])
        assert msaddr == ms["address"]
        # watched script: a payment to it shows up in wallet tracking
        node.rpc.generatetoaddress(1, addr)  # clear mempool
        txid3 = node.rpc.sendtoaddress(msaddr, 3.0)
        node.rpc.generatetoaddress(1, addr)
        got = node.rpc.gettransaction(txid3)
        assert got["confirmations"] == 1

        # -- fundrawtransaction ------------------------------------------
        raw_unfunded = node.rpc.createrawtransaction([], {d1: 7.0})
        funded = node.rpc.fundrawtransaction(raw_unfunded)
        signed = node.rpc.signrawtransaction(funded["hex"])
        assert signed["complete"] is True
        txid4 = node.rpc.sendrawtransaction(signed["hex"])
        assert txid4 in node.rpc.getrawmempool()


def test_importmulti():
    from bitcoincashplus_tpu.consensus.params import regtest_params
    from bitcoincashplus_tpu.wallet.keys import CKey

    with FunctionalFramework(num_nodes=1,
                             extra_args=[["-listen=0"]]) as f:
        node = f.nodes[0]
        addr = node.rpc.getnewaddress()
        node.rpc.generatetoaddress(101, addr)
        params = regtest_params()
        k1, k2 = CKey(0xA1), CKey(0xA2)
        watch_addr = CKey(0xA3).p2pkh_address(params)
        # pay all three BEFORE importing; importmulti's rescan must find them
        node.rpc.sendtoaddress(k1.p2pkh_address(params), 1.0)
        node.rpc.sendtoaddress(k2.p2pkh_address(params), 2.0)
        node.rpc.sendtoaddress(watch_addr, 3.0)
        node.rpc.generatetoaddress(1, addr)

        res = node.rpc.importmulti([
            {"keys": [k1.to_wif(params)], "timestamp": 0},
            {"pubkeys": [k2.pubkey.hex()], "timestamp": 0},
            {"scriptPubKey": {"address": watch_addr}, "timestamp": 0},
            {"scriptPubKey": {"address": "notanaddress"}, "timestamp": 0},
            # valid WIF + bad pubkey in ONE request: must fail atomically
            {"keys": [CKey(0xA4).to_wif(params)], "pubkeys": ["zz"],
             "timestamp": 0},
            {"keys": [CKey(0xA5).to_wif(params)]},  # missing timestamp
        ])
        assert [r["success"] for r in res] == [True, True, True,
                                               False, False, False]
        assert res[3]["error"]["code"] == -5
        assert "timestamp" in res[5]["error"]["message"]
        # the atomically-failed request imported NOTHING
        assert node.rpc.dumpprivkey(
            k1.p2pkh_address(params)) == k1.to_wif(params)
        try:
            node.rpc.dumpprivkey(CKey(0xA4).p2pkh_address(params))
            raise AssertionError("partial import leaked a key")
        except Exception:
            pass
        unspent = node.rpc.listunspent()
        # k1's coin is spendable (private key imported); k2 + watch are not
        spendable = {round(u["amount"], 8) for u in unspent if u["spendable"]}
        watchonly = {round(u["amount"], 8) for u in unspent if not u["spendable"]}
        assert 1.0 in spendable
        assert {2.0, 3.0} <= watchonly
