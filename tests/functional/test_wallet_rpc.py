"""Wallet RPC surface functional test — rpcwallet.cpp flows against a real
bcpd process: mine to a wallet address, spend, encrypt, restart (wallet file
reload + rescan), unlock, spend again."""

import pytest

from .framework import FunctionalFramework, wait_until
from .test_node_basic import KEY, _regtest_address


def _rpc_error_code(exc_info):
    return getattr(exc_info.value, "code", None)


def test_wallet_rpc_lifecycle():
    with FunctionalFramework(num_nodes=1,
                             extra_args=[["-listen=0"]]) as f:
        node = f.nodes[0]
        addr = node.rpc.getnewaddress()
        assert addr.startswith(("m", "n"))  # regtest P2PKH prefixes

        node.rpc.generatetoaddress(101, addr)
        bal = node.rpc.getbalance()
        assert bal == 100.0  # two mature 50-coin coinbases

        # received-by accounting counts all receipts at >= minconf
        assert node.rpc.getreceivedbyaddress(addr) == 101 * 50.0
        rows = node.rpc.listreceivedbyaddress()
        assert any(r["address"] == addr and r["amount"] == 101 * 50.0
                   for r in rows)

        # plain spend to a foreign address
        dest = _regtest_address(KEY)
        txid = node.rpc.sendtoaddress(dest, 1.5)
        assert txid in node.rpc.getrawmempool()
        unspent = node.rpc.listunspent()
        assert all(u["spendable"] for u in unspent)

        # encrypt: wallet locks; spending fails with unlock-needed
        node.rpc.encryptwallet("secret phrase")
        info = node.rpc.getwalletinfo()
        assert info["unlocked_until"] == 0
        from bitcoincashplus_tpu.rpc.client import JSONRPCException as RPCClientError

        with pytest.raises(RPCClientError):
            node.rpc.sendtoaddress(dest, 1.0)
        with pytest.raises(RPCClientError):
            node.rpc.getnewaddress()

        # wrong passphrase rejected
        with pytest.raises(RPCClientError):
            node.rpc.walletpassphrase("wrong", 60)

        node.rpc.walletpassphrase("secret phrase", 600)
        assert node.rpc.getwalletinfo()["unlocked_until"] > 0
        txid2 = node.rpc.sendtoaddress(dest, 1.0)
        assert txid2 in node.rpc.getrawmempool()
        node.rpc.walletlock()
        with pytest.raises(RPCClientError):
            node.rpc.sendtoaddress(dest, 1.0)

        # restart: encrypted wallet file reloads, rescan restores coins
        node.stop()
        node.start()
        info = node.rpc.getwalletinfo()
        assert info["unlocked_until"] == 0  # still encrypted+locked
        assert node.rpc.getbalance() > 0  # rescan found the coins
        node.rpc.walletpassphrase("secret phrase", 60)
        txid3 = node.rpc.sendtoaddress(dest, 0.5)
        assert txid3 in node.rpc.getrawmempool()

        # passphrase change
        node.rpc.walletpassphrasechange("secret phrase", "new phrase")
        node.rpc.walletlock()
        with pytest.raises(RPCClientError):
            node.rpc.walletpassphrase("secret phrase", 60)
        node.rpc.walletpassphrase("new phrase", 60)
