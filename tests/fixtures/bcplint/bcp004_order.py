"""Seeded BCP004 violation: two methods take the same lock pair in
opposite orders — a latent deadlock the runtime may never hit."""


class TwoLocks:
    def ab(self):
        with self.a_lock:
            with self.b_lock:  # BCPLINT-EXPECT
                pass

    def ba(self):
        with self.b_lock:
            with self.a_lock:
                pass
