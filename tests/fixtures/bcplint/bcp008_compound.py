"""Seeded BCP008 violations: non-GIL-atomic compound mutations of
shared state reached from a concurrent root (executor submits) with no
lock held — the ``+=`` read-modify-write tear and the PR 7 sigcache
check-then-mutate interleave."""

from concurrent.futures import ThreadPoolExecutor


class Tally:
    def __init__(self):
        self.pool = ThreadPoolExecutor(max_workers=4)
        self.hits = 0
        self.cache = {}

    def bump(self):
        self.hits += 1  # BCPLINT-EXPECT

    def remember(self, key, value):
        if key not in self.cache:
            self.cache[key] = value  # BCPLINT-EXPECT-CHECK

    def serve(self, key, value):
        self.pool.submit(self.bump)
        self.pool.submit(self.remember, key, value)

    def close(self):
        self.pool.shutdown(wait=True)
