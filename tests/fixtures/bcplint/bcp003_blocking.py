"""Seeded BCP003 violation: fsync while cs_main is statically held."""

import os


class NodeLike:
    def flush(self, fd):
        with self.cs_main:
            os.fsync(fd)  # BCPLINT-EXPECT

    def ok_released(self, fd, fut):
        with self.cs_main:
            self.cs_main.release()
            try:
                fut.result()  # fine: cs_main explicitly released around it
            finally:
                self.cs_main.acquire()
