"""A tests-tree stand-in that never mentions the fixture fault site
(deliberately not test_-prefixed so pytest never collects it)."""

COVERED = "some_other_site"
