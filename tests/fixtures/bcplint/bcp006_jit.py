"""Seeded BCP006 violations: a traced-value coercion inside a jitted
body, and a devicewatch program registered with no shape budget."""

import jax


@jax.jit
def bad_coercion(x):
    return int(x) + 1  # BCPLINT-EXPECT


def register(dw):
    return dw.program("fixture_unbudgeted_prog")  # BCPLINT-EXPECT-PROGRAM
