"""Seeded BCP001 violation: a collector re-emits a native family name.

Never imported — parsed by tools/bcplint only (the golden corpus keeps
each check honest: if a refactor stops the rule from firing here, the
fixture test fails before the real tree can regress).
"""

from util import telemetry as tm  # noqa — AST-only, never imported

_DEPTH_G = tm.gauge("bcp_fix_depth", "native gauge owning its name")


def _families():
    return [
        {"name": "bcp_fix_depth", "type": "counter",  # BCPLINT-EXPECT
         "help": "re-emits the native family with a conflicting TYPE",
         "samples": [({}, 1.0)]},
    ]
