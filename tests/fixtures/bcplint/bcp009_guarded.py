"""Seeded BCP009 violation: an attribute declared guarded via the
trailing-comment convention is written without the declared lock held.
The compliant write in ``ok`` proves the rule only fires on the
unguarded site."""

import threading


class Ledger:
    def __init__(self):
        self.cs_lock = threading.Lock()
        self.total = 0  # GUARDED_BY(cs_lock)

    def ok(self):
        with self.cs_lock:
            self.total = 1

    def sneaky(self):
        self.total = 5  # BCPLINT-EXPECT
