"""Seeded BCP010 violation: a thread stored on ``self`` and started,
with no ``join()`` reachable from ``close()`` — the thread outlives its
owner (BCP002's register/unregister pairing extended to threads)."""

import threading


class Leaky:
    def __init__(self):
        self._worker = threading.Thread(  # BCPLINT-EXPECT
            target=self._run, daemon=True)

    def start(self):
        self._worker.start()

    def _run(self):
        pass

    def close(self):
        pass  # forgets self._worker.join()
