"""Seeded BCP007 violation: two spawned threads write the same
attribute, each under a *different* lock — every write site is locked,
but no single lock consistently guards the field, so the writes still
race. The same pattern (run with watched locks) trips the runtime
lockwatch sentinel via its opposite-order nested acquisitions — the
cross-check test ties the static and runtime halves together."""

import threading


class RaceBox:
    def __init__(self):
        self.a_lock = threading.Lock()
        self.b_lock = threading.Lock()
        self.latest = 0
        self.scratch_a = 0
        self.scratch_b = 0
        self._t1 = threading.Thread(target=self._writer_a, daemon=True)
        self._t2 = threading.Thread(target=self._writer_b, daemon=True)

    def start(self):
        self._t1.start()
        self._t2.start()

    def _writer_a(self):
        with self.a_lock:
            self.latest = 1  # BCPLINT-EXPECT
            with self.b_lock:
                self.scratch_a = 1

    def _writer_b(self):
        with self.b_lock:
            self.latest = 2
            with self.a_lock:
                self.scratch_b = 2

    def close(self):
        self._t1.join()
        self._t2.join()
