"""Seeded BCP005 violation: a declared fault site no test ever drills.
AST-only fixture (path shape matters: the SITES rule keys on
util/faults.py)."""

SITES = ("fixture_untested_site",)  # BCPLINT-EXPECT
