"""Seeded BCP004 violation via *explicit* acquire/release pairs: the
same lock pair taken in opposite orders, but through ``.acquire()`` /
``.release()`` statements instead of ``with`` blocks — the blind spot
the gateway/banlist idiom exposed (edges must be minted from
document-order pairs too)."""


class TwoLocksExplicit:
    def ab(self):
        self.a_lock.acquire()
        self.b_lock.acquire()  # BCPLINT-EXPECT
        self.b_lock.release()
        self.a_lock.release()

    def ba(self):
        self.b_lock.acquire()
        self.a_lock.acquire()
        self.a_lock.release()
        self.b_lock.release()
