"""Seeded BCP002 violation: register_collector with no unregister
reachable from close()."""


class Leaky:
    def __init__(self, registry):
        self.registry = registry
        registry.register_collector("leaky", self._families)  # BCPLINT-EXPECT

    def _families(self):
        return []

    def close(self):
        pass  # forgot registry.unregister_collector("leaky")
