"""Standardness policy matrix — IsStandardTx / AreInputsStandard / dust.

Mirrors src/test/policy tests + policyestimator-adjacent checks in
transaction_tests.cpp (the reference spreads these across suites).
"""

import pytest

from bitcoincashplus_tpu.consensus.tx import COutPoint, CTransaction, CTxIn, CTxOut
from bitcoincashplus_tpu.mempool.policy import (
    MAX_OP_RETURN_RELAY,
    are_inputs_standard,
    get_dust_threshold,
    get_min_relay_fee,
    is_standard_tx,
)
from bitcoincashplus_tpu.script import script as S
from bitcoincashplus_tpu.wallet.keys import CKey

KEY = CKey(0x1234)
P2PKH = KEY.p2pkh_script()
P2PK = bytes([len(KEY.pubkey)]) + KEY.pubkey + bytes([S.OP_CHECKSIG])


def _tx(vout, script_sig=b"\x51", version=1):
    return CTransaction(
        version=version,
        vin=(CTxIn(COutPoint(b"\x11" * 32, 0), script_sig),),
        vout=tuple(vout),
    )


class TestIsStandardTx:
    def test_p2pkh_standard(self):
        ok, reason = is_standard_tx(_tx([CTxOut(100_000, P2PKH)]))
        assert ok, reason

    def test_version_gate(self):
        ok, reason = is_standard_tx(_tx([CTxOut(100_000, P2PKH)], version=3))
        assert not ok and reason == "version"

    def test_nonstandard_script(self):
        # bare OP_TRUE output is not a standard template
        ok, reason = is_standard_tx(_tx([CTxOut(100_000, b"\x51")]))
        assert not ok and reason == "scriptpubkey"

    def test_scriptsig_not_pushonly(self):
        tx = _tx([CTxOut(100_000, P2PKH)], script_sig=bytes([S.OP_DUP]))
        ok, reason = is_standard_tx(tx)
        assert not ok and reason == "scriptsig-not-pushonly"

    def test_op_return_standard_within_limit(self):
        data = b"\x6a" + bytes([40]) + b"\xab" * 40  # OP_RETURN + push
        ok, reason = is_standard_tx(_tx([CTxOut(0, data), CTxOut(100_000, P2PKH)]))
        assert ok, reason

    def test_oversize_op_return(self):
        n = MAX_OP_RETURN_RELAY  # script longer than the cap
        data = b"\x6a\x4c" + bytes([n]) + b"\xab" * n
        ok, reason = is_standard_tx(_tx([CTxOut(0, data)]))
        assert not ok and reason == "oversize-op-return"

    def test_multi_op_return(self):
        data = b"\x6a\x01\xab"
        ok, reason = is_standard_tx(_tx([CTxOut(0, data), CTxOut(0, data)]))
        assert not ok and reason == "multi-op-return"

    def test_dust_rejected(self):
        ok, reason = is_standard_tx(_tx([CTxOut(545, P2PKH)]))
        assert not ok and reason == "dust"
        ok, reason = is_standard_tx(_tx([CTxOut(546, P2PKH)]))
        assert ok, reason


class TestDustThreshold:
    def test_p2pkh_is_546(self):
        """ADVICE r2 #4: threshold must derive from serialized size — the
        canonical 546 for a 34-byte P2PKH output at 1000 sat/kB."""
        assert get_dust_threshold(CTxOut(0, P2PKH)) == 546

    def test_larger_script_larger_threshold(self):
        big = CTxOut(0, b"\x51" * 100)
        assert get_dust_threshold(big) > get_dust_threshold(CTxOut(0, P2PKH))

    def test_scales_with_rate(self):
        out = CTxOut(0, P2PKH)
        assert get_dust_threshold(out, rate=2000) == 2 * 546


class TestMinRelayFee:
    def test_fee_math(self):
        assert get_min_relay_fee(1000) == 1000  # 1 sat/byte at default rate
        assert get_min_relay_fee(250) == 250
        # sub-1-sat truncation floors at the rate (CFeeRate::GetFee)
        assert get_min_relay_fee(0) == 1000


class TestAreInputsStandard:
    def test_p2pkh_input_ok(self):
        tx = _tx([CTxOut(100_000, P2PKH)])
        assert are_inputs_standard(tx, [CTxOut(200_000, P2PKH)])

    def test_nonstandard_prevout(self):
        tx = _tx([CTxOut(100_000, P2PKH)])
        assert not are_inputs_standard(tx, [CTxOut(200_000, b"\x51")])

    def test_p2sh_sigop_cap(self):
        from bitcoincashplus_tpu.crypto.hashes import hash160

        # redeem script with 16 CHECKSIGs exceeds MAX_P2SH_SIGOPS=15
        redeem = bytes([S.OP_CHECKSIG] * 16) + bytes([S.OP_TRUE])
        p2sh = bytes([S.OP_HASH160, 20]) + hash160(redeem) + bytes([S.OP_EQUAL])
        sig = bytes([len(redeem)]) + redeem
        tx = _tx([CTxOut(100_000, P2PKH)], script_sig=sig)
        assert not are_inputs_standard(tx, [CTxOut(200_000, p2sh)])

        # 15 sigops is allowed
        redeem_ok = bytes([S.OP_CHECKSIG] * 15) + bytes([S.OP_TRUE])
        p2sh_ok = bytes([S.OP_HASH160, 20]) + hash160(redeem_ok) + bytes([S.OP_EQUAL])
        tx_ok = _tx([CTxOut(100_000, P2PKH)],
                    script_sig=bytes([len(redeem_ok)]) + redeem_ok)
        assert are_inputs_standard(tx_ok, [CTxOut(200_000, p2sh_ok)])
