"""Script layer tests.

Mirrors the reference's script_tests.cpp / sighash_tests.cpp strategy
(SURVEY.md §5.1) — but the reference's JSON vector files are unavailable
offline, so vectors are generated from our own signer and cross-checked
through two independent paths (SURVEY.md §8.5.3 mitigation): the
interpreter with immediate CPU verification, and the deferred-batch
checker settled by the CPU oracle.
"""

import hashlib

import pytest
pytest.importorskip("hypothesis")  # property tests need the optional test extra
from hypothesis import given, settings
from hypothesis import strategies as st

from bitcoincashplus_tpu.consensus.tx import COutPoint, CTransaction, CTxIn, CTxOut
from bitcoincashplus_tpu.crypto import secp256k1 as secp
from bitcoincashplus_tpu.crypto.hashes import hash160, ripemd160, sha256, sha256d
from bitcoincashplus_tpu.script import script as S
from bitcoincashplus_tpu.script.interpreter import (
    SCRIPT_ENABLE_SIGHASH_FORKID,
    SCRIPT_VERIFY_CLEANSTACK,
    SCRIPT_VERIFY_DERSIG,
    SCRIPT_VERIFY_LOW_S,
    SCRIPT_VERIFY_MINIMALDATA,
    SCRIPT_VERIFY_NULLDUMMY,
    SCRIPT_VERIFY_NULLFAIL,
    SCRIPT_VERIFY_P2SH,
    SCRIPT_VERIFY_STRICTENC,
    DeferringSignatureChecker,
    EvalScript,
    BaseSignatureChecker,
    ScriptError,
    TransactionSignatureChecker,
    VerifyScript,
    cast_to_bool,
    is_valid_signature_encoding,
)
from bitcoincashplus_tpu.script.script import CScriptNum, ScriptNumError
from bitcoincashplus_tpu.script.sighash import (
    SIGHASH_ALL,
    SIGHASH_ANYONECANPAY,
    SIGHASH_NONE,
    SIGHASH_SINGLE,
    signature_hash_legacy,
)
from bitcoincashplus_tpu.wallet.keys import CKey
from bitcoincashplus_tpu.wallet.signing import sign_transaction

FLAGS = (
    SCRIPT_VERIFY_P2SH | SCRIPT_VERIFY_STRICTENC | SCRIPT_VERIFY_DERSIG
    | SCRIPT_VERIFY_LOW_S | SCRIPT_VERIFY_NULLDUMMY | SCRIPT_VERIFY_NULLFAIL
)
FLAGS_FORKID = FLAGS | SCRIPT_ENABLE_SIGHASH_FORKID


# ---- CScriptNum ----

@given(st.integers(min_value=-(2**31) + 1, max_value=2**31 - 1))
def test_scriptnum_roundtrip(n):
    enc = CScriptNum.encode(n)
    assert CScriptNum.decode(enc, require_minimal=True) == n


def test_scriptnum_minimality():
    # 0x0100 is 1 with a trailing zero byte: non-minimal
    with pytest.raises(ScriptNumError):
        CScriptNum.decode(b"\x01\x00", require_minimal=True)
    assert CScriptNum.decode(b"\x01\x00") == 1
    # negative zero
    with pytest.raises(ScriptNumError):
        CScriptNum.decode(b"\x80", require_minimal=True)
    assert CScriptNum.decode(b"\x80") == 0
    with pytest.raises(ScriptNumError):
        CScriptNum.decode(b"\x01\x02\x03\x04\x05")  # > 4 bytes


def test_scriptnum_negative_encoding():
    assert CScriptNum.encode(-1) == b"\x81"
    assert CScriptNum.encode(-127) == b"\xff"
    assert CScriptNum.encode(-128) == b"\x80\x80"
    assert CScriptNum.encode(255) == b"\xff\x00"
    assert CScriptNum.decode(b"\x80\x80") == -128


# ---- push / parse ----

@given(st.binary(max_size=600))
def test_pushdata_roundtrip(data):
    script = S.push_data_raw(data)
    ops = list(S.get_script_ops(script))
    assert len(ops) == 1
    assert ops[0][1] == data


def test_truncated_push_raises():
    with pytest.raises(S.ScriptParseError):
        list(S.get_script_ops(bytes([10, 1, 2])))  # claims 10, has 2
    with pytest.raises(S.ScriptParseError):
        list(S.get_script_ops(bytes([S.OP_PUSHDATA1])))


def test_classify_templates():
    key = CKey(12345)
    assert S.classify_script(S.p2pkh_script(key.pubkey_hash)) == "pubkeyhash"
    assert S.classify_script(S.p2pk_script(key.pubkey)) == "pubkey"
    redeem = S.multisig_script(1, [key.pubkey])
    assert S.classify_script(redeem) == "multisig"
    assert S.classify_script(S.p2sh_script_for_redeem(redeem)) == "scripthash"
    assert S.classify_script(S.null_data_script(b"hello")) == "nulldata"
    assert S.classify_script(b"\x51") == "nonstandard"


def test_sigop_counting():
    key = CKey(7)
    assert S.count_sigops(S.p2pkh_script(key.pubkey_hash)) == 1
    ms = S.multisig_script(2, [key.pubkey] * 3)
    assert S.count_sigops(ms) == 20  # inaccurate mode
    assert S.count_sigops(ms, accurate=True) == 3
    spk = S.p2sh_script_for_redeem(ms)
    script_sig = b"\x00" + S.push_data_raw(ms)
    assert S.count_p2sh_sigops(spk, script_sig) == 3


# ---- EvalScript basics ----

def run_script(script: bytes, flags: int = 0, stack=None):
    stack = stack if stack is not None else []
    EvalScript(stack, script, flags, BaseSignatureChecker())
    return stack


def test_arithmetic_ops():
    # 2 3 ADD 5 EQUAL
    out = run_script(bytes([S.OP_2, S.OP_3, S.OP_ADD, S.OP_5, S.OP_EQUAL]))
    assert cast_to_bool(out[-1])
    out = run_script(bytes([S.OP_10, S.OP_3, S.OP_SUB]))
    assert CScriptNum.decode(out[-1]) == 7
    out = run_script(bytes([S.OP_1NEGATE, S.OP_ABS]))
    assert CScriptNum.decode(out[-1]) == 1
    out = run_script(bytes([S.OP_5, S.OP_3, S.OP_MIN, S.OP_2, S.OP_MAX]))
    assert CScriptNum.decode(out[-1]) == 3
    out = run_script(bytes([S.OP_3, S.OP_2, S.OP_5, S.OP_WITHIN]))
    assert cast_to_bool(out[-1])


def test_stack_ops():
    out = run_script(bytes([S.OP_1, S.OP_2, S.OP_SWAP]))
    assert [CScriptNum.decode(x) for x in out] == [2, 1]
    out = run_script(bytes([S.OP_1, S.OP_2, S.OP_3, S.OP_ROT]))
    assert [CScriptNum.decode(x) for x in out] == [2, 3, 1]
    out = run_script(bytes([S.OP_1, S.OP_2, S.OP_TUCK]))
    assert [CScriptNum.decode(x) for x in out] == [2, 1, 2]
    out = run_script(bytes([S.OP_1, S.OP_2, S.OP_2DUP, S.OP_DEPTH]))
    assert CScriptNum.decode(out[-1]) == 4
    out = run_script(bytes([S.OP_1, S.OP_2, S.OP_3, S.OP_2, S.OP_PICK]))
    assert CScriptNum.decode(out[-1]) == 1


def test_if_else():
    # IF 2 ELSE 3 ENDIF on true
    body = bytes([S.OP_IF, S.OP_2, S.OP_ELSE, S.OP_3, S.OP_ENDIF])
    out = run_script(bytes([S.OP_1]) + body)
    assert CScriptNum.decode(out[-1]) == 2
    out = run_script(bytes([S.OP_0]) + body)
    assert CScriptNum.decode(out[-1]) == 3
    with pytest.raises(ScriptError, match="unbalanced"):
        run_script(bytes([S.OP_1, S.OP_IF]))
    with pytest.raises(ScriptError, match="unbalanced"):
        run_script(bytes([S.OP_ENDIF]))
    # unexecuted branch may hold unknown opcodes but not disabled ones
    run_script(bytes([S.OP_0, S.OP_IF, 0xBA, S.OP_ENDIF]))
    with pytest.raises(ScriptError, match="disabled"):
        run_script(bytes([S.OP_0, S.OP_IF, S.OP_CAT, S.OP_ENDIF]))


def test_hash_ops():
    data = b"graft"
    out = run_script(S.push_data(data) + bytes([S.OP_SHA256]))
    assert out[-1] == sha256(data)
    out = run_script(S.push_data(data) + bytes([S.OP_HASH160]))
    assert out[-1] == hash160(data)
    out = run_script(S.push_data(data) + bytes([S.OP_HASH256]))
    assert out[-1] == sha256d(data)
    out = run_script(S.push_data(data) + bytes([S.OP_RIPEMD160]))
    assert out[-1] == ripemd160(data)
    out = run_script(S.push_data(data) + bytes([S.OP_SHA1]))
    assert out[-1] == hashlib.sha1(data).digest()


def test_op_return_and_verify():
    with pytest.raises(ScriptError, match="op-return"):
        run_script(bytes([S.OP_RETURN]))
    with pytest.raises(ScriptError, match="verify"):
        run_script(bytes([S.OP_0, S.OP_VERIFY]))
    run_script(bytes([S.OP_1, S.OP_VERIFY]))


def test_minimaldata_flag():
    # push of 1 via PUSHDATA1 is non-minimal
    script = bytes([S.OP_PUSHDATA1, 1, 5])
    run_script(script)  # fine without the flag
    with pytest.raises(ScriptError, match="minimaldata"):
        run_script(script, SCRIPT_VERIFY_MINIMALDATA)


def test_op_count_limit():
    ok = bytes([S.OP_1] + [S.OP_NOP] * 201)
    run_script(ok)
    with pytest.raises(ScriptError, match="op-count"):
        run_script(bytes([S.OP_1] + [S.OP_NOP] * 202))


def test_stack_size_limit():
    run_script(bytes([S.OP_1] * 1000))  # exactly at the limit
    with pytest.raises(ScriptError, match="stack-size"):
        run_script(bytes([S.OP_1] * 1001))


# ---- sighash ----

def _dummy_tx(n_in=2, n_out=2):
    vin = tuple(
        CTxIn(COutPoint(bytes([i + 1]) * 32, i), b"", 0xFFFFFFFE)
        for i in range(n_in)
    )
    vout = tuple(CTxOut(50000 * (i + 1), bytes([S.OP_1])) for i in range(n_out))
    return CTransaction(vin=vin, vout=vout, locktime=0)


def test_sighash_single_bug():
    tx = _dummy_tx(n_in=3, n_out=1)
    # input 2 with SIGHASH_SINGLE and no output 2 -> the "one" constant
    h = signature_hash_legacy(b"\x51", tx, 2, SIGHASH_SINGLE)
    assert h == (1).to_bytes(32, "little")


def test_sighash_variants_differ():
    tx = _dummy_tx()
    code = bytes([S.OP_DUP])
    hashes = {
        signature_hash_legacy(code, tx, 0, t)
        for t in (
            SIGHASH_ALL, SIGHASH_NONE, SIGHASH_SINGLE,
            SIGHASH_ALL | SIGHASH_ANYONECANPAY,
        )
    }
    assert len(hashes) == 4  # all distinct


def test_sighash_anyonecanpay_ignores_other_inputs():
    tx1 = _dummy_tx(n_in=2)
    # same tx but different OTHER input
    vin = (tx1.vin[0], CTxIn(COutPoint(b"\xAA" * 32, 9), b"", 1))
    tx2 = CTransaction(vin=vin, vout=tx1.vout, locktime=0)
    t = SIGHASH_ALL | SIGHASH_ANYONECANPAY
    assert signature_hash_legacy(b"\x51", tx1, 0, t) == signature_hash_legacy(
        b"\x51", tx2, 0, t
    )
    assert signature_hash_legacy(b"\x51", tx1, 0, SIGHASH_ALL) != (
        signature_hash_legacy(b"\x51", tx2, 0, SIGHASH_ALL)
    )


# ---- end-to-end P2PKH / P2PK / P2SH ----

def _spend_fixture(key: CKey, script_pubkey: bytes, amount=50000):
    """A 1-in-1-out tx spending `script_pubkey`."""
    tx = CTransaction(
        vin=(CTxIn(COutPoint(b"\x11" * 32, 0)),),
        vout=(CTxOut(amount - 1000, bytes([S.OP_1])),),
    )
    return tx


@pytest.mark.parametrize("forkid", [False, True])
def test_p2pkh_spend_verifies(forkid):
    key = CKey(0xC0FFEE)
    spk = S.p2pkh_script(key.pubkey_hash)
    amount = 50000
    tx = _spend_fixture(key, spk, amount)
    signed = sign_transaction(
        tx, [(spk, amount)], lambda i: key if i == key.pubkey_hash else None,
        enable_forkid=forkid,
    )
    flags = FLAGS_FORKID if forkid else FLAGS
    checker = TransactionSignatureChecker(signed, 0, amount)
    VerifyScript(signed.vin[0].script_sig, spk, flags, checker)


def test_p2pkh_wrong_key_fails():
    key, wrong = CKey(0xC0FFEE), CKey(0xBADBAD)
    spk = S.p2pkh_script(key.pubkey_hash)
    tx = _spend_fixture(key, spk)
    signed = sign_transaction(
        tx, [(spk, 50000)], lambda i: wrong,  # signs with the wrong key
    )
    checker = TransactionSignatureChecker(signed, 0, 50000)
    with pytest.raises(ScriptError):
        VerifyScript(signed.vin[0].script_sig, spk, FLAGS, checker)


def test_p2pkh_tampered_output_fails():
    key = CKey(0xC0FFEE)
    spk = S.p2pkh_script(key.pubkey_hash)
    tx = _spend_fixture(key, spk)
    signed = sign_transaction(tx, [(spk, 50000)], lambda i: key)
    # attacker redirects the output after signing
    tampered = CTransaction(
        signed.version, signed.vin,
        (CTxOut(49000, bytes([S.OP_2])),), signed.locktime,
    )
    checker = TransactionSignatureChecker(tampered, 0, 50000)
    with pytest.raises(ScriptError, match="nullfail|eval-false"):
        VerifyScript(tampered.vin[0].script_sig, spk, FLAGS, checker)


def test_forkid_amount_commitment():
    """FORKID digests commit to the spent amount; legacy does not."""
    key = CKey(0xABCDEF)
    spk = S.p2pkh_script(key.pubkey_hash)
    tx = _spend_fixture(key, spk)
    signed = sign_transaction(
        tx, [(spk, 50000)], lambda i: key, enable_forkid=True
    )
    # verifier believes a different amount -> must fail
    checker = TransactionSignatureChecker(signed, 0, 99999)
    with pytest.raises(ScriptError):
        VerifyScript(signed.vin[0].script_sig, spk, FLAGS_FORKID, checker)
    # legacy signature ignores amount
    signed_legacy = sign_transaction(tx, [(spk, 50000)], lambda i: key)
    checker = TransactionSignatureChecker(signed_legacy, 0, 99999)
    VerifyScript(signed_legacy.vin[0].script_sig, spk, FLAGS, checker)


def test_p2pk_spend():
    key = CKey(0x1234)
    spk = S.p2pk_script(key.pubkey)
    tx = _spend_fixture(key, spk)
    signed = sign_transaction(
        tx, [(spk, 50000)], lambda i: key if i == key.pubkey else None
    )
    checker = TransactionSignatureChecker(signed, 0, 50000)
    VerifyScript(signed.vin[0].script_sig, spk, FLAGS, checker)


def test_p2sh_multisig_2of3():
    keys = [CKey(1000 + i) for i in range(3)]
    redeem = S.multisig_script(2, [k.pubkey for k in keys])
    spk = S.p2sh_script_for_redeem(redeem)
    tx = _spend_fixture(keys[0], spk)

    by_pub = {k.pubkey: k for k in keys[:2]}  # only 2 of 3 known
    signed = sign_transaction(
        tx, [(spk, 50000)], lambda i: by_pub.get(i),
        redeem_scripts={hash160(redeem): redeem},
    )
    checker = TransactionSignatureChecker(signed, 0, 50000)
    VerifyScript(
        signed.vin[0].script_sig, spk,
        FLAGS | SCRIPT_VERIFY_CLEANSTACK, checker,
    )
    # and 1 key is not enough
    one = {keys[1].pubkey: keys[1]}
    with pytest.raises(Exception):
        sign_transaction(
            tx, [(spk, 50000)], lambda i: one.get(i),
            redeem_scripts={hash160(redeem): redeem},
        )


def test_multisig_sig_order_matters():
    keys = [CKey(2000 + i) for i in range(3)]
    redeem = S.multisig_script(2, [k.pubkey for k in keys])
    spk = S.p2sh_script_for_redeem(redeem)
    tx = _spend_fixture(keys[0], spk)
    by_pub = {k.pubkey: k for k in keys}
    signed = sign_transaction(
        tx, [(spk, 50000)], lambda i: by_pub.get(i) if i != keys[1].pubkey else None,
        redeem_scripts={hash160(redeem): redeem},
    )  # signs with keys 0 and 2, in key order
    checker = TransactionSignatureChecker(signed, 0, 50000)
    VerifyScript(signed.vin[0].script_sig, spk, FLAGS, checker)

    # swap the two sigs: order violates the in-key-order rule -> fail
    ops = list(S.get_script_ops(signed.vin[0].script_sig))
    sig_a, sig_b, redeem_push = ops[1][1], ops[2][1], ops[3][1]
    swapped = (
        b"\x00" + S.push_data_raw(sig_b) + S.push_data_raw(sig_a)
        + S.push_data_raw(redeem_push)
    )
    with pytest.raises(ScriptError):
        VerifyScript(swapped, spk, FLAGS, checker)


def test_nulldummy():
    keys = [CKey(3000)]
    redeem = S.multisig_script(1, [k.pubkey for k in keys])
    spk = S.p2sh_script_for_redeem(redeem)
    tx = _spend_fixture(keys[0], spk)
    signed = sign_transaction(
        tx, [(spk, 50000)], lambda i: keys[0],
        redeem_scripts={hash160(redeem): redeem},
    )
    # replace the OP_0 dummy with OP_1
    sig_part = signed.vin[0].script_sig[1:]
    bad = bytes([S.OP_1]) + sig_part
    checker = TransactionSignatureChecker(signed, 0, 50000)
    with pytest.raises(ScriptError, match="nulldummy"):
        VerifyScript(bad, spk, FLAGS, checker)
    VerifyScript(bad, spk, FLAGS & ~SCRIPT_VERIFY_NULLDUMMY, checker)


# ---- deferred batch checker ----

def test_deferring_checker_records_and_oracle_settles():
    key = CKey(0x5EED)
    spk = S.p2pkh_script(key.pubkey_hash)
    tx = _spend_fixture(key, spk)
    signed = sign_transaction(tx, [(spk, 50000)], lambda i: key)

    records = []
    checker = DeferringSignatureChecker(signed, 0, 50000, records)
    VerifyScript(signed.vin[0].script_sig, spk, FLAGS, checker)
    assert len(records) == 1
    rec = records[0]
    assert secp.ecdsa_verify(rec.pubkey, rec.r, rec.s, rec.msg_hash)
    assert rec.txid == signed.txid and rec.in_idx == 0


def test_deferring_checker_bad_sig_caught_by_batch():
    """The deferral contract: interpreter says OK, batch says no."""
    key = CKey(0x5EED)
    spk = S.p2pkh_script(key.pubkey_hash)
    tx = _spend_fixture(key, spk)
    signed = sign_transaction(tx, [(spk, 50000)], lambda i: key)
    # flip a bit mid-signature (keeps DER valid: flip inside s value)
    ss = bytearray(signed.vin[0].script_sig)
    ss[40] ^= 0x01
    tampered_sig = bytes(ss)

    records = []
    checker = DeferringSignatureChecker(signed, 0, 50000, records)
    try:
        VerifyScript(tampered_sig, spk, FLAGS, checker)
    except ScriptError:
        return  # DER/low-s encoding may reject outright: also correct
    assert len(records) == 1
    rec = records[0]
    assert not secp.ecdsa_verify(rec.pubkey, rec.r, rec.s, rec.msg_hash)


def test_deferring_requires_nullfail():
    key = CKey(0x5EED)
    spk = S.p2pkh_script(key.pubkey_hash)
    signed = sign_transaction(
        _spend_fixture(key, spk), [(spk, 50000)], lambda i: key
    )
    checker = DeferringSignatureChecker(signed, 0, 50000, [])
    with pytest.raises(AssertionError):
        VerifyScript(
            signed.vin[0].script_sig, spk,
            FLAGS & ~SCRIPT_VERIFY_NULLFAIL, checker,
        )


# ---- signature encoding ----

def test_der_encoding_checks():
    key = CKey(42)
    sig = key.sign(b"\x01" * 32) + bytes([SIGHASH_ALL])
    assert is_valid_signature_encoding(sig)
    assert not is_valid_signature_encoding(sig[:-2])  # truncated
    assert not is_valid_signature_encoding(b"")
    # high-S rejected under LOW_S
    r, s = secp.sig_der_decode(sig[:-1])
    high_s = secp.sig_der_encode(r, secp.N - s) + bytes([SIGHASH_ALL])
    spk = S.p2pk_script(key.pubkey)
    stack = [high_s]
    checker = BaseSignatureChecker()
    with pytest.raises(ScriptError, match="high-s"):
        EvalScript(stack, spk, FLAGS | SCRIPT_VERIFY_LOW_S, checker)


def test_forkid_flag_gating():
    """STRICTENC: FORKID bit required iff the fork flag is on."""
    key = CKey(0xF0F0)
    spk = S.p2pkh_script(key.pubkey_hash)
    tx = _spend_fixture(key, spk)
    signed_fork = sign_transaction(
        tx, [(spk, 50000)], lambda i: key, enable_forkid=True
    )
    checker = TransactionSignatureChecker(signed_fork, 0, 50000)
    with pytest.raises(ScriptError, match="illegal-forkid"):
        VerifyScript(signed_fork.vin[0].script_sig, spk, FLAGS, checker)
    signed_legacy = sign_transaction(tx, [(spk, 50000)], lambda i: key)
    checker = TransactionSignatureChecker(signed_legacy, 0, 50000)
    with pytest.raises(ScriptError, match="must-use-forkid"):
        VerifyScript(
            signed_legacy.vin[0].script_sig, spk, FLAGS_FORKID, checker
        )


# ---- randomized differential: immediate vs deferred+oracle ----

@settings(max_examples=20, deadline=None)
@given(st.integers(min_value=1, max_value=secp.N - 1), st.booleans())
def test_immediate_vs_deferred_equivalence(secret, forkid):
    key = CKey(secret)
    spk = S.p2pkh_script(key.pubkey_hash)
    tx = _spend_fixture(key, spk)
    signed = sign_transaction(
        tx, [(spk, 50000)], lambda i: key, enable_forkid=forkid
    )
    flags = FLAGS_FORKID if forkid else FLAGS

    ok_immediate = True
    try:
        VerifyScript(
            signed.vin[0].script_sig, spk, flags,
            TransactionSignatureChecker(signed, 0, 50000),
        )
    except ScriptError:
        ok_immediate = False

    records = []
    ok_deferred = True
    try:
        VerifyScript(
            signed.vin[0].script_sig, spk, flags,
            DeferringSignatureChecker(signed, 0, 50000, records),
        )
    except ScriptError:
        ok_deferred = False
    if ok_deferred:
        ok_deferred = all(
            secp.ecdsa_verify(r.pubkey, r.r, r.s, r.msg_hash) for r in records
        )
    assert ok_immediate == ok_deferred == True  # noqa: E712
