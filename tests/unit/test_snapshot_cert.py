"""Proof-carrying snapshot certificates (store/certificate.py, ISSUE 17).

Certificate algebra against pure-Python oracles (MMR peak/bag vs a
recursive reference, epoch trajectory vs forward simulation including
the tail epoch), golden (de)serialization vectors pinned in
tests/fixtures/, the full forged-snapshot tamper matrix at
``load_snapshot`` (wrong MMR root, truncated trajectory, bit-flipped
certificate — every one rejected with the chainstate wiped, never
half-loaded), and the ``snapshot_cert`` fault-site drills: fail-*
proves the reject-and-wipe path, poison-output proves the build-time
forged-epoch shape the shadow validator's divergence abort exists to
catch (BCP005 parity).
"""

import copy
import hashlib
import json
import os
import struct

import pytest

from bitcoincashplus_tpu.store import certificate as cert_mod
from bitcoincashplus_tpu.store import muhash
from bitcoincashplus_tpu.store import snapshot as snapshot_mod
from bitcoincashplus_tpu.store.certificate import (
    CertificateError,
    SNAPSHOT_CERT_SITE,
    build_certificate,
    checkpoint_heights,
    commitment_chain,
    epoch_trajectory,
    mmr_peaks,
    mmr_root,
    sample_epochs,
    verify_certificate,
)
from bitcoincashplus_tpu.store.sharded import ShardedCoinsDB
from bitcoincashplus_tpu.util.faults import InjectedFault

FIXTURES = os.path.join(os.path.dirname(__file__), "..", "fixtures")


def sha256d(b: bytes) -> bytes:
    return hashlib.sha256(hashlib.sha256(b).digest()).digest()


def _h(tag: str) -> bytes:
    return hashlib.sha256(tag.encode()).digest()


# -- MMR vs pure-Python oracle -----------------------------------------


def _oracle_root(leaves):
    """Independent MMR reference: recursive perfect-tree roots over the
    pow2 decomposition, bagged right-to-left."""

    def tree(ls):
        if len(ls) == 1:
            return ls[0]
        mid = len(ls) // 2
        return sha256d(tree(ls[:mid]) + tree(ls[mid:]))

    peaks, pos, n = [], 0, len(leaves)
    for bit in range(n.bit_length() - 1, -1, -1):
        size = 1 << bit
        if n & size:
            peaks.append(tree(leaves[pos:pos + size]))
            pos += size
    acc = peaks[-1]
    for p in reversed(peaks[:-1]):
        acc = sha256d(p + acc)
    return acc


class TestMMR:
    def test_root_matches_oracle_across_sizes(self):
        # covers every pow2-decomposition shape through 3 peaks and the
        # device-batched level path is exercised by larger functional
        # dumps; here the host loop is the oracle's mirror
        for n in list(range(1, 34)) + [63, 64, 65, 100]:
            leaves = [_h(f"leaf:{n}:{i}") for i in range(n)]
            assert mmr_root(leaves) == _oracle_root(leaves), n

    def test_peak_count_is_popcount(self):
        for n in (1, 2, 3, 7, 12, 31, 100):
            leaves = [_h(f"p:{i}") for i in range(n)]
            assert len(mmr_peaks(leaves)) == bin(n).count("1")

    def test_append_changes_root(self):
        leaves = [_h(f"a:{i}") for i in range(9)]
        r9 = mmr_root(leaves)
        assert mmr_root(leaves + [_h("a:9")]) != r9
        # and order matters — an MMR is a commitment to the sequence
        assert mmr_root(list(reversed(leaves))) != r9

    def test_zero_leaves_is_an_error(self):
        with pytest.raises(CertificateError):
            mmr_root([])


# -- epoch trajectory vs forward simulation ----------------------------


def _scenario(height=10, epoch=3):
    """Deterministic chain: 2 coins created per block, FIFO spend of one
    coin per block from height 3. Returns (header_hashes, final_state,
    deltas tip->1, {height: state})."""
    header_hashes = [_h(f"hdr:{i}") for i in range(height + 1)]
    state, coins, deltas, hist = 1, [], [], {}
    for h in range(1, height + 1):
        created = []
        for j in range(2):
            key36 = _h(f"coin:{h}:{j}")[:32] + struct.pack("<I", j)
            ser = bytes([h * 2, 5, 4]) + _h(f"ser:{h}:{j}")[:4]
            created.append((key36, ser))
        spent = [coins.pop(0)] if h >= 3 else []
        coins.extend(created)
        for k, s in created:
            state = state * muhash.coin_element(k, s) % muhash.MUHASH_P
        for k, s in spent:
            state = (state * pow(muhash.coin_element(k, s), -1,
                                 muhash.MUHASH_P)) % muhash.MUHASH_P
        hist[h] = state
        deltas.append((h, created, spent))
    return header_hashes, state, list(reversed(deltas)), hist


class TestTrajectory:
    def test_checkpoint_schedule(self):
        assert checkpoint_heights(9, 3) == [3, 6, 9]
        assert checkpoint_heights(10, 3) == [3, 6, 9, 10]  # tail epoch
        assert checkpoint_heights(2, 5) == [2]  # single short epoch
        assert checkpoint_heights(1, 1) == [1]
        with pytest.raises(CertificateError):
            checkpoint_heights(0, 3)
        with pytest.raises(CertificateError):
            checkpoint_heights(10, 0)

    def test_backward_walk_matches_forward_simulation(self):
        hh, state, deltas, hist = _scenario(10, 3)
        traj = epoch_trajectory(state, iter(deltas), 10, 3)
        assert [e["height"] for e in traj] == [3, 6, 9, 10]
        for e in traj:
            assert e["muhash"] == \
                muhash.digest_of(hist[e["height"]]).hex(), e["height"]

    def test_tail_epoch_when_height_divides(self):
        hh, state, deltas, hist = _scenario(9, 3)
        traj = epoch_trajectory(state, iter(deltas), 9, 3)
        assert [e["height"] for e in traj] == [3, 6, 9]
        assert traj[-1]["muhash"] == muhash.digest_of(state).hex()

    def test_in_block_create_and_spend_cancels(self):
        """A coin created and spent inside the same block must vanish
        from every checkpoint — the abelian cancellation the backward
        walk relies on."""
        keep = (_h("keep")[:32] + b"\x00" * 4, bytes([4, 5, 1, 9]))
        eph = (_h("ephemeral")[:32] + b"\x00" * 4, bytes([6, 5, 1, 7]))
        state = muhash.coin_element(*keep) % muhash.MUHASH_P
        # block 1 creates the kept coin; block 2 creates AND spends the
        # ephemeral one — dividing block 2 back out must recover the
        # height-1 state exactly
        deltas = [(2, [eph], [eph]), (1, [keep], [])]
        traj = epoch_trajectory(state, iter(deltas), 2, 1)
        assert [e["height"] for e in traj] == [1, 2]
        assert traj[0]["muhash"] == muhash.digest_of(state).hex()
        assert traj[1]["muhash"] == muhash.digest_of(state).hex()

    def test_out_of_order_walk_rejected(self):
        hh, state, deltas, _ = _scenario(6, 2)
        bad = [deltas[0], deltas[2], deltas[1]] + deltas[3:]
        with pytest.raises(CertificateError, match="out of order"):
            epoch_trajectory(state, iter(bad), 6, 2)

    def test_short_walk_rejected(self):
        hh, state, deltas, _ = _scenario(6, 2)
        with pytest.raises(CertificateError, match="ended before"):
            epoch_trajectory(state, iter(deltas[:2]), 6, 2)


# -- build / verify / tamper matrix ------------------------------------


class TestCertificate:
    def _built(self, height=10, epoch=3):
        hh, state, deltas, _ = _scenario(height, epoch)
        cert = build_certificate(hh, height, epoch, state, iter(deltas))
        return hh, state, cert

    def test_round_trip(self):
        hh, state, cert = self._built()
        cps = verify_certificate(cert, hh, 10,
                                 muhash.digest_of(state).hex())
        assert cps == {e["height"]: e["muhash"] for e in cert["epochs"]}
        assert cert["commitment"] == commitment_chain(
            bytes.fromhex(cert["mmr_root"]), 10, 3, cert["epochs"]).hex()

    def test_json_serialization_round_trip(self, tmp_path):
        from bitcoincashplus_tpu.store.kvstore import (
            atomic_write_json,
            read_json,
        )

        hh, state, cert = self._built()
        p = str(tmp_path / "CERTIFICATE.json")
        atomic_write_json(p, cert)
        again = read_json(p)
        assert again == cert
        verify_certificate(again, hh, 10, muhash.digest_of(state).hex())

    def test_golden_vectors(self):
        """The pinned fixture: any drift in MMR construction, MuHash
        element derivation, trajectory algebra or commitment chaining is
        a format break and must be deliberate."""
        with open(os.path.join(FIXTURES, "snapshot_cert_golden.json")) as f:
            golden = json.load(f)
        hh, state, cert = self._built(10, 3)
        assert muhash.digest_of(state).hex() == golden["final_digest"]
        assert cert == golden["certificate"]

    def test_tamper_matrix(self):
        hh, state, cert = self._built()
        digest = muhash.digest_of(state).hex()

        def rejected(mutate, match):
            bad = copy.deepcopy(cert)
            mutate(bad)
            with pytest.raises(CertificateError, match=match):
                verify_certificate(bad, hh, 10, digest)

        # wrong MMR root (and equivalently: headers not matching it)
        rejected(lambda c: c.update(mmr_root="00" * 32), "MMR root")
        # truncated / misaligned epoch trajectory
        rejected(lambda c: c["epochs"].pop(0), "truncated")
        rejected(lambda c: c["epochs"].pop(), "truncated")
        # bit-flipped epoch digest breaks the commitment chain
        rejected(lambda c: c["epochs"][1].update(muhash="11" * 32),
                 "commitment chain")
        # bit-flipped commitment itself
        rejected(lambda c: c.update(commitment="22" * 32),
                 "commitment chain")
        # height / header-count forgery
        rejected(lambda c: c.update(height=9), "height")
        rejected(lambda c: c.update(headers=10), "header count")
        # stride forgery desynchronizes the schedule
        rejected(lambda c: c.update(epoch_blocks=5), "truncated")
        # version confusion is a hard stop
        rejected(lambda c: c.update(version=99), "version")
        # the final checkpoint must cover the snapshot digest itself
        bad = copy.deepcopy(cert)
        with pytest.raises(CertificateError, match="snapshot digest"):
            verify_certificate(bad, hh, 10, "ab" * 32)
        # truncated header chain (the truncated-MMR forgery)
        with pytest.raises(CertificateError, match="header count"):
            verify_certificate(cert, hh[:-1], 10, digest)

    def test_header_swap_rejected(self):
        """Same length, different history — the MMR recompute over the
        snapshot's own headers is what catches it."""
        hh, state, cert = self._built()
        digest = muhash.digest_of(state).hex()
        swapped = list(hh)
        swapped[4] = _h("forged header")
        with pytest.raises(CertificateError, match="MMR root"):
            verify_certificate(cert, swapped, 10, digest)


# -- spot-check sampling -----------------------------------------------


class TestSampling:
    def test_final_epoch_always_included(self):
        for k in (1, 2, 3):
            s = sample_epochs([3, 6, 9, 12, 15], k, seed=11)
            assert 15 in s and len(s) == k and s == sorted(s)

    def test_seed_replays_identically(self):
        eps = list(range(10, 210, 10))
        assert sample_epochs(eps, 5, seed=42) == \
            sample_epochs(eps, 5, seed=42)
        assert sample_epochs(eps, 5, seed=42) != \
            sample_epochs(eps, 5, seed=43)

    def test_oversample_degrades_to_full_coverage(self):
        assert sample_epochs([3, 6, 9], 99, seed=1) == [3, 6, 9]
        assert sample_epochs([], 3) == []


# -- load_snapshot integration: certificate gating ---------------------


def _key(i: int) -> bytes:
    return bytes([i % 251]) * 32 + struct.pack("<I", i)


def _coin(i: int) -> bytes:
    return bytes([2, 5, 20]) + bytes([i % 256]) * 20


def _certified_snapshot(tmp_path, n_coins=60, height=4, epoch=2):
    """A structurally-honest certified snapshot over synthetic headers:
    the trajectory partitions the coin set evenly across blocks (no
    spends), so every checkpoint digest is exact MuHash algebra."""
    db = ShardedCoinsDB(str(tmp_path / "src"), n_shards=2)
    best = b"\xaa" * 32
    entries = [(_key(i), _coin(i)) for i in range(n_coins)]
    db.batch_write_serialized(entries, best)
    headers = [(_h(f"raw:{i}") * 3)[:80] for i in range(height + 1)]
    header_hashes = [sha256d(hd) for hd in headers]
    per = n_coins // height
    deltas = [(h, entries[(h - 1) * per: h * per], [])
              for h in range(height, 0, -1)]
    cert = build_certificate(header_hashes, height, epoch,
                             db.muhash_state(), iter(deltas))
    path = str(tmp_path / "snap")
    snapshot_mod.dump_snapshot(db, path, headers, height, best, "regtest",
                               certificate=cert)
    digest = db.muhash_digest()
    db.close()
    return path, best, digest, cert


class TestLoadGating:
    def test_certified_load_verifies_and_stamps(self, tmp_path):
        path, best, digest, cert = _certified_snapshot(tmp_path)
        db = ShardedCoinsDB(str(tmp_path / "dst"), n_shards=4)
        info = snapshot_mod.load_snapshot(
            path, db, "regtest", expected_hash=best,
            expected_digest=digest)
        assert info["certificate"] == cert
        assert info["cert_checkpoints"] == \
            {e["height"]: e["muhash"] for e in cert["epochs"]}
        sub = db.snapshot_state["cert"]
        assert sub["present"] and sub["verified"]
        assert sub["epochs"] == len(cert["epochs"])
        db.close()

    def test_bitflipped_certificate_rejected_and_wiped(self, tmp_path):
        path, best, digest, cert = _certified_snapshot(tmp_path)
        doc = json.load(open(os.path.join(path, cert_mod.CERT_NAME)))
        raw = bytearray(bytes.fromhex(doc["epochs"][0]["muhash"]))
        raw[7] ^= 0x20
        doc["epochs"][0]["muhash"] = bytes(raw).hex()
        json.dump(doc, open(os.path.join(path, cert_mod.CERT_NAME), "w"))
        db = ShardedCoinsDB(str(tmp_path / "dst"), n_shards=2)
        with pytest.raises(snapshot_mod.SnapshotError,
                           match="certificate rejected"):
            snapshot_mod.load_snapshot(path, db, "regtest",
                                       expected_hash=best,
                                       expected_digest=digest)
        assert db.count_coins() == 0  # never half-loaded
        assert db.snapshot_state is None
        db.close()

    def test_truncated_mmr_rejected(self, tmp_path):
        """headers.dat shortened out from under the certificate — the
        manifest checksum catches the torn file, and a consistently
        re-checksummed truncation still fails the cert header count."""
        path, best, digest, cert = _certified_snapshot(tmp_path)
        # rewrite headers.dat one header short, with a matching manifest
        # so ONLY the certificate check is left to object
        hdr_path = os.path.join(path, snapshot_mod.HEADERS_NAME)
        blob = open(hdr_path, "rb").read()[:-80]
        open(hdr_path, "wb").write(blob)
        man_path = os.path.join(path, snapshot_mod.MANIFEST_NAME)
        man = json.load(open(man_path))
        man["headers"]["count"] -= 1
        man["headers"]["sha256"] = hashlib.sha256(blob).hexdigest()
        json.dump(man, open(man_path, "w"))
        db = ShardedCoinsDB(str(tmp_path / "dst"), n_shards=2)
        with pytest.raises(snapshot_mod.SnapshotError,
                           match="certificate rejected"):
            snapshot_mod.load_snapshot(path, db, "regtest",
                                       expected_hash=best,
                                       expected_digest=digest)
        assert db.count_coins() == 0
        db.close()

    def test_certless_snapshot_loads_unverified(self, tmp_path):
        path, best, digest, _ = _certified_snapshot(tmp_path)
        os.remove(os.path.join(path, cert_mod.CERT_NAME))
        db = ShardedCoinsDB(str(tmp_path / "dst"), n_shards=2)
        info = snapshot_mod.load_snapshot(path, db, "regtest",
                                          expected_hash=best,
                                          expected_digest=digest)
        assert info["certificate"] is None
        sub = db.snapshot_state["cert"]
        assert not sub["present"] and not sub["verified"]
        db.close()

    def test_certless_snapshot_refused_when_required(self, tmp_path):
        path, best, digest, _ = _certified_snapshot(tmp_path)
        os.remove(os.path.join(path, cert_mod.CERT_NAME))
        db = ShardedCoinsDB(str(tmp_path / "dst"), n_shards=2)
        with pytest.raises(snapshot_mod.SnapshotError,
                           match="snapshotcertrequired"):
            snapshot_mod.load_snapshot(path, db, "regtest",
                                       expected_hash=best,
                                       expected_digest=digest,
                                       require_certificate=True)
        assert db.count_coins() == 0
        db.close()


# -- snapshot_cert fault-site drills (BCP005 parity) -------------------


@pytest.mark.faults
class TestSnapshotCertFaultSite:
    def test_fail_at_verify_takes_wipe_and_reject(self, tmp_path,
                                                  fault_harness):
        """fail-*: the certificate check blowing up mid-load must exit
        through the same clear_coins() wipe as a digest mismatch."""
        path, best, digest, _ = _certified_snapshot(tmp_path)
        db = ShardedCoinsDB(str(tmp_path / "dst"), n_shards=2)
        fault_harness("fail-always", ops=SNAPSHOT_CERT_SITE)
        with pytest.raises(InjectedFault):
            snapshot_mod.load_snapshot(path, db, "regtest",
                                       expected_hash=best,
                                       expected_digest=digest)
        assert db.count_coins() == 0
        assert db.snapshot_state is None
        db.close()

    def test_fail_once_then_clean_reload_succeeds(self, tmp_path,
                                                  fault_harness):
        """The re-admission story: after the injected failure clears,
        the same snapshot loads clean — nothing was left half-stamped."""
        path, best, digest, _ = _certified_snapshot(tmp_path)
        db = ShardedCoinsDB(str(tmp_path / "dst"), n_shards=2)
        fault_harness("fail-once", ops=SNAPSHOT_CERT_SITE)
        with pytest.raises(InjectedFault):
            snapshot_mod.load_snapshot(path, db, "regtest",
                                       expected_hash=best,
                                       expected_digest=digest)
        info = snapshot_mod.load_snapshot(path, db, "regtest",
                                          expected_hash=best,
                                          expected_digest=digest)
        assert info["cert_checkpoints"]
        assert db.snapshot_state["cert"]["verified"]
        db.close()

    def test_poison_at_build_forges_one_internally_consistent_epoch(
            self, tmp_path, fault_harness):
        """poison-output: the build-leg drill produces the dangerous
        artifact — a certificate that PASSES structural verification but
        commits a wrong mid-trajectory digest. Exactly the forgery the
        shadow validator's epoch-divergence abort is for; the final
        checkpoint is never the one forged (that would be caught at load
        against the manifest digest)."""
        hh, state, deltas, _ = _scenario(10, 3)
        honest = build_certificate(hh, 10, 3, state, iter(deltas))
        fault_harness("poison-output", ops=SNAPSHOT_CERT_SITE)
        hh, state, deltas, _ = _scenario(10, 3)
        forged = build_certificate(hh, 10, 3, state, iter(deltas))
        # structurally valid: load-time verification WILL accept it
        cps = verify_certificate(forged, hh, 10,
                                 muhash.digest_of(state).hex())
        diffs = [e for e, o in zip(forged["epochs"], honest["epochs"])
                 if e["muhash"] != o["muhash"]]
        assert len(diffs) == 1  # one forged epoch
        assert diffs[0]["height"] != 10  # never the manifest-checked tail
        # and a shadow validator replaying honestly diverges exactly there
        honest_map = {e["height"]: e["muhash"] for e in honest["epochs"]}
        assert cps[diffs[0]["height"]] != honest_map[diffs[0]["height"]]

    def test_all_does_not_arm_snapshot_cert(self, tmp_path, fault_harness):
        """Explicit-only semantics: BCP_FAULT_OPS=all keeps meaning the
        accelerator subsystems — a dead-backend drill must not reject
        snapshot onboarding."""
        fault_harness("fail-always", ops="all")
        path, best, digest, _ = _certified_snapshot(tmp_path)
        db = ShardedCoinsDB(str(tmp_path / "dst"), n_shards=2)
        info = snapshot_mod.load_snapshot(path, db, "regtest",
                                          expected_hash=best,
                                          expected_digest=digest)
        assert info["cert_checkpoints"]
        db.close()
