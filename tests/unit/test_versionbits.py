"""BIP9 versionbits state machine tests — the scenarios of the reference's
versionbits_tests.cpp, on a synthetic CBlockIndex chain with a small window."""

from bitcoincashplus_tpu.consensus.block import CBlockHeader
from bitcoincashplus_tpu.consensus.versionbits import (
    ALWAYS_ACTIVE,
    NO_TIMEOUT,
    VERSIONBITS_TOP_BITS,
    ThresholdState,
    VBDeployment,
    VersionBitsCache,
    compute_block_version,
    get_state_for,
    get_state_since_height,
    unknown_version_signalling,
)
from bitcoincashplus_tpu.validation.chain import CBlockIndex

WINDOW = 4
THRESHOLD = 3
BIT = 5
SIGNAL = VERSIONBITS_TOP_BITS | (1 << BIT)
NO_SIGNAL = VERSIONBITS_TOP_BITS

DEP = VBDeployment("dep", BIT, 0, NO_TIMEOUT)


def build_chain(versions, times=None):
    """Index chain from a list of block versions (genesis first)."""
    chain = []
    prev = None
    for h, v in enumerate(versions):
        hdr = CBlockHeader(
            version=v, hash_prev_block=b"\x00" * 32,
            hash_merkle_root=h.to_bytes(32, "little"),
            time=times[h] if times else 1000 + h,
            bits=0x207FFFFF, nonce=h,
        )
        idx = CBlockIndex(hdr, h.to_bytes(32, "big"), prev)
        chain.append(idx)
        prev = idx
    return chain


def state_at(chain, height, dep=DEP, cache=None):
    """State for the block AT `height` (prev = height-1)."""
    prev = chain[height - 1] if height > 0 else None
    return get_state_for(dep, prev, WINDOW, THRESHOLD, cache)


def test_all_signalling_reaches_active():
    chain = build_chain([SIGNAL] * 16)
    assert state_at(chain, 0) == ThresholdState.DEFINED
    assert state_at(chain, 2) == ThresholdState.DEFINED
    assert state_at(chain, 4) == ThresholdState.STARTED
    assert state_at(chain, 7) == ThresholdState.STARTED
    assert state_at(chain, 8) == ThresholdState.LOCKED_IN
    assert state_at(chain, 11) == ThresholdState.LOCKED_IN
    assert state_at(chain, 12) == ThresholdState.ACTIVE
    assert state_at(chain, 15) == ThresholdState.ACTIVE


def test_below_threshold_stays_started_then_locks():
    # period h4..h7: only 2 of 4 signal -> stays STARTED;
    # period h8..h11: 3 signal -> LOCKED_IN at h12
    versions = (
        [NO_SIGNAL] * 4
        + [SIGNAL, NO_SIGNAL, SIGNAL, NO_SIGNAL]
        + [SIGNAL, SIGNAL, NO_SIGNAL, SIGNAL]
        + [NO_SIGNAL] * 4
    )
    chain = build_chain(versions)
    assert state_at(chain, 8) == ThresholdState.STARTED
    assert state_at(chain, 12) == ThresholdState.LOCKED_IN
    # LOCKED_IN -> ACTIVE regardless of further signalling
    chain2 = build_chain(versions + [NO_SIGNAL] * 4)
    assert state_at(chain2, 16) == ThresholdState.ACTIVE


def test_timeout_fails():
    dep = VBDeployment("dep", BIT, 0, 1010)  # times are 1000+h
    chain = build_chain([NO_SIGNAL] * 20)  # never signals -> cannot lock in
    # MTP crosses 1010 a few blocks after h10; once a boundary's MTP is past
    # timeout while STARTED, the next period is FAILED — and stays FAILED
    states = [state_at(chain, h, dep) for h in range(0, 20, WINDOW)]
    assert ThresholdState.FAILED in states
    assert states[-1] == ThresholdState.FAILED
    # terminal: never ACTIVE afterwards
    assert ThresholdState.ACTIVE not in states


def test_never_started_before_start_time():
    dep = VBDeployment("dep", BIT, 10_000, NO_TIMEOUT)  # start far in future
    chain = build_chain([SIGNAL] * 16)
    for h in range(0, 16, WINDOW):
        assert state_at(chain, h, dep) == ThresholdState.DEFINED


def test_always_active_sentinel():
    dep = VBDeployment("dep", BIT, ALWAYS_ACTIVE, NO_TIMEOUT)
    chain = build_chain([NO_SIGNAL] * 4)
    assert state_at(chain, 2, dep) == ThresholdState.ACTIVE


def test_state_since_height():
    chain = build_chain([SIGNAL] * 16)
    prev = chain[14]
    assert get_state_for(DEP, prev, WINDOW, THRESHOLD) == ThresholdState.ACTIVE
    assert get_state_since_height(DEP, prev, WINDOW, THRESHOLD) == 12


def test_cache_consistency():
    chain = build_chain([SIGNAL] * 16)
    cache = {}
    uncached = [state_at(chain, h) for h in range(16)]
    cached = [state_at(chain, h, cache=cache) for h in range(16)]
    assert uncached == cached
    assert cache  # boundaries were memoized
    # cached re-query still right
    assert state_at(chain, 15, cache=cache) == ThresholdState.ACTIVE


def test_compute_block_version_signals_only_while_pending():
    chain = build_chain([SIGNAL] * 16)
    # STARTED at h4..h11 boundaries -> signal; ACTIVE at h12 -> stop
    v_started = compute_block_version(chain[5], (DEP,), WINDOW, THRESHOLD)
    assert v_started & (1 << BIT)
    v_active = compute_block_version(chain[14], (DEP,), WINDOW, THRESHOLD)
    assert not v_active & (1 << BIT)
    assert v_active == VERSIONBITS_TOP_BITS
    cache = VersionBitsCache()
    assert compute_block_version(chain[5], (DEP,), WINDOW, THRESHOLD,
                                 cache) == v_started


def test_unknown_version_warning():
    # half the recent blocks signal an unknown bit (not DEP's)
    unknown = VERSIONBITS_TOP_BITS | (1 << 7)
    chain = build_chain([SIGNAL, unknown] * 8)
    n = unknown_version_signalling(chain[-1], (DEP,), WINDOW)
    assert n == 2  # window=4 lookback: 2 of the last 4 blocks
    assert unknown_version_signalling(chain[-1], (DEP, VBDeployment("x", 7, 0, NO_TIMEOUT)), WINDOW) == 0
