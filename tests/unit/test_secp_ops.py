"""Differential tests: TPU secp256k1 field/point/verify vs the Python-int
oracle (crypto/secp256k1.py) — the secp tests.c randomized-identity strategy
(SURVEY.md §5.4.4)."""

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bitcoincashplus_tpu.crypto import secp256k1 as oracle
from bitcoincashplus_tpu.ops import secp256k1 as S

rng = random.Random(4242)


def rand_field(n):
    return [rng.randrange(oracle.P) for _ in range(n)]


def limbs(vals):
    return jnp.asarray(S.pack_batch_np(vals))


def unpack(arr):
    a = np.asarray(arr)
    return [S.from_limbs_np(a[:, k]) for k in range(a.shape[1])]


class TestFieldOps:
    def test_mul(self):
        va, vb = rand_field(64), rand_field(64)
        out = unpack(jax.jit(S.f_mul)(limbs(va), limbs(vb)))
        for g, a, b in zip(out, va, vb):
            assert g % oracle.P == a * b % oracle.P

    def test_mul_extremes(self):
        va = [0, 1, oracle.P - 1, oracle.P - 1, 2**256 % oracle.P, 0x1FFF]
        vb = [5, oracle.P - 1, oracle.P - 1, 1, 977, 0x1FFF]
        out = unpack(jax.jit(S.f_mul)(limbs(va), limbs(vb)))
        for g, a, b in zip(out, va, vb):
            assert g % oracle.P == a * b % oracle.P

    def test_add_sub_roundtrip(self):
        va, vb = rand_field(32), rand_field(32)
        add = unpack(jax.jit(lambda a, b: S.f_carry(S.f_add(a, b)))(limbs(va), limbs(vb)))
        sub = unpack(jax.jit(S.f_carry_sub)(limbs(va), limbs(vb)))
        for g, a, b in zip(add, va, vb):
            assert g % oracle.P == (a + b) % oracle.P
        for g, a, b in zip(sub, va, vb):
            assert g % oracle.P == (a - b) % oracle.P

    def test_canonical_and_eq(self):
        va = rand_field(16)
        # a and a+p must compare equal; a and a+1 must not
        a_pl = limbs(va)
        b_pl = limbs([(v + oracle.P) % (1 << 260) for v in va])  # non-canonical alias
        c_pl = limbs([(v + 1) % oracle.P for v in va])
        eq_ab = np.asarray(jax.jit(S.f_eq)(a_pl, b_pl))
        eq_ac = np.asarray(jax.jit(S.f_eq)(a_pl, c_pl))
        assert eq_ab.all()
        assert not eq_ac.any()
        canon = unpack(jax.jit(S.f_canonical)(b_pl))
        for g, v in zip(canon, va):
            assert g == v

    def test_sqr_matches_mul(self):
        va = rand_field(32)
        sq = unpack(jax.jit(S.f_sqr)(limbs(va)))
        for g, a in zip(sq, va):
            assert g % oracle.P == a * a % oracle.P


def _scalar_mult_device(ks, pts):
    """Device k*Q for test purposes: reuses the verify loop with u1=0."""
    B = len(ks)
    bits = np.zeros((256, B), np.uint32)
    for j, k in enumerate(ks):
        for i in range(256):
            bits[i, j] = (k >> (255 - i)) & 1
    qx = limbs([p[0] for p in pts])
    qy = limbs([p[1] for p in pts])

    @jax.jit
    def run(bits, qx, qy):
        B = qx.shape[1]
        never = jnp.zeros((B,), bool)

        def step(i, acc):
            acc = S.pt_double(acc)
            added = S.pt_add_mixed(acc, qx, qy, never)
            return S.pt_select(bits[i].astype(bool), added, acc)

        acc = jax.lax.fori_loop(0, 256, step, S.pt_infinity(B))
        return (
            S.f_canonical(acc["X"]),
            S.f_canonical(acc["Y"]),
            S.f_canonical(acc["Z"]),
            acc["inf"],
        )

    X, Y, Z, inf = run(jnp.asarray(bits), qx, qy)
    out = []
    for j, (x, y, z) in enumerate(zip(unpack(X), unpack(Y), unpack(Z))):
        if bool(np.asarray(inf)[j]):
            out.append(None)
            continue
        zi = pow(z, oracle.P - 2, oracle.P)
        out.append((x * zi * zi % oracle.P, y * zi * zi * zi % oracle.P))
    return out


@pytest.mark.slow
class TestPointOps:
    def test_scalar_mult_matches_oracle(self):
        ks = [1, 2, 3, 0, oracle.N - 1, rng.randrange(oracle.N), rng.randrange(oracle.N)]
        pts = [oracle.G] * len(ks)
        got = _scalar_mult_device(ks, pts)
        for k, g in zip(ks, got):
            expect = oracle.point_mul(k, oracle.G)
            assert g == expect, f"k={k}"

    def test_scalar_mult_random_points(self):
        ks, pts = [], []
        for _ in range(5):
            d = rng.randrange(1, oracle.N)
            pts.append(oracle.point_mul(d, oracle.G))
            ks.append(rng.randrange(oracle.N))
        got = _scalar_mult_device(ks, pts)
        for k, p, g in zip(ks, pts, got):
            assert g == oracle.point_mul(k, p)

    def test_distributivity_on_device(self):
        # (a+b)G == aG + bG via two device multiplies + oracle add
        a, b = rng.randrange(oracle.N), rng.randrange(oracle.N)
        got = _scalar_mult_device([a, b, (a + b) % oracle.N], [oracle.G] * 3)
        assert oracle.point_add(got[0], got[1]) == got[2]


def _make_sig_batch(n_valid, n_invalid):
    """Returns (u1b, u2b, qx, qy, qinf, r0, rn, wrap_ok, expected)."""
    entries = []
    for i in range(n_valid + n_invalid):
        d = rng.randrange(1, oracle.N)
        pub = oracle.point_mul(d, oracle.G)
        e = rng.randrange(1 << 256)
        r, s = oracle.ecdsa_sign(d, e)
        valid = i < n_valid
        if not valid:
            kind = i % 3
            if kind == 0:
                e = (e + 1) % (1 << 256)  # wrong message
            elif kind == 1:
                r = (r + 1) % oracle.N or 1  # corrupt r
            else:
                pub = oracle.point_mul(d + 1, oracle.G)  # wrong key
        assert oracle.ecdsa_verify(pub, r, s, e) == valid
        entries.append((pub, r, s, e, valid))

    B = len(entries)
    u1b = np.zeros((256, B), np.uint32)
    u2b = np.zeros((256, B), np.uint32)
    r0v, rnv, qxv, qyv, expected = [], [], [], [], []
    for j, (pub, r, s, e, valid) in enumerate(entries):
        w = pow(s, oracle.N - 2, oracle.N)
        u1, u2 = e * w % oracle.N, r * w % oracle.N
        for i in range(256):
            u1b[i, j] = (u1 >> (255 - i)) & 1
            u2b[i, j] = (u2 >> (255 - i)) & 1
        r0v.append(r)
        rnv.append(r + oracle.N)  # kernel's wrap_ok mask gates admissibility
        qxv.append(pub[0])
        qyv.append(pub[1])
        expected.append(valid)
    qinf = jnp.zeros((B,), bool)
    wrap_ok = jnp.asarray(
        np.array([r + oracle.N < oracle.P for r in r0v])
    )
    return (
        jnp.asarray(u1b), jnp.asarray(u2b), limbs(qxv), limbs(qyv), qinf,
        limbs(r0v), limbs(rnv), wrap_ok, expected,
    )


@pytest.mark.slow
class TestVerifyBatch:
    def test_valid_and_invalid_lanes(self):
        u1b, u2b, qx, qy, qinf, r0, rn, wrap, expected = _make_sig_batch(5, 4)
        got = np.asarray(
            S.ecdsa_verify_batch_jit(u1b, u2b, qx, qy, qinf, r0, rn, wrap)
        )
        assert got.tolist() == expected

    def test_poisoned_lane_reports_false(self):
        u1b, u2b, qx, qy, _, r0, rn, wrap, expected = _make_sig_batch(2, 0)
        qinf = jnp.asarray(np.array([False, True]))
        got = np.asarray(
            S.ecdsa_verify_batch_jit(u1b, u2b, qx, qy, qinf, r0, rn, wrap)
        )
        assert got.tolist() == [True, False]
