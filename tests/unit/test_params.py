"""Chain-parameter tests — genesis self-consistency is our strongest offline
consensus anchor (SURVEY.md §8.5.3)."""

import pytest

# NB: alias the testnet accessor — a bare `testnet_params` name would be
# collected by pytest as a test function.
from bitcoincashplus_tpu.consensus.params import (
    get_block_subsidy,
    main_params,
    regtest_params,
    select_params,
)
from bitcoincashplus_tpu.consensus.params import testnet_params as get_testnet_params
from bitcoincashplus_tpu.consensus.tx import COIN


class TestGenesis:
    def test_mainnet_genesis_hash(self):
        assert main_params().genesis.hash_hex == (
            "000000000019d6689c085ae165831e934ff763ae46a2a6c172b3f1b60a8ce26f"
        )

    def test_testnet_genesis_hash(self):
        assert get_testnet_params().genesis.hash_hex == (
            "000000000933ea01ad0ee984209779baaec3ced90fa3f408719526f8d77f4943"
        )

    def test_regtest_genesis_hash(self):
        assert regtest_params().genesis.hash_hex == (
            "0f9188f13cb7b2c71f2a335e3a4fc328bf5beb436012afca590b1a11466e2206"
        )

    def test_genesis_merkle_equals_coinbase_txid(self):
        for params in (main_params(), get_testnet_params(), regtest_params()):
            g = params.genesis
            assert g.header.hash_merkle_root == g.vtx[0].txid


class TestSelect:
    def test_select(self):
        assert select_params("main").network == "main"
        assert select_params("regtest").network == "regtest"
        assert select_params("testnet").network == "test"
        with pytest.raises(ValueError):
            select_params("nope")


class TestSubsidy:
    def test_halving_schedule_main(self):
        c = main_params().consensus
        assert get_block_subsidy(0, c) == 50 * COIN
        assert get_block_subsidy(209_999, c) == 50 * COIN
        assert get_block_subsidy(210_000, c) == 25 * COIN
        assert get_block_subsidy(420_000, c) == 12 * COIN + COIN // 2
        assert get_block_subsidy(64 * 210_000, c) == 0

    def test_total_supply_under_cap(self):
        c = main_params().consensus
        total = sum(
            get_block_subsidy(h * c.subsidy_halving_interval, c)
            * c.subsidy_halving_interval
            for h in range(70)
        )
        assert total < 21_000_000 * COIN

    def test_regtest_halving(self):
        c = regtest_params().consensus
        assert get_block_subsidy(150, c) == 25 * COIN
