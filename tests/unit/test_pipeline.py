"""Pipelined IBD validation engine — settle horizon, cross-block lane
packer, late-settle unwind, and the BIP30/sigcache satellites (ISSUE 4).

The load-bearing guarantees under test:
  - pipelined and serial engines produce byte-identical coin sets and
    identical per-block verdicts on the same block sequence (both
    feeding orders);
  - a block whose signature batch fails AFTER K descendants were
    speculatively connected unwinds to the byte-identical pre-block
    coin set, and nothing past the horizon is externalized early;
  - the cross-block lane packer attributes a bad lane to the right
    block even when blocks share (or split across) device dispatches.

Marker: ``pipeline`` — conftest orders these after the plain unit suite
and before the functional/adversarial campaigns; everything here runs
under JAX_PLATFORMS=cpu in tier-1 (backend="cpu" end to end).
"""

import functools
import hashlib

import numpy as np
import pytest

from bitcoincashplus_tpu.consensus.params import regtest_params
from bitcoincashplus_tpu.consensus.tx import COutPoint, CTransaction, CTxIn, CTxOut
from bitcoincashplus_tpu.mining.generate import generate_blocks
from bitcoincashplus_tpu.ops import dispatch, ecdsa_batch
from bitcoincashplus_tpu.store.blockstore import MemoryBlockStore
from bitcoincashplus_tpu.validation.chain import BlockStatus
from bitcoincashplus_tpu.validation.chainstate import (
    BlockValidationError,
    ChainstateManager,
)
from bitcoincashplus_tpu.validation.coins import MemoryCoinsView
from bitcoincashplus_tpu.validation.scriptcheck import BlockScriptVerifier
from bitcoincashplus_tpu.validation.sigcache import SignatureCache
from bitcoincashplus_tpu.wallet.keys import CKey
from bitcoincashplus_tpu.wallet.signing import sign_transaction

from test_validation import TILE, _hand_mine

pytestmark = pytest.mark.pipeline

KEY = CKey(0xDEADBEEFCAFE)
SPK = KEY.p2pkh_script()


def _make_cs(depth: int = 1, start_time: int = 1_600_000_000):
    import dataclasses

    # regtest_params() is lru_cached — give each chainstate its OWN
    # checkpoints dict so per-test checkpoint edits can't leak globally
    params = regtest_params()
    params = dataclasses.replace(
        params, checkpoints=dict(params.checkpoints))
    t = [start_time]

    def fake_time():
        t[0] += 60
        return t[0]

    verifier = BlockScriptVerifier(params, backend="cpu")
    cs = ChainstateManager(
        params, MemoryCoinsView(), MemoryBlockStore(),
        script_verifier=verifier, get_time=fake_time,
    )
    cs.pipeline_depth = depth
    return cs


def _signed_spend(op, value, key=KEY, out_spk=SPK, fee=10_000):
    tx = CTransaction(vin=(CTxIn(op),), vout=(CTxOut(value - fee, out_spk),))
    return sign_transaction(
        tx, [(SPK, value)],
        lambda i: key if i in (key.pubkey_hash, key.pubkey) else None,
        enable_forkid=True,
    )


def _coin_digest(cs) -> str:
    """Byte digest of the SETTLED coin set (cache flushed into the memory
    base, rows key-sorted)."""
    cs.coins.flush()
    base = cs.coins.base
    h = hashlib.sha256()
    for op, coin in sorted(base._coins.items(),
                           key=lambda kv: (kv[0].hash, kv[0].n)):
        h.update(op.hash)
        h.update(op.n.to_bytes(4, "little"))
        h.update(coin.serialize())
    h.update(cs.coins.best_block())
    return h.hexdigest()


def _tampered(spend, op):
    """Flip one byte inside the DER s-value: encodings stay valid (the
    host scan passes and DEFERS the record), the math fails — the verdict
    can only arrive at signature settle."""
    ss = bytearray(spend.vin[0].script_sig)
    ss[40] ^= 0x01
    return CTransaction(spend.version, (CTxIn(op, bytes(ss)),),
                        spend.vout, spend.locktime)


RUNWAY = 104


@functools.lru_cache(maxsize=None)
def _runway_blocks():
    """Mine the 104-block coinbase runway ONCE per test session; replayers
    get the blocks plus the miner's final clock value (their fake clocks
    start there so time-too-new can never fire on replay)."""
    src = _make_cs()
    generate_blocks(src, SPK, RUNWAY, tile=TILE)
    blocks = tuple(src.get_block(src.chain[h].hash)
                   for h in range(1, RUNWAY + 1))
    return blocks, src.get_time()


def _with_runway(depth: int = 1):
    """A chainstate with the shared runway replayed onto it — identical
    tip/coin state across instances, no re-mining."""
    blocks, t_base = _runway_blocks()
    cs = _make_cs(depth, start_time=t_base)
    for b in blocks:
        cs.process_new_block(b)
    return cs


@functools.lru_cache(maxsize=None)
def _build_sequence(n_good_pre=2, bad=False, n_children=2):
    """A replayable block sequence on a throwaway source chain (runway +
    sequence): n_good_pre valid signed spends, optionally one
    bad-signature block B (tampered s-value — passes the host scan, fails
    at signature settle), then n_children empty blocks built ON B.
    Cached: blocks are treated read-only by every consumer."""
    src = _with_runway()
    runway = tuple(src.get_block(src.chain[h].hash)
                   for h in range(1, src.tip().height + 1))
    seq = []

    def extend(txs):
        tip = src.tip()
        blk = _hand_mine(tip.hash, tip.height + 1, src.get_time() + 10,
                         tip.bits, txs)
        # grow the source chain WITHOUT script checks so invalid-sig blocks
        # can be built upon (children must reference B as their parent)
        sv, src.script_verifier = src.script_verifier, None
        try:
            src.process_new_block(blk)
        finally:
            src.script_verifier = sv
        seq.append(blk)
        return blk

    spendables = [(COutPoint(runway[h].vtx[0].txid, 0),
                   runway[h].vtx[0].vout[0].value)
                  for h in range(0, 4)]
    for k in range(n_good_pre):
        extend((_signed_spend(*spendables[k]),))
    if bad:
        op, value = spendables[n_good_pre]
        extend((_tampered(_signed_spend(op, value), op),))
        for _ in range(n_children):
            extend(())
    return runway, tuple(seq)


def _feed(cs, blocks, pipelined: bool):
    verdicts = []
    for blk in blocks:
        try:
            if pipelined:
                cs.process_new_block_pipelined(blk)
            else:
                cs.process_new_block(blk)
            verdicts.append("ok")
        except BlockValidationError as e:
            verdicts.append(e.reason)
    cs.settle_horizon()
    return verdicts


class TestPipelinedEquivalence:
    def test_valid_chain_identical_coin_set(self):
        runway, seq = _build_sequence(n_good_pre=3, bad=False)
        results = {}
        for depth in (1, 3):
            cs = _with_runway(depth)
            _feed(cs, seq, pipelined=(depth > 1))
            results[depth] = (cs.tip().hash, _coin_digest(cs))
        assert results[1] == results[3]

    def test_differential_both_orders(self):
        """The serial and pipelined engines must accept/reject the SAME
        set of blocks (a pipelined verdict just lands later, at settle)
        and land on the identical tip + byte-identical coin set for a
        sequence containing a bad-signature block — whichever engine runs
        first."""
        runway, seq = _build_sequence(n_good_pre=2, bad=True, n_children=2)
        bad_and_children = {b.get_hash() for b in seq[2:]}

        def active_set(cs):
            return {cs.chain[h].hash
                    for h in range(cs.tip().height + 1)}

        outcomes = []
        for order in (("serial", "pipelined"), ("pipelined", "serial")):
            pair = {}
            for mode in order:
                cs = _with_runway(5 if mode == "pipelined" else 1)
                _feed(cs, seq, pipelined=(mode == "pipelined"))
                active = active_set(cs)
                assert not (active & bad_and_children), mode
                pair[mode] = (cs.tip().hash, frozenset(active),
                              _coin_digest(cs))
            assert pair["serial"] == pair["pipelined"], order
            outcomes.append(pair["serial"])
        assert outcomes[0] == outcomes[1]

    def test_max_depth_bounded(self):
        runway, seq = _build_sequence(n_good_pre=3, bad=False)
        cs = _with_runway(2)
        _feed(cs, seq, pipelined=True)
        assert 0 < cs.pipeline_stats["max_depth"] <= 2
        snap = cs.pipeline_snapshot()
        for key in ("depth", "in_horizon", "settled_blocks", "unwinds",
                    "scan_ms", "settle_wait_ms", "commit_ms",
                    "overlap_fraction", "packer"):
            assert key in snap
        assert snap["in_horizon"] == 0


class TestLateSettleFailure:
    def test_unwind_restores_pre_block_coin_set(self):
        """Block B's batch fails after K=2 children were speculatively
        connected: the coin set must come back byte-identical to the
        pre-B state, B marked invalid, children FAILED_CHILD, and the
        serial engine must reach the same tip + coin set."""
        runway, seq = _build_sequence(n_good_pre=1, bad=True, n_children=2)
        cs = _with_runway(depth=6)  # deep enough that B settles late
        good, bad_blk, child1, child2 = seq
        cs.process_new_block_pipelined(good)
        cs.settle_horizon()
        pre = _coin_digest(cs)
        pre_tip = cs.tip()

        cs.process_new_block_pipelined(bad_blk)
        cs.process_new_block_pipelined(child1)
        cs.process_new_block_pipelined(child2)
        # all three are speculative: the settled world hasn't moved
        assert len(cs._horizon) == 3
        assert cs.settled_tip() is pre_tip
        assert cs.chain.tip().hash == child2.get_hash()

        cs.settle_horizon()  # B's batch fails here -> full unwind
        assert cs.tip() is pre_tip
        assert _coin_digest(cs) == pre
        assert cs.pipeline_stats["unwinds"] == 1
        assert cs.pipeline_stats["unwound_blocks"] == 3
        b_idx = cs.block_index[bad_blk.get_hash()]
        assert b_idx.status & BlockStatus.FAILED_VALID
        for child in (child1, child2):
            c_idx = cs.block_index[child.get_hash()]
            assert c_idx.status & BlockStatus.FAILED_CHILD

        # differential: the serial engine on the same sequence lands on
        # the identical tip and byte-identical coin set
        cs2 = _with_runway(1)
        _feed(cs2, seq, pipelined=False)
        assert cs2.tip().hash == pre_tip.hash
        assert _coin_digest(cs2) == pre

    def test_unwind_leaves_no_inflight_dispatches(self):
        runway, seq = _build_sequence(n_good_pre=1, bad=True, n_children=2)
        cs = _with_runway(depth=6)
        _feed(cs, seq, pipelined=True)
        assert ecdsa_batch.STATS.in_flight == 0
        if cs._packer is not None:
            assert cs._packer.snapshot()["pending_lanes"] == 0

    def test_backpressure_triggers_unwind_mid_feed(self):
        """With a shallow horizon the bad block's settle fires from the
        backpressure path while later blocks are being fed; children must
        then be rejected on accept (bad-prevblk), like the serial engine's
        ordering would produce."""
        runway, seq = _build_sequence(n_good_pre=2, bad=True, n_children=3)
        cs = _with_runway(depth=2)
        verdicts = _feed(cs, seq, pipelined=True)
        assert cs.pipeline_stats["unwinds"] == 1
        assert "bad-prevblk" in verdicts  # a late child hit dead ancestry
        cs2 = _with_runway(1)
        _feed(cs2, seq, pipelined=False)
        assert cs2.tip().hash == cs.tip().hash
        assert _coin_digest(cs2) == _coin_digest(cs)


def _oracle_records(n, bad_at=()):
    from bitcoincashplus_tpu.crypto import secp256k1 as oracle
    from bitcoincashplus_tpu.script.interpreter import SigCheckRecord

    recs = []
    for i in range(n):
        secret = 0xC0FFEE + 31 * i
        pub = oracle.point_mul(secret, oracle.G)
        e = (0xFACE0FF + i) % oracle.N
        r, s = oracle.ecdsa_sign(secret, e)
        if i in bad_at:
            e = (e + 1) % oracle.N  # wrong message: verifies False
        recs.append(SigCheckRecord(pub, r, s, e))
    return recs


class TestLanePacker:
    def test_per_block_futures_and_attribution(self):
        packer = ecdsa_batch.LanePacker(backend="cpu", lanes=8)
        g1 = _oracle_records(3)
        g2 = _oracle_records(4, bad_at=(2,))
        g3 = _oracle_records(2)
        f1, f2, f3 = packer.add(g1), packer.add(g2), packer.add(g3)
        packer.flush()
        assert f1.result().all()
        ok2 = f2.result()
        assert list(ok2) == [True, True, False, True]
        assert f3.result().all()
        snap = packer.snapshot()
        assert snap["blocks"] == 3
        assert snap["lanes_real"] == 9
        assert snap["pending_lanes"] == 0

    def test_block_split_across_dispatches(self):
        """A block bigger than the lane target spans multiple shared
        dispatches; its future still returns lanes in submission order."""
        packer = ecdsa_batch.LanePacker(backend="cpu", lanes=4)
        recs = _oracle_records(10, bad_at=(7,))
        fut = packer.add(recs)
        packer.flush()
        ok = fut.result()
        assert len(ok) == 10
        assert list(np.nonzero(~ok)[0]) == [7]
        assert packer.snapshot()["dispatches"] >= 3

    def test_settle_forces_flush_of_parked_lanes(self):
        """result() on a future whose lanes are still parked behind the
        fill target must flush rather than deadlock."""
        packer = ecdsa_batch.LanePacker(backend="cpu", lanes=1 << 20)
        fut = packer.add(_oracle_records(2))
        assert fut.result().all()  # no explicit flush()
        assert packer.snapshot()["pending_lanes"] == 0

    def test_drain_discards_parked_lanes(self):
        """Abort-path drain must DISCARD a future's still-parked lanes
        (never verify doomed work) while leaving other futures' records
        and offsets intact."""
        packer = ecdsa_batch.LanePacker(backend="cpu", lanes=1 << 20)
        f1 = packer.add(_oracle_records(3))
        f2 = packer.add(_oracle_records(2, bad_at=(0,)))
        f2.drain()
        snap = packer.snapshot()
        assert snap["lanes_discarded"] == 2
        assert f1.result().all()  # offsets survive the mid-buffer discard
        assert packer.snapshot()["lanes_real"] == 3
        assert packer.snapshot()["pending_lanes"] == 0

    def test_unhealthy_breaker_disables_aggregation(self):
        dispatch.reset()
        try:
            br = dispatch.breaker("ecdsa")
            for _ in range(br.cfg.threshold):
                br.record_failure(RuntimeError("boom"))
            assert not br.healthy()
            packer = ecdsa_batch.LanePacker(backend="auto", lanes=1 << 20)
            fut = packer.add(_oracle_records(2))
            # device distrusted: records dispatched immediately, not parked
            assert packer.snapshot()["pending_lanes"] == 0
            assert fut.result().all()
        finally:
            dispatch.reset()


class TestSupervisedEnqueue:
    def test_async_settle_supervision(self):
        dispatch.reset()
        try:
            h = dispatch.supervised_enqueue(
                "pipetest", lambda: (lambda: 7), lambda: -1, items=3)
            assert h.result() == 7 and h.used_device
            # enqueue failure: breaker charged, CPU verdict served
            h2 = dispatch.supervised_enqueue(
                "pipetest", lambda: (_ for _ in ()).throw(RuntimeError("x")),
                lambda: -1)
            assert h2.result() == -1 and not h2.used_device
            # settle-time failure: supervision still catches it
            def enqueue():
                def settle():
                    raise RuntimeError("died at settle")
                return settle
            h3 = dispatch.supervised_enqueue("pipetest", enqueue, lambda: -2)
            assert h3.result() == -2 and not h3.used_device
            snap = dispatch.breaker("pipetest").snapshot()
            assert snap["consecutive_failures"] >= 1
            assert snap["fallback_calls"] >= 2
            # validation probe gates the accept side
            h4 = dispatch.supervised_enqueue(
                "pipetest", lambda: (lambda: 9), lambda: -3,
                validate=lambda out: out == 10)
            assert h4.result() == -3
        finally:
            dispatch.reset()


class TestSigCacheSatellite:
    def test_counters_and_entry_cap_lru(self):
        c = SignatureCache(max_entries=3)
        keys = [bytes([i]) * 129 for i in range(5)]
        for k in keys[:3]:
            c.add(k)
        assert c.inserts == 3 and len(c) == 3
        assert c.contains(keys[0])  # refresh 0 -> 1 is now stalest
        assert not c.contains(keys[4])
        c.add(keys[3])  # evicts 1 (LRU), not 0
        assert c.evictions == 1
        assert c.contains(keys[0]) and not c.contains(keys[1])
        snap = c.snapshot()
        assert snap["entries"] == 3 and snap["inserts"] == 4
        assert snap["hits"] == 2 and snap["evictions"] == 1
        assert 0 < snap["hit_rate"] < 1

    def test_byte_cap_binds(self):
        from bitcoincashplus_tpu.validation.sigcache import ENTRY_COST_BYTES

        c = SignatureCache(max_entries=1 << 20,
                           max_bytes=2 * ENTRY_COST_BYTES)
        for i in range(4):
            c.add(bytes([i]) * 129)
        assert len(c) == 2
        assert c.evictions == 2
        assert c.estimated_bytes() <= 2 * ENTRY_COST_BYTES


class TestBIP30Satellite:
    def test_duplicate_tx_rejected_via_cache_resident_probe(self):
        """A tx duplicated in a later block trips BIP30 from the cache
        layer (its unspent outputs are resident), without a store probe."""
        cs = _make_cs()
        generate_blocks(cs, SPK, 104, tile=TILE)
        blk1 = cs.get_block(cs.chain[1].hash)
        spend = _signed_spend(COutPoint(blk1.vtx[0].txid, 0),
                              blk1.vtx[0].vout[0].value)
        tip = cs.tip()
        a = _hand_mine(tip.hash, tip.height + 1, cs.get_time() + 10,
                       tip.bits, (spend,))
        cs.process_new_block(a)
        assert cs.tip().hash == a.get_hash()
        before = dict(cs.bip30_stats)
        b = _hand_mine(a.get_hash(), tip.height + 2, cs.get_time() + 10,
                       tip.bits, (spend,))  # same tx again
        idx = cs.accept_block(b)
        with pytest.raises(BlockValidationError) as ei:
            cs.connect_block(b, idx)
        assert ei.value.reason == "bad-txns-BIP30"
        st = cs.bip30_stats
        assert st["lookups"] > before["lookups"]
        assert st["cache_resolved"] > before["cache_resolved"]

    def test_scan_skipped_above_checkpoint(self):
        """Core's BIP34-era exemption: above the last active-chain
        checkpoint the per-output scan is skipped entirely."""
        cs = _make_cs()
        generate_blocks(cs, SPK, 2, tile=TILE)
        cs.params.checkpoints[1] = cs.chain[1].hash
        before = dict(cs.bip30_stats)
        generate_blocks(cs, SPK, 3, tile=TILE)
        st = cs.bip30_stats
        # >= 3: mine_block's TestBlockValidity dry-run connects each block
        # once more, and the dry-run skips too
        assert st["skipped_scans"] >= before["skipped_scans"] + 3
        assert st["skipped_lookups"] > before["skipped_lookups"]
        assert st["lookups"] == before["lookups"]

    def test_no_checkpoints_means_no_skip(self):
        cs = _make_cs()
        before = dict(cs.bip30_stats)
        generate_blocks(cs, SPK, 2, tile=TILE)
        st = cs.bip30_stats
        assert st["skipped_scans"] == before["skipped_scans"]
        assert st["lookups"] > before["lookups"]


class TestNodeKnob:
    def test_pipelinedepth_flag_wires_through(self, tmp_path):
        from bitcoincashplus_tpu.node.config import Config
        from bitcoincashplus_tpu.node.node import Node

        cfg = Config()
        cfg.args["datadir"] = [str(tmp_path)]
        cfg.args["regtest"] = ["1"]
        cfg.args["pipelinedepth"] = ["3"]
        node = Node(config=cfg)
        try:
            assert node.chainstate.pipeline_depth == 3
            snap = node.chainstate.pipeline_snapshot()
            assert snap["depth"] == 3 and snap["in_horizon"] == 0
        finally:
            node.close()
