"""The "dead backend" end-to-end acceptance test: with fail-always faults
armed on EVERY TPU subsystem, a multi-block connect run (including signed
spends, a large-ish merkle block, mining, and batched header PoW) must
complete with verdicts and a final coin set byte-identical to the pure-CPU
reference engine, while every circuit breaker reports open with nonzero
fallback counts — the whole robustness tentpole in one scenario."""

import numpy as np
import pytest

from bitcoincashplus_tpu.consensus.tx import COutPoint, CTransaction, CTxIn, CTxOut
from bitcoincashplus_tpu.consensus.params import regtest_params
from bitcoincashplus_tpu.mining.generate import generate_blocks
from bitcoincashplus_tpu.ops import dispatch, ecdsa_batch
from bitcoincashplus_tpu.store.blockstore import MemoryBlockStore
from bitcoincashplus_tpu.validation.chainstate import ChainstateManager
from bitcoincashplus_tpu.validation.coins import MemoryCoinsView
from bitcoincashplus_tpu.validation.scriptcheck import BlockScriptVerifier
from bitcoincashplus_tpu.wallet.keys import CKey
from bitcoincashplus_tpu.wallet.signing import sign_transaction

from test_validation import TILE, _hand_mine

pytestmark = pytest.mark.faults

KEY = CKey(0xFEEDFACE1234)
SPK_KEY = KEY.p2pkh_script()
SPK_SINK = bytes.fromhex("76a914") + b"\x99" * 20 + bytes.fromhex("88ac")


def _build_chainstate(backend: str, start: int = 1_600_000_000):
    params = regtest_params()
    t = [start]

    def fake_time():
        t[0] += 60
        return t[0]

    base = MemoryCoinsView()
    cs = ChainstateManager(
        params, base, MemoryBlockStore(),
        script_verifier=BlockScriptVerifier(params, backend=backend),
        get_time=fake_time,
    )
    cs.test_base = base
    cs.test_clock = t
    return cs


def _coin_set(cs) -> dict:
    """Byte-exact snapshot of the flushed UTXO set + best-block marker."""
    cs.coins.flush()
    coins = {
        (op.hash, op.n): coin.serialize()
        for op, coin in cs.test_base.all_coins()
    }
    coins["best"] = cs.test_base.best_block()
    return coins


@pytest.fixture
def fake_ecdsa_kernel(monkeypatch):
    """Oracle-backed stand-in for the XLA ECDSA kernel (the real one costs
    minutes of compile on the CPU test backend; the supervision plumbing
    under test is identical). Only reachable through half-open probes —
    with fail-always armed the injector kills the dispatch first."""
    import bitcoincashplus_tpu.ops.secp256k1 as dev
    from bitcoincashplus_tpu.crypto import secp256k1 as oracle

    monkeypatch.setenv("BCP_SECP_PALLAS", "0")
    # pin the w4/XLA kernel so a half-open probe hits this stub, not the
    # real GLV program (which would pay a real kernel compile here)
    monkeypatch.setenv("BCP_ECDSA_KERNEL", "w4")
    state: dict = {"mask": []}
    real_pack = ecdsa_batch.pack_records

    def spy_pack(records, bucket):
        state["mask"] = [
            oracle.ecdsa_verify(r.pubkey, r.r, r.s, r.msg_hash)
            for r in records
        ]
        return real_pack(records, bucket)

    def fake_jit(u1b, u2b, qx, qy, q_inf, r0, rn, wrap_ok):
        out = np.zeros(q_inf.shape[0], bool)
        out[: len(state["mask"])] = state["mask"]
        return out

    monkeypatch.setattr(ecdsa_batch, "pack_records", spy_pack)
    monkeypatch.setattr(dev, "ecdsa_verify_batch_jit", fake_jit)


def test_dead_backend_end_to_end(fault_harness, fake_ecdsa_kernel,
                                 monkeypatch):
    # -- 1. reference run: pure-CPU engine mines the canonical chain ------
    dispatch.reset()
    ref = _build_chainstate(backend="cpu")
    generate_blocks(ref, SPK_KEY, 102, tile=TILE)
    spends = []
    for h in (1, 2):
        blk = ref.get_block(ref.chain[h].hash)
        cb = blk.vtx[0]
        tx = CTransaction(
            vin=(CTxIn(COutPoint(cb.txid, 0)),),
            vout=(CTxOut(cb.vout[0].value - 10_000, SPK_SINK),),
        )
        spends.append(sign_transaction(
            tx, [(SPK_KEY, cb.vout[0].value)],
            lambda i: KEY if i == KEY.pubkey_hash else None,
            enable_forkid=True,
        ))
    tip = ref.tip()
    spend_block = _hand_mine(
        tip.hash, tip.height + 1, ref.get_time() + 10, tip.bits,
        tuple(spends),
    )
    ref.process_new_block(spend_block)
    assert ref.tip().hash == spend_block.get_hash()
    chain_blocks = [ref.get_block(ref.chain[h].hash)
                    for h in range(1, ref.tip().height + 1)]

    # -- 2. faulty run: every TPU op dead, device backend forced ----------
    # breaker: first failure opens, no probes — the dead device stays dead
    dispatch.configure(threshold=1, retries=0, cooldown=1e9, probe=0.0)
    fault_harness("fail-always", ops="all")
    # force the device merkle path even for small blocks so the merkle
    # breaker is exercised during connect
    monkeypatch.setenv("BCP_TPU_MERKLE_MIN", "2")

    # start the faulty node's clock where the reference's ended — the
    # mined headers carry the reference clock's timestamps
    faulty = _build_chainstate(backend="device", start=ref.test_clock[0])
    for blk in chain_blocks:
        faulty.process_new_block(blk)
    assert faulty.tip().hash == ref.tip().hash

    # mining still works on the dead backend (scalar CPU loop under the
    # miner breaker) and the mined block is valid on the reference engine
    mined = generate_blocks(faulty, SPK_SINK, 1, tile=TILE)
    assert len(mined) == 1
    extra = faulty.get_block(mined[0])
    ref.test_clock[0] = faulty.test_clock[0]  # keep the clocks in step
    ref.process_new_block(extra)
    assert ref.tip().hash == faulty.tip().hash

    # batched header PoW (sha256 subsystem) under the dead backend
    from bitcoincashplus_tpu.consensus.pow import check_headers_pow_batch

    headers = [b.header.serialize() for b in chain_blocks[:8]]
    assert check_headers_pow_batch(
        headers, regtest_params().consensus) == [True] * len(headers)

    # -- 3. acceptance: verdicts + coin set byte-identical ----------------
    assert _coin_set(faulty) == _coin_set(ref)

    # -- 4. gettpuinfo: open breakers with nonzero fallback counts --------
    snap = dispatch.snapshot()
    for site in ("ecdsa", "merkle", "miner", "sha256"):
        assert snap[site]["state"] == "open", (site, snap[site])
        assert snap[site]["fallback_items"] > 0, (site, snap[site])
    assert ecdsa_batch.STATS.fault_fallback_sigs >= 2
