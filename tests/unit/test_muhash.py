"""MuHash3072 accumulator algebra (store/muhash.py).

The sharded chainstate's set digest must be a true multiset homomorphism:
order/partition independent, invertible, and the numpy limb batch-product
path must agree bit-for-bit with the python-int reference. These are the
properties the cross-shard digest, snapshot verification, and the
incremental commit-time maintenance all lean on.
"""

import random

import pytest

from bitcoincashplus_tpu.store import muhash


def _rand_elems(rng, n):
    return [muhash.element(rng.randbytes(rng.randint(1, 80)))
            for _ in range(n)]


class TestElement:
    def test_element_is_reduced_and_nonzero(self):
        rng = random.Random(1)
        for _ in range(50):
            e = muhash.element(rng.randbytes(40))
            assert 0 < e < muhash.MUHASH_P

    def test_element_deterministic(self):
        assert muhash.element(b"abc") == muhash.element(b"abc")
        assert muhash.element(b"abc") != muhash.element(b"abd")

    def test_coin_element_binds_key_and_value(self):
        k = b"k" * 36
        assert muhash.coin_element(k, b"v1") != muhash.coin_element(k, b"v2")
        assert muhash.coin_element(k, b"v1") != \
            muhash.coin_element(b"j" * 36, b"v1")


class TestAccumulator:
    def test_insert_remove_roundtrip(self):
        acc = muhash.MuHash()
        base = acc.digest()
        acc.insert(b"one")
        acc.insert(b"two")
        acc.remove(b"one")
        acc.remove(b"two")
        assert acc.digest() == base

    def test_order_independence(self):
        items = [b"a", b"b", b"c", b"d"]
        a, b = muhash.MuHash(), muhash.MuHash()
        for it in items:
            a.insert(it)
        for it in reversed(items):
            b.insert(it)
        assert a.digest() == b.digest()

    def test_apply_batch_equals_singles(self):
        rng = random.Random(2)
        added = [rng.randbytes(20) for _ in range(17)]
        removed = added[:5]
        a = muhash.MuHash()
        for it in added:
            a.insert(it)
        for it in removed:
            a.remove(it)
        b = muhash.MuHash()
        b.apply([muhash.element(x) for x in added],
                [muhash.element(x) for x in removed])
        assert a.digest() == b.digest()

    def test_serialization_roundtrip(self):
        acc = muhash.MuHash()
        acc.insert(b"state")
        again = muhash.MuHash.from_bytes(acc.to_bytes())
        assert again.digest() == acc.digest()
        assert len(acc.to_bytes()) == 384

    def test_partition_independence(self):
        """digest(all) == digest(combine(per-shard states)) for any split
        — the cross-shard invariant gettxoutsetinfo relies on."""
        rng = random.Random(3)
        items = [rng.randbytes(30) for _ in range(40)]
        whole = muhash.MuHash()
        shards = [muhash.MuHash() for _ in range(4)]
        for it in items:
            whole.insert(it)
            shards[rng.randrange(4)].insert(it)
        combined = muhash.combine([s.state for s in shards])
        assert muhash.digest_of(combined) == whole.digest()


class TestBatchProduct:
    @pytest.mark.parametrize("n", [1, 2, 7, 8, 9, 31, 64, 100])
    def test_limb_backend_matches_reference(self, n):
        if muhash._np is None:
            pytest.skip("numpy unavailable")
        rng = random.Random(n)
        vals = _rand_elems(rng, n)
        assert muhash._batch_product_limbs(vals) == \
            muhash.batch_product_ref(vals)

    @pytest.mark.parametrize("n", [1, 8, 100])
    def test_dispatch_matches_reference(self, n):
        rng = random.Random(100 + n)
        vals = _rand_elems(rng, n)
        assert muhash.batch_product(vals) == muhash.batch_product_ref(vals)

    def test_values_near_p(self):
        """Reduction edge: products whose partial results straddle p."""
        if muhash._np is None:
            pytest.skip("numpy unavailable")
        vals = [muhash.MUHASH_P - 1, muhash.MUHASH_P - 2,
                muhash.MUHASH_P - muhash.MUHASH_C, 2, 3, 5, 7, 11]
        assert muhash._batch_product_limbs(vals) == \
            muhash.batch_product_ref(vals)

    def test_empty(self):
        assert muhash.batch_product([]) == 1

    def test_limb_roundtrip(self):
        if muhash._np is None:
            pytest.skip("numpy unavailable")
        rng = random.Random(5)
        vals = _rand_elems(rng, 8)
        limbs = muhash._to_limbs(vals)
        assert [muhash._from_limbs(limbs[i]) for i in range(8)] == vals
