"""Hostile-input / property tests (SURVEY.md §8.6): random and mutated
inputs must produce typed errors — never crashes, hangs, or silent
acceptance. Pure Python, seeded, deterministic."""

import numpy as np
import pytest

from bitcoincashplus_tpu.consensus.serialize import ByteReader
from bitcoincashplus_tpu.consensus.tx import CTransaction
from bitcoincashplus_tpu.mempool.mempool import CTxMemPool
from bitcoincashplus_tpu.consensus.tx import COutPoint, CTxIn, CTxOut
from bitcoincashplus_tpu.script.interpreter import (
    BaseSignatureChecker,
    ScriptError,
    EvalScript,
    VerifyScript,
)
from bitcoincashplus_tpu.p2p.protocol import (
    MessageHeader,
    NetMessageError,
    check_payload,
    deser_headers,
    deser_inv,
)


class _NullChecker(BaseSignatureChecker):
    pass


def test_random_scripts_never_crash():
    """4k random byte strings through EvalScript: the only acceptable
    failure is ScriptError (typed, attributable)."""
    rng = np.random.default_rng(0xF0)
    for _ in range(4000):
        script = rng.bytes(rng.integers(0, 64))
        stack = [b"\x01"] * int(rng.integers(0, 4))
        try:
            EvalScript(stack, script, 0, _NullChecker())
        except ScriptError:
            pass


def test_random_script_pairs_verify():
    rng = np.random.default_rng(0xF1)
    for _ in range(1500):
        sig = rng.bytes(rng.integers(0, 32))
        spk = rng.bytes(rng.integers(0, 48))
        flags = int(rng.integers(0, 1 << 17))
        try:
            VerifyScript(sig, spk, flags, _NullChecker())
        except (ScriptError, AssertionError):
            # AssertionError only from the CLEANSTACK-without-P2SH pairing
            # assert, which mirrors the reference's own assert
            pass


def test_mutated_tx_bytes_never_crash():
    """Bit-flipped and truncated real transactions either round-trip or
    raise the serializer's typed error."""
    from bitcoincashplus_tpu.consensus.serialize import DeserializationError
    from bitcoincashplus_tpu.consensus.params import regtest_params

    base = regtest_params().genesis.vtx[0].serialize()
    rng = np.random.default_rng(0xF2)
    for _ in range(1500):
        raw = bytearray(base)
        for _ in range(int(rng.integers(1, 4))):
            raw[int(rng.integers(0, len(raw)))] ^= int(rng.integers(1, 256))
        cut = int(rng.integers(1, len(raw) + 1))
        try:
            CTransaction.deserialize(ByteReader(bytes(raw[:cut])))
        except (DeserializationError, ValueError):
            pass


def test_p2p_garbage_never_crashes():
    """Random wire headers / inv / headers payloads raise NetMessageError
    (the discharge path), never anything else."""
    rng = np.random.default_rng(0xF3)
    magic = b"\xfa\xbf\xb5\xda"
    for _ in range(2000):
        raw = rng.bytes(24)
        try:
            MessageHeader.parse(bytes(raw), magic)
        except NetMessageError:
            pass
    for _ in range(2000):
        payload = rng.bytes(rng.integers(0, 64))
        for fn in (deser_inv, deser_headers):
            try:
                fn(payload)
            except NetMessageError:
                pass


def test_mempool_aggregate_invariants_random_ops():
    """mempool_tests.cpp-style bookkeeping check: after any interleaving of
    adds and removes, every entry's ancestor/descendant aggregates must
    equal what a from-scratch graph walk computes."""
    rng = np.random.default_rng(0xF4)
    pool = CTxMemPool()
    txs = {}  # txid -> tx

    def free_outpoint(parent):
        for n in range(2):
            op = COutPoint(parent, n)
            if op not in pool.map_next_tx:
                return op
        return None

    def mk_tx(parents):
        vin = []
        for p in parents:
            op = free_outpoint(p)
            if op is not None:
                vin.append(CTxIn(op))
        if not vin:
            vin = [CTxIn(COutPoint(rng.bytes(32), 0))]
        vout = (CTxOut(10_000, b"\x51"), CTxOut(10_000, b"\x52"))
        return CTransaction(vin=tuple(vin), vout=vout)

    def walk(txid, direction):
        """Transitive closure over in-pool parents/children incl. self."""
        seen, todo = set(), [txid]
        while todo:
            t = todo.pop()
            if t in seen or t not in pool.entries:
                continue
            seen.add(t)
            e = pool.entries[t]
            if direction == "up":
                nxt = {i.prevout.hash for i in e.tx.vin
                       if i.prevout.hash in pool.entries}
            else:
                nxt = {pool.map_next_tx[COutPoint(t, n)]
                       for n in range(len(e.tx.vout))
                       if COutPoint(t, n) in pool.map_next_tx}
            todo.extend(nxt)
        return seen

    for step in range(300):
        op = rng.random()
        if op < 0.7 or not pool.entries:
            n_parents = int(rng.integers(0, min(3, len(pool.entries) + 1)))
            parents = list(rng.choice(
                [t for t in pool.entries], size=n_parents, replace=False
            )) if n_parents and pool.entries else []
            tx = mk_tx(parents)
            if tx.txid in pool.entries:
                continue
            txs[tx.txid] = tx
            from bitcoincashplus_tpu.mempool.mempool import MempoolEntry

            pool.add_unchecked(MempoolEntry(tx, fee=1000, entry_time=step,
                                            entry_height=1))
        else:
            victim = list(pool.entries)[int(rng.integers(0, len(pool.entries)))]
            pool.remove_recursive(victim)

        # invariant check over every entry
        for txid, e in pool.entries.items():
            anc = walk(txid, "up")
            desc = walk(txid, "down")
            assert e.count_with_ancestors == len(anc), "ancestor count"
            assert e.count_with_descendants == len(desc), "descendant count"
            assert e.size_with_ancestors == sum(
                pool.entries[t].size for t in anc
            )
            assert e.fees_with_descendants == sum(
                pool.entries[t].fee for t in desc
            )
