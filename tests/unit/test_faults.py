"""Unit coverage for the failure-side toolkit: jittered backoff, the
fault injector, the circuit-breaker state machine, and the generic
supervised_call wrapper (util/faults.py + ops/dispatch.py)."""

import random

import pytest

from bitcoincashplus_tpu.ops import dispatch
from bitcoincashplus_tpu.util import faults
from bitcoincashplus_tpu.util.faults import (
    Backoff,
    InjectedFault,
    retry_call,
)


@pytest.fixture(autouse=True)
def _clean_dispatch_state():
    """Breakers and the injector are process-global by design; every test
    in this file starts and ends with a pristine registry."""
    dispatch.reset()
    faults.INJECTOR.reload()
    yield
    dispatch.reset()
    faults.INJECTOR.reload()


class TestBackoff:
    def test_growth_jitter_and_reset(self):
        b = Backoff(base=1.0, factor=2.0, maximum=8.0, jitter=0.5,
                    rng=random.Random(7))
        delays = [b.next() for _ in range(6)]
        # each delay lies in [(1-jitter)*d_k, d_k] with d_k = min(2^k, 8)
        for k, d in enumerate(delays):
            ceiling = min(2.0 ** k, 8.0)
            assert 0.5 * ceiling <= d <= ceiling
        # the cap binds: late delays never exceed the max
        assert max(delays) <= 8.0
        b.reset()
        assert b.next() <= 1.0  # back to the base window

    def test_deterministic_with_seeded_rng(self):
        a = Backoff(base=1.0, rng=random.Random(3))
        b = Backoff(base=1.0, rng=random.Random(3))
        assert [a.next() for _ in range(4)] == [b.next() for _ in range(4)]

    def test_retry_call_retries_then_raises(self):
        calls = []

        def flaky():
            calls.append(1)
            raise ValueError("nope")

        with pytest.raises(ValueError):
            retry_call(flaky, attempts=3, sleep=lambda _t: None)
        assert len(calls) == 3

    def test_retry_call_success_after_transient(self):
        state = {"n": 0}

        def flaky():
            state["n"] += 1
            if state["n"] < 2:
                raise ValueError("transient")
            return "ok"

        assert retry_call(flaky, attempts=3, sleep=lambda _t: None) == "ok"


class TestFaultInjector:
    def test_off_by_default(self):
        inj = faults.FaultInjector()
        inj.on_call("sha256")  # no raise
        assert not inj.should_poison("sha256")

    def test_fail_once_fires_exactly_once_per_site(self, monkeypatch):
        monkeypatch.setenv("BCP_FAULT_MODE", "fail-once")
        monkeypatch.setenv("BCP_FAULT_OPS", "all")
        inj = faults.FaultInjector()
        with pytest.raises(InjectedFault):
            inj.on_call("sha256")
        inj.on_call("sha256")  # second call passes
        with pytest.raises(InjectedFault):
            inj.on_call("merkle")  # independent per-site counter
        assert inj.injected == {"sha256": 1, "merkle": 1}

    def test_fail_n_and_site_filter(self, monkeypatch):
        monkeypatch.setenv("BCP_FAULT_MODE", "fail-n")
        monkeypatch.setenv("BCP_FAULT_N", "2")
        monkeypatch.setenv("BCP_FAULT_OPS", "ecdsa")
        inj = faults.FaultInjector()
        inj.on_call("sha256")  # unlisted site: untouched
        for _ in range(2):
            with pytest.raises(InjectedFault):
                inj.on_call("ecdsa")
        inj.on_call("ecdsa")  # third call passes

    def test_fail_rate_deterministic_under_seed(self, monkeypatch):
        monkeypatch.setenv("BCP_FAULT_MODE", "fail-rate")
        monkeypatch.setenv("BCP_FAULT_RATE", "0.5")
        monkeypatch.setenv("BCP_FAULT_SEED", "42")

        def run():
            inj = faults.FaultInjector()
            out = []
            for _ in range(16):
                try:
                    inj.on_call("miner")
                    out.append(False)
                except InjectedFault:
                    out.append(True)
            return out

        assert run() == run()
        assert any(run())

    def test_poison_mode_counts(self, monkeypatch):
        monkeypatch.setenv("BCP_FAULT_MODE", "poison-output")
        monkeypatch.setenv("BCP_FAULT_OPS", "merkle")
        inj = faults.FaultInjector()
        assert inj.should_poison("merkle")
        assert not inj.should_poison("sha256")
        assert inj.snapshot()["poisoned"] == {"merkle": 1}


class TestCircuitBreaker:
    def test_open_after_threshold_and_halfopen_recovery(self):
        dispatch.configure(threshold=2, cooldown=0.0, probe=1.0, retries=0)
        br = dispatch.breaker("test")
        assert br.allow() and br.state == "closed"
        br.record_failure(RuntimeError("one"))
        assert br.state == "closed"  # below threshold
        br.record_failure(RuntimeError("two"))
        assert br.state == "open" and br.trips == 1
        # probe=1.0, cooldown=0 -> the next allow() IS the half-open probe
        assert br.allow() and br.state == "half-open"
        br.record_success()
        assert br.state == "closed" and br.recoveries == 1

    def test_halfopen_failure_reopens(self):
        dispatch.configure(threshold=1, cooldown=0.0, probe=1.0, retries=0)
        br = dispatch.breaker("test")
        br.record_failure(RuntimeError("boom"))
        assert br.state == "open"
        assert br.allow()  # probe
        br.record_failure(RuntimeError("still broken"))
        assert br.state == "open" and br.trips == 2

    def test_open_breaker_blocks_without_probe(self):
        dispatch.configure(threshold=1, cooldown=1e9, probe=0.0, retries=0)
        br = dispatch.breaker("test")
        br.record_failure(RuntimeError("dead"))
        assert not any(br.allow() for _ in range(10))

    def test_fallback_accounting(self):
        br = dispatch.breaker("test")
        br.note_fallback(7)
        br.note_fallback(3)
        snap = br.snapshot()
        assert snap["fallback_calls"] == 2 and snap["fallback_items"] == 10


class TestSupervisedCall:
    def test_device_result_used_when_healthy(self):
        out, used = dispatch.supervised_call("test", lambda: "dev",
                                             lambda: "cpu")
        assert (out, used) == ("dev", True)

    def test_retry_absorbs_transient_failure(self):
        dispatch.configure(retries=1, threshold=3)
        state = {"n": 0}

        def flaky():
            state["n"] += 1
            if state["n"] == 1:
                raise RuntimeError("transient")
            return "dev"

        out, used = dispatch.supervised_call("test", flaky, lambda: "cpu")
        assert (out, used) == ("dev", True)
        assert dispatch.breaker("test").state == "closed"

    def test_hard_failure_falls_back_and_charges_breaker(self):
        dispatch.configure(retries=0, threshold=2, cooldown=1e9, probe=0.0)

        def dead():
            raise RuntimeError("device gone")

        for _ in range(2):
            out, used = dispatch.supervised_call("test", dead, lambda: "cpu",
                                                 items=5)
            assert (out, used) == ("cpu", False)
        br = dispatch.breaker("test")
        assert br.state == "open"
        # breaker open: device_fn is not even attempted any more
        out, used = dispatch.supervised_call(
            "test", lambda: pytest.fail("must not run"), lambda: "cpu")
        assert (out, used) == ("cpu", False)
        assert br.snapshot()["fallback_items"] >= 11

    def test_validation_probe_gates_output(self):
        dispatch.configure(retries=0, threshold=1, cooldown=1e9, probe=0.0)
        out, used = dispatch.supervised_call(
            "test", lambda: "corrupt", lambda: "cpu",
            validate=lambda r: r != "corrupt")
        assert (out, used) == ("cpu", False)
        assert dispatch.breaker("test").state == "open"


def test_connman_uses_shared_backoff(tmp_path):
    """The reconnect loop's pacing is the util/faults.Backoff helper, not a
    fixed sleep (satellite: unified timeout/reconnect handling)."""
    from types import SimpleNamespace

    from bitcoincashplus_tpu.p2p.connman import CConnman

    node = SimpleNamespace(
        params=SimpleNamespace(netmagic=b"\xfa\xbf\xb5\xda"),
        datadir=str(tmp_path),
        config=SimpleNamespace(get_int=lambda _k, d: d),
    )
    cm = CConnman(node)
    assert isinstance(cm._dial_backoff, Backoff)
    assert cm._dial_backoff.base == 5.0 and cm._dial_backoff.maximum == 60.0
    first = cm._dial_backoff.next()
    later = [cm._dial_backoff.next() for _ in range(6)]
    assert first <= 5.0 and max(later) > 5.0  # it actually backs off
    cm._dial_backoff.reset()
    assert cm._dial_backoff.next() <= 5.0


class TestNetFaultSite:
    """The 'net' injection site (p2p message dispatch) is explicit opt-in:
    BCP_FAULT_OPS=all still means the accelerator subsystems only, so the
    dead-backend drills never silently start dropping P2P traffic."""

    def test_all_does_not_arm_net(self, fault_harness):
        inj = fault_harness("fail-always", ops="all")
        assert not inj.armed_for(faults.NET_SITE)
        for site in faults.SITES:
            assert inj.armed_for(site)

    def test_explicit_net_arms_and_fires(self, fault_harness):
        inj = fault_harness("fail-always", ops="net")
        assert inj.armed_for(faults.NET_SITE)
        with pytest.raises(InjectedFault):
            inj.on_call(faults.NET_SITE)
        assert inj.injected[faults.NET_SITE] == 1
        # the accelerator sites stay dark
        assert not inj.armed_for("ecdsa")

    def test_latency_helper_for_event_loop_callers(self, fault_harness):
        """latency() hands the sleep to async callers instead of blocking
        inside on_call; it is zero for any other mode/site."""
        inj = fault_harness("latency-spike", ops="net", latency_ms=80)
        assert inj.latency(faults.NET_SITE) == pytest.approx(0.08)
        assert inj.latency("ecdsa") == 0.0
        inj = fault_harness("fail-always", ops="net")
        assert inj.latency(faults.NET_SITE) == 0.0


class TestChaosSchedule:
    def test_deterministic_from_seed(self):
        a = faults.ChaosSchedule(seed=1234)
        b = faults.ChaosSchedule(seed=1234)
        assert [a.next_action() for _ in range(32)] == \
               [b.next_action() for _ in range(32)]
        assert a.randbytes(64) == b.randbytes(64)
        assert a.randhash() == b.randhash()
        assert [a.pause() for _ in range(8)] == [b.pause() for _ in range(8)]
        assert a.burst_size() == b.burst_size()
        assert a.history == b.history

    def test_different_seeds_diverge(self):
        a = faults.ChaosSchedule(seed=1)
        b = faults.ChaosSchedule(seed=2)
        assert [a.next_action() for _ in range(64)] != \
               [b.next_action() for _ in range(64)]

    def test_draw_bounds(self):
        s = faults.ChaosSchedule(seed=7, min_pause=0.1, max_pause=0.2)
        for _ in range(64):
            assert 0.1 <= s.pause() <= 0.2
            assert 4 <= s.burst_size(4, 32) <= 32
            assert s.next_action() in faults.CHAOS_ACTIONS
        assert len(s.randhash()) == 32

    def test_fleet_bipartition_seeded(self):
        """The fork-storm fleet draws (ISSUE 9): a bipartition is two
        non-empty sorted halves covering every node, replayable from the
        seed; choice() draws from any sequence deterministically."""
        a = faults.ChaosSchedule(seed=1109)
        b = faults.ChaosSchedule(seed=1109)
        for n in (2, 3, 4, 7):
            pa, pb = a.bipartition(n), b.bipartition(n)
            assert pa == pb
            left, right = pa
            assert left and right
            assert sorted(left + right) == list(range(n))
        assert [a.choice("xyz") for _ in range(8)] == \
               [b.choice("xyz") for _ in range(8)]
        assert set(faults.FLEET_ACTIONS) >= {"partition", "heal",
                                             "mine", "fork"}
