"""BIP32 derivation — the BIP's published test vectors 1 and 2 plus
CKDpub/CKDpriv consistency properties (src/test/bip32_tests.cpp)."""

import pytest

pytest.importorskip("hypothesis")  # property tests need the optional test extra
from hypothesis import given, settings
from hypothesis import strategies as st

from bitcoincashplus_tpu.wallet.bip32 import HARDENED, ExtKey

# BIP32 test vector 1 (seed 000102030405060708090a0b0c0d0e0f)
TV1 = [
    ("m",
     "xprv9s21ZrQH143K3QTDL4LXw2F7HEK3wJUD2nW2nRk4stbPy6cq3jPPqjiChkVvvNKmPGJxWUtg6LnF5kejMRNNU3TGtRBeJgk33yuGBxrMPHi",
     "xpub661MyMwAqRbcFtXgS5sYJABqqG9YLmC4Q1Rdap9gSE8NqtwybGhePY2gZ29ESFjqJoCu1Rupje8YtGqsefD265TMg7usUDFdp6W1EGMcet8"),
    ("m/0'",
     "xprv9uHRZZhk6KAJC1avXpDAp4MDc3sQKNxDiPvvkX8Br5ngLNv1TxvUxt4cV1rGL5hj6KCesnDYUhd7oWgT11eZG7XnxHrnYeSvkzY7d2bhkJ7",
     "xpub68Gmy5EdvgibQVfPdqkBBCHxA5htiqg55crXYuXoQRKfDBFA1WEjWgP6LHhwBZeNK1VTsfTFUHCdrfp1bgwQ9xv5ski8PX9rL2dZXvgGDnw"),
    ("m/0'/1",
     "xprv9wTYmMFdV23N2TdNG573QoEsfRrWKQgWeibmLntzniatZvR9BmLnvSxqu53Kw1UmYPxLgboyZQaXwTCg8MSY3H2EU4pWcQDnRnrVA1xe8fs",
     "xpub6ASuArnXKPbfEwhqN6e3mwBcDTgzisQN1wXN9BJcM47sSikHjJf3UFHKkNAWbWMiGj7Wf5uMash7SyYq527Hqck2AxYysAA7xmALppuCkwQ"),
    ("m/0'/1/2'",
     "xprv9z4pot5VBttmtdRTWfWQmoH1taj2axGVzFqSb8C9xaxKymcFzXBDptWmT7FwuEzG3ryjH4ktypQSAewRiNMjANTtpgP4mLTj34bhnZX7UiM",
     "xpub6D4BDPcP2GT577Vvch3R8wDkScZWzQzMMUm3PWbmWvVJrZwQY4VUNgqFJPMM3No2dFDFGTsxxpG5uJh7n7epu4trkrX7x7DogT5Uv6fcLW5"),
    ("m/0'/1/2'/2",
     "xprvA2JDeKCSNNZky6uBCviVfJSKyQ1mDYahRjijr5idH2WwLsEd4Hsb2Tyh8RfQMuPh7f7RtyzTtdrbdqqsunu5Mm3wDvUAKRHSC34sJ7in334",
     "xpub6FHa3pjLCk84BayeJxFW2SP4XRrFd1JYnxeLeU8EqN3vDfZmbqBqaGJAyiLjTAwm6ZLRQUMv1ZACTj37sR62cfN7fe5JnJ7dh8zL4fiyLHV"),
    ("m/0'/1/2'/2/1000000000",
     "xprvA41z7zogVVwxVSgdKUHDy1SKmdb533PjDz7J6N6mV6uS3ze1ai8FHa8kmHScGpWmj4WggLyQjgPie1rFSruoUihUZREPSL39UNdE3BBDu76",
     "xpub6H1LXWLaKsWFhvm6RVpEL9P4KfRZSW7abD2ttkWP3SSQvnyA8FSVqNTEcYFgJS2UaFcxupHiYkro49S8yGasTvXEYBVPamhGW6cFJodrTHy"),
]

# BIP32 test vector 2 (the long fffcf9f6... seed)
TV2_SEED = bytes.fromhex(
    "fffcf9f6f3f0edeae7e4e1dedbd8d5d2cfccc9c6c3c0bdbab7b4b1aeaba8a5a2"
    "9f9c999693908d8a8784817e7b7875726f6c696663605d5a5754514e4b484542")
TV2 = [
    ("m",
     "xprv9s21ZrQH143K31xYSDQpPDxsXRTUcvj2iNHm5NUtrGiGG5e2DtALGdso3pGz6ssrdK4PFmM8NSpSBHNqPqm55Qn3LqFtT2emdEXVYsCzC2U",
     "xpub661MyMwAqRbcFW31YEwpkMuc5THy2PSt5bDMsktWQcFF8syAmRUapSCGu8ED9W6oDMSgv6Zz8idoc4a6mr8BDzTJY47LJhkJ8UB7WEGuduB"),
    ("m/0",
     "xprv9vHkqa6EV4sPZHYqZznhT2NPtPCjKuDKGY38FBWLvgaDx45zo9WQRUT3dKYnjwih2yJD9mkrocEZXo1ex8G81dwSM1fwqWpWkeS3v86pgKt",
     "xpub69H7F5d8KSRgmmdJg2KhpAK8SR3DjMwAdkxj3ZuxV27CprR9LgpeyGmXUbC6wb7ERfvrnKZjXoUmmDznezpbZb7ap6r1D3tgFxHmwMkQTPH"),
]


class TestVectors:
    def test_vector1(self):
        seed = bytes.fromhex("000102030405060708090a0b0c0d0e0f")
        master = ExtKey.from_seed(seed)
        for path, xprv, xpub in TV1:
            node = master.derive_path(path)
            assert node.serialize() == xprv, path
            assert node.neuter().serialize() == xpub, path

    def test_vector2(self):
        master = ExtKey.from_seed(TV2_SEED)
        for path, xprv, xpub in TV2:
            node = master.derive_path(path)
            assert node.serialize() == xprv, path
            assert node.neuter().serialize() == xpub, path

    def test_parse_roundtrip(self):
        master = ExtKey.from_seed(b"\x07" * 32)
        node = master.derive_path("m/0'/0'/7'")
        back = ExtKey.parse(node.serialize())
        assert back.secret == node.secret
        assert back.chain_code == node.chain_code
        assert back.depth == node.depth == 3
        pub = ExtKey.parse(node.neuter().serialize())
        assert pub.secret is None and pub.point == node.point
        assert ExtKey.parse("xprvJunk") is None


class TestProperties:
    @settings(max_examples=15, deadline=None)
    @given(st.binary(min_size=16, max_size=64), st.integers(0, 2**31 - 1))
    def test_ckdpub_matches_ckdpriv(self, seed, i):
        """N(CKDpriv(k, i)) == CKDpub(N(k), i) for non-hardened i."""
        try:
            master = ExtKey.from_seed(seed)
        except ValueError:
            return
        via_priv = master.derive(i).neuter()
        via_pub = master.neuter().derive(i)
        assert via_priv.pubkey_bytes() == via_pub.pubkey_bytes()
        assert via_priv.chain_code == via_pub.chain_code

    def test_hardened_from_pub_raises(self):
        master = ExtKey.from_seed(b"\x01" * 32)
        pub = master.neuter()
        try:
            pub.derive(HARDENED)
            assert False, "hardened derivation from xpub must fail"
        except ValueError:
            pass
