"""Runtime lock-order sentinel tests (util/lockwatch, ``lint`` marker).

The core provocation: two threads take a fake lock pair in opposite
orders — with schedules arranged so the runs never actually deadlock —
and the monitor must still report the inversion, because the order
*graph* has the cycle even when the timeline got lucky. That is the
whole point of the sentinel: it generalizes over schedules the way
bcplint's BCP004 generalizes over call sites.
"""

import threading

import pytest

from bitcoincashplus_tpu.util import lockwatch
from bitcoincashplus_tpu.util.lockwatch import (
    MONITOR,
    WatchedLock,
    watched_condition,
    watched_lock,
    watched_rlock,
)

pytestmark = pytest.mark.lint


@pytest.fixture(autouse=True)
def _fresh_monitor(monkeypatch):
    """Every test runs against an armed gate and an empty graph; the
    process-global MONITOR is scrubbed afterwards so nothing leaks into
    the telemetry/functional suites."""
    monkeypatch.setenv("BCP_LOCKWATCH", "1")
    MONITOR.reset()
    yield
    MONITOR.reset()


def _run_threads(*targets):
    threads = [threading.Thread(target=t) for t in targets]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
        assert not t.is_alive(), "worker wedged"


# ---------------------------------------------------------------------------
# the inversion provocation
# ---------------------------------------------------------------------------


def test_two_lock_inversion_is_reported():
    a = watched_lock("fake_a")
    b = watched_lock("fake_b")
    gate = threading.Barrier(2, timeout=30)

    def ab():
        with a:
            with b:
                pass
        gate.wait()  # thread 2 starts only after this one fully released

    def ba():
        gate.wait()
        with b:
            with a:
                pass

    _run_threads(ab, ba)

    cycles = MONITOR.cycles()
    assert len(cycles) == 1, cycles
    cyc = cycles[0]
    assert cyc["locks"] == ["fake_a", "fake_b"]
    # both legs are present, each with the real acquire site recorded
    assert set(cyc["edges"]) == {"fake_a->fake_b", "fake_b->fake_a"}
    for site in cyc["edges"].values():
        assert site.startswith("test_lockwatch.py:"), site

    snap = MONITOR.snapshot()
    assert snap["inversions"] == 1
    assert snap["acquisitions_total"] == 4
    assert snap["max_depth"] == 2


def test_consistent_order_reports_no_cycle():
    a = watched_lock("ord_a")
    b = watched_lock("ord_b")

    def t1():
        with a:
            with b:
                pass

    def t2():
        with a:
            with b:
                pass

    _run_threads(t1, t2)
    assert MONITOR.cycles() == []
    assert MONITOR.snapshot()["order_edges"] == {"ord_a->ord_b": 2}


# ---------------------------------------------------------------------------
# re-entrancy, gating, condition bookkeeping
# ---------------------------------------------------------------------------


def test_rlock_reentry_adds_depth_never_edges():
    r = watched_rlock("reent")
    with r:
        with r:
            with r:
                pass
    snap = MONITOR.snapshot()
    # one first-hold acquisition, zero edges, zero self-cycles
    assert snap["acquisitions"]["reent"] == 1
    assert snap["order_edges"] == {}
    assert snap["inversions"] == 0


def test_gate_off_returns_plain_primitives(monkeypatch):
    monkeypatch.setenv("BCP_LOCKWATCH", "0")
    assert isinstance(watched_lock("off"), type(threading.Lock()))
    assert isinstance(watched_rlock("off"), type(threading.RLock()))
    cond = watched_condition("off")
    assert isinstance(cond, threading.Condition)
    assert not isinstance(cond._lock, WatchedLock)
    assert lockwatch.snapshot() == {"enabled": False}
    # nothing registered: the monitor never heard about these locks
    assert "off" not in MONITOR.snapshot()["locks"]


def test_condition_wait_keeps_stack_coherent():
    """Across a cv.wait() the lock is released (stack must drop it) and
    reacquired (stack must regain it) — holding another lock over the
    wake-side acquire still mints the correct edge, and nothing wedges."""
    cv = watched_condition("fake_cv")
    outer = watched_lock("fake_outer")
    ready = threading.Event()
    woke = threading.Event()

    def waiter():
        with cv:
            ready.set()
            assert cv.wait(timeout=30)
        woke.set()

    def waker():
        assert ready.wait(timeout=30)
        with outer:
            with cv:
                cv.notify_all()
        assert woke.wait(timeout=30)

    _run_threads(waiter, waker)

    snap = MONITOR.snapshot()
    # waiter: enter + reacquire-after-wait; waker: one acquire
    assert snap["acquisitions"]["fake_cv"] == 3
    assert snap["order_edges"] == {"fake_outer->fake_cv": 1}
    assert snap["inversions"] == 0


def test_condition_over_rlock_wait_restores_depth():
    """An RLock-backed condition entered re-entrantly: wait() must drop
    every recursion level (or the notifier could never acquire) and the
    restore must reinstate the full depth."""
    lock = watched_rlock("fake_rcv")
    cv = threading.Condition(lock)
    ready = threading.Event()

    def waiter():
        with lock:          # depth 1
            with cv:        # depth 2, same lock
                ready.set()
                assert cv.wait(timeout=30)
            # __exit__ back to depth 1 without underflow
        # fully released here

    def waker():
        assert ready.wait(timeout=30)
        with cv:  # only acquirable if wait() really dropped both levels
            cv.notify_all()

    _run_threads(waiter, waker)
    snap = MONITOR.snapshot()
    assert snap["inversions"] == 0
    # first-holds only: waiter enter + reacquire, waker enter
    assert snap["acquisitions"]["fake_rcv"] == 3


def test_release_out_of_acquisition_order():
    """The held-set is not a strict LIFO: A-acquire, B-acquire,
    A-release, B-release must keep counts coherent."""
    a = watched_lock("ooo_a")
    b = watched_lock("ooo_b")
    a.acquire()
    b.acquire()
    a.release()
    b.release()
    snap = MONITOR.snapshot()
    assert snap["order_edges"] == {"ooo_a->ooo_b": 1}
    # a second pass must not double-register or wedge
    a.acquire()
    a.release()
    assert MONITOR.snapshot()["acquisitions"]["ooo_a"] == 2


def test_snapshot_shape_matches_gettpuinfo_contract():
    """gettpuinfo's ``lockwatch`` section and the telemetry collector
    both project these exact keys — keep the contract pinned."""
    lk = watched_lock("contract")
    with lk:
        pass
    snap = lockwatch.snapshot()
    assert snap["enabled"] is True
    for key in ("locks", "acquisitions", "acquisitions_total",
                "max_depth", "order_edges", "inversions", "cycles",
                "declared_guards"):
        assert key in snap, key
    assert "contract" in snap["locks"]


# ---------------------------------------------------------------------------
# GUARDED_BY vocabulary (bcplint BCP009 <-> runtime agreement)
# ---------------------------------------------------------------------------


def test_declared_guards_surface_in_snapshot():
    """Classes adopting the static ``GUARDED_BY`` annotation publish the
    same vocabulary to the runtime sentinel, so gettpuinfo.lockwatch and
    docs/CONCURRENCY.md name the same locks as declared guards."""
    lockwatch.declare_guards("ban_lock", ["_banned", "_ban_seq"])
    lockwatch.declare_guards("ban_lock", ["_banned"])  # idempotent merge
    lockwatch.declare_guards("ban_io_lock", ["_ban_saved_seq"])
    snap = lockwatch.snapshot()
    assert snap["declared_guards"] == {
        "ban_io_lock": ["_ban_saved_seq"],
        "ban_lock": ["_ban_seq", "_banned"],
    }
    MONITOR.reset()
    assert lockwatch.snapshot()["declared_guards"] == {}


def test_bcp007_fixture_pattern_trips_runtime_sentinel():
    """The seeded BCP007 fixture (tests/fixtures/bcplint/bcp007_race.py)
    pairs its no-common-lock writes with opposite-order nested
    acquisitions. Executed with watched locks — writers serialized so
    the schedule cannot actually deadlock — the runtime monitor still
    reports the inversion: the static finding and the runtime sentinel
    flag the same pattern."""
    a = watched_lock("race_a")
    b = watched_lock("race_b")
    box = {"latest": 0}

    def writer_a():
        with a:
            box["latest"] = 1
            with b:
                pass

    def writer_b():
        with b:
            box["latest"] = 2
            with a:
                pass

    _run_threads(writer_a)   # serialized on purpose: the order graph
    _run_threads(writer_b)   # has the cycle even when the timeline can't
    cycles = MONITOR.cycles()
    assert cycles, "runtime sentinel missed the fixture pattern"
    assert {"race_a", "race_b"} <= set(cycles[0]["locks"])
    assert lockwatch.snapshot()["inversions"] >= 1
