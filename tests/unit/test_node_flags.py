"""-par / -dbcache flag wiring (SURVEY §6.6 parity-flag contract:
advertised flags must be consumed, not help-text-only)."""

import os

from bitcoincashplus_tpu import native
from bitcoincashplus_tpu.node.config import Config
from bitcoincashplus_tpu.node.node import Node


def _mk_node(tmp_path, **args):
    cfg = Config()
    cfg.args["datadir"] = [str(tmp_path)]
    cfg.args["regtest"] = ["1"]
    for k, v in args.items():
        cfg.args[k] = [str(v)]
    return Node(config=cfg)


def test_par_sets_native_thread_budget(tmp_path):
    old = native.PAR_THREADS
    try:
        node = _mk_node(tmp_path / "a", par=2)
        assert native.PAR_THREADS == 2
        node.close()
        # negative -par keeps reference leave-N-cores-free semantics
        node = _mk_node(tmp_path / "b", par=-1)
        assert native.PAR_THREADS == max(1, (os.cpu_count() or 1) - 1)
        node.close()
    finally:
        native.PAR_THREADS = old


def test_dbcache_bounds_coins_cache(tmp_path):
    from bitcoincashplus_tpu.mining.generate import generate_blocks

    node = _mk_node(tmp_path / "c", dbcache=7)
    try:
        assert node.dbcache_bytes == 7 * 1024 * 1024
        # force the memory trigger: pretend the budget is 1 byte — the next
        # connected block must flush the coins cache even though the
        # block-interval policy wouldn't
        node.dbcache_bytes = 1
        node.flush_interval = 10_000
        spk = bytes.fromhex("76a914") + b"\x11" * 20 + bytes.fromhex("88ac")
        with node.cs_main:
            generate_blocks(node.chainstate, spk, 1, tile=1 << 12)
        assert node.chainstate.coins.cache_size() == 0  # flushed
        assert node._blocks_since_flush == 0
    finally:
        node.close()


def test_rescan_yields_cs_main(tmp_path):
    """VERDICT r3 #10: the O(height) wallet rescan must not hold cs_main
    for the whole walk — another thread can take the lock mid-rescan."""
    import threading

    from bitcoincashplus_tpu.mining.generate import generate_blocks

    node = _mk_node(tmp_path / "d")
    try:
        node.SCAN_CHUNK = 5
        spk = bytes.fromhex("76a914") + b"\x11" * 20 + bytes.fromhex("88ac")
        with node.cs_main:
            generate_blocks(node.chainstate, spk, 30, tile=1 << 12)
        wallet = node.load_wallet()
        wallet.get_new_address()  # give the wallet keys so rescan runs

        acquired_mid_rescan = threading.Event()
        rescan_started = threading.Event()

        orig_connected = wallet.block_connected

        def slow_connected(block, idx):
            rescan_started.set()
            orig_connected(block, idx)

        wallet.block_connected = slow_connected

        def contender():
            rescan_started.wait(timeout=10)
            # must get the lock while the rescan is still in progress
            if node.cs_main.acquire(timeout=10):
                node.cs_main.release()
                acquired_mid_rescan.set()

        t = threading.Thread(target=contender)
        t.start()
        with node.cs_main:  # simulate the RPC layer's hold
            node._rescan_wallet()
        t.join(timeout=15)
        assert acquired_mid_rescan.is_set()
    finally:
        node.close()


def test_txindex_backfill_background(tmp_path):
    """-txindex backfill syncs on a background thread; lookups work once
    synced; the flag persists so a restart skips the backfill."""
    import time as _t

    from bitcoincashplus_tpu.mining.generate import generate_blocks

    d = tmp_path / "e"
    node = _mk_node(d)
    spk = bytes.fromhex("76a914") + b"\x33" * 20 + bytes.fromhex("88ac")
    with node.cs_main:
        generate_blocks(node.chainstate, spk, 20, tile=1 << 12)
        coinbase_txid = node.chainstate.get_block(
            node.chainstate.chain[7].hash
        ).vtx[0].txid
    node.close()

    node = _mk_node(d, txindex=1)
    try:
        deadline = _t.time() + 30
        while not node._txindex_synced and _t.time() < deadline:
            _t.sleep(0.1)
        assert node._txindex_synced
        assert node.txindex_lookup(coinbase_txid) == \
            node.chainstate.chain[7].hash
    finally:
        node.close()


def test_compilecache_knob(tmp_path, monkeypatch):
    """-compilecache=<dir>: jax's persistent compilation cache points at
    the directory, BCP_COMPILE_CACHE is seeded for child processes, and
    gettpuinfo.device gains the compilation_cache block (default: off)."""
    import jax

    from bitcoincashplus_tpu.util import devicewatch as dw

    monkeypatch.delenv("BCP_COMPILE_CACHE", raising=False)
    old_dir = jax.config.jax_compilation_cache_dir
    try:
        cache_dir = tmp_path / "xla-cache"
        node = _mk_node(tmp_path / "cc", compilecache=str(cache_dir))
        try:
            assert jax.config.jax_compilation_cache_dir == str(cache_dir)
            assert os.environ["BCP_COMPILE_CACHE"] == str(cache_dir)
            assert cache_dir.is_dir()
            snap = dw.snapshot()["compilation_cache"]
            assert snap["enabled"] and snap["dir"] == str(cache_dir)
            assert "cache_hits" in snap
        finally:
            node.close()
    finally:
        jax.config.update("jax_compilation_cache_dir", old_dir)
