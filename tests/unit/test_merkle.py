"""Merkle tests (reference model: src/test/merkle_tests.cpp — cross-check vs a
naive recursive algorithm, plus the CVE-2012-2459 mutation edge)."""

import os

import pytest

pytest.importorskip("hypothesis")  # property tests need the optional test extra
from hypothesis import given
from hypothesis import strategies as st

from bitcoincashplus_tpu.consensus.merkle import (
    compute_merkle_root,
    merkle_root_naive,
)

hash32 = st.binary(min_size=32, max_size=32)


class TestMerkleRoot:
    def test_empty(self):
        root, mutated = compute_merkle_root([])
        assert root == b"\x00" * 32 and not mutated

    def test_single(self):
        h = os.urandom(32)
        root, mutated = compute_merkle_root([h])
        assert root == h and not mutated

    @given(st.lists(hash32, min_size=1, max_size=64, unique=True))
    def test_matches_naive(self, hashes):
        root, mutated = compute_merkle_root(hashes)
        assert root == merkle_root_naive(hashes)
        assert not mutated  # unique leaves can't trip the duplication check

    def test_genesis_root(self):
        from bitcoincashplus_tpu.consensus.params import main_params

        g = main_params().genesis
        root, mutated = compute_merkle_root([g.vtx[0].txid])
        assert root == g.header.hash_merkle_root and not mutated

    def test_cve_2012_2459_mutation_detected(self):
        """A tx list ending in a duplicated pair yields the same root as the
        shorter list but must set the mutated flag."""
        a, b, c = (bytes([i]) * 32 for i in (1, 2, 3))
        root3, mut3 = compute_merkle_root([a, b, c])
        root4, mut4 = compute_merkle_root([a, b, c, c])
        assert root3 == root4
        assert not mut3
        assert mut4

    def test_odd_padding_not_flagged(self):
        # 3 distinct leaves: level-1 duplication of the last node is the
        # consensus rule, not a mutation.
        leaves = [os.urandom(32) for _ in range(3)]
        _, mutated = compute_merkle_root(leaves)
        assert not mutated
