"""Fleet front-door policy suite (serving/gateway + serving/replicas).

Every rotation/admission/coalescing/failover policy is exercised with
injected fake transports — no subprocesses, tier-1 fast. The fault
drills arm the explicit-only ``gateway`` and ``replica_rpc`` sites
(util/faults.GATEWAY_SITE / REPLICA_RPC_SITE) and prove the ISSUE 16
robustness story: a dying replica leg fails over mid-request, a dark
rotation falls back to the validator, and overload sheds read-only
traffic before tip-critical — metered, never silent."""

from __future__ import annotations

import concurrent.futures as cf
import threading
import time

import pytest

from bitcoincashplus_tpu.ops.dispatch import BreakerConfig
from bitcoincashplus_tpu.serving.gateway import (
    Gateway,
    GatewayReject,
    BackendRPCError,
)
from bitcoincashplus_tpu.serving.replicas import (
    Replica,
    ReplicaPool,
    ReplicaRPCError,
)
from bitcoincashplus_tpu.util.faults import (
    GATEWAY_SITE,
    REPLICA_RPC_SITE,
    InjectedFault,
)

pytestmark = pytest.mark.serving


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def chaininfo(height: int) -> dict:
    return {"blocks": height, "bestblockhash": f"hash{height:04d}"}


class FakeBackendTracker:
    """Validator-leg stand-in recording every call."""

    def __init__(self, result="validator"):
        self.calls: list[tuple] = []
        self.result = result
        self._lock = threading.Lock()

    def __call__(self, method, params):
        with self._lock:
            self.calls.append((method, list(params)))
        return self.result


def make_replica(name, transport, clock=None, threshold=2,
                 cooldown=5.0) -> Replica:
    cfg = BreakerConfig(threshold=threshold, cooldown=cooldown,
                        probe=1.0, seed=7)
    return Replica(name, transport,
                   breaker_cfg=cfg, clock=clock or time.monotonic)


def make_pool(replicas, max_lag=2, tip=10) -> ReplicaPool:
    pool = ReplicaPool(replicas, max_lag=max_lag, validator_tip=lambda: tip)
    pool.probe_once()
    return pool


def healthy_transport(height=10, tag="r"):
    def call(method, params):
        if method == "getblockchaininfo":
            return chaininfo(height)
        return f"{tag}:{method}"
    return call


# -- admission + graduated shedding ------------------------------------


class TestAdmission:
    def test_read_sheds_before_tip_at_the_soft_ceiling(self):
        backend = FakeBackendTracker()
        gw = Gateway(backend, make_pool([]), soft_inflight=0,
                     hard_inflight=100)
        try:
            with pytest.raises(GatewayReject, match="overload"):
                gw.handle("getblockcount", [], "c")
            # tip-critical rides to the hard ceiling: still admitted
            assert gw.handle("sendrawtransaction", ["00"], "c") \
                == "validator"
            assert gw.stats["sheds"]["read"] == 1
            assert gw.stats["sheds"]["tip"] == 0
        finally:
            gw.close()

    def test_token_bucket_leaves_a_tip_reserve(self):
        backend = FakeBackendTracker()
        # burst=4, read_reserve=0.25 -> reads must stop at 1 token;
        # rate=0 so nothing refills mid-test
        gw = Gateway(backend, make_pool([]), rate=0.0, burst=4.0,
                     read_reserve=0.25)
        try:
            for _ in range(3):
                gw.handle("getblockcount", [], "alice")
            with pytest.raises(GatewayReject, match="rate"):
                gw.handle("getblockcount", [], "alice")
            # the reserved token is still there for tip-critical
            assert gw.handle("submitblock", ["00"], "alice") == "validator"
            with pytest.raises(GatewayReject, match="rate"):
                gw.handle("submitblock", ["00"], "alice")
            # a different client has its own bucket
            assert gw.handle("getblockcount", [], "bob") == "validator"
            assert gw.stats["sheds"] == {"read": 1, "tip": 1}
        finally:
            gw.close()

    def test_rejects_are_metered_never_silent(self):
        gw = Gateway(FakeBackendTracker(), make_pool([]), rate=0.0,
                     burst=1.0, read_reserve=0.0)
        try:
            gw.handle("getblockcount", [], "c")
            shed_before = gw.stats["sheds"]["read"]
            with pytest.raises(GatewayReject):
                gw.handle("getblockcount", [], "c")
            assert gw.stats["sheds"]["read"] == shed_before + 1
            # and the HTTP-facing execute() path converts it to a
            # 429-style JSON-RPC error object, not an exception
            resp = gw.execute({"id": 9, "method": "getblockcount",
                               "params": []}, "c")
            assert resp["error"]["code"] == -429
            assert "shed" in resp["error"]["message"]
        finally:
            gw.close()


# -- request coalescing -------------------------------------------------


class TestCoalescing:
    def test_identical_inflight_queries_hit_the_backend_once(self):
        gate = threading.Event()
        calls = []
        lock = threading.Lock()

        def backend(method, params):
            with lock:
                calls.append(method)
            gate.wait(timeout=5)  # hold the leader so followers pile up
            return "tpl"

        gw = Gateway(backend, make_pool([]), soft_inflight=64)
        try:
            with cf.ThreadPoolExecutor(8) as ex:
                futs = [ex.submit(gw.handle, "getblocktemplate", [],
                                  f"c{i}") for i in range(8)]
                # wait until all 8 are inside the gateway, then release
                deadline = time.monotonic() + 5
                while gw.snapshot()["inflight"] < 8 \
                        and time.monotonic() < deadline:
                    time.sleep(0.005)
                gate.set()
                results = [f.result(timeout=10) for f in futs]
            assert results == ["tpl"] * 8
            assert len(calls) == 1  # ONE backend call for eight clients
            assert gw.stats["coalesce_hits"] == 7
        finally:
            gw.close()

    def test_distinct_params_do_not_coalesce(self):
        backend = FakeBackendTracker()
        gw = Gateway(backend, make_pool([]))
        try:
            gw.handle("getblockhash", [1], "c")
            gw.handle("getblockhash", [2], "c")
            assert len(backend.calls) == 2
            assert gw.stats["coalesce_hits"] == 0
        finally:
            gw.close()

    def test_leader_error_is_shared_with_followers(self):
        def backend(method, params):
            raise BackendRPCError({"code": -5, "message": "Block not found"})

        gw = Gateway(backend, make_pool([]))
        try:
            with pytest.raises(BackendRPCError, match="not found"):
                gw.handle("getblocktemplate", [], "c")
        finally:
            gw.close()


# -- replica rotation: failover, breakers, lag gate ---------------------


class TestFailover:
    def test_mid_request_failover_to_a_healthy_replica(self):
        def dead(method, params):
            if method == "getblockchaininfo":
                return chaininfo(10)
            raise OSError("connection reset")

        r_dead = make_replica("dead", dead)
        r_ok = make_replica("ok", healthy_transport(10, "ok"))
        pool = make_pool([r_dead, r_ok])
        gw = Gateway(FakeBackendTracker(), pool)
        try:
            # every read lands an answer regardless of which replica the
            # round-robin tries first; a dead leg is retried elsewhere
            for _ in range(4):
                assert gw.handle("getblockcount", [], "c") \
                    in ("ok:getblockcount",)
            assert gw.stats["failovers"] >= 1
            assert r_dead.breaker.consecutive_failures >= 1 or \
                r_dead.breaker.state != "closed"
        finally:
            gw.close()

    def test_rpc_level_error_is_definitive_not_failed_over(self):
        def answers_error(method, params):
            if method == "getblockchaininfo":
                return chaininfo(10)
            raise ReplicaRPCError({"code": -5, "message": "Block not found"})

        r = make_replica("r", answers_error)
        gw = Gateway(FakeBackendTracker(), make_pool([r]))
        try:
            with pytest.raises(BackendRPCError, match="not found"):
                gw.handle("getblock", ["00"], "c")
            assert gw.stats["failovers"] == 0
            assert r.breaker.healthy()  # answered — not replica sickness
        finally:
            gw.close()

    def test_exhausted_rotation_falls_back_to_the_validator(self):
        def dead(method, params):
            if method == "getblockchaininfo":
                return chaininfo(10)
            raise OSError("dead")

        backend = FakeBackendTracker()
        gw = Gateway(backend, make_pool([make_replica("d1", dead),
                                         make_replica("d2", dead)]))
        try:
            assert gw.handle("getblockcount", [], "c") == "validator"
            assert gw.stats["validator_fallback"] == 1
            assert gw.stats["failovers"] == 2
            assert backend.calls == [("getblockcount", [])]
        finally:
            gw.close()

    def test_breaker_trips_evicts_and_readmits_on_probe_success(self):
        clock = FakeClock()
        state = {"dead": True}

        def flaky(method, params):
            if state["dead"]:
                raise OSError("down")
            if method == "getblockchaininfo":
                return chaininfo(10)
            return "back"

        r = make_replica("flaky", flaky, clock=clock, threshold=2,
                         cooldown=5.0)
        pool = ReplicaPool([r], max_lag=2, validator_tip=lambda: 10)
        # two failed probes trip the breaker -> out of rotation
        pool.probe_once()
        pool.probe_once()
        assert r.breaker.state == "open"
        assert not r.in_rotation
        # still dark within the cooldown: no probe is even attempted
        calls_before = r.calls
        pool.probe_once()
        assert r.calls == calls_before
        # the replica heals; after the cooldown the half-open probe
        # succeeds and the replica is re-admitted to the rotation
        state["dead"] = False
        clock.advance(6.0)
        pool.probe_once()
        assert r.breaker.state == "closed"
        assert r.in_rotation

    def test_lagging_replica_is_rotated_out_not_served(self):
        r_tip = make_replica("tip", healthy_transport(10, "tip"))
        r_lag = make_replica("lag", healthy_transport(6, "lag"))
        pool = make_pool([r_tip, r_lag], max_lag=2, tip=10)
        assert pool.fanout_height == 10
        assert r_tip.in_rotation and r_lag.lagging and not r_lag.in_rotation
        assert pool.rotations_out == 0  # never admitted, never "rotated"
        gw = Gateway(FakeBackendTracker(), pool)
        try:
            for _ in range(6):
                assert gw.handle("getbestblockhash", [], "c") \
                    == "tip:getbestblockhash"
        finally:
            gw.close()

    def test_replica_catching_up_rejoins_the_rotation(self):
        height = {"h": 6}

        def catching_up(method, params):
            if method == "getblockchaininfo":
                return chaininfo(height["h"])
            return "r"

        r = make_replica("r", catching_up)
        pool = make_pool([r], max_lag=2, tip=10)
        assert not r.in_rotation
        height["h"] = 9  # within max_lag of fanout 10
        pool.probe_once()
        assert r.in_rotation

    def test_rotation_out_is_counted(self):
        height = {"h": 10}

        def transport(method, params):
            if method == "getblockchaininfo":
                return chaininfo(height["h"])
            return "r"

        pool = make_pool([make_replica("r", transport)], max_lag=2, tip=10)
        assert pool.replicas[0].in_rotation
        # validator races ahead; the replica wedges at 10
        pool.validator_tip = lambda: 20
        pool.probe_once()
        assert not pool.replicas[0].in_rotation
        assert pool.rotations_out == 1


# -- telemetry discipline ----------------------------------------------


class TestGatewayTelemetry:
    def test_collector_projects_replicas_and_unregisters_on_close(self):
        from bitcoincashplus_tpu.util import telemetry as tm

        pool = make_pool([make_replica("r1", healthy_transport(10))])
        gw = Gateway(FakeBackendTracker(), pool)
        fams = {f["name"]: f for f in tm.REGISTRY._collected()}
        assert "bcp_gateway_replica_state" in fams
        assert "bcp_gateway_replica_in_rotation" in fams
        samples = dict(
            (lbl["replica"], v)
            for lbl, v in fams["bcp_gateway_replica_in_rotation"]["samples"])
        assert samples == {"r1": 1}
        gw.close()
        fams = {f["name"] for f in tm.REGISTRY._collected()}
        assert "bcp_gateway_replica_state" not in fams  # the PR 6 lesson

    def test_two_gateways_do_not_collide(self):
        gw1 = Gateway(FakeBackendTracker(), make_pool([]))
        gw2 = Gateway(FakeBackendTracker(), make_pool([]))
        gw1.close()
        gw2.close()


# -- fault drills: the gateway and replica_rpc sites --------------------


class TestFaultDrills:
    def test_replica_rpc_fail_always_drives_validator_fallback(
            self, fault_harness):
        fault_harness("fail-always", ops="replica_rpc")
        r = make_replica("r", healthy_transport(10))
        r.tip_height, r.in_rotation = 10, True  # pre-armed rotation
        pool = ReplicaPool([r], max_lag=2, validator_tip=lambda: 10)
        backend = FakeBackendTracker()
        gw = Gateway(backend, pool)
        try:
            # the replica leg is dark; the read still lands an answer
            assert gw.handle("getblockcount", [], "c") == "validator"
            assert gw.stats["failovers"] >= 1
            assert gw.stats["validator_fallback"] == 1
        finally:
            gw.close()

    def test_replica_rpc_fail_n_proves_mid_request_failover(
            self, fault_harness):
        fault_harness("fail-n", ops="replica_rpc", n=1)
        r1 = make_replica("r1", healthy_transport(10, "r1"))
        r2 = make_replica("r2", healthy_transport(10, "r2"))
        for r in (r1, r2):
            r.tip_height, r.in_rotation = 10, True
        pool = ReplicaPool([r1, r2], max_lag=2, validator_tip=lambda: 10)
        gw = Gateway(FakeBackendTracker(), pool)
        try:
            # first replica attempt eats the injected fault; the SAME
            # request retries on the other replica and succeeds
            result = gw.handle("getblockcount", [], "c")
            assert result in ("r1:getblockcount", "r2:getblockcount")
            assert gw.stats["failovers"] == 1
            assert gw.stats["validator_fallback"] == 0
        finally:
            gw.close()

    def test_gateway_site_fails_the_front_door_not_the_backends(
            self, fault_harness):
        inj = fault_harness("fail-once", ops="gateway")
        backend = FakeBackendTracker()
        gw = Gateway(backend, make_pool([]))
        try:
            with pytest.raises(InjectedFault):
                gw.handle("getblockcount", [], "c")
            assert backend.calls == []  # failed BEFORE admission/dispatch
            assert inj.injected.get(GATEWAY_SITE) == 1
            # next request sails through — and execute() wraps the fault
            # as a JSON-RPC error, never a silent drop
            assert gw.handle("getblockcount", [], "c") == "validator"
        finally:
            gw.close()

    def test_gateway_latency_spike_is_observed(self, fault_harness):
        fault_harness("latency-spike", ops="gateway", latency_ms=40)
        gw = Gateway(FakeBackendTracker(), make_pool([]))
        try:
            t0 = time.monotonic()
            gw.handle("getblockcount", [], "c")
            assert time.monotonic() - t0 >= 0.035
        finally:
            gw.close()

    def test_sites_are_explicit_only_all_does_not_arm_them(
            self, fault_harness):
        inj = fault_harness("fail-always", ops="all")
        assert not inj.armed_for(GATEWAY_SITE)
        assert not inj.armed_for(REPLICA_RPC_SITE)
        gw = Gateway(FakeBackendTracker(), make_pool([]))
        try:
            assert gw.handle("getblockcount", [], "c") == "validator"
        finally:
            gw.close()


# -- certificate quarantine (ISSUE 17) ---------------------------------


def quarantined_transport(height=10, verified=False):
    """A replica that onboarded from a snapshot: getblockchaininfo
    carries the certificate/quarantine sub-doc the probe keys on."""
    state = {"verified": verified}

    def call(method, params):
        if method == "getblockchaininfo":
            info = chaininfo(height)
            info["snapshot"] = {
                "height": height, "validated": False,
                "cert_present": state["verified"],
                "cert_verified": state["verified"],
                "certificate_verified": state["verified"],
            }
            return info
        return f"q:{method}"

    return call, state


class TestQuarantine:
    def test_unverified_snapshot_replica_is_shed(self):
        t, _ = quarantined_transport(height=10, verified=False)
        quar = make_replica("q", t)
        ok = make_replica("ok", healthy_transport(10, tag="ok"))
        pool = make_pool([quar, ok], tip=10)
        # pool-visible (probed, tip feeds fan-out) but never served from
        assert quar.tip_height == 10 and quar.quarantined
        assert not quar.in_rotation and ok.in_rotation
        assert pool.snapshot()["quarantined"] == 1
        for _ in range(6):
            assert pool.pick().name == "ok"

    def test_verified_certificate_admits_immediately(self):
        t, _ = quarantined_transport(height=10, verified=True)
        rep = make_replica("r", t)
        pool = make_pool([rep], tip=10)
        assert not rep.quarantined and rep.in_rotation

    def test_readmission_when_certificate_verifies(self):
        t, state = quarantined_transport(height=10, verified=False)
        rep = make_replica("r", t)
        pool = make_pool([rep], tip=10)
        assert rep.quarantined and not rep.in_rotation
        # background validation (or a clean certified reload) completes
        state["verified"] = True
        pool.probe_once()
        assert not rep.quarantined and rep.in_rotation
        assert pool.pick().name == "r"

    def test_nodes_without_snapshot_subdoc_never_quarantine(self):
        rep = make_replica("r", healthy_transport(10))
        make_pool([rep], tip=10)
        assert not rep.quarantined and rep.in_rotation

    def test_quarantine_rotation_is_counted_and_metered(self):
        t, state = quarantined_transport(height=10, verified=True)
        rep = make_replica("r", t)
        pool = make_pool([rep], tip=10)
        assert rep.in_rotation
        state["verified"] = False  # poisoned reload mid-flight
        pool.probe_once()
        assert not rep.in_rotation
        assert pool.quarantines == 1
        assert pool.rotations_out == 1
        gw = Gateway(FakeBackendTracker(), pool)
        try:
            fams = {f["name"]: f for f in gw._collect()}
            q = fams["bcp_gateway_replica_quarantined"]["samples"]
            assert q == [({"replica": "r"}, 1)]
        finally:
            gw.close()
