"""Serialization codec tests (reference model: src/test/serialize_tests.cpp)."""

import pytest
pytest.importorskip("hypothesis")  # property tests need the optional test extra
from hypothesis import given
from hypothesis import strategies as st

from bitcoincashplus_tpu.consensus.serialize import (
    ByteReader,
    DeserializationError,
    deser_compact_size,
    deser_var_bytes,
    hash_to_hex,
    hex_to_hash,
    ser_compact_size,
    ser_var_bytes,
    uint256_from_bytes,
    uint256_to_bytes,
)


class TestCompactSize:
    @pytest.mark.parametrize(
        "n,encoded",
        [
            (0, b"\x00"),
            (252, b"\xfc"),
            (253, b"\xfd\xfd\x00"),
            (0xFFFF, b"\xfd\xff\xff"),
            (0x10000, b"\xfe\x00\x00\x01\x00"),
            (0x02000000, b"\xfe\x00\x00\x00\x02"),
        ],
    )
    def test_known_encodings(self, n, encoded):
        assert ser_compact_size(n) == encoded
        assert deser_compact_size(ByteReader(encoded)) == n

    @given(st.integers(min_value=0, max_value=0x02000000))
    def test_roundtrip(self, n):
        assert deser_compact_size(ByteReader(ser_compact_size(n))) == n

    @pytest.mark.parametrize(
        "bad",
        [
            b"\xfd\xfc\x00",
            b"\xfe\xff\xff\x00\x00",
            b"\xff" + (0xFFFFFFFF).to_bytes(8, "little"),  # fits in 0xfe form
        ],
    )
    def test_non_canonical_rejected(self, bad):
        with pytest.raises(DeserializationError):
            deser_compact_size(ByteReader(bad))

    def test_max_size_enforced(self):
        with pytest.raises(DeserializationError):
            deser_compact_size(ByteReader(b"\xfe\x01\x00\x00\x02"))

    def test_truncated(self):
        with pytest.raises(DeserializationError):
            deser_compact_size(ByteReader(b"\xfd\x01"))


class TestVarBytes:
    @given(st.binary(max_size=512))
    def test_roundtrip(self, b):
        assert deser_var_bytes(ByteReader(ser_var_bytes(b))) == b


class TestUint256:
    def test_hex_reversal(self):
        wire = bytes(range(32))
        assert hex_to_hash(hash_to_hex(wire)) == wire

    @given(st.integers(min_value=0, max_value=(1 << 256) - 1))
    def test_int_roundtrip(self, v):
        assert uint256_from_bytes(uint256_to_bytes(v)) == v
