"""Block-level script verification e2e — the graft's second half.

Covers VERDICT r1 item 1's done-criteria: a regtest block containing real
signed P2PKH spends validates through the deferred batch layer; an
invalid-signature block is rejected with correct (tx, input) attribution;
plus the headers-first missing-parent regression (nChainTx gating) and
sigcache behavior.
"""

import pytest

from bitcoincashplus_tpu.consensus.params import regtest_params
from bitcoincashplus_tpu.consensus.tx import COutPoint, CTransaction, CTxIn, CTxOut
from bitcoincashplus_tpu.mining.generate import generate_blocks
from bitcoincashplus_tpu.ops import ecdsa_batch
from bitcoincashplus_tpu.script import script as S
from bitcoincashplus_tpu.store.blockstore import MemoryBlockStore
from bitcoincashplus_tpu.validation.chainstate import (
    BlockValidationError,
    ChainstateManager,
)
from bitcoincashplus_tpu.validation.coins import MemoryCoinsView
from bitcoincashplus_tpu.validation.scriptcheck import (
    BlockScriptVerifier,
    block_script_flags,
)
from bitcoincashplus_tpu.script.interpreter import (
    SCRIPT_ENABLE_SIGHASH_FORKID,
    SCRIPT_VERIFY_NULLFAIL,
)
from bitcoincashplus_tpu.wallet.keys import CKey
from bitcoincashplus_tpu.wallet.signing import sign_transaction

from test_validation import TILE, _hand_mine

KEY = CKey(0xDEADBEEFCAFE)
SPK_KEY = KEY.p2pkh_script()
SPK_OTHER = bytes.fromhex("76a914") + b"\x77" * 20 + bytes.fromhex("88ac")


@pytest.fixture
def chainstate():
    params = regtest_params()
    t = [1_600_000_000]

    def fake_time():
        t[0] += 60
        return t[0]

    verifier = BlockScriptVerifier(params, backend="cpu")
    cs = ChainstateManager(
        params, MemoryCoinsView(), MemoryBlockStore(),
        script_verifier=verifier, get_time=fake_time,
    )
    cs.test_verifier = verifier
    return cs


def _matured_chain(chainstate, n_spendable=1):
    """Mine 100+n blocks paying our key; returns spendable coinbase outpoints."""
    generate_blocks(chainstate, SPK_KEY, 100 + n_spendable, tile=TILE)
    outs = []
    for h in range(1, 1 + n_spendable):
        blk = chainstate.get_block(chainstate.chain[h].hash)
        outs.append((COutPoint(blk.vtx[0].txid, 0), blk.vtx[0].vout[0].value))
    return outs


def _signed_spend(outpoint, value, out_spk=SPK_OTHER, fee=10_000):
    tx = CTransaction(
        vin=(CTxIn(outpoint),),
        vout=(CTxOut(value - fee, out_spk),),
    )
    return sign_transaction(
        tx, [(SPK_KEY, value)], lambda i: KEY if i == KEY.pubkey_hash else None,
        enable_forkid=True,  # regtest uahf_height=0: post-fork flags
    )


def test_regtest_flags_include_forkid_nullfail():
    flags = block_script_flags(1, 1_600_000_000, regtest_params())
    assert flags & SCRIPT_ENABLE_SIGHASH_FORKID
    assert flags & SCRIPT_VERIFY_NULLFAIL


def test_historical_flags_are_era_correct():
    """Mainnet reindex safety: early blocks must NOT get modern flags."""
    from bitcoincashplus_tpu.consensus.params import main_params
    from bitcoincashplus_tpu.script.interpreter import (
        SCRIPT_VERIFY_DERSIG,
        SCRIPT_VERIFY_P2SH,
        SCRIPT_VERIFY_STRICTENC,
    )

    p = main_params()
    # 2010 block: no P2SH, no strict DER, no STRICTENC
    f = block_script_flags(100_000, 1_293_623_863, p)
    assert not f & (SCRIPT_VERIFY_P2SH | SCRIPT_VERIFY_DERSIG
                    | SCRIPT_VERIFY_STRICTENC)
    # 2013 block: P2SH on (time gate), still no DERSIG
    f = block_script_flags(250_000, 1_375_533_383, p)
    assert f & SCRIPT_VERIFY_P2SH and not f & SCRIPT_VERIFY_DERSIG
    # post-BIP66, pre-fork: DERSIG but not FORKID
    f = block_script_flags(400_000, 1_456_000_000, p)
    assert f & SCRIPT_VERIFY_DERSIG and not f & SCRIPT_ENABLE_SIGHASH_FORKID
    # post-fork: the whole bundle
    f = block_script_flags(500_000, 1_510_000_000, p)
    assert f & SCRIPT_ENABLE_SIGHASH_FORKID and f & SCRIPT_VERIFY_NULLFAIL


class TestSignedBlockConnect:
    def test_signed_p2pkh_spend_connects(self, chainstate):
        (op, value), = _matured_chain(chainstate)
        spend = _signed_spend(op, value)
        tip = chainstate.tip()
        blk = _hand_mine(
            tip.hash, tip.height + 1, chainstate.get_time() + 10,
            tip.bits, (spend,),
        )
        chainstate.process_new_block(blk)
        assert chainstate.tip().hash == blk.get_hash()
        assert chainstate.coins.get_coin(op) is None  # spent
        # the sig went through the batch layer and into the sigcache
        assert len(chainstate.test_verifier.sigcache) == 1

    def test_unsigned_spend_rejected(self, chainstate):
        (op, value), = _matured_chain(chainstate)
        bogus = CTransaction(
            vin=(CTxIn(op, b"\x51"),),  # OP_TRUE scriptSig, no signature
            vout=(CTxOut(value - 10_000, SPK_OTHER),),
        )
        tip = chainstate.tip()
        blk = _hand_mine(
            tip.hash, tip.height + 1, chainstate.get_time() + 10,
            tip.bits, (bogus,),
        )
        chainstate.process_new_block(blk)
        assert chainstate.tip().hash != blk.get_hash()  # rejected at connect

    def test_tampered_sig_rejected_with_attribution(self, chainstate):
        (op, value), = _matured_chain(chainstate)
        spend = _signed_spend(op, value)
        # corrupt one byte inside the DER s-value
        ss = bytearray(spend.vin[0].script_sig)
        ss[40] ^= 0x01
        tampered = CTransaction(
            spend.version,
            (CTxIn(op, bytes(ss)),),
            spend.vout, spend.locktime,
        )
        tip = chainstate.tip()
        blk = _hand_mine(
            tip.hash, tip.height + 1, chainstate.get_time() + 10,
            tip.bits, (tampered,),
        )
        # drive connect directly for the attribution message
        idx = chainstate.accept_block(blk)
        with pytest.raises(BlockValidationError) as ei:
            chainstate.connect_block(blk, idx)
        assert tampered.txid_hex in str(ei.value)
        assert "input 0" in str(ei.value)

    def test_wrong_amount_rejected_forkid(self, chainstate):
        """FORKID sighash commits to the amount: a block whose UTXO amount
        differs from what was signed must fail."""
        (op, value), = _matured_chain(chainstate)
        # sign claiming the wrong amount
        tx = CTransaction(
            vin=(CTxIn(op),), vout=(CTxOut(value - 10_000, SPK_OTHER),),
        )
        bad = sign_transaction(
            tx, [(SPK_KEY, value + 1)], lambda i: KEY, enable_forkid=True
        )
        tip = chainstate.tip()
        blk = _hand_mine(
            tip.hash, tip.height + 1, chainstate.get_time() + 10,
            tip.bits, (bad,),
        )
        chainstate.process_new_block(blk)
        assert chainstate.tip().hash != blk.get_hash()

    def test_multi_input_block_one_dispatch(self, chainstate):
        """Several signed txs in one block -> one batch (STATS delta)."""
        outs = _matured_chain(chainstate, n_spendable=3)
        spends = tuple(_signed_spend(op, v) for op, v in outs)
        tip = chainstate.tip()
        blk = _hand_mine(
            tip.hash, tip.height + 1, chainstate.get_time() + 10,
            tip.bits, spends,
        )
        before = ecdsa_batch.STATS.cpu_fallback_sigs
        chainstate.process_new_block(blk)
        assert chainstate.tip().hash == blk.get_hash()
        assert ecdsa_batch.STATS.cpu_fallback_sigs == before + 3
        assert len(chainstate.test_verifier.sigcache) == 3

    def test_chunked_pipeline_dispatch(self, chainstate):
        """P3 pipeline overlap: with chunk=1 every tx's records ship as an
        independent in-flight dispatch; verdict and sigcache behavior are
        identical to the single-batch path."""
        outs = _matured_chain(chainstate, n_spendable=3)
        spends = tuple(_signed_spend(op, v) for op, v in outs)
        tip = chainstate.tip()
        blk = _hand_mine(
            tip.hash, tip.height + 1, chainstate.get_time() + 10,
            tip.bits, spends,
        )
        chainstate.test_verifier.chunk = 1  # force per-tx chunks
        before = ecdsa_batch.STATS.cpu_fallback_sigs
        try:
            chainstate.process_new_block(blk)
        finally:
            chainstate.test_verifier.chunk = 4096
        assert chainstate.tip().hash == blk.get_hash()
        assert ecdsa_batch.STATS.cpu_fallback_sigs == before + 3
        assert len(chainstate.test_verifier.sigcache) == 3

    def test_chunked_pipeline_attribution(self, chainstate):
        """A bad sig in a later chunk still attributes to (tx, input)."""
        outs = _matured_chain(chainstate, n_spendable=2)
        good = _signed_spend(*outs[0])
        bad_src = _signed_spend(*outs[1])
        ss = bytearray(bad_src.vin[0].script_sig)
        ss[40] ^= 0x01
        bad = CTransaction(
            bad_src.version, (CTxIn(outs[1][0], bytes(ss)),),
            bad_src.vout, bad_src.locktime,
        )
        tip = chainstate.tip()
        blk = _hand_mine(
            tip.hash, tip.height + 1, chainstate.get_time() + 10,
            tip.bits, (good, bad),
        )
        chainstate.test_verifier.chunk = 1
        idx = chainstate.accept_block(blk)
        try:
            with pytest.raises(BlockValidationError) as ei:
                chainstate.connect_block(blk, idx)
        finally:
            chainstate.test_verifier.chunk = 4096
        assert bad.txid_hex in str(ei.value)

    def test_multisig_spend_metered_as_eager(self, chainstate):
        """CHECKMULTISIG trials bypass the batch by design (outcome-dependent
        sig->pubkey assignment); VERDICT r2 weak #8: they must be METERED.
        A 1-of-1 bare multisig spend connects and bumps eager_multisig_sigs."""
        (op, value), = _matured_chain(chainstate)
        ms_spk = S.multisig_script(1, [KEY.pubkey])
        setup = _signed_spend(op, value, out_spk=ms_spk)
        tip = chainstate.tip()
        blk1 = _hand_mine(
            tip.hash, tip.height + 1, chainstate.get_time() + 10,
            tip.bits, (setup,),
        )
        chainstate.process_new_block(blk1)
        assert chainstate.tip().hash == blk1.get_hash()

        tx = CTransaction(
            vin=(CTxIn(COutPoint(setup.txid, 0)),),
            vout=(CTxOut(setup.vout[0].value - 10_000, SPK_OTHER),),
        )
        spend = sign_transaction(
            tx, [(ms_spk, setup.vout[0].value)],
            lambda ident: KEY if ident == KEY.pubkey else None,
            enable_forkid=True,
        )
        before = ecdsa_batch.STATS.eager_multisig_sigs
        tip = chainstate.tip()
        blk2 = _hand_mine(
            tip.hash, tip.height + 1, chainstate.get_time() + 10,
            tip.bits, (spend,),
        )
        chainstate.process_new_block(blk2)
        assert chainstate.tip().hash == blk2.get_hash()
        assert ecdsa_batch.STATS.eager_multisig_sigs == before + 1

    def test_sigcache_skips_reverification(self, chainstate):
        (op, value), = _matured_chain(chainstate)
        spend = _signed_spend(op, value)
        tip = chainstate.tip()
        blk = _hand_mine(
            tip.hash, tip.height + 1, chainstate.get_time() + 10,
            tip.bits, (spend,),
        )
        chainstate.process_new_block(blk)
        cache = chainstate.test_verifier.sigcache
        hits_before = cache.hits
        # replay the same records through the verifier: all cache hits
        idx = chainstate.block_index[blk.get_hash()]
        from bitcoincashplus_tpu.validation.coins import Coin

        spent = [[Coin(CTxOut(value, SPK_KEY), 1, True)]]
        chainstate.script_verifier(blk, idx, spent)
        assert cache.hits > hits_before


class TestHeadersFirst:
    def test_child_block_waits_for_parent_data(self, chainstate):
        """ADVICE r1 #4 regression: header-only parent + full child must
        not crash or advance the tip; once the parent block arrives both
        connect."""
        generate_blocks(chainstate, SPK_KEY, 1, tile=TILE)
        tip = chainstate.tip()
        t0 = chainstate.get_time() + 10
        parent = _hand_mine(tip.hash, tip.height + 1, t0, tip.bits, ())
        child = _hand_mine(
            parent.get_hash(), tip.height + 2, t0 + 60, tip.bits, ()
        )
        chainstate.accept_block_header(parent.header)
        chainstate.process_new_block(child)  # parent data missing
        assert chainstate.tip() is tip  # no crash, no premature advance
        chainstate.process_new_block(parent)
        assert chainstate.tip().hash == child.get_hash()
