"""Supervised-dispatch fault injection across the four accelerator entry
points (ops/sha256, ops/merkle, ops/miner, ops/ecdsa_batch).

For every injected failure mode the assertions are the tentpole's two
invariants: (a) the verdict/output is IDENTICAL to the pure-CPU reference
engine — a dead or lying backend can never change consensus — and (b) the
subsystem's circuit breaker trips on hard failures and recovers through a
half-open probe once the fault clears.

The ECDSA device kernel is stubbed (oracle-backed fake for the XLA entry)
so the harness logic — KAT lanes, settle-time detection, CPU re-verify —
is exercised without the minutes-long kernel compile; everything else runs
the real jitted paths on the CPU backend. All tests here are tier-1 fast
and run by default (pytest -m faults for the smoke subset alone).
"""

import numpy as np
import pytest

from bitcoincashplus_tpu.consensus.merkle import compute_merkle_root
from bitcoincashplus_tpu.consensus.params import regtest_params
from bitcoincashplus_tpu.crypto import secp256k1 as oracle
from bitcoincashplus_tpu.crypto.hashes import sha256d
from bitcoincashplus_tpu.ops import dispatch, ecdsa_batch
from bitcoincashplus_tpu.ops.merkle import compute_merkle_root_tpu
from bitcoincashplus_tpu.ops.miner import sweep_header_cpu
from bitcoincashplus_tpu.ops.sha256 import sha256d_headers, sha256d_headers_cpu
from bitcoincashplus_tpu.script.interpreter import SigCheckRecord
from bitcoincashplus_tpu.util import faults

pytestmark = pytest.mark.faults

TILE = 1 << 12
rng = np.random.default_rng(1234)


@pytest.fixture(autouse=True)
def _clean(fault_harness):
    """Every test starts from a pristine breaker registry (fault_harness
    from conftest owns teardown)."""
    dispatch.reset()
    yield


def _open_fast():
    """Breaker config for fail-always tests: first hard failure opens, no
    probes until explicitly re-enabled."""
    dispatch.configure(threshold=1, retries=0, cooldown=1e9, probe=0.0)


# ---------------------------------------------------------------------------
# sha256 — batched header hashing
# ---------------------------------------------------------------------------

class TestSha256Faults:
    HDRS = rng.integers(0, 256, (8, 80), dtype=np.uint8)

    def _ref(self):
        return sha256d_headers_cpu(self.HDRS)

    def test_fail_once_absorbed_by_retry(self, fault_harness):
        dispatch.configure(retries=1, threshold=2)
        fault_harness("fail-once", ops="sha256")
        out = sha256d_headers(self.HDRS)
        assert np.array_equal(out, self._ref())
        assert dispatch.breaker("sha256").state == "closed"
        assert faults.INJECTOR.injected.get("sha256") == 1

    def test_fail_always_trips_then_recovers(self, fault_harness):
        _open_fast()
        fault_harness("fail-always", ops="sha256")
        for _ in range(3):
            assert np.array_equal(sha256d_headers(self.HDRS), self._ref())
        snap = dispatch.breaker("sha256").snapshot()
        assert snap["state"] == "open" and snap["fallback_items"] >= 24
        # fault clears -> half-open probe closes the breaker
        fault_harness("off")
        br = dispatch.breaker("sha256")
        br.cfg.cooldown, br.cfg.probe = 0.0, 1.0
        assert np.array_equal(sha256d_headers(self.HDRS), self._ref())
        assert br.state == "closed" and br.snapshot()["recoveries"] == 1

    def test_poison_output_caught_by_spot_check(self, fault_harness):
        _open_fast()
        fault_harness("poison-output", ops="sha256")
        out = sha256d_headers(self.HDRS)
        assert np.array_equal(out, self._ref())  # CPU result, not poison
        assert dispatch.breaker("sha256").state == "open"


# ---------------------------------------------------------------------------
# merkle — device tree reduction
# ---------------------------------------------------------------------------

class TestMerkleFaults:
    HASHES = [rng.integers(0, 256, 32, dtype=np.uint8).tobytes()
              for _ in range(21)]

    def test_fail_once_absorbed_by_retry(self, fault_harness):
        dispatch.configure(retries=1, threshold=2)
        fault_harness("fail-once", ops="merkle")
        assert compute_merkle_root_tpu(self.HASHES) == \
            compute_merkle_root(self.HASHES)
        assert dispatch.breaker("merkle").state == "closed"

    def test_fail_always_trips_then_recovers(self, fault_harness):
        _open_fast()
        fault_harness("fail-always", ops="merkle")
        for _ in range(2):
            assert compute_merkle_root_tpu(self.HASHES) == \
                compute_merkle_root(self.HASHES)
        br = dispatch.breaker("merkle")
        assert br.state == "open" and br.snapshot()["fallback_items"] > 0
        fault_harness("off")
        br.cfg.cooldown, br.cfg.probe = 0.0, 1.0
        assert compute_merkle_root_tpu(self.HASHES) == \
            compute_merkle_root(self.HASHES)
        assert br.state == "closed"

    def test_poison_output_caught_by_witness(self, fault_harness):
        """A corrupted device root is rejected by the level-1 witness
        recompute and the CPU root reaches the caller."""
        _open_fast()
        fault_harness("poison-output", ops="merkle")
        assert compute_merkle_root_tpu(self.HASHES) == \
            compute_merkle_root(self.HASHES)
        assert dispatch.breaker("merkle").state == "open"

    def test_mutation_flag_preserved_through_fallback(self, fault_harness):
        _open_fast()
        fault_harness("fail-always", ops="merkle")
        dup = self.HASHES + self.HASHES[-1:]
        root, mutated = compute_merkle_root_tpu(dup)
        ref_root, ref_mut = compute_merkle_root(dup)
        assert (root, mutated) == (ref_root, ref_mut) and mutated


# ---------------------------------------------------------------------------
# miner — PoW nonce sweep
# ---------------------------------------------------------------------------

class TestMinerFaults:
    HEADER = bytes(regtest_params().genesis.header.serialize())
    EASY = regtest_params().consensus.pow_limit

    def test_fail_once_absorbed_by_retry(self, fault_harness):
        dispatch.configure(retries=1, threshold=2)
        fault_harness("fail-once", ops="miner")
        sweep = dispatch.supervised_sweep()
        nonce, _ = sweep(self.HEADER, self.EASY, max_nonces=1 << 16,
                         tile=TILE)
        ref, _ = sweep_header_cpu(self.HEADER, self.EASY,
                                  max_nonces=1 << 16)
        assert nonce == ref
        assert dispatch.breaker("miner").state == "closed"

    def test_fail_always_degrades_to_scalar_loop(self, fault_harness):
        _open_fast()
        fault_harness("fail-always", ops="miner")
        sweep = dispatch.supervised_sweep()
        for _ in range(2):
            nonce, _ = sweep(self.HEADER, self.EASY, max_nonces=1 << 16,
                             tile=TILE)
            ref, _ = sweep_header_cpu(self.HEADER, self.EASY,
                                      max_nonces=1 << 16)
            assert nonce == ref
        br = dispatch.breaker("miner")
        assert br.state == "open"
        fault_harness("off")
        br.cfg.cooldown, br.cfg.probe = 0.0, 1.0
        nonce, _ = sweep(self.HEADER, self.EASY, max_nonces=1 << 16,
                         tile=TILE)
        assert nonce == sweep_header_cpu(self.HEADER, self.EASY,
                                         max_nonces=1 << 16)[0]
        assert br.state == "closed"

    def test_poison_nonce_rejected_by_host_reverify(self, fault_harness):
        """Tight target (exactly the window's minimum hash, so only ONE
        nonce can satisfy it): a poisoned nonce fails the host
        re-verification and the CPU loop's honest nonce is returned."""
        hashes = [
            int.from_bytes(
                sha256d(self.HEADER[:76] + i.to_bytes(4, "little")),
                "little")
            for i in range(512)
        ]
        ref = min(range(512), key=hashes.__getitem__)
        tight = hashes[ref]
        _open_fast()
        fault_harness("poison-output", ops="miner")
        sweep = dispatch.supervised_sweep()
        nonce, _ = sweep(self.HEADER, tight, max_nonces=1 << 16, tile=TILE)
        assert nonce == ref
        assert dispatch.breaker("miner").state == "open"


# ---------------------------------------------------------------------------
# ecdsa — batched signature verification (stubbed device kernel)
# ---------------------------------------------------------------------------

def _make_records(n_good=3, n_bad=1):
    recs = []
    for i in range(n_good):
        d, e = 0x1000 + i, (0xABCDEF + i) % oracle.N
        r, s = oracle.ecdsa_sign(d, e)
        recs.append(SigCheckRecord(oracle.point_mul(d, oracle.G), r, s, e))
    for i in range(n_bad):
        d, e = 0x2000 + i, (0x123456 + i) % oracle.N
        r, s = oracle.ecdsa_sign(d, e)
        recs.append(SigCheckRecord(oracle.point_mul(d, oracle.G), r, s,
                                   (e + 1) % oracle.N))
    return recs


@pytest.fixture
def fake_kernel(monkeypatch):
    """Stand-in for the XLA verify kernel: evaluates the packed batch's
    verdicts with the Python-int oracle at dispatch time (so KAT lanes get
    honest answers) — the dispatch/KAT/fallback plumbing under test is
    identical to the real kernel's."""
    import bitcoincashplus_tpu.ops.secp256k1 as dev

    monkeypatch.setenv("BCP_SECP_PALLAS", "0")
    # pin the w4/XLA kernel: the GLV leg (default) would bypass this stub
    # and pay a real kernel compile — the GLV drill has its own suite
    # (tests/unit/test_glv.py)
    monkeypatch.setenv("BCP_ECDSA_KERNEL", "w4")
    state: dict = {"mask": None}
    real_pack = ecdsa_batch.pack_records

    def spy_pack(records, bucket):
        state["mask"] = [
            oracle.ecdsa_verify(r.pubkey, r.r, r.s, r.msg_hash)
            for r in records
        ]
        return real_pack(records, bucket)

    def fake_jit(u1b, u2b, qx, qy, q_inf, r0, rn, wrap_ok):
        out = np.zeros(q_inf.shape[0], bool)
        out[: len(state["mask"])] = state["mask"]
        return out

    monkeypatch.setattr(ecdsa_batch, "pack_records", spy_pack)
    monkeypatch.setattr(dev, "ecdsa_verify_batch_jit", fake_jit)
    return state


class TestEcdsaFaults:
    EXPECTED = np.array([True, True, True, False])

    def test_fail_once_absorbed_by_retry(self, fault_harness, fake_kernel):
        dispatch.configure(retries=1, threshold=2)
        fault_harness("fail-once", ops="ecdsa")
        recs = _make_records()
        got = ecdsa_batch.verify_batch(recs, backend="device")
        assert np.array_equal(got, self.EXPECTED)
        assert dispatch.breaker("ecdsa").state == "closed"

    def test_fail_always_cpu_reverify_and_recovery(self, fault_harness,
                                                   fake_kernel):
        _open_fast()
        fault_harness("fail-always", ops="ecdsa")
        recs = _make_records()
        before = ecdsa_batch.STATS.fault_fallback_sigs
        for _ in range(3):
            got = ecdsa_batch.verify_batch(recs, backend="device")
            assert np.array_equal(got, self.EXPECTED)
        br = dispatch.breaker("ecdsa")
        snap = br.snapshot()
        assert snap["state"] == "open" and snap["fallback_items"] >= 8
        # every fallback sig is metered (satellite: sigop metering)
        assert ecdsa_batch.STATS.fault_fallback_sigs - before == 12
        fault_harness("off")
        br.cfg.cooldown, br.cfg.probe = 0.0, 1.0
        got = ecdsa_batch.verify_batch(recs, backend="device")
        assert np.array_equal(got, self.EXPECTED)
        assert br.state == "closed" and br.snapshot()["recoveries"] == 1

    def test_poison_mask_caught_by_kat_lanes(self, fault_harness,
                                             fake_kernel):
        """An inverted validity mask flips BOTH known-answer lanes wrong-
        side; the batch is discarded and the verdict is a fresh CPU
        verification — invalid sigs stay invalid, valid ones valid."""
        _open_fast()
        fault_harness("poison-output", ops="ecdsa")
        recs = _make_records()
        kat_before = ecdsa_batch.STATS.kat_failures
        got = ecdsa_batch.verify_batch(recs, backend="device")
        assert np.array_equal(got, self.EXPECTED)
        assert ecdsa_batch.STATS.kat_failures == kat_before + 1
        assert dispatch.breaker("ecdsa").state == "open"

    def test_open_breaker_routes_straight_to_cpu(self, fault_harness,
                                                 fake_kernel):
        _open_fast()
        fault_harness("fail-always", ops="ecdsa")
        recs = _make_records()
        ecdsa_batch.verify_batch(recs, backend="device")  # trips it
        fault_harness("off")  # device would work again, but breaker is open
        calls_before = faults.INJECTOR.calls.get("ecdsa", 0)
        got = ecdsa_batch.verify_batch(recs, backend="device")
        assert np.array_equal(got, self.EXPECTED)
        assert faults.INJECTOR.calls.get("ecdsa", 0) == calls_before


# ---------------------------------------------------------------------------
# consensus/pow — batched header PoW rides the sha256 breaker
# ---------------------------------------------------------------------------

class TestHeadersPowBatch:
    def test_verdict_matches_scalar_check(self):
        from bitcoincashplus_tpu.consensus.pow import (
            check_headers_pow_batch,
            check_proof_of_work,
        )

        params = regtest_params()
        good = params.genesis.header.serialize()
        bad = bytearray(good)
        bad[0] ^= 0x01  # version flip invalidates the (easy) regtest PoW?
        # regtest PoW is nearly always satisfied — build a header failing
        # the target by pointing nBits at an impossible compact target
        bad2 = bytearray(good)
        bad2[72:76] = (0x01003456).to_bytes(4, "little")  # tiny target
        batch = [bytes(good), bytes(bad), bytes(bad2)]
        got = check_headers_pow_batch(batch, params.consensus)
        ref = [
            check_proof_of_work(
                sha256d(h), int.from_bytes(h[72:76], "little"),
                params.consensus)
            for h in batch
        ]
        assert got == ref

    def test_dead_backend_same_verdict(self, fault_harness):
        from bitcoincashplus_tpu.consensus.pow import check_headers_pow_batch

        params = regtest_params()
        batch = [params.genesis.header.serialize()] * 4
        ref = check_headers_pow_batch(batch, params.consensus)
        _open_fast()
        fault_harness("fail-always", ops="sha256")
        got = check_headers_pow_batch(batch, params.consensus)
        assert got == ref
        assert dispatch.breaker("sha256").state == "open"


# ---------------------------------------------------------------------------
# gettpuinfo surfaces breaker + fault state
# ---------------------------------------------------------------------------

def test_gettpuinfo_reports_breakers_and_faults(fault_harness):
    from types import SimpleNamespace

    from bitcoincashplus_tpu.rpc.control import gettpuinfo
    from bitcoincashplus_tpu.validation.sigcache import SignatureCache

    _open_fast()
    fault_harness("fail-always", ops="sha256")
    hdrs = rng.integers(0, 256, (4, 80), dtype=np.uint8)
    sha256d_headers(hdrs)
    node = SimpleNamespace(backend="auto", sigcache=SignatureCache(),
                           chainstate=SimpleNamespace(bench={}))
    info = gettpuinfo(node, [])
    assert info["breakers"]["sha256"]["state"] == "open"
    assert info["breakers"]["sha256"]["fallback_items"] >= 4
    assert info["faults"]["mode"] == "fail-always"
    assert "batch" in info and "fault_fallback_sigs" in info["batch"]
