"""Differential tests for the specialized truncated-h7 sweep kernel
(ops/sha256_sweep.py) against the hashlib scalar oracle."""

import hashlib

import numpy as np
import jax.numpy as jnp
import pytest

from bitcoincashplus_tpu.crypto.hashes import header_midstate, sha256d
from bitcoincashplus_tpu.ops.sha256 import bytes_to_words_np, target_to_limbs_np
from bitcoincashplus_tpu.ops import miner
from bitcoincashplus_tpu.ops.sha256_sweep import (
    sweep_fast_jit,
    sweep_h7,
    sweep_header_fast,
)


def _oracle_h7(header80: bytes) -> int:
    """Digest word h[7] (BE) of sha256d(header) == digest bytes 28..32."""
    return int.from_bytes(sha256d(header80)[28:32], "big")


def _parts(header80):
    mid = np.array(header_midstate(header80), dtype=np.uint32)
    tail = bytes_to_words_np(np.frombuffer(header80[64:76], dtype=np.uint8))
    return mid, tail


def test_h7_matches_oracle_numpy_consts():
    """Trace-time-folded path: midstate/tail as numpy scalars."""
    rng = np.random.default_rng(7)
    header = rng.integers(0, 256, size=80, dtype=np.uint8).tobytes()
    mid, tail = _parts(header)
    nonces = rng.integers(0, 2**32, size=64, dtype=np.uint32)
    h7 = np.asarray(sweep_h7(list(mid), list(tail), jnp.asarray(nonces)))
    for i, n in enumerate(nonces):
        hdr = header[:76] + int(n).to_bytes(4, "little")
        assert int(h7[i]) == _oracle_h7(hdr)


@pytest.mark.slow
def test_h7_matches_oracle_traced_scalars():
    """One-compilation path: midstate/tail as traced device arrays.
    slow: the unrolled ~120-round program is compile-heavy on the CPU
    backend (see ops/sha256._use_unrolled); the TPU bench exercises it."""
    import jax

    rng = np.random.default_rng(8)
    header = rng.integers(0, 256, size=80, dtype=np.uint8).tobytes()
    mid, tail = _parts(header)

    @jax.jit
    def f(mid, tail, nonces):
        return sweep_h7([mid[i] for i in range(8)], [tail[i] for i in range(3)], nonces)

    nonces = rng.integers(0, 2**32, size=32, dtype=np.uint32)
    h7 = np.asarray(f(jnp.asarray(mid), jnp.asarray(tail), jnp.asarray(nonces)))
    for i, n in enumerate(nonces):
        hdr = header[:76] + int(n).to_bytes(4, "little")
        assert int(h7[i]) == _oracle_h7(hdr)


def test_sweep_fast_agrees_with_generic_sweep():
    """Same first-hit nonce as ops.miner.sweep_header on a regtest-easy
    target (exercises the candidate/verify/resume loop end to end).
    Runs eagerly (disable_jit) so the unrolled program never hits the slow
    CPU XLA compile; the jitted path is covered by the slow tests + bench."""
    import jax

    header = bytes(range(80))
    target = (1 << 255) - 1  # ~every second hash passes: forces candidates
    with jax.disable_jit():
        n_ref, _ = miner.sweep_header(header, target, max_nonces=1 << 10, tile=1 << 7)
        n_fast, _ = sweep_header_fast(header, target, max_nonces=1 << 10, tile=1 << 7)
    assert n_ref is not None and n_fast == n_ref


def test_sweep_fast_false_positive_resume():
    """A target whose top limb matches some hash's limb7 while the full
    256-bit compare fails forces the candidate/reject/resume path: pick the
    target just below a known hash so limb7 ties but the hash is > target."""
    import jax

    header = b"\xab" * 80
    # hash of nonce 0 for this header
    h0 = int.from_bytes(sha256d(header[:76] + b"\x00" * 4), "little")
    target = h0 - 1  # limb7 equal (almost surely), full compare fails
    with jax.disable_jit():
        nonce, _ = sweep_header_fast(header, target, max_nonces=1 << 9, tile=1 << 7)
    if nonce is not None:
        hdr = header[:76] + nonce.to_bytes(4, "little")
        assert int.from_bytes(sha256d(hdr), "little") <= target
        assert nonce != 0


@pytest.mark.slow
def test_sweep_fast_regtest_difficulty():
    """Regtest-grade target (top limb 0x007fffff): hit must exact-verify
    and be the first passing nonce. slow: compiles the jitted sweep."""
    header = b"\xab" * 80
    target = 0x7FFFFF << (8 * 29)
    nonce, hashes = sweep_header_fast(header, target, max_nonces=1 << 14, tile=1 << 9)
    assert nonce is not None
    hdr = header[:76] + nonce.to_bytes(4, "little")
    assert int.from_bytes(sha256d(hdr), "little") <= target
    # and it is the FIRST such nonce
    for n in range(nonce):
        h = header[:76] + n.to_bytes(4, "little")
        assert int.from_bytes(sha256d(h), "little") > target


def test_sweep_fast_no_hit():
    """Impossible target: full sweep, no result, correct hash count."""
    import jax

    header = b"\x01" * 80
    with jax.disable_jit():
        nonce, hashes = sweep_header_fast(header, 0, max_nonces=1 << 9, tile=1 << 7)
    # limb7 == 0 prefilter can fire spuriously only with p ~ 2^-32; with 512
    # nonces a candidate is (overwhelmingly) never produced, and any produced
    # candidate would be rejected by the exact host check anyway.
    assert nonce is None
    assert hashes >= 1 << 9
