"""Tier-1 telemetry smoke test (ISSUE 6 satellite): boot a real in-process
Node with -telemetry=trace, import a small corpus through the pipelined
Python engine, and validate the dumped trace's JSON schema plus the
/metrics + getmetrics subsystem coverage — the whole observability
surface exercised end to end, CPU backend, no sockets."""

from __future__ import annotations

import json
import sys

import pytest

from bitcoincashplus_tpu.node.config import Config, ConfigError
from bitcoincashplus_tpu.node.node import Node
from bitcoincashplus_tpu.util import telemetry as tm

sys.path.insert(0, __file__.rsplit("/tests/", 1)[0])

pytestmark = pytest.mark.telemetry

SPK = bytes.fromhex("76a914") + b"\x22" * 20 + bytes.fromhex("88ac")


def _mk_node(path, **args):
    cfg = Config()
    cfg.args["datadir"] = [str(path)]
    cfg.args["regtest"] = ["1"]
    for k, v in args.items():
        cfg.args[k] = [str(v)]
    return Node(config=cfg)


@pytest.fixture
def restore_mode():
    yield
    tm.reset()


def test_node_trace_smoke(tmp_path, monkeypatch, restore_mode):
    datadir = tmp_path / "node"
    tracefile = tmp_path / "trace.json"

    # phase 1: mine a small chain (telemetry default: counters)
    node = _mk_node(datadir)
    with node.cs_main:
        node.generate_to_script(SPK, 6)
    node.close()

    # phase 2: -reindex through the PIPELINED PYTHON engine with
    # -telemetry=trace and a -tracefile sink (native fast-import pinned
    # off so the settle-horizon spans are the ones under test)
    monkeypatch.setenv("BCP_NO_NATIVE_IMPORT", "1")
    tm.TRACER.clear()
    node = _mk_node(datadir, reindex=1, pipelinedepth=4,
                    telemetry="trace", tracefile=str(tracefile))
    assert node.telemetry_mode == "trace"
    try:
        assert node.chainstate.tip().height == 6

        # gettpuinfo stays a superset of its PR-5 shape on a REAL node
        from bitcoincashplus_tpu.rpc.control import (dumptrace, getmetrics,
                                                     gettpuinfo)

        info = gettpuinfo(node, [])
        for key in ("backend", "batch", "breakers", "sigcache", "pipeline",
                    "telemetry"):
            assert key in info
        assert info["telemetry"]["mode"] == "trace"
        assert info["telemetry"]["spans"]["recorded"] > 0

        # getmetrics + /metrics cover every subsystem the issue names
        # (net via the collector a connman would register — simulated
        # here so the smoke test stays socket-free)
        tm.register_collector("net", lambda: [{
            "name": "bcp_net_peers", "type": "gauge", "help": "",
            "samples": [({}, 0)]}])
        snap = getmetrics(node, [])
        from bitcoincashplus_tpu.rpc.rest import handle_metrics

        _st, _ct, body = handle_metrics(node)
        text = body.decode()
        for prefix in ("bcp_dispatch_", "bcp_ecdsa_", "bcp_pipeline_",
                       "bcp_sigcache_", "bcp_mempool_", "bcp_net_",
                       # device-lane families (util/devicewatch): the
                       # compile sentinel, transfer totals, and the
                       # memory collector must be visible after a
                       # regtest import — ISSUE 8 acceptance surface
                       "bcp_xla_compile_", "bcp_device_transfer_bytes",
                       "bcp_device_memory_", "bcp_watchdog_"):
            assert any(n.startswith(prefix) for n in snap), prefix
            assert prefix in text, prefix
        # the pipelined import actually recorded per-block legs
        scan = snap["bcp_pipeline_scan_seconds"]["values"][0]
        assert scan["count"] >= 6
        assert {"p50", "p90", "p99"} <= set(scan)

        # dumptrace mid-flight works too (independent of -tracefile)
        mid = dumptrace(node, [str(tmp_path / "mid.json")])
        assert mid["events"] > 0 and mid["mode"] == "trace"
    finally:
        node.close()
        tm.REGISTRY.unregister_collector("net")  # the simulated one

    # phase 3: the -tracefile shutdown dump, schema-validated
    assert tracefile.exists()
    trace = json.loads(tracefile.read_text())
    events = trace["traceEvents"]
    assert isinstance(events, list) and events
    names = set()
    for ev in events:
        assert isinstance(ev["name"], str)
        assert ev["ph"] in ("X", "i")
        assert isinstance(ev["ts"], (int, float)) and ev["ts"] >= 0
        assert isinstance(ev["pid"], int) and isinstance(ev["tid"], int)
        assert isinstance(ev["args"], dict)
        if ev["ph"] == "X":
            assert isinstance(ev["dur"], (int, float)) and ev["dur"] >= 0
            assert isinstance(ev["args"]["corr"], int)
            assert isinstance(ev["args"]["span_id"], int)
        names.add(ev["name"])
    # the pipeline's span vocabulary made it into the dump
    assert {"block.scan", "block.settle", "block.commit"} <= names

    # and the offline summarizer measures a per-block overlap from it
    from tools import trace_view

    blocks = trace_view.block_overlap(events)
    assert len(blocks) >= 6
    for b in blocks:
        assert 0.0 <= b["overlap"] <= 1.0
    report = trace_view.summarize(events)
    assert "aggregate overlap fraction:" in report
    assert "top 10 slowest settles" in report


def test_unknown_telemetry_level_rejected_at_startup(tmp_path,
                                                     restore_mode):
    with pytest.raises(ConfigError, match="telemetry"):
        _mk_node(tmp_path / "bad", telemetry="verbose")


def test_tracefile_implies_trace_mode(tmp_path, restore_mode):
    node = _mk_node(tmp_path / "imp", tracefile=str(tmp_path / "t.json"))
    try:
        assert node.telemetry_mode == "trace"
    finally:
        node.close()
    assert (tmp_path / "t.json").exists()


def test_tracefile_with_lower_level_rejected(tmp_path, restore_mode):
    """-telemetry=counters -tracefile=x would silently write an empty
    dump — the contradiction is rejected at startup instead."""
    with pytest.raises(ConfigError, match="tracefile"):
        _mk_node(tmp_path / "c", telemetry="counters",
                 tracefile=str(tmp_path / "t.json"))


def test_close_unregisters_node_collectors(tmp_path, restore_mode):
    """A closed node's bound-method collectors must not keep its object
    graph alive in the process-global registry."""
    node = _mk_node(tmp_path / "u")
    reg = tm.REGISTRY
    assert {"sigcache", "pipeline", "mempool"} <= set(reg._collectors)
    node.close()
    assert not ({"sigcache", "pipeline", "mempool"}
                & set(reg._collectors))


def test_no_duplicate_metric_families_in_exposition(restore_mode):
    """The ecdsa collector must not re-emit names owned by native
    families (bcp_ecdsa_in_flight was once emitted as BOTH a gauge and a
    collected counter — an invalid duplicate-TYPE exposition)."""
    from bitcoincashplus_tpu.ops import ecdsa_batch

    ecdsa_batch.STATS.in_flight = 1
    try:
        ecdsa_batch._IN_FLIGHT_G.set(1)
        text = tm.REGISTRY.prometheus_text()
    finally:
        ecdsa_batch.STATS.in_flight = 0
    type_lines = [ln for ln in text.splitlines() if ln.startswith("# TYPE ")]
    names = [ln.split()[2] for ln in type_lines]
    assert len(names) == len(set(names)), (
        f"duplicate families: {sorted(n for n in names if names.count(n) > 1)}")


def test_logjson_stamps_correlation_ids(tmp_path, restore_mode):
    """-logjson: records are JSON objects; one emitted inside an active
    span carries its correlation id (log <-> trace cross-reference)."""
    from bitcoincashplus_tpu.util.log import log_init, log_printf

    node = _mk_node(tmp_path / "lj", logjson=1, telemetry="trace")
    try:
        logfile = tmp_path / "lj" / "regtest" / "debug.log"
        with tm.span("logtest") as sp:
            log_printf("correlated hello")
        lines = [json.loads(ln) for ln in
                 logfile.read_text().splitlines() if ln.strip()]
        hits = [rec for rec in lines if rec.get("msg") == "correlated hello"]
        assert hits and hits[0]["corr"] == sp.corr
        assert all("ts" in rec and "msg" in rec for rec in lines)
    finally:
        node.close()
        # node.close() logged through the json logger; restore the plain
        # text logger for whatever runs next in this process
        log_init()
