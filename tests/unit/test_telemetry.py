"""Unified telemetry (util/telemetry, ISSUE 6): histogram bucket/quantile
math against a NumPy reference, registry thread-safety under concurrent
writers, a Prometheus exposition golden test, span nesting + correlation
across the supervised-dispatch thread boundary, and gettpuinfo parity
(every pre-existing key still present and equal to its source).

Marker: ``telemetry`` — conftest orders these after the pipeline group
(the mode/registry fixtures are process-global) and before functional.
"""

from __future__ import annotations

import json
import threading
import types

import numpy as np
import pytest

from bitcoincashplus_tpu.util import telemetry as tm

pytestmark = pytest.mark.telemetry


@pytest.fixture
def telemetry_mode():
    """Set the process-global telemetry mode for one test and restore the
    env-derived default (plus a clean span buffer) afterwards."""
    def set_(name):
        tm.set_mode(name)
        tm.TRACER.clear()
        return tm

    yield set_
    tm.reset()


# ---------------------------------------------------------------------------
# histogram math vs NumPy
# ---------------------------------------------------------------------------

BOUNDS = tuple(float(b) for b in np.geomspace(1e-4, 10.0, 40))


def test_histogram_bucket_counts_match_numpy():
    rng = np.random.default_rng(7)
    samples = rng.lognormal(mean=-4.0, sigma=2.0, size=5000)
    h = tm.Histogram(buckets=BOUNDS)
    for s in samples:
        h.observe(float(s))
    # NumPy reference: le-bucketing == searchsorted(side="left") counts
    idx = np.searchsorted(np.asarray(BOUNDS), samples, side="left")
    ref = np.bincount(idx, minlength=len(BOUNDS) + 1)
    assert h.counts == ref.tolist()
    assert h.count == len(samples)
    assert h.sum == pytest.approx(float(samples.sum()))


def _numpy_quantile_from_buckets(bounds, counts, q):
    """Independent reference for the interpolated histogram quantile:
    np.interp over the cumulative distribution at the bucket edges."""
    cum = np.cumsum(counts)
    total = cum[-1]
    rank = q * total
    i = int(np.searchsorted(cum, rank, side="left"))
    if i >= len(bounds):
        return bounds[-1]
    lo = bounds[i - 1] if i > 0 else 0.0
    in_bucket = counts[i]
    if in_bucket <= 0:
        return bounds[i]
    prev = cum[i] - in_bucket
    return float(np.interp(rank, [prev, cum[i]], [lo, bounds[i]]))


def test_histogram_quantiles_match_numpy_reference():
    rng = np.random.default_rng(11)
    samples = rng.lognormal(mean=-3.0, sigma=1.5, size=8000)
    h = tm.Histogram(buckets=BOUNDS)
    for s in samples:
        h.observe(float(s))
    for q in (0.5, 0.9, 0.99):
        ref = _numpy_quantile_from_buckets(BOUNDS, h.counts, q)
        assert h.quantile(q) == pytest.approx(ref, rel=1e-9)
    # and the estimate tracks the TRUE percentile within bucket
    # granularity (geomspace ratio ~1.34 -> allow 1.5x either way)
    for q in (0.5, 0.9, 0.99):
        true = float(np.percentile(samples, q * 100))
        est = h.quantile(q)
        assert true / 1.5 <= est <= true * 1.5, (q, est, true)


def test_histogram_edge_cases():
    h = tm.Histogram(buckets=(1.0, 2.0))
    assert h.quantile(0.5) == 0.0  # empty
    h.observe(100.0)  # overflow clamps to the last finite bound
    assert h.quantile(0.99) == 2.0
    with pytest.raises(ValueError):
        tm.Histogram(buckets=(2.0, 1.0))  # must ascend


# ---------------------------------------------------------------------------
# registry thread-safety
# ---------------------------------------------------------------------------

def test_registry_thread_safety_under_concurrent_writers():
    reg = tm.Registry()
    c = reg.counter("t_total", labels=("who",))
    g = reg.gauge("t_gauge")
    h = reg.histogram("t_hist", buckets=(0.5, 1.0))
    n_threads, n_iter = 8, 5000

    def work(i):
        child = c.labels(who=str(i % 2))
        for k in range(n_iter):
            child.inc()
            h.observe(0.25 if k % 2 else 0.75)
            g.set(k)

    threads = [threading.Thread(target=work, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    total = sum(child.value for _lbl, child in c.samples())
    assert total == n_threads * n_iter  # no lost increments
    assert h._children[()].count == n_threads * n_iter
    counts = h._children[()].counts
    assert counts[0] == counts[1] == n_threads * n_iter // 2


# ---------------------------------------------------------------------------
# Prometheus exposition golden
# ---------------------------------------------------------------------------

def test_prometheus_exposition_golden():
    reg = tm.Registry()
    c = reg.counter("bcp_test_ops_total", "Ops served", labels=("site",))
    c.labels(site="ecdsa").inc(3)
    c.labels(site="sha256").inc()
    reg.gauge("bcp_test_depth", "Current depth").set(4)
    h = reg.histogram("bcp_test_latency_seconds", "Latency",
                      buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 0.5, 2.0):
        h.observe(v)
    reg.register_collector("extra", lambda: [{
        "name": "bcp_test_collected", "type": "gauge", "help": "From afar",
        "samples": [({"peer": "1"}, 7.5)],
    }])
    expected = (
        "# HELP bcp_test_ops_total Ops served\n"
        "# TYPE bcp_test_ops_total counter\n"
        'bcp_test_ops_total{site="ecdsa"} 3\n'
        'bcp_test_ops_total{site="sha256"} 1\n'
        "# HELP bcp_test_depth Current depth\n"
        "# TYPE bcp_test_depth gauge\n"
        "bcp_test_depth 4\n"
        "# HELP bcp_test_latency_seconds Latency\n"
        "# TYPE bcp_test_latency_seconds histogram\n"
        'bcp_test_latency_seconds_bucket{le="0.1"} 1\n'
        'bcp_test_latency_seconds_bucket{le="1"} 3\n'
        'bcp_test_latency_seconds_bucket{le="+Inf"} 4\n'
        "bcp_test_latency_seconds_sum 3.05\n"
        "bcp_test_latency_seconds_count 4\n"
        "# HELP bcp_test_collected From afar\n"
        "# TYPE bcp_test_collected gauge\n"
        'bcp_test_collected{peer="1"} 7.5\n'
    )
    assert reg.prometheus_text() == expected


def test_snapshot_carries_quantiles_and_buckets():
    reg = tm.Registry()
    h = reg.histogram("bcp_test_h", buckets=(1.0, 2.0))
    h.observe(0.5)
    h.observe(1.5)
    snap = reg.snapshot()
    val = snap["bcp_test_h"]["values"][0]
    assert val["count"] == 2
    assert val["buckets"] == {"1.0": 1, "2.0": 1, "+Inf": 0}
    assert set(val) >= {"p50", "p90", "p99"}


def test_registry_rejects_type_redefinition():
    reg = tm.Registry()
    reg.counter("bcp_test_x")
    with pytest.raises(ValueError):
        reg.gauge("bcp_test_x")


def test_off_mode_freezes_metrics(telemetry_mode):
    telemetry_mode("off")
    reg = tm.Registry()
    c = reg.counter("bcp_test_frozen")
    c.inc(5)
    assert c._children[()].value == 0  # off = no-op record calls
    tm.set_mode("counters")
    c.inc(5)
    assert c._children[()].value == 5


# ---------------------------------------------------------------------------
# spans: nesting + correlation across the supervised-dispatch boundary
# ---------------------------------------------------------------------------

def test_span_nesting_and_parentage(telemetry_mode):
    telemetry_mode("trace")
    with tm.span("outer", k=1):
        with tm.span("inner"):
            pass
    evs = {ev["name"]: ev for ev in tm.TRACER.events()}
    outer, inner = evs["outer"], evs["inner"]
    assert inner["args"]["corr"] == outer["args"]["corr"]
    assert inner["args"]["parent"] == outer["args"]["span_id"]
    assert "parent" not in outer["args"]  # top-level span
    assert outer["args"]["k"] == 1
    assert outer["dur"] >= inner["dur"]


def test_span_off_mode_is_noop(telemetry_mode):
    telemetry_mode("counters")
    with tm.span("nothing"):
        assert tm.trace_context() is None
    assert tm.TRACER.events() == []


def test_span_correlation_across_thread_handoff(telemetry_mode):
    telemetry_mode("trace")
    ctx = {}
    with tm.span("dispatcher") as sp:
        ctx["t"] = tm.trace_context()
        assert ctx["t"] == (sp.corr, sp.span_id)

    def worker():
        with tm.span("settler", parent=ctx["t"]):
            pass

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    evs = {ev["name"]: ev for ev in tm.TRACER.events()}
    disp, settle = evs["dispatcher"], evs["settler"]
    assert settle["args"]["corr"] == disp["args"]["corr"]
    assert settle["args"]["parent"] == disp["args"]["span_id"]
    assert settle["tid"] != disp["tid"]  # genuinely crossed threads


def test_supervised_enqueue_settle_correlates_across_threads(
        telemetry_mode):
    """The real boundary: supervised_enqueue captures the enqueue span's
    context into the handle; result() — on ANOTHER thread — opens its
    settle span with that parent. dumptrace stitches them back together."""
    from bitcoincashplus_tpu.ops import dispatch

    telemetry_mode("trace")
    dispatch.reset()
    try:
        handle = dispatch.supervised_enqueue(
            "teletest", lambda: (lambda: 42), cpu_fn=lambda: -1)
        out = {}
        t = threading.Thread(target=lambda: out.update(
            r=handle.result()))
        t.start()
        t.join()
        assert out["r"] == 42 and handle.used_device
        evs = {ev["name"]: ev for ev in tm.TRACER.events()}
        enq, settle = evs["dispatch.enqueue"], evs["dispatch.settle"]
        assert enq["args"]["site"] == settle["args"]["site"] == "teletest"
        assert settle["args"]["corr"] == enq["args"]["corr"]
        assert settle["args"]["parent"] == enq["args"]["span_id"]
        assert settle["tid"] != enq["tid"]
    finally:
        dispatch.reset()


def test_ring_buffer_bounds_and_chrome_shape(telemetry_mode):
    telemetry_mode("trace")
    tracer = tm.Tracer(capacity=8)
    for i in range(20):
        with tracer.span("s", i=i):
            pass
    st = tracer.stats()
    assert st["buffered"] == 8 and st["recorded"] == 20
    assert st["dropped"] == 12
    trace = tracer.chrome_trace()
    assert set(trace) >= {"traceEvents", "displayTimeUnit"}
    for ev in trace["traceEvents"]:
        assert ev["ph"] == "X"
        assert {"name", "ts", "dur", "pid", "tid", "args"} <= set(ev)
    # the ring kept the NEWEST spans
    assert [ev["args"]["i"] for ev in trace["traceEvents"]] == \
        list(range(12, 20))


def test_dump_roundtrip(tmp_path, telemetry_mode):
    telemetry_mode("trace")
    with tm.span("a"):
        tm.instant("mark", why="test")
    path = str(tmp_path / "trace.json")
    n = tm.TRACER.dump(path)
    data = json.loads(open(path).read())
    assert len(data["traceEvents"]) == n == 2
    phases = {ev["ph"] for ev in data["traceEvents"]}
    assert phases == {"X", "i"}


# ---------------------------------------------------------------------------
# gettpuinfo parity + the new surfaces
# ---------------------------------------------------------------------------

# the PR-5 gettpuinfo shape: every key here must stay present and equal
# to its underlying source — telemetry turned the RPC into a superset,
# never a rewrite
PR5_KEYS = ("backend", "devices", "ecdsa", "batch", "breakers", "faults",
            "sigcache", "connectblock", "pipeline", "bip30", "net")


def _stub_node():
    from bitcoincashplus_tpu.validation.sigcache import SignatureCache

    return types.SimpleNamespace(
        backend="cpu",
        sigcache=SignatureCache(),
        chainstate=types.SimpleNamespace(
            bench={"blocks": 3, "verify_ms": 1.5},
            pipeline_snapshot=lambda: {"depth": 4, "in_horizon": 0},
            bip30_stats={"lookups": 9},
        ),
        connman=None,
    )


def test_gettpuinfo_parity_and_telemetry_section():
    from bitcoincashplus_tpu.ops import dispatch, ecdsa_batch
    from bitcoincashplus_tpu.rpc.control import gettpuinfo
    from bitcoincashplus_tpu.util import faults

    node = _stub_node()
    out = gettpuinfo(node, [])
    for key in PR5_KEYS:
        assert key in out, f"gettpuinfo lost pre-existing key {key!r}"
    # equality against the exact sources the PR-5 shape read
    assert out["batch"] == ecdsa_batch.STATS.snapshot()
    assert out["breakers"] == dispatch.snapshot()
    assert out["faults"] == faults.INJECTOR.snapshot()
    assert out["sigcache"] == node.sigcache.snapshot()
    assert out["ecdsa"] == ecdsa_batch.kernel_info()
    assert out["connectblock"] == node.chainstate.bench
    assert out["pipeline"] == node.chainstate.pipeline_snapshot()
    assert out["bip30"] == node.chainstate.bip30_stats
    assert out["net"] == {}
    # the PR-6 superset: telemetry mode, span stats, accept latency
    tel = out["telemetry"]
    assert tel["mode"] in tm.MODES
    assert {"recorded", "buffered", "dropped"} <= set(tel["spans"])
    assert {"p50_ms", "p90_ms", "p99_ms", "accepted",
            "rejected"} <= set(tel["accept_latency"])


def test_getmetrics_and_metrics_endpoint_cover_subsystems():
    """getmetrics + /metrics must expose families for dispatch, ecdsa,
    pipeline, sigcache, and mempool-accept (net joins once a connman
    registers its collector — test_connman_tick drives that); the node
    smoke test (test_telemetry_node) asserts the full set live."""
    from bitcoincashplus_tpu.rpc.control import getmetrics
    from bitcoincashplus_tpu.rpc.rest import handle_metrics

    snap = getmetrics(_stub_node(), [])
    names = set(snap)
    for prefix in ("bcp_dispatch_latency_seconds", "bcp_ecdsa_",
                   "bcp_pipeline_", "bcp_mempool_accept_seconds",
                   "bcp_packer_"):
        assert any(n.startswith(prefix) for n in names), prefix
    status, ctype, body = handle_metrics(None)
    assert status == 200 and ctype.startswith("text/plain")
    text = body.decode()
    assert "# TYPE bcp_dispatch_latency_seconds histogram" in text
    assert "# TYPE bcp_mempool_accept_seconds histogram" in text


def test_mempool_accept_latency_lands_in_histogram(telemetry_mode):
    """The serving-path p50/p99 plumbing: a rejected accept still records
    an observation (labeled rejected), an accepted one feeds the p50/p99
    estimate gettpuinfo reports."""
    from bitcoincashplus_tpu.mempool import accept as accept_mod

    telemetry_mode("counters")
    acc = accept_mod._ACCEPT_H.labels(result="accepted")
    rej = accept_mod._ACCEPT_H.labels(result="rejected")
    base_acc, base_rej = acc.count, rej.count

    class _BoomPool(dict):
        map_deltas = {}

        def __contains__(self, txid):
            return False

        def get_spender(self, op):
            return None

    class _Tip:
        height = 100

        @staticmethod
        def get_median_time_past():
            return 1_600_000_000

    class _Chainstate:
        class params:
            require_standard = False

        @staticmethod
        def tip():
            return _Tip

    from bitcoincashplus_tpu.consensus.tx import CTransaction
    from bitcoincashplus_tpu.mempool.mempool import MempoolError

    bad = CTransaction(vin=(), vout=())  # fails check_transaction: empty
    with pytest.raises(MempoolError):
        accept_mod.accept_to_memory_pool(_BoomPool(), _Chainstate, bad)
    assert rej.count == base_rej + 1
    assert acc.count == base_acc
    q = accept_mod.accept_latency_quantiles()
    assert q["rejected"] == rej.count
