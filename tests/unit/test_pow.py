"""PoW rule tests (reference model: src/test/pow_tests.cpp — retarget math on
synthetic header chains; compact-bits codec edges from arith_uint256 tests)."""

from dataclasses import dataclass, field

import pytest
pytest.importorskip("hypothesis")  # property tests need the optional test extra
from hypothesis import given
from hypothesis import strategies as st

from bitcoincashplus_tpu.consensus.params import main_params, regtest_params
from bitcoincashplus_tpu.consensus.pow import (
    calculate_next_work_required,
    check_proof_of_work,
    compact_to_target,
    get_block_proof,
    get_next_work_required,
    target_to_compact,
)


@dataclass
class FakeIndex:
    """Minimal CBlockIndex stand-in for retarget tests."""

    height: int
    time: int
    bits: int
    prev: "FakeIndex | None" = None
    chain_work: int = 0

    def get_ancestor(self, height: int):
        idx = self
        while idx is not None and idx.height > height:
            idx = idx.prev
        return idx


class TestCompactBits:
    @pytest.mark.parametrize(
        "bits,target,bad",
        [
            (0, 0, False),
            (0x00123456, 0, False),
            (0x01003456, 0, False),
            (0x01123456, 0x12, False),
            (0x02008000, 0x80, False),
            (0x05009234, 0x92340000, False),
            (0x04923456, 0, True),  # negative
            (0x1D00FFFF, 0xFFFF << 208, False),
            (0xFF123456, 0, True),  # overflow
        ],
    )
    def test_decode_vectors(self, bits, target, bad):
        # vectors from upstream bignum_tests/arith_uint256 SetCompact table
        t, flag = compact_to_target(bits)
        if not bad:
            assert t == target
        assert flag == bad

    @given(st.integers(min_value=1, max_value=(1 << 255) - 1))
    def test_roundtrip_via_compact(self, target):
        bits = target_to_compact(target)
        t2, bad = compact_to_target(bits)
        assert not bad
        # compact encoding keeps 23-24 bits of mantissa; re-encoding is stable
        assert target_to_compact(t2) == bits

    def test_mainnet_powlimit_encoding(self):
        assert target_to_compact(main_params().consensus.pow_limit) == 0x1D00FFFF


class TestCheckProofOfWork:
    def test_genesis_passes(self):
        p = main_params()
        assert check_proof_of_work(p.genesis.get_hash(), p.genesis.header.bits, p.consensus)

    def test_wrong_nonce_fails(self):
        p = main_params()
        hdr = p.genesis.header.with_nonce(p.genesis.header.nonce + 1)
        assert not check_proof_of_work(hdr.get_hash(), hdr.bits, p.consensus)

    def test_target_above_powlimit_rejected(self):
        p = main_params()
        easy_bits = target_to_compact(p.consensus.pow_limit * 2)
        assert not check_proof_of_work(b"\x00" * 32, easy_bits, p.consensus)

    def test_zero_and_negative_rejected(self):
        p = main_params()
        assert not check_proof_of_work(b"\x00" * 32, 0x01003456, p.consensus)
        assert not check_proof_of_work(b"\x00" * 32, 0x04923456, p.consensus)


class TestRetarget:
    """Mirrors pow_tests.cpp GetBlockProofEquivalentTime-family cases."""

    def _prev(self, height, time, bits):
        return FakeIndex(height=height, time=time, bits=bits)

    def test_exact_two_weeks_no_change(self):
        p = main_params().consensus
        prev = self._prev(2015, 1261130161, 0x1D00FFFF)
        # pow_tests: nLastRetargetTime chosen so actual == target timespan
        first_time = prev.time - p.pow_target_timespan
        assert calculate_next_work_required(prev, first_time, p) == 0x1D00FFFF

    def test_clamp_lower(self):
        """Actual timespan < timespan/4 clamps to /4 (difficulty up max 4x)."""
        p = main_params().consensus
        prev = self._prev(2015, 1262152739, 0x1D00FFFF)
        first_time = prev.time  # zero elapsed
        bits = calculate_next_work_required(prev, first_time, p)
        t_new, _ = compact_to_target(bits)
        t_old, _ = compact_to_target(0x1D00FFFF)
        assert t_new == target_to_compact_roundtrip(t_old // 4)

    def test_clamp_upper(self):
        """Actual timespan > 4*target clamps (difficulty down max 4x), bounded
        by pow_limit."""
        p = main_params().consensus
        prev = self._prev(2015, 1262152739, 0x1D00FFFF)
        first_time = prev.time - 100 * p.pow_target_timespan
        bits = calculate_next_work_required(prev, first_time, p)
        # 0x1D00FFFF * 4 > pow_limit → clamp to pow_limit, whose compact
        # encoding is 0x1D00FFFF (matches pow_tests.cpp expectations)
        assert bits == 0x1D00FFFF

    def test_regtest_no_retargeting(self):
        p = regtest_params().consensus
        prev = self._prev(2015, 1_000_000, 0x207FFFFF)
        assert get_next_work_required(prev, 2_000_000, p) == 0x207FFFFF

    def test_regtest_min_difficulty_rule_still_applies(self):
        """fPowNoRetargeting must not bypass the min-difficulty special case:
        tip at non-limit bits + >2x spacing gap → pow-limit bits (reference
        keeps the no-retarget check inside CalculateNextWorkRequired only)."""
        p = regtest_params().consensus
        prev = self._prev(10, 1_000_000, 0x207FFFFE)
        bits = get_next_work_required(prev, 1_000_000 + p.pow_target_spacing * 2 + 1, p)
        assert bits == 0x207FFFFF

    def test_genesis_gets_powlimit(self):
        p = main_params().consensus
        assert get_next_work_required(None, 0, p) == 0x1D00FFFF

    def test_mid_interval_keeps_bits(self):
        p = main_params().consensus
        chain = FakeIndex(height=0, time=0, bits=0x1D00FFFF)
        for h in range(1, 100):
            chain = FakeIndex(height=h, time=h * 600, bits=0x1D00FFFF, prev=chain)
        assert get_next_work_required(chain, 100 * 600, p) == 0x1D00FFFF

    def test_full_interval_retarget_fires(self):
        """Build 2016 blocks at half spacing: difficulty must increase 2x."""
        p = main_params().consensus
        chain = FakeIndex(height=0, time=0, bits=0x1C0FFFFF)
        for h in range(1, 2016):
            chain = FakeIndex(height=h, time=h * 300, bits=0x1C0FFFFF, prev=chain)
        bits = get_next_work_required(chain, 2016 * 300, p)
        t_old, _ = compact_to_target(0x1C0FFFFF)
        # Exact reference arithmetic: timespan spans 2015 gaps of 300s
        expected = target_to_compact(t_old * (2015 * 300) // p.pow_target_timespan)
        assert bits == expected
        t_new, _ = compact_to_target(bits)
        assert t_new < t_old  # difficulty increased


def target_to_compact_roundtrip(target: int) -> int:
    t, _ = compact_to_target(target_to_compact(target))
    return t


class TestBlockProof:
    def test_proof_monotonic(self):
        hard, _ = compact_to_target(0x1C0FFFFF)
        assert get_block_proof(0x1C0FFFFF) > get_block_proof(0x1D00FFFF)

    def test_genesis_proof(self):
        # 0x1D00FFFF → proof = 2^32 / (0xFFFF0000... + 1) ≈ 2^32 / 2^224·k
        assert get_block_proof(0x1D00FFFF) == 0x100010001
