"""Device-resident mining loop + chunk-2 midstate hoisting (ISSUE 10).

Covers: hoisted-vs-unhoisted bit-identity against the CPU oracle, the
2^32 tile-accounting clamp, resident-loop rollover/template-refresh
semantics, the devicewatch retrace sentinel staying quiet across buffer
swaps, the regtest-CPU scalar fast path, knob validation, and the
bcp_mining_* telemetry families. ``mining`` marker: conftest orders this
suite after devprof and before serving.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from bitcoincashplus_tpu.crypto.hashes import (
    chunk2_round_state,
    header_midstate,
    sha256d,
)
from bitcoincashplus_tpu.ops import miner
from bitcoincashplus_tpu.ops import sha256 as gen_sha
from bitcoincashplus_tpu.ops.sha256 import bytes_to_words_np
from bitcoincashplus_tpu.ops.sha256_sweep import (
    hoist_template,
    sweep_digest_hoisted,
    sweep_h7_hoisted,
    sweep_header_fast,
)
from bitcoincashplus_tpu.mining.resident import ResidentSweep

pytestmark = pytest.mark.mining

EASY = 0x7FFFFF << (8 * 29)  # regtest-grade target


def _parts(header80):
    mid = np.array(header_midstate(header80), dtype=np.uint32)
    tail = bytes_to_words_np(np.frombuffer(header80[64:76], dtype=np.uint8))
    return list(mid), list(tail)


def _oracle_digest_words(header80, nonce):
    dig = sha256d(header80[:76] + int(nonce).to_bytes(4, "little"))
    return [int.from_bytes(dig[4 * j:4 * j + 4], "big") for j in range(8)]


def _first_hit_from(header80, target, start, budget):
    """Scalar oracle over the resident sweep order (rollover wrap)."""
    for i in range(budget):
        n = (start + i) & 0xFFFFFFFF
        hdr = header80[:76] + n.to_bytes(4, "little")
        if int.from_bytes(sha256d(hdr), "little") <= target:
            return n
    return None


# ---------------------------------------------------------------------------
# Chunk-2 hoist correctness
# ---------------------------------------------------------------------------

def test_hoist_state_matches_cpu_oracle():
    """The hoisted early-round state (chunk-2 rounds 0..2) is pinned
    bit-exactly against the pure-Python oracle."""
    rng = np.random.default_rng(11)
    for _ in range(8):
        header = rng.integers(0, 256, size=80, dtype=np.uint8).tobytes()
        mid, tail = _parts(header)
        pre = hoist_template(mid, tail)
        got = tuple(int(x) for x in pre["st3"])
        exp = chunk2_round_state(header_midstate(header), header[64:76])
        assert got == exp


def test_hoisted_digests_bit_identical():
    """Randomized 80-byte headers: hoisted full-digest and h7 kernels are
    bit-identical to BOTH the hashlib oracle and the unhoisted generic
    sweep digest (ops/sha256.header_sweep_digest)."""
    rng = np.random.default_rng(12)
    with jax.disable_jit():
        for _ in range(4):
            header = rng.integers(0, 256, size=80, dtype=np.uint8).tobytes()
            mid, tail = _parts(header)
            nonces = rng.integers(0, 2**32, size=32, dtype=np.uint32)
            pre = hoist_template(mid, tail)
            h8 = [np.asarray(x)
                  for x in sweep_digest_hoisted(pre, jnp.asarray(nonces))]
            h7 = np.asarray(sweep_h7_hoisted(pre, jnp.asarray(nonces)))
            un8 = [np.asarray(x) for x in gen_sha.header_sweep_digest(
                [np.uint32(m) for m in mid], [np.uint32(t) for t in tail],
                jnp.asarray(nonces))]
            for i, n in enumerate(nonces):
                exp = _oracle_digest_words(header, n)
                assert [int(h8[j][i]) for j in range(8)] == exp
                assert [int(un8[j][i]) for j in range(8)] == exp
                assert int(h7[i]) == exp[7]


def test_hoisted_hits_identical_nonces():
    """Hoisted sweeps find hits at the same nonces as the scalar CPU
    reference loop (sweep_header_cpu) — generic and h7 paths."""
    header = b"\xab" * 80
    with jax.disable_jit():
        n_cpu, _ = miner.sweep_header_cpu(header, EASY, max_nonces=1 << 10)
        n_gen, _ = miner.sweep_header(header, EASY, max_nonces=1 << 10,
                                      tile=1 << 7)
        n_fast, _ = sweep_header_fast(header, EASY, max_nonces=1 << 10,
                                      tile=1 << 7)
    assert n_cpu is not None
    assert n_gen == n_cpu
    assert n_fast == n_cpu


# ---------------------------------------------------------------------------
# Satellite: 2^32 boundary tile clamp
# ---------------------------------------------------------------------------

def test_boundary_tile_clamp_math():
    t = 1 << 16
    # plenty of space: clamp is the max_nonces ceiling
    assert miner._boundary_tiles(0, 1 << 20, t) == (1 << 20) // t
    # near the top: space wins over max_nonces
    start = (1 << 32) - 3 * t
    assert miner._boundary_tiles(start, 1 << 32, t) == 3
    # unaligned start: ceil of the remaining space
    start = (1 << 32) - 3 * t - 7
    assert miner._boundary_tiles(start, 1 << 32, t) == 4


def test_sweep_header_clamps_at_boundary():
    """A sweep starting near the top of the nonce space must stop at
    2^32 — no wrap into (re-hashing of) low nonces, and the attempted-
    hash count is bounded by the remaining space."""
    header = b"\xab" * 80
    tile = 1 << 7
    start = (1 << 32) - 4 * tile
    space = (1 << 32) - start
    with jax.disable_jit():
        # impossible target: full clamped sweep, honest accounting
        nonce, hashes = miner.sweep_header(header, 0, start_nonce=start,
                                           max_nonces=1 << 32, tile=tile)
        assert nonce is None
        assert hashes <= space
        # the fast path clamps identically
        nonce_f, hashes_f = sweep_header_fast(header, 0, start_nonce=start,
                                              max_nonces=1 << 32, tile=tile)
    assert nonce_f is None
    assert hashes_f <= space
    # a hit that exists only BELOW the start (i.e. past the wrap) must
    # NOT be found by the clamped per-dispatch sweep
    low_hit = _first_hit_from(header, EASY, 0, 1 << 10)
    assert low_hit is not None and low_hit < start
    with jax.disable_jit():
        nonce, _ = miner.sweep_header(header, EASY, start_nonce=start,
                                      max_nonces=1 << 32, tile=tile)
    if nonce is not None:  # a hit inside [start, 2^32) is legitimate
        assert nonce >= start


# ---------------------------------------------------------------------------
# Resident loop semantics
# ---------------------------------------------------------------------------

@pytest.fixture
def resident():
    rs = ResidentSweep(tile=1 << 9, seg_tiles=2, inflight=2, kernel="exact")
    yield rs
    rs.close()


def test_resident_matches_cpu_oracle(resident):
    header = b"\xab" * 80
    n, hashes = resident.sweep(header, EASY, max_nonces=1 << 13)
    n_cpu, _ = miner.sweep_header_cpu(header, EASY, max_nonces=1 << 13)
    assert n == n_cpu and hashes >= 1


def test_resident_h7_matches_cpu_oracle():
    rs = ResidentSweep(tile=1 << 9, seg_tiles=2, inflight=2, kernel="h7")
    try:
        header = b"\xcd" * 80
        n, _ = rs.sweep(header, EASY, max_nonces=1 << 13)
        n_cpu, _ = miner.sweep_header_cpu(header, EASY, max_nonces=1 << 13)
        assert n == n_cpu
    finally:
        rs.close()


def test_resident_rollover_wrap_hit(resident):
    """A sweep crossing 2^32 rolls over on-loop and finds the first hit
    in wrap order — identical to the scalar oracle's uint32 semantics."""
    header = b"\xab" * 80
    start = (1 << 32) - (1 << 10)
    n, _ = resident.sweep(header, EASY, start_nonce=start,
                          max_nonces=1 << 13)
    assert n == _first_hit_from(header, EASY, start, 1 << 13)
    assert resident.passes >= 1
    assert resident.snapshot()["rollover_passes"] >= 1


def test_template_refresh_mid_sweep(resident):
    """In-flight segments of the OLD template are discarded at a refresh
    and the hit comes from the NEW template (the buffer-swap path)."""
    header_a, header_b = b"\x11" * 80, b"\x22" * 80
    resident.set_template(header_a, 0)          # impossible target
    resident._pump(1 << 12)                     # segments in flight for A
    assert len(resident._segments) > 0
    swaps_before = resident.buffer_swaps
    n, _ = resident.sweep(header_b, EASY, max_nonces=1 << 13)
    n_cpu, _ = miner.sweep_header_cpu(header_b, EASY, max_nonces=1 << 13)
    assert n == n_cpu                           # hit from the NEW template
    assert resident.buffer_swaps == swaps_before + 1
    assert resident.segments_discarded > 0


def test_resident_fifo_poll_surface(resident):
    """advance()/take_hits(): the host polls a bounded FIFO instead of
    blocking on (found, nonce, tiles)."""
    resident.set_template(b"\x33" * 80, 1 << 250)  # several hits expected
    parked = resident.advance(1 << 13)
    assert parked >= 1
    assert resident.snapshot()["fifo_depth"] == parked
    hits = resident.take_hits()
    assert len(hits) == parked
    gen = resident.generation
    for h in hits:
        assert h["generation"] == gen
        hdr = b"\x33" * 76 + h["nonce"].to_bytes(4, "little")
        assert int.from_bytes(sha256d(hdr), "little") <= (1 << 250)
    assert resident.snapshot()["fifo_depth"] == 0


def test_advance_resumes_past_false_positive():
    """advance() must not drop the unsearched remainder of a segment
    after an h7 false positive: the cursor already moved past the whole
    segment at dispatch time, so the loop resumes synchronously (as
    sweep() does) and a REAL hit later in the same segment is still
    parked in the FIFO."""
    header = b"\x66" * 80
    target = 1 << 250
    real = [n for n in range(1 << 11)
            if int.from_bytes(
                sha256d(header[:76] + n.to_bytes(4, "little")),
                "little") <= target]
    assert len(real) >= 2
    rs = ResidentSweep(tile=1 << 10, seg_tiles=2, inflight=1, kernel="h7")
    try:
        true_confirm = rs._confirm
        rejected = []

        def confirm(nonce):
            # simulate the ~2^-32 limb7 tie on the first real hit
            if nonce == real[0] and not rejected:
                rejected.append(nonce)
                return False
            return true_confirm(nonce)

        rs._confirm = confirm
        rs.set_template(header, target)
        parked = rs.advance(1 << 11)
        got = [h["nonce"] for h in rs.take_hits()]
        assert rejected, "the planted false positive never fired"
        assert rs.false_positives >= 1
        assert real[0] not in got
        assert real[1] in got   # resumed remainder found the next hit
        assert parked == len(got)
    finally:
        rs.close()


def test_template_swaps_do_not_retrace():
    """>= 3 template refreshes re-dispatch the SAME compiled shape: the
    devicewatch retrace sentinel stays quiet and the shape count is flat
    (the swap is a buffer swap, not a recompile)."""
    from bitcoincashplus_tpu.mining.resident import PROGRAM
    from bitcoincashplus_tpu.util import devicewatch as dw

    rs = ResidentSweep(tile=1 << 9, seg_tiles=2, inflight=2, kernel="exact")
    try:
        rs.sweep(b"\x41" * 80, EASY, max_nonces=1 << 11)
        snap = dw.program(PROGRAM).snapshot()
        shapes_after_first = snap["shapes"]
        retraces_before = snap["retraces_unexpected"]
        for fill in (0x42, 0x43, 0x44):
            rs.sweep(bytes([fill]) * 80, EASY, max_nonces=1 << 11)
        snap = dw.program(PROGRAM).snapshot()
        assert rs.buffer_swaps >= 4
        assert snap["shapes"] == shapes_after_first
        assert snap["retraces_unexpected"] == retraces_before
    finally:
        rs.close()


def test_supervised_resident_degrades_to_scalar(fault_harness):
    """The resident loop rides the miner breaker: a dead device path
    degrades to the scalar host sweep with an identical hit."""
    from bitcoincashplus_tpu.ops import dispatch

    fault_harness("fail-always", ops="miner")
    rs = ResidentSweep(tile=1 << 9, seg_tiles=2, inflight=2, kernel="exact")
    try:
        sweep = dispatch.supervised_resident_sweep(rs)
        header = b"\xab" * 80
        n, _ = sweep(header, EASY, max_nonces=1 << 12)
        n_cpu, _ = miner.sweep_header_cpu(header, EASY, max_nonces=1 << 12)
        assert n == n_cpu
        assert dispatch.breaker("miner").fallback_calls >= 1
        assert rs.polls == 0  # the resident loop itself never ran
    finally:
        rs.close()


def test_mining_telemetry_families():
    """bcp_mining_* native families exist with correct TYPEs and count
    resident activity."""
    from bitcoincashplus_tpu.util import telemetry

    rs = ResidentSweep(tile=1 << 9, seg_tiles=2, inflight=2, kernel="exact")
    try:
        rs.sweep(b"\x55" * 80, EASY, max_nonces=1 << 12)
    finally:
        rs.close()
    fams = telemetry.REGISTRY.snapshot()
    assert fams["bcp_mining_tiles_swept_total"]["type"] == "counter"
    assert fams["bcp_mining_template_swaps_total"]["type"] == "counter"
    assert fams["bcp_mining_candidates_total"]["type"] == "counter"
    assert fams["bcp_mining_fifo_depth"]["type"] == "gauge"
    assert fams["bcp_mining_poll_seconds"]["type"] == "histogram"
    tiles = sum(v["value"]
                for v in fams["bcp_mining_tiles_swept_total"]["values"])
    assert tiles >= 1


# ---------------------------------------------------------------------------
# Node wiring: engine selection, knob validation, gettpuinfo section
# ---------------------------------------------------------------------------

def _mk_node(tmp_path, **args):
    from bitcoincashplus_tpu.node.config import Config
    from bitcoincashplus_tpu.node.node import Node

    cfg = Config()
    cfg.args["datadir"] = [str(tmp_path)]
    cfg.args["regtest"] = ["1"]
    for k, v in args.items():
        cfg.args[k] = [str(v)]
    return Node(config=cfg)


def test_regtest_cpu_keeps_scalar_fastpath(tmp_path):
    """Regtest CPU nodes keep the PR 7 ~1 ms/block scalar host sweep —
    the resident loop must NOT replace the trivial-target fast path."""
    node = _mk_node(tmp_path / "scalar")
    try:
        spk = bytes.fromhex("76a914") + b"\x11" * 20 + bytes.fromhex("88ac")
        hashes = node.generate_to_script(spk, 2)
        assert len(hashes) == 2
        assert node.sweep_engine == "scalar-host"
        assert node.resident_miner is None
        snap = node.mining_snapshot()
        assert snap["engine"] == "scalar-host"
        assert snap["resident"] is False
    finally:
        node.close()


def test_residentminer_force_engages_loop(tmp_path):
    node = _mk_node(tmp_path / "force", residentminer="force")
    try:
        spk = bytes.fromhex("76a914") + b"\x11" * 20 + bytes.fromhex("88ac")
        hashes = node.generate_to_script(spk, 2)
        assert len(hashes) == 2
        assert node.sweep_engine == "resident-exact"
        snap = node.mining_snapshot()
        assert snap["resident"] is True
        assert snap["template_generation"] >= 2   # one swap per extranonce
        assert snap["hits"] >= 2
        # the registry projection exports the state gauges
        from bitcoincashplus_tpu.util import telemetry

        fams = telemetry.REGISTRY.snapshot()
        assert fams["bcp_mining_state_tiles_swept"]["type"] == "gauge"
    finally:
        node.close()


def test_residentminer_knob_validation(tmp_path):
    from bitcoincashplus_tpu.node.config import ConfigError

    with pytest.raises(ConfigError):
        _mk_node(tmp_path / "bad", residentminer="sideways")
