"""Differential tests for the native C++ runtime library against the
Python reference implementations (hashlib, consensus/merkle.py, the wire
serializer). Skipped when no toolchain/library is available."""

import hashlib

import pytest

from bitcoincashplus_tpu import native
from bitcoincashplus_tpu.consensus.merkle import compute_merkle_root
from bitcoincashplus_tpu.consensus.params import main_params, regtest_params
from bitcoincashplus_tpu.consensus.tx import COutPoint, CTransaction, CTxIn, CTxOut

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable"
)


def _sha256d_py(b: bytes) -> bytes:
    return hashlib.sha256(hashlib.sha256(b).digest()).digest()


def test_sha256d_matches_hashlib():
    for msg in (b"", b"a", b"x" * 63, b"y" * 64, b"z" * 65, b"q" * 1000):
        assert native.sha256d(msg) == _sha256d_py(msg)


def test_hash_headers_genesis():
    genesis = main_params().genesis
    hdr = genesis.header.serialize()
    digests = native.hash_headers(hdr * 3)
    assert digests == [genesis.get_hash()] * 3


def test_scan_block_offsets_and_txids():
    blk = regtest_params().genesis
    raw = blk.serialize()
    scan = native.scan_block(raw)
    assert scan is not None
    assert scan.txids == [tx.txid for tx in blk.vtx]
    for tx, (s, e) in zip(blk.vtx, scan.offsets):
        assert raw[s:e] == tx.serialize()


def test_scan_block_multi_tx():
    from bitcoincashplus_tpu.consensus.block import CBlock

    genesis = regtest_params().genesis
    txs = [genesis.vtx[0]]
    for i in range(5):
        txs.append(CTransaction(
            vin=(CTxIn(COutPoint(bytes([i]) * 32, i), bytes([0x51] * (i * 7))),),
            vout=(CTxOut(1000 * i, b"\x51" * (i + 1)), CTxOut(5, b"")),
        ))
    blk = CBlock(genesis.header, tuple(txs))
    raw = blk.serialize()
    scan = native.scan_block(raw)
    assert scan is not None
    assert scan.txids == [tx.txid for tx in txs]


def test_scan_block_rejects_truncation():
    raw = regtest_params().genesis.serialize()
    for cut in (10, 79, 81, len(raw) - 1):
        assert native.scan_block(raw[:cut]) is None
    # oversized CompactSize tx count must not allocate or crash
    evil = raw[:80] + b"\xfe\xff\xff\xff\xff"
    assert native.scan_block(evil) is None


def test_merkle_root_matches_python():
    import numpy as np

    rng = np.random.default_rng(9)
    for n in (1, 2, 3, 7, 64, 101):
        txids = [rng.bytes(32) for _ in range(n)]
        root_py, mut_py = compute_merkle_root(txids)
        root_c, mut_c = native.merkle_root(txids)
        assert root_c == root_py and mut_c == mut_py
    # CVE-2012-2459 mutation: duplicated final pair flags on both
    txids = [rng.bytes(32) for _ in range(3)]
    mutated = txids + [txids[2]]
    _, mut_py = compute_merkle_root(mutated)
    _, mut_c = native.merkle_root(mutated)
    assert mut_c == mut_py


# ---- native scalar secp256k1 (native/secp256k1.cpp) ----

def _sig_corpus(n=25, seed=7):
    """Signed + mutated (pubkey, r, s, e) cases with oracle verdicts."""
    import random

    from bitcoincashplus_tpu.crypto import secp256k1 as o

    rng = random.Random(seed)
    cases = []
    for _ in range(n):
        sk = rng.randrange(1, o.N)
        e = rng.getrandbits(256)
        r, s = o.ecdsa_sign(sk, e)
        pub = o.point_mul(sk, o.G)
        cases += [
            (pub, r, s, e),           # valid
            (pub, r, o.N - s, e),     # high-s twin: still raw-ECDSA valid
            (pub, r, s, e + 1),       # wrong message
            (pub, (r + 1) % o.N or 1, s, e),
            (pub, r, 0, e),           # out-of-range scalars
            (pub, 0, s, e),
            (pub, r, o.N, e),
            (pub, r, s, 0),           # degenerate message hashes
            (pub, r, s, o.N),
            (pub, r, s, o.N - 1),
        ]
    return cases


def test_ecdsa_verify_differential():
    from bitcoincashplus_tpu.crypto import secp256k1 as o

    for pub, r, s, e in _sig_corpus():
        assert native.ecdsa_verify(pub, r, s, e) == o.ecdsa_verify(
            pub, r, s, e
        ), (hex(r), hex(s), hex(e))


def test_ecdsa_verify_batch_matches_scalar():
    from dataclasses import dataclass

    from bitcoincashplus_tpu.crypto import secp256k1 as o

    @dataclass
    class Rec:
        pubkey: tuple
        r: int
        s: int
        msg_hash: int

    cases = _sig_corpus(n=10, seed=11)
    recs = [Rec(p, r, s, e) for p, r, s, e in cases]
    got = native.ecdsa_verify_batch(recs)
    want = [o.ecdsa_verify(p, r, s, e) for p, r, s, e in cases]
    assert got == want
    # threaded path agrees with single-thread
    assert native.ecdsa_verify_batch(recs, nthreads=4) == want


def test_ecdsa_precompute_matches_python():
    import random
    from dataclasses import dataclass

    from bitcoincashplus_tpu.crypto import secp256k1 as o

    @dataclass
    class Rec:
        pubkey: tuple
        r: int
        s: int
        msg_hash: int

    rng = random.Random(3)
    recs = []
    for _ in range(16):
        sk = rng.randrange(1, o.N)
        e = rng.getrandbits(256)
        r, s = o.ecdsa_sign(sk, e)
        recs.append(Rec(o.point_mul(sk, o.G), r, s, e))
    u1_blob, u2_blob, ok = native.ecdsa_precompute(recs)
    assert all(ok)
    for i, rec in enumerate(recs):
        w = pow(rec.s, o.N - 2, o.N)
        u1 = rec.msg_hash % o.N * w % o.N
        u2 = rec.r * w % o.N
        assert int.from_bytes(u1_blob[32 * i:32 * i + 32], "big") == u1
        assert int.from_bytes(u2_blob[32 * i:32 * i + 32], "big") == u2
    # out-of-range records come back flagged, not garbage-accepted
    bad = [Rec(recs[0].pubkey, 0, recs[0].s, recs[0].msg_hash),
           Rec(recs[0].pubkey, recs[0].r, o.N, recs[0].msg_hash)]
    _, _, ok = native.ecdsa_precompute(bad)
    assert ok == [False, False]


def test_ecdsa_wraparound_acceptance():
    """The r vs r+n x-coordinate wraparound: chosen-key construction of a
    signature whose R.x lies in [n, p) so verification MUST accept via the
    (r+n)*Z^2 candidate (the same gate the TPU kernel enforces in-kernel),
    plus raw rejection of r >= n."""
    import ctypes
    import random

    from bitcoincashplus_tpu.crypto import secp256k1 as o

    # find an on-curve x in (n, p) — x = n itself is on-curve but gives
    # r = 0, which the range check rejects; density ~50% per candidate
    x = o.N + 1
    while True:
        y2 = (x * x * x + o.B) % o.P
        y = pow(y2, (o.P + 1) // 4, o.P)
        if y * y % o.P == y2:
            break
        x += 1
    R = (x, y)
    r = x - o.N           # in [1, p-n): the wraparound-aliased r
    assert 1 <= r < o.N
    s, e = 7, 1234567     # arbitrary; Q makes the equation hold
    # verify computes R' = (e/s)G + (r/s)Q; force R' == R:
    # Q = (s*R - e*G) * r^{-1}
    r_inv = pow(r, o.N - 2, o.N)
    Q = o.point_mul(
        r_inv, o.point_add(o.point_mul(s, R), o.point_mul(-e % o.N, o.G))
    )
    assert o.ecdsa_verify(Q, r, s, e), "oracle must accept via x_R = r + n"
    assert native.ecdsa_verify(Q, r, s, e), "native must accept via r + n"

    # and r in [n, 2^256) must be rejected by the C range check — drive the
    # raw entry point so the Python wrapper's mod-2^256 cannot alias it
    rng = random.Random(5)
    sk = rng.randrange(1, o.N)
    pub = o.point_mul(sk, o.G)
    e2 = rng.getrandbits(256)
    r2, s2 = o.ecdsa_sign(sk, e2)
    lib = native.load()
    pub_b = pub[0].to_bytes(32, "big") + pub[1].to_bytes(32, "big")
    e_b = (e2 % (1 << 256)).to_bytes(32, "big")
    for r_bad in [o.N] + ([o.N + r2] if o.N + r2 < (1 << 256) else []):
        rs_b = r_bad.to_bytes(32, "big") + s2.to_bytes(32, "big")
        assert lib.bcp_ecdsa_verify(
            ctypes.c_char_p(pub_b), ctypes.c_char_p(rs_b),
            ctypes.c_char_p(e_b)) == 0


def test_ecdsa_sign_matches_oracle():
    import random

    from bitcoincashplus_tpu.crypto import secp256k1 as o

    rng = random.Random(21)
    for _ in range(12):
        sk = rng.randrange(1, o.N)
        e = rng.getrandbits(256)
        assert native.ecdsa_sign(sk, e) == o.ecdsa_sign(sk, e)


def test_pubkey_parse_matches_oracle():
    import random

    from bitcoincashplus_tpu.crypto import secp256k1 as o

    rng = random.Random(22)
    for _ in range(20):
        pt = o.point_mul(rng.randrange(1, o.N), o.G)
        for comp in (True, False):
            data = o.pubkey_serialize(pt, comp)
            assert native.pubkey_parse(data) == o.pubkey_parse(data)
        x, y = pt
        for pref in (6, 7):  # hybrid: parity must match
            data = bytes([pref]) + x.to_bytes(32, "big") + y.to_bytes(32, "big")
            assert native.pubkey_parse(data) == o.pubkey_parse(data)
    for bad in (
        b"\x02" + o.P.to_bytes(32, "big"),       # x >= p
        b"\x02" + (5).to_bytes(32, "big"),       # x with no sqrt / on-curve?
        b"\x05" + b"\x00" * 32,                  # bad prefix
        b"\x02" + b"\x00" * 31,                  # bad length
        b"\x04" + o.P.to_bytes(32, "big") + b"\x01" * 32,
        b"",
    ):
        assert native.pubkey_parse(bad) == o.pubkey_parse(bad)
