"""Differential tests for the native C++ runtime library against the
Python reference implementations (hashlib, consensus/merkle.py, the wire
serializer). Skipped when no toolchain/library is available."""

import hashlib

import pytest

from bitcoincashplus_tpu import native
from bitcoincashplus_tpu.consensus.merkle import compute_merkle_root
from bitcoincashplus_tpu.consensus.params import main_params, regtest_params
from bitcoincashplus_tpu.consensus.tx import COutPoint, CTransaction, CTxIn, CTxOut

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable"
)


def _sha256d_py(b: bytes) -> bytes:
    return hashlib.sha256(hashlib.sha256(b).digest()).digest()


def test_sha256d_matches_hashlib():
    for msg in (b"", b"a", b"x" * 63, b"y" * 64, b"z" * 65, b"q" * 1000):
        assert native.sha256d(msg) == _sha256d_py(msg)


def test_hash_headers_genesis():
    genesis = main_params().genesis
    hdr = genesis.header.serialize()
    digests = native.hash_headers(hdr * 3)
    assert digests == [genesis.get_hash()] * 3


def test_scan_block_offsets_and_txids():
    blk = regtest_params().genesis
    raw = blk.serialize()
    scan = native.scan_block(raw)
    assert scan is not None
    assert scan.txids == [tx.txid for tx in blk.vtx]
    for tx, (s, e) in zip(blk.vtx, scan.offsets):
        assert raw[s:e] == tx.serialize()


def test_scan_block_multi_tx():
    from bitcoincashplus_tpu.consensus.block import CBlock

    genesis = regtest_params().genesis
    txs = [genesis.vtx[0]]
    for i in range(5):
        txs.append(CTransaction(
            vin=(CTxIn(COutPoint(bytes([i]) * 32, i), bytes([0x51] * (i * 7))),),
            vout=(CTxOut(1000 * i, b"\x51" * (i + 1)), CTxOut(5, b"")),
        ))
    blk = CBlock(genesis.header, tuple(txs))
    raw = blk.serialize()
    scan = native.scan_block(raw)
    assert scan is not None
    assert scan.txids == [tx.txid for tx in txs]


def test_scan_block_rejects_truncation():
    raw = regtest_params().genesis.serialize()
    for cut in (10, 79, 81, len(raw) - 1):
        assert native.scan_block(raw[:cut]) is None
    # oversized CompactSize tx count must not allocate or crash
    evil = raw[:80] + b"\xfe\xff\xff\xff\xff"
    assert native.scan_block(evil) is None


def test_merkle_root_matches_python():
    import numpy as np

    rng = np.random.default_rng(9)
    for n in (1, 2, 3, 7, 64, 101):
        txids = [rng.bytes(32) for _ in range(n)]
        root_py, mut_py = compute_merkle_root(txids)
        root_c, mut_c = native.merkle_root(txids)
        assert root_c == root_py and mut_c == mut_py
    # CVE-2012-2459 mutation: duplicated final pair flags on both
    txids = [rng.bytes(32) for _ in range(3)]
    mutated = txids + [txids[2]]
    _, mut_py = compute_merkle_root(mutated)
    _, mut_c = native.merkle_root(mutated)
    assert mut_c == mut_py
