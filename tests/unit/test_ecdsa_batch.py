"""ECDSA batch dispatch layer tests.

Fast tests exercise packing, bucketing, CPU fallback, and stats; the
device-kernel differential (single-device and 8-chip sharded) is marked
``slow`` — the 256-step verify loop costs minutes of XLA compile on the
CPU test backend (it compiles once per bucket on real hardware).
"""

import random

import numpy as np
import pytest

from bitcoincashplus_tpu.crypto import secp256k1 as oracle
from bitcoincashplus_tpu.ops import ecdsa_batch
from bitcoincashplus_tpu.ops.ecdsa_batch import (
    BUCKETS,
    _bucket_for,
    decompose_scalars,
    pack_records,
    verify_batch,
)
from bitcoincashplus_tpu.script.interpreter import SigCheckRecord

rng = random.Random(99)


def make_records(n, n_bad=0):
    recs, expected = [], []
    for i in range(n):
        d = rng.randrange(1, oracle.N)
        pub = oracle.point_mul(d, oracle.G)
        e = rng.randrange(1 << 256)
        r, s = oracle.ecdsa_sign(d, e)
        if i < n_bad:
            e ^= 1
        recs.append(SigCheckRecord(pub, r, s, e))
        expected.append(oracle.ecdsa_verify(pub, r, s, e))
    return recs, expected


def test_bucket_selection():
    assert _bucket_for(1) == BUCKETS[0]
    assert _bucket_for(BUCKETS[0]) == BUCKETS[0]
    assert _bucket_for(BUCKETS[0] + 1) == BUCKETS[1]
    assert _bucket_for(BUCKETS[-1] + 1) == 2 * BUCKETS[-1]


def test_decompose_scalars_matches_oracle_math():
    recs, _ = make_records(4)
    for rec, (u1, u2) in zip(recs, decompose_scalars(recs)):
        w = pow(rec.s, oracle.N - 2, oracle.N)
        assert u1 == rec.msg_hash * w % oracle.N
        assert u2 == rec.r * w % oracle.N
        # u1*G + u2*Q lands on x = r (the verify equation, oracle side)
        pt = oracle.point_add(
            oracle.point_mul(u1, oracle.G), oracle.point_mul(u2, rec.pubkey)
        )
        assert pt is not None and (pt[0] - rec.r) % oracle.N == 0


def test_pack_padding_is_poisoned():
    recs, _ = make_records(3)
    u1b, u2b, qx, qy, q_inf, r0, rn, wrap_ok = pack_records(recs, 8)
    assert q_inf.tolist() == [False] * 3 + [True] * 5
    assert not wrap_ok[3:].any()
    assert u1b.shape == (256, 8) and qx.shape[1] == 8
    # bit planes reconstruct the scalars
    u1, _ = decompose_scalars(recs[:1])[0]
    got = 0
    for i in range(256):
        got = (got << 1) | int(u1b[i, 0])
    assert got == u1


def test_cpu_fallback_small_batch():
    recs, expected = make_records(3, n_bad=1)
    before = ecdsa_batch.STATS.cpu_fallback_sigs
    ok = verify_batch(recs, backend="auto")  # 3 < CPU_FLOOR
    assert ok.tolist() == expected
    assert ecdsa_batch.STATS.cpu_fallback_sigs == before + 3


def test_empty_batch():
    assert verify_batch([]).shape == (0,)


def test_device_batch_minimal_differential():
    """ALWAYS runs (not slow-marked): the consensus-critical kernel path —
    one valid lane, one invalid lane, plus the wrap_ok gating — must be
    exercised by every default suite run. First fresh run pays the XLA
    compile; the persistent cache (conftest) amortizes it afterwards."""
    recs, expected = make_records(2, n_bad=1)
    ok = verify_batch(recs, backend="device")
    assert ok.tolist() == expected


def test_wrap_ok_gate_blocks_bogus_wraparound():
    """A signature whose r is replaced by r' = x_R - n (claiming the
    wraparound) must NOT verify unless r' + n < p actually held — the
    in-kernel wrap_ok mask (ADVICE r1 finding). Exercised via the CPU
    oracle equivalence: the kernel's gate mirrors
    secp256k1_ecdsa_sig_verify's r+n<p retry bound."""
    d = rng.randrange(1, oracle.N)
    pub = oracle.point_mul(d, oracle.G)
    e = rng.randrange(1 << 256)
    r, s = oracle.ecdsa_sign(d, e)
    recs = [SigCheckRecord(pub, r, s, e)]
    u1b, u2b, qx, qy, q_inf, r0, rn, wrap_ok = pack_records(recs, 2)
    assert wrap_ok[0] == (r + oracle.N < oracle.P)
    # the padded lane stays gated off
    assert not wrap_ok[1] and q_inf[1]


@pytest.mark.slow
def test_device_batch_differential():
    recs, expected = make_records(12, n_bad=4)
    ok = verify_batch(recs, backend="device")
    assert ok.tolist() == expected
    assert ecdsa_batch.STATS.dispatches >= 1


@pytest.mark.slow
def test_sharded_batch_differential():
    from bitcoincashplus_tpu.parallel.sig_shard import verify_batch_sharded

    recs, expected = make_records(16, n_bad=5)
    # pin w4: this is the w4 sharded differential (the GLV sharded one
    # lives in test_glv.py) — the default kernel would route to GLV
    ok = verify_batch_sharded(recs, 8, kernel="w4")
    assert ok.tolist() == expected


def test_pallas_bucket_ladder_boundaries():
    """The w4 bucket ladder: every bucket is >= n, a multiple of 1024 (the
    3D program's hard assert), and drawn from the bounded shape set."""
    from bitcoincashplus_tpu.ops.ecdsa_batch import _bucket_for

    allowed = {1024, 2048, 4096} | set(range(6144, 16385, 2048))
    for n in (129, 1000, 1024, 1025, 2048, 2049, 4096, 4097, 6144, 6145,
              10000, 16384):
        b = _bucket_for(n, pallas=True)
        assert b >= n and b % 1024 == 0, (n, b)
        assert b in allowed, (n, b)
    # beyond the split point: 16384-granular multiples
    for n in (16385, 30000, 32769):
        b = _bucket_for(n, pallas=True)
        assert b >= n and b % 16384 == 0, (n, b)
    # small batches keep the 2D kernel's buckets
    assert _bucket_for(128, pallas=True) == 128
    assert _bucket_for(8, pallas=True) == 32


def test_pallas_programming_errors_are_not_swallowed(monkeypatch):
    """A NameError/AttributeError inside the Pallas path is a BUG, not a
    toolchain limitation — it must propagate, not degrade silently to the
    XLA fallback (regression: a refactor deleted a module constant and
    every test stayed green on the fallback)."""
    import pytest

    from bitcoincashplus_tpu.ops import ecdsa_batch as eb

    with pytest.raises(NameError):
        eb._note_pallas_failure(NameError("name '_GONE' is not defined"))
    with pytest.raises(AttributeError):
        eb._note_pallas_failure(AttributeError("no attribute"))
    # toolchain-class failures still fall back (and latch when Mosaic)
    before = eb.STATS.pallas_fallbacks
    eb._note_pallas_failure(RuntimeError("remote compile service sneeze"))
    assert eb.STATS.pallas_fallbacks == before + 1
