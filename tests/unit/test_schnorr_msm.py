"""Schnorr signatures + Pippenger MSM batch verification (ISSUE 19).

Covers the whole vertical: the BCH 2019-05 Schnorr oracle
(crypto/secp256k1), the script interpreter's 64-byte-sig length
discrimination (CHECKSIG accepts, CHECKMULTISIG bans, the deferring
checker records algo), the sigcache scheme tag (a cached ECDSA TRUE can
never satisfy a Schnorr probe), and the MSM batch check in
ops/ecdsa_batch: MSM-vs-oracle differentials over a crafted-scalar
corpus, bad-sig-in-batch adversarial drills (forged sig at every
position, all-bad, deduped lane) asserted byte-identical against the
per-lane oracle with bisect depth metered, and the "ecdsa_msm" fault
site's BCP005 drill parity (fail-* proves the bisect-to-oracle fallback
rung, poison-output proves the canary gate catches a corrupted verdict
stream).

Every MSM dispatch in this file stays on the bucket-64 shape (batches of
at most 31 records) — the only _MSM_BUCKETS rung whose XLA compile is
unit-test-priced; the sharded differential (a separate compiled shape)
is slow-marked.
"""

import hashlib
import os

import numpy as np
import pytest

from bitcoincashplus_tpu.consensus.tx import COutPoint, CTransaction, CTxIn, CTxOut
from bitcoincashplus_tpu.crypto import secp256k1 as oracle
from bitcoincashplus_tpu.ops import ecdsa_batch as eb
from bitcoincashplus_tpu.script import script as S
from bitcoincashplus_tpu.script.interpreter import (
    SCRIPT_ENABLE_SIGHASH_FORKID,
    SCRIPT_VERIFY_NULLDUMMY,
    SCRIPT_VERIFY_NULLFAIL,
    SCRIPT_VERIFY_P2SH,
    SCRIPT_VERIFY_STRICTENC,
    DeferringSignatureChecker,
    ScriptError,
    SigCheckRecord,
    TransactionSignatureChecker,
    VerifyScript,
    is_schnorr_signature,
)
from bitcoincashplus_tpu.script.sighash import SIGHASH_ALL, SIGHASH_FORKID, signature_hash
from bitcoincashplus_tpu.validation.sigcache import SignatureCache
from bitcoincashplus_tpu.wallet.keys import CKey

pytestmark = pytest.mark.msm

FLAGS = (SCRIPT_VERIFY_P2SH | SCRIPT_VERIFY_STRICTENC
         | SCRIPT_VERIFY_NULLDUMMY | SCRIPT_VERIFY_NULLFAIL
         | SCRIPT_ENABLE_SIGHASH_FORKID)
HASHTYPE = SIGHASH_ALL | SIGHASH_FORKID


def _srecord(i: int, good: bool = True) -> SigCheckRecord:
    """A deterministic Schnorr sigcheck record (algo='schnorr')."""
    d = 0x3333 + i
    e = int.from_bytes(hashlib.sha256(b"msm%d" % i).digest(),
                       "big") % oracle.N
    r, s = oracle.schnorr_sign(d, e)
    pub = oracle.point_mul(d, oracle.G)
    return SigCheckRecord(pub, r, s, e if good else (e + 1) % oracle.N,
                          algo="schnorr")


def _oracle_verdicts(records) -> list:
    return [oracle.schnorr_verify(r.pubkey, r.r, r.s, r.msg_hash)
            for r in records]


@pytest.fixture
def msm_seed(monkeypatch):
    """Pin the MSM coefficient stream (deterministic drills)."""
    monkeypatch.setenv("BCP_MSM_SEED", "0x5eed")


# ----------------------------------------------------------------------
# Schnorr oracle (crypto/secp256k1)
# ----------------------------------------------------------------------


class TestSchnorrOracle:
    def test_sign_verify_roundtrip(self):
        for i in range(8):
            d = 0x1111 + i
            e = int.from_bytes(hashlib.sha256(b"rt%d" % i).digest(), "big")
            r, s = oracle.schnorr_sign(d, e)
            pub = oracle.point_mul(d, oracle.G)
            assert oracle.schnorr_verify(pub, r, s, e)
            assert not oracle.schnorr_verify(pub, r, s, e ^ 1)
            assert not oracle.schnorr_verify(pub, r, (s + 1) % oracle.N, e)

    def test_out_of_range_rejected(self):
        d, e = 0xABC, 0xDEF
        r, s = oracle.schnorr_sign(d, e)
        pub = oracle.point_mul(d, oracle.G)
        assert not oracle.schnorr_verify(pub, r + oracle.P, s, e)
        assert not oracle.schnorr_verify(pub, r, s + oracle.N, e)

    def test_lift_x_matches_verify_acceptance(self):
        """The host pre-reject is oracle-consistent: lift_x(r) exists
        exactly when r could ever be a valid Schnorr R.x (r^3+7 must be
        a quadratic residue), and the lifted point has jacobi(y) = 1 —
        the same root the verify equation demands."""
        d, e = 0x777, 0x888
        r, s = oracle.schnorr_sign(d, e)
        lift = oracle.schnorr_lift_x(r)
        assert lift is not None and lift[0] == r
        assert oracle.jacobi(lift[1]) == 1
        # an x whose cube+7 is a non-residue is unliftable AND can never
        # verify, whatever the other inputs
        x = 2
        while oracle.schnorr_lift_x(x) is not None:
            x += 1
        pub = oracle.point_mul(d, oracle.G)
        assert not oracle.schnorr_verify(pub, x, s, e)

    def test_deterministic_nonce(self):
        assert oracle.schnorr_sign(0x42, 0x99) == oracle.schnorr_sign(0x42, 0x99)


# ----------------------------------------------------------------------
# script interpreter: 64-byte-sig discrimination
# ----------------------------------------------------------------------


def _schnorr_spend(key: CKey, amount: int = 50_000):
    """A P2PKH spend signed with a 65-byte Schnorr signature."""
    spk = key.p2pkh_script()
    tx = CTransaction(
        vin=(CTxIn(COutPoint(b"\x11" * 32, 0)),),
        vout=(CTxOut(amount - 1000, bytes([S.OP_1])),),
    )
    ehash = signature_hash(spk, tx, 0, HASHTYPE, amount, enable_forkid=True)
    r, s = oracle.schnorr_sign(key.secret, int.from_bytes(ehash, "big"))
    sig65 = r.to_bytes(32, "big") + s.to_bytes(32, "big") + bytes([HASHTYPE])
    script_sig = S.push_data_raw(sig65) + S.push_data_raw(key.pubkey)
    tx = CTransaction(
        vin=(CTxIn(COutPoint(b"\x11" * 32, 0), script_sig=script_sig),),
        vout=tx.vout,
    )
    return tx, spk, sig65, amount


class TestInterpreterDiscrimination:
    def test_is_schnorr_signature_length_rule(self):
        assert is_schnorr_signature(b"\x00" * 65)
        assert not is_schnorr_signature(b"\x00" * 64)
        assert not is_schnorr_signature(b"\x00" * 71)  # DER-sized

    def test_checksig_accepts_schnorr(self):
        key = CKey(0xC0FFEE)
        tx, spk, _sig, amount = _schnorr_spend(key)
        checker = TransactionSignatureChecker(tx, 0, amount)
        VerifyScript(tx.vin[0].script_sig, spk, FLAGS, checker)

    def test_checksig_rejects_tampered_schnorr(self):
        key = CKey(0xC0FFEE)
        tx, spk, sig65, amount = _schnorr_spend(key)
        bad = bytearray(sig65)
        bad[40] ^= 1
        script_sig = S.push_data_raw(bytes(bad)) + S.push_data_raw(key.pubkey)
        checker = TransactionSignatureChecker(tx, 0, amount)
        with pytest.raises(ScriptError):
            VerifyScript(script_sig, spk, FLAGS, checker)

    def test_deferring_checker_records_algo(self):
        key = CKey(0xC0FFEE)
        tx, spk, _sig, amount = _schnorr_spend(key)
        records: list = []
        checker = DeferringSignatureChecker(tx, 0, amount, records)
        VerifyScript(tx.vin[0].script_sig, spk, FLAGS, checker)
        assert len(records) == 1 and records[0].algo == "schnorr"
        # the deferred record settles TRUE on the oracle
        assert _oracle_verdicts(records) == [True]

    def test_deferring_checker_range_gate(self):
        """Out-of-range Schnorr scalars fail fast, never deferred."""
        key = CKey(0xC0FFEE)
        tx, spk, sig65, amount = _schnorr_spend(key)
        r_big = oracle.P.to_bytes(32, "big")
        bad = r_big + sig65[32:64] + bytes([HASHTYPE])
        script_sig = S.push_data_raw(bad) + S.push_data_raw(key.pubkey)
        records: list = []
        checker = DeferringSignatureChecker(tx, 0, amount, records)
        with pytest.raises(ScriptError):
            VerifyScript(script_sig, spk, FLAGS, checker)
        assert records == []

    def test_checkmultisig_bans_schnorr_size(self):
        """BCH consensus: 65-byte sigs inside CHECKMULTISIG are
        sig-badlength, whatever their content."""
        keys = [CKey(7000 + i) for i in range(2)]
        redeem = S.multisig_script(2, [k.pubkey for k in keys])
        spk = S.p2sh_script_for_redeem(redeem)
        amount = 50_000
        tx = CTransaction(
            vin=(CTxIn(COutPoint(b"\x22" * 32, 0)),),
            vout=(CTxOut(amount - 1000, bytes([S.OP_1])),),
        )
        ehash = signature_hash(redeem, tx, 0, HASHTYPE, amount,
                               enable_forkid=True)
        e = int.from_bytes(ehash, "big")
        sigs = []
        for k in keys:
            r, s = oracle.schnorr_sign(k.secret, e)
            sigs.append(r.to_bytes(32, "big") + s.to_bytes(32, "big")
                        + bytes([HASHTYPE]))
        script_sig = (b"\x00" + b"".join(S.push_data_raw(x) for x in sigs)
                      + S.push_data_raw(redeem))
        tx = CTransaction(vin=(CTxIn(COutPoint(b"\x22" * 32, 0),
                                     script_sig=script_sig),),
                          vout=tx.vout)
        checker = TransactionSignatureChecker(tx, 0, amount)
        with pytest.raises(ScriptError, match="sig-badlength"):
            VerifyScript(script_sig, spk, FLAGS, checker)


# ----------------------------------------------------------------------
# sigcache scheme tag
# ----------------------------------------------------------------------


class TestSigcacheSchemeTag:
    def test_cross_scheme_keys_disjoint(self):
        """Crafted cross-scheme collision: the SAME (sighash, r, s,
        pubkey) byte material keyed under both schemes must produce
        distinct keys differing exactly in the trailing tag byte."""
        rec = _srecord(0)
        k_ecdsa = SignatureCache.entry_key(rec.msg_hash, rec.r, rec.s,
                                           rec.pubkey, "ecdsa")
        k_schnorr = SignatureCache.entry_key(rec.msg_hash, rec.r, rec.s,
                                             rec.pubkey, "schnorr")
        assert k_ecdsa != k_schnorr
        assert k_ecdsa[:-1] == k_schnorr[:-1]
        assert (k_ecdsa[-1], k_schnorr[-1]) == (0, 1)

    def test_cached_ecdsa_true_never_satisfies_schnorr_probe(self):
        rec = _srecord(1)
        cache = SignatureCache()
        cache.add(SignatureCache.entry_key(rec.msg_hash, rec.r, rec.s,
                                           rec.pubkey, "ecdsa"))
        assert not cache.contains(SignatureCache.entry_key(
            rec.msg_hash, rec.r, rec.s, rec.pubkey, "schnorr"))
        # and the reverse direction
        cache.add(SignatureCache.entry_key(rec.msg_hash, rec.r, rec.s,
                                           rec.pubkey, "schnorr"))
        assert cache.contains(SignatureCache.entry_key(
            rec.msg_hash, rec.r, rec.s, rec.pubkey, "schnorr"))

    def test_default_algo_is_ecdsa(self):
        rec = _srecord(2)
        assert SignatureCache.entry_key(
            rec.msg_hash, rec.r, rec.s, rec.pubkey
        ) == SignatureCache.entry_key(
            rec.msg_hash, rec.r, rec.s, rec.pubkey, "ecdsa")


# ----------------------------------------------------------------------
# kernel selection
# ----------------------------------------------------------------------


class TestKernelSelection:
    def test_msm_in_ladder_and_settable(self):
        assert "msm" in eb.ECDSA_KERNELS
        prev = eb.active_kernel()
        try:
            assert eb.set_kernel("msm") == "msm"
            assert eb.active_kernel() == "msm"
        finally:
            eb.set_kernel(prev)

    def test_unknown_kernel_rejected_at_startup(self):
        with pytest.raises(ValueError, match="ecdsakernel"):
            eb.set_kernel("pippenger")

    def test_msm_site_declared_explicit_only(self):
        # BCP005 parity: the fault site constant is the drill handle
        assert eb.MSM_SITE == "ecdsa_msm"
        from bitcoincashplus_tpu.util.faults import SITES

        assert eb.MSM_SITE not in SITES  # explicit opt-in only


# ----------------------------------------------------------------------
# MSM batch check vs the per-lane oracle (bucket-64 shapes only)
# ----------------------------------------------------------------------


def _msm_verify(records):
    h = eb.dispatch_batch(records, backend="device", kernel="msm")
    return h.result()


class TestMsmDifferential:
    def test_all_good_batch_accepts(self, msm_seed):
        recs = [_srecord(100 + i) for i in range(12)]
        before = eb.STATS.msm_batches_accepted
        got = _msm_verify(recs)
        assert got.tolist() == _oracle_verdicts(recs)
        assert got.all()
        assert eb.STATS.msm_batches_accepted > before

    def test_crafted_scalar_corpus(self, msm_seed):
        """Byte-identical accept/reject across the crafted corpus: valid
        sigs, same-R pairs (one signer, one message, two records), the
        unliftable-r pre-reject, boundary/out-of-range scalars, and a
        zero scalar — every lane must match the per-lane oracle."""
        good = _srecord(200)
        # same-R pair: identical record twice (deterministic nonce) plus
        # its forged twin sharing r
        twin = SigCheckRecord(good.pubkey, good.r, good.s, good.msg_hash,
                              algo="schnorr")
        forged_same_r = SigCheckRecord(good.pubkey, good.r,
                                       (good.s + 1) % oracle.N,
                                       good.msg_hash, algo="schnorr")
        x = 2
        while oracle.schnorr_lift_x(x) is not None:
            x += 1
        unliftable = SigCheckRecord(good.pubkey, x, good.s, good.msg_hash,
                                    algo="schnorr")
        corpus = [
            good, twin, forged_same_r, unliftable,
            SigCheckRecord(good.pubkey, 0, good.s, good.msg_hash,
                           algo="schnorr"),
            SigCheckRecord(good.pubkey, oracle.P - 1, good.s,
                           good.msg_hash, algo="schnorr"),
            SigCheckRecord(good.pubkey, good.r, 0, good.msg_hash,
                           algo="schnorr"),
            SigCheckRecord(good.pubkey, good.r, oracle.N - 1,
                           good.msg_hash, algo="schnorr"),
            _srecord(201), _srecord(202),
        ]
        got = _msm_verify(corpus)
        assert got.tolist() == _oracle_verdicts(corpus)

    def test_forged_sig_at_every_position(self, msm_seed):
        """One forged signature at every batch position: verdicts stay
        byte-identical to the oracle, the batch bisects (depth metered,
        O(log N) sub-batches), and the forged lane's False always comes
        off the per-lane oracle (reject side never trusts the device)."""
        n = 12
        base = [_srecord(300 + i) for i in range(n)]
        for pos in range(n):
            batch = list(base)
            batch[pos] = SigCheckRecord(
                base[pos].pubkey, base[pos].r,
                (base[pos].s + 1) % oracle.N, base[pos].msg_hash,
                algo="schnorr")
            b_bisects = eb.STATS.msm_bisects
            b_cpu = eb.STATS.schnorr_cpu_sigs
            got = _msm_verify(batch)
            ref = _oracle_verdicts(batch)
            assert got.tolist() == ref, f"forged at {pos}"
            assert not got[pos]
            assert got.sum() == n - 1
            assert eb.STATS.msm_bisects > b_bisects, \
                "a rejected batch must bisect, not settle on the device"
            assert eb.STATS.schnorr_cpu_sigs > b_cpu, \
                "the forged lane's verdict must come off the oracle"
        # 12 -> 6+6 with MSM_MIN_BATCH=8: every drill bottoms out at
        # depth 1
        assert eb.STATS.msm_bisect_depth_max >= 1

    def test_all_bad_batch(self, msm_seed):
        recs = [_srecord(400 + i, good=False) for i in range(10)]
        got = _msm_verify(recs)
        assert got.tolist() == _oracle_verdicts(recs)
        assert not got.any()

    def test_mixed_algo_batch_merges_in_order(self, msm_seed):
        """ECDSA lanes ride the existing ladder under -ecdsakernel=msm;
        verdicts re-merge in submission order."""
        def erec(i, good=True):
            d = 0x4444 + i
            e = int.from_bytes(hashlib.sha256(b"mx%d" % i).digest(),
                               "big") % oracle.N
            r, s = oracle.ecdsa_sign(d, e)
            return SigCheckRecord(oracle.point_mul(d, oracle.G), r, s,
                                  e if good else (e + 1) % oracle.N)

        batch = [erec(0), _srecord(500), erec(1, good=False),
                 _srecord(501, good=False), erec(2), _srecord(502)]
        got = eb.dispatch_batch(batch, backend="cpu").result()
        assert got.tolist() == [True, True, False, False, True, True]

    def test_empty_and_precheck_only_batches(self, msm_seed):
        assert eb.dispatch_batch([], backend="cpu").result().size == 0
        # every lane host-pre-rejected: no device work, all False
        bad = SigCheckRecord(_srecord(0).pubkey, 0, 0, 1, algo="schnorr")
        before = eb.STATS.msm_dispatches
        got = _msm_verify([bad] * 9)
        assert not got.any()
        assert eb.STATS.msm_dispatches == before


# ----------------------------------------------------------------------
# serving-path dedup (satellite 3: bad sig sharing a deduped lane)
# ----------------------------------------------------------------------


class TestServingDedup:
    def test_bad_sig_shared_deduped_lane(self, msm_seed):
        """Two submissions carrying the SAME bad Schnorr record (same
        dedup key) must both read the one verified lane's False — and a
        good record's True — byte-identical to the oracle."""
        from bitcoincashplus_tpu.serving import SigService

        prev = eb.active_kernel()
        eb.set_kernel("msm")
        svc = SigService(backend="device", lanes=10_000,
                         deadline_ms=60_000).start()
        try:
            good = _srecord(600)
            bad = _srecord(601, good=False)
            fut1 = svc.submit([good, bad])
            fut2 = svc.submit([bad])  # dedups onto fut1's in-flight lane
            assert svc.stats["dedup_hits"] == 1
            assert fut1.result().tolist() == [True, False]
            assert fut2.result().tolist() == [False]
        finally:
            svc.stop()
            eb.set_kernel(prev)


# ----------------------------------------------------------------------
# "ecdsa_msm" fault-site drills (BCP005 parity)
# ----------------------------------------------------------------------


class TestMsmFaultDrills:
    def test_fail_always_falls_back_to_oracle(self, fault_harness, msm_seed):
        """fail-* on ecdsa_msm proves the fallback rung: the batch check
        dies on every attempt, the dispatch exhausts its retries, and
        the whole batch settles on the per-lane oracle — verdicts
        byte-identical, fallback metered."""
        inj = fault_harness("fail-always", ops="ecdsa_msm")
        recs = [_srecord(700 + i) for i in range(3)] + [
            _srecord(710, good=False)]
        b_fb = eb.STATS.msm_fallback_sigs
        got = _msm_verify(recs)
        assert got.tolist() == _oracle_verdicts(recs)
        assert got.tolist() == [True, True, True, False]
        assert eb.STATS.msm_fallback_sigs == b_fb + len(recs)
        assert inj.injected.get("ecdsa_msm", 0) > 0

    def test_poison_output_caught_by_canary(self, fault_harness, msm_seed):
        """poison-output on ecdsa_msm flips EVERY batch verdict — canary
        batches included — so the canary gate must trip (a known-good
        batch reading reject / known-bad reading accept), poisoning must
        never reach a caller verdict, and the records settle on the
        oracle."""
        fault_harness("poison-output", ops="ecdsa_msm")
        recs = [_srecord(800 + i) for i in range(3)] + [
            _srecord(810, good=False)]
        b_canary = eb.STATS.msm_canary_failures
        b_kat = eb.STATS.kat_failures
        got = _msm_verify(recs)
        assert got.tolist() == _oracle_verdicts(recs)
        assert got.tolist() == [True, True, True, False]
        assert eb.STATS.msm_canary_failures > b_canary
        assert eb.STATS.kat_failures > b_kat


# ----------------------------------------------------------------------
# sharded MSM (separate compiled shape -> slow-marked)
# ----------------------------------------------------------------------


@pytest.mark.slow
def test_sharded_msm_matches_oracle():
    """The mesh-sharded partial-MSM fold agrees with the host oracle on
    both polarities (exact zero combination accepted, one perturbed
    scalar rejected)."""
    import random as _random

    from bitcoincashplus_tpu.parallel.sig_shard import msm_is_infinity_sharded

    rng = _random.Random(13)
    terms = []
    for _ in range(8):
        d = rng.randrange(1, oracle.N)
        k = rng.randrange(1, oracle.N)
        p = oracle.point_mul(d, oracle.G)
        terms.append((p[0], p[1], k))
        terms.append((p[0], p[1], oracle.N - k))
    assert msm_is_infinity_sharded(terms, 2) is True
    bad = terms[:-1] + [(terms[-1][0], terms[-1][1],
                         (terms[-1][2] + 1) % oracle.N)]
    assert msm_is_infinity_sharded(bad, 2) is False
