"""SigService — the always-on micro-batching signature service (ISSUE 7).

Covers the flush policy (full / deadline / kick / stop), sigcache
awareness (pre-enqueue hits, in-flight dedup, settle-side insertion),
block-import preemption, degradation (flush failure -> caller-side CPU
re-verify; programming error -> visible thread death with inline
fallback), the serviced AcceptToMemoryPool path (verdicts identical to
the synchronous path, stale-context retry), and the -sigservice* node
knobs. Tier-1: JAX_PLATFORMS=cpu, no device needed.
"""

import hashlib
import threading
import time
from contextlib import contextmanager

import numpy as np
import pytest

from bitcoincashplus_tpu.consensus.params import regtest_params
from bitcoincashplus_tpu.consensus.tx import COutPoint, CTransaction, CTxIn, CTxOut
from bitcoincashplus_tpu.crypto import secp256k1 as oracle
from bitcoincashplus_tpu.mempool import CTxMemPool, MempoolError
from bitcoincashplus_tpu.mempool.accept import accept_to_memory_pool
from bitcoincashplus_tpu.mining.generate import generate_blocks
from bitcoincashplus_tpu.ops import ecdsa_batch
from bitcoincashplus_tpu.script.interpreter import SigCheckRecord
from bitcoincashplus_tpu.serving import SigService, prewarm_block_sigs
from bitcoincashplus_tpu.store.blockstore import MemoryBlockStore
from bitcoincashplus_tpu.validation.chainstate import ChainstateManager
from bitcoincashplus_tpu.validation.coins import MemoryCoinsView
from bitcoincashplus_tpu.validation.scriptcheck import BlockScriptVerifier
from bitcoincashplus_tpu.validation.sigcache import SignatureCache
from bitcoincashplus_tpu.wallet.keys import CKey
from bitcoincashplus_tpu.wallet.signing import sign_transaction

from test_validation import TILE

pytestmark = pytest.mark.serving

KEY = CKey(0xC0FFEE)
SPK_KEY = KEY.p2pkh_script()


def _record(i: int, good: bool = True) -> SigCheckRecord:
    d = 0x2222 + i
    e = int.from_bytes(hashlib.sha256(b"svc%d" % i).digest(),
                       "big") % oracle.N
    r, s = oracle.ecdsa_sign(d, e)
    pub = oracle.point_mul(d, oracle.G)
    return SigCheckRecord(pub, r, s, e if good else (e + 1) % oracle.N)


def _key_of(rec) -> bytes:
    return SignatureCache.entry_key(rec.msg_hash, rec.r, rec.s, rec.pubkey)


@contextmanager
def _service(**kw):
    kw.setdefault("backend", "cpu")
    svc = SigService(**kw).start()
    try:
        yield svc
    finally:
        svc.stop()


# ----------------------------------------------------------------------
# flush policy
# ----------------------------------------------------------------------


class TestFlushPolicy:
    def test_flush_on_full(self):
        with _service(lanes=4, deadline_ms=60_000) as svc:
            fut = svc.submit([_record(i) for i in range(4)])
            deadline = time.monotonic() + 10
            while not fut.done() and time.monotonic() < deadline:
                time.sleep(0.005)
            assert fut.done(), "full bucket must flush without a kick"
            assert svc.stats["flush_full"] == 1
            assert fut.result().all()

    def test_flush_on_deadline(self):
        with _service(lanes=10_000, deadline_ms=30) as svc:
            fut = svc.submit([_record(10)])
            deadline = time.monotonic() + 10
            while not fut.done() and time.monotonic() < deadline:
                time.sleep(0.005)
            assert fut.done(), "lone tx must not starve behind the bucket"
            assert svc.stats["flush_deadline"] == 1

    def test_kick_on_result(self):
        with _service(lanes=10_000, deadline_ms=60_000) as svc:
            fut = svc.submit([_record(20)])
            t0 = time.monotonic()
            assert fut.result().all()
            # a blocked waiter flushes immediately, not at the deadline
            assert time.monotonic() - t0 < 30.0
            assert svc.stats["flush_kick"] == 1

    def test_stop_drains_pending(self):
        svc = SigService(backend="cpu", lanes=10_000,
                         deadline_ms=60_000).start()
        fut = svc.submit([_record(30)])
        svc.stop()
        assert svc.stats["flush_stop"] == 1
        assert fut.result().all()

    def test_submit_after_stop_runs_inline(self):
        svc = SigService(backend="cpu").start()
        svc.stop()
        assert svc.submit([_record(40)]).result().all()
        assert not svc.submit([_record(41, good=False)]).result().any()

    def test_bad_lane_verdict(self):
        with _service(lanes=4, deadline_ms=60_000) as svc:
            good = [_record(50 + i) for i in range(3)]
            fut = svc.submit(good + [_record(59, good=False)])
            assert fut.result().tolist() == [True, True, True, False]


# ----------------------------------------------------------------------
# sigcache awareness
# ----------------------------------------------------------------------


class TestSigcache:
    def test_pre_enqueue_hit_skips_lane(self):
        sc = SignatureCache()
        rec = _record(60)
        sc.add(_key_of(rec))
        with _service(sigcache=sc) as svc:
            fut = svc.submit([rec])
            # resolved inline: no lane, no dispatch needed
            assert fut.done()
            assert fut.result().all()
            assert svc.stats["cache_hits"] == 1
            assert svc.stats["lanes_enqueued"] == 0

    def test_settle_inserts_true_verdicts_only(self):
        sc = SignatureCache()
        good, bad = _record(61), _record(62, good=False)
        with _service(sigcache=sc) as svc:
            svc.submit([good, bad]).result()
        assert sc.snapshot()["inserts"] == 1
        assert _key_of(good) in sc._set
        assert _key_of(bad) not in sc._set

    def test_inflight_dedup_shares_one_lane(self):
        sc = SignatureCache()
        rec = _record(63)
        with _service(sigcache=sc, lanes=10_000,
                      deadline_ms=60_000) as svc:
            f1 = svc.submit([rec])
            f2 = svc.submit([rec])  # parked: joins f1's lane
            assert svc.stats["dedup_hits"] == 1
            assert svc.stats["lanes_enqueued"] == 1
            assert f1.result().all() and f2.result().all()
            assert svc.stats["dispatches"] == 1
        # the dedup is surfaced in the sigcache snapshot
        assert sc.snapshot()["service_dedup_hits"] == 1

    def test_dedup_within_one_submit(self):
        rec = _record(64)
        with _service() as svc:
            fut = svc.submit([rec, rec])
            assert fut.result().tolist() == [True, True]
            assert svc.stats["dedup_hits"] == 1


# ----------------------------------------------------------------------
# preemption + degradation
# ----------------------------------------------------------------------


class TestDegradation:
    def test_import_priority_preempts(self):
        with _service() as svc:
            with svc.import_priority():
                assert svc.snapshot()["priority_depth"] == 1
                with svc.import_priority():  # re-entrant
                    assert svc.submit([_record(70)]).result().all()
            assert svc.snapshot()["priority_depth"] == 0
        assert svc.stats["preempted_dispatches"] >= 1

    def test_flush_error_degrades_to_caller_cpu(self, monkeypatch):
        calls = {"n": 0}
        real = ecdsa_batch.dispatch_batch

        def boom(records, backend="auto", kernel=None):
            calls["n"] += 1
            if calls["n"] == 1:
                raise ValueError("injected flush failure")
            return real(records, backend=backend, kernel=kernel)

        monkeypatch.setattr(ecdsa_batch, "dispatch_batch", boom)
        with _service() as svc:
            fut = svc.submit([_record(71), _record(72, good=False)])
            # verdicts survive the failed flush via caller-side CPU
            # re-verify — never dropped, never fabricated
            assert fut.result().tolist() == [True, False]
            assert svc.stats["flush_errors"] == 1
            assert svc.running()  # a non-programming error is survivable

    def test_degraded_path_caches_and_dedups(self, monkeypatch):
        """A failed flush's caller-side re-verify is ONE batched call,
        TRUE verdicts land in the sigcache, and a second future sharing
        the errored lane resolves from the cache without re-verifying."""
        calls = []
        real = ecdsa_batch.dispatch_batch

        def boom(records, backend="auto", kernel=None):
            calls.append(len(records))
            if len(calls) == 1:
                raise ValueError("injected flush failure")
            return real(records, backend=backend, kernel=kernel)

        monkeypatch.setattr(ecdsa_batch, "dispatch_batch", boom)
        sc = SignatureCache()
        rec_a, rec_b = _record(80), _record(81)
        with _service(sigcache=sc, lanes=10_000,
                      deadline_ms=60_000) as svc:
            f1 = svc.submit([rec_a, rec_b])
            f2 = svc.submit([rec_a])  # dedup: shares the doomed lane
            assert f1.result().tolist() == [True, True]
            # ONE batched re-verify covered both records of f1
            assert calls == [2, 2]
            # the degraded path still populated the sigcache...
            assert sc.snapshot()["inserts"] == 2
            # ...so the sharing future resolves from it, no third call
            assert f2.result().tolist() == [True]
            assert calls == [2, 2]

    def test_wait_is_advisory(self, monkeypatch):
        """wait() never re-verifies: on timeout it just reports False
        (the prewarm contract — a backlogged service costs the relay
        path the timeout, not a serial CPU pass)."""

        def never(records, backend="auto", kernel=None):
            raise ValueError("wedged")

        monkeypatch.setattr(ecdsa_batch, "dispatch_batch", never)
        with _service(lanes=10_000, deadline_ms=60_000) as svc:
            fut = svc.submit([_record(85)])
            # errored lanes settle (err set) -> wait returns True fast,
            # and crucially performs no verification of its own
            assert fut.wait(5.0) is True
            assert fut._sources[0].err is not None

    def test_programming_error_kills_thread_visibly(self, monkeypatch):
        calls = {"n": 0}
        real = ecdsa_batch.dispatch_batch

        def boom(records, backend="auto", kernel=None):
            calls["n"] += 1
            if calls["n"] == 1:
                raise NameError("refactor broke the dispatch layer")
            return real(records, backend=backend, kernel=kernel)

        monkeypatch.setattr(ecdsa_batch, "dispatch_batch", boom)
        with _service() as svc:
            fut = svc.submit([_record(73)])
            ok = fut.result()  # caller-side CPU re-verify still lands
            assert ok.all()
            deadline = time.monotonic() + 5
            while svc.running() and time.monotonic() < deadline:
                time.sleep(0.01)
            assert not svc.running(), "NameError must not degrade silently"
            # with the thread dead, later submits flush inline
            assert svc.submit([_record(74)]).result().all()

    def test_double_buffered_flushes_overlap(self, monkeypatch):
        """With a slow device verify, the service packs and dispatches
        flush N+1 while flush N is still in flight (-sigservicebuffers=2,
        the ROADMAP PR 7 headroom item): overlapped_flushes meters it
        and every verdict still lands correctly."""
        real = ecdsa_batch.dispatch_batch
        inflight = {"now": 0, "max": 0}
        lock = threading.Lock()

        class SlowHandle:
            def __init__(self, handle):
                self._handle = handle

            def result(self):
                time.sleep(0.05)  # the device window the host can hide in
                with lock:
                    inflight["now"] -= 1
                return self._handle.result()

        def slow(records, backend="auto", kernel=None):
            with lock:
                inflight["now"] += 1
                inflight["max"] = max(inflight["max"], inflight["now"])
            return SlowHandle(real(records, backend=backend, kernel=kernel))

        monkeypatch.setattr(ecdsa_batch, "dispatch_batch", slow)
        with _service(lanes=4, deadline_ms=60_000, buffers=2) as svc:
            recs = [_record(300 + i, good=(i != 5)) for i in range(12)]
            fut = svc.submit(recs)  # 3 full buckets back to back
            ok = fut.result()
            assert ok.tolist() == [i != 5 for i in range(12)]
            assert svc.stats["dispatches"] == 3
            assert svc.stats["overlapped_flushes"] >= 1
            assert inflight["max"] >= 2  # two flushes genuinely co-flying
            assert svc.snapshot()["buffers"] == 2

    def test_single_buffer_identical_verdicts(self):
        """-sigservicebuffers=1 is the PR 7 single-slot loop — the
        differential: same records, same verdicts, no overlap."""
        recs = [_record(340 + i, good=(i % 3 != 2)) for i in range(9)]
        with _service(lanes=4, deadline_ms=1, buffers=1) as svc:
            ok1 = svc.submit(recs).result()
            assert svc.stats["overlapped_flushes"] == 0
        with _service(lanes=4, deadline_ms=1, buffers=2) as svc:
            ok2 = svc.submit(recs).result()
        assert ok1.tolist() == ok2.tolist() == [i % 3 != 2
                                                for i in range(9)]

    def test_buffered_flush_error_isolated_to_its_bucket(self, monkeypatch):
        """A failing flush in slot N must not poison slot N+1's verdicts
        — only N's lanes degrade to the caller-side CPU re-verify."""
        calls = {"n": 0}
        real = ecdsa_batch.dispatch_batch

        def boom_first(records, backend="auto", kernel=None):
            calls["n"] += 1
            if calls["n"] == 1:
                raise ValueError("injected slot-0 failure")
            return real(records, backend=backend, kernel=kernel)

        monkeypatch.setattr(ecdsa_batch, "dispatch_batch", boom_first)
        with _service(lanes=3, deadline_ms=60_000, buffers=2) as svc:
            recs = [_record(360 + i, good=(i != 1)) for i in range(6)]
            fut = svc.submit(recs)
            ok = fut.result()
            assert ok.tolist() == [i != 1 for i in range(6)]
            assert svc.stats["flush_errors"] == 1
            assert svc.running()

    def test_stop_drains_inflight_slots(self, monkeypatch):
        real = ecdsa_batch.dispatch_batch

        class SlowHandle:
            def __init__(self, handle):
                self._handle = handle

            def result(self):
                time.sleep(0.03)
                return self._handle.result()

        monkeypatch.setattr(
            ecdsa_batch, "dispatch_batch",
            lambda records, backend="auto", kernel=None:
            SlowHandle(real(records, backend=backend, kernel=kernel)))
        svc = SigService(backend="cpu", lanes=2, deadline_ms=60_000,
                         buffers=2).start()
        fut = svc.submit([_record(380 + i) for i in range(6)])
        svc.stop()  # must settle every dispatched slot before joining
        assert fut.done()
        assert fut.result().all()

    def test_rejects_bad_buffers(self):
        with pytest.raises(ValueError, match="sigservicebuffers"):
            SigService(buffers=0)

    def test_concurrent_submissions_share_one_bucket(self):
        # six transactions enqueue BEFORE anyone awaits (the open-loop
        # storm shape): the first result() kick must flush every parked
        # lane as one shared bucket, not one dispatch per submitter
        with _service(lanes=10_000, deadline_ms=60_000) as svc:
            futs = [svc.submit([_record(100 + i * 4 + j) for j in range(4)])
                    for i in range(6)]
            assert all(f.result().all() for f in futs)
            assert svc.stats["dispatches"] == 1
            assert svc.stats["lanes_real"] == 24


# ----------------------------------------------------------------------
# serviced AcceptToMemoryPool — verdicts identical to the sync path
# ----------------------------------------------------------------------


@pytest.fixture(scope="module")
def chain():
    """chainstate trio with 103 mined blocks (module-scoped: the mining
    cost is paid once; each test gets a FRESH pool + sigcache)."""
    params = regtest_params()
    t = [1_600_000_000]

    def fake_time():
        t[0] += 60
        return t[0]

    cs = ChainstateManager(
        params, MemoryCoinsView(), MemoryBlockStore(),
        script_verifier=BlockScriptVerifier(params, backend="cpu",
                                            sigcache=SignatureCache()),
        get_time=fake_time,
    )
    generate_blocks(cs, SPK_KEY, 110, tile=TILE)  # heights 1-10 mature
    return cs


def _coinbase_out(cs, height):
    blk = cs.get_block(cs.chain[height].hash)
    return COutPoint(blk.vtx[0].txid, 0), blk.vtx[0].vout[0].value


def _spend(op, value, fee=10_000, n_out=1):
    per_out = (value - fee) // n_out
    tx = CTransaction(
        vin=(CTxIn(op, b""),),
        vout=tuple(CTxOut(per_out, SPK_KEY) for _ in range(n_out)),
    )
    return sign_transaction(
        tx, [(SPK_KEY, value)], lambda i: KEY if i == KEY.pubkey_hash else None,
        enable_forkid=True,
    )


class TestServicedAccept:
    def test_accept_matches_sync_path(self, chain):
        cs = chain
        op, value = _coinbase_out(cs, 1)
        tx = _spend(op, value)
        sync_pool, svc_pool = CTxMemPool(), CTxMemPool()
        sync_sc, svc_sc = SignatureCache(), SignatureCache()
        e_sync = accept_to_memory_pool(sync_pool, cs, tx, sigcache=sync_sc)
        with _service(sigcache=svc_sc) as svc:
            e_svc = accept_to_memory_pool(svc_pool, cs, tx, sigcache=svc_sc,
                                          sig_service=svc)
        assert e_svc.txid == e_sync.txid
        assert e_svc.fee == e_sync.fee and e_svc.sigops == e_sync.sigops
        # both paths populated the sigcache for the eventual connect
        assert len(sync_sc) == len(svc_sc) == 1

    def test_bad_signature_rejected_identically(self, chain):
        cs = chain
        op, value = _coinbase_out(cs, 2)
        tx = _spend(op, value)
        ss = bytearray(tx.vin[0].script_sig)
        ss[40] ^= 1
        bad = CTransaction(tx.version, (CTxIn(op, bytes(ss)),), tx.vout,
                           tx.locktime)
        pool, sc = CTxMemPool(), SignatureCache()
        with _service(sigcache=sc) as svc:
            with pytest.raises(MempoolError, match="script-verify"):
                accept_to_memory_pool(pool, cs, bad, sigcache=sc,
                                      sig_service=svc)
        assert bad.txid not in pool and len(sc) == 0

    def test_stale_parent_retries_to_missing_inputs(self, chain):
        """An in-pool parent evicted during the verdict wait: the accept
        retries and the FINAL synchronous attempt derives missing-inputs
        — never a phantom entry over a vanished coin."""
        cs = chain
        op, value = _coinbase_out(cs, 3)
        parent = _spend(op, value, n_out=2)
        pool, sc = CTxMemPool(), SignatureCache()
        with _service(sigcache=sc) as svc:
            accept_to_memory_pool(pool, cs, parent, sigcache=sc,
                                  sig_service=svc)
            child = _spend(COutPoint(parent.txid, 0),
                           parent.vout[0].value)
            evicted = {"done": False}

            @contextmanager
            def evict_parent_mid_wait():
                if not evicted["done"]:
                    evicted["done"] = True
                    pool.remove_recursive(parent.txid)
                yield

            with pytest.raises(MempoolError, match="missing-inputs"):
                accept_to_memory_pool(pool, cs, child, sigcache=sc,
                                      sig_service=svc,
                                      wait_ctx=evict_parent_mid_wait)
        assert child.txid not in pool

    def test_conflict_added_mid_wait_rejected(self, chain):
        cs = chain
        op, value = _coinbase_out(cs, 4)
        tx = _spend(op, value)
        rival = _spend(op, value, fee=20_000)
        pool, sc = CTxMemPool(), SignatureCache()
        with _service(sigcache=sc) as svc:
            injected = {"done": False}

            @contextmanager
            def add_rival_mid_wait():
                if not injected["done"]:
                    injected["done"] = True
                    accept_to_memory_pool(pool, cs, rival, sigcache=sc)
                yield

            with pytest.raises(MempoolError, match="mempool-conflict"):
                accept_to_memory_pool(pool, cs, tx, sigcache=sc,
                                      sig_service=svc,
                                      wait_ctx=add_rival_mid_wait)
        assert rival.txid in pool and tx.txid not in pool


# ----------------------------------------------------------------------
# prewarm (tip relay / getblocktemplate re-validation)
# ----------------------------------------------------------------------


class _StubNode:
    def __init__(self, cs, pool, svc):
        self.chainstate = cs
        self.mempool = pool
        self.sigservice = svc


class TestPrewarm:
    def test_prewarm_populates_sigcache(self, chain):
        cs = chain
        op, value = _coinbase_out(cs, 5)
        tx = _spend(op, value)
        pool = CTxMemPool()
        # a decoy entry: the prewarm gate requires a live mempool
        d_op, d_val = _coinbase_out(cs, 6)
        sc = SignatureCache()
        with _service(sigcache=sc) as svc:
            node = _StubNode(cs, pool, svc)
            accept_to_memory_pool(pool, cs, _spend(d_op, d_val),
                                  sigcache=sc)
            # a tip-extending block carrying a NON-mempool tx
            from dataclasses import replace

            from bitcoincashplus_tpu.mining.assembler import BlockAssembler

            from bitcoincashplus_tpu.consensus.merkle import (
                block_merkle_root,
            )

            blk = BlockAssembler(cs, pool).create_new_block(SPK_KEY).block
            blk = replace(blk, vtx=(blk.vtx[0], tx))
            # re-commit the swapped body (prewarm's merkle gate is real)
            blk = replace(blk, header=replace(
                blk.header, hash_merkle_root=block_merkle_root(blk)[0]))
            inserts_before = sc.snapshot()["inserts"]
            # the template is unmined — proposal-mode shape, PoW waived
            n = prewarm_block_sigs(node, blk, require_pow=False)
            assert n == 1
            assert sc.snapshot()["inserts"] == inserts_before + 1
            assert svc.stats["prewarm_txs"] == 1
            # P2P shape: real PoW required; a mainnet-difficulty header
            # (impossible for this unmined template) gates the prewarm
            hdr = replace(blk.header, bits=0x1803A30C)
            assert prewarm_block_sigs(node, replace(blk, header=hdr)) == 0
            # a body not committed by the merkle root is gated too
            bad = replace(blk, vtx=(blk.vtx[0], tx, tx))
            assert prewarm_block_sigs(node, bad, require_pow=False) == 0

    def test_prewarm_skips_without_mempool(self, chain):
        cs = chain
        op, value = _coinbase_out(cs, 7)
        tx = _spend(op, value)
        with _service(sigcache=SignatureCache()) as svc:
            node = _StubNode(cs, CTxMemPool(), svc)
            from dataclasses import replace

            from bitcoincashplus_tpu.mining.assembler import BlockAssembler

            blk = BlockAssembler(cs, node.mempool) \
                .create_new_block(SPK_KEY).block
            blk = replace(blk, vtx=(blk.vtx[0], tx))
            # IBD gate: empty mempool bails before PoW/merkle work
            assert prewarm_block_sigs(node, blk, require_pow=False) == 0


# ----------------------------------------------------------------------
# node knobs + observability
# ----------------------------------------------------------------------


class TestNodeWiring:
    def _mk_config(self, tmp_path, **args):
        from bitcoincashplus_tpu.node.config import Config

        cfg = Config()
        cfg.args["datadir"] = [str(tmp_path)]
        cfg.args["regtest"] = ["1"]
        for k, v in args.items():
            cfg.args[k] = [str(v)]
        return cfg

    def test_bad_sigservice_flag_rejected(self, tmp_path):
        from bitcoincashplus_tpu.node.config import ConfigError
        from bitcoincashplus_tpu.node.node import Node

        with pytest.raises(ConfigError, match="sigservice"):
            Node(config=self._mk_config(tmp_path / "a", sigservice="maybe"))
        with pytest.raises(ConfigError, match="sigservicedeadline"):
            Node(config=self._mk_config(tmp_path / "b",
                                        sigservicedeadline="-5"))
        with pytest.raises(ConfigError, match="sigservicelanes"):
            Node(config=self._mk_config(tmp_path / "c", sigservicelanes="0"))

    def test_service_default_on_and_off_knob(self, tmp_path):
        from bitcoincashplus_tpu.node.node import Node
        from bitcoincashplus_tpu.rpc.control import gettpuinfo

        node = Node(config=self._mk_config(tmp_path / "on"))
        try:
            assert node.sigservice is not None and node.sigservice.running()
            assert node.chainstate.sig_service is node.sigservice
            info = gettpuinfo(node, [])
            assert info["serving"]["enabled"] is True
            assert info["serving"]["lanes"] == 2046
        finally:
            node.close()
        assert not node.sigservice.running()  # close() stopped the thread

        node = Node(config=self._mk_config(tmp_path / "off",
                                           sigservice="off"))
        try:
            assert node.sigservice is None
            assert gettpuinfo(node, [])["serving"] == {"enabled": False}
        finally:
            node.close()

    def test_snapshot_and_registry_families(self):
        from bitcoincashplus_tpu.util import telemetry

        with _service() as svc:
            svc.submit([_record(90)]).result()
            snap = svc.snapshot()
            for key in ("queue_depth", "dispatches", "flush_kick",
                        "dedup_hits", "cache_hits", "deadline_ms",
                        "wait_ms", "preempted_dispatches"):
                assert key in snap, key
        text = telemetry.REGISTRY.prometheus_text()
        for fam in ("bcp_sigservice_queue_depth", "bcp_sigservice_flush_total",
                    "bcp_sigservice_wait_seconds"):
            assert fam in text, fam
