"""BIP37 bloom filters + partial merkle trees.

Mirrors src/test/bloom_tests.cpp (including its exact serialized-filter
vectors, which pin MurmurHash3 bit-for-bit) and src/test/pmt_tests.cpp
(randomized build/serialize/deserialize/extract round-trips).
"""

import struct

import pytest

pytest.importorskip("hypothesis")  # property tests need the optional test extra
from hypothesis import given, settings
from hypothesis import strategies as st

from bitcoincashplus_tpu.consensus.merkleblock import (
    CMerkleBlock,
    CPartialMerkleTree,
)
from bitcoincashplus_tpu.consensus.merkle import compute_merkle_root
from bitcoincashplus_tpu.consensus.serialize import ByteReader
from bitcoincashplus_tpu.consensus.tx import (
    COutPoint,
    CTransaction,
    CTxIn,
    CTxOut,
)
from bitcoincashplus_tpu.crypto.hashes import sha256d
from bitcoincashplus_tpu.p2p.bloom import (
    BLOOM_UPDATE_ALL,
    BLOOM_UPDATE_P2PUBKEY_ONLY,
    CBloomFilter,
    deser_filterload,
    murmur3,
    ser_filterload,
)


class TestMurmur3:
    def test_reference_vectors(self):
        # canonical MurmurHash3 x86_32 test values
        assert murmur3(0, b"") == 0
        assert murmur3(1, b"") == 0x514E28B7
        assert murmur3(0, b"hello") == 0x248BFA47
        assert murmur3(0x9747B28C, b"The quick brown fox jumps over the lazy dog") == 0x2FA826CD


class TestBloomFilter:
    def test_insert_serialize(self):
        """bloom_tests.cpp bloom_create_insert_serialize — exact bytes."""
        f = CBloomFilter(3, 0.01, 0, BLOOM_UPDATE_ALL)
        f.insert(bytes.fromhex("99108ad8ed9bb6274d3980bab5a85c048f0950c8"))
        assert f.contains(bytes.fromhex("99108ad8ed9bb6274d3980bab5a85c048f0950c8"))
        # one bit different → miss
        assert not f.contains(bytes.fromhex("19108ad8ed9bb6274d3980bab5a85c048f0950c8"))
        f.insert(bytes.fromhex("b5a2c786d9ef4658287ced5914b37a1b4aa32eee"))
        assert f.contains(bytes.fromhex("b5a2c786d9ef4658287ced5914b37a1b4aa32eee"))
        f.insert(bytes.fromhex("b9300670b4c5366e95b2699e8b18bc75e5f729c5"))
        assert f.contains(bytes.fromhex("b9300670b4c5366e95b2699e8b18bc75e5f729c5"))
        assert ser_filterload(f).hex() == "03614e9b050000000000000001"

    def test_insert_serialize_with_tweak(self):
        """bloom_tests.cpp bloom_create_insert_serialize_with_tweaks."""
        f = CBloomFilter(3, 0.01, 2147483649, BLOOM_UPDATE_ALL)
        f.insert(bytes.fromhex("99108ad8ed9bb6274d3980bab5a85c048f0950c8"))
        f.insert(bytes.fromhex("b5a2c786d9ef4658287ced5914b37a1b4aa32eee"))
        f.insert(bytes.fromhex("b9300670b4c5366e95b2699e8b18bc75e5f729c5"))
        assert ser_filterload(f).hex() == "03ce4299050000000100008001"

    def test_wire_roundtrip(self):
        f = CBloomFilter(10, 0.001, 42, BLOOM_UPDATE_P2PUBKEY_ONLY)
        f.insert(b"payload")
        g = deser_filterload(ser_filterload(f))
        assert bytes(g.data) == bytes(f.data)
        assert g.n_hash_funcs == f.n_hash_funcs
        assert g.tweak == 42 and g.flags == BLOOM_UPDATE_P2PUBKEY_ONLY
        assert g.contains(b"payload") and not g.contains(b"other")

    def test_relevant_txid_match(self):
        tx = _tx()
        f = CBloomFilter(1, 0.0001, 0, BLOOM_UPDATE_ALL)
        f.insert(tx.txid)
        assert f.is_relevant_and_update(tx)
        f2 = CBloomFilter(1, 0.0001, 0, BLOOM_UPDATE_ALL)
        f2.insert(b"\x55" * 32)
        assert not f2.is_relevant_and_update(tx)

    def test_output_match_inserts_outpoint(self):
        """A matched output's outpoint enters the filter (UPDATE_ALL), so a
        later spend of it matches too."""
        key_hash = b"\xab" * 20
        from bitcoincashplus_tpu.script.script import p2pkh_script

        tx = CTransaction(
            vin=(CTxIn(COutPoint(b"\x01" * 32, 0), b""),),
            vout=(CTxOut(5000, p2pkh_script(key_hash)),),
        )
        f = CBloomFilter(1, 0.0001, 0, BLOOM_UPDATE_ALL)
        f.insert(key_hash)
        assert f.is_relevant_and_update(tx)
        spend = CTransaction(
            vin=(CTxIn(COutPoint(tx.txid, 0), b""),),
            vout=(CTxOut(4000, b"\x51"),),
        )
        # spend matches ONLY via the auto-inserted outpoint
        assert f.is_relevant_and_update(spend)
        # without the update, it would not have
        g = CBloomFilter(1, 0.0001, 0, BLOOM_UPDATE_P2PUBKEY_ONLY)
        g.insert(key_hash)
        assert g.is_relevant_and_update(tx)  # matches the pkh push
        assert not g.is_relevant_and_update(spend)  # p2pkh not auto-added

    def test_prevout_and_scriptsig_match(self):
        tx = _tx()
        f = CBloomFilter(1, 0.0001, 0, BLOOM_UPDATE_ALL)
        f.insert_outpoint(tx.vin[0].prevout)
        assert f.is_relevant_and_update(tx)
        g = CBloomFilter(1, 0.0001, 0, BLOOM_UPDATE_ALL)
        g.insert(b"\x11" * 33)  # data push inside the scriptSig
        sig_tx = CTransaction(
            vin=(CTxIn(COutPoint(b"\x01" * 32, 0), b"\x21" + b"\x11" * 33),),
            vout=(CTxOut(1000, b"\x51"),),
        )
        assert g.is_relevant_and_update(sig_tx)


def _tx(salt: int = 7) -> CTransaction:
    return CTransaction(
        vin=(CTxIn(COutPoint(bytes([salt]) * 32, 1), b"\x51"),),
        vout=(CTxOut(1000, b"\x51"),),
    )


# ----------------------------------------------------------------------
# CPartialMerkleTree (pmt_tests.cpp)
# ----------------------------------------------------------------------


def _txids(n: int) -> list[bytes]:
    return [sha256d(struct.pack("<I", i)) for i in range(n)]


class TestPartialMerkleTree:
    def test_single_tx(self):
        txids = _txids(1)
        pmt = CPartialMerkleTree.from_txids(txids, [True])
        root, matches = pmt.extract_matches()
        assert root == txids[0]
        assert matches == [(0, txids[0])]

    def test_no_matches_root_only(self):
        txids = _txids(9)
        pmt = CPartialMerkleTree.from_txids(txids, [False] * 9)
        root, matches = pmt.extract_matches()
        assert root == compute_merkle_root(txids)[0]
        assert matches == []
        assert len(pmt.hashes) == 1  # pruned to the bare root

    @settings(max_examples=40, deadline=None)
    @given(st.integers(min_value=1, max_value=64), st.data())
    def test_random_roundtrip(self, n, data):
        txids = _txids(n)
        matches = [data.draw(st.booleans()) for _ in range(n)]
        pmt = CPartialMerkleTree.from_txids(txids, matches)
        # wire round-trip
        wire = pmt.serialize()
        pmt2 = CPartialMerkleTree.deserialize(ByteReader(wire))
        got = pmt2.extract_matches()
        assert got is not None
        root, extracted = got
        assert root == compute_merkle_root(txids)[0]
        assert [t for _p, t in extracted] == [
            t for t, m in zip(txids, matches) if m
        ]
        assert [p for p, _t in extracted] == [
            i for i, m in enumerate(matches) if m
        ]

    def test_tampered_proof_rejected(self):
        txids = _txids(16)
        matches = [i in (3, 7) for i in range(16)]
        pmt = CPartialMerkleTree.from_txids(txids, matches)
        root, _ = pmt.extract_matches()
        # flip a byte in one contained hash → different root (not None, but
        # the root check upstream fails)
        pmt.hashes[0] = bytes([pmt.hashes[0][0] ^ 1]) + pmt.hashes[0][1:]
        got = pmt.extract_matches()
        assert got is None or got[0] != root

    def test_malformed_shapes_rejected(self):
        assert CPartialMerkleTree(0, [], []).extract_matches() is None
        # more hashes than transactions
        assert CPartialMerkleTree(
            1, [True], [b"\x00" * 32, b"\x01" * 32]
        ).extract_matches() is None
        # absurd transaction count
        assert CPartialMerkleTree(
            10**9, [True], [b"\x00" * 32]
        ).extract_matches() is None
        # trailing unconsumed hash
        txids = _txids(4)
        pmt = CPartialMerkleTree.from_txids(txids, [True, False, False, False])
        pmt.hashes.append(b"\x77" * 32)
        assert pmt.extract_matches() is None

    def test_merkleblock_from_block(self):
        """CMerkleBlock over a synthetic block, filter and txid_set paths."""
        class _Blk:
            pass

        txs = [_tx(i) for i in range(1, 8)]
        blk = _Blk()
        blk.vtx = txs
        from bitcoincashplus_tpu.consensus.block import CBlockHeader

        root, _ = compute_merkle_root([t.txid for t in txs])
        blk.header = CBlockHeader(hash_merkle_root=root)
        target = txs[3].txid
        mb = CMerkleBlock.from_block(blk, txid_set={target})
        assert mb.matched_txids == [target]
        wire = mb.serialize()
        mb2 = CMerkleBlock.from_bytes(wire)
        got_root, matches = mb2.pmt.extract_matches()
        assert got_root == mb2.header.hash_merkle_root == root
        assert matches == [(3, target)]
