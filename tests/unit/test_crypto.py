"""CPU crypto tests (reference model: src/test/crypto_tests.cpp)."""

import hashlib
import struct

import pytest

pytest.importorskip("hypothesis")  # property tests need the optional test extra
from hypothesis import given
from hypothesis import strategies as st

from bitcoincashplus_tpu.crypto.hashes import (
    SHA256_INIT,
    hash160,
    header_midstate,
    ripemd160,
    sha256,
    sha256_compress,
    sha256d,
    sha256d_from_midstate,
)


class TestVectors:
    def test_sha256_nist(self):
        # FIPS 180-4 examples
        assert sha256(b"abc").hex() == (
            "ba7816bf8f01cfea414140de5dae2223b00361a396177a9cb410ff61f20015ad"
        )
        assert sha256(b"").hex() == (
            "e3b0c44298fc1c149afbf4c8996fb92427ae41e4649b934ca495991b7852b855"
        )

    def test_sha256d(self):
        assert sha256d(b"hello").hex() == (
            "9595c9df90075148eb06860365df33584b75bff782a510c6cd4883a419833d50"
        )

    def test_ripemd160(self):
        assert ripemd160(b"abc").hex() == "8eb208f7e05d987a9b044a8e98c6b087f15a0bfc"

    def test_hash160(self):
        # Genesis output pubkey -> well-known P2PKH hash
        pubkey = bytes.fromhex(
            "04678afdb0fe5548271967f1a67130b7105cd6a828e03909a67962e0ea1f61deb6"
            "49f6bc3f4cef38c4f35504e51ec112de5c384df7ba0b8d578a4c702b6bf11d5f"
        )
        assert hash160(pubkey).hex() == "62e907b15cbf27d5425399ebf6f0fb50ebb88f18"


class TestCompression:
    """The pure-Python compression must agree with hashlib — it seeds the
    midstates used by the mining kernel."""

    @given(st.binary(min_size=64, max_size=64))
    def test_single_block_vs_hashlib(self, block):
        # hash of exactly-64-byte message: compress(msg) then compress(padding)
        st1 = sha256_compress(SHA256_INIT, block)
        pad = b"\x80" + b"\x00" * 55 + struct.pack(">Q", 512)
        st2 = sha256_compress(st1, pad)
        assert struct.pack(">8I", *st2) == hashlib.sha256(block).digest()

    @given(st.binary(min_size=80, max_size=80))
    def test_midstate_header_path(self, header):
        expect = sha256d(header)
        mid = header_midstate(header)
        assert sha256d_from_midstate(mid, header[64:]) == expect

    def test_genesis_header_midstate(self):
        from bitcoincashplus_tpu.consensus.params import main_params

        hdr = main_params().genesis.header.serialize()
        mid = header_midstate(hdr)
        got = sha256d_from_midstate(mid, hdr[64:])
        assert bytes(reversed(got)).hex() == (
            "000000000019d6689c085ae165831e934ff763ae46a2a6c172b3f1b60a8ce26f"
        )
