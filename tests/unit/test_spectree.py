"""Speculation tree + EDA/DAA difficulty rules (ISSUE 9).

The load-bearing guarantees under test:
  - competing tips validate CONCURRENTLY as sibling subtrees (branch
    gauges > 1), the most-work branch settles in order, and losing
    branches drop un-externalized with digests identical to the serial
    engine's verdicts;
  - a settle FAILURE unwinds exactly the failing branch — sibling
    branches survive, settle, and the coin set is byte-identical to the
    serial engine's on the same feed;
  - reorg activation routes through the pipelined driver (serial
    undo-based disconnects + tree-speculative reconnects), metered as
    bcp_reorgs_total/bcp_reorg_depth, with zero serial fallbacks on
    linear segments;
  - the degradation ladder collapses tree -> single-branch -> serial
    under unwind pressure / an unhealthy ecdsa breaker and re-opens
    after sustained clean settles;
  - the BCH-lineage EDA/cw-144 DAA rules route by daa_height, and deep
    reorgs across the boundary converge digest-identically on both
    engines in both feed orders.

Marker: ``pipeline`` — ordered with the pipelined-IBD suite; tier-1,
JAX_PLATFORMS=cpu, backend="cpu" end to end.
"""

import dataclasses
import hashlib

import pytest

from bitcoincashplus_tpu.consensus.block import CBlockHeader
from bitcoincashplus_tpu.consensus.params import regtest_params
from bitcoincashplus_tpu.consensus.pow import (
    compact_to_target,
    eda_bits,
    get_next_work_required,
    get_next_work_required_cash,
    target_to_compact,
)
from bitcoincashplus_tpu.consensus.tx import COutPoint
from bitcoincashplus_tpu.ops import dispatch
from bitcoincashplus_tpu.util import devicewatch as dw
from bitcoincashplus_tpu.validation.chain import BlockStatus, CBlockIndex
from bitcoincashplus_tpu.validation.chainstate import BlockValidationError

from test_pipeline import (
    _coin_digest,
    _feed,
    _make_cs,
    _runway_blocks,
    _signed_spend,
    _tampered,
    _with_runway,
)
from test_validation import _hand_mine

pytestmark = pytest.mark.pipeline


def _runway_spendable(k: int):
    blocks, _t = _runway_blocks()
    cb = blocks[k].vtx[0]
    return COutPoint(cb.txid, 0), cb.vout[0].value


def _mk(cs, prev_hash, height, t, txs=(), extra=b""):
    tip_bits = regtest_params().genesis.header.bits
    return _hand_mine(prev_hash, height, t, tip_bits, txs, extra=extra)


class TestSpecTreeShape:
    def test_competing_tips_validate_concurrently(self):
        """Two children of the settled tip + one grandchild: the tree
        holds two live branches, the most-work branch settles, the loser
        drops un-externalized — and the serial engine lands on the
        identical tip + coin set for the same feed."""
        cs = _with_runway(depth=6)
        tip = cs.tip()
        t = cs.get_time()
        a1 = _mk(cs, tip.hash, tip.height + 1, t + 10, extra=b"A")
        b1 = _mk(cs, tip.hash, tip.height + 1, t + 10, extra=b"B")
        a2 = _mk(cs, a1.get_hash(), tip.height + 2, t + 20)
        for blk in (a1, b1, a2):
            cs.process_new_block_pipelined(blk)
        assert len(cs._spec) == 3
        assert len(cs._spec_roots()) == 2
        snap = cs.pipeline_snapshot()["tree"]
        assert snap["branches"] == 2
        assert snap["branches_live_max"] == 2
        assert cs.chain.tip().hash == a2.get_hash()
        assert cs.settled_tip() is tip  # nothing externalized yet

        cs.settle_horizon()
        assert not cs._spec
        assert cs.tip().hash == a2.get_hash()
        snap = cs.pipeline_snapshot()["tree"]
        assert snap["branch_drops"] == 1
        assert snap["dropped_blocks"] == 1
        # the loser was NOT marked invalid — it lost on work, and stays
        # a valid candidate for a future (real) reorg
        b1_idx = cs.block_index[b1.get_hash()]
        assert not (b1_idx.status & BlockStatus.FAILED_MASK)

        cs2 = _with_runway(1)
        _feed(cs2, (a1, b1, a2), pipelined=False)
        assert cs2.tip().hash == cs.tip().hash
        assert _coin_digest(cs2) == _coin_digest(cs)

    def test_mid_branch_fork(self):
        """A fork off a NON-root tree entry shares the prefix layers:
        one root, two leaves; settling the shared prefix promotes both
        children to competing roots and the work winner survives."""
        cs = _with_runway(depth=6)
        tip = cs.tip()
        t = cs.get_time()
        a1 = _mk(cs, tip.hash, tip.height + 1, t + 10)
        a2 = _mk(cs, a1.get_hash(), tip.height + 2, t + 20, extra=b"A")
        b2 = _mk(cs, a1.get_hash(), tip.height + 2, t + 20, extra=b"B")
        a3 = _mk(cs, a2.get_hash(), tip.height + 3, t + 30)
        for blk in (a1, a2, b2, a3):
            cs.process_new_block_pipelined(blk)
        assert len(cs._spec) == 4
        assert len(cs._spec_roots()) == 1
        assert cs.pipeline_snapshot()["tree"]["branches"] == 2
        cs.settle_horizon()
        assert cs.tip().hash == a3.get_hash()
        assert cs.pipeline_snapshot()["tree"]["branch_drops"] == 1

    def test_max_branches_declines_extra_forks(self):
        cs = _with_runway(depth=6)
        cs.max_branches = 2
        tip = cs.tip()
        t = cs.get_time()
        blocks = [_mk(cs, tip.hash, tip.height + 1, t + 10, extra=bytes([i]))
                  for i in range(3)]
        for blk in blocks:
            cs.process_new_block_pipelined(blk)
        # the third competing tip was declined (serial candidate path),
        # not speculatively connected
        assert len(cs._spec_roots()) == 2
        assert cs.pipeline_snapshot()["tree"]["branches"] == 2
        cs.settle_horizon()
        assert not cs._spec

    def test_watchdog_beats_per_speculative_connect(self):
        before = dw.WATCHDOG.beat_totals().get("pipeline", 0)
        cs = _with_runway(depth=6)
        tip = cs.tip()
        blk = _mk(cs, tip.hash, tip.height + 1, cs.get_time() + 10)
        cs.process_new_block_pipelined(blk)
        assert dw.WATCHDOG.beat_totals().get("pipeline", 0) > before
        cs.settle_horizon()


class TestBranchUnwindIsolation:
    def test_failing_branch_unwinds_siblings_survive(self):
        """The WINNING branch's root fails at settle: exactly that
        subtree unwinds, the sibling branch survives, settles, and the
        coin set matches the serial engine byte for byte."""
        cs = _with_runway(depth=6)
        tip = cs.tip()
        t = cs.get_time()
        op, value = _runway_spendable(0)
        bad = _tampered(_signed_spend(op, value), op)
        b1 = _mk(cs, tip.hash, tip.height + 1, t + 10, txs=(bad,))
        b2 = _mk(cs, b1.get_hash(), tip.height + 2, t + 20)
        a1 = _mk(cs, tip.hash, tip.height + 1, t + 10, extra=b"A")
        pre = _coin_digest(cs)
        for blk in (b1, b2, a1):
            cs.process_new_block_pipelined(blk)
        assert len(cs._spec_roots()) == 2
        # B has more work -> winning root -> its settle fails
        cs.settle_horizon()
        assert cs.tip().hash == a1.get_hash()
        ps = cs.pipeline_stats
        assert ps["unwinds"] == 1
        assert ps["unwound_blocks"] == 2  # exactly the B subtree
        assert cs.pipeline_stats["settled_blocks"] >= 1  # A still settled
        assert cs.block_index[b1.get_hash()].status & BlockStatus.FAILED_VALID
        assert cs.block_index[b2.get_hash()].status & BlockStatus.FAILED_CHILD
        a_idx = cs.block_index[a1.get_hash()]
        assert not (a_idx.status & BlockStatus.FAILED_MASK)

        # serial differential: same feed, same verdicts, same bytes
        cs2 = _with_runway(1)
        _feed(cs2, (b1, b2, a1), pipelined=False)
        assert cs2.tip().hash == a1.get_hash()
        assert _coin_digest(cs2) == _coin_digest(cs)
        # and unwinding B left the settled world pre-B + A only
        cs3 = _with_runway(1)
        _feed(cs3, (a1,), pipelined=False)
        assert _coin_digest(cs3) == _coin_digest(cs)
        assert _coin_digest(cs) != pre  # A externalized

    def test_unwind_streak_and_recovery(self):
        cs = _with_runway(depth=6)
        assert cs._collapse_level() == 0
        cs._unwind_streak = 2
        assert cs._collapse_level() == 1
        cs._unwind_streak = 4
        assert cs._collapse_level() == 2
        # 8 clean settles re-open the tree
        cs._unwind_streak = 2
        tip = cs.tip()
        t = cs.get_time()
        prev = tip.hash
        for i in range(8):
            blk = _mk(cs, prev, tip.height + 1 + i, t + 10 * (i + 1))
            cs.process_new_block_pipelined(blk)
            prev = blk.get_hash()
        cs.settle_horizon()
        assert cs._unwind_streak == 0
        assert cs._collapse_level() == 0

    def test_breaker_unhealthy_narrows_to_single_branch(self):
        dispatch.reset()
        try:
            br = dispatch.breaker("ecdsa")
            for _ in range(br.cfg.threshold):
                br.record_failure(RuntimeError("boom"))
            assert not br.healthy()
            cs = _with_runway(depth=6)
            assert cs._collapse_level() == 1
            tip = cs.tip()
            t = cs.get_time()
            a1 = _mk(cs, tip.hash, tip.height + 1, t + 10, extra=b"A")
            b1 = _mk(cs, tip.hash, tip.height + 1, t + 10, extra=b"B")
            cs.process_new_block_pipelined(a1)
            cs.process_new_block_pipelined(b1)
            # single-branch mode: the competitor was NOT speculated
            assert len(cs._spec_roots()) <= 1
            cs.settle_horizon()
            assert cs.tip().hash == a1.get_hash()
        finally:
            dispatch.reset()

    def test_serial_collapse_still_converges(self):
        cs = _with_runway(depth=6)
        cs._unwind_streak = 4  # forced serial mode
        tip = cs.tip()
        t = cs.get_time()
        a1 = _mk(cs, tip.hash, tip.height + 1, t + 10)
        a2 = _mk(cs, a1.get_hash(), tip.height + 2, t + 20)
        for blk in (a1, a2):
            cs.process_new_block_pipelined(blk)
        assert not cs._spec  # nothing speculative in serial mode
        assert cs.tip().hash == a2.get_hash()
        assert cs.pipeline_stats["degraded_connects"] >= 2
        cs2 = _with_runway(1)
        _feed(cs2, (a1, a2), pipelined=False)
        assert _coin_digest(cs2) == _coin_digest(cs)


class TestPipelinedReorg:
    def test_reorg_routes_through_tree(self):
        """A most-work branch forking BELOW the settled tip: settled
        blocks disconnect serially (metered as a reorg), the new path
        speculatively connects through tree layers, and the digest
        matches the serial engine — with zero linear serial fallbacks."""
        cs = _with_runway(depth=4)
        tip = cs.tip()
        t = cs.get_time()
        m1 = _mk(cs, tip.hash, tip.height + 1, t + 10, extra=b"M")
        m2 = _mk(cs, m1.get_hash(), tip.height + 2, t + 20, extra=b"M")
        for blk in (m1, m2):
            cs.process_new_block_pipelined(blk)
        cs.settle_horizon()
        assert cs.settled_tip().hash == m2.get_hash()

        fork = [
            _mk(cs, tip.hash, tip.height + 1, t + 10, extra=b"N"),
        ]
        for i in range(2, 6):
            fork.append(_mk(cs, fork[-1].get_hash(), tip.height + i,
                            t + 10 * i, extra=b"N"))
        for blk in fork:
            cs.process_new_block_pipelined(blk)
        cs.settle_horizon()
        assert cs.tip().hash == fork[-1].get_hash()
        ps = cs.pipeline_stats
        assert ps["reorgs"] == 1
        assert ps["reorg_depth_max"] == 2
        assert ps["serial_linear_fallbacks"] == 0

        cs2 = _with_runway(1)
        _feed(cs2, (m1, m2, *fork), pipelined=False)
        assert cs2.tip().hash == cs.tip().hash
        assert _coin_digest(cs2) == _coin_digest(cs)

    def test_activation_survives_backpressure_moving_the_anchor(self):
        """Inside the activation path loop a backpressure settle can
        advance the settled tip past the fork point mid-connect; the
        speculative connect must DECLINE (never base the layer on the
        moved coin state, never mark the valid block invalid) and the
        retry must still converge to the most-work branch with a digest
        identical to the serial engine's."""
        cs = _with_runway(depth=2)
        cs.max_branches = 1  # B-blocks may not enter the tree on feed
        tip = cs.tip()
        t = cs.get_time()
        a1 = _mk(cs, tip.hash, tip.height + 1, t + 10, extra=b"A")
        a2 = _mk(cs, a1.get_hash(), tip.height + 2, t + 20, extra=b"A")
        b = [_mk(cs, tip.hash, tip.height + 1, t + 10, extra=b"B")]
        for i in range(2, 5):
            b.append(_mk(cs, b[-1].get_hash(), tip.height + i,
                         t + 10 * i, extra=b"B"))
        blocks = (a1, a2, *b)
        for blk in blocks:
            cs.process_new_block_pipelined(blk)
        cs.settle_horizon()
        assert cs.tip().hash == b[-1].get_hash()
        for blk in blocks:  # nothing valid was marked invalid en route
            assert not (cs.block_index[blk.get_hash()].status
                        & BlockStatus.FAILED_MASK)
        cs2 = _with_runway(1)
        _feed(cs2, blocks, pipelined=False)
        assert cs2.tip().hash == cs.tip().hash
        assert _coin_digest(cs2) == _coin_digest(cs)

    def test_connect_declines_on_detached_parent(self):
        """Direct probe of the anchor guard: a speculative connect whose
        parent is neither the settled tip nor in-tree returns False
        without touching state."""
        cs = _with_runway(depth=4)
        tip = cs.tip()
        t = cs.get_time()
        a1 = _mk(cs, tip.hash, tip.height + 1, t + 10, extra=b"A")
        cs.process_new_block_pipelined(a1)
        cs.settle_horizon()  # settled tip is now a1
        orphan_parent = _mk(cs, tip.hash, tip.height + 1, t + 10,
                            extra=b"O")
        child = _mk(cs, orphan_parent.get_hash(), tip.height + 2, t + 20)
        cs.accept_block(orphan_parent)
        idx = cs.accept_block(child)
        pre = _coin_digest(cs)
        assert cs._connect_tip_speculative(idx, child) is False
        assert not (idx.status & BlockStatus.FAILED_MASK)
        assert not cs._spec
        assert _coin_digest(cs) == pre

    def test_packer_branch_attribution(self):
        """Competing branches carrying real signatures share the packer;
        the lane split is attributed per branch tag."""
        cs = _with_runway(depth=6)
        tip = cs.tip()
        t = cs.get_time()
        op_a, val_a = _runway_spendable(0)
        op_b, val_b = _runway_spendable(1)
        a1 = _mk(cs, tip.hash, tip.height + 1, t + 10,
                 txs=(_signed_spend(op_a, val_a),), extra=b"A")
        b1 = _mk(cs, tip.hash, tip.height + 1, t + 10,
                 txs=(_signed_spend(op_b, val_b),), extra=b"B")
        cs.process_new_block_pipelined(a1)
        cs.process_new_block_pipelined(b1)
        snap = cs._packer.snapshot()
        assert len(snap["branch_lanes"]) == 2
        assert all(v >= 1 for v in snap["branch_lanes"].values())
        cs.settle_horizon()
        assert cs._packer.snapshot()["pending_lanes"] == 0

    def test_packer_discard_attribution(self):
        """A losing branch whose lanes are still PARKED when it drops
        (the winner carried no signatures, so nothing forced a flush)
        has its discards attributed to its branch tag."""
        cs = _with_runway(depth=6)
        tip = cs.tip()
        t = cs.get_time()
        op_b, val_b = _runway_spendable(0)
        b1 = _mk(cs, tip.hash, tip.height + 1, t + 10,
                 txs=(_signed_spend(op_b, val_b),), extra=b"B")
        a1 = _mk(cs, tip.hash, tip.height + 1, t + 10, extra=b"A")
        a2 = _mk(cs, a1.get_hash(), tip.height + 2, t + 20)
        for blk in (b1, a1, a2):
            cs.process_new_block_pipelined(blk)
        cs.settle_horizon()
        assert cs.tip().hash == a2.get_hash()
        snap = cs._packer.snapshot()
        assert sum(snap["branch_discards"].values()) >= 1
        assert snap["pending_lanes"] == 0


# ---------------------------------------------------------------------------
# EDA / cw-144 DAA difficulty rules (consensus/pow.py)
# ---------------------------------------------------------------------------

_BITS = 0x1F00FFFF  # comfortably below the synthetic pow_limit


def _cash_consensus(daa_height: int = -1):
    base = regtest_params().consensus
    return dataclasses.replace(
        base, use_cash_daa=True, daa_height=daa_height,
        pow_no_retargeting=False,
        pow_allow_min_difficulty_blocks=False,
        pow_limit=(1 << 250) - 1,
    )


def _synth_chain(n: int, spacing: int = 600, bits: int = _BITS,
                 t0: int = 1_500_000_000):
    """A synthetic CBlockIndex chain (no blocks, headers only) — the
    difficulty rules read times/bits/work off the index alone."""
    prev = None
    for i in range(n):
        header = CBlockHeader(
            version=0x20000000,
            hash_prev_block=prev.hash if prev else b"\x00" * 32,
            hash_merkle_root=b"\x00" * 32,
            time=t0 + i * spacing, bits=bits, nonce=0,
        )
        h = hashlib.sha256(f"synth{i}".encode()).digest()
        prev = CBlockIndex(header, h, prev)
    return prev


class TestCashDifficulty:
    def test_eda_quiet_chain_carries_bits(self):
        params = _cash_consensus()
        tip = _synth_chain(20, spacing=600)
        assert get_next_work_required(tip, tip.time + 600, params) == _BITS

    def test_eda_fires_on_twelve_hour_mtp_gap(self):
        params = _cash_consensus()
        # 13h spacing: MTP(prev) - MTP(prev-6) = 6 * 13h > 12h
        tip = _synth_chain(20, spacing=13 * 3600)
        got = get_next_work_required(tip, tip.time + 600, params)
        target, _ = compact_to_target(_BITS)
        assert got == target_to_compact(target + (target >> 2))
        assert got == eda_bits(tip, params)
        # and the adjustment clamps at pow_limit
        near_limit = target_to_compact(params.pow_limit)
        tip2 = _synth_chain(20, spacing=13 * 3600, bits=near_limit)
        assert (get_next_work_required(tip2, tip2.time + 600, params)
                == near_limit)

    def test_eda_runs_on_min_difficulty_chains(self):
        """Regtest/testnet-shaped chains (pow_allow_min_difficulty) still
        RUN the EDA rule in the cash era — the 20-minute exception wins
        first, then eda_bits (which clamps at pow_limit, so a
        min-difficulty chain's bits never actually move). This is the
        path the fork-storm fleet's pre-DAA blocks take live."""
        params = dataclasses.replace(
            _cash_consensus(), pow_allow_min_difficulty_blocks=True)
        limit_bits = target_to_compact(params.pow_limit)
        tip = _synth_chain(20, spacing=600, bits=limit_bits)
        # quiet chain: EDA carries the previous bits (== the limit here)
        assert (get_next_work_required(tip, tip.time + 600, params)
                == eda_bits(tip, params) == limit_bits)
        # 20-minute gap: the min-difficulty exception answers first
        assert (get_next_work_required(tip, tip.time + 1201, params)
                == limit_bits)
        # and a sub-limit chain with a 13h gap still adjusts
        tip2 = _synth_chain(20, spacing=13 * 3600)
        got = get_next_work_required(tip2, tip2.time + 600, params)
        assert got == eda_bits(tip2, params) != _BITS

    def test_eda_walks_back_past_min_difficulty_blocks(self):
        """One 20-minute-gap min-difficulty block must not floor the rest
        of the interval: the EDA era anchors on the last REAL-difficulty
        block (the reference walk-back), so the next normally-paced
        block returns to _BITS instead of carrying pow_limit forward."""
        params = dataclasses.replace(
            _cash_consensus(), pow_allow_min_difficulty_blocks=True)
        limit_bits = target_to_compact(params.pow_limit)
        real = _synth_chain(20, spacing=600)  # bits=_BITS throughout
        mindiff_header = CBlockHeader(
            version=0x20000000, hash_prev_block=real.hash,
            hash_merkle_root=b"\x00" * 32,
            time=real.time + 1300, bits=limit_bits, nonce=0)
        tip = CBlockIndex(mindiff_header, b"\x77" * 32, real)
        assert (get_next_work_required(tip, tip.time + 600, params)
                == _BITS)

    def test_daa_routing_and_response(self):
        params = _cash_consensus(daa_height=0)
        tip = _synth_chain(150, spacing=600)
        got = get_next_work_required(tip, tip.time + 600, params)
        assert got == get_next_work_required_cash(tip, tip.time + 600,
                                                  params)
        # faster blocks -> more work demanded (smaller target)
        fast = _synth_chain(150, spacing=300)
        got_fast = get_next_work_required(fast, fast.time + 300, params)
        t_slow, _ = compact_to_target(got)
        t_fast, _ = compact_to_target(got_fast)
        assert t_fast < t_slow

    def test_boundary_routes_eda_below_daa_at(self):
        daa_h = 151
        params = _cash_consensus(daa_height=daa_h)
        tip = _synth_chain(daa_h - 1, spacing=13 * 3600)  # next height = daa_h - 1? no:
        # tip height = daa_h - 2, next block height = daa_h - 1 < daa_h: EDA
        assert tip.height == daa_h - 2
        assert (get_next_work_required(tip, tip.time + 600, params)
                == eda_bits(tip, params))
        tip2 = _synth_chain(daa_h + 1, spacing=600)  # next height > daa_h
        assert (get_next_work_required(tip2, tip2.time + 600, params)
                == get_next_work_required_cash(tip2, tip2.time + 600,
                                               params))


class TestDeepReorgAcrossDaaBoundary:
    """Deep reorg crossing the EDA->DAA switch on a regtest-shaped chain
    (bits pinned at the limit on both sides of the boundary, so the
    cached runway replays — the rules still RUN and must agree)."""

    DAA_H = 107  # runway is 104; the reorg crosses this

    def _cs(self, depth):
        cs = _with_runway(depth)
        cs.params = dataclasses.replace(
            cs.params,
            consensus=dataclasses.replace(
                cs.params.consensus, use_cash_daa=True,
                daa_height=self.DAA_H))
        return cs

    def _sequences(self):
        cs = self._cs(1)
        tip = cs.tip()
        t = cs.get_time()
        main = [_mk(cs, tip.hash, tip.height + 1, t + 10, extra=b"M")]
        for i in range(2, 5):  # heights 105..108: crosses 107
            main.append(_mk(cs, main[-1].get_hash(), tip.height + i,
                            t + 10 * i, extra=b"M"))
        fork = [_mk(cs, tip.hash, tip.height + 1, t + 10, extra=b"F")]
        for i in range(2, 7):  # heights 105..110: deeper, crosses 107
            fork.append(_mk(cs, fork[-1].get_hash(), tip.height + i,
                            t + 10 * i, extra=b"F"))
        return main, fork

    def test_both_engines_both_orders_identical(self):
        main, fork = self._sequences()
        outcomes = set()
        for order in ((*main, *fork), (*fork, *main)):
            for depth in (1, 4):
                cs = self._cs(depth)
                _feed(cs, order, pipelined=(depth > 1))
                outcomes.add((cs.tip().hash, _coin_digest(cs)))
                assert cs.tip().hash == fork[-1].get_hash()
        assert len(outcomes) == 1

    def test_pipelined_reorg_metrics_across_boundary(self):
        main, fork = self._sequences()
        cs = self._cs(4)
        _feed(cs, main, pipelined=True)
        _feed(cs, fork, pipelined=True)
        assert cs.tip().hash == fork[-1].get_hash()
        assert cs.pipeline_stats["reorgs"] == 1
        assert cs.pipeline_stats["reorg_depth_max"] == 4
        assert cs.pipeline_stats["serial_linear_fallbacks"] == 0


@pytest.mark.slow
class TestUnwindStormSoak:
    def test_repeated_deep_unwinds_with_ecdsa_faults(self, fault_harness):
        """The unwind storm: K-deep bad-signature branches over and over
        with device faults injected at the ecdsa site. The node must
        never wedge, the ladder must engage and recover, and the final
        chain must match a fault-free serial control byte for byte."""
        fault_harness("fail-rate", ops="ecdsa", rate="0.3", seed="9")
        cs = _with_runway(depth=5)
        fed: list = []

        def storm_round(round_i: int, with_bad: bool):
            tip = cs.settled_tip()
            t = cs.get_time()
            blocks = []
            if with_bad:
                op, value = _runway_spendable(round_i % 4)
                bad = _tampered(_signed_spend(op, value), op)
                b1 = _mk(cs, tip.hash, tip.height + 1, t + 10, txs=(bad,),
                         extra=b"bad%d" % round_i)
                b2 = _mk(cs, b1.get_hash(), tip.height + 2, t + 20)
                b3 = _mk(cs, b2.get_hash(), tip.height + 3, t + 30)
                blocks += [b1, b2, b3]
            g1 = _mk(cs, tip.hash, tip.height + 1, t + 10,
                     extra=b"good%d" % round_i)
            blocks.append(g1)
            for blk in blocks:
                try:
                    cs.process_new_block_pipelined(blk)
                except BlockValidationError:
                    pass  # bad ancestry noticed at accept — fine
            cs.settle_horizon()
            assert cs.tip().hash == g1.get_hash(), round_i
            fed.extend(blocks)

        # phase 1: the storm — every round converges on the good chain
        # while the ladder collapses tree -> single-branch -> serial
        for round_i in range(6):
            storm_round(round_i, with_bad=True)
        assert cs.pipeline_stats["unwinds"] >= 2
        assert cs._unwind_streak >= 4  # fully collapsed at some point
        assert cs._collapse_level() == 2
        assert cs.pipeline_stats["degraded_connects"] >= 1

        # phase 2: the storm passes — sustained clean activations re-open
        # the ladder and the tree speculates again
        for round_i in range(6, 16):
            storm_round(round_i, with_bad=False)
        assert cs._collapse_level() == 0
        tip = cs.settled_tip()
        t = cs.get_time()
        f1 = _mk(cs, tip.hash, tip.height + 1, t + 10, extra=b"f1")
        f2 = _mk(cs, tip.hash, tip.height + 1, t + 10, extra=b"f2")
        cs.process_new_block_pipelined(f1)
        cs.process_new_block_pipelined(f2)
        assert len(cs._spec_roots()) == 2  # the tree is open for business
        cs.settle_horizon()
        fed.extend([f1, f2])

        # fault-free serial control over the same feed
        import os

        for key in [k for k in os.environ if k.startswith("BCP_FAULT")]:
            os.environ.pop(key, None)
        from bitcoincashplus_tpu.util import faults

        faults.INJECTOR.reload()
        cs2 = _with_runway(1)
        for blk in fed:
            try:
                cs2.process_new_block(blk)
            except BlockValidationError:
                pass
        assert cs2.tip().hash == cs.tip().hash
        assert _coin_digest(cs2) == _coin_digest(cs)
