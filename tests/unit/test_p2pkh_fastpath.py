"""P2PKH fast-path differential tests: for every input shape the template
accepts, the fast verify must produce EXACTLY the generic interpreter's
outcome — same success, same ScriptError code — and the template detector
must reject anything whose semantics it cannot reproduce."""

import random

import pytest

from bitcoincashplus_tpu.consensus.tx import COutPoint, CTransaction, CTxIn, CTxOut
from bitcoincashplus_tpu.crypto import secp256k1 as o
from bitcoincashplus_tpu.script import script as S
from bitcoincashplus_tpu.script.interpreter import (
    SCRIPT_ENABLE_SIGHASH_FORKID,
    SCRIPT_VERIFY_LOW_S,
    SCRIPT_VERIFY_NULLFAIL,
    SCRIPT_VERIFY_P2SH,
    SCRIPT_VERIFY_STRICTENC,
    ScriptError,
    TransactionSignatureChecker,
    VerifyScript,
)
from bitcoincashplus_tpu.script.sighash import SIGHASH_ALL, SIGHASH_FORKID
from bitcoincashplus_tpu.validation.scriptcheck import (
    _p2pkh_fast_verify,
    _p2pkh_template,
)
from bitcoincashplus_tpu.wallet.keys import CKey
from bitcoincashplus_tpu.wallet.signing import make_signature

KEY = CKey(0xD00D)
KEY2 = CKey(0xBEEF)
FLAGS = (SCRIPT_VERIFY_P2SH | SCRIPT_VERIFY_STRICTENC | SCRIPT_VERIFY_LOW_S
         | SCRIPT_VERIFY_NULLFAIL | SCRIPT_ENABLE_SIGHASH_FORKID)
AMOUNT = 50_000_000


def _spend(spk: bytes, script_sig: bytes) -> CTransaction:
    tx = CTransaction(
        version=2,
        vin=(CTxIn(COutPoint(b"\x55" * 32, 0), script_sig, 0xFFFFFFFE),),
        vout=(CTxOut(AMOUNT - 1000, b"\x51"),),
    )
    return tx


def _outcome_generic(spk, script_sig, flags=FLAGS):
    tx = _spend(spk, script_sig)
    try:
        VerifyScript(script_sig, spk, flags,
                     TransactionSignatureChecker(tx, 0, AMOUNT))
        return "OK"
    except ScriptError as e:
        return e.code


def _outcome_fast(spk, script_sig, flags=FLAGS):
    tpl = _p2pkh_template(script_sig, spk)
    if tpl is None:
        return None  # template rejected: generic path would be used
    tx = _spend(spk, script_sig)
    try:
        _p2pkh_fast_verify(tpl[0], tpl[1], spk, flags,
                           TransactionSignatureChecker(tx, 0, AMOUNT))
        return "OK"
    except ScriptError as e:
        return e.code


def _signed_sig(key, spk, script_sig_placeholder=b"", flags=FLAGS,
                hashtype=SIGHASH_ALL | SIGHASH_FORKID):
    tx = _spend(spk, script_sig_placeholder)
    return make_signature(key, spk, tx, 0, AMOUNT, hashtype & 0xBF,
                          enable_forkid=bool(hashtype & SIGHASH_FORKID))


def _push(b: bytes) -> bytes:
    return S.push_data_raw(b)


def test_differential_matrix():
    spk = KEY.p2pkh_script()
    sig = _signed_sig(KEY, spk)
    r, s = o.sig_der_decode(sig[:-1])
    high_s = o.sig_der_encode(r, o.N - s) + sig[-1:]
    wrong_key_sig = _signed_sig(KEY2, spk)
    legacy_sig = _signed_sig(KEY, spk, hashtype=SIGHASH_ALL)
    pt = o.pubkey_parse(KEY.pubkey)
    hybrid = bytes([6 + (pt[1] & 1)]) + pt[0].to_bytes(32, "big") + \
        pt[1].to_bytes(32, "big")

    cases = [
        _push(sig) + _push(KEY.pubkey),                  # valid
        _push(wrong_key_sig) + _push(KEY.pubkey),        # wrong key
        _push(sig) + _push(KEY2.pubkey),                 # wrong pkh
        _push(high_s) + _push(KEY.pubkey),               # high-S vs LOW_S
        _push(legacy_sig) + _push(KEY.pubkey),           # must-use-forkid
        _push(sig[:-1]) + _push(KEY.pubkey),             # hashtype missing
        _push(sig[:10]) + _push(KEY.pubkey),             # truncated DER
        _push(b"") + _push(KEY.pubkey),                  # empty sig (OP_0)
        b"\x00" + _push(KEY.pubkey),                     # OP_0 empty sig
        _push(sig) + _push(hybrid),                      # hybrid pubkey
        _push(sig) + _push(KEY.pubkey[:-1]),             # truncated pubkey
        _push(b"\x30\x06\x02\x01\x01\x02\x01\x01\x01")
        + _push(KEY.pubkey),                             # garbage DER-ish
    ]
    for i, ss in enumerate(cases):
        generic = _outcome_generic(spk, ss)
        fast = _outcome_fast(spk, ss)
        assert fast is not None, f"case {i}: template should accept"
        assert fast == generic, f"case {i}: fast={fast} generic={generic}"

    # without NULLFAIL, a failing sig ends as eval-false on both paths
    flags2 = FLAGS & ~SCRIPT_VERIFY_NULLFAIL
    assert _outcome_generic(spk, cases[1], flags2) == \
        _outcome_fast(spk, cases[1], flags2) == "eval-false"

    # and without STRICTENC the hybrid pubkey verifies on both paths
    flags3 = (SCRIPT_VERIFY_NULLFAIL | SCRIPT_ENABLE_SIGHASH_FORKID)
    hspk = spk  # hash160 mismatch for hybrid encoding vs compressed key
    got_g = _outcome_generic(hspk, cases[9], flags3)
    got_f = _outcome_fast(hspk, cases[9], flags3)
    assert got_g == got_f  # equalverify (hash of hybrid form differs)


def test_template_rejects_nonstandard_shapes():
    spk = KEY.p2pkh_script()
    sig = _signed_sig(KEY, spk)
    ok_ss = _push(sig) + _push(KEY.pubkey)
    # wrong spk shapes
    assert _p2pkh_template(ok_ss, spk[:-1]) is None
    assert _p2pkh_template(ok_ss, b"\x51" * 25) is None
    assert _p2pkh_template(ok_ss, S.p2sh_script(b"\x11" * 20)) is None
    # trailing bytes, extra push, PUSHDATA1 form, non-push opcode
    assert _p2pkh_template(ok_ss + b"\x51", spk) is None
    assert _p2pkh_template(ok_ss + _push(b"x"), spk) is None
    pd1 = b"\x4c" + bytes([len(sig)]) + sig + _push(KEY.pubkey)
    assert _p2pkh_template(pd1, spk) is None
    assert _p2pkh_template(b"\x76" + ok_ss, spk) is None
    # truncated push length
    assert _p2pkh_template(b"\x4b\x01", spk) is None
    assert _p2pkh_template(b"", spk) is None


def test_fastpath_randomized_mutations():
    rng = random.Random(99)
    spk = KEY.p2pkh_script()
    sig = _signed_sig(KEY, spk)
    base = _push(sig) + _push(KEY.pubkey)
    for _ in range(120):
        ss = bytearray(base)
        pos = rng.randrange(len(ss))
        ss[pos] ^= 1 << rng.randrange(8)
        ss = bytes(ss)
        fast = _outcome_fast(spk, ss)
        if fast is None:
            continue  # template rejected the mutation: generic path used
        assert fast == _outcome_generic(spk, ss), ss.hex()
