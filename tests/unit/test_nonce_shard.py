"""Multi-chip nonce-shard tests on the virtual 8-device CPU mesh
(conftest sets xla_force_host_platform_device_count=8)."""

import jax
import numpy as np
import pytest

from bitcoincashplus_tpu.consensus.block import CBlockHeader
from bitcoincashplus_tpu.consensus.pow import compact_to_target
from bitcoincashplus_tpu.parallel.nonce_shard import sweep_header_sharded

rng = np.random.default_rng(77)


def _regtest_header():
    return CBlockHeader(
        version=0x20000000,
        hash_prev_block=rng.integers(0, 256, 32, dtype=np.uint8).tobytes(),
        hash_merkle_root=rng.integers(0, 256, 32, dtype=np.uint8).tobytes(),
        time=1_300_000_000,
        bits=0x207FFFFF,
        nonce=0,
    )


def test_mesh_has_8_devices():
    from bitcoincashplus_tpu.parallel.mesh import local_devices

    assert len(local_devices()) == 8


def test_sharded_sweep_finds_valid_nonce():
    hdr = _regtest_header()
    target, _ = compact_to_target(hdr.bits)
    nonce, hashes = sweep_header_sharded(
        hdr.serialize(), target, max_nonces=1 << 16, tile=1 << 12
    )
    assert nonce is not None
    assert int.from_bytes(hdr.with_nonce(nonce).get_hash(), "little") <= target
    assert hashes > 0


def test_sharded_sweep_matches_single_chip_result():
    """The globally-reduced winner must be a genuine hit; with a regtest
    target chip 0 nearly always hits in its first tile, making the reduced
    min equal the single-chip first hit."""
    from bitcoincashplus_tpu.ops.miner import sweep_header

    hdr = _regtest_header()
    target, _ = compact_to_target(hdr.bits)
    n_multi, _ = sweep_header_sharded(
        hdr.serialize(), target, max_nonces=1 << 16, tile=1 << 12
    )
    n_single, _ = sweep_header(
        hdr.serialize(), target, tile=1 << 12, max_nonces=1 << 13
    )
    assert n_single is not None and n_multi is not None
    assert n_multi == n_single


def test_mine_block_with_sharded_sweep():
    """mine_block's documented sweep-injection hook must accept the sharded
    sweep (regression: kwarg contract mismatch)."""
    from bitcoincashplus_tpu.consensus.params import regtest_params
    from bitcoincashplus_tpu.mining.assembler import BlockAssembler
    from bitcoincashplus_tpu.mining.generate import mine_block
    from bitcoincashplus_tpu.store.blockstore import MemoryBlockStore
    from bitcoincashplus_tpu.validation.chainstate import ChainstateManager
    from bitcoincashplus_tpu.validation.coins import MemoryCoinsView

    cs = ChainstateManager(
        regtest_params(), MemoryCoinsView(), MemoryBlockStore(),
        get_time=lambda: 1_600_000_000,
    )
    block = mine_block(
        BlockAssembler(cs), b"\x51", tile=1 << 12, sweep=sweep_header_sharded
    )
    assert block is not None
    cs.process_new_block(block)
    assert cs.chain.height() == 1


def test_sharded_not_found():
    hdr = _regtest_header()
    nonce, hashes = sweep_header_sharded(
        hdr.serialize(), target=0, max_nonces=1 << 15, tile=1 << 12
    )
    assert nonce is None
    assert hashes == 8 * (1 << 12)
