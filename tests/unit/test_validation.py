"""Validation engine tests — the coins/connect/reorg coverage the reference
keeps in coins_tests.cpp / validation_block_tests.cpp (SURVEY.md §5.1)."""

import numpy as np
import pytest

from bitcoincashplus_tpu.consensus.block import CBlock, CBlockHeader
from bitcoincashplus_tpu.consensus.merkle import block_merkle_root
from bitcoincashplus_tpu.consensus.params import get_block_subsidy, regtest_params
from bitcoincashplus_tpu.consensus.pow import compact_to_target
from bitcoincashplus_tpu.consensus.tx import COutPoint, CTransaction, CTxIn, CTxOut
from bitcoincashplus_tpu.mining.assembler import (
    BlockAssembler,
    bip34_coinbase_script_sig,
)
from bitcoincashplus_tpu.mining.generate import generate_blocks, mine_block
from bitcoincashplus_tpu.ops.miner import sweep_header
from bitcoincashplus_tpu.store.blockstore import MemoryBlockStore
from bitcoincashplus_tpu.validation.chainstate import (
    BlockValidationError,
    ChainstateManager,
)
from bitcoincashplus_tpu.validation.coins import BlockUndo, Coin, MemoryCoinsView

SPK_A = bytes.fromhex("76a914") + b"\x11" * 20 + bytes.fromhex("88ac")  # P2PKH-shaped
SPK_B = bytes.fromhex("76a914") + b"\x22" * 20 + bytes.fromhex("88ac")

TILE = 1 << 12


@pytest.fixture
def chainstate():
    params = regtest_params()
    t = [1_600_000_000]

    def fake_time():
        t[0] += 60
        return t[0]

    return ChainstateManager(
        params, MemoryCoinsView(), MemoryBlockStore(), script_verifier=None,
        get_time=fake_time,
    )


def _mine_on(chainstate, n, spk=SPK_A):
    return generate_blocks(chainstate, spk, n, tile=TILE)


def _hand_mine(prev_hash, height, block_time, bits, txs, spk=SPK_B, extra=b""):
    """Build + mine a block directly (the blocktools.create_block pattern of
    the reference's functional framework — lets tests craft forks/invalid
    blocks without the assembler's safety rails)."""
    fees = 0
    coinbase = CTransaction(
        version=1,
        vin=(CTxIn(COutPoint(), bip34_coinbase_script_sig(height) + extra, 0xFFFFFFFF),),
        vout=(CTxOut(fees + get_block_subsidy(height, regtest_params().consensus), spk),),
    )
    vtx = (coinbase, *txs)

    class V:  # duck-typed for block_merkle_root
        pass

    v = V()
    v.vtx = vtx
    root, _ = block_merkle_root(v)
    header = CBlockHeader(
        version=0x20000000, hash_prev_block=prev_hash, hash_merkle_root=root,
        time=block_time, bits=bits, nonce=0,
    )
    target, _ = compact_to_target(bits)
    nonce, _ = sweep_header(header.serialize(), target, tile=TILE)
    assert nonce is not None
    return CBlock(header.with_nonce(nonce), vtx)


class TestMiningSlice:
    def test_generate_grows_chain(self, chainstate):
        hashes = _mine_on(chainstate, 3)
        assert len(hashes) == 3
        assert chainstate.chain.height() == 3
        assert chainstate.tip().hash == hashes[-1]
        # every block connects and spends nothing; UTXO grows by 1/block
        assert chainstate.coins.best_block() == hashes[-1]

    def test_subsidy_paid(self, chainstate):
        _mine_on(chainstate, 1)
        tip = chainstate.tip()
        block = chainstate.get_block(tip.hash)
        assert block.vtx[0].total_output_value() == 50 * 100_000_000
        coin = chainstate.coins.get_coin(COutPoint(block.vtx[0].txid, 0))
        assert coin is not None and coin.is_coinbase and coin.height == 1

    def test_connected_blocks_pass_pow(self, chainstate):
        hashes = _mine_on(chainstate, 2)
        params = chainstate.params
        for h in hashes:
            block = chainstate.get_block(h)
            target, _ = compact_to_target(block.header.bits)
            assert int.from_bytes(block.get_hash(), "little") <= target

    def test_bip34_height_in_coinbase(self, chainstate):
        _mine_on(chainstate, 2)
        block = chainstate.get_block(chainstate.tip().hash)
        sig = block.vtx[0].vin[0].script_sig
        # CScript() << 2 emits the OP_2 single-byte opcode (reference
        # CScriptNum push semantics; ADVICE r1 low finding)
        assert sig[0] == 0x52
        _mine_on(chainstate, 15)
        block = chainstate.get_block(chainstate.tip().hash)
        assert block.vtx[0].vin[0].script_sig[:2] == bytes([1, 17])  # 17 > OP_16


class TestRejection:
    def test_bad_pow_rejected(self, chainstate):
        tip = chainstate.tip()
        blk = _hand_mine(tip.hash, 1, 1_600_000_100, tip.bits, ())
        bad = CBlock(blk.header.with_nonce((blk.header.nonce + 1) % (1 << 32)), blk.vtx)
        target, _ = compact_to_target(bad.header.bits)
        if int.from_bytes(bad.get_hash(), "little") <= target:
            pytest.skip("nonce+1 also satisfies regtest target (rare)")
        with pytest.raises(BlockValidationError, match="high-hash"):
            chainstate.process_new_block(bad)

    def test_bad_merkle_rejected(self, chainstate):
        tip = chainstate.tip()
        blk = _hand_mine(tip.hash, 1, 1_600_000_100, tip.bits, ())
        from dataclasses import replace

        hdr = replace(blk.header, hash_merkle_root=b"\x42" * 32)
        target, _ = compact_to_target(hdr.bits)
        nonce, _ = sweep_header(hdr.serialize(), target, tile=TILE)
        bad = CBlock(hdr.with_nonce(nonce), blk.vtx)
        with pytest.raises(BlockValidationError, match="bad-txnmrklroot"):
            chainstate.process_new_block(bad)

    def test_unknown_parent_rejected(self, chainstate):
        blk = _hand_mine(b"\x99" * 32, 1, 1_600_000_100, 0x207FFFFF, ())
        with pytest.raises(BlockValidationError, match="prev-blk-not-found"):
            chainstate.process_new_block(blk)

    def test_excess_subsidy_rejected(self, chainstate):
        tip = chainstate.tip()
        coinbase = CTransaction(
            version=1,
            vin=(CTxIn(COutPoint(), bip34_coinbase_script_sig(1), 0xFFFFFFFF),),
            vout=(CTxOut(51 * 100_000_000, SPK_B),),  # 1 BCH too much
        )

        class V:
            pass

        v = V()
        v.vtx = (coinbase,)
        root, _ = block_merkle_root(v)
        header = CBlockHeader(
            version=0x20000000, hash_prev_block=tip.hash, hash_merkle_root=root,
            time=1_600_000_100, bits=tip.bits, nonce=0,
        )
        target, _ = compact_to_target(tip.bits)
        nonce, _ = sweep_header(header.serialize(), target, tile=TILE)
        bad = CBlock(header.with_nonce(nonce), (coinbase,))
        chainstate.process_new_block(bad)  # accepted to tree...
        # ...but ConnectBlock must have refused it: tip unchanged
        assert chainstate.chain.height() == 0

    def test_failed_connect_preserves_unflushed_edits(self, chainstate):
        """Regression: a failing ConnectBlock must not wipe earlier blocks'
        unflushed coin edits (scratch-layer isolation)."""
        hashes = _mine_on(chainstate, 2)
        blk1 = chainstate.get_block(hashes[0])
        tip = chainstate.tip()
        # invalid: spends a coinbase prematurely
        spend = CTransaction(
            vin=(CTxIn(COutPoint(blk1.vtx[0].txid, 0), b"\x51"),),
            vout=(CTxOut(50 * 100_000_000, SPK_B),),
        )
        bad = _hand_mine(tip.hash, 3, chainstate.get_time() + 10, tip.bits, (spend,))
        chainstate.process_new_block(bad)
        assert chainstate.tip() is tip  # rejected
        # earlier unflushed coinbase coins still visible and flushable
        for h in hashes:
            blk = chainstate.get_block(h)
            assert chainstate.coins.get_coin(COutPoint(blk.vtx[0].txid, 0)) is not None
        chainstate.flush()
        assert len(chainstate.coins.base) == 3  # genesis + 2 coinbases

    def test_premature_coinbase_spend_rejected(self, chainstate):
        _mine_on(chainstate, 2)
        tip = chainstate.tip()
        blk1 = chainstate.get_block(chainstate.chain[1].hash)
        spend = CTransaction(
            vin=(CTxIn(COutPoint(blk1.vtx[0].txid, 0), b"\x51"),),
            vout=(CTxOut(50 * 100_000_000, SPK_B),),
        )
        bad = _hand_mine(tip.hash, 3, chainstate.get_time() + 10, tip.bits, (spend,))
        chainstate.process_new_block(bad)
        assert chainstate.tip().hash != bad.get_hash()  # rejected at connect


class TestSpendAndReorg:
    def test_spend_matured_coinbase(self, chainstate):
        _mine_on(chainstate, 101)  # block 1's coinbase now matured
        blk1 = chainstate.get_block(chainstate.chain[1].hash)
        cb_out = COutPoint(blk1.vtx[0].txid, 0)
        spend = CTransaction(
            vin=(CTxIn(cb_out, b"\x51"),),
            vout=(CTxOut(49 * 100_000_000, SPK_B),),  # 1 BCH fee
        )
        tip = chainstate.tip()
        blk = _hand_mine(tip.hash, 102, chainstate.get_time() + 10, tip.bits, (spend,))
        chainstate.process_new_block(blk)
        assert chainstate.chain.height() == 102
        assert chainstate.coins.get_coin(cb_out) is None  # spent
        assert chainstate.coins.get_coin(COutPoint(spend.txid, 0)) is not None

    def test_reorg_to_longer_chain(self, chainstate):
        _mine_on(chainstate, 2)
        fork_base = chainstate.chain[1]
        old_tip = chainstate.tip()
        # build a 2-block fork off height 1 -> total height 3 beats height 2
        t0 = chainstate.get_time() + 100
        f1 = _hand_mine(fork_base.hash, 2, t0, fork_base.bits, ())
        f2 = _hand_mine(f1.get_hash(), 3, t0 + 60, fork_base.bits, ())
        chainstate.process_new_block(f1)
        assert chainstate.tip() is old_tip  # equal work: first-seen wins
        chainstate.process_new_block(f2)
        assert chainstate.chain.height() == 3
        assert chainstate.tip().hash == f2.get_hash()
        # the orphaned block-2 coinbase coin must be gone from the UTXO
        orphan = chainstate.get_block(old_tip.hash)
        assert chainstate.coins.get_coin(COutPoint(orphan.vtx[0].txid, 0)) is None
        # and the fork's coinbases present
        assert chainstate.coins.get_coin(COutPoint(f1.vtx[0].txid, 0)) is not None

    def test_reorg_back_and_forth_utxo_consistent(self, chainstate):
        _mine_on(chainstate, 1)
        base = chainstate.tip()
        t0 = chainstate.get_time() + 100
        a2 = _hand_mine(base.hash, 2, t0, base.bits, (), extra=b"\x01")
        chainstate.process_new_block(a2)
        b2 = _hand_mine(base.hash, 2, t0 + 1, base.bits, (), extra=b"\x02")
        b3 = _hand_mine(b2.get_hash(), 3, t0 + 61, base.bits, (), extra=b"\x02")
        chainstate.process_new_block(b2)
        chainstate.process_new_block(b3)
        assert chainstate.tip().hash == b3.get_hash()
        # flush + count: genesis + h1 + b2 + b3 coinbases = 4 coins
        chainstate.flush()
        assert len(chainstate.coins.base) == 4

    def test_invalidate_block(self, chainstate):
        _mine_on(chainstate, 3)
        h2 = chainstate.chain[2]
        chainstate.invalidate_block(h2)
        assert chainstate.chain.height() == 1
        # re-mining extends from height 1 again
        _mine_on(chainstate, 1)
        assert chainstate.chain.height() == 2


class TestUndoRoundtrip:
    def test_blockundo_serialization(self):
        coin = Coin(CTxOut(12345, b"\x76\xa9\x14" + b"\x33" * 20 + b"\x88\xac"), 7, False)
        cb = Coin(CTxOut(50 * 100_000_000, b"\x51"), 1, True)
        undo = BlockUndo([])
        from bitcoincashplus_tpu.validation.coins import TxUndo

        undo.vtxundo = [TxUndo([coin, cb]), TxUndo([coin])]
        rt = BlockUndo.from_bytes(undo.serialize())
        assert rt.vtxundo[0].prevouts[0] == coin
        assert rt.vtxundo[0].prevouts[1] == cb
        assert rt.vtxundo[1].prevouts == [coin]


class TestPreciousBlock:
    def test_precious_wins_equal_work_tie(self, chainstate):
        """PreciousBlock semantics: first-seen wins an equal-work race until
        preciousblock re-ranks the competitor; precious can flip back too."""
        _mine_on(chainstate, 1)
        tip = chainstate.tip()
        t = chainstate.get_time()
        blk_a = _hand_mine(tip.hash, tip.height + 1, t + 10, tip.bits, ())
        blk_b = _hand_mine(tip.hash, tip.height + 1, t + 11, tip.bits, ())
        assert blk_a.get_hash() != blk_b.get_hash()
        chainstate.process_new_block(blk_a)
        chainstate.process_new_block(blk_b)
        assert chainstate.tip().hash == blk_a.get_hash()  # first seen

        idx_b = chainstate.block_index[blk_b.get_hash()]
        chainstate.precious_block(idx_b)
        assert chainstate.tip().hash == blk_b.get_hash()

        idx_a = chainstate.block_index[blk_a.get_hash()]
        chainstate.precious_block(idx_a)
        assert chainstate.tip().hash == blk_a.get_hash()

        # precious on the active tip is a no-op
        chainstate.precious_block(idx_a)
        assert chainstate.tip().hash == blk_a.get_hash()
