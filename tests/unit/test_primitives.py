"""Tx/block primitive tests (reference model: src/test/transaction_tests.cpp
round-trip parts, src/test/uint256_tests.cpp)."""

import pytest
pytest.importorskip("hypothesis")  # property tests need the optional test extra
from hypothesis import given
from hypothesis import strategies as st

from bitcoincashplus_tpu.consensus.block import CBlock, CBlockHeader
from bitcoincashplus_tpu.consensus.serialize import ByteReader, DeserializationError
from bitcoincashplus_tpu.consensus.tx import COutPoint, CTransaction, CTxIn, CTxOut

# hypothesis strategies for consensus objects
outpoints = st.builds(
    COutPoint, st.binary(min_size=32, max_size=32), st.integers(0, 0xFFFFFFFF)
)
txins = st.builds(
    CTxIn, outpoints, st.binary(max_size=100), st.integers(0, 0xFFFFFFFF)
)
txouts = st.builds(
    CTxOut, st.integers(-1, 21_000_000 * 100_000_000), st.binary(max_size=100)
)
txs = st.builds(
    CTransaction,
    st.integers(-(2**31), 2**31 - 1),
    st.lists(txins, max_size=5).map(tuple),
    st.lists(txouts, max_size=5).map(tuple),
    st.integers(0, 0xFFFFFFFF),
)
headers = st.builds(
    CBlockHeader,
    st.integers(-(2**31), 2**31 - 1),
    st.binary(min_size=32, max_size=32),
    st.binary(min_size=32, max_size=32),
    st.integers(0, 0xFFFFFFFF),
    st.integers(0, 0xFFFFFFFF),
    st.integers(0, 0xFFFFFFFF),
)


class TestRoundTrip:
    @given(txs)
    def test_tx(self, tx):
        assert CTransaction.from_bytes(tx.serialize()) == tx

    @given(headers)
    def test_header(self, hdr):
        assert CBlockHeader.from_bytes(hdr.serialize()) == hdr
        assert len(hdr.serialize()) == 80

    @given(st.lists(txs, min_size=1, max_size=4))
    def test_block(self, vtx):
        blk = CBlock(CBlockHeader(), tuple(vtx))
        rt = CBlock.from_bytes(blk.serialize())
        assert rt.header == blk.header
        assert [t.txid for t in rt.vtx] == [t.txid for t in blk.vtx]


class TestKnownSerialization:
    def test_genesis_coinbase_txid(self):
        from bitcoincashplus_tpu.consensus.params import main_params

        cb = main_params().genesis.vtx[0]
        assert cb.txid_hex == (
            "4a5e1e4baab89f3a32518a88c31bc87f618f76673e2cc77ab2127b7afdeda33b"
        )
        assert cb.is_coinbase()

    def test_genesis_block_size(self):
        from bitcoincashplus_tpu.consensus.params import main_params

        assert main_params().genesis.size() == 285  # canonical genesis size

    def test_trailing_bytes_rejected(self):
        from bitcoincashplus_tpu.consensus.params import main_params

        raw = main_params().genesis.vtx[0].serialize()
        with pytest.raises(DeserializationError):
            CTransaction.from_bytes(raw + b"\x00")

    def test_truncated_rejected(self):
        from bitcoincashplus_tpu.consensus.params import main_params

        raw = main_params().genesis.serialize()
        with pytest.raises(DeserializationError):
            CBlock.from_bytes(raw[:-1])
