"""Differential tests for the parallel-form field ops (the TPU device path
of ops/secp256k1: _pcarry_round/_fold_parallel/_exact_norm20 and the
parallel f_mul/f_carry/f_is_zero) against the Python-int oracle. Runs the
ops EAGERLY with BCP_SECP_PARALLEL=1 — no XLA compile, so these stay in the
default CPU suite."""

import numpy as np
import jax.numpy as jnp
import pytest

import bitcoincashplus_tpu.ops.secp256k1 as dev
from bitcoincashplus_tpu.crypto.secp256k1 import P

B = 8


@pytest.fixture(autouse=True)
def _force_parallel(monkeypatch):
    monkeypatch.setenv("BCP_SECP_PARALLEL", "1")


def _vals(rng, n=B):
    return [int.from_bytes(rng.bytes(32), "big") % P for _ in range(n)]


def _pack(vals):
    return np.stack([dev.to_limbs_np(v) for v in vals], axis=1)


def _unpack(arr):
    return [dev.from_limbs_np(arr[:, b]) for b in range(arr.shape[1])]


def _cols_value(cols):
    return [
        sum(int(cols[i, b]) << (13 * i) for i in range(cols.shape[0]))
        for b in range(cols.shape[1])
    ]


@pytest.mark.parametrize("cols", [
    np.full((39, B), (1 << 31) - 1, np.uint32),   # worst-case magnitude
    np.full((20, B), (1 << 31) - 1, np.uint32),
    np.zeros((39, B), np.uint32),
])
def test_parallel_carry_extremes(cols):
    out = np.asarray(dev.f_carry(jnp.asarray(cols)))
    for want, got in zip(_cols_value(cols), _unpack(out)):
        assert got % P == want % P
    assert out.max() <= 10000          # multiply-safe weak bound
    assert out[19].max() <= 0x1FF + 32  # top-limb weak bound


def test_parallel_carry_random():
    rng = np.random.default_rng(1)
    cols = rng.integers(0, 1 << 31, (39, B), dtype=np.uint32)
    out = np.asarray(dev.f_carry(jnp.asarray(cols)))
    for want, got in zip(_cols_value(cols), _unpack(out)):
        assert got % P == want % P


def test_parallel_mul_random_and_worst_case():
    rng = np.random.default_rng(2)
    va, vb = _vals(rng), _vals(rng)
    out = np.asarray(dev.f_mul(jnp.asarray(_pack(va)), jnp.asarray(_pack(vb))))
    for a, b_, got in zip(va, vb, _unpack(out)):
        assert got % P == (a * b_) % P
    # all limbs at the weak bound: products must not overflow u32 columns
    w = np.full((20, B), 8200, np.uint32)
    vw = dev.from_limbs_np(w[:, 0])
    out = np.asarray(dev.f_mul(jnp.asarray(w), jnp.asarray(w)))
    assert _unpack(out)[0] % P == (vw * vw) % P
    assert out.max() <= 10000


def test_parallel_mul_chain_maintains_discipline():
    """50 chained muls: magnitudes must stay multiply-safe forever."""
    rng = np.random.default_rng(3)
    va, vb = _vals(rng), _vals(rng)
    x, b_ = _pack(va), jnp.asarray(_pack(vb))
    want = list(va)
    for _ in range(50):
        x = np.asarray(dev.f_mul(jnp.asarray(x), b_))
        want = [(w * v) % P for w, v in zip(want, vb)]
        assert x.max() <= 10000
    assert [g % P for g in _unpack(x)] == want


def test_exact_norm_and_is_zero():
    rng = np.random.default_rng(4)
    vals = _vals(rng)
    vals[3] = 0
    vals[5] = P  # non-canonical zero (value == p)
    arr = jnp.asarray(_pack(vals))
    # weak-ify through a carry first (representation with eps limbs)
    weak = dev.f_carry(jnp.asarray(np.asarray(arr, np.uint32)))
    z = np.asarray(dev.f_is_zero(weak))
    assert list(z) == [v % P == 0 for v in vals]
    # exact normalization yields canonical 13-bit limbs
    exact = np.asarray(dev._exact_norm20(weak))
    assert exact.max() <= 0x1FFF
    for v, got in zip(vals, _unpack(exact)):
        assert got % P == v % P


def test_f_eq_parallel():
    rng = np.random.default_rng(6)
    va = _vals(rng)
    a = jnp.asarray(_pack(va))
    b_ = jnp.asarray(_pack(list(reversed(va))))
    eq = np.asarray(dev.f_eq(a, a))
    assert eq.all()
    neq = np.asarray(dev.f_eq(a, b_))
    expected = [x == y for x, y in zip(va, reversed(va))]
    assert list(neq) == expected
