"""AES-256 + CBC known-answer tests (FIPS-197 / NIST SP 800-38A) and the
wallet crypter (src/wallet/crypter.cpp semantics)."""

import pytest

from bitcoincashplus_tpu.crypto.aes import (
    _decrypt_block,
    _encrypt_block,
    _expand_key,
    aes256_cbc_decrypt,
    aes256_cbc_encrypt,
)
from bitcoincashplus_tpu.wallet.crypter import (
    bytes_to_key_sha512,
    decrypt_secret,
    encrypt_secret,
    new_master_key,
    unseal_master_key,
)

# FIPS-197 appendix C.3: AES-256 single block
FIPS_KEY = bytes.fromhex(
    "000102030405060708090a0b0c0d0e0f101112131415161718191a1b1c1d1e1f")
FIPS_PT = bytes.fromhex("00112233445566778899aabbccddeeff")
FIPS_CT = bytes.fromhex("8ea2b7ca516745bfeafc49904b496089")

# NIST SP 800-38A F.2.5: CBC-AES256 encrypt
NIST_KEY = bytes.fromhex(
    "603deb1015ca71be2b73aef0857d77811f352c073b6108d72d9810a30914dff4")
NIST_IV = bytes.fromhex("000102030405060708090a0b0c0d0e0f")
NIST_PT = bytes.fromhex(
    "6bc1bee22e409f96e93d7e117393172a"
    "ae2d8a571e03ac9c9eb76fac45af8e51"
    "30c81c46a35ce411e5fbc1191a0a52ef"
    "f69f2445df4f9b17ad2b417be66c3710")
NIST_CT = bytes.fromhex(
    "f58c4c04d6e5f1ba779eabfb5f7bfbd6"
    "9cfc4e967edb808d679f777bc6702c7d"
    "39f23369a9d9bacfa530e26304231461"
    "b2eb05e2c39be9fcda6c19078c6a9d1b")


def test_fips197_block():
    rks = _expand_key(FIPS_KEY)
    assert _encrypt_block(FIPS_PT, rks) == FIPS_CT
    assert _decrypt_block(FIPS_CT, rks) == FIPS_PT


def test_nist_cbc_vectors():
    ct = aes256_cbc_encrypt(NIST_KEY, NIST_IV, NIST_PT, pad=False)
    assert ct == NIST_CT
    assert aes256_cbc_decrypt(NIST_KEY, NIST_IV, NIST_CT, pad=False) == NIST_PT


def test_cbc_padding_roundtrip():
    key, iv = b"\x11" * 32, b"\x22" * 16
    for n in (0, 1, 15, 16, 17, 100):
        data = bytes(range(n % 256))[:n]
        ct = aes256_cbc_encrypt(key, iv, data)
        assert len(ct) % 16 == 0 and len(ct) > len(data)
        assert aes256_cbc_decrypt(key, iv, ct) == data


def test_cbc_bad_padding_raises():
    key, iv = b"\x11" * 32, b"\x22" * 16
    ct = aes256_cbc_encrypt(key, iv, b"hello")
    bad = ct[:-1] + bytes([ct[-1] ^ 1])
    with pytest.raises(ValueError):
        aes256_cbc_decrypt(key, iv, bad)


def test_kdf_deterministic_and_salted():
    k1, iv1 = bytes_to_key_sha512(b"pass", b"salt0000", 100)
    k2, iv2 = bytes_to_key_sha512(b"pass", b"salt0000", 100)
    k3, _ = bytes_to_key_sha512(b"pass", b"salt0001", 100)
    assert (k1, iv1) == (k2, iv2)
    assert k1 != k3 and len(k1) == 32 and len(iv1) == 16


def test_master_key_seal_unseal():
    rec, master = new_master_key("hunter2", rounds=100)
    assert unseal_master_key(rec, "hunter2") == master
    assert unseal_master_key(rec, "wrong") is None
    # round-trips its dict form
    from bitcoincashplus_tpu.wallet.crypter import MasterKey

    rec2 = MasterKey.from_dict(rec.to_dict())
    assert unseal_master_key(rec2, "hunter2") == master


def test_secret_encryption_bound_to_pubkey():
    _, master = new_master_key("x", rounds=10)
    secret = bytes(range(32))
    pub_a, pub_b = b"\x02" + b"\xaa" * 32, b"\x02" + b"\xbb" * 32
    ct = encrypt_secret(master, secret, pub_a)
    assert decrypt_secret(master, ct, pub_a) == secret
    # wrong pubkey -> wrong iv -> garbage or padding failure, never the secret
    assert decrypt_secret(master, ct, pub_b) != secret
