"""Crash-safe chainstate commits: the journal codec, the fsync-before-
rename publish, startup replay/rollback, and the acceptance matrix — a
subprocess is HARD-KILLED (os._exit, no sqlite rollback, no atexit) at
every step inside a journaled coins commit, the store is reopened, and the
recovered UTXO set must equal exactly the pre- or post-batch state, never
a torn mix."""

import os
import subprocess
import sys

import pytest

import bitcoincashplus_tpu
from bitcoincashplus_tpu.store.chainstatedb import (
    CoinsDB,
    _decode_journal,
    _encode_journal,
)
from bitcoincashplus_tpu.store.kvstore import KVStore, atomic_write_bytes

pytestmark = pytest.mark.faults

REPO = os.path.dirname(os.path.dirname(
    os.path.abspath(bitcoincashplus_tpu.__file__)))

# the committing worker: reopens the seeded store and applies one "block
# connect" batch (spend A, create B2/C, advance the best-block marker)
# with BCP_FAULT_CRASH armed by the parent. jax-free import chain — each
# run is a fast real process death.
WORKER = f"""
import sys
sys.path.insert(0, {REPO!r})
from bitcoincashplus_tpu.store.kvstore import KVStore
from bitcoincashplus_tpu.store.chainstatedb import CoinsDB
path, journal = sys.argv[1], sys.argv[2]
db = CoinsDB(KVStore(path), journal_path=journal)
db._commit({{b"Cb2": b"coinB2", b"Cc": b"coinC", b"B": b"\\x22"*32}},
           [b"Ca"])
"""

PRE = {b"Ca": b"coinA", b"Cd": b"coinD", b"B": b"\x11" * 32}
POST = {b"Cd": b"coinD", b"Cb2": b"coinB2", b"Cc": b"coinC",
        b"B": b"\x22" * 32}

# every crash point inside the commit, with the state the reopened store
# MUST resolve to: before the journal is durable the batch never happened
# (rollback); from durability on, recovery replays it (post).
STEPS = [
    ("journal:tmp-written", "pre"),
    ("journal:durable", "post"),
    ("kv:begin", "post"),
    ("kv:applied", "post"),     # torn sqlite txn discarded, journal replays
    ("kv:committed", "post"),
    ("journal:pre-clear", "post"),
]


def _state_of(path: str) -> dict:
    kv = KVStore(path)
    out = dict(kv.iterate())
    kv.close()
    return out


def _seed(tmp_path):
    path = str(tmp_path / "chainstate.sqlite")
    journal = str(tmp_path / "chainstate.journal")
    kv = KVStore(path)
    kv.write_batch(dict(PRE), sync=True)
    kv.close()
    return path, journal


class TestJournalCodec:
    def test_roundtrip(self):
        puts = {b"Ca": b"1", b"B": b"\x22" * 32, b"": b""}
        dels = [b"Cb", b"Cz"]
        assert _decode_journal(_encode_journal(puts, dels)) == (puts, dels)

    def test_rejects_garbage_and_truncation(self):
        blob = _encode_journal({b"k": b"v" * 100}, [b"d"])
        assert _decode_journal(b"") is None
        assert _decode_journal(b"garbage") is None
        assert _decode_journal(blob[:-5]) is None          # torn tail
        assert _decode_journal(b"XXXX" + blob[4:]) is None  # bad magic
        flipped = bytearray(blob)
        flipped[20] ^= 0x01
        assert _decode_journal(bytes(flipped)) is None      # bad checksum


class TestAtomicWrite:
    def test_publish_and_overwrite(self, tmp_path):
        p = str(tmp_path / "f.bin")
        atomic_write_bytes(p, b"one")
        assert open(p, "rb").read() == b"one"
        atomic_write_bytes(p, b"two")
        assert open(p, "rb").read() == b"two"
        assert not os.path.exists(p + ".tmp")


class TestRecovery:
    def test_no_journal_is_noop(self, tmp_path):
        path, journal = _seed(tmp_path)
        db = CoinsDB(KVStore(path), journal_path=journal)
        assert db.recover_journal() is False
        db.kv.close()
        assert _state_of(path) == PRE

    def test_torn_journal_rolls_back(self, tmp_path):
        path, journal = _seed(tmp_path)
        blob = _encode_journal({b"Cx": b"half"}, [])
        with open(journal, "wb") as f:
            f.write(blob[: len(blob) // 2])  # torn mid-write
        db = CoinsDB(KVStore(path), journal_path=journal)
        assert db.recover_journal() is False
        db.kv.close()
        assert _state_of(path) == PRE
        assert not os.path.exists(journal)

    def test_stale_tmp_fragment_discarded(self, tmp_path):
        path, journal = _seed(tmp_path)
        with open(journal + ".tmp", "wb") as f:
            f.write(b"partial")
        db = CoinsDB(KVStore(path), journal_path=journal)
        assert db.recover_journal() is False
        db.kv.close()
        assert not os.path.exists(journal + ".tmp")

    def test_replay_is_idempotent(self, tmp_path):
        path, journal = _seed(tmp_path)
        blob = _encode_journal(
            {b"Cb2": b"coinB2", b"Cc": b"coinC", b"B": b"\x22" * 32},
            [b"Ca"])
        # journal present AND batch already fully applied (crash between
        # commit and journal clear): replay must land on the same state
        db = CoinsDB(KVStore(path), journal_path=journal)
        db._commit({b"Cb2": b"coinB2", b"Cc": b"coinC", b"B": b"\x22" * 32},
                   [b"Ca"])
        with open(journal, "wb") as f:
            f.write(blob)
        assert db.recover_journal() is True
        db.kv.close()
        assert _state_of(path) == POST


@pytest.mark.parametrize("step,expect", STEPS)
def test_crash_at_every_journal_step(tmp_path, step, expect):
    """Kill the committing process at ``step``; the reopened + recovered
    store holds exactly the expected whole state."""
    path, journal = _seed(tmp_path)
    env = dict(os.environ)
    env["BCP_FAULT_CRASH"] = step
    proc = subprocess.run(
        [sys.executable, "-c", WORKER, path, journal],
        env=env, capture_output=True, timeout=60,
    )
    assert proc.returncode == 137, (step, proc.stderr.decode()[-500:])
    db = CoinsDB(KVStore(path), journal_path=journal)
    db.recover_journal()
    db.kv.close()
    state = _state_of(path)
    assert state == (PRE if expect == "pre" else POST), (step, state)
    assert not os.path.exists(journal)  # always cleared after recovery


def test_uninjected_commit_completes(tmp_path):
    path, journal = _seed(tmp_path)
    proc = subprocess.run(
        [sys.executable, "-c", WORKER, path, journal],
        env=dict(os.environ), capture_output=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr.decode()[-500:]
    assert _state_of(path) == POST
    assert not os.path.exists(journal)


# ---------------------------------------------------------------------------
# Sharded drill: the same hard-kill acceptance matrix over the N-shard
# facade (store/sharded.py). The commit is a multi-file protocol —
# per-shard journals (sequential) -> parallel sqlite applies -> epoch
# manifest -> journal clear — so the contract widens: the recovered store
# must land on a whole pre- or post-batch state ACROSS ALL SHARDS, with
# the incremental accumulator equal to a from-scratch recompute and no
# shard ever ahead of the recovered epoch.
# ---------------------------------------------------------------------------

SHARDED_WORKER = f"""
import struct, sys
sys.path.insert(0, {REPO!r})
from bitcoincashplus_tpu.store.sharded import ShardedCoinsDB
datadir, n, wal = sys.argv[1], int(sys.argv[2]), sys.argv[3] == "1"
key = lambda i: bytes([i % 251]) * 32 + struct.pack("<I", i)
coin = lambda i: bytes([2, 5, 20]) + bytes([i % 256]) * 20
db = ShardedCoinsDB(datadir, n_shards=n, wal=wal)
entries = [(key(i), coin(i)) for i in range(40, 60)]
entries += [(key(i), None) for i in range(0, 10)]
db.batch_write_serialized(entries, b"\\x22" * 32)
"""

# (step, expected-state-fn(n_shards)): before ANY journal is durable the
# batch never happened; with only SOME journals durable (journal:durable
# fires after each shard's leg — the kill lands after shard 0's) the
# partial set must roll back, except at 1 shard where "some" == "all";
# from the all-journals-durable barrier on, recovery replays forward.
SHARDED_STEPS = [
    ("journal:tmp-written", lambda n: "pre"),
    ("journal:durable", lambda n: "post" if n == 1 else "pre"),
    ("shard:journals-durable", lambda n: "post"),
    ("kv:begin", lambda n: "post"),
    ("kv:applied", lambda n: "post"),
    ("kv:committed", lambda n: "post"),
    ("shard:applied", lambda n: "post"),
    ("manifest:written", lambda n: "post"),
    ("journal:pre-clear", lambda n: "post"),
]


def _skey(i: int) -> bytes:
    import struct

    return bytes([i % 251]) * 32 + struct.pack("<I", i)


def _scoin(i: int) -> bytes:
    return bytes([2, 5, 20]) + bytes([i % 256]) * 20


def _seed_sharded(tmp_path, n_shards: int, wal: bool = False) -> str:
    from bitcoincashplus_tpu.store.sharded import ShardedCoinsDB

    datadir = str(tmp_path)
    db = ShardedCoinsDB(datadir, n_shards=n_shards, wal=wal)
    db.batch_write_serialized(
        [(_skey(i), _scoin(i)) for i in range(40)], b"\x11" * 32)
    db.close()
    return datadir

def _assert_sharded_state(datadir: str, n_shards: int, expect: str, ctx,
                          wal: bool = False):
    from bitcoincashplus_tpu.store.sharded import ShardedCoinsDB

    db = ShardedCoinsDB(datadir, n_shards=n_shards, wal=wal)
    db.recover_journal()
    want_keys = (set(range(40)) if expect == "pre"
                 else set(range(10, 60)))
    want_best = b"\x11" * 32 if expect == "pre" else b"\x22" * 32
    rows = dict(db.iterate_coins())
    assert set(rows) == {_skey(i) for i in want_keys}, ctx
    assert all(rows[_skey(i)] == _scoin(i) for i in want_keys), ctx
    assert db.best_block() == want_best, ctx
    # accumulator recovered alongside the rows, and every shard sits at
    # the recovered epoch — no shard ahead, no journal left behind
    assert db.muhash_digest() == db.recompute_digest(), ctx
    for i in range(n_shards):
        assert db._shard_epoch(i) <= db.epoch, ctx
        assert not os.path.exists(db.shards[i].journal_path), ctx
    db.close()


@pytest.mark.parametrize("n_shards", [1, 2, 4])
@pytest.mark.parametrize("step,expect_fn", SHARDED_STEPS)
def test_sharded_crash_at_every_step(tmp_path, n_shards, step, expect_fn):
    """Hard-kill the sharded commit at ``step`` for every shard count;
    recovery must land on a whole cross-shard state."""
    datadir = _seed_sharded(tmp_path, n_shards)
    env = dict(os.environ)
    env["BCP_FAULT_CRASH"] = step
    proc = subprocess.run(
        [sys.executable, "-c", SHARDED_WORKER, datadir, str(n_shards), "0"],
        env=env, capture_output=True, timeout=120,
    )
    assert proc.returncode == 137, (step, proc.stderr.decode()[-500:])
    _assert_sharded_state(datadir, n_shards, expect_fn(n_shards),
                          (step, n_shards))


@pytest.mark.parametrize("step,expect_fn", SHARDED_STEPS)
def test_sharded_wal_crash_at_every_step(tmp_path, step, expect_fn):
    """The ``-coinswal`` knob (synchronous=FULL, no per-commit WAL
    checkpoint) through the same hard-kill matrix: the durability
    boundary moves from the explicit checkpoint to sqlite's COMMIT
    record, and the whole-state acceptance contract must hold
    unchanged. 2 shards: the only count where the partial-journal and
    cross-shard barrier cases are all distinct and cheap."""
    n_shards = 2
    datadir = _seed_sharded(tmp_path, n_shards, wal=True)
    env = dict(os.environ)
    env["BCP_FAULT_CRASH"] = step
    proc = subprocess.run(
        [sys.executable, "-c", SHARDED_WORKER, datadir, str(n_shards), "1"],
        env=env, capture_output=True, timeout=120,
    )
    assert proc.returncode == 137, (step, proc.stderr.decode()[-500:])
    _assert_sharded_state(datadir, n_shards, expect_fn(n_shards),
                          (step, n_shards, "wal"), wal=True)


@pytest.mark.parametrize("wal", [False, True])
@pytest.mark.parametrize("n_shards", [1, 2, 4])
def test_sharded_uninjected_commit_completes(tmp_path, n_shards, wal):
    datadir = _seed_sharded(tmp_path, n_shards, wal=wal)
    proc = subprocess.run(
        [sys.executable, "-c", SHARDED_WORKER, datadir, str(n_shards),
         "1" if wal else "0"],
        env=dict(os.environ), capture_output=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr.decode()[-500:]
    _assert_sharded_state(datadir, n_shards, "post", n_shards, wal=wal)


def test_wal_knob_sets_synchronous_full(tmp_path):
    """wal=True is operational, not layout: same on-disk WAL-mode sqlite
    files, but COMMIT itself fsyncs (synchronous=FULL) instead of the
    per-sync'd-batch wal_checkpoint(FULL), and a store written with the
    knob on reopens cleanly with it off (and vice versa)."""
    from bitcoincashplus_tpu.store.sharded import ShardedCoinsDB

    datadir = str(tmp_path)
    db = ShardedCoinsDB(datadir, n_shards=2, wal=True)
    assert db.stats()["wal"] is True
    for shard in db.shards:
        assert shard.kv.wal is True
        (sync,) = shard.kv._db.execute("PRAGMA synchronous").fetchone()
        assert sync == 2  # FULL
    db.batch_write_serialized(
        [(_skey(i), _scoin(i)) for i in range(8)], b"\x11" * 32)
    db.close()

    db = ShardedCoinsDB(datadir, n_shards=2)  # reopen with the knob OFF
    assert db.stats()["wal"] is False
    for shard in db.shards:
        (sync,) = shard.kv._db.execute("PRAGMA synchronous").fetchone()
        assert sync == 1  # NORMAL + explicit checkpoint on sync'd batches
    assert dict(db.iterate_coins()) == {
        _skey(i): _scoin(i) for i in range(8)}
    db.close()


def test_chainstate_manager_replays_journal_at_startup(tmp_path):
    """The startup replay path (validation/chainstate.py): a journal left
    by a crash is applied before the chainstate reads anything."""
    from bitcoincashplus_tpu.consensus.params import regtest_params
    from bitcoincashplus_tpu.store.blockstore import MemoryBlockStore
    from bitcoincashplus_tpu.validation.chainstate import ChainstateManager

    params = regtest_params()
    path = str(tmp_path / "cs.sqlite")
    journal = str(tmp_path / "cs.journal")
    kv = KVStore(path)
    # pending journal: best-block -> genesis + one coin row
    with open(journal, "wb") as f:
        f.write(_encode_journal(
            {b"B": params.genesis_hash, b"C" + b"\xaa" * 36: b"\x02\x05\x00"},
            []))
    db = CoinsDB(kv, journal_path=journal)
    ChainstateManager(params, db, MemoryBlockStore())
    assert not os.path.exists(journal)
    assert kv.get(b"B") == params.genesis_hash
    assert kv.get(b"C" + b"\xaa" * 36) is not None
    kv.close()
