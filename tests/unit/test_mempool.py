"""Mempool tests — CTxMemPool invariants + AcceptToMemoryPool e2e.

Mirrors src/test/mempool_tests.cpp (aggregate bookkeeping, removal,
eviction ordering) and the ATMP acceptance/reject matrix that
qa/rpc-tests exercises via sendrawtransaction.
"""

import pytest

from bitcoincashplus_tpu.consensus.params import regtest_params
from bitcoincashplus_tpu.consensus.tx import COutPoint, CTransaction, CTxIn, CTxOut
from bitcoincashplus_tpu.mempool import (
    CTxMemPool,
    MempoolEntry,
    MempoolError,
    accept_to_memory_pool,
)
from bitcoincashplus_tpu.mining.assembler import BlockAssembler
from bitcoincashplus_tpu.mining.generate import generate_blocks
from bitcoincashplus_tpu.store.blockstore import MemoryBlockStore
from bitcoincashplus_tpu.validation.chainstate import ChainstateManager
from bitcoincashplus_tpu.validation.coins import MemoryCoinsView
from bitcoincashplus_tpu.validation.scriptcheck import BlockScriptVerifier
from bitcoincashplus_tpu.validation.sigcache import SignatureCache
from bitcoincashplus_tpu.wallet.keys import CKey
from bitcoincashplus_tpu.wallet.signing import sign_transaction

from test_validation import TILE, _hand_mine

KEY = CKey(0xDEADBEEFCAFE)
SPK_KEY = KEY.p2pkh_script()


# ----------------------------------------------------------------------
# pure pool mechanics (no chainstate): mempool_tests.cpp analogues
# ----------------------------------------------------------------------


def _fake_tx(inputs, n_out=1, value=10_000, salt=0):
    """A structurally-valid unsigned tx for pool bookkeeping tests."""
    return CTransaction(
        vin=tuple(CTxIn(op, bytes([salt % 256])) for op in inputs),
        vout=tuple(CTxOut(value, b"\x51") for _ in range(n_out)),
    )


def _entry(tx, fee=1000, t=0, height=1):
    return MempoolEntry(tx, fee, t, height)


def _root_tx(salt, n_out=1):
    return _fake_tx([COutPoint(bytes([salt]) * 32, 0)], n_out=n_out, salt=salt)


class TestPoolAggregates:
    def test_chain_aggregates(self):
        pool = CTxMemPool()
        parent = _root_tx(1, n_out=2)
        child = _fake_tx([COutPoint(parent.txid, 0)], salt=2)
        grandchild = _fake_tx([COutPoint(child.txid, 0)], salt=3)
        pool.add_unchecked(_entry(parent, fee=1000))
        pool.add_unchecked(_entry(child, fee=2000))
        pool.add_unchecked(_entry(grandchild, fee=4000))

        pe, ce, ge = pool.get(parent.txid), pool.get(child.txid), pool.get(grandchild.txid)
        assert pe.count_with_descendants == 3
        assert ce.count_with_descendants == 2
        assert ge.count_with_descendants == 1
        assert ge.count_with_ancestors == 3
        assert ce.count_with_ancestors == 2
        assert pe.count_with_ancestors == 1
        assert pe.fees_with_descendants == 7000
        assert ge.fees_with_ancestors == 7000
        assert pool.total_size == pe.size + ce.size + ge.size

    def test_remove_middle_fixes_aggregates(self):
        pool = CTxMemPool()
        parent = _root_tx(1, n_out=2)
        child = _fake_tx([COutPoint(parent.txid, 0)], salt=2)
        pool.add_unchecked(_entry(parent, fee=1000))
        pool.add_unchecked(_entry(child, fee=2000))
        pool.remove_recursive(child.txid)
        pe = pool.get(parent.txid)
        assert pe.count_with_descendants == 1
        assert pe.fees_with_descendants == 1000
        assert child.txid not in pool
        # child's input spend is released
        assert pool.get_spender(COutPoint(parent.txid, 0)) is None

    def test_remove_recursive_takes_descendants(self):
        pool = CTxMemPool()
        parent = _root_tx(1, n_out=2)
        c1 = _fake_tx([COutPoint(parent.txid, 0)], salt=2)
        c2 = _fake_tx([COutPoint(parent.txid, 1)], salt=3)
        for tx, fee in ((parent, 1000), (c1, 1000), (c2, 1000)):
            pool.add_unchecked(_entry(tx, fee=fee))
        removed = pool.remove_recursive(parent.txid)
        assert set(removed) == {parent.txid, c1.txid, c2.txid}
        assert len(pool) == 0 and pool.total_size == 0 and pool.total_fee == 0

    def test_conflict_assertion(self):
        pool = CTxMemPool()
        a = _root_tx(1)
        op = COutPoint(bytes([1]) * 32, 0)  # same prevout as a
        b = _fake_tx([op], salt=9)
        pool.add_unchecked(_entry(a))
        with pytest.raises(AssertionError):
            pool.add_unchecked(_entry(b))

    def test_expiry(self):
        pool = CTxMemPool(expiry_seconds=100)
        old = _root_tx(1, n_out=2)
        child = _fake_tx([COutPoint(old.txid, 0)], salt=2)
        fresh = _root_tx(3)
        pool.add_unchecked(_entry(old, t=0))
        pool.add_unchecked(_entry(child, t=150))  # young but descends from old
        pool.add_unchecked(_entry(fresh, t=150))
        n = pool.expire(now=200)
        assert n == 2  # old + its descendant
        assert fresh.txid in pool

    def test_trim_to_size_evicts_lowest_descendant_score(self):
        pool = CTxMemPool()
        cheap = _root_tx(1)
        rich = _root_tx(2)
        pool.add_unchecked(_entry(cheap, fee=100))
        pool.add_unchecked(_entry(rich, fee=100_000))
        pool.trim_to_size(max_bytes=pool.get(rich.txid).size)
        assert rich.txid in pool and cheap.txid not in pool


class TestSelectForBlock:
    def test_parent_emitted_before_child(self):
        pool = CTxMemPool()
        parent = _root_tx(1, n_out=2)
        child = _fake_tx([COutPoint(parent.txid, 0)], salt=2)
        pool.add_unchecked(_entry(parent, fee=100))
        pool.add_unchecked(_entry(child, fee=100_000))  # high child fee
        sel = pool.select_for_block(max_size=1_000_000, height=10, block_time=0)
        txids = [e.txid for e in sel]
        assert txids.index(parent.txid) < txids.index(child.txid)

    def test_package_feerate_orders_selection(self):
        pool = CTxMemPool()
        solo_hi = _root_tx(1)
        solo_lo = _root_tx(2)
        pool.add_unchecked(_entry(solo_hi, fee=50_000))
        pool.add_unchecked(_entry(solo_lo, fee=10))
        sel = pool.select_for_block(max_size=1_000_000, height=10, block_time=0)
        assert [e.txid for e in sel] == [solo_hi.txid, solo_lo.txid]

    def test_size_cap_respected(self):
        pool = CTxMemPool()
        a, b = _root_tx(1), _root_tx(2)
        pool.add_unchecked(_entry(a, fee=1000))
        pool.add_unchecked(_entry(b, fee=999))
        one_size = pool.get(a.txid).size
        sel = pool.select_for_block(max_size=one_size, height=10, block_time=0)
        assert [e.txid for e in sel] == [a.txid]

    def test_nonfinal_excluded_with_descendants(self):
        """ADVICE r2 #3: a future-locktime tx (and its child) must not be
        selected into a template."""
        pool = CTxMemPool()
        locked = CTransaction(
            vin=(CTxIn(COutPoint(bytes([1]) * 32, 0), b"", 0),),  # seq != final
            vout=(CTxOut(10_000, b"\x51"), CTxOut(10_000, b"\x51")),
            locktime=500,  # height-locked above current height
        )
        child = _fake_tx([COutPoint(locked.txid, 0)], salt=2)
        ok = _root_tx(3)
        pool.add_unchecked(_entry(locked))
        pool.add_unchecked(_entry(child))
        pool.add_unchecked(_entry(ok))
        sel = pool.select_for_block(max_size=1_000_000, height=100, block_time=0)
        assert [e.txid for e in sel] == [ok.txid]
        # at height 501 it becomes final and selectable
        sel = pool.select_for_block(max_size=1_000_000, height=501, block_time=0)
        assert {e.txid for e in sel} == {locked.txid, child.txid, ok.txid}


# ----------------------------------------------------------------------
# AcceptToMemoryPool e2e on a real regtest chain
# ----------------------------------------------------------------------


@pytest.fixture
def node():
    """chainstate + mempool + sigcache trio with 103 mined blocks."""
    params = regtest_params()
    t = [1_600_000_000]

    def fake_time():
        t[0] += 60
        return t[0]

    sigcache = SignatureCache()
    cs = ChainstateManager(
        params, MemoryCoinsView(), MemoryBlockStore(),
        script_verifier=BlockScriptVerifier(params, backend="cpu",
                                            sigcache=sigcache),
        get_time=fake_time,
    )
    generate_blocks(cs, SPK_KEY, 103, tile=TILE)
    pool = CTxMemPool()
    cs.on_block_connected.append(lambda blk, idx: pool.remove_for_block(blk.vtx))
    return cs, pool, sigcache


def _coinbase_out(cs, height):
    blk = cs.get_block(cs.chain[height].hash)
    return COutPoint(blk.vtx[0].txid, 0), blk.vtx[0].vout[0].value


def _spend(op, value, fee=10_000, n_out=1, locktime=0, sequence=0xFFFFFFFF):
    per_out = (value - fee) // n_out
    tx = CTransaction(
        vin=(CTxIn(op, b"", sequence),),
        vout=tuple(CTxOut(per_out, SPK_KEY) for _ in range(n_out)),
        locktime=locktime,
    )
    return sign_transaction(
        tx, [(SPK_KEY, value)], lambda i: KEY if i == KEY.pubkey_hash else None,
        enable_forkid=True,
    )


class TestATMP:
    def test_accept_and_mine(self, node):
        cs, pool, sigcache = node
        op, value = _coinbase_out(cs, 1)
        tx = _spend(op, value)
        entry = accept_to_memory_pool(pool, cs, tx, sigcache=sigcache)
        assert entry.txid == tx.txid and tx.txid in pool
        assert len(sigcache) == 1  # ATMP populated the cache
        # template picks it up, block mines, pool drains
        hits_before = sigcache.hits
        generate_blocks(cs, SPK_KEY, 1, mempool=pool, tile=TILE)
        blk = cs.get_block(cs.tip().hash)
        assert any(t.txid == tx.txid for t in blk.vtx[1:])
        assert len(pool) == 0
        # connect re-used the ATMP-verified sig via the cache
        assert sigcache.hits > hits_before
        # miner collected the fee
        assert blk.vtx[0].total_output_value() > 50 * 10**8 // 2

    def test_duplicate_rejected(self, node):
        cs, pool, sigcache = node
        op, value = _coinbase_out(cs, 1)
        tx = _spend(op, value)
        accept_to_memory_pool(pool, cs, tx, sigcache=sigcache)
        with pytest.raises(MempoolError, match="already-in-mempool"):
            accept_to_memory_pool(pool, cs, tx, sigcache=sigcache)

    def test_conflict_rejected(self, node):
        cs, pool, sigcache = node
        op, value = _coinbase_out(cs, 1)
        accept_to_memory_pool(pool, cs, _spend(op, value), sigcache=sigcache)
        double = _spend(op, value, fee=20_000)  # same prevout, different tx
        with pytest.raises(MempoolError, match="mempool-conflict"):
            accept_to_memory_pool(pool, cs, double, sigcache=sigcache)

    def test_coinbase_rejected(self, node):
        cs, pool, sigcache = node
        blk = cs.get_block(cs.chain[1].hash)
        with pytest.raises(MempoolError, match="coinbase"):
            accept_to_memory_pool(pool, cs, blk.vtx[0], sigcache=sigcache)

    def test_missing_inputs(self, node):
        cs, pool, sigcache = node
        ghost = COutPoint(b"\xaa" * 32, 0)
        tx = CTransaction(
            vin=(CTxIn(ghost, b"\x51"),), vout=(CTxOut(1000, SPK_KEY),)
        )
        with pytest.raises(MempoolError, match="missing-inputs"):
            accept_to_memory_pool(pool, cs, tx, sigcache=sigcache)

    def test_premature_coinbase_spend(self, node):
        cs, pool, sigcache = node
        op, value = _coinbase_out(cs, cs.tip().height)  # freshly mined
        with pytest.raises(MempoolError, match="premature-spend-of-coinbase"):
            accept_to_memory_pool(pool, cs, _spend(op, value), sigcache=sigcache)

    def test_low_fee_rejected(self, node):
        cs, pool, sigcache = node
        op, value = _coinbase_out(cs, 1)
        with pytest.raises(MempoolError, match="min-fee-not-met"):
            accept_to_memory_pool(pool, cs, _spend(op, value, fee=10),
                                  sigcache=sigcache)

    def test_bad_signature_rejected(self, node):
        cs, pool, sigcache = node
        op, value = _coinbase_out(cs, 1)
        tx = _spend(op, value)
        ss = bytearray(tx.vin[0].script_sig)
        ss[40] ^= 1
        bad = CTransaction(tx.version, (CTxIn(op, bytes(ss)),), tx.vout, tx.locktime)
        with pytest.raises(MempoolError, match="script-verify"):
            accept_to_memory_pool(pool, cs, bad, sigcache=sigcache)
        assert bad.txid not in pool

    def test_nonfinal_rejected(self, node):
        cs, pool, sigcache = node
        op, value = _coinbase_out(cs, 1)
        tx = _spend(op, value, locktime=cs.tip().height + 100, sequence=0)
        with pytest.raises(MempoolError, match="non-final"):
            accept_to_memory_pool(pool, cs, tx, sigcache=sigcache)

    def test_unconfirmed_chain_accepted(self, node):
        """Child spending an in-pool parent's output is admitted (the
        CCoinsViewMemPool leg) and mined in parent-first order."""
        cs, pool, sigcache = node
        op, value = _coinbase_out(cs, 1)
        parent = _spend(op, value, n_out=2)
        accept_to_memory_pool(pool, cs, parent, sigcache=sigcache)
        child_in = COutPoint(parent.txid, 0)
        child = _spend(child_in, parent.vout[0].value)
        accept_to_memory_pool(pool, cs, child, sigcache=sigcache)
        assert pool.get(child.txid).count_with_ancestors == 2
        generate_blocks(cs, SPK_KEY, 1, mempool=pool, tile=TILE)
        blk = cs.get_block(cs.tip().hash)
        txids = [t.txid for t in blk.vtx]
        assert txids.index(parent.txid) < txids.index(child.txid)
        assert len(pool) == 0

    def test_ancestor_limit(self, node):
        cs, pool, sigcache = node
        op, value = _coinbase_out(cs, 1)
        tx = _spend(op, value, fee=10_000)
        accept_to_memory_pool(pool, cs, tx, sigcache=sigcache)
        for _ in range(24):
            nxt = _spend(COutPoint(tx.txid, 0), tx.vout[0].value, fee=10_000)
            accept_to_memory_pool(pool, cs, nxt, sigcache=sigcache)
            tx = nxt
        over = _spend(COutPoint(tx.txid, 0), tx.vout[0].value, fee=10_000)
        with pytest.raises(MempoolError, match="too-long-mempool-chain"):
            accept_to_memory_pool(pool, cs, over, sigcache=sigcache)

    def test_conflict_pruned_on_block_connect(self, node):
        """A tx double-spent by a mined block is evicted as a conflict."""
        cs, pool, sigcache = node
        op, value = _coinbase_out(cs, 1)
        pool_tx = _spend(op, value)
        accept_to_memory_pool(pool, cs, pool_tx, sigcache=sigcache)
        # mine a block containing a DIFFERENT spend of the same outpoint
        rival = _spend(op, value, fee=20_000)
        tip = cs.tip()
        blk = _hand_mine(tip.hash, tip.height + 1, cs.get_time() + 10,
                         tip.bits, (rival,))
        cs.process_new_block(blk)
        assert cs.tip().hash == blk.get_hash()
        assert pool_tx.txid not in pool  # conflict removed


class TestPrioritise:
    def test_delta_moves_mining_score(self):
        pool = CTxMemPool()
        a, b = _root_tx(1), _root_tx(2)
        pool.add_unchecked(_entry(a, fee=1000))
        pool.add_unchecked(_entry(b, fee=1000))
        pool.prioritise(a.txid, 5000)
        ea, eb = pool.get(a.txid), pool.get(b.txid)
        assert ea.fee == 6000 and ea.base_fee == 1000
        assert ea.ancestor_fee_rate() > eb.ancestor_fee_rate()
        sel = pool.select_for_block(10_000_000, 1, 0)
        assert sel[0].txid == a.txid
        # de-prioritise back below b
        pool.prioritise(a.txid, -7000)
        assert pool.map_deltas[a.txid] == -2000
        sel = pool.select_for_block(10_000_000, 1, 0)
        assert sel[0].txid == b.txid

    def test_delta_propagates_to_relatives(self):
        pool = CTxMemPool()
        parent = _root_tx(1)
        child = _fake_tx([COutPoint(parent.txid, 0)], salt=2)
        pool.add_unchecked(_entry(parent, fee=1000))
        pool.add_unchecked(_entry(child, fee=1000))
        pool.prioritise(child.txid, 4000)
        assert pool.get(parent.txid).fees_with_descendants == 6000
        assert pool.get(child.txid).fees_with_ancestors == 6000
        pool.prioritise(parent.txid, 2000)
        assert pool.get(child.txid).fees_with_ancestors == 8000
        assert pool.total_fee == 8000
        # removal keeps aggregates consistent
        pool.remove_recursive(child.txid)
        assert pool.get(parent.txid).fees_with_descendants == 3000

    def test_delta_applies_on_entry(self, node):
        """mapDeltas set BEFORE the tx arrives boosts it at ATMP time."""
        cs, pool, sigcache = node
        op, value = _coinbase_out(cs, 1)
        tx = _spend(op, value, fee=100)  # below the 1000 sat/kB floor
        with pytest.raises(MempoolError, match="min-fee"):
            accept_to_memory_pool(pool, cs, tx, sigcache=sigcache)
        pool.prioritise(tx.txid, 10_000)
        entry = accept_to_memory_pool(pool, cs, tx, sigcache=sigcache)
        assert entry.base_fee == 100 and entry.fee == 10_100


class TestMempoolPersist:
    class _Shim:
        """Just enough node for load_mempool: pool + ATMP closure."""

        def __init__(self, cs, pool, sigcache):
            self.mempool = pool
            self._cs, self._sigcache = cs, sigcache

        def accept_to_mempool(self, tx, now=None,
                              fee_estimate=True):
            return accept_to_memory_pool(self.mempool, self._cs, tx,
                                         sigcache=self._sigcache, now=now)

    def test_dump_load_roundtrip(self, node, tmp_path):
        from bitcoincashplus_tpu.mempool.persist import dump_mempool, load_mempool

        cs, pool, sigcache = node
        op1, v1 = _coinbase_out(cs, 1)
        parent = _spend(op1, v1, n_out=2)
        child = _spend(COutPoint(parent.txid, 0), parent.vout[0].value)
        accept_to_memory_pool(pool, cs, parent, sigcache=sigcache)
        accept_to_memory_pool(pool, cs, child, sigcache=sigcache)
        pool.prioritise(child.txid, 777)
        pool.map_deltas[b"\xaa" * 32] = 123  # delta for a tx we never saw
        path = str(tmp_path / "mempool.dat")
        assert dump_mempool(pool, path) == 2

        pool2 = CTxMemPool()
        shim = self._Shim(cs, pool2, SignatureCache())
        accepted, failed, expired = load_mempool(shim, path)
        assert (accepted, failed, expired) == (2, 0, 0)
        assert parent.txid in pool2 and child.txid in pool2
        assert pool2.get(child.txid).fee == pool.get(child.txid).fee
        assert pool2.map_deltas[b"\xaa" * 32] == 123
        assert pool2.get(child.txid).base_fee + 777 == pool2.get(child.txid).fee

    def test_expired_entries_skipped(self, node, tmp_path):
        from bitcoincashplus_tpu.mempool.persist import dump_mempool, load_mempool

        cs, pool, sigcache = node
        op, value = _coinbase_out(cs, 1)
        tx = _spend(op, value)
        accept_to_memory_pool(pool, cs, tx, sigcache=sigcache, now=1000)
        path = str(tmp_path / "mempool.dat")
        dump_mempool(pool, path)
        pool2 = CTxMemPool()
        shim = self._Shim(cs, pool2, SignatureCache())
        accepted, failed, expired = load_mempool(
            shim, path, now=1000 + pool2.expiry_seconds + 1)
        assert (accepted, expired) == (0, 1)

    def test_corrupt_file_survives(self, node, tmp_path):
        from bitcoincashplus_tpu.mempool.persist import load_mempool

        cs, pool, sigcache = node
        path = str(tmp_path / "mempool.dat")
        with open(path, "wb") as f:
            f.write(b"\x01\x00\x00\x00\x00\x00\x00\x00\xff\xff")
        shim = self._Shim(cs, CTxMemPool(), SignatureCache())
        load_mempool(shim, path)  # must not raise

    def test_missing_file_noop(self, node, tmp_path):
        from bitcoincashplus_tpu.mempool.persist import load_mempool

        cs, pool, sigcache = node
        shim = self._Shim(cs, CTxMemPool(), SignatureCache())
        assert load_mempool(shim, str(tmp_path / "nope.dat")) == (0, 0, 0)
