"""Differential tests for the native block-connect engine
(native/connect.cpp) against the Python validation engine
(validation/chainstate.py) — the fast -reindex import path's correctness
contract: same undo blobs, same chainstate rows, same accept/reject
verdicts, and sig-scan records that match the Python interpreter's
deferred SigCheckRecords bit for bit.

Reference: src/validation.cpp ConnectBlock / LoadExternalBlockFile — the
reference's import pipeline is a single C++ engine; here the native engine
must agree with the Python reference implementation instead.
"""

from __future__ import annotations

import os
import struct

import pytest

from bitcoincashplus_tpu import native
from bitcoincashplus_tpu.consensus.block import CBlock, CBlockHeader
from bitcoincashplus_tpu.consensus.params import (
    get_block_subsidy,
    regtest_params,
)
from bitcoincashplus_tpu.consensus.pow import compact_to_target
from bitcoincashplus_tpu.consensus.serialize import ByteReader
from bitcoincashplus_tpu.consensus.tx import (
    COutPoint,
    CTransaction,
    CTxIn,
    CTxOut,
)
from bitcoincashplus_tpu.crypto.hashes import sha256d
from bitcoincashplus_tpu.mining.assembler import bip34_coinbase_script_sig
from bitcoincashplus_tpu.script.interpreter import (
    DeferringSignatureChecker,
    VerifyScript,
)
from bitcoincashplus_tpu.script.script import script_int
from bitcoincashplus_tpu.script.sighash import SighashCache
from bitcoincashplus_tpu.store.blockstore import MemoryBlockStore
from bitcoincashplus_tpu.validation.chainstate import (
    BlockValidationError,
    ChainstateManager,
)
from bitcoincashplus_tpu.validation.coins import MemoryCoinsView
from bitcoincashplus_tpu.validation.scriptcheck import block_script_flags
from bitcoincashplus_tpu.wallet.keys import CKey
from bitcoincashplus_tpu.wallet.signing import sign_transaction

pytestmark = pytest.mark.skipif(
    not native.engine_available(), reason="native connect engine unavailable"
)

PARAMS = regtest_params()
KEY = CKey(0xB00B1E5 * 31, compressed=True)
SPK = KEY.p2pkh_script()


def _key_for(ident):
    return KEY if ident in (KEY.pubkey_hash, KEY.pubkey) else None


def _mine(header: CBlockHeader) -> CBlockHeader:
    target, _ = compact_to_target(header.bits)
    nonce = 0
    raw = bytearray(header.serialize())
    while True:
        struct.pack_into("<I", raw, 76, nonce)
        if int.from_bytes(sha256d(bytes(raw)), "little") <= target:
            return header.with_nonce(nonce)
        nonce += 1


def _block(prev_hash: bytes, height: int, t: int, txs=()) -> CBlock:
    from bitcoincashplus_tpu.consensus.merkle import block_merkle_root

    fees = 10_000 * len(txs)
    coinbase = CTransaction(
        version=1,
        vin=(CTxIn(COutPoint(), bip34_coinbase_script_sig(height) + b"t",
                   0xFFFFFFFF),),
        vout=(CTxOut(fees + get_block_subsidy(height, PARAMS.consensus),
                     SPK),),
    )
    vtx = (coinbase, *txs)

    class _V:
        pass

    v = _V()
    v.vtx = vtx
    root, _ = block_merkle_root(v)
    header = CBlockHeader(
        version=0x20000000, hash_prev_block=prev_hash,
        hash_merkle_root=root, time=t,
        bits=PARAMS.genesis.header.bits, nonce=0,
    )
    return CBlock(_mine(header), vtx)


def _spend(prevouts, values, n_out=1) -> CTransaction:
    total = sum(values) - 10_000
    unsigned = CTransaction(
        version=1,
        vin=tuple(CTxIn(op, b"", 0xFFFFFFFE) for op in prevouts),
        vout=tuple(CTxOut(total // n_out, SPK) for _ in range(n_out)),
    )
    return sign_transaction(unsigned, [(SPK, v) for v in values], _key_for,
                            enable_forkid=True)


class _Chain:
    """A tiny spendable regtest chain built through the PYTHON engine,
    with per-block raw bytes and undo blobs recorded for comparison."""

    def __init__(self, runway=102):
        self.cs = ChainstateManager(PARAMS, MemoryCoinsView(),
                                    MemoryBlockStore(), script_verifier=None)
        self.undo = {}
        orig = self.cs.block_store.put_undo
        self.cs.block_store.put_undo = (
            lambda h, raw: (self.undo.__setitem__(h, raw), orig(h, raw))[1]
        )
        self.raws = []
        self.t = PARAMS.genesis.header.time
        self.coinbases = []  # (txid, value)
        for _ in range(runway):
            blk = self.push()
            self.coinbases.append((blk.vtx[0].txid, blk.vtx[0].vout[0].value))

    def push(self, txs=()):
        tip = self.cs.tip()
        self.t += 60
        blk = _block(tip.hash, tip.height + 1, self.t, tuple(txs))
        self.cs.process_new_block(blk)
        self.raws.append(blk.serialize())
        return blk

    def spendable(self, i):
        return self.coinbases[i]


@pytest.fixture(scope="module")
def chain():
    c = _Chain()
    # two spend blocks: a fan-out then a many-input spend (sig-dense shape)
    txid, value = c.spendable(0)
    fan = _spend([COutPoint(txid, 0)], [value], n_out=8)
    c.push([fan])
    per = fan.vout[0].value
    spend = _spend([COutPoint(fan.txid, i) for i in range(8)], [per] * 8)
    c.push([spend])
    # a 2-tx chain within one block (intra-block spend)
    txid2, value2 = c.spendable(1)
    a = _spend([COutPoint(txid2, 0)], [value2], n_out=2)
    b = _spend([COutPoint(a.txid, 0)], [a.vout[0].value])
    c.push([a, b])
    return c


def _engine_for(chain) -> native.ConnectEngine:
    eng = native.ConnectEngine()
    genesis = PARAMS.genesis
    eng.set_best(genesis.get_hash())
    for tx in genesis.vtx:
        for i, out in enumerate(tx.vout):
            eng.insert(tx.txid + struct.pack("<I", i), 1, out.value,
                       out.script_pubkey)
    return eng


def _replay(chain, eng, want_sigs=True, upto=None):
    """Run the recorded raw blocks through the native engine; returns the
    per-block NativeConnectResults."""
    results = []
    height = 0
    headers = [PARAMS.genesis.header]
    for raw in chain.raws[:upto]:
        height += 1
        times = sorted(h.time for h in headers[-11:])
        mtp = times[len(times) // 2]
        flags = block_script_flags(height,
                                   struct.unpack_from("<I", raw, 68)[0],
                                   PARAMS)
        res = eng.connect_block(
            raw, height, get_block_subsidy(height, PARAMS.consensus),
            PARAMS.max_block_size, PARAMS.consensus.coinbase_maturity, mtp,
            script_int(height), flags, want_sigs=want_sigs)
        results.append(res)
        headers.append(CBlockHeader.deserialize(ByteReader(raw[:80])))
    return results


def test_undo_blobs_match_python(chain):
    eng = _engine_for(chain)
    results = _replay(chain, eng)
    assert len(results) == len(chain.raws)
    for res in results:
        assert chain.undo[res.block_hash] == res.undo
    assert eng.best() == chain.cs.tip().hash
    eng.close()


def test_flush_rows_match_python_coins(chain):
    eng = _engine_for(chain)
    _replay(chain, eng)
    chain.cs.coins.flush()
    py = {
        op.hash + struct.pack("<I", op.n): coin.serialize()
        for op, coin in chain.cs.coins.base.all_coins()
    }
    nat = {k: ser for k, ser in eng.flush_entries() if ser is not None}
    # the genesis coin was seeded CLEAN into the engine (it is in the base
    # store in real operation) — exclude it from the dirty-flush comparison
    gen_txid = PARAMS.genesis.vtx[0].txid
    py.pop(gen_txid + struct.pack("<I", 0), None)
    assert nat == py
    eng.close()


def test_sigscan_matches_interpreter_records(chain):
    """The native P2PKH scan's (pubkey, r, s, msg) blobs must equal the
    records the Python interpreter defers for the same blocks."""
    eng = _engine_for(chain)
    results = _replay(chain, eng)
    for raw, res in zip(chain.raws, results):
        if res.n_inputs == 0:
            continue
        assert int((res.sig_status == 0).sum()) == res.n_inputs
        block = CBlock.from_bytes(raw)
        height = chain.cs.block_index[res.block_hash].height
        flags = block_script_flags(height, block.header.time, PARAMS)
        g = 0
        for t_i, tx in enumerate(block.vtx[1:], start=1):
            cache = SighashCache(tx)
            for in_i, txin in enumerate(tx.vin):
                records = []
                spk = bytes(res.spent_spk_blob[
                    int(res.spent_spk_offsets[g]):
                    int(res.spent_spk_offsets[g + 1])])
                checker = DeferringSignatureChecker(
                    tx, in_i, int(res.spent_values[g]), records, cache)
                VerifyScript(txin.script_sig, spk, flags, checker)
                assert len(records) == 1
                rec = records[0]
                assert rec.pubkey[0].to_bytes(32, "big") == \
                    res.sig_pub[g, :32].tobytes()
                assert rec.pubkey[1].to_bytes(32, "big") == \
                    res.sig_pub[g, 32:].tobytes()
                assert rec.r.to_bytes(32, "big") == \
                    res.sig_rs[g, :32].tobytes()
                assert rec.s.to_bytes(32, "big") == \
                    res.sig_rs[g, 32:].tobytes()
                assert rec.msg_hash.to_bytes(32, "big") == \
                    res.sig_msg[g].tobytes()
                assert (t_i, in_i) == (int(res.sig_txin[g, 0]),
                                       int(res.sig_txin[g, 1]))
                g += 1
    eng.close()


def test_dispatch_packed_verifies(chain):
    """End to end: native sigscan blobs through the packed batch dispatch
    (CPU lane here) — all lanes verify; a corrupted message fails its
    lane only."""
    import numpy as np

    from bitcoincashplus_tpu.ops import ecdsa_batch

    eng = _engine_for(chain)
    results = _replay(chain, eng)
    res = next(r for r in results if r.n_inputs >= 8)
    ok = ecdsa_batch.dispatch_packed(
        res.sig_pub, res.sig_rs, res.sig_msg, res.sig_rn, res.sig_wrap,
        backend="cpu").result()
    assert bool(np.all(ok))
    bad_msg = res.sig_msg.copy()
    bad_msg[3, 0] ^= 0xFF
    ok = ecdsa_batch.dispatch_packed(
        res.sig_pub, res.sig_rs, bad_msg, res.sig_rn, res.sig_wrap,
        backend="cpu").result()
    assert not ok[3] and bool(np.all(np.delete(ok, 3)))
    eng.close()


def test_missing_inputs_roundtrip(chain):
    """Spends of flushed-out coins surface as EngineMissing; inserting the
    base rows and retrying succeeds (the import loop's miss servicing)."""
    eng = _engine_for(chain)
    _replay(chain, eng, upto=len(chain.raws) - 1)
    # flush-and-clear, then connect the last block: its inputs are gone
    rows = {k: ser for k, ser in eng.flush_entries()}
    best = eng.best()
    eng.clear()
    eng.set_best(best)
    height = len(chain.raws)
    raw = chain.raws[-1]
    times = sorted(
        CBlockHeader.deserialize(ByteReader(r[:80])).time
        for r in chain.raws[-12:-1]
    )
    mtp = times[len(times) // 2]
    flags = block_script_flags(height, struct.unpack_from("<I", raw, 68)[0],
                               PARAMS)

    def connect():
        return eng.connect_block(
            raw, height, get_block_subsidy(height, PARAMS.consensus),
            PARAMS.max_block_size, PARAMS.consensus.coinbase_maturity, mtp,
            script_int(height), flags, want_sigs=True)

    with pytest.raises(native.EngineMissing) as exc:
        connect()
    for key in exc.value.keys:
        ser = rows.get(key)
        assert ser is not None
        r = ByteReader(ser)
        from bitcoincashplus_tpu.consensus.serialize import (
            deser_compact_size,
            deser_var_bytes,
        )

        code = deser_compact_size(r, range_check=False)
        value = deser_compact_size(r, range_check=False)
        eng.insert(key, code, value, deser_var_bytes(r))
    res = connect()
    assert chain.undo[res.block_hash] == res.undo
    eng.close()


def test_invalid_blocks_rejected_with_matching_reasons(chain):
    """Mutated blocks must be rejected by BOTH engines, and the native
    reason must map onto the Python reject reason."""
    eng = _engine_for(chain)
    _replay(chain, eng, upto=len(chain.raws) - 1)
    height = len(chain.raws)
    raw = bytearray(chain.raws[-1])
    times = sorted(
        CBlockHeader.deserialize(ByteReader(r[:80])).time
        for r in chain.raws[-12:-1]
    )
    mtp = times[len(times) // 2]
    flags = block_script_flags(height, struct.unpack_from("<I", raw, 68)[0],
                               PARAMS)

    def native_verdict(mutated: bytes):
        try:
            eng.connect_block(
                bytes(mutated), height,
                get_block_subsidy(height, PARAMS.consensus),
                PARAMS.max_block_size, PARAMS.consensus.coinbase_maturity,
                mtp, script_int(height), flags, want_sigs=True,
                commit=False)
        except native.EngineError as e:
            eng.abort()
            return e.reason
        except native.EngineMissing:
            eng.abort()
            return "missing"
        eng.abort()
        return None

    def python_verdict(mutated: bytes):
        try:
            blk = CBlock.from_bytes(bytes(mutated))
        except Exception:
            return "deserialize"
        try:
            chain.cs.check_block(blk, check_pow=False)
            # context + connect on a throwaway view
            from bitcoincashplus_tpu.validation.coins import CoinsCache
            from bitcoincashplus_tpu.validation.chain import CBlockIndex

            idx = CBlockIndex(blk.header, blk.get_hash(), chain.cs.tip())
            chain.cs.connect_block(blk, idx, check_scripts=False,
                                   view=CoinsCache(chain.cs.coins))
        except BlockValidationError as e:
            return e.reason
        return None

    # merkle-root corruption
    bad = bytearray(raw)
    bad[40] ^= 0xFF
    assert native_verdict(bad) == "bad-txnmrklroot" == python_verdict(bad)
    # truncated tail
    bad = raw[: len(raw) - 3]
    assert native_verdict(bad) == "deserialize" == python_verdict(bad)
    # valid block connects cleanly in both (sanity that the fixture works)
    assert native_verdict(raw) is None
    eng.close()


def test_clean_inserts_not_flushed(chain):
    eng = native.ConnectEngine()
    eng.insert(b"\x11" * 36, 7, 1234, b"\x51")
    assert eng.get(b"\x11" * 36) == (7, 1234, b"\x51")
    assert eng.flush_entries() == []
    assert eng.entries() == 1
    eng.clear()
    assert eng.entries() == 0
    eng.close()


def test_mutation_matrix_verdicts_agree(chain):
    """Broader native-vs-Python verdict agreement: structured mutations of
    a valid block must be rejected by BOTH engines with the same reason
    class (the fast import falls back to Python on any native error, so
    agreement on 'invalid at all' is the safety bar; the reason match is
    the quality bar)."""
    import random

    eng = _engine_for(chain)
    _replay(chain, eng, upto=len(chain.raws) - 1)
    height = len(chain.raws)
    raw = chain.raws[-1]
    times = sorted(
        CBlockHeader.deserialize(ByteReader(r[:80])).time
        for r in chain.raws[-12:-1]
    )
    mtp = times[len(times) // 2]
    flags = block_script_flags(height, struct.unpack_from("<I", raw, 68)[0],
                               PARAMS)

    def native_verdict(mutated: bytes):
        try:
            eng.connect_block(
                bytes(mutated), height,
                get_block_subsidy(height, PARAMS.consensus),
                PARAMS.max_block_size, PARAMS.consensus.coinbase_maturity,
                mtp, script_int(height), flags, want_sigs=True,
                commit=False)
        except native.EngineError as e:
            eng.abort()
            return e.reason
        except native.EngineMissing:
            eng.abort()
            return "missing-inputs"
        eng.abort()
        return None

    # a Python chainstate at height len-1: the fixture's cs already holds
    # the final block, whose coinbase would trip BIP30 and mask the real
    # reason for any mutation that keeps the original coinbase
    cs2 = ChainstateManager(PARAMS, MemoryCoinsView(), MemoryBlockStore(),
                            script_verifier=None)
    for r in chain.raws[:-1]:
        cs2.process_new_block(CBlock.from_bytes(r))

    def python_verdict(mutated: bytes):
        try:
            blk = CBlock.from_bytes(bytes(mutated))
        except Exception:
            return "deserialize"
        from bitcoincashplus_tpu.validation.chain import CBlockIndex
        from bitcoincashplus_tpu.validation.coins import CoinsCache

        try:
            cs2.check_block(blk, check_pow=False)
            idx = CBlockIndex(blk.header, blk.get_hash(), cs2.tip())
            cs2.connect_block(blk, idx, check_scripts=False,
                              view=CoinsCache(cs2.coins))
        except BlockValidationError as e:
            return e.reason
        return None

    block = CBlock.from_bytes(raw)

    def rebuild(vtx, header=None):
        from bitcoincashplus_tpu.consensus.merkle import block_merkle_root

        class _V:
            pass

        v = _V()
        v.vtx = tuple(vtx)
        root, _ = block_merkle_root(v)
        hdr = header or block.header
        hdr = CBlockHeader(
            version=hdr.version, hash_prev_block=hdr.hash_prev_block,
            hash_merkle_root=root, time=hdr.time, bits=hdr.bits,
            nonce=hdr.nonce)
        return CBlock(hdr, tuple(vtx)).serialize()

    spend = block.vtx[1]
    cases = []
    # duplicate input within a tx
    t = CTransaction(spend.version,
                     (spend.vin[0], spend.vin[0]) + spend.vin[1:],
                     spend.vout, spend.locktime)
    cases.append(("dup-input", rebuild([block.vtx[0], t])))
    # output value negative
    t = CTransaction(spend.version, spend.vin,
                     (CTxOut(-1, spend.vout[0].script_pubkey),),
                     spend.locktime)
    cases.append(("neg-value", rebuild([block.vtx[0], t])))
    # in < out (value conjured from nowhere)
    t = CTransaction(spend.version, spend.vin,
                     (CTxOut(spend.vout[0].value + 10**12,
                             spend.vout[0].script_pubkey),),
                     spend.locktime)
    cases.append(("in-below-out", rebuild([block.vtx[0], t])))
    # spend of a nonexistent outpoint
    t = CTransaction(spend.version,
                     (CTxIn(COutPoint(b"\x77" * 32, 1), spend.vin[0].script_sig,
                            0xFFFFFFFE),),
                     spend.vout, spend.locktime)
    cases.append(("missing-prevout", rebuild([block.vtx[0], t])))
    # double coinbase
    cases.append(("double-coinbase",
                  rebuild([block.vtx[0], block.vtx[0], *block.vtx[1:]])))
    # no coinbase first
    cases.append(("cb-not-first", rebuild(list(block.vtx[1:]))))
    # corrupt a signature byte (NULLFAIL-era: script error)
    mutated = bytearray(raw)
    # find the first scriptSig push in the spend tx region and flip a byte
    off = raw.index(spend.vin[0].script_sig[:20])
    mutated[off + 5] ^= 0x01
    cases.append(("bad-sig-byte", bytes(mutated)))
    # random byte flips (parse-level chaos)
    rng = random.Random(7)
    for i in range(20):
        m = bytearray(raw)
        pos = rng.randrange(80, len(m))
        m[pos] ^= 1 << rng.randrange(8)
        cases.append((f"flip-{pos}", bytes(m)))

    for name, mut in cases:
        nv = native_verdict(mut)
        pv = python_verdict(mut)
        if name == "bad-sig-byte":
            # native catches it in the sigscan; the scripts-off python
            # connect above doesn't check sigs — native must reject, and
            # the full python interpreter agrees (covered by the
            # scriptcheck differential suites); only assert native reject
            assert nv is not None, name
            continue
        assert (nv is None) == (pv is None), (name, nv, pv)
        if nv is not None and nv != "missing-inputs" \
                and pv != "bad-txns-duplicate" and nv != "deserialize":
            # exact reason match, modulo check-order differences where a
            # mutation violates several rules at once
            assert nv == pv or {nv, pv} <= {
                "bad-txns-inputs-missingorspent", "bad-txns-BIP30",
                "bad-cb-multiple", "bad-txnmrklroot",
            }, (name, nv, pv)
    eng.close()


def test_fast_import_falls_back_on_invalid_block(tmp_path):
    """Node-level fast/slow interplay: a blk file containing a valid chain,
    an INVALID block (premature coinbase spend), then more valid blocks on
    the honest tip. The native fast path must reject the bad block, defer
    to the Python engine for the authoritative verdict, and keep importing
    the valid remainder."""
    import os

    from bitcoincashplus_tpu.node.config import Config
    from bitcoincashplus_tpu.node.node import Node
    from bitcoincashplus_tpu.store.blockstore import BlockStore
    from bitcoincashplus_tpu.store.chainstatedb import BlockIndexDB, CoinsDB
    from bitcoincashplus_tpu.store.kvstore import KVStore
    from bitcoincashplus_tpu.validation.chain import BlockStatus

    net_dir = os.path.join(tmp_path, "regtest")
    blocks_dir = os.path.join(net_dir, "blocks")
    os.makedirs(blocks_dir, exist_ok=True)
    index_kv = KVStore(os.path.join(blocks_dir, "index.sqlite"))
    coins_kv = KVStore(os.path.join(net_dir, "chainstate.sqlite"))
    store = BlockStore(net_dir, PARAMS.netmagic)
    cs = ChainstateManager(PARAMS, CoinsDB(coins_kv), store,
                           script_verifier=None,
                           index_db=BlockIndexDB(index_kv))

    t = PARAMS.genesis.header.time
    coinbases = []
    for _ in range(103):
        t += 60
        tip = cs.tip()
        blk = _block(tip.hash, tip.height + 1, t, ())
        cs.process_new_block(blk)
        coinbases.append((blk.vtx[0].txid, blk.vtx[0].vout[0].value))

    # invalid: spends the height-103 coinbase at height 104 (immature) —
    # write the raw record into the blk file BEHIND the store's back
    tip = cs.tip()
    bad_spend = _spend([COutPoint(coinbases[-1][0], 0)], [coinbases[-1][1]])
    t += 60
    bad = _block(tip.hash, tip.height + 1, t, (bad_spend,))
    # valid continuation on the same tip: spends the MATURE height-1 coin
    good_spend = _spend([COutPoint(coinbases[0][0], 0)], [coinbases[0][1]])
    good = _block(tip.hash, tip.height + 1, t + 60, (good_spend,))
    raw_bad = bad.serialize()
    raw_good = good.serialize()
    with open(os.path.join(blocks_dir, "blk00000.dat"), "ab") as f:
        f.write(PARAMS.netmagic + struct.pack("<I", len(raw_bad)) + raw_bad)
        f.write(PARAMS.netmagic + struct.pack("<I", len(raw_good)) + raw_good)
    cs.flush()
    store.close()
    index_kv.close()
    coins_kv.close()

    cfg = Config()
    cfg.args["datadir"] = [str(tmp_path)]
    cfg.args["regtest"] = ["1"]
    cfg.args["reindex"] = ["1"]
    node = Node(config=cfg)
    try:
        assert node.chainstate.tip().hash == good.get_hash()
        bad_idx = node.chainstate.block_index.get(bad.get_hash())
        assert bad_idx is not None
        assert bad_idx.status & BlockStatus.FAILED_MASK
        if node.last_import_stats:  # native path ran
            assert node.last_import_stats["slow_path_blocks"] >= 1
    finally:
        node.close()


@pytest.mark.skipif(not os.environ.get("BCP_SLOW_TESTS"),
                    reason="slow randomized campaign (BCP_SLOW_TESTS=1)")
def test_randomized_differential_campaign():
    """170-block randomized stream (random input counts, fan-outs,
    intra-block chains) through both engines: identical undo blobs and
    final coin sets. Run with BCP_SLOW_TESTS=1 (several minutes)."""
    import random

    rng = random.Random(20260731)
    chain = _Chain(runway=140)
    heights = {txid: i + 1 for i, (txid, _v) in enumerate(chain.coinbases)}
    for _bi in range(30):
        txs = []
        next_h = chain.cs.tip().height + 1
        mature = [e for e in chain.coinbases
                  if next_h - heights[e[0]] >= 100]
        for _ in range(rng.randrange(1, 4)):
            if not mature:
                break
            txid, value = mature.pop(rng.randrange(len(mature)))
            chain.coinbases.remove((txid, value))
            t = _spend([COutPoint(txid, 0)], [value],
                       n_out=rng.randrange(1, 5))
            txs.append(t)
            if rng.random() < 0.5:
                t2 = _spend([COutPoint(t.txid, 0)], [t.vout[0].value])
                txs.append(t2)
        blk = chain.push(txs)
        assert chain.cs.tip().height == next_h
        chain.coinbases.append((blk.vtx[0].txid, blk.vtx[0].vout[0].value))
        heights[blk.vtx[0].txid] = next_h

    eng = _engine_for(chain)
    results = _replay(chain, eng)
    assert all(chain.undo[res.block_hash] == res.undo for res in results)
    chain.cs.coins.flush()
    py = {op.hash + struct.pack("<I", op.n): c.serialize()
          for op, c in chain.cs.coins.base.all_coins()}
    py.pop(PARAMS.genesis.vtx[0].txid + struct.pack("<I", 0), None)
    nat = {k: s for k, s in eng.flush_entries() if s is not None}
    assert nat == py
    eng.close()
