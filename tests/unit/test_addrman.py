"""AddrMan — lifecycle, selection, persistence + addr wire codec
(src/test/addrman_tests.cpp analogues at the collapsed-table level)."""

import time

from bitcoincashplus_tpu.p2p.addrman import AddrMan
from bitcoincashplus_tpu.p2p.protocol import (
    deser_addr_entries,
    ser_addr_entries,
)


class TestAddrMan:
    def test_add_and_dedup(self):
        am = AddrMan()
        assert am.add("10.0.0.1", 8333) is True
        assert am.add("10.0.0.1", 8333) is False  # refresh, not new
        assert am.add("10.0.0.1", 8334) is True  # different port = new
        assert len(am) == 2

    def test_good_promotes_to_tried(self):
        am = AddrMan()
        am.add("10.0.0.1", 8333)
        am.addrs["10.0.0.1:8333"].attempts = 2
        am.good("10.0.0.1", 8333)
        a = am.addrs["10.0.0.1:8333"]
        assert a.tried and a.attempts == 0

    def test_select_excludes_connected_and_failed(self):
        am = AddrMan()
        am.add("10.0.0.1", 1)
        am.add("10.0.0.2", 2)
        # exhausted retries with a recent failure: not selected...
        am.addrs["10.0.0.1:1"].attempts = 10
        am.addrs["10.0.0.1:1"].last_try = time.time() - 60
        for _ in range(20):
            got = am.select()
            assert got is not None and got.key == "10.0.0.2:2"
        assert am.select(exclude={"10.0.0.2:2"}) is None
        # ...but the cutoff is time-windowed, not permanent (IsTerrible)
        am.addrs["10.0.0.1:1"].last_try = time.time() - 7200
        assert am.select(exclude={"10.0.0.2:2"}).key == "10.0.0.1:1"

    def test_recent_failure_backoff(self):
        am = AddrMan()
        am.add("10.0.0.1", 1)
        am.attempt("10.0.0.1", 1)
        assert am.select() is None  # just failed: in backoff
        am.addrs["10.0.0.1:1"].last_try = time.time() - 3600
        assert am.select() is not None

    def test_persistence_roundtrip(self, tmp_path):
        am = AddrMan()
        am.add("10.0.0.1", 8333, services=5)
        am.good("10.0.0.1", 8333)
        am.add("192.168.1.9", 18444)
        path = str(tmp_path / "peers.json")
        am.save(path)
        am2 = AddrMan()
        assert am2.load(path) == 2
        a = am2.addrs["10.0.0.1:8333"]
        assert a.tried and a.services == 5
        assert not am2.addrs["192.168.1.9:18444"].tried

    def test_corrupt_file_tolerated(self, tmp_path):
        path = str(tmp_path / "peers.json")
        with open(path, "w") as f:
            f.write("{ not json")
        assert AddrMan().load(path) == 0

    def test_addresses_sample_is_fresh(self):
        am = AddrMan()
        am.add("10.0.0.1", 1, seen_time=int(time.time()))
        am.add("10.0.0.2", 2, seen_time=100)  # decades stale
        got = am.addresses()
        assert [a.key for a in got] == ["10.0.0.1:1"]


class TestAddrCodec:
    def test_roundtrip(self):
        entries = [(1_700_000_000, 1, "127.0.0.1", 18444),
                   (1_700_000_100, 9, "10.1.2.3", 8333)]
        back = deser_addr_entries(ser_addr_entries(entries))
        assert back == entries

    def test_oversized_rejected(self):
        import pytest

        from bitcoincashplus_tpu.consensus.serialize import ser_compact_size
        from bitcoincashplus_tpu.p2p.protocol import NetMessageError

        with pytest.raises(NetMessageError):
            deser_addr_entries(ser_compact_size(50_000))
