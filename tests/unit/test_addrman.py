"""AddrMan — lifecycle, selection, persistence + addr wire codec
(src/test/addrman_tests.cpp analogues at the collapsed-table level)."""

import time

from bitcoincashplus_tpu.p2p.addrman import AddrMan
from bitcoincashplus_tpu.p2p.protocol import (
    deser_addr_entries,
    ser_addr_entries,
)


class TestAddrMan:
    def test_add_and_dedup(self):
        # deterministic bucket keys: with OS-entropy siphash keys the two
        # ports collide on the same (bucket, slot) in ~1.4% of processes
        # and the healthy incumbent defends it — a coin-flip failure, not
        # a dedup regression (slot defense itself is covered below)
        am = AddrMan(seed=0)
        assert am.add("10.0.0.1", 8333) is True
        assert am.add("10.0.0.1", 8333) is False  # refresh, not new
        assert am.add("10.0.0.1", 8334) is True  # different port = new
        assert len(am) == 2

    def test_good_promotes_to_tried(self):
        am = AddrMan()
        am.add("10.0.0.1", 8333)
        am.addrs["10.0.0.1:8333"].attempts = 2
        am.good("10.0.0.1", 8333)
        a = am.addrs["10.0.0.1:8333"]
        assert a.tried and a.attempts == 0

    def test_select_excludes_connected_and_failed(self):
        am = AddrMan()
        am.add("10.0.0.1", 1)
        am.add("10.0.0.2", 2)
        # exhausted retries with a recent failure: not selected...
        am.addrs["10.0.0.1:1"].attempts = 10
        am.addrs["10.0.0.1:1"].last_try = time.time() - 60
        for _ in range(20):
            got = am.select()
            assert got is not None and got.key == "10.0.0.2:2"
        assert am.select(exclude={"10.0.0.2:2"}) is None
        # ...but the cutoff is time-windowed, not permanent (IsTerrible)
        am.addrs["10.0.0.1:1"].last_try = time.time() - 7200
        assert am.select(exclude={"10.0.0.2:2"}).key == "10.0.0.1:1"

    def test_recent_failure_backoff(self):
        am = AddrMan()
        am.add("10.0.0.1", 1)
        am.attempt("10.0.0.1", 1)
        assert am.select() is None  # just failed: in backoff
        am.addrs["10.0.0.1:1"].last_try = time.time() - 3600
        assert am.select() is not None

    def test_persistence_roundtrip(self, tmp_path):
        am = AddrMan()
        am.add("10.0.0.1", 8333, services=5)
        am.good("10.0.0.1", 8333)
        am.add("192.168.1.9", 18444)
        path = str(tmp_path / "peers.json")
        am.save(path)
        am2 = AddrMan()
        assert am2.load(path) == 2
        a = am2.addrs["10.0.0.1:8333"]
        assert a.tried and a.services == 5
        assert not am2.addrs["192.168.1.9:18444"].tried

    def test_corrupt_file_tolerated(self, tmp_path):
        path = str(tmp_path / "peers.json")
        with open(path, "w") as f:
            f.write("{ not json")
        assert AddrMan().load(path) == 0

    def test_addresses_sample_is_fresh(self):
        am = AddrMan()
        am.add("10.0.0.1", 1, seen_time=int(time.time()))
        am.add("10.0.0.2", 2, seen_time=100)  # decades stale
        got = am.addresses()
        assert [a.key for a in got] == ["10.0.0.1:1"]


class TestAddrCodec:
    def test_roundtrip(self):
        entries = [(1_700_000_000, 1, "127.0.0.1", 18444),
                   (1_700_000_100, 9, "10.1.2.3", 8333)]
        back = deser_addr_entries(ser_addr_entries(entries))
        assert back == entries

    def test_oversized_rejected(self):
        import pytest

        from bitcoincashplus_tpu.consensus.serialize import ser_compact_size
        from bitcoincashplus_tpu.p2p.protocol import NetMessageError

        with pytest.raises(NetMessageError):
            deser_addr_entries(ser_compact_size(50_000))


class TestBucketing:
    """Eclipse-resistance properties of the 1024/256 bucket layout
    (src/addrman.h ADDRMAN_* constants; addrman_tests.cpp shapes)."""

    def test_single_source_group_is_capacity_bounded(self):
        """One /16 source announcing thousands of addresses can occupy at
        most NEW_BUCKETS_PER_SOURCE_GROUP * BUCKET_SIZE new slots."""
        from bitcoincashplus_tpu.p2p.addrman import (
            BUCKET_SIZE,
            NEW_BUCKETS_PER_SOURCE_GROUP,
            AddrMan,
        )

        am = AddrMan(seed=7)
        added = 0
        # 10k distinct addresses, all announced by sources in ONE /16
        for i in range(10_000):
            host = f"{(i >> 16) & 255}.{(i >> 8) & 255}.{i & 255}.7"
            if am.add(host, 8333, source=f"66.66.{i & 3}.{i & 7}"):
                added += 1
        cap = NEW_BUCKETS_PER_SOURCE_GROUP * BUCKET_SIZE
        assert added <= cap, (added, cap)
        assert len(am) == added
        # distinct buckets reached must not exceed the per-source-group cap
        buckets = {b for (b, _s) in am.new_tbl}
        assert len(buckets) <= NEW_BUCKETS_PER_SOURCE_GROUP

    def test_diverse_sources_reach_more_buckets(self):
        from bitcoincashplus_tpu.p2p.addrman import (
            NEW_BUCKETS_PER_SOURCE_GROUP,
            AddrMan,
        )

        am = AddrMan(seed=8)
        for i in range(4_000):
            host = f"10.{(i >> 8) & 255}.{i & 255}.9"
            src = f"{(i * 13) & 255}.{(i * 7) & 255}.1.1"  # many /16 groups
            am.add(host, 8333, source=src)
        buckets = {b for (b, _s) in am.new_tbl}
        assert len(buckets) > NEW_BUCKETS_PER_SOURCE_GROUP

    def test_healthy_incumbent_defends_slot(self):
        from bitcoincashplus_tpu.p2p.addrman import AddrMan

        am = AddrMan(seed=9)
        # fill the attacker's reachable slots with fresh (healthy) entries,
        # then flood again: the flood must not displace anything
        for i in range(6_000):
            am.add(f"10.0.{(i >> 8) & 255}.{i & 255}", 1, source="6.6.1.1")
        before = set(am.addrs)
        for i in range(6_000):
            am.add(f"11.1.{(i >> 8) & 255}.{i & 255}", 1, source="6.6.1.1")
        # every pre-existing fresh entry survived the second flood
        assert before <= set(am.addrs)

    def test_stale_incumbent_is_evicted(self):
        import time as _t

        from bitcoincashplus_tpu.p2p.addrman import AddrMan

        am = AddrMan(seed=10)
        stale_seen = int(_t.time()) - 90 * 86400  # far past the horizon
        for i in range(3_000):
            am.add(f"10.0.{(i >> 8) & 255}.{i & 255}", 1,
                   seen_time=stale_seen, source="6.6.1.1")
        n_stale = len(am)
        for i in range(3_000):
            am.add(f"11.1.{(i >> 8) & 255}.{i & 255}", 1, source="6.6.1.1")
        # fresh flood displaced stale incumbents (same buckets reachable)
        fresh = [k for k, a in am.addrs.items() if a.time > stale_seen]
        assert len(fresh) >= n_stale // 2

    def test_tried_collision_displaces_back_to_new(self):
        from bitcoincashplus_tpu.p2p.addrman import AddrMan

        am = AddrMan(seed=11)
        # force a tried-slot collision by promoting many addresses in one
        # network group (tried buckets per group = 8, slots = 64 => >512
        # promotions MUST collide)
        n = 700
        for i in range(n):
            host = f"10.9.{(i >> 8) & 255}.{i & 255}"
            am.add(host, 1, source="1.2.3.4")
            am.good(host, 1)
        tried = [a for a in am.addrs.values() if a.tried]
        displaced = [a for a in am.addrs.values() if not a.tried]
        assert len(tried) <= 8 * 64
        # displaced incumbents were returned to the new table, not lost
        assert len(tried) + len(displaced) == len(am)
        assert all(
            am._pos[a.key][0] == ("tried" if a.tried else "new")
            for a in am.addrs.values()
        )

    def test_persistence_keeps_bucket_key_and_tables(self, tmp_path):
        from bitcoincashplus_tpu.p2p.addrman import AddrMan

        am = AddrMan(seed=12)
        for i in range(100):
            am.add(f"10.3.{i}.1", 8333, source=f"{i & 7}.1.1.1")
        am.good("10.3.5.1", 8333)
        path = str(tmp_path / "peers.json")
        am.save(path)
        am2 = AddrMan(seed=99)
        am2.load(path)
        assert (am2._k0, am2._k1) == (am._k0, am._k1)
        assert am2.addrs["10.3.5.1:8333"].tried
        # every loaded entry has a consistent table position
        for key, pos in am2._pos.items():
            tbl = am2.new_tbl if pos[0] == "new" else am2.tried_tbl
            assert tbl[(pos[1], pos[2])] == key
