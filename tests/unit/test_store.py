"""Persistence tests: KV batch atomicity, block files, coins DB round-trip —
the reference's dbwrapper_tests.cpp / coins_tests.cpp flush coverage."""

import os

import pytest

from bitcoincashplus_tpu.consensus.params import regtest_params
from bitcoincashplus_tpu.consensus.tx import COutPoint, CTxOut
from bitcoincashplus_tpu.store.blockstore import BlockStore, MemoryBlockStore
from bitcoincashplus_tpu.store.chainstatedb import BlockIndexDB, CoinsDB
from bitcoincashplus_tpu.store.kvstore import KVStore
from bitcoincashplus_tpu.validation.coins import Coin, CoinsCache


class TestKVStore:
    def test_put_get_delete(self, tmp_path):
        kv = KVStore(str(tmp_path / "kv.sqlite"))
        kv.put(b"a", b"1")
        assert kv.get(b"a") == b"1"
        kv.put(b"a", b"2")
        assert kv.get(b"a") == b"2"
        kv.delete(b"a")
        assert kv.get(b"a") is None

    def test_batch_and_ordered_iteration(self, tmp_path):
        kv = KVStore(str(tmp_path / "kv.sqlite"))
        kv.write_batch({b"Cb": b"2", b"Ca": b"1", b"D": b"x"}, [])
        assert [k for k, _ in kv.iterate(b"C")] == [b"Ca", b"Cb"]
        kv.write_batch({}, [b"Ca"])
        assert [k for k, _ in kv.iterate(b"C")] == [b"Cb"]

    def test_reopen_persists(self, tmp_path):
        path = str(tmp_path / "kv.sqlite")
        kv = KVStore(path)
        kv.write_batch({b"k": b"v"}, [], sync=True)
        kv.close()
        assert KVStore(path).get(b"k") == b"v"


class TestBlockStore:
    def test_roundtrip_and_framing(self, tmp_path):
        params = regtest_params()
        bs = BlockStore(str(tmp_path), params.netmagic)
        raw = params.genesis.serialize()
        h = params.genesis_hash
        bs.put_block(h, raw)
        bs.put_undo(h, b"\x00")
        assert bs.get_block(h) == raw
        assert bs.get_undo(h) == b"\x00"
        bs.flush()
        # on-disk framing: netmagic + LE size + payload (reference layout)
        with open(os.path.join(str(tmp_path), "blocks", "blk00000.dat"), "rb") as f:
            data = f.read()
        assert data[:4] == params.netmagic
        assert int.from_bytes(data[4:8], "little") == len(raw)
        assert data[8 : 8 + len(raw)] == raw

    def test_positions_reusable_after_reopen(self, tmp_path):
        params = regtest_params()
        bs = BlockStore(str(tmp_path), params.netmagic)
        raw = params.genesis.serialize()
        h = params.genesis_hash
        bs.put_block(h, raw)
        pos = bs.positions[h]
        bs.flush()
        bs.close()
        bs2 = BlockStore(str(tmp_path), params.netmagic)
        bs2.positions[h] = pos  # normally restored via BlockIndexDB
        assert bs2.get_block(h) == raw


class TestCoinsDB:
    def test_flush_and_reload(self, tmp_path):
        kv = KVStore(str(tmp_path / "chainstate.sqlite"))
        db = CoinsDB(kv)
        cache = CoinsCache(db)
        op = COutPoint(b"\xaa" * 32, 1)
        coin = Coin(CTxOut(777, b"\x51"), 9, False)
        cache.add_coin(op, coin)
        cache.set_best_block(b"\xbb" * 32)
        cache.flush()
        # fresh cache over the same DB sees the flushed state
        cache2 = CoinsCache(CoinsDB(kv))
        assert cache2.get_coin(op) == coin
        assert cache2.best_block() == b"\xbb" * 32
        # spend + flush removes it
        cache2.spend_coin(op)
        cache2.flush()
        assert CoinsDB(kv).get_coin(op) is None

    def test_tombstone_layering(self, tmp_path):
        kv = KVStore(str(tmp_path / "cs.sqlite"))
        db = CoinsDB(kv)
        l1 = CoinsCache(db)
        op = COutPoint(b"\xcc" * 32, 0)
        l1.add_coin(op, Coin(CTxOut(5, b""), 1, False))
        l2 = CoinsCache(l1)
        assert l2.get_coin(op) is not None
        l2.spend_coin(op)
        assert l2.get_coin(op) is None
        assert l1.get_coin(op) is not None  # not yet merged
        l2.flush()
        assert l1.get_coin(op) is None  # tombstone propagated


class TestBlockIndexDB:
    def test_index_roundtrip(self, tmp_path):
        params = regtest_params()
        kv = KVStore(str(tmp_path / "index.sqlite"))
        db = BlockIndexDB(kv)
        h = params.genesis_hash
        db.put_index_batch(
            [(h, params.genesis.header.serialize(), 0, 0x1D, 1, (0, 8, 285), None)]
        )
        rows = list(db.iterate_index())
        assert len(rows) == 1
        rh, header, height, status, n_tx, blkpos, undopos = rows[0]
        assert rh == h
        assert header.get_hash() == h
        assert (height, status, n_tx) == (0, 0x1D, 1)
        assert blkpos == (0, 8, 285) and undopos is None

    def test_flags(self, tmp_path):
        kv = KVStore(str(tmp_path / "index.sqlite"))
        db = BlockIndexDB(kv)
        assert not db.get_flag(b"txindex")
        db.put_flag(b"txindex", True)
        assert db.get_flag(b"txindex")


def test_concurrent_write_batches_serialize(tmp_path):
    """Two threads batching into one store must not interleave sqlite
    transactions ('cannot start a transaction within a transaction' — the
    txindex-backfill-vs-init race)."""
    import threading

    from bitcoincashplus_tpu.store.kvstore import KVStore

    kv = KVStore(str(tmp_path / "kv.sqlite"))
    errors = []

    def writer(tag: bytes):
        try:
            for i in range(200):
                kv.write_batch({tag + bytes([i % 256]): tag * 4})
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=writer, args=(bytes([t]),))
               for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    assert kv.get(b"\x00\x00") is not None
    kv.close()
