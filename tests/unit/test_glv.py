"""GLV verification kernel tests (ISSUE 5).

Host-side suite (decomposition lattice, fixed-base comb tables, packer
shapes) is plain-fast. Kernel differentials run the GLV program at its
floor bucket (1024) — one XLA compile, persistent-cached (conftest) —
against both the w4 oracle kernel and the pure-CPU verifier, including
the adversarial edge corpus (wrap-claim lanes, k2=0 splits, λ-boundary
scalars, negative-half decompositions, u1=0, poisoned lanes). The 10k
random corpus differential is `slow`-marked like the other full kernel
differentials; the `glv` marker selects this suite (ordered with the
unit group by conftest).
"""

import random

import numpy as np
import pytest

from bitcoincashplus_tpu.crypto import secp256k1 as oracle
from bitcoincashplus_tpu.ops import ecdsa_batch
from bitcoincashplus_tpu.ops import secp256k1 as dev
from bitcoincashplus_tpu.script.interpreter import SigCheckRecord

rng = random.Random(1905)

pytestmark = pytest.mark.glv


def _recompose(k):
    s1, n1, s2, n2 = dev.glv_decompose(k)
    k1 = -s1 if n1 else s1
    k2 = -s2 if n2 else s2
    return (k1 + k2 * dev.LAMBDA) % oracle.N


def test_glv_constants():
    assert pow(dev.LAMBDA, 3, oracle.N) == 1 and dev.LAMBDA != 1
    assert pow(dev.BETA, 3, oracle.P) == 1 and dev.BETA != 1
    # φ(G) = λ·G — the endomorphism the kernel's λ streams rely on
    assert oracle.point_mul(dev.LAMBDA, oracle.G) == (
        dev.BETA * oracle.GX % oracle.P, oracle.GY)
    # the lattice basis annihilates λ mod n
    assert (dev._GLV_A1 - dev._GLV_MINUS_B1 * dev.LAMBDA) % oracle.N == 0
    assert (dev._GLV_A2 + dev._GLV_B2 * dev.LAMBDA) % oracle.N == 0


def test_glv_decompose_properties():
    cases = [0, 1, 2, oracle.N - 1, oracle.N - 2, dev.LAMBDA,
             dev.LAMBDA - 1, dev.LAMBDA + 1, oracle.N - dev.LAMBDA,
             oracle.N // 2, 1 << 128, (1 << 128) - 1, 1 << 255]
    cases += [rng.randrange(oracle.N) for _ in range(3000)]
    sign_combos = set()
    for k in cases:
        s1, n1, s2, n2 = dev.glv_decompose(k)
        assert s1 < (1 << 128) and s2 < (1 << 128), k
        assert _recompose(k) == k % oracle.N, k
        sign_combos.add((n1, n2))
    # the corpus must hit every sign quadrant (negative-half scalars)
    assert sign_combos == {(0, 0), (0, 1), (1, 0), (1, 1)}
    # k2 = 0 split: tiny scalars stay in the first lattice cell
    assert dev.glv_decompose(5) == (5, 0, 0, 0)


def test_glv_comb_tables():
    gx, gy, lx = dev._glv_comb()
    T = dev.GLV_COMB_TEETH
    assert gx.shape == gy.shape == lx.shape == (T, 512, dev.N_LIMBS)
    assert dev.GLV_TABLE_BUILD_S > 0.0  # build time surfaced (gettpuinfo)
    for i, d in ((0, 1), (0, 255), (4, 129), (T - 1, 7)):
        pt = oracle.point_mul(d * (1 << (8 * i)), oracle.G)
        assert dev.from_limbs_np(gx[i, d]) == pt[0]
        assert dev.from_limbs_np(gy[i, d]) == pt[1]
        # sign half: negated y, same x
        assert dev.from_limbs_np(gx[i, 256 + d]) == pt[0]
        assert dev.from_limbs_np(gy[i, 256 + d]) == oracle.P - pt[1]
        # λ stream: x mapped through β (φ leaves y alone)
        assert dev.from_limbs_np(lx[i, d]) == pt[0] * dev.BETA % oracle.P
    # d = 0 slots are the masked dummy (= d = 1), never garbage
    assert dev.from_limbs_np(gx[0, 0]) == oracle.GX
    # built once per process: same object back
    assert dev._glv_comb() is dev._glv_comb()


def test_kernel_selection_knob():
    old = ecdsa_batch._KERNEL
    try:
        assert ecdsa_batch.set_kernel("w4") == "w4"
        assert ecdsa_batch.active_kernel() == "w4"
        assert ecdsa_batch.set_kernel("glv") == "glv"
        with pytest.raises(ValueError, match="ecdsakernel"):
            ecdsa_batch.set_kernel("turbo9000")
        assert ecdsa_batch.active_kernel() == "glv"  # rejected = unchanged
    finally:
        ecdsa_batch._KERNEL = old


def test_node_rejects_unknown_kernel_at_startup(tmp_path):
    from bitcoincashplus_tpu.node.config import Config, ConfigError
    from bitcoincashplus_tpu.node.node import Node

    cfg = Config()
    cfg.args["datadir"] = [str(tmp_path)]
    cfg.args["regtest"] = ["1"]
    cfg.args["ecdsakernel"] = ["frobnicate"]
    old = ecdsa_batch._KERNEL
    try:
        with pytest.raises(ConfigError, match="frobnicate"):
            Node(config=cfg)
    finally:
        ecdsa_batch._KERNEL = old


def test_glv_failure_bookkeeping():
    """Programming errors in the GLV leg re-raise (no silent w4 green);
    toolchain errors latch, transients don't — mirror of the pallas
    bookkeeping invariant."""
    before = ecdsa_batch.STATS.glv_fallbacks
    with pytest.raises(NameError):
        ecdsa_batch._note_glv_failure(NameError("name '_GONE' is not defined"))
    old = ecdsa_batch._GLV_BROKEN
    try:
        ecdsa_batch._note_glv_failure(RuntimeError("transient sneeze"))
        assert ecdsa_batch.STATS.glv_fallbacks == before + 1
        assert not ecdsa_batch._GLV_BROKEN
        ecdsa_batch._note_glv_failure(RuntimeError("Mosaic lowering died"))
        assert ecdsa_batch._GLV_BROKEN
    finally:
        ecdsa_batch._GLV_BROKEN = old


def test_pack_records_glv_shapes_and_poison():
    recs = _records_with_scalars([(rng.randrange(oracle.N),
                                   rng.randrange(1, oracle.N),
                                   rng.randrange(1, oracle.N))
                                  for _ in range(3)])
    arrays = ecdsa_batch.pack_records_glv([r for r, _ in recs], 8)
    (d1m, d2m, sg1, sg2, s1m, s2m, ydiff, qxb, qyb, qinf, r0b, rnb,
     wrap8) = arrays
    assert d1m.shape == (8, 16) and s1m.shape == (8, 16)
    assert qxb.shape == (8, 32)
    assert qinf.tolist() == [0, 0, 0, 1, 1, 1, 1, 1]  # padding poisoned
    assert not wrap8[3:].any()
    # digit planes reconstruct the lattice split of u1
    rec = recs[0][0]
    w = pow(rec.s, oracle.N - 2, oracle.N)
    u1 = rec.msg_hash * w % oracle.N
    s11, n11, s12, _n12 = dev.glv_decompose(u1)
    assert int.from_bytes(d1m[0].tobytes(), "little") == s11
    assert int.from_bytes(d2m[0].tobytes(), "little") == s12
    assert sg1[0] == n11


def _records_with_scalars(triples):
    """Forge valid signatures with CHOSEN verify scalars: given (u1, u2,
    q) with u2 != 0, R = u1·G + u2·Q determines r = R.x mod n, then
    s = r·u2⁻¹ and e = u1·s reproduce exactly (u1, u2) in the verifier —
    the λ-boundary / k2=0 / negative-half edges become directly
    constructible. Returns [(record, expected_bool)]."""
    out = []
    for u1, u2, q in triples:
        Q = oracle.point_mul(q, oracle.G)
        R = oracle.point_add(oracle.point_mul(u1, oracle.G),
                             oracle.point_mul(u2, Q))
        if R is None:
            continue
        r = R[0] % oracle.N
        if r == 0 or u2 % oracle.N == 0:
            continue
        s = r * pow(u2, oracle.N - 2, oracle.N) % oracle.N
        if s == 0:
            continue
        e = u1 * s % oracle.N
        rec = SigCheckRecord(Q, r, s, e)
        assert oracle.ecdsa_verify(Q, r, s, e)
        out.append((rec, True))
    return out


def _edge_corpus():
    """Adversarial edges: λ-boundary and k2 = 0 scalar splits, every sign
    quadrant, u1 = 0 (comb idle), tiny u2 (ladder nearly idle), bogus
    x-wraparound claims (rn lane + wrap_ok gate), and corrupt twins."""
    L = dev.LAMBDA
    n = oracle.N
    specials = [0, 1, 7, (1 << 128) - 1, L - 1, L, L + 1, n - L, n - 1,
                n // 2, 1 << 127]
    triples = []
    for u2 in specials:
        if u2 % n == 0:
            continue
        triples.append((rng.randrange(n), u2, rng.randrange(1, n)))
    for u1 in specials:
        triples.append((u1, rng.randrange(1, n), rng.randrange(1, n)))
    recs = _records_with_scalars(triples)
    # corrupt twins: same lanes, message nudged -> must be False everywhere
    bad = [(SigCheckRecord(r.pubkey, r.r, r.s, (r.msg_hash + 1) % n), False)
           for r, _ in recs[::3]]
    # bogus wraparound claim: tiny r with wrap_ok admissible — the rn
    # candidate lane is exercised and must still reject
    base = recs[0][0]
    bad.append((SigCheckRecord(base.pubkey, 5, base.s, base.msg_hash),
                False))
    return recs + bad


def _cpu_verdicts(records):
    return [oracle.ecdsa_verify(r.pubkey, r.r, r.s, r.msg_hash)
            for r in records]


def test_glv_kernel_edge_differential():
    """ALWAYS runs (tier-1): the GLV kernel over the adversarial edge
    corpus, bit-identical to the CPU verifier. One bucket-1024 compile,
    persistent-cached."""
    pairs = _edge_corpus()
    records = [r for r, _ in pairs]
    expected = _cpu_verdicts(records)
    assert expected == [e for _, e in pairs]
    got = ecdsa_batch.verify_batch(records, backend="device", kernel="glv")
    assert got.tolist() == expected
    assert ecdsa_batch.STATS.glv_dispatches >= 1


def test_glv_fallback_drill(fault_harness):
    """Dispatch-breaker drill: a poisoned/failed GLV kernel must degrade
    glv -> w4 -> CPU with verdict parity and metered fallbacks."""
    pairs = _edge_corpus()[:10]
    records = [r for r, _ in pairs]
    expected = _cpu_verdicts(records)

    # leg 1: GLV dispatch fails outright -> same-attempt w4 fallback
    fault_harness("fail-always", ops=ecdsa_batch.GLV_SITE)
    before = ecdsa_batch.STATS.glv_fallbacks
    got = ecdsa_batch.verify_batch(records, backend="device", kernel="glv")
    assert got.tolist() == expected
    assert ecdsa_batch.STATS.glv_fallbacks == before + 1

    # leg 2: GLV output poisoned -> the riding KAT lanes catch the lie at
    # settle and the verdict is a fresh CPU re-verification
    fault_harness("poison-output", ops=ecdsa_batch.GLV_SITE)
    kat0 = ecdsa_batch.STATS.kat_failures
    ff0 = ecdsa_batch.STATS.fault_fallback_sigs
    got = ecdsa_batch.verify_batch(records, backend="device", kernel="glv")
    assert got.tolist() == expected
    assert ecdsa_batch.STATS.kat_failures == kat0 + 1
    assert ecdsa_batch.STATS.fault_fallback_sigs >= ff0 + len(records)


@pytest.mark.slow
def test_glv_differential_corpus_10k():
    """The 10k random + adversarial corpus: GLV vs the w4 oracle kernel
    vs the CPU verifier, bit-identical verdicts (acceptance criterion)."""
    from bitcoincashplus_tpu import native

    distinct = []
    sign = native.ecdsa_sign if native.available() else oracle.ecdsa_sign
    for i in range(128):
        d = rng.randrange(1, oracle.N)
        pub = oracle.point_mul(d, oracle.G)
        e = rng.getrandbits(256)
        r, s = sign(d, e)
        if i % 5 == 4:
            e ^= 0xFF  # invalid lanes ride along
        distinct.append(SigCheckRecord(pub, r, s, e))
    edge = [r for r, _ in _edge_corpus()]
    records = [distinct[i % len(distinct)] for i in range(10238 - len(edge))]
    records += edge
    if native.available():
        cpu = list(native.ecdsa_verify_batch(records))
    else:
        cpu = _cpu_verdicts(records)
    glv = ecdsa_batch.verify_batch(records, backend="device", kernel="glv")
    w4 = ecdsa_batch.verify_batch(records, backend="device", kernel="w4")
    assert glv.tolist() == cpu
    assert w4.tolist() == cpu


@pytest.mark.slow
def test_glv_sharded_differential():
    """The GLV program sharded over the 8-chip virtual mesh (parallel/
    sig_shard) agrees with the CPU verifier."""
    from bitcoincashplus_tpu.parallel.sig_shard import verify_batch_sharded

    pairs = _edge_corpus()[:12]
    records = [r for r, _ in pairs]
    expected = _cpu_verdicts(records)
    got = verify_batch_sharded(records, 8, kernel="glv")
    assert got.tolist() == expected


# ---- device-side decomposition (ISSUE 11) ----------------------------------


def _decompose_edge_scalars():
    """Crafted decompose inputs: λ-boundary, k2 = 0 (tiny scalars), u1 = 0,
    max-limb carry patterns (all-ones limbs ripple end to end in the limb
    normalizers), and enough random mass to hit every sign quadrant."""
    n = oracle.N
    specials = [0, 1, 5, 7, dev.LAMBDA - 1, dev.LAMBDA, dev.LAMBDA + 1,
                n - dev.LAMBDA, n - 1, n - 2, n // 2, (1 << 128) - 1,
                1 << 127, 1 << 128, (1 << 255) % n,
                int("1fff" * 16, 16) % n,       # all-ones 13-bit limbs
                int("ffff" * 16, 16),           # all-ones 16-bit limbs
                ((1 << 256) - 1) % n]
    specials += [rng.randrange(n) for _ in range(64)]
    return specials


def _scalar_bytes(ks):
    return np.frombuffer(
        b"".join(k.to_bytes(32, "big") for k in ks), np.uint8
    ).reshape(len(ks), 32)


def test_host_decompose_batch_np_differential():
    """The numpy limb-batch host split (the retained fallback AND the
    packer's vectorized decompose) is bit-identical to glv_decompose."""
    ks = _decompose_edge_scalars()
    m1, n1, m2, n2 = dev.glv_decompose_batch_np(_scalar_bytes(ks))
    quadrants = set()
    for i, k in enumerate(ks):
        s1, e1, s2, e2 = dev.glv_decompose(k)
        got = (int.from_bytes(m1[i].tobytes(), "little"), int(n1[i]),
               int.from_bytes(m2[i].tobytes(), "little"), int(n2[i]))
        assert got == (s1, e1, s2, e2), hex(k)
        quadrants.add((e1, e2))
    assert quadrants == {(0, 0), (0, 1), (1, 0), (1, 1)}


def test_device_decompose_differential():
    """The in-kernel device split (the production hot path since ISSUE
    11) is bit-identical to the glv_decompose host oracle over the
    crafted edge corpus — exact rounding, not estimate-grade."""
    ks = _decompose_edge_scalars()[:32]
    m1, n1, m2, n2 = dev.glv_decompose_device_batch(_scalar_bytes(ks))
    for i, k in enumerate(ks):
        s1, e1, s2, e2 = dev.glv_decompose(k)
        got = (int.from_bytes(m1[i].tobytes(), "little"), int(n1[i]),
               int.from_bytes(m2[i].tobytes(), "little"), int(n2[i]))
        assert got == (s1, e1, s2, e2), hex(k)


def test_field_neg_bytes_np():
    ys = [rng.randrange(oracle.P) for _ in range(16)] + [1, oracle.P - 1]
    got = dev.field_neg_bytes_np(_scalar_bytes(ys))
    for i, y in enumerate(ys):
        assert int.from_bytes(got[i].tobytes(), "big") == oracle.P - y


def test_glv_dev_failure_bookkeeping():
    """Mirror of the GLV/pallas invariant for the device-decompose leg:
    programming errors re-raise, toolchain errors latch, transients
    don't."""
    before = ecdsa_batch.STATS.glv_dev_fallbacks
    with pytest.raises(AttributeError):
        ecdsa_batch._note_glv_dev_failure(
            AttributeError("module has no attribute '_GONE'"))
    old = ecdsa_batch._GLV_DEV_BROKEN
    try:
        ecdsa_batch._note_glv_dev_failure(RuntimeError("transient sneeze"))
        assert ecdsa_batch.STATS.glv_dev_fallbacks == before + 1
        assert not ecdsa_batch._GLV_DEV_BROKEN
        ecdsa_batch._note_glv_dev_failure(
            RuntimeError("NotImplementedError: no lowering"))
        assert ecdsa_batch._GLV_DEV_BROKEN
        assert not ecdsa_batch.glv_dev_enabled()
    finally:
        ecdsa_batch._GLV_DEV_BROKEN = old


def test_glv_dev_fallback_drill(fault_harness):
    """Degradation-ladder drill for the new leg: a failed device-decompose
    dispatch degrades to the HOST-decompose GLV pack (same supervised
    attempt, verdict parity); a poisoned one is caught by the riding KAT
    lanes and settles on the CPU engine."""
    pairs = _edge_corpus()[:10]
    records = [r for r, _ in pairs]
    expected = _cpu_verdicts(records)

    # leg 1: device-decompose fails -> host-decompose GLV (not w4)
    fault_harness("fail-always", ops=ecdsa_batch.GLV_DEV_SITE)
    dev_fb0 = ecdsa_batch.STATS.glv_dev_fallbacks
    glv0 = ecdsa_batch.STATS.glv_dispatches
    w4_fb0 = ecdsa_batch.STATS.glv_fallbacks
    got = ecdsa_batch.verify_batch(records, backend="device", kernel="glv")
    assert got.tolist() == expected
    assert ecdsa_batch.STATS.glv_dev_fallbacks == dev_fb0 + 1
    assert ecdsa_batch.STATS.glv_dispatches == glv0 + 1  # host leg ran
    assert ecdsa_batch.STATS.glv_fallbacks == w4_fb0     # w4 NOT needed

    # leg 2: device-decompose output poisoned -> KAT gate -> CPU engine
    fault_harness("poison-output", ops=ecdsa_batch.GLV_DEV_SITE)
    kat0 = ecdsa_batch.STATS.kat_failures
    got = ecdsa_batch.verify_batch(records, backend="device", kernel="glv")
    assert got.tolist() == expected
    assert ecdsa_batch.STATS.kat_failures == kat0 + 1


def test_glv_dev_retrace_sentinel_and_packer():
    """devicewatch acceptance: >= 3 decompose-program dispatches at
    DISTINCT batch fills stay inside the declared shape budget with
    retraces_unexpected == 0 (the fills share the 1024 bucket — that IS
    the bounded-shape design); one of them rides the cross-block
    LanePacker so the aggregation layer provably feeds the fused
    program; host decompose stays untouched the whole time."""
    from bitcoincashplus_tpu.util import devicewatch as dw

    pw = dw.program("ecdsa_glv_decompose")
    d0 = pw.snapshot()["dispatches"]
    dev0 = ecdsa_batch.STATS.glv_dev_dispatches
    host_dec0 = ecdsa_batch.STATS.glv_decompose_s
    emit0 = ecdsa_batch.STATS.glv_emit_s

    fills = (6, 40, 90)
    pairs = _edge_corpus()
    records = [r for r, _ in pairs]
    expected = _cpu_verdicts(records)
    for i, fill in enumerate(fills):
        recs = [records[j % len(records)] for j in range(fill)]
        exp = [expected[j % len(records)] for j in range(fill)]
        if i == 1:
            packer = ecdsa_batch.LanePacker(backend="device", lanes=fill,
                                            kernel="glv")
            fut = packer.add(recs)
            packer.flush()
            got = fut.result()
        else:
            got = ecdsa_batch.verify_batch(recs, backend="device",
                                           kernel="glv")
        assert got.tolist() == exp, fill

    snap = pw.snapshot()
    assert snap["dispatches"] >= d0 + 3
    assert snap["retraces_unexpected"] == 0
    assert snap["shape_budget"] == ecdsa_batch.PALLAS_SHAPE_BUDGET
    assert snap["shapes"] <= snap["shape_budget"]
    assert ecdsa_batch.STATS.glv_dev_dispatches >= dev0 + 3
    # the device path pays byte EMISSION, never host decompose
    assert ecdsa_batch.STATS.glv_decompose_s == host_dec0
    assert ecdsa_batch.STATS.glv_emit_s > emit0
    info = ecdsa_batch.kernel_info()
    assert info["dev_decompose"]["enabled"]
    assert info["dev_decompose"]["dispatches"] >= 3
    for key in ("decompose_s", "pack_s", "emit_s", "dispatch_s"):
        assert key in info
