"""bcp-tx offline transaction builder (src/bitcoin-tx.cpp equivalent)."""

import json

from bitcoincashplus_tpu.cli.bcp_tx import main
from bitcoincashplus_tpu.consensus.params import regtest_params
from bitcoincashplus_tpu.consensus.serialize import ByteReader
from bitcoincashplus_tpu.consensus.tx import CTransaction
from bitcoincashplus_tpu.script.interpreter import (
    SCRIPT_ENABLE_SIGHASH_FORKID,
    SCRIPT_VERIFY_NULLFAIL,
    SCRIPT_VERIFY_P2SH,
    TransactionSignatureChecker,
    VerifyScript,
)
from bitcoincashplus_tpu.wallet.keys import CKey

KEY = CKey(0xFACE)
TXID = "bb" * 32


def _run(capsys, *args) -> str:
    assert main(list(args)) == 0
    return capsys.readouterr().out.strip()


def test_create_edit_decode(capsys):
    addr = KEY.p2pkh_address(regtest_params())
    raw = _run(capsys, "-regtest", "-create", "nversion=2", "locktime=99",
               f"in={TXID}:1:4000000000", f"out=1.25:{addr}",
               "outdata=cafebabe")
    tx = CTransaction.deserialize(ByteReader(bytes.fromhex(raw)))
    assert tx.version == 2 and tx.locktime == 99
    assert tx.vin[0].prevout.n == 1 and tx.vin[0].sequence == 4000000000
    assert tx.vout[0].value == 125_000_000
    assert tx.vout[1].script_pubkey.startswith(b"\x6a")  # OP_RETURN

    decoded = json.loads(_run(capsys, "-regtest", "-json", raw, "delout=1"))
    assert decoded["version"] == 2 and len(decoded["vout"]) == 1

    raw2 = _run(capsys, "-regtest", raw, "delin=0")
    assert len(CTransaction.deserialize(ByteReader(bytes.fromhex(raw2))).vin) == 0


def test_sign_produces_valid_spend(capsys):
    params = regtest_params()
    addr = KEY.p2pkh_address(params)
    spk = KEY.p2pkh_script()
    wif = KEY.to_wif(params)
    raw = _run(capsys, "-regtest", "-create", f"in={TXID}:0",
               f"out=0.4:{addr}",
               f"sign={wif}:{TXID}:0:{spk.hex()}:0.5")
    tx = CTransaction.deserialize(ByteReader(bytes.fromhex(raw)))
    flags = (SCRIPT_VERIFY_P2SH | SCRIPT_VERIFY_NULLFAIL
             | SCRIPT_ENABLE_SIGHASH_FORKID)
    checker = TransactionSignatureChecker(tx, 0, 50_000_000)
    VerifyScript(tx.vin[0].script_sig, spk, flags, checker)  # raises on fail


def test_bad_input_errors(capsys):
    assert main(["-regtest", "zz"]) == 1
    assert main(["-regtest", "-create", "bogus=1"]) == 1
    assert main(["-regtest", "-create", "out=1.0:notanaddress"]) == 1
