"""Fake-clock coverage for CConnman's supervision tick (_tick) and the
ban-score ledger: inactivity/ping cadence, receive-rate ceilings, block-
download stall detection with re-request + eviction, the bounded seeded-
random orphan pool with per-peer attribution, and banlist persistence.

No sockets, no event loop: peers get fake transports and _tick is driven
directly with an advanced ``now`` — the path TIMEOUT_INTERVAL previously
only exercised implicitly through a live node."""

from __future__ import annotations

import struct
import time

import pytest

from bitcoincashplus_tpu.p2p import connman as cm_mod
from bitcoincashplus_tpu.p2p.connman import (
    CHARGE_RECV_FLOOD,
    MAX_ORPHAN_BYTES,
    MAX_ORPHAN_TX,
    ORPHAN_EXPIRE_TIME,
    PING_INTERVAL,
    TIMEOUT_INTERVAL,
    CConnman,
    Peer,
)
from bitcoincashplus_tpu.p2p.protocol import HEADER_SIZE, VersionPayload
from bitcoincashplus_tpu.store.kvstore import atomic_write_json


class FakeWriter:
    def __init__(self):
        self.closed = False
        self.sent = b""

    def get_extra_info(self, name):
        return ("127.0.0.1", 48444)

    def write(self, data):
        self.sent += data

    def close(self):
        self.closed = True

    def commands(self) -> list[str]:
        """Parse the framed commands written so far."""
        out, buf = [], self.sent
        while len(buf) >= HEADER_SIZE:
            cmd = buf[4:16].rstrip(b"\x00").decode()
            (length,) = struct.unpack_from("<I", buf, 16)
            out.append(cmd)
            buf = buf[HEADER_SIZE + length:]
        return out


class StubConfig:
    def __init__(self, **kv):
        self.kv = kv

    def get_int(self, name, default=0):
        return self.kv.get(name, default)


class StubNode:
    def __init__(self, datadir, **limits):
        class _P:
            netmagic = b"\xfa\xbf\xb5\xda"

        self.params = _P()
        self.datadir = str(datadir)
        self.config = StubConfig()
        self.net_limits = {
            "banscore": 100,
            "blockdownloadtimeout": 10,
            "nettick": 5,
            "maxrecvrate": 1000,
            "netseed": 42,
            **limits,
        }


class StubTx:
    def __init__(self, n: int, size: int = 200):
        self.txid = n.to_bytes(32, "little")
        self.txid_hex = self.txid[::-1].hex()
        self._raw = b"\x00" * size
        self.vin = ()

    def serialize(self) -> bytes:
        return self._raw


def make_connman(tmp_path, **limits) -> CConnman:
    return CConnman(StubNode(tmp_path, **limits))


def make_peer(cm: CConnman, handshaked: bool = True) -> Peer:
    peer = Peer(cm, None, FakeWriter(), outbound=False)
    if handshaked:
        peer.version = VersionPayload()
        peer.got_verack = True
    cm.peers[peer.id] = peer
    return peer


class TestInactivityAndPing:
    def test_inactivity_timeout_drops_peer(self, tmp_path):
        cm = make_connman(tmp_path)
        peer = make_peer(cm)
        peer.last_recv = peer.connected_at
        cm._tick(peer.connected_at + TIMEOUT_INTERVAL + 1)
        assert peer.writer.closed

    def test_quiet_but_within_interval_is_kept(self, tmp_path):
        cm = make_connman(tmp_path)
        peer = make_peer(cm)
        peer.last_recv = peer.connected_at
        cm._tick(peer.connected_at + TIMEOUT_INTERVAL - 1)
        assert not peer.writer.closed

    def test_ping_cadence_follows_wall_clock_not_tick_rate(self, tmp_path):
        cm = make_connman(tmp_path)
        peer = make_peer(cm)
        t0 = peer.connected_at
        # many fast ticks before PING_INTERVAL elapses: no ping
        for dt in (1, 5, 30, PING_INTERVAL - 1):
            cm._tick(t0 + dt)
        assert "ping" not in peer.writer.commands()
        cm._tick(t0 + PING_INTERVAL + 1)
        assert peer.writer.commands().count("ping") == 1
        # immediately after, the cadence gate holds
        cm._tick(t0 + PING_INTERVAL + 2)
        assert peer.writer.commands().count("ping") == 1
        cm._tick(t0 + 2 * PING_INTERVAL + 2)
        assert peer.writer.commands().count("ping") == 2

    def test_unhandshaked_peer_is_never_pinged(self, tmp_path):
        cm = make_connman(tmp_path)
        peer = make_peer(cm, handshaked=False)
        cm._tick(peer.connected_at + PING_INTERVAL + 1)
        assert "ping" not in peer.writer.commands()


class TestRecvRateCeiling:
    def test_flood_charges_accumulate_to_eviction(self, tmp_path):
        # ceiling: 1000 B/s over a 5 s tick window = 5000 bytes/tick
        cm = make_connman(tmp_path)
        peer = make_peer(cm)
        now = peer.connected_at
        for i in range(1, 4):
            peer.recv_window = 1_000_000
            cm._tick(now + i)
            assert peer.ban_score == CHARGE_RECV_FLOOD * i
            assert peer.flood_strikes == i
            assert not peer.discharged
            assert peer.recv_window == 0  # window closed each tick
        peer.recv_window = 1_000_000
        cm._tick(now + 4)
        assert peer.discharged and peer.writer.closed
        assert cm.net_stats["flood_charges"] == 4
        assert cm.net_stats["discharged_peers"] == 1
        assert cm.discharge_reasons == {"recv-flood": 1}

    def test_rate_below_ceiling_is_free(self, tmp_path):
        cm = make_connman(tmp_path)
        peer = make_peer(cm)
        peer.recv_window = 4_000  # under 5000/tick
        cm._tick(peer.connected_at + 1)
        assert peer.ban_score == 0
        assert peer.recv_rate == pytest.approx(800.0)

    def test_zero_ceiling_disables_the_check(self, tmp_path):
        cm = make_connman(tmp_path, maxrecvrate=0)
        peer = make_peer(cm)
        peer.recv_window = 10_000_000
        cm._tick(peer.connected_at + 1)
        assert peer.ban_score == 0

    def test_solicited_block_bytes_are_exempt(self, tmp_path):
        """An honest peer serving our own getdata at wire speed must not
        be flood-charged: delivered in-flight blocks credit their wire
        bytes back out of the window. Unsolicited replays don't."""
        cm = make_connman(tmp_path)
        peer = make_peer(cm)
        h = b"\x07" * 32
        cm._request_blocks(peer, [h], now=peer.connected_at)
        peer.recv_window = 2_000_000
        cm._note_block_arrival(peer, h, wire_bytes=2_000_000)
        assert peer.recv_window == 0
        cm._tick(peer.connected_at + 1)
        assert peer.ban_score == 0
        # the same bytes from a block nobody asked for still count
        peer.recv_window = 2_000_000
        cm._note_block_arrival(peer, b"\x08" * 32, wire_bytes=2_000_000)
        assert peer.recv_window == 2_000_000
        cm._tick(peer.connected_at + 2)
        assert peer.ban_score == CHARGE_RECV_FLOOD

    def test_rate_normalizes_by_actual_elapsed_time(self, tmp_path):
        """A delayed tick draining a backlog must divide by the real
        elapsed time, not the nominal cadence."""
        cm = make_connman(tmp_path)  # ceiling 1000 B/s
        peer = make_peer(cm)
        t0 = peer.connected_at
        cm._tick(t0 + 1)
        # 10 s of silence, then 9000 buffered bytes drain: 900 B/s, legal
        peer.recv_window = 9_000
        cm._tick(t0 + 11)
        assert peer.recv_rate == pytest.approx(900.0)
        assert peer.ban_score == 0


def announce(cm: CConnman, peer: Peer, *hashes: bytes) -> None:
    """Record ``peer`` as an announcer of the hashes, the way a headers
    batch or cmpctblock does — re-requests route only to announcers."""
    for h in hashes:
        cm._block_sources.setdefault(h, set()).add(peer.id)


class TestStallDetection:
    H1, H2 = b"\x01" * 32, b"\x02" * 32

    def test_stall_charges_rerequests_then_evicts(self, tmp_path):
        cm = make_connman(tmp_path)  # blockdownloadtimeout=10
        staller = make_peer(cm)
        other = make_peer(cm)
        announce(cm, other, self.H1, self.H2)
        t0 = time.time()
        cm._request_blocks(staller, [self.H1, self.H2], now=t0)
        assert "getdata" in staller.writer.commands()
        assert staller.inflight == {self.H1, self.H2}

        # within the timeout: nothing happens
        cm._tick(t0 + 9)
        assert not staller.stalling and staller.ban_score == 0

        # first timeout: charged half the threshold, marked stalling, and
        # the blocks move to the other peer in one getdata
        cm._tick(t0 + 11)
        assert staller.stalling
        assert staller.ban_score == 50
        assert staller.charges == {"stalled-block": 50}
        assert not staller.discharged  # the charge is observable pre-evict
        assert staller.inflight == set()
        assert other.inflight == {self.H1, self.H2}
        assert cm._requested_blocks == {self.H1: other.id, self.H2: other.id}
        assert "getdata" in other.writer.commands()
        assert cm.net_stats["stall_rerequests"] == 2

        # second timeout without redemption: discharged and evicted
        cm._tick(t0 + 22)
        assert staller.discharged and staller.writer.closed
        assert cm.net_stats["evicted_stallers"] == 1
        assert cm.discharge_reasons == {"stalled-block": 1}

    def test_block_arrival_redeems_a_stalling_peer(self, tmp_path):
        cm = make_connman(tmp_path)
        peer = make_peer(cm)
        t0 = time.time()
        cm._request_blocks(peer, [self.H1], now=t0)
        cm._tick(t0 + 11)
        assert peer.stalling and peer.ban_score == 50
        cm._note_block_arrival(peer, self.H1)
        assert not peer.stalling
        # redemption rolls the provisional charge back off the ledger —
        # an honest slow link must not be one episode from eviction
        assert peer.ban_score == 0
        assert "stalled-block" not in peer.charges
        cm._tick(t0 + 22)
        assert not peer.discharged and not peer.writer.closed
        # a second slow episode charges afresh, it does NOT discharge
        cm._request_blocks(peer, [self.H2], now=t0 + 22)
        cm._tick(t0 + 34)
        assert peer.stalling and peer.ban_score == 50
        assert not peer.discharged

    def test_no_fallback_parks_blocks_then_first_peer_gets_them(self, tmp_path):
        cm = make_connman(tmp_path)
        staller = make_peer(cm)
        t0 = time.time()
        cm._request_blocks(staller, [self.H1], now=t0)
        cm._tick(t0 + 11)  # stall with no other peer: parked
        assert self.H1 in cm._unrequested
        assert self.H1 not in cm._requested_blocks
        late = make_peer(cm)
        announce(cm, late, self.H1)  # the newcomer announced it too
        cm._tick(t0 + 12)
        assert cm._unrequested == set()
        assert late.inflight == {self.H1}
        assert "getdata" in late.writer.commands()

    def test_unsolicited_duplicates_do_not_defeat_the_stall_detector(
            self, tmp_path):
        """A withholding peer feeding blocks we never asked it for (e.g.
        replaying genesis) must not count as download progress: the stall
        still fires and its reserved blocks still move on."""
        cm = make_connman(tmp_path)
        staller = make_peer(cm)
        other = make_peer(cm)
        announce(cm, other, self.H1)
        t0 = time.time()
        cm._request_blocks(staller, [self.H1], now=t0 - 11)
        # unsolicited noise right before the tick — not an owed block
        cm._note_block_arrival(staller, b"\xee" * 32)
        cm._tick(t0)
        assert staller.stalling and staller.ban_score == 50
        assert other.inflight == {self.H1}
        # more noise can't redeem it either; eviction proceeds
        cm._note_block_arrival(staller, b"\xdd" * 32)
        assert staller.stalling
        cm._tick(t0 + 11)
        assert staller.discharged

    def test_late_delivery_clears_the_reassigned_owner(self, tmp_path):
        """A slow-but-honest peer delivering AFTER its block moved to
        another peer must not leave the new owner with a phantom
        in-flight entry (which would falsely stall and evict it)."""
        cm = make_connman(tmp_path)
        slow = make_peer(cm)
        other = make_peer(cm)
        announce(cm, other, self.H1)
        t0 = time.time()
        cm._request_blocks(slow, [self.H1], now=t0)
        cm._tick(t0 + 11)  # slow stalls; H1 reassigned to other
        assert other.inflight == {self.H1}
        cm._note_block_arrival(slow, self.H1)  # the laggard delivers
        assert other.inflight == set()
        cm._tick(t0 + 25)
        assert not other.stalling and not other.discharged

    def test_trickled_requests_do_not_refresh_the_stall_clock(self, tmp_path):
        """A withholding peer that keeps announcing one new header per
        timeout window earns a fresh getdata each time — the SENDS must
        not count as download progress, or its growing in-flight set
        never trips the stall detector (header-trickle hostage attack)."""
        cm = make_connman(tmp_path)  # blockdownloadtimeout=10
        peer = make_peer(cm)
        t0 = time.time()
        cm._request_blocks(peer, [self.H1], now=t0)
        cm._request_blocks(peer, [self.H2], now=t0 + 8)  # trickle
        cm._request_blocks(peer, [b"\x03" * 32], now=t0 + 10.5)
        cm._tick(t0 + 11)  # H1 is 11s old with zero arrivals: stalled
        assert peer.stalling
        assert peer.ban_score == 50

    def test_progress_refreshes_the_stall_clock(self, tmp_path):
        cm = make_connman(tmp_path)
        peer = make_peer(cm)
        t0 = time.time()
        # requested 15 s ago — would stall at t0+5 with no progress...
        cm._request_blocks(peer, [self.H1, self.H2], now=t0 - 15)
        # ...but a block arriving now restarts the clock for the rest
        cm._note_block_arrival(peer, self.H1)
        cm._tick(t0 + 5)
        assert not peer.stalling

    def test_non_announcers_are_never_handed_a_stallers_blocks(
            self, tmp_path):
        """Re-requests route only to peers that announced the block: an
        attacker's undeliverable announcement must not migrate onto an
        honest peer (who could not serve it and would be stall-charged
        and cascade-evicted for the attacker's lie). With no announcer
        left the download is forgotten entirely."""
        cm = make_connman(tmp_path)
        attacker = make_peer(cm)
        honest = make_peer(cm)  # never announced H1
        t0 = time.time()
        cm._request_blocks(attacker, [self.H1], now=t0)
        cm._tick(t0 + 11)  # attacker stalls
        assert attacker.stalling
        # the hash is parked (attacker is still the only live announcer),
        # never assigned to the honest non-announcer
        assert honest.inflight == set()
        assert self.H1 in cm._unrequested
        assert honest.ban_score == 0
        # attacker disconnects: no announcer left -> download dropped
        del cm.peers[attacker.id]
        cm._tick(t0 + 12)
        assert self.H1 not in cm._unrequested
        assert self.H1 not in cm._block_sources
        assert honest.inflight == set()

    def test_stalling_announcer_cannot_rereserve_blocks(self, tmp_path):
        """A peer already marked stalling that announces fresh headers
        must not get the getdata (re-reserving hashes against itself
        would buy an extra timeout of sync delay per stall-reannounce
        cycle): the hashes park for a healthy announcer instead."""
        import threading

        from bitcoincashplus_tpu.consensus.block import CBlockHeader
        from bitcoincashplus_tpu.consensus.serialize import ser_compact_size

        cm = make_connman(tmp_path)
        peer = make_peer(cm)
        peer.stalling = True
        cm.node.cs_main = threading.RLock()
        hdr = CBlockHeader(version=0x20000000, hash_prev_block=b"\x11" * 32,
                           hash_merkle_root=b"\x22" * 32, time=1,
                           bits=0x207FFFFF, nonce=0)
        wanted = hdr.get_hash()

        class _Idx:
            status = 0
            hash = wanted

        class _CS:
            block_index = {}

            @staticmethod
            def accept_block_header(header):
                return _Idx()

        cm.node.chainstate = _CS()
        payload = ser_compact_size(1) + hdr.serialize() + b"\x00"
        cm._msg_headers(peer, payload)
        assert peer.inflight == set()
        assert wanted not in cm._requested_blocks
        assert wanted in cm._unrequested
        # ...but it IS recorded as an announcer (fair game once redeemed)
        assert peer.id in cm._block_sources[wanted]

    def test_partially_connecting_batch_does_not_reset_the_counter(
            self, tmp_path):
        """Prepending one known header (e.g. genesis) to every garbage
        batch must not evade the graduated non-connecting-headers charge:
        only a batch that connects end to end redeems the counter."""
        import threading

        from bitcoincashplus_tpu.consensus.block import CBlockHeader
        from bitcoincashplus_tpu.consensus.serialize import ser_compact_size
        from bitcoincashplus_tpu.validation.chainstate import (
            BlockValidationError,
        )

        cm = make_connman(tmp_path)
        peer = make_peer(cm)
        cm.node.cs_main = threading.RLock()

        known = CBlockHeader(version=0x20000000,
                             hash_prev_block=b"\x11" * 32,
                             hash_merkle_root=b"\x22" * 32, time=1,
                             bits=0x207FFFFF, nonce=0)
        garbage = CBlockHeader(version=0x20000000,
                               hash_prev_block=b"\x99" * 32,
                               hash_merkle_root=b"\x22" * 32, time=1,
                               bits=0x207FFFFF, nonce=1)

        class _Idx:
            status = cm_mod.BlockStatus.HAVE_DATA
            hash = known.get_hash()

        class _Chain:
            @staticmethod
            def get_locator(*a):
                return []

        class _CS:
            chain = _Chain()
            block_index = {known.get_hash(): _Idx}

            @staticmethod
            def accept_block_header(header):
                if header.get_hash() == known.get_hash():
                    return _Idx()  # the known prefix accepts cleanly
                raise BlockValidationError("prev-blk-not-found", "x")

        cm.node.chainstate = _CS()
        batch = (ser_compact_size(2) + known.serialize() + b"\x00"
                 + garbage.serialize() + b"\x00")
        for i in range(1, cm.max_unconnecting + 1):
            cm._msg_headers(peer, batch)
            assert peer.unconnecting_headers == i  # never reset mid-batch
        assert peer.charges.get("non-connecting-headers") == \
            cm_mod.CHARGE_NONCONNECTING_HEADERS

        # the cross-batch variant: alternating a garbage batch with a
        # REPLAY of known headers must not reset the counter either —
        # only a batch that teaches a new connecting header redeems
        peer2 = make_peer(cm)
        garbage_batch = ser_compact_size(1) + garbage.serialize() + b"\x00"
        known_batch = ser_compact_size(1) + known.serialize() + b"\x00"
        for i in range(1, cm.max_unconnecting + 1):
            cm._msg_headers(peer2, garbage_batch)
            cm._msg_headers(peer2, known_batch)  # replay, not redemption
            assert peer2.unconnecting_headers == i
        assert peer2.charges.get("non-connecting-headers") == \
            cm_mod.CHARGE_NONCONNECTING_HEADERS

    def test_blocktxn_stale_hash_not_tracked_for_non_announcer(
            self, tmp_path):
        """The blocktxn stale-reply path must not register an
        attacker-chosen hash in the download tracker (nobody can ever
        deliver it); only a hash the peer actually announced is
        re-fetched in full."""
        cm = make_connman(tmp_path)
        peer = make_peer(cm)
        garbage = b"\x66" * 32
        # simulate the guard condition directly: not an announced hash
        assert peer.id not in cm._block_sources.get(garbage, ())
        # announced hashes pass the same gate
        announce(cm, peer, self.H1)
        assert peer.id in cm._block_sources.get(self.H1, ())


class TestMisbehavingLedger:
    def test_graduated_charges_reach_threshold_once(self, tmp_path):
        cm = make_connman(tmp_path)
        peer = make_peer(cm)
        for _ in range(9):
            cm.misbehaving(peer, 10, "non-connecting-headers")
        assert peer.ban_score == 90 and not peer.discharged
        cm.misbehaving(peer, 10, "non-connecting-headers")
        assert peer.discharged and peer.writer.closed
        # further charges don't double-count the discharge
        cm.misbehaving(peer, 10, "non-connecting-headers")
        assert cm.net_stats["discharged_peers"] == 1
        assert cm.net_stats["misbehavior_charges"] == 11
        assert peer.charges == {"non-connecting-headers": 110}

    def test_custom_threshold(self, tmp_path):
        cm = make_connman(tmp_path, banscore=30)
        peer = make_peer(cm)
        cm.misbehaving(peer, 25, "recv-flood")
        assert not peer.discharged
        cm.misbehaving(peer, 5, "recv-flood")
        assert peer.discharged

    def test_reason_keys_are_bounded(self, tmp_path):
        """Reason strings can embed attacker-chosen values; the ledger
        dicts cap key length and distinct-key count (overflow buckets to
        'other') so a reconnecting attacker can't grow them unboundedly."""
        cm = make_connman(tmp_path, banscore=10_000_000)
        peer = make_peer(cm)
        for i in range(200):
            cm.misbehaving(peer, 1, f"oversized payload {i} " + "x" * 100)
        assert len(peer.charges) <= CConnman.MAX_REASON_KEYS + 1
        assert all(len(k) <= CConnman.MAX_REASON_LEN for k in peer.charges)
        assert peer.charges["other"] > 0
        assert sum(peer.charges.values()) == 200  # nothing lost

    def test_info_exposes_the_ledger(self, tmp_path):
        cm = make_connman(tmp_path)
        peer = make_peer(cm)
        cm.misbehaving(peer, 10, "invalid-tx")
        info = peer.info()
        assert info["banscore"] == 10
        assert info["charges"] == {"invalid-tx": 10}
        assert info["inflight"] == 0 and info["stalling"] is False


class TestOrphanPool:
    def test_count_cap_with_seeded_random_eviction(self, tmp_path):
        cm = make_connman(tmp_path)
        for i in range(MAX_ORPHAN_TX + 20):
            cm._add_orphan(None, StubTx(i, size=100))
        assert len(cm._orphans) == MAX_ORPHAN_TX
        assert cm.net_stats["orphans_evicted"] == 20
        # deterministic: the same seed evicts the same victims
        cm2 = make_connman(tmp_path)
        for i in range(MAX_ORPHAN_TX + 20):
            cm2._add_orphan(None, StubTx(i, size=100))
        assert set(cm._orphans) == set(cm2._orphans)

    def test_byte_budget_binds_before_the_count_cap(self, tmp_path):
        cm = make_connman(tmp_path)
        big = MAX_ORPHAN_BYTES // 6
        for i in range(10):
            cm._add_orphan(None, StubTx(i, size=big))
        assert cm._orphan_bytes <= MAX_ORPHAN_BYTES
        assert len(cm._orphans) < 10

    def test_oversized_orphan_is_dropped_outright(self, tmp_path):
        cm = make_connman(tmp_path)
        cm._add_orphan(None, StubTx(1, size=150_000))
        assert cm._orphans == {} and cm._orphan_bytes == 0

    def test_per_peer_attribution_erase(self, tmp_path):
        cm = make_connman(tmp_path)
        a, b = make_peer(cm), make_peer(cm)
        for i in range(4):
            cm._add_orphan(a, StubTx(i))
        for i in range(4, 6):
            cm._add_orphan(b, StubTx(i))
        cm._erase_orphans_for(a.id)
        assert len(cm._orphans) == 2
        assert all(e[1] == b.id for e in cm._orphans.values())
        assert cm._orphan_bytes == sum(e[2] for e in cm._orphans.values())

    def test_expiry_in_tick(self, tmp_path):
        cm = make_connman(tmp_path)
        cm._add_orphan(None, StubTx(1))
        cm._add_orphan(None, StubTx(2))
        txid = StubTx(1).txid
        tx, pid, size, _added = cm._orphans[txid]
        cm._orphans[txid] = (tx, pid, size,
                             time.time() - ORPHAN_EXPIRE_TIME - 1)
        cm._tick(time.time())
        assert txid not in cm._orphans
        assert len(cm._orphans) == 1


class TestBanlistPersistence:
    def test_write_through_and_reload(self, tmp_path):
        cm = make_connman(tmp_path)
        cm.ban("203.0.113.7", 3600)
        assert (tmp_path / "banlist.json").exists()
        cm2 = make_connman(tmp_path)
        assert cm2.is_banned("203.0.113.7")
        assert cm2.unban("203.0.113.7")
        cm3 = make_connman(tmp_path)
        assert not cm3.is_banned("203.0.113.7")

    def test_expired_entries_are_pruned_on_load(self, tmp_path):
        atomic_write_json(str(tmp_path / "banlist.json"), {
            "version": 1,
            "banned": {"198.51.100.1": time.time() - 10,
                       "198.51.100.2": time.time() + 3600},
        })
        cm = make_connman(tmp_path)
        assert not cm.is_banned("198.51.100.1")
        assert cm.is_banned("198.51.100.2")

    def test_corrupt_banlist_is_ignored(self, tmp_path):
        (tmp_path / "banlist.json").write_bytes(b"{not json")
        cm = make_connman(tmp_path)
        assert cm.banned() == {}

    def test_structurally_malformed_banlist_is_ignored(self, tmp_path):
        # valid JSON, wrong shape: a list where the dict should be, and a
        # non-numeric expiry — startup must start clean, not die
        for blob in (b'{"banned": ["1.2.3.4"]}',
                     b'{"banned": {"1.2.3.4": "soon"}}',
                     b'{"banned": 7}'):
            (tmp_path / "banlist.json").write_bytes(blob)
            cm = make_connman(tmp_path)
            assert cm.banned() == {}

    def test_clear_banned_writes_through(self, tmp_path):
        cm = make_connman(tmp_path)
        cm.ban("203.0.113.9", 3600)
        cm.clear_banned()
        cm2 = make_connman(tmp_path)
        assert cm2.banned() == {}


class TestChargePolicy:
    """Reject reasons that must (and must not) reach the misbehavior
    ledger: policy and clock-subjective rejections are never charged."""

    @staticmethod
    def _accept_with_reject(cm, peer, reason):
        from bitcoincashplus_tpu.mempool.mempool import MempoolError

        def _reject(tx, now=None, fee_estimate=True):
            raise MempoolError(reason)

        cm.node.accept_to_mempool = _reject
        cm._accept_tx(peer, StubTx(1))

    @pytest.mark.parametrize("reason", sorted(cm_mod.POLICY_BAD_TXNS) + [
        "non-final", "txn-already-in-mempool", "mempool-min-fee-not-met",
        "dust",
        # script failures are ambiguous (mempool verifies with STANDARD
        # flags, a superset of consensus): never charged
        "mandatory-script-verify-flag-failed",
    ])
    def test_policy_rejects_charge_nothing(self, tmp_path, reason):
        cm = make_connman(tmp_path)
        peer = make_peer(cm)
        self._accept_with_reject(cm, peer, reason)
        assert peer.ban_score == 0
        assert not peer.writer.closed

    @pytest.mark.parametrize("reason", [
        "bad-txns-vin-empty", "bad-txns-in-belowout", "coinbase",
    ])
    def test_consensus_rejects_are_charged(self, tmp_path, reason):
        cm = make_connman(tmp_path)
        peer = make_peer(cm)
        self._accept_with_reject(cm, peer, reason)
        assert peer.ban_score == cm_mod.CHARGE_INVALID_TX
        assert peer.charges == {"invalid-tx": cm_mod.CHARGE_INVALID_TX}

    def test_time_too_new_header_neither_charges_nor_disconnects(
            self, tmp_path):
        """A headers announcement our skewed clock rejects as
        time-too-new is dropped without charge and without ending the
        connection — the block path has the same exemption."""
        import threading

        from bitcoincashplus_tpu.consensus.block import CBlockHeader
        from bitcoincashplus_tpu.consensus.serialize import ser_compact_size
        from bitcoincashplus_tpu.validation.chainstate import (
            BlockValidationError,
        )

        cm = make_connman(tmp_path)
        peer = make_peer(cm)
        cm.node.cs_main = threading.RLock()

        class _CS:
            block_index = {}

            @staticmethod
            def accept_block_header(header):
                raise BlockValidationError(
                    "time-too-new", "block timestamp too far in the future")

        cm.node.chainstate = _CS()
        hdr = CBlockHeader(version=0x20000000, hash_prev_block=b"\x11" * 32,
                           hash_merkle_root=b"\x22" * 32, time=2**31,
                           bits=0x207FFFFF, nonce=0)
        payload = ser_compact_size(1) + hdr.serialize() + b"\x00"
        cm._msg_headers(peer, payload)  # must not raise NetMessageError
        assert peer.ban_score == 0
        assert not peer.writer.closed
        assert "getdata" not in peer.writer.commands()

    def test_time_too_new_cmpctblock_neither_charges_nor_disconnects(
            self, tmp_path):
        """Compact blocks are the default tip-relay mode — the
        clock-subjective exemption must cover that path too."""
        import threading

        from bitcoincashplus_tpu.consensus.block import CBlockHeader
        from bitcoincashplus_tpu.p2p.compact import HeaderAndShortIDs
        from bitcoincashplus_tpu.validation.chainstate import (
            BlockValidationError,
        )

        cm = make_connman(tmp_path)
        peer = make_peer(cm)
        cm.node.cs_main = threading.RLock()

        class _CS:
            block_index = {}

            @staticmethod
            def accept_block_header(header):
                raise BlockValidationError(
                    "time-too-new", "block timestamp too far in the future")

        cm.node.chainstate = _CS()
        hdr = CBlockHeader(version=0x20000000, hash_prev_block=b"\x11" * 32,
                           hash_merkle_root=b"\x22" * 32, time=2**31,
                           bits=0x207FFFFF, nonce=0)
        payload = HeaderAndShortIDs(hdr, nonce=7, shortids=[],
                                    prefilled=[]).serialize()
        cm._msg_cmpctblock(peer, payload)  # must not raise
        assert peer.ban_score == 0
        assert not peer.writer.closed

    def test_poisoned_delivery_reparks_a_still_wanted_block(self, tmp_path):
        """A garbage 'block' whose header hash matches a wanted download
        must not untrack it permanently: the deliverer is discharged and
        the hash is parked for re-request from a healthy peer. A hash
        whose index is marked FAILED stays dead."""
        import threading

        from bitcoincashplus_tpu.validation.chain import BlockStatus
        from bitcoincashplus_tpu.validation.chainstate import (
            BlockValidationError,
        )

        cm = make_connman(tmp_path)
        evil = make_peer(cm)
        cm.node.cs_main = threading.RLock()
        h = b"\x55" * 32

        class _Idx:
            status = 0  # header accepted, no data, not failed

        class _CS:
            block_index = {h: _Idx()}

            @staticmethod
            def process_new_block(block):
                raise BlockValidationError(
                    "bad-txnmrklroot", "hashMerkleRoot mismatch")

        cm.node.chainstate = _CS()

        class _Blk:
            vtx = ()

            @staticmethod
            def get_hash():
                return h

        cm._process_block_obj(evil, _Blk())
        assert evil.discharged  # invalid-block = immediate discharge
        assert h in cm._unrequested  # ...but the download survives
        # a FAILED index is not re-parked (genuinely invalid block)
        cm._unrequested.clear()
        _Idx.status = BlockStatus.FAILED_VALID
        evil2 = make_peer(cm)
        cm._process_block_obj(evil2, _Blk())
        assert h not in cm._unrequested


class TestNetSnapshot:
    def test_snapshot_shape(self, tmp_path):
        cm = make_connman(tmp_path)
        snap = cm.net_snapshot()
        assert snap["ban_threshold"] == 100
        assert snap["orphans"] == {"count": 0, "bytes": 0}
        assert snap["discharge_reasons"] == {}
        assert snap["requested_blocks"] == 0
        for key in ("misbehavior_charges", "discharged_peers",
                    "stall_rerequests", "evicted_stallers", "flood_charges",
                    "orphans_evicted", "banned"):
            assert snap[key] == 0


class TestBackfillHardening:
    """ISSUE 16 satellite: the assumeutxo backfill pull must never wedge
    behind one dead peer — per-hash deadlines tear overdue requests off
    their owner, retry on the next peer after a jittered Backoff pause,
    and strike repeat offenders out of the backfill rotation."""

    H1, H2 = b"\x11" * 32, b"\x12" * 32

    def test_dead_backfill_peer_does_not_wedge_the_pull(self, tmp_path):
        cm = make_connman(tmp_path, backfilltimeout=2)
        dead = make_peer(cm)
        alive = make_peer(cm)
        t0 = time.time()
        # no event loop in this harness: request_backfill dispatches
        # inline (the production path queues the same call on the loop)
        cm._backfill_dispatch([self.H1, self.H2], t0)
        owners = {cm._requested_blocks[self.H1],
                  cm._requested_blocks[self.H2]}
        assert owners == {dead.id, alive.id}  # round-robined
        my = [h for h in (self.H1, self.H2)
              if cm._requested_blocks[h] == dead.id][0]

        # within the backfill deadline nothing moves
        cm._tick(t0 + 1)
        assert cm._requested_blocks[my] == dead.id

        # deadline fires: the hash is torn off the dead peer and, after
        # the jittered pause, re-requested from the other peer
        cm._tick(t0 + 3)
        assert cm.net_stats["backfill_retries"] >= 1
        assert my not in cm._requested_blocks
        assert my not in dead.inflight
        cm._tick(t0 + 9)  # past any Backoff pause (max 5s)
        assert cm._requested_blocks.get(my) == alive.id

    def test_repeat_offender_is_struck_out_then_redeemed(self, tmp_path):
        cm = make_connman(tmp_path, backfilltimeout=2)
        flaky = make_peer(cm)
        t0 = time.time()
        # three missed deadlines strike the only peer out of the
        # backfill rotation (BACKFILL_EVICT_STRIKES)
        now = t0
        for _ in range(cm.BACKFILL_EVICT_STRIKES):
            cm._backfill_dispatch([self.H1], now)
            now += cm.backfill_timeout + 1
            cm._tick(now)          # deadline fires, strike charged
            now += 6
            cm._tick(now)          # pause elapses, retry dispatched
            # sole peer: the retry necessarily lands back on it (a
            # degraded pull beats a wedged one)
        assert cm._backfill_evicted == {flaky.id}
        assert cm.net_stats["backfill_peer_evictions"] == 1

        # a struck-out peer is skipped while ANY alternative exists
        fresh = make_peer(cm)
        cm._backfill.clear()
        cm._requested_blocks.pop(self.H2, None)
        cm._backfill_dispatch([self.H2], now)
        assert cm._requested_blocks[self.H2] == fresh.id

        # delivering a wanted backfill block redeems the striker
        cm._backfill_dispatch([self.H1], now)
        owner = cm._requested_blocks.get(self.H1)
        if owner != flaky.id:  # hand it to the flaky peer explicitly
            cm._requested_blocks.pop(self.H1, None)
            cm._request_blocks(flaky, [self.H1], now=now)
            cm._backfill[self.H1]["peer"] = flaky.id
        cm._note_block_arrival(flaky, self.H1, now=now)
        assert flaky.id not in cm._backfill_evicted
        assert flaky.id not in cm._backfill_strikes

    def test_no_peers_parks_then_counts_retries_only_on_expiry(
            self, tmp_path):
        cm = make_connman(tmp_path, backfilltimeout=2)
        t0 = time.time()
        cm._backfill_dispatch([self.H1], t0)
        assert self.H1 in cm._unrequested  # parked, not dropped
        assert cm.net_stats["backfill_retries"] == 0
        # once a peer shows up the parked pull is retried onto it
        peer = make_peer(cm)
        cm._tick(t0 + 3)   # deadline fires while parked
        cm._tick(t0 + 9)   # pause elapses -> re-request on the new peer
        assert cm._requested_blocks.get(self.H1) == peer.id

    def test_arrival_retires_the_backfill_entry(self, tmp_path):
        cm = make_connman(tmp_path, backfilltimeout=2)
        peer = make_peer(cm)
        t0 = time.time()
        cm._backfill_dispatch([self.H1], t0)
        cm._note_block_arrival(peer, self.H1, now=t0 + 1)
        assert self.H1 not in cm._backfill
        cm._tick(t0 + 5)  # no ghost retries for a delivered block
        assert cm.net_stats["backfill_retries"] == 0

    def test_backfill_counters_in_net_snapshot(self, tmp_path):
        cm = make_connman(tmp_path)
        snap = cm.net_snapshot()
        assert snap["backfill_retries"] == 0
        assert snap["backfill_peer_evictions"] == 0
