"""ZMTP 3.0 codec round-trips — publisher frames parsed by the SUB-side
reader, including the long-frame (>255 byte) form the functional test's
small regtest blocks never exercise."""

import struct

from bitcoincashplus_tpu.rpc.zmq import _command, _frame, _greeting


def _parse_frames(buf: bytes) -> list[tuple[int, bytes]]:
    out = []
    pos = 0
    while pos < len(buf):
        flags = buf[pos]
        pos += 1
        if flags & 0x02:
            (size,) = struct.unpack_from(">Q", buf, pos)
            pos += 8
        else:
            size = buf[pos]
            pos += 1
        out.append((flags, buf[pos:pos + size]))
        pos += size
    return out


def test_greeting_shape():
    g = _greeting(as_server=True)
    assert len(g) == 64
    assert g[0] == 0xFF and g[9] == 0x7F
    assert g[10:12] == bytes([3, 0])
    assert g[12:16] == b"NULL"
    assert g[32] == 1  # as-server
    assert _greeting(as_server=False)[32] == 0


def test_short_frame_roundtrip():
    frames = _parse_frames(_frame(b"topic", more=True) + _frame(b"x", more=False))
    assert frames == [(0x01, b"topic"), (0x00, b"x")]


def test_long_frame_roundtrip():
    body = bytes(range(256)) * 5  # 1280 bytes: forces the 8-byte length form
    wire = _frame(body, more=False)
    assert wire[0] & 0x02  # long flag
    frames = _parse_frames(wire)
    assert frames == [(0x02, body)]
    # boundary: exactly 255 stays short, 256 goes long
    assert not _frame(b"a" * 255, more=False)[0] & 0x02
    assert _frame(b"a" * 256, more=False)[0] & 0x02


def test_command_framing():
    wire = _command(b"READY", b"\x0bSocket-Type\x00\x00\x00\x03PUB")
    assert wire[0] == 0x04  # short command
    assert wire[2] == 5 and wire[3:8] == b"READY"
    big = _command(b"READY", b"z" * 300)
    assert big[0] == 0x06  # long command
    (size,) = struct.unpack_from(">Q", big, 1)
    assert size == 1 + 5 + 300


def test_multipart_message_wire():
    """[topic, body, LE32 seq] exactly as publish() writes it."""
    topic, body, seq = b"hashblock", b"\xab" * 32, struct.pack("<I", 7)
    wire = (_frame(topic, more=True) + _frame(body, more=True)
            + _frame(seq, more=False))
    frames = _parse_frames(wire)
    assert [f[1] for f in frames] == [topic, body, seq]
    assert [bool(f[0] & 0x01) for f in frames] == [True, True, False]
