"""bcplint static-analysis suite (ISSUE 15, tier-1, ``lint`` marker).

Three layers of coverage:

1. **Golden fixtures** — one seeded violation per check under
   ``tests/fixtures/bcplint/``.  Each fixture carries a
   ``# BCPLINT-EXPECT`` marker on the offending line; the test asserts
   the rule fires at exactly that file:line with the expected message.
   If a checks.py refactor stops a rule from firing, this fails before
   the real tree can regress.
2. **Repo-tree clean** — ``run_lint`` over the actual package with the
   checked-in baseline must be clean, every baselined entry justified.
   This is the same invariant CI enforces via the ``bcplint`` script.
3. **Baseline machinery** — unjustified and stale entries are
   themselves failures (the baseline can only shrink honestly).

Pure-AST: nothing here imports jax or the analyzed modules, so the
conftest orders the ``lint`` group first for the cheapest signal.
"""

import os
import shutil
import subprocess
import sys
import time

import pytest

from tools.bcplint.cli import DEFAULT_BASELINE, main as cli_main
from tools.bcplint.engine import parse_baseline, run_lint

pytestmark = pytest.mark.lint

ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
FIXTURES = os.path.join(ROOT, "tests", "fixtures", "bcplint")


def _expect_line(relpath: str, marker: str = "BCPLINT-EXPECT") -> int:
    """1-based line of the seeded violation in a fixture (the marker
    comment sits on the offending line, so the fixtures stay
    self-documenting and the tests never hard-code line numbers)."""
    with open(os.path.join(ROOT, relpath), encoding="utf-8") as f:
        for i, line in enumerate(f, 1):
            if marker in line and marker + "-" not in line:
                return i
    raise AssertionError("no %s marker in %s" % (marker, relpath))


def _lint_fixture(name: str, tests_dir=None):
    path = os.path.join(FIXTURES, name)
    return run_lint(ROOT, paths=[path], tests_dir=tests_dir)


def _sole_finding(result, rule):
    matches = [f for f in result.findings if f.rule == rule]
    assert matches, "expected a %s finding, got: %r" % (
        rule, [f.render() for f in result.findings])
    assert len(matches) == 1, [f.render() for f in matches]
    return matches[0]


# ---------------------------------------------------------------------------
# golden fixtures: one seeded violation per check
# ---------------------------------------------------------------------------


def test_bcp001_fires_on_native_family_reemission():
    rel = "tests/fixtures/bcplint/bcp001_telemetry.py"
    f = _sole_finding(_lint_fixture("bcp001_telemetry.py"), "BCP001")
    assert f.path == rel
    assert f.line == _expect_line(rel)
    assert "bcp_fix_depth" in f.message
    assert "native" in f.message


def test_bcp002_fires_on_unpaired_register():
    rel = "tests/fixtures/bcplint/bcp002_pairing.py"
    f = _sole_finding(_lint_fixture("bcp002_pairing.py"), "BCP002")
    assert f.path == rel
    assert f.line == _expect_line(rel)
    assert "'leaky'" in f.message
    assert "unregister" in f.message


def test_bcp003_fires_on_fsync_under_cs_main():
    rel = "tests/fixtures/bcplint/bcp003_blocking.py"
    result = _lint_fixture("bcp003_blocking.py")
    f = _sole_finding(result, "BCP003")
    assert f.path == rel
    assert f.line == _expect_line(rel)
    assert "fsync" in f.message and "cs_main" in f.message
    # the release/.result()/acquire pattern in the same fixture must NOT
    # be flagged — the sole finding above already proves it, but make the
    # intent explicit: no finding anchors on the released .result() call
    assert not any("result" in g.anchor for g in result.findings)


def test_bcp004_fires_on_lock_order_inversion():
    rel = "tests/fixtures/bcplint/bcp004_order.py"
    f = _sole_finding(_lint_fixture("bcp004_order.py"), "BCP004")
    assert f.path == rel
    assert f.line == _expect_line(rel)
    assert "TwoLocks.a_lock" in f.message and "TwoLocks.b_lock" in f.message
    assert "opposite orders" in f.message


def test_bcp005_fires_on_undrilled_fault_site():
    rel = "tests/fixtures/bcplint/bcp005_proj/util/faults.py"
    result = run_lint(
        ROOT, paths=[os.path.join(FIXTURES, "bcp005_proj")],
        tests_dir=os.path.join(FIXTURES, "bcp005_tests"))
    f = _sole_finding(result, "BCP005")
    assert f.path == rel
    assert f.line == _expect_line(rel)
    assert "fixture_untested_site" in f.message
    assert "no test" in f.message


def test_bcp006_fires_on_coercion_and_missing_budget():
    rel = "tests/fixtures/bcplint/bcp006_jit.py"
    result = _lint_fixture("bcp006_jit.py")
    found = [f for f in result.findings if f.rule == "BCP006"]
    assert len(found) == 2, [f.render() for f in result.findings]
    by_line = {f.line: f for f in found}
    coerce = by_line[_expect_line(rel)]
    assert "int(x)" in coerce.message and "traced" in coerce.message
    budget = by_line[_expect_line(rel, "BCPLINT-EXPECT-PROGRAM")]
    assert "fixture_unbudgeted_prog" in budget.message
    assert "shape_budget" in budget.message


# ---------------------------------------------------------------------------
# repo-tree invariant: the actual package is clean under the baseline
# ---------------------------------------------------------------------------


def test_repo_tree_clean_under_baseline():
    result = run_lint(ROOT, baseline_path=DEFAULT_BASELINE)
    assert result.ok, "bcplint regression:\n" + "\n".join(
        [f.render() for f in result.findings]
        + ["stale: " + k for k in result.stale_entries]
        + ["unjustified: " + k for k in result.unjustified_entries]
        + ["%s: %s" % e for e in result.errors])
    # the deliberate designs stay visible, not silently suppressed
    assert result.baselined, "baseline matched nothing — was it emptied?"


def test_every_baseline_entry_is_justified():
    entries = parse_baseline(DEFAULT_BASELINE)
    assert entries, "baseline file is empty"
    missing = [k for k, just in entries.items() if not just]
    assert not missing, "unjustified baseline entries: %r" % missing


# ---------------------------------------------------------------------------
# baseline machinery: unjustified and stale entries are failures
# ---------------------------------------------------------------------------


def test_unjustified_baseline_entry_is_a_failure(tmp_path):
    fixture = os.path.join(FIXTURES, "bcp002_pairing.py")
    raw = run_lint(ROOT, paths=[fixture])
    key = _sole_finding(raw, "BCP002").key
    bl = tmp_path / "baseline"
    bl.write_text(key + "\n")  # no " # why" justification
    result = run_lint(ROOT, paths=[fixture], baseline_path=str(bl))
    assert not result.ok
    assert result.unjustified_entries == [key]


def test_stale_baseline_entry_is_a_failure(tmp_path):
    fixture = os.path.join(FIXTURES, "bcp002_pairing.py")
    raw = run_lint(ROOT, paths=[fixture])
    key = _sole_finding(raw, "BCP002").key
    bl = tmp_path / "baseline"
    bl.write_text(
        key + "  # the seeded leak is deliberate\n"
        "BCP001 no/such/file.py::gone::flat:bcp_x  # stale\n")
    result = run_lint(ROOT, paths=[fixture], baseline_path=str(bl))
    assert not result.ok
    assert not result.findings  # the real finding IS baselined...
    assert result.stale_entries == [  # ...but the dead entry fails the run
        "BCP001 no/such/file.py::gone::flat:bcp_x"]


def test_justified_baseline_suppresses_finding(tmp_path):
    fixture = os.path.join(FIXTURES, "bcp002_pairing.py")
    raw = run_lint(ROOT, paths=[fixture])
    key = _sole_finding(raw, "BCP002").key
    bl = tmp_path / "baseline"
    bl.write_text(key + "  # the seeded leak is deliberate\n")
    result = run_lint(ROOT, paths=[fixture], baseline_path=str(bl))
    assert result.ok
    assert [f.key for f in result.baselined] == [key]


def test_finding_keys_are_line_stable():
    """The baseline key must not embed line numbers — unrelated churn
    above a deliberate design must not invalidate its entry."""
    raw = run_lint(ROOT, paths=[os.path.join(FIXTURES, "bcp002_pairing.py")])
    key = _sole_finding(raw, "BCP002").key
    assert "%d" % _sole_finding(raw, "BCP002").line not in key.split("::")[-1]
    assert key.startswith("BCP002 tests/fixtures/bcplint/bcp002_pairing.py::")


# ---------------------------------------------------------------------------
# CLI: exit codes and the console-script contract
# ---------------------------------------------------------------------------


def test_cli_clean_tree_exits_zero(capsys):
    rc = cli_main(["--root", ROOT])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "bcplint: clean" in out


def test_cli_findings_exit_one(capsys):
    rc = cli_main(["--root", ROOT, "--no-baseline",
                   os.path.join(FIXTURES, "bcp003_blocking.py")])
    out = capsys.readouterr().out
    assert rc == 1
    assert "BCP003" in out


def test_cli_list_checks(capsys):
    assert cli_main(["--list-checks"]) == 0
    out = capsys.readouterr().out
    for rule in ("BCP001", "BCP002", "BCP003", "BCP004", "BCP005",
                 "BCP006", "BCP007", "BCP008", "BCP009", "BCP010"):
        assert rule in out


def test_module_invocation_matches_console_script():
    """`python -m tools.bcplint.cli` is the no-install path CI uses."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.bcplint.cli"],
        cwd=ROOT, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "bcplint: clean" in proc.stdout


# ---------------------------------------------------------------------------
# concurrency analysis goldens (ISSUE 18): BCP007-BCP010 + the BCP004
# explicit-acquire blind-spot regression
# ---------------------------------------------------------------------------


def test_bcp004_fires_on_explicit_acquire_release_pairs():
    """Regression for the blind spot: order edges must be minted from
    document-order .acquire()/.release() pairs, not only ``with``."""
    rel = "tests/fixtures/bcplint/bcp004_acquire.py"
    f = _sole_finding(_lint_fixture("bcp004_acquire.py"), "BCP004")
    assert f.path == rel
    assert f.line == _expect_line(rel)
    assert ("TwoLocksExplicit.a_lock" in f.message
            and "TwoLocksExplicit.b_lock" in f.message)
    assert "opposite orders" in f.message


def test_bcp007_fires_on_no_common_lockset():
    rel = "tests/fixtures/bcplint/bcp007_race.py"
    result = _lint_fixture("bcp007_race.py")
    f = _sole_finding(result, "BCP007")
    assert f.path == rel
    assert f.line == _expect_line(rel)
    assert "RaceBox.latest" in f.message
    assert "RaceBox._writer_a" in f.message
    assert "RaceBox._writer_b" in f.message
    assert "no common lock" in f.message
    # every write site IS under a lock — coverage, not presence, fails;
    # and the per-writer scratch fields (single root each) stay silent
    assert not any("scratch" in g.message for g in result.findings)


def test_bcp008_fires_on_compound_mutations():
    rel = "tests/fixtures/bcplint/bcp008_compound.py"
    result = _lint_fixture("bcp008_compound.py")
    found = [f for f in result.findings if f.rule == "BCP008"]
    assert len(found) == 2, [f.render() for f in result.findings]
    by_line = {f.line: f for f in found}
    aug = by_line[_expect_line(rel)]
    assert "Tally.hits" in aug.message
    assert "read-modify-write" in aug.message
    check = by_line[_expect_line(rel, "BCPLINT-EXPECT-CHECK")]
    assert "Tally.cache" in check.message
    assert "check-then-mutate" in check.message
    # de-overlap: BCP008-flagged attrs must not double-report as BCP007
    assert not any(f.rule == "BCP007" for f in result.findings)


def test_bcp009_fires_on_declared_guard_violation():
    rel = "tests/fixtures/bcplint/bcp009_guarded.py"
    result = _lint_fixture("bcp009_guarded.py")
    f = _sole_finding(result, "BCP009")
    assert f.path == rel
    assert f.line == _expect_line(rel)
    assert "Ledger.total" in f.message and "'cs_lock'" in f.message
    assert "GUARDED_BY" in f.message
    # the compliant write in ok() must not anchor anything
    assert not any("Ledger.ok" in g.anchor for g in result.findings)


def test_bcp009_subset_run_trusts_in_edge_locksets():
    """Linting connman.py alone (the --changed shape) must not flag
    _ban_seq: the RPC roots that reach _snapshot_banlist live in
    rpc/net.py, outside the subset, so BCP009 falls back to the in-edge
    locksets — setban/unban/clear_banned all call it with ban_lock held,
    proving the caller-holds convention locally."""
    path = os.path.join(ROOT, "bitcoincashplus_tpu", "p2p", "connman.py")
    result = run_lint(ROOT, paths=[path])
    assert not any(f.rule == "BCP009" for f in result.findings), \
        [f.message for f in result.findings if f.rule == "BCP009"]


def test_bcp010_fires_on_unjoined_thread():
    rel = "tests/fixtures/bcplint/bcp010_lifecycle.py"
    f = _sole_finding(_lint_fixture("bcp010_lifecycle.py"), "BCP010")
    assert f.path == rel
    assert f.line == _expect_line(rel)
    assert "Leaky._worker" in f.message
    assert "join()" in f.message and "close()" in f.message


def test_bcp010_stays_silent_when_close_joins():
    """The BCP007 fixture joins both threads from close() — its result
    must contain no BCP010 (the credit side of the lifecycle rule)."""
    result = _lint_fixture("bcp007_race.py")
    assert not any(f.rule == "BCP010" for f in result.findings)


# ---------------------------------------------------------------------------
# inline suppression machinery: # BCPLINT-IGNORE[BCP00N]: <why>
# ---------------------------------------------------------------------------

_IGNORE_FIXTURE_SRC = '''\
from concurrent.futures import ThreadPoolExecutor


class T:
    def __init__(self):
        self.pool = ThreadPoolExecutor(max_workers=2)
        self.hits = 0

    def bump(self):
        self.hits += 1  {comment}

    def serve(self):
        self.pool.submit(self.bump)

    def close(self):
        self.pool.shutdown(wait=True)  {stale}
'''


def _ignore_fixture(tmp_path, comment="", stale=""):
    f = tmp_path / "mod.py"
    f.write_text(_IGNORE_FIXTURE_SRC.format(comment=comment, stale=stale))
    return str(f)


def test_justified_inline_ignore_suppresses_finding(tmp_path):
    path = _ignore_fixture(
        tmp_path, comment="# BCPLINT-IGNORE[BCP008]: single-writer pool")
    result = run_lint(str(tmp_path), paths=[path])
    assert result.ok, [f.render() for f in result.findings]
    assert len(result.ignored) == 1
    assert result.ignored[0].rule == "BCP008"


def test_unjustified_inline_ignore_is_a_hard_failure(tmp_path):
    path = _ignore_fixture(tmp_path, comment="# BCPLINT-IGNORE[BCP008]")
    result = run_lint(str(tmp_path), paths=[path])
    assert not result.ok
    assert result.unjustified_ignores == ["mod.py:10 BCP008"]
    # the finding itself survives — an unjustified IGNORE hides nothing
    assert any(f.rule == "BCP008" for f in result.findings)


def test_stale_inline_ignore_is_a_failure(tmp_path):
    path = _ignore_fixture(
        tmp_path, comment="# BCPLINT-IGNORE[BCP008]: single-writer pool",
        stale="# BCPLINT-IGNORE[BCP003]: never fires here")
    result = run_lint(str(tmp_path), paths=[path])
    assert not result.ok
    assert result.stale_ignores == ["mod.py:16 BCP003"]


def test_stale_inline_ignore_tolerated_in_partial_runs(tmp_path):
    """--changed subset runs legitimately miss cross-module findings, so
    staleness proves nothing there (same contract as baseline entries)."""
    path = _ignore_fixture(
        tmp_path, comment="# BCPLINT-IGNORE[BCP008]: single-writer pool",
        stale="# BCPLINT-IGNORE[BCP003]: never fires here")
    result = run_lint(str(tmp_path), paths=[path], partial=True)
    assert result.ok
    assert not result.stale_ignores


def test_docstring_mention_of_ignore_syntax_is_not_a_suppression(tmp_path):
    """Only real COMMENT tokens register — the engine's own docstring
    quotes the syntax and must not create stale entries."""
    f = tmp_path / "mod.py"
    f.write_text('"""Example:\n\n    x += 1  '
                 '# BCPLINT-IGNORE[BCP008]: quoted\n"""\nX = 1\n')
    result = run_lint(str(tmp_path), paths=[str(f)])
    assert result.ok
    assert not result.stale_ignores


# ---------------------------------------------------------------------------
# --changed incremental mode
# ---------------------------------------------------------------------------


def _git(cwd, *args):
    subprocess.run(
        ["git", "-c", "user.email=t@t", "-c", "user.name=t"] + list(args),
        cwd=cwd, check=True, capture_output=True, timeout=60)


@pytest.fixture
def tiny_repo(tmp_path):
    pkg = tmp_path / "bitcoincashplus_tpu"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "clean.py").write_text("X = 1\n")
    _git(tmp_path, "init", "-q")
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-q", "-m", "seed")
    return tmp_path


def test_cli_changed_lints_only_touched_files(tiny_repo, capsys):
    shutil.copy(os.path.join(FIXTURES, "bcp004_acquire.py"),
                tiny_repo / "bitcoincashplus_tpu" / "bad.py")
    rc = cli_main(["--root", str(tiny_repo), "--changed", "HEAD",
                   "--no-baseline"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "BCP004" in out and "bad.py" in out
    assert "clean.py" not in out


def test_cli_changed_with_no_changes_exits_zero(tiny_repo, capsys):
    rc = cli_main(["--root", str(tiny_repo), "--changed", "HEAD"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "no linted .py files changed" in out


def test_cli_changed_and_paths_are_exclusive(capsys):
    rc = cli_main(["--root", ROOT, "--changed", "HEAD",
                   os.path.join(FIXTURES, "bcp004_acquire.py")])
    assert rc == 2
    assert "exclusive" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# the concurrency report is a checked-in, regenerable artifact
# ---------------------------------------------------------------------------


def test_concurrency_report_regenerates_byte_identically():
    from tools.bcplint.race import build_report

    with open(os.path.join(ROOT, "docs", "CONCURRENCY.md"),
              encoding="utf-8") as f:
        committed = f.read()
    assert build_report(ROOT) == committed, (
        "docs/CONCURRENCY.md is stale — regenerate with "
        "`python -m tools.bcplint.cli --concurrency-report > "
        "docs/CONCURRENCY.md`")


def test_concurrency_report_names_known_roots():
    from tools.bcplint.race import build_report

    report = build_report(ROOT)
    for root_name in ("CConnman._run", "ReplicaPool._probe_loop",
                      "SigService._run", "Watchdog._tick_loop"):
        assert root_name in report, root_name
    assert "## Guarded state" in report
    assert "CConnman._banned" in report


# ---------------------------------------------------------------------------
# tier-1 wall budget: the lint stage must never eat the 870 s cap
# ---------------------------------------------------------------------------


def test_full_tree_run_under_wall_budget():
    t0 = time.monotonic()
    result = run_lint(ROOT, baseline_path=DEFAULT_BASELINE)
    elapsed = time.monotonic() - t0
    assert result.ok
    assert elapsed < 10.0, (
        "full-tree bcplint took %.1fs — the 10s budget keeps the "
        "conftest-ordered lint group a cheap first signal" % elapsed)
