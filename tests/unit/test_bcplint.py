"""bcplint static-analysis suite (ISSUE 15, tier-1, ``lint`` marker).

Three layers of coverage:

1. **Golden fixtures** — one seeded violation per check under
   ``tests/fixtures/bcplint/``.  Each fixture carries a
   ``# BCPLINT-EXPECT`` marker on the offending line; the test asserts
   the rule fires at exactly that file:line with the expected message.
   If a checks.py refactor stops a rule from firing, this fails before
   the real tree can regress.
2. **Repo-tree clean** — ``run_lint`` over the actual package with the
   checked-in baseline must be clean, every baselined entry justified.
   This is the same invariant CI enforces via the ``bcplint`` script.
3. **Baseline machinery** — unjustified and stale entries are
   themselves failures (the baseline can only shrink honestly).

Pure-AST: nothing here imports jax or the analyzed modules, so the
conftest orders the ``lint`` group first for the cheapest signal.
"""

import os
import subprocess
import sys

import pytest

from tools.bcplint.cli import DEFAULT_BASELINE, main as cli_main
from tools.bcplint.engine import parse_baseline, run_lint

pytestmark = pytest.mark.lint

ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
FIXTURES = os.path.join(ROOT, "tests", "fixtures", "bcplint")


def _expect_line(relpath: str, marker: str = "BCPLINT-EXPECT") -> int:
    """1-based line of the seeded violation in a fixture (the marker
    comment sits on the offending line, so the fixtures stay
    self-documenting and the tests never hard-code line numbers)."""
    with open(os.path.join(ROOT, relpath), encoding="utf-8") as f:
        for i, line in enumerate(f, 1):
            if marker in line and marker + "-" not in line:
                return i
    raise AssertionError("no %s marker in %s" % (marker, relpath))


def _lint_fixture(name: str, tests_dir=None):
    path = os.path.join(FIXTURES, name)
    return run_lint(ROOT, paths=[path], tests_dir=tests_dir)


def _sole_finding(result, rule):
    matches = [f for f in result.findings if f.rule == rule]
    assert matches, "expected a %s finding, got: %r" % (
        rule, [f.render() for f in result.findings])
    assert len(matches) == 1, [f.render() for f in matches]
    return matches[0]


# ---------------------------------------------------------------------------
# golden fixtures: one seeded violation per check
# ---------------------------------------------------------------------------


def test_bcp001_fires_on_native_family_reemission():
    rel = "tests/fixtures/bcplint/bcp001_telemetry.py"
    f = _sole_finding(_lint_fixture("bcp001_telemetry.py"), "BCP001")
    assert f.path == rel
    assert f.line == _expect_line(rel)
    assert "bcp_fix_depth" in f.message
    assert "native" in f.message


def test_bcp002_fires_on_unpaired_register():
    rel = "tests/fixtures/bcplint/bcp002_pairing.py"
    f = _sole_finding(_lint_fixture("bcp002_pairing.py"), "BCP002")
    assert f.path == rel
    assert f.line == _expect_line(rel)
    assert "'leaky'" in f.message
    assert "unregister" in f.message


def test_bcp003_fires_on_fsync_under_cs_main():
    rel = "tests/fixtures/bcplint/bcp003_blocking.py"
    result = _lint_fixture("bcp003_blocking.py")
    f = _sole_finding(result, "BCP003")
    assert f.path == rel
    assert f.line == _expect_line(rel)
    assert "fsync" in f.message and "cs_main" in f.message
    # the release/.result()/acquire pattern in the same fixture must NOT
    # be flagged — the sole finding above already proves it, but make the
    # intent explicit: no finding anchors on the released .result() call
    assert not any("result" in g.anchor for g in result.findings)


def test_bcp004_fires_on_lock_order_inversion():
    rel = "tests/fixtures/bcplint/bcp004_order.py"
    f = _sole_finding(_lint_fixture("bcp004_order.py"), "BCP004")
    assert f.path == rel
    assert f.line == _expect_line(rel)
    assert "TwoLocks.a_lock" in f.message and "TwoLocks.b_lock" in f.message
    assert "opposite orders" in f.message


def test_bcp005_fires_on_undrilled_fault_site():
    rel = "tests/fixtures/bcplint/bcp005_proj/util/faults.py"
    result = run_lint(
        ROOT, paths=[os.path.join(FIXTURES, "bcp005_proj")],
        tests_dir=os.path.join(FIXTURES, "bcp005_tests"))
    f = _sole_finding(result, "BCP005")
    assert f.path == rel
    assert f.line == _expect_line(rel)
    assert "fixture_untested_site" in f.message
    assert "no test" in f.message


def test_bcp006_fires_on_coercion_and_missing_budget():
    rel = "tests/fixtures/bcplint/bcp006_jit.py"
    result = _lint_fixture("bcp006_jit.py")
    found = [f for f in result.findings if f.rule == "BCP006"]
    assert len(found) == 2, [f.render() for f in result.findings]
    by_line = {f.line: f for f in found}
    coerce = by_line[_expect_line(rel)]
    assert "int(x)" in coerce.message and "traced" in coerce.message
    budget = by_line[_expect_line(rel, "BCPLINT-EXPECT-PROGRAM")]
    assert "fixture_unbudgeted_prog" in budget.message
    assert "shape_budget" in budget.message


# ---------------------------------------------------------------------------
# repo-tree invariant: the actual package is clean under the baseline
# ---------------------------------------------------------------------------


def test_repo_tree_clean_under_baseline():
    result = run_lint(ROOT, baseline_path=DEFAULT_BASELINE)
    assert result.ok, "bcplint regression:\n" + "\n".join(
        [f.render() for f in result.findings]
        + ["stale: " + k for k in result.stale_entries]
        + ["unjustified: " + k for k in result.unjustified_entries]
        + ["%s: %s" % e for e in result.errors])
    # the deliberate designs stay visible, not silently suppressed
    assert result.baselined, "baseline matched nothing — was it emptied?"


def test_every_baseline_entry_is_justified():
    entries = parse_baseline(DEFAULT_BASELINE)
    assert entries, "baseline file is empty"
    missing = [k for k, just in entries.items() if not just]
    assert not missing, "unjustified baseline entries: %r" % missing


# ---------------------------------------------------------------------------
# baseline machinery: unjustified and stale entries are failures
# ---------------------------------------------------------------------------


def test_unjustified_baseline_entry_is_a_failure(tmp_path):
    fixture = os.path.join(FIXTURES, "bcp002_pairing.py")
    raw = run_lint(ROOT, paths=[fixture])
    key = _sole_finding(raw, "BCP002").key
    bl = tmp_path / "baseline"
    bl.write_text(key + "\n")  # no " # why" justification
    result = run_lint(ROOT, paths=[fixture], baseline_path=str(bl))
    assert not result.ok
    assert result.unjustified_entries == [key]


def test_stale_baseline_entry_is_a_failure(tmp_path):
    fixture = os.path.join(FIXTURES, "bcp002_pairing.py")
    raw = run_lint(ROOT, paths=[fixture])
    key = _sole_finding(raw, "BCP002").key
    bl = tmp_path / "baseline"
    bl.write_text(
        key + "  # the seeded leak is deliberate\n"
        "BCP001 no/such/file.py::gone::flat:bcp_x  # stale\n")
    result = run_lint(ROOT, paths=[fixture], baseline_path=str(bl))
    assert not result.ok
    assert not result.findings  # the real finding IS baselined...
    assert result.stale_entries == [  # ...but the dead entry fails the run
        "BCP001 no/such/file.py::gone::flat:bcp_x"]


def test_justified_baseline_suppresses_finding(tmp_path):
    fixture = os.path.join(FIXTURES, "bcp002_pairing.py")
    raw = run_lint(ROOT, paths=[fixture])
    key = _sole_finding(raw, "BCP002").key
    bl = tmp_path / "baseline"
    bl.write_text(key + "  # the seeded leak is deliberate\n")
    result = run_lint(ROOT, paths=[fixture], baseline_path=str(bl))
    assert result.ok
    assert [f.key for f in result.baselined] == [key]


def test_finding_keys_are_line_stable():
    """The baseline key must not embed line numbers — unrelated churn
    above a deliberate design must not invalidate its entry."""
    raw = run_lint(ROOT, paths=[os.path.join(FIXTURES, "bcp002_pairing.py")])
    key = _sole_finding(raw, "BCP002").key
    assert "%d" % _sole_finding(raw, "BCP002").line not in key.split("::")[-1]
    assert key.startswith("BCP002 tests/fixtures/bcplint/bcp002_pairing.py::")


# ---------------------------------------------------------------------------
# CLI: exit codes and the console-script contract
# ---------------------------------------------------------------------------


def test_cli_clean_tree_exits_zero(capsys):
    rc = cli_main(["--root", ROOT])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "bcplint: clean" in out


def test_cli_findings_exit_one(capsys):
    rc = cli_main(["--root", ROOT, "--no-baseline",
                   os.path.join(FIXTURES, "bcp003_blocking.py")])
    out = capsys.readouterr().out
    assert rc == 1
    assert "BCP003" in out


def test_cli_list_checks(capsys):
    assert cli_main(["--list-checks"]) == 0
    out = capsys.readouterr().out
    for rule in ("BCP001", "BCP002", "BCP003", "BCP004", "BCP005", "BCP006"):
        assert rule in out


def test_module_invocation_matches_console_script():
    """`python -m tools.bcplint.cli` is the no-install path CI uses."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.bcplint.cli"],
        cwd=ROOT, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "bcplint: clean" in proc.stdout
