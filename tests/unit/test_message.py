"""Signed messages — SignCompact/RecoverCompact round-trips.

Mirrors the reference's key_tests.cpp recoverable-signature coverage and
the rpc_signmessage functional test: sign with a key, verify against the
address, reject tampered messages/signatures/wrong addresses.
"""

import base64

import pytest
pytest.importorskip("hypothesis")  # property tests need the optional test extra
from hypothesis import given, settings
from hypothesis import strategies as st

from bitcoincashplus_tpu.consensus.params import main_params, regtest_params
from bitcoincashplus_tpu.crypto import secp256k1 as secp
from bitcoincashplus_tpu.wallet.keys import CKey
from bitcoincashplus_tpu.wallet.message import (
    message_hash,
    recover_pubkey,
    sign_message,
    verify_message,
)


def test_recover_matches_signer():
    key = CKey(0x12345678DEADBEEF)
    e = int.from_bytes(message_hash("hello"), "big")
    r, s, recid = secp.ecdsa_sign_recoverable(key.secret, e)
    # the recoverable sig is a valid plain ECDSA sig
    assert secp.ecdsa_verify(secp.pubkey_parse(key.pubkey), r, s, e)
    pt = secp.ecdsa_recover(r, s, recid, e)
    assert secp.pubkey_serialize(pt, True) == key.pubkey


def test_sign_verify_roundtrip():
    params = regtest_params()
    key = CKey.generate()
    addr = key.p2pkh_address(params)
    sig = sign_message(key, "TPU says hi")
    assert verify_message(addr, sig, "TPU says hi", params)
    # wrong message
    assert not verify_message(addr, sig, "TPU says bye", params)
    # wrong address
    other = CKey.generate().p2pkh_address(params)
    assert not verify_message(other, sig, "TPU says hi", params)


def test_uncompressed_key_roundtrip():
    params = regtest_params()
    key = CKey(0xC0FFEE, compressed=False)
    sig = sign_message(key, "msg")
    assert verify_message(key.p2pkh_address(params), sig, "msg", params)
    pub = recover_pubkey(sig, "msg")
    assert pub == key.pubkey
    assert len(pub) == 65


def test_malformed_signatures_rejected():
    params = regtest_params()
    key = CKey.generate()
    addr = key.p2pkh_address(params)
    assert not verify_message(addr, "not base64!!", "m", params)
    assert not verify_message(addr, base64.b64encode(b"\x00" * 64).decode(),
                              "m", params)  # too short
    blob = base64.b64decode(sign_message(key, "m"))
    # invalid header byte
    bad = bytes([0]) + blob[1:]
    assert not verify_message(addr, base64.b64encode(bad).decode(), "m", params)
    # flipped recid bit recovers a different key
    flipped = bytes([blob[0] ^ 1]) + blob[1:]
    assert not verify_message(addr, base64.b64encode(flipped).decode(), "m",
                              params)


def test_known_magic_hash():
    # independent recomputation of the magic-prefixed digest
    import hashlib

    msg = b"abc"
    data = (bytes([24]) + b"Bitcoin Signed Message:\n" + bytes([3]) + msg)
    expect = hashlib.sha256(hashlib.sha256(data).digest()).digest()
    assert message_hash("abc") == expect


def test_p2sh_address_never_verifies():
    params = main_params()
    key = CKey(0xABCDEF)
    sig = sign_message(key, "m")
    from bitcoincashplus_tpu.crypto.base58 import b58check_encode

    p2sh = b58check_encode(bytes([params.script_addr_prefix]) + b"\x11" * 20)
    assert not verify_message(p2sh, sig, "m", params)


@settings(max_examples=20, deadline=None)
@given(st.integers(min_value=1, max_value=secp.N - 1),
       st.text(max_size=64))
def test_property_roundtrip(secret, message):
    params = regtest_params()
    key = CKey(secret)
    sig = sign_message(key, message)
    assert verify_message(key.p2pkh_address(params), sig, message, params)
