"""BIP152 compact blocks — shortid derivation, wire round-trips,
reconstruction (src/test/blockencodings_tests.cpp analogues)."""

import struct

import pytest

pytest.importorskip("hypothesis")  # property tests need the optional test extra
from hypothesis import given, settings
from hypothesis import strategies as st

from bitcoincashplus_tpu.consensus.block import CBlock, CBlockHeader
from bitcoincashplus_tpu.consensus.merkle import compute_merkle_root
from bitcoincashplus_tpu.consensus.serialize import ByteReader
from bitcoincashplus_tpu.consensus.tx import (
    COutPoint,
    CTransaction,
    CTxIn,
    CTxOut,
)
from bitcoincashplus_tpu.crypto.siphash import siphash24
from bitcoincashplus_tpu.p2p.compact import (
    BlockTransactions,
    BlockTransactionsRequest,
    HeaderAndShortIDs,
    short_id,
    short_id_keys,
)


def test_siphash_reference_vectors():
    """SipHash-2-4 paper vectors (same table crypto_tests.cpp pins)."""
    k0, k1 = 0x0706050403020100, 0x0F0E0D0C0B0A0908
    expect = [0x726FDB47DD0E0E31, 0x74F839C593DC67FD,
              0x0D6C8009D9A94F5A, 0x85676696D7FB7E2D]
    for n, e in enumerate(expect):
        assert siphash24(k0, k1, bytes(range(n))) == e


def _tx(salt: int) -> CTransaction:
    return CTransaction(
        vin=(CTxIn(COutPoint(bytes([salt]) * 32, 0), bytes([salt])),),
        vout=(CTxOut(1000 + salt, b"\x51"),),
    )


def _block(n_tx: int) -> CBlock:
    txs = tuple(_tx(i + 1) for i in range(n_tx))
    root, _ = compute_merkle_root([t.txid for t in txs])
    return CBlock(CBlockHeader(hash_merkle_root=root, bits=0x207FFFFF), txs)


class TestHeaderAndShortIDs:
    def test_wire_roundtrip(self):
        blk = _block(5)
        hs = HeaderAndShortIDs.from_block(blk, nonce=42)
        wire = hs.serialize()
        back = HeaderAndShortIDs.deserialize(ByteReader(wire))
        assert back.nonce == 42
        assert back.shortids == hs.shortids
        assert len(back.shortids) == 4  # coinbase prefilled
        assert back.prefilled[0][0] == 0
        assert back.prefilled[0][1].txid == blk.vtx[0].txid
        assert back.header.get_hash() == blk.header.get_hash()

    def test_shortids_are_48bit_and_keyed(self):
        blk = _block(3)
        a = HeaderAndShortIDs.from_block(blk, nonce=1)
        b = HeaderAndShortIDs.from_block(blk, nonce=2)
        assert all(s < (1 << 48) for s in a.shortids)
        assert a.shortids != b.shortids  # nonce changes the key

    def test_reconstruct_full_mempool(self):
        blk = _block(6)
        hs = HeaderAndShortIDs.from_block(blk, nonce=7)
        k0, k1 = short_id_keys(blk.header, 7)
        pool = {short_id(k0, k1, t.txid): t for t in blk.vtx[1:]}
        got, missing = hs.reconstruct(pool.get)
        assert missing == [] and got is not None
        assert got.serialize() == blk.serialize()

    def test_reconstruct_reports_missing(self):
        blk = _block(6)
        hs = HeaderAndShortIDs.from_block(blk, nonce=7)
        k0, k1 = short_id_keys(blk.header, 7)
        # mempool knows only txs 1 and 3 (absolute indexes)
        pool = {short_id(k0, k1, blk.vtx[i].txid): blk.vtx[i] for i in (1, 3)}
        got, missing = hs.reconstruct(pool.get)
        assert got is None
        assert missing == [2, 4, 5]
        # supply them via BlockTransactions and complete
        for i in missing:
            pool[short_id(k0, k1, blk.vtx[i].txid)] = blk.vtx[i]
        got, missing = hs.reconstruct(pool.get)
        assert missing == [] and got.serialize() == blk.serialize()

    def test_wrong_tx_rejected_by_shortid(self):
        blk = _block(3)
        hs = HeaderAndShortIDs.from_block(blk, nonce=9)
        rogue = _tx(99)
        got, missing = hs.reconstruct(lambda sid: rogue)
        assert got is None and missing == [1, 2]


class TestRequestAndAnswer:
    def test_request_differential_roundtrip(self):
        req = BlockTransactionsRequest(b"\xab" * 32, [0, 2, 3, 10])
        back = BlockTransactionsRequest.deserialize(ByteReader(req.serialize()))
        assert back.block_hash == b"\xab" * 32
        assert back.indexes == [0, 2, 3, 10]

    def test_blocktxn_roundtrip(self):
        txs = [_tx(1), _tx(2)]
        bt = BlockTransactions(b"\xcd" * 32, txs)
        back = BlockTransactions.deserialize(ByteReader(bt.serialize()))
        assert back.block_hash == b"\xcd" * 32
        assert [t.txid for t in back.txs] == [t.txid for t in txs]

    @settings(max_examples=25, deadline=None)
    @given(st.lists(st.integers(0, 5000), min_size=1, max_size=50,
                    unique=True))
    def test_request_property(self, indexes):
        indexes = sorted(indexes)
        req = BlockTransactionsRequest(b"\x01" * 32, indexes)
        back = BlockTransactionsRequest.deserialize(ByteReader(req.serialize()))
        assert back.indexes == indexes
