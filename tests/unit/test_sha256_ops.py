"""Differential tests: TPU (jnp) SHA-256d paths vs hashlib / the Python
oracle — the reference's crypto_tests.cpp + randomized-equivalence strategy
(SURVEY.md §5.4.4)."""

import hashlib
import os
import struct

import numpy as np
import pytest

from bitcoincashplus_tpu.consensus.block import CBlockHeader
from bitcoincashplus_tpu.consensus.merkle import compute_merkle_root
from bitcoincashplus_tpu.consensus.params import main_params, regtest_params
from bitcoincashplus_tpu.consensus.pow import compact_to_target
from bitcoincashplus_tpu.crypto.hashes import header_midstate, sha256d
from bitcoincashplus_tpu.ops import miner as tpu_miner
from bitcoincashplus_tpu.ops import sha256 as ops_sha
from bitcoincashplus_tpu.ops.merkle import compute_merkle_root_tpu

import jax.numpy as jnp

rng = np.random.default_rng(1234)


def _random_headers(n):
    return rng.integers(0, 256, size=(n, 80), dtype=np.uint8)


class TestBatchedHeaderHash:
    def test_vs_hashlib_random(self):
        headers = _random_headers(257)
        got = ops_sha.sha256d_headers(headers)
        for i in range(len(headers)):
            expect = sha256d(headers[i].tobytes())
            assert got[i].tobytes() == expect

    def test_genesis_header(self):
        params = main_params()
        h80 = params.genesis.header.serialize()
        got = ops_sha.sha256d_headers(np.frombuffer(h80, np.uint8).reshape(1, 80))
        assert got[0].tobytes() == params.genesis.get_hash()

    def test_pow_check_batch(self):
        params = main_params()
        h80 = params.genesis.header.serialize()
        bad = bytearray(h80)
        bad[76] ^= 1  # wrong nonce
        headers = np.stack(
            [np.frombuffer(bytes(x), np.uint8) for x in (h80, bytes(bad))]
        )
        target, _ = compact_to_target(params.genesis.header.bits)
        words = jnp.asarray(ops_sha.headers_to_words_np(headers))
        tgt = jnp.asarray(ops_sha.target_to_limbs_np(target))
        _, ok = ops_sha.check_headers_pow_jit(words, tgt)
        assert bool(ok[0]) and not bool(ok[1])


class TestSweepDigest:
    def test_midstate_sweep_vs_hashlib(self):
        header = _random_headers(1)[0].tobytes()
        midstate = np.array(header_midstate(header), dtype=np.uint32)
        tail = ops_sha.bytes_to_words_np(np.frombuffer(header[64:76], np.uint8))
        nonces = rng.integers(0, 1 << 32, size=64, dtype=np.uint32)
        h8 = ops_sha.header_sweep_digest(
            [jnp.uint32(m) for m in midstate],
            [jnp.uint32(t) for t in tail],
            jnp.asarray(nonces),
        )
        digests = ops_sha.digests_to_bytes([np.asarray(h) for h in h8])
        for i, n in enumerate(nonces):
            expect = sha256d(header[:76] + struct.pack("<I", int(n)))
            assert digests[i].tobytes() == expect

    def test_limb_compare_vs_python_int(self):
        # Random 256-bit hash/target pairs: limb compare == int compare.
        hashes = rng.integers(0, 256, size=(128, 32), dtype=np.uint8)
        target = int.from_bytes(rng.integers(0, 256, size=32, dtype=np.uint8).tobytes(), "little")
        # hash words (BE view of digest bytes) -> limbs
        h_words = ops_sha.bytes_to_words_np(hashes)
        limbs = [jnp.asarray(ops_sha.bswap32(h_words[:, j])) for j in range(8)]
        tgt = ops_sha.target_to_limbs_np(target)
        got = np.asarray(ops_sha.le256(limbs, [jnp.uint32(t) for t in tgt]))
        for i in range(len(hashes)):
            expect = int.from_bytes(hashes[i].tobytes(), "little") <= target
            assert bool(got[i]) == expect


class TestSweep:
    def test_finds_known_nonce_regtest(self):
        """Mine a regtest-difficulty header and verify the found nonce."""
        params = regtest_params()
        hdr = CBlockHeader(
            version=0x20000000,
            hash_prev_block=params.genesis.get_hash(),
            hash_merkle_root=rng.integers(0, 256, 32, dtype=np.uint8).tobytes(),
            time=1_300_000_000,
            bits=0x207FFFFF,
            nonce=0,
        )
        target, _ = compact_to_target(hdr.bits)
        nonce, hashes = tpu_miner.sweep_header(
            hdr.serialize(), target, tile=4096, max_nonces=1 << 20
        )
        assert nonce is not None
        mined = hdr.with_nonce(nonce)
        assert int.from_bytes(mined.get_hash(), "little") <= target
        # First-hit semantics: no smaller nonce passes within the swept range
        # (spot-check the tile that contained the hit).
        base = (nonce // 4096) * 4096
        for n in range(base, nonce):
            cand = hdr.with_nonce(n)
            assert int.from_bytes(cand.get_hash(), "little") > target

    def test_not_found_at_impossible_target(self):
        hdr = _random_headers(1)[0].tobytes()
        nonce, hashes = tpu_miner.sweep_header(hdr, target=0, max_nonces=1 << 14, tile=4096)
        assert nonce is None
        assert hashes == 1 << 14

    def test_nonce_wraparound(self):
        """Sweep starting near 2^32 wraps like the reference's uint32."""
        params = regtest_params()
        hdr = CBlockHeader(
            version=1, hash_prev_block=b"\x11" * 32, hash_merkle_root=b"\x22" * 32,
            time=1_300_000_123, bits=0x207FFFFF, nonce=0,
        )
        target, _ = compact_to_target(hdr.bits)
        nonce, _ = tpu_miner.sweep_header(
            hdr.serialize(), target, start_nonce=(1 << 32) - 2048, tile=4096,
            max_nonces=1 << 16,
        )
        assert nonce is not None
        assert int.from_bytes(hdr.with_nonce(nonce).get_hash(), "little") <= target


class TestMerkleTPU:
    @pytest.mark.parametrize("n", [1, 2, 3, 5, 32, 33, 127, 513])
    def test_vs_cpu(self, n):
        hashes = [rng.integers(0, 256, 32, dtype=np.uint8).tobytes() for _ in range(n)]
        root_cpu, mut_cpu = compute_merkle_root(hashes)
        root_tpu, mut_tpu = compute_merkle_root_tpu(hashes)
        assert root_cpu == root_tpu
        assert mut_cpu == mut_tpu

    @pytest.mark.parametrize("n,dup_tail", [(3, 1), (6, 2)])
    def test_mutation_detected(self, n, dup_tail):
        """CVE-2012-2459: appending a copy of the final odd-duplicated
        node(s) yields the SAME root but must set the mutated flag."""
        h = [rng.integers(0, 256, 32, dtype=np.uint8).tobytes() for _ in range(n)]
        dup = h + h[-dup_tail:]
        root_cpu, mut_cpu = compute_merkle_root(dup)
        root_tpu, mut_tpu = compute_merkle_root_tpu(dup)
        assert root_cpu == root_tpu
        assert mut_cpu and mut_tpu
        # and the mutated root equals the honest root (the actual CVE)
        assert root_cpu == compute_merkle_root(h)[0]

    def test_odd_duplication_not_flagged(self):
        h = [rng.integers(0, 256, 32, dtype=np.uint8).tobytes() for _ in range(3)]
        _, mutated = compute_merkle_root_tpu(h)
        assert not mutated

    def test_empty(self):
        assert compute_merkle_root_tpu([]) == (b"\x00" * 32, False)
