"""ShardedCoinsDB facade (store/sharded.py) + snapshot format.

Differential against the single-file CoinsDB reference (the facade is a
pure partition of the same contract), incremental-accumulator equality
with a from-scratch recompute, the store_shard fault site's whole-commit
abort semantics, manifest shard-count pinning, and dump/load round-trips
across shard counts including digest-rejection.
"""

import os
import struct

import pytest

from bitcoincashplus_tpu.store import muhash
from bitcoincashplus_tpu.store import snapshot as snapshot_mod
from bitcoincashplus_tpu.store.chainstatedb import CoinsDB
from bitcoincashplus_tpu.store.kvstore import KVStore
from bitcoincashplus_tpu.store.sharded import (
    MANIFEST_NAME,
    STORE_SHARD_SITE,
    ShardedCoinsDB,
    shard_of,
)
from bitcoincashplus_tpu.util.faults import InjectedFault


def _key(i: int) -> bytes:
    return bytes([i % 251]) * 32 + struct.pack("<I", i)


def _coin(i: int) -> bytes:
    # valid Coin serialization: compact(height*2+cb), compact(value),
    # var_bytes(script) — height 1, value 5, 20-byte script
    return bytes([2, 5, 20]) + bytes([i % 256]) * 20


def _entries(lo: int, hi: int, delete=()):
    out = [(_key(i), _coin(i)) for i in range(lo, hi)]
    out += [(_key(i), None) for i in delete]
    return out


@pytest.fixture
def sharded(tmp_path):
    db = ShardedCoinsDB(str(tmp_path), n_shards=4)
    yield db
    db.close()


class TestFacade:
    def test_power_of_two_enforced(self, tmp_path):
        for bad in (0, 3, 5, 300, -1):
            with pytest.raises(ValueError):
                ShardedCoinsDB(str(tmp_path), n_shards=bad)

    def test_differential_vs_single_coinsdb(self, tmp_path, sharded):
        """Same batches through the facade and a plain CoinsDB — every
        read surface must agree (the facade is only a partition)."""
        ref_kv = KVStore(str(tmp_path / "ref.sqlite"))
        ref = CoinsDB(ref_kv)
        best1 = b"\x01" * 32
        best2 = b"\x02" * 32
        sharded.batch_write_serialized(_entries(0, 200), best1)
        ref.batch_write_serialized(_entries(0, 200), best1)
        # overwrite a run, delete a run
        sharded.batch_write_serialized(
            _entries(50, 80, delete=range(100, 140)), best2)
        ref.batch_write_serialized(
            _entries(50, 80, delete=range(100, 140)), best2)

        assert sharded.best_block() == ref.best_block() == best2
        assert sharded.count_coins() == ref.count_coins() == 160
        keys = [_key(i) for i in range(0, 220)]
        assert sharded.get_serialized_many(keys) == \
            ref.get_serialized_many(keys)
        assert dict(sharded.iterate_coins()) == dict(ref.iterate_coins())
        ref_kv.close()

    def test_rows_actually_partition(self, sharded, tmp_path):
        sharded.batch_write_serialized(_entries(0, 64), b"\x01" * 32)
        per_shard = []
        for i in range(4):
            kv = sharded.shards[i].kv
            rows = {k[1:]: v for k, v in kv.iterate(b"C")}
            for k36 in rows:
                assert shard_of(k36, 4) == i
            per_shard.append(len(rows))
        assert sum(per_shard) == 64
        assert sum(1 for n in per_shard if n > 0) > 1  # really spread

    def test_incremental_digest_tracks_recompute(self, sharded):
        best = b"\x01" * 32
        sharded.batch_write_serialized(_entries(0, 100), best)
        assert sharded.muhash_digest() == sharded.recompute_digest()
        sharded.batch_write_serialized(
            _entries(20, 40, delete=range(60, 90)), best)
        assert sharded.muhash_digest() == sharded.recompute_digest()
        # digest must be independent of the shard count: a 1-shard store
        # with the same coin set lands on the same value
        assert sharded.muhash_digest() != muhash.digest_of(1)

    def test_epoch_and_manifest_pinning(self, tmp_path, sharded):
        sharded.batch_write_serialized(_entries(0, 10), b"\x01" * 32)
        epoch = sharded.epoch
        assert epoch >= 1
        sharded.close()
        # reopen asking for a different count: the manifest wins
        again = ShardedCoinsDB(str(tmp_path), n_shards=16)
        assert again.n_shards == 4
        assert again.requested_shards == 16
        assert again.epoch == epoch
        assert again.muhash_digest() == again.recompute_digest()
        again.close()

    def test_stats_shape(self, sharded):
        sharded.batch_write_serialized(_entries(0, 10), b"\x01" * 32)
        s = sharded.stats()
        assert s["shards"] == 4
        assert s["epoch"] >= 1
        assert len(s["shard_bytes"]) == 4
        assert s["last_flush"]["fanout"] == 4


class TestShardFaultSite:
    def test_one_failing_shard_aborts_whole_commit(self, tmp_path,
                                                   fault_harness):
        db = ShardedCoinsDB(str(tmp_path), n_shards=4)
        best = b"\x01" * 32
        db.batch_write_serialized(_entries(0, 40), best)
        epoch = db.epoch
        digest = db.muhash_digest()
        fault_harness("fail-once", ops=STORE_SHARD_SITE)
        with pytest.raises(InjectedFault):
            db.batch_write_serialized(
                _entries(40, 80, delete=range(0, 10)), b"\x02" * 32)
        # clean abort: no journal survives, no shard moved past the
        # manifest epoch, state is exactly pre-commit
        for i in range(4):
            assert not os.path.exists(
                os.path.join(str(tmp_path), f"chainstate.shard{i}.journal"))
        assert db.epoch == epoch
        assert db.best_block() == best
        assert db.count_coins() == 40
        assert db.muhash_digest() == digest == db.recompute_digest()
        db.close()
        # and the store reopens consistent (recovery sees nothing to do)
        again = ShardedCoinsDB(str(tmp_path), n_shards=4)
        assert again.epoch == epoch
        assert again.count_coins() == 40
        again.close()

    def test_all_does_not_arm_store_shard(self, tmp_path, fault_harness):
        """BCP_FAULT_OPS=all must keep meaning the accelerator subsystems
        — a dead-backend drill may not fail chainstate flushes."""
        fault_harness("fail-always", ops="all")
        db = ShardedCoinsDB(str(tmp_path), n_shards=2)
        db.batch_write_serialized(_entries(0, 10), b"\x01" * 32)
        assert db.count_coins() == 10
        db.close()


class TestSnapshot:
    @pytest.mark.parametrize("src,dst", [(4, 4), (4, 1), (1, 4), (2, 8)])
    def test_round_trip_across_shard_counts(self, tmp_path, src, dst):
        a = ShardedCoinsDB(str(tmp_path / "a"), n_shards=src)
        best = b"\xaa" * 32
        a.batch_write_serialized(_entries(0, 300), best)
        digest = a.muhash_digest()
        headers = [bytes(80)]
        manifest = snapshot_mod.dump_snapshot(
            a, str(tmp_path / "snap"), headers, 0, best, "regtest")
        assert manifest["muhash"] == digest.hex()
        assert manifest["coins"] == 300

        b = ShardedCoinsDB(str(tmp_path / "b"), n_shards=dst)
        info = snapshot_mod.load_snapshot(
            str(tmp_path / "snap"), b, "regtest",
            expected_hash=best, expected_digest=digest)
        assert info["best_block"] == best
        assert b.count_coins() == 300
        assert b.best_block() == best
        assert b.muhash_digest() == digest == b.recompute_digest()
        assert dict(b.iterate_coins()) == dict(a.iterate_coins())
        assert b.snapshot_state is not None
        assert b.snapshot_state["validated"] is False
        a.close()
        b.close()

    def test_bad_digest_rejected_and_wiped(self, tmp_path):
        a = ShardedCoinsDB(str(tmp_path / "a"), n_shards=2)
        best = b"\xaa" * 32
        a.batch_write_serialized(_entries(0, 50), best)
        snapshot_mod.dump_snapshot(a, str(tmp_path / "snap"),
                                   [bytes(80)], 0, best, "regtest")
        a.close()
        # corrupt one utxo stream (keep its length so the row parse
        # succeeds and only the checksum/digest trips)
        target = next(str(p) for p in (tmp_path / "snap").iterdir()
                      if p.name.startswith("utxo-") and p.stat().st_size)
        blob = bytearray(open(target, "rb").read())
        blob[-1] ^= 0xFF
        open(target, "wb").write(bytes(blob))

        b = ShardedCoinsDB(str(tmp_path / "b"), n_shards=2)
        with pytest.raises(snapshot_mod.SnapshotError):
            snapshot_mod.load_snapshot(str(tmp_path / "snap"), b, "regtest")
        assert b.count_coins() == 0  # wiped, not half-loaded
        assert b.snapshot_state is None
        b.close()

    def test_wrong_authorization_rejected(self, tmp_path):
        a = ShardedCoinsDB(str(tmp_path / "a"), n_shards=2)
        best = b"\xaa" * 32
        a.batch_write_serialized(_entries(0, 20), best)
        snapshot_mod.dump_snapshot(a, str(tmp_path / "snap"),
                                   [bytes(80)], 0, best, "regtest")
        a.close()
        b = ShardedCoinsDB(str(tmp_path / "b"), n_shards=2)
        with pytest.raises(snapshot_mod.SnapshotError):
            snapshot_mod.load_snapshot(
                str(tmp_path / "snap"), b, "regtest",
                expected_hash=b"\xbb" * 32)
        with pytest.raises(snapshot_mod.SnapshotError):
            snapshot_mod.load_snapshot(
                str(tmp_path / "snap"), b, "regtest",
                expected_digest=b"\xcc" * 32)
        with pytest.raises(snapshot_mod.SnapshotError):
            snapshot_mod.load_snapshot(str(tmp_path / "snap"), b, "test")
        b.close()

    def test_legacy_store_detection(self, tmp_path):
        """A datadir with chainstate.sqlite and no manifest is the legacy
        layout — the node keeps it on plain CoinsDB (checked here at the
        layout level: the manifest only appears after a sharded commit)."""
        kv = KVStore(str(tmp_path / "chainstate.sqlite"))
        CoinsDB(kv).batch_write_serialized(_entries(0, 5), b"\x01" * 32)
        kv.close()
        assert not os.path.exists(str(tmp_path / MANIFEST_NAME))
