"""Device-lane observability suite (ISSUE 8): the compile/retrace
sentinel, transfer & memory accounting, dispatch-phase plumbing, the
profiler RPC round trip, and the stall watchdog on a fake clock.

Tier-1, CPU backend ('devprof' marker — conftest orders it after the
telemetry group, before serving). Kernel-heavy integration (the ecdsa
programs' real budgets) is covered by the driver bench, not here: every
jit in this file is a trivially-compiling toy so the suite stays fast.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax

from bitcoincashplus_tpu.util import devicewatch as dw
from bitcoincashplus_tpu.util import telemetry as tm

pytestmark = pytest.mark.devprof


@pytest.fixture(autouse=True)
def _isolate(monkeypatch):
    """Fresh program/transfer/watchdog state per test; the telemetry
    families survive (module-level handles) but are zeroed."""
    monkeypatch.setenv("BCP_TELEMETRY", "counters")
    tm.reset()
    dw.reset()
    yield
    tm.reset()
    dw.reset()


def _family_value(name: str, **labels) -> float:
    fam = tm.REGISTRY.snapshot().get(name, {"values": []})
    for v in fam["values"]:
        if all(v["labels"].get(k) == str(val) for k, val in labels.items()):
            return v.get("value", v.get("count", 0.0))
    return 0.0


# ---------------------------------------------------------------------------
# compile/retrace sentinel
# ---------------------------------------------------------------------------


def test_retrace_sentinel_fires_beyond_budget(monkeypatch):
    """Forcing an un-bucketed shape — a distinct signature beyond the
    declared budget — must fire the counter, a warning, and keep the
    verdict path untouched (observe-only)."""
    warnings = []
    monkeypatch.setattr(dw, "log_printf",
                        lambda msg, *a: warnings.append(msg % a))
    f = jax.jit(lambda x: x + 1)
    pw = dw.program("sentinel_prog", shape_budget=2)
    for n in (4, 8):  # inside the budget: no sentinel
        x = np.arange(n, dtype=np.float32)
        with pw.dispatch(x.shape):
            f(x)
    assert pw.snapshot()["retraces_unexpected"] == 0
    assert not warnings
    x = np.arange(16, dtype=np.float32)  # the un-bucketed shape
    with pw.dispatch(x.shape):
        f(x)
    snap = pw.snapshot()
    assert snap["shapes"] == 3
    assert snap["retraces_unexpected"] == 1
    assert "unexpected retrace" in snap["last_warning"]
    assert "sentinel_prog" in snap["last_warning"]
    assert any("unexpected retrace" in w for w in warnings)
    assert _family_value("bcp_xla_retrace_unexpected_total",
                         program="sentinel_prog") == 1
    # a REPEAT of a known shape is not a retrace
    with pw.dispatch((16,)):
        f(np.arange(16, dtype=np.float32))
    assert pw.snapshot()["retraces_unexpected"] == 1


def test_compile_accounting_counts_compiles_not_dispatches():
    f = jax.jit(lambda x: x * 3)
    pw = dw.program("compile_prog")
    x = np.arange(8, dtype=np.float32)
    for _ in range(3):  # one compile, three dispatches
        with pw.dispatch(x.shape):
            f(x)
    snap = pw.snapshot()
    assert snap["dispatches"] == 3
    assert snap["compiles"] == 1
    assert snap["compile_seconds"] > 0
    assert snap["signatures"] == {str(((8,),)): 3}
    with pw.dispatch((16,)):  # second shape, second compile
        f(np.arange(16, dtype=np.float32))
    assert pw.snapshot()["compiles"] == 2
    assert _family_value("bcp_xla_compiles_total",
                         program="compile_prog") == 2
    # the compile-time histogram saw both
    fam = tm.REGISTRY.snapshot()["bcp_xla_compile_seconds"]
    counts = {tuple(v["labels"].items()): v["count"]
              for v in fam["values"]}
    assert counts[(("program", "compile_prog"),)] == 2


def test_cost_analysis_captured_at_first_compile():
    f = jax.jit(lambda x: (x * 2 + 1).sum())
    pw = dw.program("cost_prog")
    x = np.arange(64, dtype=np.float32)
    with pw.dispatch(x.shape, jitfn=f, args=(x,)):
        f(x)
    cost = pw.snapshot()["cost"]
    assert str(((64,),)) in cost
    assert cost[str(((64,),))]["flops"] > 0
    # never: the knob must suppress the second compile entirely
    import os

    os.environ["BCP_DEVICEWATCH_COST"] = "never"
    try:
        with pw.dispatch((128,), jitfn=f,
                         args=(np.arange(128, dtype=np.float32),)):
            f(np.arange(128, dtype=np.float32))
        assert str(((128,),)) not in pw.snapshot()["cost"]
    finally:
        os.environ.pop("BCP_DEVICEWATCH_COST", None)


def test_dispatch_bookkeeping_survives_a_raising_call():
    """A failed kernel call (the glv->w4 degradation path) still counts
    the shape attempt — and the watch context unwinds cleanly."""
    pw = dw.program("boom_prog", shape_budget=1)
    with pytest.raises(RuntimeError):
        with pw.dispatch((32,)):
            raise RuntimeError("mosaic says no")
    snap = pw.snapshot()
    assert snap["dispatches"] == 1
    assert snap["shapes"] == 1
    assert dw._ctx_stack() == []


def test_ecdsa_programs_declare_budgets():
    """The ecdsa dispatch legs register watched programs with the bucket
    design's declared shape budgets at import."""
    from bitcoincashplus_tpu.ops import ecdsa_batch as eb

    progs = dw.snapshot()["programs"]
    # ops/ecdsa_batch was imported (and thus registered) by other suites;
    # after dw.reset() re-derive the handles the module holds
    assert eb._PW_GLV.shape_budget == eb.PALLAS_SHAPE_BUDGET
    assert eb._PW_GLV_DEV.shape_budget == eb.PALLAS_SHAPE_BUDGET
    assert eb._PW_W4_BYTES.shape_budget == eb.PALLAS_SHAPE_BUDGET
    assert eb._PW_XLA.shape_budget == len(eb.BUCKETS)
    assert isinstance(progs, dict)


# ---------------------------------------------------------------------------
# transfer & memory accounting
# ---------------------------------------------------------------------------


def test_transfer_accounting_totals_and_families():
    dw.note_transfer("ecdsa", "h2d", 1024)
    dw.note_transfer("ecdsa", "h2d", 512)
    dw.note_transfer("ecdsa", "d2h", 16, seconds=0.002)
    assert dw.transfer_snapshot() == {
        "ecdsa": {"d2h": 16, "h2d": 1536}}
    assert _family_value("bcp_device_transfer_bytes_total",
                         site="ecdsa", direction="h2d") == 1536
    assert _family_value("bcp_device_transfer_bytes_total",
                         site="ecdsa", direction="d2h") == 16
    # the transfer-time histogram only saw the timed crossing
    fam = tm.REGISTRY.snapshot()["bcp_device_transfer_seconds"]
    assert sum(v["count"] for v in fam["values"]) == 1


def test_memory_collector_is_a_graceful_noop_on_cpu():
    """CPU devices answer memory_stats() with None: the families still
    export (stable namespace) with supported=0 and no byte samples."""
    fams = {f["name"]: f for f in dw._collect_device_memory()}
    assert set(fams) == {"bcp_device_memory_bytes",
                         "bcp_device_memory_supported",
                         "bcp_device_count"}
    assert fams["bcp_device_memory_bytes"]["samples"] == []
    sups = fams["bcp_device_memory_supported"]["samples"]
    assert sups and all(v == 0 for _labels, v in sups)
    assert fams["bcp_device_count"]["samples"][0][1] >= 1
    # and the scrape surfaces them (collector registered at import)
    text = tm.REGISTRY.prometheus_text()
    for name in ("bcp_device_memory_bytes", "bcp_device_memory_supported",
                 "bcp_device_count", "bcp_xla_compile_seconds",
                 "bcp_device_transfer_bytes_total"):
        assert f"# TYPE {name}" in text, name


def test_phase_histogram_records_per_site_phases():
    with dw.phase("ecdsa", "pack"):
        pass
    dw.note_phase("ecdsa", "execute", 0.01)
    fam = tm.REGISTRY.snapshot()["bcp_dispatch_phase_seconds"]
    seen = {(v["labels"]["site"], v["labels"]["phase"]): v["count"]
            for v in fam["values"]}
    assert seen[("ecdsa", "pack")] == 1
    assert seen[("ecdsa", "execute")] == 1


# ---------------------------------------------------------------------------
# profiler RPC round trip
# ---------------------------------------------------------------------------


def test_profiler_rpc_round_trip(tmp_path):
    import gzip
    import os
    import types

    from bitcoincashplus_tpu.rpc.control import startprofile, stopprofile
    from bitcoincashplus_tpu.rpc.registry import RPCError

    node = types.SimpleNamespace(datadir=str(tmp_path))
    with pytest.raises(RPCError):
        stopprofile(node, [])  # not running yet
    out = startprofile(node, [])
    assert out["active"] and out["path"] == str(tmp_path / "profile")
    with pytest.raises(RPCError):  # double start rejected
        startprofile(node, [])
    jax.jit(lambda x: x + 1)(np.arange(8, dtype=np.float32))
    stopped = stopprofile(node, [])
    assert stopped["path"] == out["path"]
    assert stopped["seconds"] >= 0
    # TensorBoard-compatible dump landed (plugins/profile/<ts>/...)
    files = []
    for root, _dirs, fs in os.walk(out["path"]):
        files += [os.path.join(root, f) for f in fs]
    assert any(f.endswith(".xplane.pb") for f in files), files
    tj = [f for f in files if f.endswith("trace.json.gz")]
    assert tj and gzip.open(tj[0]).read(1)  # non-empty, readable
    with pytest.raises(RPCError):
        stopprofile(node, [])  # stopped twice
    assert dw.profile_snapshot() == {"active": False, "path": None,
                                     "dumps": 1}


def test_gettpuinfo_gains_device_section():
    import types

    from bitcoincashplus_tpu.rpc.control import gettpuinfo
    from bitcoincashplus_tpu.validation.sigcache import SignatureCache

    node = types.SimpleNamespace(
        backend="cpu",
        sigcache=SignatureCache(),
        chainstate=types.SimpleNamespace(
            bench={}, pipeline_snapshot=lambda: {}, bip30_stats={}),
        connman=None,
    )
    dw.note_transfer("ecdsa", "h2d", 64)
    out = gettpuinfo(node, [])
    dev = out["device"]
    assert {"programs", "transfer_bytes", "profiler",
            "watchdog", "unattributed_compiles"} <= set(dev)
    assert dev["transfer_bytes"]["ecdsa"]["h2d"] == 64
    assert dev["profiler"]["active"] is False


# ---------------------------------------------------------------------------
# stall watchdog (fake clock)
# ---------------------------------------------------------------------------


def test_watchdog_fires_and_clears_on_fake_clock(monkeypatch):
    warnings = []
    monkeypatch.setattr(dw, "log_printf",
                        lambda msg, *a: warnings.append(msg % a))
    clk = [0.0]
    pending = [0]
    wd = dw.Watchdog(clock=lambda: clk[0])
    wd.register("svc", pending_fn=lambda: pending[0], quiet_s=5.0)

    assert wd.check() == []          # idle, no pending: never stalls
    clk[0] = 100.0
    assert wd.check() == []
    pending[0] = 7                   # work appears
    wd.beat("svc")                   # progress at t=100
    clk[0] = 104.9
    assert wd.check() == []          # inside the quiet period
    clk[0] = 105.1
    assert wd.check() == ["svc"]     # quiet period elapsed: stalled
    snap = wd.snapshot()["svc"]
    assert snap["stalled"] and snap["episodes"] == 1
    assert any("stalled" in w and "observe-only" in w for w in warnings)
    assert wd.check() == ["svc"]     # still stalled: ONE episode, no spam
    assert wd.snapshot()["svc"]["episodes"] == 1
    wd.beat("svc")                   # progress clears it
    assert not wd.snapshot()["svc"]["stalled"]
    assert wd.check() == []
    clk[0] = 200.0                   # second episode
    assert wd.check() == ["svc"]
    assert wd.snapshot()["svc"]["episodes"] == 2
    pending[0] = 0                   # work drained without a beat: clear
    assert wd.check() == []
    assert not wd.snapshot()["svc"]["stalled"]


def test_watchdog_quiet_zero_disables_detection():
    clk = [0.0]
    wd = dw.Watchdog(clock=lambda: clk[0])
    wd.register("off", pending_fn=lambda: 5, quiet_s=0)
    clk[0] = 1e6
    assert wd.check() == []
    assert wd.snapshot()["off"]["stalled"] is False


def test_watchdog_beat_on_unregistered_name_is_a_noop():
    wd = dw.Watchdog(clock=lambda: 0.0)
    wd.beat("ghost")  # must not raise
    wd.register("x", pending_fn=lambda: 0)
    wd.unregister("x")
    wd.beat("x")
    assert wd.check() == []


def test_watchdog_gauge_and_episode_counter_export(monkeypatch):
    clk = [0.0]
    wd = dw.Watchdog(clock=lambda: clk[0])
    wd.register("expo", pending_fn=lambda: 3, quiet_s=1.0)
    clk[0] = 2.0
    wd.check()
    assert _family_value("bcp_watchdog_stalled", subsystem="expo") == 1
    assert _family_value("bcp_watchdog_stall_episodes_total",
                         subsystem="expo") == 1
    wd.beat("expo")
    assert _family_value("bcp_watchdog_stalled", subsystem="expo") == 0


def test_sigservice_wires_the_watchdog():
    """The service registers on start, beats per flush, unregisters on
    stop — the wiring the node knob (-watchdogquiet) parameterizes."""
    from bitcoincashplus_tpu.crypto import secp256k1 as oracle
    from bitcoincashplus_tpu.script.interpreter import SigCheckRecord
    from bitcoincashplus_tpu.serving import SigService

    svc = SigService(backend="cpu", deadline_ms=1, lanes=4,
                     watchdog_quiet=123.0).start()
    try:
        assert "sigservice" in dw.WATCHDOG.snapshot()
        assert dw.WATCHDOG.snapshot()["sigservice"]["quiet_s"] == 123.0
        sk = 0x1234
        e = 0x5678
        r, s = oracle.ecdsa_sign(sk, e)
        rec = SigCheckRecord(oracle.point_mul(sk, oracle.G), r, s, e)
        assert svc.submit([rec]).result().tolist() == [True]
        assert dw.WATCHDOG.beat_totals().get("sigservice", 0) >= 1
        assert svc.snapshot()["watchdog"]["beats"] >= 1
    finally:
        svc.stop()
    assert "sigservice" not in dw.WATCHDOG.snapshot()


def test_chainstate_registers_pipeline_watchdog():
    """A ChainstateManager registers the settle-horizon probe at init
    (the node re-registers with -watchdogquiet and unregisters at
    close); the probe reads the live horizon depth."""
    from bitcoincashplus_tpu.consensus.params import regtest_params
    from bitcoincashplus_tpu.store.blockstore import MemoryBlockStore
    from bitcoincashplus_tpu.validation.chainstate import ChainstateManager
    from bitcoincashplus_tpu.validation.coins import MemoryCoinsView

    cs = ChainstateManager(regtest_params(), MemoryCoinsView(),
                           MemoryBlockStore(), script_verifier=None)
    assert "pipeline" in dw.WATCHDOG.snapshot()
    # the probe tracks the speculation tree's total entry count
    # (ISSUE 9: _horizon is now the derived winning-path view; the
    # pending work the watchdog cares about is every open layer)
    cs._spec[b"\x11" * 32] = {"idx": None, "parent": None,
                              "children": []}
    clk_entry = dw.WATCHDOG._entries["pipeline"]
    assert clk_entry["pending_fn"]() == 1
    cs._spec.clear()
    assert clk_entry["pending_fn"]() == 0


def test_persistent_cache_hits_surface_in_snapshot(tmp_path):
    """Second compile of the same program is served from the persistent
    cache and the monitoring listener tallies it — the cache_hits field
    gettpuinfo.device.compilation_cache exposes (and that the functional
    suite asserts > 0 on re-spawned nodes via conftest's seeded
    BCP_COMPILE_CACHE). Toy jit, so the 2 s min-compile-time floor is
    lowered for the duration; all cache config is restored after."""
    saved_dir = jax.config.jax_compilation_cache_dir
    saved_min = jax.config.jax_persistent_cache_min_compile_time_secs
    saved_cc = dict(dir=dw._COMPILE_CACHE["dir"],
                    enabled=dw._COMPILE_CACHE["enabled"])
    try:
        dw.enable_compile_cache(str(tmp_path / "cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)

        @jax.jit
        def f(x):
            return x * 2 + 1

        assert int(f(np.int32(20))) == 41  # cold: writes the cache entry
        jax.clear_caches()  # drop the in-memory executable
        assert int(f(np.int32(20))) == 41  # warm: persistent-cache read
        snap = dw.compile_cache_snapshot()
        assert snap["enabled"]
        assert snap["dir"] == str(tmp_path / "cache")
        assert snap["cache_hits"] > 0
    finally:
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          saved_min)
        if saved_dir is not None:
            jax.config.update("jax_compilation_cache_dir", saved_dir)
        with dw._LOCK:
            dw._COMPILE_CACHE.update(saved_cc)
