"""tools/trace_view.py — the offline -tracefile summarizer (ISSUE 6
satellite): per-stage table, measured overlap fraction, top-10 slowest
settles. Golden-output: the report is deterministic text."""

from __future__ import annotations

import json
import sys

import pytest

sys.path.insert(0, __file__.rsplit("/tests/", 1)[0])

from tools import trace_view  # noqa: E402

pytestmark = pytest.mark.telemetry


def _span(name, ts, dur, **args):
    return {"name": name, "ph": "X", "ts": ts, "dur": dur,
            "pid": 1, "tid": 1, "args": args}


# A synthetic 3-block pipelined import, microsecond timestamps:
#   h=1: scan 0..100ms, settle 150..170ms  -> inflight 70ms, blocked 20ms
#   h=2: scan 100..190ms, settle 250..330ms -> inflight 140ms, blocked 80ms
#   h=3: scan 190..260ms, settle 330..335ms -> inflight 75ms, blocked 5ms
EVENTS = [
    _span("block.scan", 0, 100_000, height=1),
    _span("block.scan", 100_000, 90_000, height=2),
    _span("block.scan", 190_000, 70_000, height=3),
    _span("block.settle", 150_000, 20_000, height=1),
    _span("block.settle", 250_000, 80_000, height=2),
    _span("block.settle", 330_000, 5_000, height=3),
    _span("ecdsa.settle", 150_000, 18_000, lanes=2046),
    {"name": "block.unwind", "ph": "i", "s": "t", "ts": 400_000,
     "pid": 1, "tid": 1,
     "args": {"height": 4, "dropped": 2, "reason": "blk-bad-inputs"}},
]

GOLDEN = """\
trace summary: 8 events, 7 spans

per-stage time
stage                         count    total_ms   mean_ms    p50_ms    p99_ms
block.scan                        3       260.0     86.67     90.00    100.00
block.settle                      3       105.0     35.00     20.00     80.00
ecdsa.settle                      1        18.0     18.00     18.00     18.00

pipeline overlap (block.scan end -> block.settle end)
blocks measured: 3
aggregate overlap fraction: 0.6316  (in-flight 285.0 ms, blocked 105.0 ms)

top 10 slowest settles
  height   settle_ms   overlap
       2       80.00    0.4286
       1       20.00    0.7143
       3        5.00    0.9333

unwinds: 1
  height 4: dropped 2 block(s) (blk-bad-inputs)
"""


def test_summarize_golden():
    assert trace_view.summarize(EVENTS) == GOLDEN


def test_block_overlap_math():
    blocks = trace_view.block_overlap(EVENTS)
    assert [b["height"] for b in blocks] == [1, 2, 3]
    b1 = blocks[0]
    # scan end 100ms, settle end 170ms -> 70ms in flight, 20ms blocked
    assert b1["inflight_ms"] == pytest.approx(70.0)
    assert b1["settle_ms"] == pytest.approx(20.0)
    assert b1["overlap"] == pytest.approx(1 - 20.0 / 70.0)
    # a block missing its settle span (unwound) is skipped
    partial = [_span("block.scan", 0, 10_000, height=9)]
    assert trace_view.block_overlap(partial) == []


def test_block_overlap_pairs_by_hash_across_unwind():
    """An unwound block's scan at height 3 must NOT pair with the
    competing block's settle at the same height — pairing keys on the
    hash arg when present."""
    events = [
        _span("block.scan", 0, 10_000, height=3, hash="aaaa"),   # unwound
        _span("block.scan", 500_000, 10_000, height=3, hash="bbbb"),
        _span("block.settle", 520_000, 5_000, height=3, hash="bbbb"),
    ]
    blocks = trace_view.block_overlap(events)
    assert len(blocks) == 1
    b = blocks[0]
    # paired with bbbb's scan (end 510ms), not aaaa's (end 10ms):
    # in-flight = 525 - 510 = 15ms, not 515ms
    assert b["inflight_ms"] == pytest.approx(15.0)
    assert b["overlap"] == pytest.approx(1 - 5.0 / 15.0)


def test_percentile_nearest_rank():
    durs = [1.0, 2.0, 3.0, 4.0]
    assert trace_view.percentile(durs, 0.5) == 2.0
    assert trace_view.percentile(durs, 0.99) == 4.0
    assert trace_view.percentile([], 0.5) == 0.0


def test_load_accepts_both_dump_forms(tmp_path):
    wrapped = tmp_path / "w.json"
    wrapped.write_text(json.dumps({"traceEvents": EVENTS}))
    bare = tmp_path / "b.json"
    bare.write_text(json.dumps(EVENTS))
    assert trace_view.load(str(wrapped)) == EVENTS
    assert trace_view.load(str(bare)) == EVENTS
    bad = tmp_path / "x.json"
    bad.write_text('{"nope": 1}')
    with pytest.raises((ValueError, KeyError)):
        trace_view.load(str(bad))


def test_main_prints_report(tmp_path, capsys):
    path = tmp_path / "trace.json"
    path.write_text(json.dumps({"traceEvents": EVENTS}))
    assert trace_view.main(["trace_view.py", str(path)]) == 0
    assert capsys.readouterr().out == GOLDEN
    assert trace_view.main(["trace_view.py"]) == 2


# -- signature serving section (ISSUE 7) -------------------------------


SERVING_EVENTS = [
    _span("serving.flush", 10_000, 3_000, reason="deadline", lanes=4),
    _span("serving.flush", 50_000, 8_000, reason="full", lanes=2046),
    _span("serving.flush", 70_000, 2_000, reason="kick", lanes=2),
    _span("serving.flush", 90_000, 2_500, reason="kick", lanes=3),
    _span("serving.settle", 10_500, 2_000, lanes=4),
    _span("serving.settle", 50_500, 7_000, lanes=2046),
    _span("serving.settle", 70_500, 1_500, lanes=2),
    _span("serving.settle", 90_500, 2_000, lanes=3),
    {"name": "serving.deadline_miss", "ph": "i", "s": "t", "ts": 9_000,
     "pid": 1, "tid": 1,
     "args": {"age_ms": 12.5, "deadline_ms": 4.0, "lanes": 4}},
]


def test_serving_section_reports_flush_breakdown():
    lines = trace_view.serving_section(SERVING_EVENTS)
    text = "\n".join(lines)
    assert "signature serving" in text
    # flush-reason breakdown, most-frequent reason first
    kick_row = next(ln for ln in lines if ln.startswith("kick"))
    assert "2" in kick_row.split()[1]  # count
    full_row = next(ln for ln in lines if ln.startswith("full"))
    assert "2046" in full_row
    # the flush -> settle chain
    assert "4 flush / 4 settle spans" in text
    # the deadline-miss list
    assert "deadline misses: 1" in text
    assert "age 12.5 ms vs deadline 4.0 ms (4 lane(s))" in text


def test_serving_section_absent_without_serving_spans():
    # pre-serving dumps keep their byte-stable golden report
    assert trace_view.serving_section(EVENTS) == []
    assert "signature serving" not in trace_view.summarize(EVENTS)


def test_summarize_includes_serving_when_present():
    out = trace_view.summarize(EVENTS + SERVING_EVENTS)
    assert "signature serving" in out
    assert out.index("signature serving") < out.index("unwinds:")


# -- reorg report (ISSUE 9 speculation tree) ---------------------------


def _instant(name, ts, **args):
    return {"name": name, "ph": "i", "s": "t", "ts": ts,
            "pid": 1, "tid": 1, "args": args}


REORG_EVENTS = [
    _instant("block.reorg", 100_000, depth=3, to_height=42,
             to_hash="00aa11bb22cc33dd"),
    _instant("block.reorg", 200_000, depth=1, to_height=43,
             to_hash="00ee11ff22aa33bb"),
    _instant("block.unwind", 300_000, height=44, branch="deadbeef0001",
             dropped=2, reason="blk-bad-inputs"),
    _instant("block.branch_drop", 400_000, branch="cafecafe0002",
             height=44, hash="1122334455667788", blocks=3,
             reason="lost-work", lifetime_ms=512.25),
    _instant("block.branch_drop", 500_000, branch="cafecafe0003",
             height=45, hash="99aabbccddeeff00", blocks=1,
             reason="lost-work", lifetime_ms=87.75),
]

REORG_GOLDEN = """\

reorg report (speculation tree)
reorgs: 2  depth max 3 mean 2.00
  depth 3 -> 00aa11bb22cc33dd height 42
  depth 1 -> 00ee11ff22aa33bb height 43
settle-failure unwinds: 1 (2 speculative block(s) dropped)
losing branches dropped: 2 (4 block(s)), lifetime mean 300.0 ms max 512.2 ms
  branch cafecafe0002 from height 44: 3 block(s), lost-work, lived 512.2 ms
  branch cafecafe0003 from height 45: 1 block(s), lost-work, lived 87.8 ms"""


def test_reorg_section_golden():
    assert "\n".join(trace_view.reorg_section(REORG_EVENTS)) == REORG_GOLDEN


def test_reorg_section_absent_without_tree_events():
    # pre-tree dumps (even ones WITH unwind instants) keep their
    # byte-stable report — the unwind list at the report tail already
    # covers them and the golden above must not regress
    assert trace_view.reorg_section(EVENTS) == []
    assert "reorg report" not in trace_view.summarize(EVENTS)


def test_summarize_includes_reorg_report_when_present():
    out = trace_view.summarize(EVENTS + REORG_EVENTS)
    assert "reorg report (speculation tree)" in out
    # ordered after serving (absent here), before the unwind tail
    assert out.index("reorg report") < out.index("unwinds:")
