"""Flood-scale mempool differentials (ISSUE 20).

The batched pool (numpy columns + incremental frontiers + staged bulk
removal) must agree ENTRY-FOR-ENTRY with the per-tx reference paths —
same survivors, same aggregates, same template, same eviction victims —
over seeded random package graphs, including deep chains at the
ancestor limits and prioritisetransaction deltas mid-storm. The
`mempoolstorm` marker groups the suite after the serving unit tests
(conftest ordering); everything here is pure pool mechanics, no
chainstate, tier-1 fast.
"""

import random

import pytest

from bitcoincashplus_tpu.consensus.tx import COutPoint, CTransaction, CTxIn, CTxOut
from bitcoincashplus_tpu.mempool import CTxMemPool, MempoolEntry
from bitcoincashplus_tpu.mempool.mempool import (
    MEMPOOL_SITE,
    feerate_gt,
    score_key,
)

pytestmark = pytest.mark.mempoolstorm


def _fake_tx(inputs, n_out=1, value=10_000, salt=0):
    return CTransaction(
        vin=tuple(CTxIn(op, bytes([salt % 256, (salt >> 8) % 256]))
                  for op in inputs),
        vout=tuple(CTxOut(value, b"\x51") for _ in range(n_out)),
    )


def _root_tx(salt, n_out=1):
    return _fake_tx(
        [COutPoint(salt.to_bytes(4, "big") * 8, 0)], n_out=n_out, salt=salt)


def _entry(tx, fee=1000, t=0, height=1):
    return MempoolEntry(tx, fee, t, height)


# ----------------------------------------------------------------------
# seeded storm: a random package-graph op sequence applied to a pool
# ----------------------------------------------------------------------


def _run_storm(pool: CTxMemPool, seed: int, n_ops: int = 300,
               max_bytes: int = None) -> None:
    """Apply a deterministic random op storm: adds (deep chains and wide
    fans alike), recursive removals, block confirmations, prioritise
    deltas (negative included), expiry sweeps, and -maxmempool trims.
    Same seed => byte-identical op sequence regardless of pool flavor."""
    rng = random.Random(seed)
    salt = seed * 1_000_000
    clock = 0
    for _ in range(n_ops):
        clock += rng.randint(0, 50)
        op = rng.random()
        if op < 0.62 or not pool.entries:
            salt += 1
            # extend an existing package (possibly to the 25-deep limit)
            # or start a fresh root
            if pool.entries and rng.random() < 0.7:
                parent = pool.entries[
                    rng.choice(sorted(pool.entries))]
                if parent.count_with_ancestors >= 25:
                    tx = _root_tx(salt, n_out=rng.randint(1, 3))
                else:
                    spent = {op_.n for op_ in pool.map_next_tx
                             if op_.hash == parent.txid}
                    free = [i for i in range(len(parent.tx.vout))
                            if i not in spent]
                    if not free:
                        tx = _root_tx(salt, n_out=rng.randint(1, 3))
                    else:
                        tx = _fake_tx(
                            [COutPoint(parent.txid, rng.choice(free))],
                            n_out=rng.randint(1, 3), salt=salt)
            else:
                tx = _root_tx(salt, n_out=rng.randint(1, 3))
            fee = rng.randint(100, 50_000)
            fee += pool.map_deltas.get(tx.txid, 0)
            pool.add_unchecked(_entry(tx, fee=fee, t=clock))
        elif op < 0.72:
            victim = rng.choice(sorted(pool.entries))
            pool.remove_recursive(victim)
        elif op < 0.82:
            txid = rng.choice(sorted(pool.entries))
            pool.prioritise(txid, rng.randint(-3000, 8000))
        elif op < 0.90:
            # confirm a package prefix in a "block" — parents first, the
            # order remove_for_block sees
            roots = [t for t, e in pool.entries.items()
                     if e.count_with_ancestors == 1]
            if roots:
                root = rng.choice(sorted(roots))
                stage = sorted(
                    pool.calculate_descendants(root),
                    key=lambda t: (pool.entries[t].count_with_ancestors, t))
                k = rng.randint(1, len(stage))
                pool.remove_for_block(
                    [pool.entries[t].tx for t in stage[:k]])
        elif op < 0.95:
            pool.expire(now=clock - rng.randint(0, 500)
                        + pool.expiry_seconds)
        elif max_bytes is not None:
            pool.trim_to_size(
                max(max_bytes, int(pool.total_size * 0.7)))


def _oracle_aggregates(pool: CTxMemPool, txid: bytes) -> tuple:
    """Brute-force recompute of one entry's cached aggregates by walking
    the live graph."""
    e = pool.entries[txid]
    anc = pool.calculate_ancestors(e.tx)
    desc = pool.calculate_descendants(txid)  # includes self
    return (
        len(anc) + 1,
        e.size + sum(pool.entries[a].size for a in anc),
        e.fee + sum(pool.entries[a].fee for a in anc),
        len(desc),
        sum(pool.entries[d].size for d in desc),
        sum(pool.entries[d].fee for d in desc),
    )


def _assert_pool_consistent(pool: CTxMemPool) -> None:
    for txid, e in pool.entries.items():
        assert (
            e.count_with_ancestors, e.size_with_ancestors,
            e.fees_with_ancestors, e.count_with_descendants,
            e.size_with_descendants, e.fees_with_descendants,
        ) == _oracle_aggregates(pool, txid), txid.hex()
        if pool.batch:
            row = pool.columns.txrow[txid]
            assert pool.columns.fees_wa[row] == e.fees_with_ancestors
            assert pool.columns.size_wd[row] == e.size_with_descendants
            assert pool.columns.count_wa[row] == e.count_with_ancestors
            assert pool.columns.fee[row] == e.fee
    assert pool.total_size == sum(e.size for e in pool.entries.values())
    assert pool.total_fee == sum(e.fee for e in pool.entries.values())
    assert len(pool.map_next_tx) == sum(
        len(e.tx.vin) for e in pool.entries.values())


class TestStormDifferential:
    @pytest.mark.parametrize("seed", [1, 7, 42, 1337])
    def test_batched_vs_reference_lockstep(self, seed):
        """Same seeded storm into a batched and a reference pool: the
        surviving sets, every cached aggregate, the template, and the
        eviction victims must be identical."""
        batched = CTxMemPool(batch=True)
        reference = CTxMemPool(batch=False)
        _run_storm(batched, seed, max_bytes=60_000)
        _run_storm(reference, seed, max_bytes=60_000)

        assert set(batched.entries) == set(reference.entries)
        for txid, e in batched.entries.items():
            r = reference.entries[txid]
            assert (e.fee, e.fees_with_ancestors, e.size_with_ancestors,
                    e.fees_with_descendants, e.size_with_descendants) == \
                   (r.fee, r.fees_with_ancestors, r.size_with_ancestors,
                    r.fees_with_descendants, r.size_with_descendants)
        _assert_pool_consistent(batched)
        _assert_pool_consistent(reference)

        # template parity at several size caps (overflow-skip coverage)
        for cap in (2_000, 10_000, 1_000_000):
            sel_b = batched.select_for_block(cap, height=1, block_time=0)
            sel_r = reference.select_for_block(cap, height=1, block_time=0)
            assert [e.txid for e in sel_b] == [e.txid for e in sel_r]

        # eviction parity: trim both to the same shrinking caps
        for frac in (0.75, 0.4, 0.0):
            cap = int(batched.total_size * frac)
            assert batched.trim_to_size(cap) == reference.trim_to_size(cap)
            assert set(batched.entries) == set(reference.entries)
        assert batched.perf["select_batched"] >= 3
        assert batched.perf["bulk_evict_episodes"] >= 1

    @pytest.mark.parametrize("seed", [3, 99])
    def test_aggregate_oracle_after_storm(self, seed):
        pool = CTxMemPool(batch=True)
        _run_storm(pool, seed, n_ops=400, max_bytes=50_000)
        _assert_pool_consistent(pool)

    def test_prioritise_mid_storm_negative_delta(self):
        """A negative delta mid-chain reorders both template and
        eviction identically in both flavors."""
        pools = [CTxMemPool(batch=True), CTxMemPool(batch=False)]
        for pool in pools:
            parent = _root_tx(1, n_out=2)
            child = _fake_tx([COutPoint(parent.txid, 0)], salt=2)
            rival = _root_tx(3)
            pool.add_unchecked(_entry(parent, fee=5000))
            pool.add_unchecked(_entry(child, fee=5000))
            pool.add_unchecked(_entry(rival, fee=4000))
            pool.prioritise(parent.txid, -4500)
        sel = [[e.txid for e in p.select_for_block(10**6, 1, 0)]
               for p in pools]
        assert sel[0] == sel[1]
        assert sel[0][0] == pools[0].entries[
            sorted(pools[0].entries,
                   key=lambda t: -score_key(
                       pools[0].entries[t].fees_with_ancestors,
                       pools[0].entries[t].size_with_ancestors))[0]].txid
        assert pools[0].trim_to_size(0) == pools[1].trim_to_size(0)

    def test_deep_chain_at_ancestor_limit(self):
        """A 25-deep chain (the ancestor limit) stays exact in both
        flavors through selection and staged removal."""
        pools = [CTxMemPool(batch=True), CTxMemPool(batch=False)]
        for pool in pools:
            prev = _root_tx(1)
            pool.add_unchecked(_entry(prev, fee=100))
            for d in range(24):
                nxt = _fake_tx([COutPoint(prev.txid, 0)], salt=d + 2)
                pool.add_unchecked(_entry(nxt, fee=100 * (d + 2)))
                prev = nxt
            assert pool.entries[prev.txid].count_with_ancestors == 25
        sels = [[e.txid for e in p.select_for_block(10**6, 1, 0)]
                for p in pools]
        assert sels[0] == sels[1] and len(sels[0]) == 25
        # confirming the middle of the chain in a block must not leak
        # aggregates in either flavor
        for pool in pools:
            stage = sorted(
                pool.entries.values(),
                key=lambda e: e.count_with_ancestors)[:13]
            pool.remove_for_block([e.tx for e in stage])
            _assert_pool_consistent(pool)
        assert set(pools[0].entries) == set(pools[1].entries)


class TestExactFeerates:
    def test_cross_multiplication_beats_float_ties(self):
        """Fee magnitudes where float64 rounds to a tie must still order
        exactly (the satellite's reason to exist)."""
        fee_a, size_a = (1 << 53) + 1, 1000
        fee_b, size_b = (1 << 53), 1000
        assert fee_a / size_a == fee_b / size_b  # float can't see it
        assert feerate_gt(fee_a, size_a, fee_b, size_b)
        assert not feerate_gt(fee_b, size_b, fee_a, size_a)
        assert score_key(fee_a, size_a) > score_key(fee_b, size_b)

    def test_score_key_matches_cross_multiplication(self):
        rng = random.Random(5)
        pairs = [(rng.randint(-10_000, 10**15), rng.randint(60, 2_500_000))
                 for _ in range(500)]
        for (fa, sa), (fb, sb) in zip(pairs[:-1], pairs[1:]):
            gt = feerate_gt(fa, sa, fb, sb)
            lt = feerate_gt(fb, sb, fa, sa)
            key_a, key_b = score_key(fa, sa), score_key(fb, sb)
            if gt:
                assert key_a > key_b
            elif lt:
                assert key_a < key_b
            else:
                assert key_a == key_b

    def test_float_forms_still_exist_for_display(self):
        e = _entry(_root_tx(1), fee=1234)
        assert e.fee_rate() == pytest.approx(1234 / e.size)
        assert e.ancestor_fee_rate() == e.descendant_fee_rate()


class TestRemoveForBlockLeak:
    def test_parent_before_child_confirmation_no_leak(self):
        """Regression: G -> A -> B with A and B confirmed in one block.
        The old sequential removal dropped A first, severing B's
        ancestor walk to G — G kept phantom descendant aggregates
        forever. The staged removal fixes both relatives against the
        pre-removal graph."""
        pool = CTxMemPool(batch=True)
        g = _root_tx(1)
        a = _fake_tx([COutPoint(g.txid, 0)], salt=2)
        b = _fake_tx([COutPoint(a.txid, 0)], salt=3)
        pool.add_unchecked(_entry(g, fee=1000))
        pool.add_unchecked(_entry(a, fee=2000))
        pool.add_unchecked(_entry(b, fee=3000))
        pool.remove_for_block([a, b])  # block order: parent first
        ge = pool.entries[g.txid]
        assert ge.count_with_descendants == 1
        assert ge.size_with_descendants == ge.size
        assert ge.fees_with_descendants == ge.fee
        _assert_pool_consistent(pool)


class TestFaultDrills:
    def test_fail_once_falls_back_to_reference(self, fault_harness):
        """BCP005 parity, fail leg: an injected fault at the mempool
        site must take the per-tx reference path and still produce the
        reference answer."""
        pool = CTxMemPool(batch=True)
        control = CTxMemPool(batch=False)
        for p in (pool, control):
            _run_storm(p, seed=11, n_ops=120)
        fault_harness("fail-once", ops="mempool")
        sel = [e.txid for e in pool.select_for_block(10**6, 1, 0)]
        ref = [e.txid for e in control.select_for_block(10**6, 1, 0)]
        assert sel == ref
        assert pool.perf["select_fallbacks"] == 1

        fault_harness("fail-once", ops="mempool")
        assert pool.trim_to_size(0) == control.trim_to_size(0)
        assert pool.perf["trim_fallbacks"] == 1

    def test_poison_caught_by_differential_gate(self, fault_harness):
        """BCP005 parity, poison leg: a corrupted batched verdict (a
        dropped template tail, a wrong eviction victim) must be caught
        by the gate and replaced with the per-tx oracle's answer."""
        pool = CTxMemPool(batch=True)
        control = CTxMemPool(batch=False)
        for p in (pool, control):
            _run_storm(p, seed=23, n_ops=120)
        fault_harness("poison-output", ops="mempool")
        sel = [e.txid for e in pool.select_for_block(10**6, 1, 0)]
        ref = [e.txid for e in control.select_for_block(10**6, 1, 0)]
        assert sel == ref  # the oracle's answer, not the poisoned one
        assert pool.perf["poisoned_verdicts"] >= 1

        before = pool.perf["poisoned_verdicts"]
        assert pool.trim_to_size(0) == control.trim_to_size(0)
        assert pool.perf["poisoned_verdicts"] > before
        assert set(pool.entries) == set(control.entries) == set()

    def test_selfcheck_clean_on_honest_verdicts(self):
        """-mempoolselfcheck with no fault armed: gates run, nothing
        diverges."""
        pool = CTxMemPool(batch=True, selfcheck=True)
        _run_storm(pool, seed=31, n_ops=150)
        pool.select_for_block(10**6, 1, 0)
        pool.trim_to_size(max(0, pool.total_size // 2))
        assert pool.perf["selfchecks"] >= 1
        assert pool.perf["poisoned_verdicts"] == 0


class TestPerfSurface:
    def test_perf_snapshot_shape(self):
        pool = CTxMemPool(batch=True)
        _run_storm(pool, seed=2, n_ops=80, max_bytes=20_000)
        snap = pool.perf_snapshot()
        assert snap["batch"] is True
        assert snap["frontier_depth"]["mining"] >= len(pool.entries)
        assert snap["columns"]["live"] == len(pool.entries)
        for key in ("column_syncs", "rows_synced", "frontier_pushes",
                    "frontier_stale_pops", "bulk_evict_episodes",
                    "staged_removals", "select_fallbacks",
                    "poisoned_verdicts"):
            assert isinstance(snap[key], int)

    def test_reference_pool_snapshot(self):
        pool = CTxMemPool(batch=False)
        _run_storm(pool, seed=2, n_ops=40)
        snap = pool.perf_snapshot()
        assert snap["batch"] is False
        assert snap["columns"]["live"] == 0

    def test_frontier_compaction_bounds_heap(self):
        """Dead keys accumulate per mutation; the lazy heaps must stay
        O(pool) via compaction."""
        pool = CTxMemPool(batch=True)
        root = _root_tx(1)
        pool.add_unchecked(_entry(root, fee=1000))
        for i in range(600):
            pool.prioritise(root.txid, 1 if i % 2 == 0 else -1)
        assert len(pool._mine_heap) <= max(256, 8 * len(pool.entries))
        assert pool.perf["frontier_rebuilds"] >= 1
        # the surviving frontier still answers exactly
        assert pool.select_for_block(10**6, 1, 0)[0].txid == root.txid


class TestColumnsGrowth:
    def test_row_recycling_and_growth(self):
        pool = CTxMemPool(batch=True)
        txids = []
        for i in range(1, 1500):
            tx = _root_tx(i)
            pool.add_unchecked(_entry(tx, fee=1000 + i))
            txids.append(tx.txid)
        assert pool.columns.cap >= 1500 and pool.columns.grows >= 1
        for t in txids[:700]:
            pool.remove_recursive(t)
        free_before = len(pool.columns.free)
        for i in range(2000, 2300):
            pool.add_unchecked(_entry(_root_tx(i), fee=500))
        assert len(pool.columns.free) == free_before - 300  # recycled
        _assert_pool_consistent(pool)
