"""Wallet e2e — coin tracking across connect/disconnect + spend round-trip.

Mirrors the reference's qa wallet.py basics: mine to the wallet, watch the
balance mature, create a transaction, mine it, see change tracked; reorg
removes the coins again. (VERDICT r2 weak #4: wallet.py had no tests.)
"""

import pytest

from bitcoincashplus_tpu.consensus.params import regtest_params
from bitcoincashplus_tpu.mempool import CTxMemPool, accept_to_memory_pool
from bitcoincashplus_tpu.mining.generate import generate_blocks
from bitcoincashplus_tpu.store.blockstore import MemoryBlockStore
from bitcoincashplus_tpu.validation.chainstate import ChainstateManager
from bitcoincashplus_tpu.validation.coins import MemoryCoinsView
from bitcoincashplus_tpu.validation.scriptcheck import BlockScriptVerifier
from bitcoincashplus_tpu.wallet.keys import CKey
from bitcoincashplus_tpu.wallet.wallet import Wallet

from test_validation import TILE

COIN = 10**8


@pytest.fixture
def rig():
    params = regtest_params()
    t = [1_600_000_000]

    def fake_time():
        t[0] += 60
        return t[0]

    cs = ChainstateManager(
        params, MemoryCoinsView(), MemoryBlockStore(),
        script_verifier=BlockScriptVerifier(params, backend="cpu"),
        get_time=fake_time,
    )
    wallet = Wallet(params)
    cs.on_block_connected.append(wallet.block_connected)
    cs.on_block_disconnected.append(wallet.block_disconnected)
    return cs, wallet


def _mine_to_wallet(cs, wallet, n):
    key = wallet.keys_by_pkh[next(iter(wallet.keys_by_pkh))] if wallet.keys_by_pkh \
        else None
    if key is None:
        addr = wallet.get_new_address()
        key = wallet.keys_by_pkh[next(iter(wallet.keys_by_pkh))]
    return generate_blocks(cs, key.p2pkh_script(), n, tile=TILE)


class TestWalletTracking:
    def test_balance_matures(self, rig):
        cs, wallet = rig
        _mine_to_wallet(cs, wallet, 101)
        tip_h = cs.tip().height
        # spendable-in-next-block rule: at tip 101 the height-1 and height-2
        # coinbases satisfy (102 - h) >= 100 (consensus maturity, one block
        # less conservative than the reference WALLET's depth>100 — consensus
        # parity is what block validation enforces)
        assert wallet.balance(tip_h) == 100 * COIN
        assert len(wallet.coins) == 101

    def test_immature_balance_zero(self, rig):
        cs, wallet = rig
        _mine_to_wallet(cs, wallet, 10)
        assert wallet.balance(cs.tip().height) == 0

    def test_spend_roundtrip(self, rig):
        """create_transaction → ATMP → mine → recipient + change tracked."""
        cs, wallet = rig
        _mine_to_wallet(cs, wallet, 105)
        tip_h = cs.tip().height
        balance0 = wallet.balance(tip_h)
        assert balance0 == 6 * 50 * COIN

        dest = wallet.get_new_address()  # pay ourselves: value stays (minus fee)
        fee = 10_000
        tx = wallet.create_transaction(dest, 30 * COIN, tip_h, fee=fee,
                                       enable_forkid=True)
        pool = CTxMemPool()
        cs.on_block_connected.append(
            lambda blk, idx: pool.remove_for_block(blk.vtx)
        )
        accept_to_memory_pool(pool, cs, tx)
        generate_blocks(cs, CKey(0x999).p2pkh_script(), 1, mempool=pool,
                        tile=TILE)
        blk = cs.get_block(cs.tip().hash)
        assert any(t.txid == tx.txid for t in blk.vtx[1:])
        # balance: lost one 50-coin input, regained 30 target + ~20 change
        # (both instantly mature, non-coinbase), and one more coinbase
        # matured when the tip advanced
        new_balance = wallet.balance(cs.tip().height)
        assert new_balance == balance0 + 50 * COIN - fee

    def test_insufficient_funds(self, rig):
        cs, wallet = rig
        _mine_to_wallet(cs, wallet, 101)
        with pytest.raises(ValueError, match="insufficient"):
            wallet.create_transaction(
                wallet.get_new_address(), 100 * COIN, cs.tip().height
            )

    def test_disconnect_removes_coins(self, rig):
        cs, wallet = rig
        _mine_to_wallet(cs, wallet, 3)
        assert len(wallet.coins) == 3
        tip = cs.tip()
        cs.invalidate_block(tip)
        assert len(wallet.coins) == 2


class TestWalletEncryption:
    """CCryptoKeyStore lifecycle (src/wallet/crypter.cpp) + wallet-file
    persistence round trips."""

    def test_encrypt_lock_unlock_spend(self, rig, tmp_path):
        cs, wallet = rig
        wallet.path = str(tmp_path / "wallet.json")
        _mine_to_wallet(cs, wallet, 101)
        assert wallet.balance(cs.tip().height) == 100 * COIN

        wallet.encrypt("correct horse")
        assert wallet.is_crypted and wallet.is_locked
        # locked: still tracks coins, refuses to sign or mint keys
        assert wallet.balance(cs.tip().height) == 100 * COIN
        from bitcoincashplus_tpu.wallet.wallet import WalletError

        with pytest.raises(WalletError):
            wallet.get_new_address()
        with pytest.raises(WalletError):
            wallet.create_transaction(
                CKey(0xBEEF).p2pkh_address(wallet.params), COIN,
                cs.tip().height, enable_forkid=True,
            )

        assert not wallet.unlock("wrong passphrase")
        assert wallet.is_locked
        assert wallet.unlock("correct horse")
        assert not wallet.is_locked
        tx = wallet.create_transaction(
            CKey(0xBEEF).p2pkh_address(wallet.params), COIN,
            cs.tip().height, enable_forkid=True,
        )
        assert tx.txid  # signed successfully

    def test_change_passphrase(self, rig, tmp_path):
        cs, wallet = rig
        wallet.path = str(tmp_path / "wallet.json")
        wallet.get_new_address()
        wallet.encrypt("old pass")
        assert not wallet.change_passphrase("bad", "new pass")
        assert wallet.change_passphrase("old pass", "new pass")
        assert not wallet.unlock("old pass")
        assert wallet.unlock("new pass")

    def test_encrypted_wallet_persists(self, rig, tmp_path):
        cs, wallet = rig
        path = str(tmp_path / "wallet.json")
        wallet.path = path
        addr = wallet.get_new_address()
        pkh_index = dict(wallet._pkh_index)
        wallet.encrypt("pass")

        reloaded = Wallet(wallet.params, path=path)
        reloaded.load()
        assert reloaded.is_crypted and reloaded.is_locked
        assert reloaded._pkh_index == pkh_index
        assert reloaded.unlock("pass")
        # the reloaded key signs for the same address
        key = next(iter(reloaded.keys_by_pkh.values()))
        assert key.p2pkh_address(wallet.params) == addr

    def test_plaintext_wallet_persists(self, rig, tmp_path):
        cs, wallet = rig
        path = str(tmp_path / "wallet.json")
        wallet.path = path
        addr = wallet.get_new_address()
        reloaded = Wallet(wallet.params, path=path)
        reloaded.load()
        key = next(iter(reloaded.keys_by_pkh.values()))
        assert key.p2pkh_address(wallet.params) == addr

    def test_unlock_timeout_relocks(self, rig):
        cs, wallet = rig
        wallet.get_new_address()
        wallet.encrypt("p")
        assert wallet.unlock("p", timeout=0.05)
        import time as _time

        _time.sleep(0.1)
        wallet.maybe_relock()
        assert wallet.is_locked


class TestHDWallet:
    def test_new_wallet_is_hd_and_deterministic(self, tmp_path):
        """A fresh wallet derives m/0'/0'/i' keys; reloading the file and
        deriving again continues the same chain (restart determinism)."""
        from bitcoincashplus_tpu.wallet.bip32 import ExtKey
        from bitcoincashplus_tpu.consensus.params import regtest_params

        params = regtest_params()
        path = str(tmp_path / "wallet.json")
        w = Wallet(params, path=path)
        a0 = w.get_new_address()
        a1 = w.get_new_address()
        assert w.hd_seed is not None and w.hd_counter == 2
        # paths recorded
        paths = set(w.key_paths.values())
        assert paths == {"m/0'/0'/0'", "m/0'/0'/1'"}
        # derivation is reproducible from the seed alone
        master = ExtKey.from_seed(w.hd_seed)
        k0 = master.derive_path("m/0'/0'/0'")
        from bitcoincashplus_tpu.wallet.keys import CKey

        assert CKey(k0.secret).p2pkh_address(params) == a0

        # reload: same seed, counter continues, old keys present
        w2 = Wallet(params, path=path)
        w2.load()
        assert w2.hd_seed == w.hd_seed and w2.hd_counter == 2
        assert set(w2.key_paths.values()) == paths
        a2 = w2.get_new_address()
        assert a2 not in (a0, a1)
        assert w2.key_paths[w2.keys_by_pkh[
            list(w2.keys_by_pkh)[-1]].pubkey] == "m/0'/0'/2'"

    def test_encrypt_seals_seed_and_unlock_restores(self, tmp_path):
        from bitcoincashplus_tpu.consensus.params import regtest_params

        params = regtest_params()
        path = str(tmp_path / "wallet.json")
        w = Wallet(params, path=path)
        a0 = w.get_new_address()
        seed = w.hd_seed
        w.encrypt("hunter2")
        assert w.hd_seed is None and w.encrypted_hd_seed is not None
        # locked wallet can't derive
        with pytest.raises(Exception):
            w.get_new_address()
        assert w.unlock("hunter2")
        assert w.hd_seed == seed
        a1 = w.get_new_address()  # HD derivation continues while unlocked
        assert w.key_paths[w.keys_by_pubkey[
            list(w.keys_by_pubkey)[-1]].pubkey].endswith("/1'")

        # reload from disk: seed ciphertext survives; unlock restores
        w2 = Wallet(params, path=path)
        w2.load()
        assert w2.encrypted_hd_seed is not None
        assert w2.unlock("hunter2")
        assert w2.hd_seed == seed

    def test_passphrase_change_reseals_seed(self, tmp_path):
        from bitcoincashplus_tpu.consensus.params import regtest_params

        params = regtest_params()
        w = Wallet(params, path=str(tmp_path / "w.json"))
        w.get_new_address()
        seed = w.hd_seed
        w.encrypt("old")
        assert w.unlock("old")
        assert w.change_passphrase("old", "new")
        w.lock()
        assert not w.unlock("old")
        assert w.unlock("new")
        assert w.hd_seed == seed

    def test_legacy_wallet_stays_random(self, tmp_path):
        """A wallet that already has imported keys but no seed keeps
        generating random keys (no retroactive HD adoption)."""
        from bitcoincashplus_tpu.consensus.params import regtest_params

        params = regtest_params()
        w = Wallet(params)
        w.add_key(CKey(0x1234), persist=False)
        w.get_new_address()
        assert w.hd_seed is None
        assert w.key_paths == {}


class TestManySmallUtxos:
    def test_fee_scales_with_input_count(self, rig):
        """VERDICT r3 weak #6: a wallet holding only small UTXOs must build
        a many-input spend whose fee scales with its real size — a flat
        1000-sat fee on a multi-kB tx fails every relay policy (including
        our own ATMP min feerate)."""
        cs, wallet = rig
        _mine_to_wallet(cs, wallet, 110)
        tip_h = cs.tip().height
        # fan one mature coinbase into 120 small outputs owned by a FRESH
        # wallet that will hold nothing else (so selection must use them)
        wallet2 = Wallet(wallet.params)
        cs.on_block_connected.append(wallet2.block_connected)
        cs.on_block_disconnected.append(wallet2.block_disconnected)
        wallet2.get_new_address()
        key2 = wallet2.keys_by_pkh[next(iter(wallet2.keys_by_pkh))]
        outputs = [(key2.p2pkh_script(), 400_000)] * 120
        fan = wallet.create_transaction_multi(
            outputs, tip_h, fee=30_000, enable_forkid=True)
        pool = CTxMemPool()
        accept_to_memory_pool(pool, cs, fan)
        generate_blocks(cs, CKey(0x999).p2pkh_script(), 1, mempool=pool,
                        tile=TILE)
        tip_h = cs.tip().height

        # now spend an amount that NEEDS ~100 of those small coins
        dest = CKey(0xABCDEF).p2pkh_address(wallet.params)
        tx = wallet2.create_transaction(
            dest, 40_000_000, tip_h, fee=1000, enable_forkid=True,
            fee_rate=1000,
        )
        assert len(tx.vin) >= 100
        size = len(tx.serialize())
        # recompute the paid fee: inputs all come from the fan tx
        values = {}
        for i, out in enumerate(fan.vout):
            values[(fan.txid, i)] = out.value
        in_total = sum(
            values.get((ti.prevout.hash, ti.prevout.n), 0)
            for ti in tx.vin
        )
        # any input not from the fan tx would make in_total undercount;
        # require full coverage so the fee math below is exact
        assert all((ti.prevout.hash, ti.prevout.n) in values
                   for ti in tx.vin)
        fee_paid = in_total - sum(o.value for o in tx.vout)
        assert fee_paid * 1000 >= size * 1000  # >= 1000 sat/kB
        # and the result actually clears ATMP at the relay floor
        pool2 = CTxMemPool()
        entry = accept_to_memory_pool(pool2, cs, tx, min_fee_rate=1000)
        assert entry.fee == fee_paid


def test_knapsack_selection_avoids_fragmenting_change():
    """SelectCoins/ApproximateBestSubset regression (VERDICT r4 item 10):
    a small spend from a wallet holding many small UTXOs plus a few huge
    ones must select a near-target subset of the small coins, not one huge
    coin with maximal change; an exact-value coin must win outright."""
    from bitcoincashplus_tpu.consensus.params import regtest_params
    from bitcoincashplus_tpu.consensus.serialize import ser_u32
    from bitcoincashplus_tpu.consensus.tx import COutPoint, CTxOut
    from bitcoincashplus_tpu.wallet.wallet import (
        MIN_CHANGE,
        Wallet,
        WalletCoin,
    )

    w = Wallet(params=regtest_params())

    def coin(i, value):
        return WalletCoin(COutPoint(ser_u32(i) * 8, 0),
                          CTxOut(value, b"\x51"), 1, False)

    small = [coin(i, 1_000_000) for i in range(50)]        # 50 x 0.01
    huge = [coin(100 + i, 1_000_000_000) for i in range(2)]  # 2 x 10
    coins = small + huge

    # near-target subset: 2.5M target -> small coins only. The reference
    # re-aims at target + CENT when the first pass can't land exactly
    # (change below CENT is near-dust), so the bound is target + 2*CENT —
    # a far cry from largest-first's 10-coin pick with ~9.975 in change.
    sel = w.select_coins(coins, 2_500_000)
    total = sum(c.txout.value for c in sel)
    assert all(c.txout.value == 1_000_000 for c in sel), \
        "picked a huge coin for a small spend"
    assert 2_500_000 <= total <= 2_500_000 + 2 * MIN_CHANGE

    # exact match wins outright (single input, zero change)
    sel = w.select_coins(coins, 1_000_000)
    assert len(sel) == 1 and sel[0].txout.value == 1_000_000

    # target above the small pool: the lowest larger coin answers
    sel = w.select_coins(coins, 200_000_000)
    assert len(sel) == 1 and sel[0].txout.value == 1_000_000_000

    # insufficient funds still raises
    import pytest

    with pytest.raises(ValueError):
        w.select_coins(coins, 10_000_000_000)
