"""Fee estimator tests — synthetic confirmation schedules against
mempool/fees.py (reference model: src/policy/fees.cpp policyestimator_tests
shape: feed txs at known feerates with known confirmation delays, then
check the per-target estimates order correctly)."""

import os

from bitcoincashplus_tpu.mempool.fees import (
    MAX_TARGET,
    FeeEstimator,
)


def _txid(i: int) -> bytes:
    return i.to_bytes(32, "little")


def _run_schedule(est, start_height, n_blocks, plan):
    """plan: list of (feerate, confirm_delay). Each block height h: enter
    one tx per plan row, confirm the ones whose delay elapsed."""
    pending = []  # (confirm_at, txid)
    next_id = [start_height * 10_000]
    for h in range(start_height, start_height + n_blocks):
        confirmed = [t for at, t in pending if at == h]
        est.process_block(h, confirmed)
        pending = [(at, t) for at, t in pending if at != h]
        for feerate, delay in plan:
            next_id[0] += 1
            t = _txid(next_id[0])
            est.process_tx(t, h, feerate)
            pending.append((h + delay, t))
    return pending


def test_target_ordering():
    """High feerates confirm fast, low slow => tight targets demand more."""
    est = FeeEstimator()
    _run_schedule(est, 1, 400, [
        (50_000, 1),   # premium: next block
        (10_000, 4),   # mid: ~4 blocks
        (2_000, 12),   # cheap: ~12 blocks
    ])
    e1 = est.estimate_fee(1)
    e5 = est.estimate_fee(5)
    e15 = est.estimate_fee(15)
    assert e1 > 0 and e5 > 0 and e15 > 0
    # a 1-block answer must demand at least the premium band; a 15-block
    # answer must have discovered the cheap band
    assert e1 >= 40_000, e1
    assert e5 <= e1
    assert e15 <= e5
    assert e15 <= 4_000, e15


def test_insufficient_data_cold():
    est = FeeEstimator()
    assert est.estimate_fee(1) == -1
    assert est.estimate_smart_fee(1) == (-1.0, 1)
    # below the reference-scale sample gate (sufficientTxVal/(1-decay)
    # ~= 50 decayed observations) NO estimate is minted — a single tracked
    # tx must never answer (VERDICT r4 item 9)
    _run_schedule(est, 1, 12, [(10_000, 2)])
    assert est.estimate_smart_fee(1) == (-1.0, 1)
    # past the gate, smart fee widens the horizon and reports the
    # answering target
    _run_schedule(est, 13, 120, [(10_000, 2)])
    est_fee, answered = est.estimate_smart_fee(1)
    assert est_fee > 0
    assert answered >= 2  # nothing ever confirmed in 1 block


def test_congestion_unconfirmed_txs_suppress_estimate():
    """A bucket whose txs mostly sit unconfirmed must not read as ~100%
    success (ADVICE r4 medium: unconfirmed txs join the denominator)."""
    est = FeeEstimator()
    # 200 blocks of 1 tx/block confirming in 2 blocks: warm, answers
    leftover = _run_schedule(est, 1, 200, [(10_000, 2)])
    # the schedule's tail txs never got their confirmation block; drop
    # them so only the deliberate flood below counts as congestion
    for _at, t in leftover:
        est.remove_tx(t)
    warm = est.estimate_fee(3)
    assert warm > 0
    # congestion: a flood of same-bucket txs enters and NEVER confirms
    for i in range(400):
        est.process_tx(_txid(10_000_000 + i), 200, 10_000)
    for h in range(201, 215):
        est.process_block(h, [])
    assert est.estimate_fee(3) == -1  # success ratio collapsed
    # the flood clearing (eviction) restores the historical answer
    for i in range(400):
        est.remove_tx(_txid(10_000_000 + i))
    assert est.estimate_fee(3) > 0


def test_slow_confirmations_fail_tight_targets():
    """Feerates that only ever confirm slowly must NOT satisfy target 1."""
    est = FeeEstimator()
    _run_schedule(est, 1, 300, [(5_000, 10)])
    assert est.estimate_fee(1) == -1
    assert est.estimate_fee(2) == -1
    assert est.estimate_fee(15) > 0


def test_eviction_does_not_poison():
    """Evicted (never-confirmed) txs must not count as confirmations."""
    est = FeeEstimator()
    for h in range(1, 200):
        t = _txid(h)
        est.process_tx(t, h, 100_000)
        est.remove_tx(t)          # evicted before any block includes it
        est.process_block(h, [])
    assert est.estimate_fee(1) == -1  # no confirmation evidence at all


def test_reorg_replay_no_double_count():
    est = FeeEstimator()
    t = _txid(1)
    est.process_tx(t, 10, 10_000)
    est.process_block(11, [t])
    before = [sum(st.tx_avg) for st in est.stats.values()]
    est.process_block(11, [t])  # replayed height: guard must ignore
    assert [sum(st.tx_avg) for st in est.stats.values()] == before


def test_persistence_roundtrip(tmp_path):
    path = os.path.join(tmp_path, "fee_estimates.json")
    est = FeeEstimator(path)
    _run_schedule(est, 1, 200, [(20_000, 2), (3_000, 8)])
    want = [est.estimate_fee(t) for t in (1, 2, 8, MAX_TARGET)]
    est.flush()
    est2 = FeeEstimator(path)
    got = [est2.estimate_fee(t) for t in (1, 2, 8, MAX_TARGET)]
    assert got == want
    # corrupt file: estimator starts cold instead of crashing
    with open(path, "w") as f:
        f.write("{broken")
    est3 = FeeEstimator(path)
    assert est3.estimate_fee(2) == -1


def test_truncated_stats_file_never_fatal(tmp_path):
    """A stats file with right outer shape but truncated inner arrays must
    start cold, not IndexError inside block connection."""
    import json

    path = os.path.join(tmp_path, "fee_estimates.json")
    est = FeeEstimator()
    nb = len(est.buckets)

    def horizon_blob(max_t, truncate_fee=0, ragged=False):
        return {"tx_avg": [0.0] * nb,
                "fee_sum": [0.0] * (nb - truncate_fee),
                "conf_avg": [[0.0] * (2 if ragged else nb)] * max_t}

    from bitcoincashplus_tpu.mempool.fees import HORIZONS

    good = {name: horizon_blob(max_t)
            for name, _d, max_t, _s in HORIZONS}
    bad = dict(good)
    bad["medium"] = horizon_blob(HORIZONS[1][2], truncate_fee=3)
    with open(path, "w") as f:
        json.dump({"version": 2, "best_height": 5, "horizons": bad}, f)
    est2 = FeeEstimator(path)
    assert est2.best_height == 0  # rejected whole file, started cold
    est2.process_tx(_txid(1), 10, 5000)
    est2.process_block(11, [_txid(1)])  # must not raise
    bad2 = dict(good)
    bad2["long"] = horizon_blob(HORIZONS[2][2], ragged=True)
    with open(path, "w") as f:
        json.dump({"version": 2, "best_height": 5, "horizons": bad2}, f)
    est3 = FeeEstimator(path)
    assert est3.best_height == 0
    est3.process_tx(_txid(2), 10, 5000)
    est3.process_block(11, [_txid(2)])  # must not raise
    # a v1 (single-horizon) file is simply outgrown: cold start
    with open(path, "w") as f:
        json.dump({"version": 1, "best_height": 5,
                   "tx_avg": [0.0] * nb, "fee_sum": [0.0] * nb,
                   "conf_avg": [[0.0] * nb] * 25}, f)
    est4 = FeeEstimator(path)
    assert est4.best_height == 0


def test_smart_fee_counts_unconf_toward_gate():
    """estimatesmartfee must not early-out cold while estimate_fee answers
    via tracked-unconfirmed denominators (review r5 regression)."""
    est = FeeEstimator()
    left = _run_schedule(est, 1, 199, [(10_000, 2)])
    for _at, t in left:
        est.remove_tx(t)
    # idle blocks decay the horizons just below their gates
    for h in range(200, 671):
        est.process_block(h, [])
    for i in range(5):
        est.process_tx(_txid(5_000_000 + i), 600, 10_000)
    raw = est.estimate_fee(30)
    smart, _answered = est.estimate_smart_fee(30)
    assert (raw > 0) == (smart > 0)


def test_nested_conf_avg_cells_rejected(tmp_path):
    """A v2 stats file whose conf_avg cells are lists (3-D after asarray)
    must start cold, not crash later estimates (review r5 regression)."""
    import json

    from bitcoincashplus_tpu.mempool.fees import HORIZONS

    path = os.path.join(tmp_path, "fee_estimates.json")
    est = FeeEstimator()
    nb = len(est.buckets)
    horizons = {}
    for name, _d, max_t, _s in HORIZONS:
        horizons[name] = {"tx_avg": [1.0] * nb, "fee_sum": [1.0] * nb,
                          "conf_avg": [[[50.0, 50.0]] * nb] * max_t}
    with open(path, "w") as f:
        json.dump({"version": 2, "best_height": 5, "horizons": horizons}, f)
    est2 = FeeEstimator(path)
    assert est2.best_height == 0
    assert est2.estimate_fee(2) == -1  # cold, no ValueError
