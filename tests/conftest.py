"""Test configuration.

Tests run on the CPU backend with an 8-device virtual mesh so multi-chip
sharding logic (parallel/) is exercised without TPU hardware — the same
mechanism the driver uses for dryrun_multichip (see __graft_entry__.py).

Note: this environment presets JAX_PLATFORMS=axon (a tunneled TPU plugin
that wins default-backend selection even over JAX_PLATFORMS=cpu), so forcing
the env var alone is not enough — we also pin jax_default_device to CPU
after import. parallel/mesh.local_devices honors JAX_PLATFORMS for the mesh
device list. Kernel-vs-real-TPU behavior is covered by the driver's bench
run (bench.py), not by this suite.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # overwrite the preset 'axon'
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import tempfile  # noqa: E402

import jax  # noqa: E402  (env must be set first)

# jax_platforms=cpu BEFORE any backend query: the env var alone does not
# stop the accelerator plugin from initializing on jax.devices(), and a
# wedged/unreachable device tunnel would hang the whole suite at import.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_device", jax.devices("cpu")[0])

# Persistent compilation cache: the ECDSA batch kernel costs ~90s of XLA
# compile on the CPU backend; caching it keeps the default suite fast
# after the first run while still exercising the real kernel every run.
jax.config.update(
    "jax_compilation_cache_dir",
    os.path.join(tempfile.gettempdir(), "bcp-jax-test-cache"),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)
