"""Test configuration.

Tests run on the CPU backend with an 8-device virtual mesh so multi-chip
sharding logic (parallel/) is exercised without TPU hardware — the same
mechanism the driver uses for dryrun_multichip (see __graft_entry__.py).

Note: this environment presets JAX_PLATFORMS=axon (a tunneled TPU plugin
that wins default-backend selection even over JAX_PLATFORMS=cpu), so forcing
the env var alone is not enough — we also pin jax_default_device to CPU
after import. parallel/mesh.local_devices honors JAX_PLATFORMS for the mesh
device list. Kernel-vs-real-TPU behavior is covered by the driver's bench
run (bench.py), not by this suite.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # overwrite the preset 'axon'
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import hashlib  # noqa: E402
import tempfile  # noqa: E402

# Persistent compilation cache, seeded into the ENVIRONMENT before jax
# (or any spawned bcpd) initializes: the fused-GLV verify programs cost
# minutes of cold XLA compile on the CPU backend, and the functional
# tests spawn real node processes that would otherwise each pay it
# again. The dir is per-checkout (path-hashed, so parallel checkouts
# never share entries) but persistent across runs — the cold compile is
# paid once per machine, and node/node.py's -compilecache env fallback
# means every spawned bcpd inherits it with no extra flags. Tests assert
# the inheritance end to end via gettpuinfo.device.compilation_cache.
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_CACHE_DIR = os.environ.setdefault(
    "BCP_COMPILE_CACHE",
    os.path.join(
        tempfile.gettempdir(),
        "bcp-jax-test-cache-"
        + hashlib.sha256(_REPO_ROOT.encode()).hexdigest()[:12]))

import jax  # noqa: E402  (env must be set first)

# jax_platforms=cpu BEFORE any backend query: the env var alone does not
# stop the accelerator plugin from initializing on jax.devices(), and a
# wedged/unreachable device tunnel would hang the whole suite at import.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_device", jax.devices("cpu")[0])

# the in-process half of the same cache (devicewatch.enable_compile_cache
# also installs the jax.monitoring listener, so in-process cache hits are
# observable just like the spawned nodes')
from bitcoincashplus_tpu.util import devicewatch as _dw  # noqa: E402

_dw.enable_compile_cache(_CACHE_DIR)

import pytest  # noqa: E402


def pytest_collection_modifyitems(config, items):
    """Fast signal first: run the unit suite before the functional suite
    (which spawns real bcpd processes at several minutes per file), and
    the adversarial chaos campaigns after the rest of the functional
    suite — under a bounded CI budget the newest, heaviest campaigns are
    the first thing a timeout cuts, never the established coverage.
    The ``pipeline`` suite (pipelined-IBD differentials/unwind, tier-1,
    JAX_PLATFORMS=cpu) runs after the plain unit suite and before the
    functional/adversarial groups; the ``glv`` and ``msm`` kernel suites
    are plain-unit (group 0) on purpose — fast, ordered with the unit
    run (the msm suite pins every MSM dispatch to the bucket-64 shape,
    the only rung whose XLA compile is unit-test-priced). The
    ``telemetry`` suite runs after ``pipeline`` (its registry-zeroing
    fixture must not interleave with suites asserting on live counters)
    and the ``serving`` suite (SigService flush policy / serviced-accept
    differentials) after ``telemetry``, both before the functional
    groups. Stable sort: order within each group is unchanged."""

    def group(item) -> int:
        # the ``devprof`` suite (device-lane observability — the same
        # registry-zeroing isolation pattern as telemetry) runs after
        # ``telemetry`` and before ``serving``; the ``mining`` suite
        # (resident loop + hoist differentials — ISSUE 10) runs after
        # ``devprof`` (it asserts on devicewatch program state) and
        # before ``serving``; the ``forkstorm`` multi-node campaigns run
        # DEAD LAST, after even the adversarial chaos suites — they are
        # the newest, heaviest coverage and the first thing a CI timeout
        # should cut
        if "functional" not in str(item.fspath):
            # the ``lint`` suite (bcplint static analysis + lockwatch
            # sentinel — ISSUE 15) runs FIRST: pure-AST, no jax import,
            # and an invariant violation is the cheapest, highest-signal
            # failure the run can produce
            if item.get_closest_marker("lint"):
                return -1
            # the ``mempoolstorm`` differential suite (ISSUE 20) is the
            # newest non-functional coverage: after ``serving``, still
            # before every functional group (fractional key — the
            # functional ladder starts at 6)
            if item.get_closest_marker("mempoolstorm"):
                return 5.5
            if item.get_closest_marker("serving"):
                return 5
            if item.get_closest_marker("mining"):
                return 4
            if item.get_closest_marker("devprof"):
                return 3
            if item.get_closest_marker("telemetry"):
                return 2
            return 1 if item.get_closest_marker("pipeline") else 0
        # the ``snapshot`` onboarding test runs after the plain
        # functional group, then adversarial, then forkstorm, then the
        # ``fleet`` multi-node serving campaigns dead last (ISSUE 16 —
        # the newest, heaviest topologies are the first thing a CI
        # timeout cuts)
        if item.get_closest_marker("fleet"):
            return 10
        if item.get_closest_marker("forkstorm"):
            return 9
        if item.get_closest_marker("adversarial"):
            return 8
        return 7 if item.get_closest_marker("snapshot") else 6

    items.sort(key=group)


@pytest.fixture
def fault_harness(monkeypatch):
    """Arm the BCP_FAULT_* harness for one test and restore a clean
    injector + breaker registry afterwards (the fault state is process-
    global by design — it must never leak across tests).

    The `faults` marker (registered in pyproject.toml) tags the
    supervised-dispatch fault suite; it is tier-1 fast — injection fires
    BEFORE any heavy kernel compile, and device stubs stand in for the
    ECDSA kernel — so it runs by default. Smoke subset alone:
    ``JAX_PLATFORMS=cpu pytest -m faults -q``.

    Usage: ``inj = fault_harness("fail-always", ops="ecdsa", n=3)``."""
    from bitcoincashplus_tpu.ops import dispatch
    from bitcoincashplus_tpu.util import faults

    def arm(mode: str, ops: str = "all", **env):
        monkeypatch.setenv("BCP_FAULT_MODE", mode)
        monkeypatch.setenv("BCP_FAULT_OPS", ops)
        for key, val in env.items():
            monkeypatch.setenv("BCP_FAULT_" + key.upper(), str(val))
        faults.INJECTOR.reload()
        return faults.INJECTOR

    yield arm
    # monkeypatch's own env restore runs AFTER this generator resumes, so
    # scrub the fault vars by hand before rebuilding the global state
    for key in [k for k in os.environ if k.startswith("BCP_FAULT")]:
        os.environ.pop(key, None)
    faults.INJECTOR.reload()
    dispatch.reset()
