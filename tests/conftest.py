"""Test configuration.

Tests run on CPU with an 8-device virtual mesh so multi-chip sharding logic
(parallel/) is exercised without TPU hardware — the same mechanism the driver
uses for dryrun_multichip (see __graft_entry__.py). Must run before jax import.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
