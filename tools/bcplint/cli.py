"""bcplint console entry point.

Usage::

    bcplint                      # lint the repo tree with the baseline
    bcplint pkg/mod.py           # lint specific files/dirs
    bcplint --no-baseline        # raw findings, baseline ignored
    bcplint --changed HEAD~1     # only files touched since a git ref
    bcplint --list-checks        # the check catalog
    bcplint --concurrency-report # docs/CONCURRENCY.md content to stdout

Exit status: 0 clean, 1 findings (or stale/unjustified baseline
entries), 2 usage error.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys

from .checks import all_checks
from .engine import render_report, run_lint

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline")


def _find_root(start: str) -> str:
    """Walk up to the checkout root (the dir holding the package),
    trying the cwd first and this file's own checkout as the fallback
    (an installed console script can run from anywhere)."""
    here = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    for base in (start, here):
        d = os.path.abspath(base)
        while True:
            if os.path.isdir(os.path.join(d, "bitcoincashplus_tpu")):
                return d
            parent = os.path.dirname(d)
            if parent == d:
                break
            d = parent
    return os.path.abspath(start)


def _changed_paths(root: str, ref: str) -> list[str] | None:
    """Repo-relative .py files under the linted trees touched since
    ``ref`` (committed diff + untracked), or None on git failure."""
    try:
        diff = subprocess.run(
            ["git", "diff", "--name-only", ref, "--"], cwd=root,
            capture_output=True, text=True, timeout=30)
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            cwd=root, capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.SubprocessError):
        return None
    if diff.returncode != 0:
        return None
    names = set(diff.stdout.splitlines())
    if untracked.returncode == 0:
        names |= set(untracked.stdout.splitlines())
    out = []
    for name in sorted(names):
        if not name.endswith(".py"):
            continue
        if not name.startswith(("bitcoincashplus_tpu/", "tools/")):
            continue
        abspath = os.path.join(root, name)
        if os.path.isfile(abspath):
            out.append(abspath)
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="bcplint",
        description="project-invariant static analysis (BCP001-BCP010)")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the package + tools)")
    ap.add_argument("--root", default=None,
                    help="checkout root (default: auto-detected)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file (default: the checked-in one)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report raw findings, ignore the baseline")
    ap.add_argument("--tests-dir", default=None,
                    help="tests tree for BCP005 parity (default: <root>/tests)")
    ap.add_argument("--changed", metavar="GIT_REF", default=None,
                    help="lint only .py files changed since GIT_REF "
                         "(fast local mode; staleness checks skipped)")
    ap.add_argument("--concurrency-report", action="store_true",
                    help="print the generated concurrency model "
                         "(docs/CONCURRENCY.md content) and exit")
    ap.add_argument("--list-checks", action="store_true")
    args = ap.parse_args(argv)

    if args.list_checks:
        for c in all_checks():
            for rule, title in getattr(c, "catalog", None) or [
                    (c.rule, c.title)]:
                print("%s  %s" % (rule, title))
        return 0

    root = args.root or _find_root(os.getcwd())

    if args.concurrency_report:
        from .race import build_report

        sys.stdout.write(build_report(root))
        return 0

    partial = False
    paths = [os.path.abspath(p) for p in args.paths] or None
    if args.changed is not None:
        if paths is not None:
            print("bcplint: --changed and explicit paths are exclusive",
                  file=sys.stderr)
            return 2
        changed = _changed_paths(root, args.changed)
        if changed is None:
            print("bcplint: git diff against %r failed" % args.changed,
                  file=sys.stderr)
            return 2
        if not changed:
            print("bcplint: no linted .py files changed since %s"
                  % args.changed)
            return 0
        paths = changed
        partial = True

    result = run_lint(
        root, paths=paths,
        baseline_path=None if args.no_baseline else args.baseline,
        tests_dir=args.tests_dir, partial=partial)
    print(render_report(result))
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
