"""bcplint console entry point.

Usage::

    bcplint                      # lint the repo tree with the baseline
    bcplint pkg/mod.py           # lint specific files/dirs
    bcplint --no-baseline        # raw findings, baseline ignored
    bcplint --list-checks        # the check catalog

Exit status: 0 clean, 1 findings (or stale/unjustified baseline
entries), 2 usage error.
"""

from __future__ import annotations

import argparse
import os
import sys

from .checks import ALL_CHECKS
from .engine import render_report, run_lint

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline")


def _find_root(start: str) -> str:
    """Walk up to the checkout root (the dir holding the package),
    trying the cwd first and this file's own checkout as the fallback
    (an installed console script can run from anywhere)."""
    here = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    for base in (start, here):
        d = os.path.abspath(base)
        while True:
            if os.path.isdir(os.path.join(d, "bitcoincashplus_tpu")):
                return d
            parent = os.path.dirname(d)
            if parent == d:
                break
            d = parent
    return os.path.abspath(start)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="bcplint",
        description="project-invariant static analysis (BCP001-BCP006)")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the package + tools)")
    ap.add_argument("--root", default=None,
                    help="checkout root (default: auto-detected)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file (default: the checked-in one)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report raw findings, ignore the baseline")
    ap.add_argument("--tests-dir", default=None,
                    help="tests tree for BCP005 parity (default: <root>/tests)")
    ap.add_argument("--list-checks", action="store_true")
    args = ap.parse_args(argv)

    if args.list_checks:
        for c in ALL_CHECKS:
            print("%s  %s" % (c.rule, c.title))
        return 0

    root = args.root or _find_root(os.getcwd())
    paths = [os.path.abspath(p) for p in args.paths] or None
    result = run_lint(
        root, paths=paths,
        baseline_path=None if args.no_baseline else args.baseline,
        tests_dir=args.tests_dir)
    print(render_report(result))
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
