"""bcplint: project-invariant static analysis for bitcoincashplus-tpu.

Each check codifies a bug class this repository has actually shipped and
re-fixed (see README "Static analysis & invariants" for the catalog and
the originating PR lesson per rule). Stdlib-only by design: the linter
parses the tree with ``ast`` and never imports the package under
analysis, so it runs in milliseconds with no jax/device footprint.
"""

from .engine import Finding, LintResult, run_lint  # noqa: F401
from .checks import ALL_CHECKS, check_by_rule  # noqa: F401
